#!/usr/bin/env python
"""agnes_metrics: heartbeat postmortem / schema-check CLI (repo shim).

The CLI logic lives in agnes_tpu/utils/metrics_cli.py (importable, so
the `agnes-metrics` console entry point resolves from the installed
package); this shim keeps the `scripts/agnes_metrics.py` invocation
(ci.sh serve-smoke gate, docs) working from a repo checkout — the
same arrangement as scripts/agnes_lint.py.  Everything imported here
is jax-free stdlib, so the shim runs on a box whose accelerator
stack is wedged.

Usage:
  scripts/agnes_metrics.py BENCH_heartbeat.ndjson      # postmortem
  scripts/agnes_metrics.py --check heartbeat.ndjson    # schema gate
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from agnes_tpu.utils.metrics_cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
