"""Resolve the jit-vs-eager timing discrepancy for the v2 verify kernel.

Times each candidate path two ways: pipelined (queue all iters, block at
the end — throughput) and serial (block every iter — latency), at two
batch sizes.
"""
import os
import sys
import time

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_cpu_parallel_codegen_split_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_cpu_parallel_codegen_split_count=1").strip()

import jax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from agnes_tpu.core import native
from agnes_tpu.crypto import ed25519_jax as E
from agnes_tpu.crypto import pallas_verify as pv
from agnes_tpu.crypto.encoding import vote_signing_bytes


def fixtures(B):
    seeds = [i.to_bytes(4, "little") + bytes(28) for i in range(B)]
    msgs = [vote_signing_bytes(1, 0, 0, i % 7) for i in range(B)]
    pks = [native.pubkey(s) for s in seeds]
    sigs = [native.sign(s, m) for s, m in zip(seeds, msgs)]
    return E.pack_verify_inputs_host(pks, msgs, sigs)


def bench(name, fn, args, iters=10):
    out = fn(*args)
    jax.block_until_ready(out)
    # pipelined
    t0 = time.perf_counter()
    outs = [fn(*args) for _ in range(iters)]
    for o in outs:
        jax.block_until_ready(o)
    piped = (time.perf_counter() - t0) / iters
    # serial
    t0 = time.perf_counter()
    for _ in range(iters):
        jax.block_until_ready(fn(*args))
    serial = (time.perf_counter() - t0) / iters
    B = args[0].shape[0]
    print(f"{name:28s} B={B:6d}  piped {piped*1e3:8.2f} ms {B/piped:>11,.0f}/s"
          f"   serial {serial*1e3:8.2f} ms {B/serial:>11,.0f}/s", flush=True)


def main():
    for B in (16384, 65536):
        pub, sig, blocks = fixtures(B)
        jit_v2 = jax.jit(pv.verify_batch_pallas)
        jit_v2_w5 = jax.jit(lambda p, s, b: pv.verify_batch_pallas(
            p, s, b, window=5))
        bench("eager v2", pv.verify_batch_pallas, (pub, sig, blocks))
        bench("jit v2 (window=4)", jit_v2, (pub, sig, blocks))
        bench("jit v2 window=5", jit_v2_w5, (pub, sig, blocks))
        # the production default route (ed25519_jax.verify_batch): on
        # TPU this is the v2 kernel at window=5, so it should track the
        # row above — a gap between them means the route is stale
        bench("jit default route", E.verify_batch_jit, (pub, sig, blocks))


if __name__ == "__main__":
    main()
