"""Analytic MFU for the fused Ed25519 verify kernel (VERDICT r4 #6:
"report MFU so 'fast' becomes a ratio, not a feeling").

Counts the kernel's field operations PER VERIFY by instrumenting the
actual building blocks (pallas_verify._fmul/_fsqr/...) with counting
wrappers and replaying the kernel's exact structure (two decompress
chains, the 17-entry table build, 52 signed-window iterations, the
cofactored compare) on tiny dummy arrays — no device needed, no
hand-derived tables to go stale.  Converts to int32 multiply ops via
the schoolbook limb structure (NLIMBS^2 vreg mults per field mul; a
dedicated squaring costs ~(NLIMBS^2+NLIMBS)/2) and divides by the
measured per-verify device time to get achieved int-mult throughput,
reported against a documented VPU peak assumption.

Usage: python scripts/mfu_verify.py [measured_us_per_verify]
(default 0.80us — the r4 marginal device rate at B=131k->262k,
HW_MEASUREMENTS_r04.md)."""

import sys

sys.path.insert(0, "/root/repo")

import numpy as np

import agnes_tpu.crypto.pallas_verify as pv
from agnes_tpu.crypto.field_jax import NLIMBS

COUNTS = {"mul": 0, "sqr": 0, "mul_const": 0, "carry": 0, "select": 0}
_orig = {}


def _wrap():
    _orig.update(_fmul=pv._fmul, _fsqr=pv._fsqr,
                 _fmul_const=pv._fmul_const, _carry=pv._carry,
                 _where_fe=pv._where_fe)

    def fmul(a, b):
        COUNTS["mul"] += 1
        return _orig["_fmul"](a, b)

    def fsqr(a):
        COUNTS["sqr"] += 1
        return _orig["_fsqr"](a)

    def fmul_const(a, c):
        COUNTS["mul_const"] += 1
        return _orig["_fmul_const"](a, c)

    def carry(r, p):
        COUNTS["carry"] += 1
        return _orig["_carry"](r, p)

    def where_fe(m, a, b):
        COUNTS["select"] += 1
        return _orig["_where_fe"](m, a, b)

    pv._fmul, pv._fsqr = fmul, fsqr
    pv._fmul_const, pv._carry, pv._where_fe = fmul_const, carry, where_fe


def _unwrap():
    pv._fmul, pv._fsqr = _orig["_fmul"], _orig["_fsqr"]
    pv._fmul_const = _orig["_fmul_const"]
    pv._carry, pv._where_fe = _orig["_carry"], _orig["_where_fe"]


def count_kernel(signed5: bool = True) -> dict:
    """Replay the kernel structure on [20, 1, 1] dummies, counting."""
    import jax.numpy as jnp

    shape = (1, 1)
    fe = jnp.ones((NLIMBS,) + shape, jnp.int32)
    sign = jnp.zeros(shape, jnp.int32)
    _wrap()
    try:
        # the real kernel body counts every stage in one pass: run it
        # via pallas interpret on a 1x1 "tile"?  No — the body only
        # needs refs for indexing; replicate its call sequence instead
        # (kept in sync with _verify_kernel by construction of the
        # pieces below being the SAME functions it calls).
        one = pv._one((NLIMBS,) + shape)
        zero = jnp.zeros_like(one)
        # decompress A and R
        xa, _ = pv._decompress(fe, sign)
        xr, _ = pv._decompress(fe, sign)
        # -A table build
        n_ent = 17 if signed5 else 16
        nax = pv._fsub(zero, xa)
        na = (nax, fe, one, pv._fmul(nax, fe))
        ext = [None] * n_ent
        ext[1] = na
        ext[2] = pv._pt_dbl(*na[:3], want_t=True)
        for e in range(3, n_ent, 2):
            ext[e] = pv._pt_add_ext(ext[e - 2], ext[2], want_t=True)
        for e in range(4, n_ent, 2):
            p = ext[e // 2]
            ext[e] = pv._pt_dbl(p[0], p[1], p[2], want_t=True)
        atab = [(one, one, zero, pv._fadd(one, one))] + [
            pv._to_niels(ext[e]) for e in range(1, n_ent)]
        # main loop: structure only — selects modelled by _select_tree
        # on real entries, adds/doublings by the real formulas
        n_win = pv.N_WIN5 if signed5 else pv.N_WIN
        dig = jnp.zeros(shape, jnp.int32)
        btab = [tuple(list(c) for c in e) for e in pv._btable(n_ent)]
        X, Y, Z = zero, one, one
        for i in range(n_win):
            for j in range(4 if not signed5 else 4):
                X, Y, Z, _ = pv._pt_dbl(X, Y, Z, want_t=False)
            X, Y, Z, T = pv._pt_dbl(X, Y, Z, want_t=True)
            n_ypx, n_ymx, n_t2d, n_z2 = pv._select_tree(dig, atab, 4)
            if signed5:
                neg = dig < 0
                n_ypx, n_ymx = (pv._where_fe(neg, n_ymx, n_ypx),
                                pv._where_fe(neg, n_ypx, n_ymx))
                n_t2d = pv._where_fe(neg, pv._carry(-n_t2d, 2), n_t2d)
            X, Y, Z, T = pv._pt_add_niels(X, Y, Z, T, n_ypx, n_ymx,
                                          n_t2d, n_z2, want_t=True)
            b_ypx, b_ymx, b_t2d = pv._select_tree(dig, btab, 4)
            b_ypx = jnp.stack(list(b_ypx), axis=0)
            b_ymx = jnp.stack(list(b_ymx), axis=0)
            b_t2d = jnp.stack(list(b_t2d), axis=0)
            if signed5:
                b_ypx, b_ymx = (pv._where_fe(neg, b_ymx, b_ypx),
                                pv._where_fe(neg, b_ypx, b_ymx))
                b_t2d = pv._where_fe(neg, pv._carry(-b_t2d, 2), b_t2d)
            X, Y, Z, _ = pv._pt_add_niels(X, Y, Z, T, b_ypx, b_ymx,
                                          b_t2d, None, want_t=False)
        # cofactored compare
        RX, RY, RZ = xr, fe, one
        for _ in range(3):
            X, Y, Z, _ = pv._pt_dbl(X, Y, Z, want_t=False)
            RX, RY, RZ, _ = pv._pt_dbl(RX, RY, RZ, want_t=False)
        pv._is_zero(pv._fmul(X, RZ) - pv._fmul(RX, Z))
        pv._is_zero(pv._fmul(Y, RZ) - pv._fmul(RY, Z))
    finally:
        _unwrap()
    return dict(COUNTS)


def main():
    us = float(sys.argv[1]) if len(sys.argv) > 1 else 0.80
    c = count_kernel(signed5=True)
    # int32 multiply ops per field op (schoolbook, NLIMBS=20 x 13-bit):
    mul_ops = NLIMBS * NLIMBS                 # 400 vreg mults
    sqr_ops = (NLIMBS * NLIMBS + NLIMBS) // 2  # ~210 (shared cross terms)
    mulc_ops = NLIMBS                          # constant has few limbs
    imuls = (c["mul"] * mul_ops + c["sqr"] * sqr_ops
             + c["mul_const"] * mulc_ops)
    print("field-op counts per verify (signed 5-bit kernel):")
    for k, v in c.items():
        print(f"  {k:10s} {v}")
    print(f"int32 multiplies per verify ~ {imuls:,} "
          f"(mul={mul_ops}, sqr={sqr_ops}, mul_const={mulc_ops} each)")
    rate = imuls / (us * 1e-6)
    # v5e VPU peak assumption (DOCUMENTED, not vendor-verified): the
    # MXU is bf16-only, so this integer kernel runs on the VPU =
    # 8x128 lanes x ~0.94 GHz; with 1 multiply-capable ALU slot per
    # lane-cycle that is ~0.96e12 int32-mult/s, with 2 slots ~1.9e12.
    lo, hi = 0.96e12, 1.9e12
    print(f"achieved int32-mult throughput at {us}us/verify: "
          f"{rate/1e12:.2f}e12/s")
    print(f"MFU vs 1-slot/2-slot VPU assumption: "
          f"{100*rate/lo:.0f}% / {100*rate/hi:.0f}%")
    print("(carries/adds/selects excluded from the numerator, so the "
          "true utilization is HIGHER than printed)")
    print("conclusion: the kernel is VPU-compute-bound at or near the "
          "integer-multiply ceiling — further speedups must cut field-"
          "op counts (or amortize verification), not scheduling")


if __name__ == "__main__":
    main()
