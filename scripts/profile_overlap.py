"""Measure + trace the host<->device overlap (VERDICT r3 next #4).

Runs the end-to-end signed pipeline twice at the same shape:

  sync        bench.bench_pipeline_native — the tick protocol with
              synchronous push and per-step message collection;
  overlapped  bench._pipeline_overlapped — the C++ worker thread
              parses/screens wire records (ingest.cpp
              ingest_worker_main) while this thread packs the next
              batch and drives the device, and message collection is
              deferred so JAX async dispatch actually overlaps host
              work with the running device step.

Prints one JSON line {sync, overlapped, speedup} and writes a
chrome-trace (chrome://tracing / perfetto) of the overlapped run with
host-side spans (pack, push_async, build, dispatch) — the gaps between
dispatch spans are the device time the host work hides inside.

Usage:  python scripts/profile_overlap.py [I V heights] [trace.json]
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the XLA:CPU codegen/serialization race workaround must land in
# XLA_FLAGS before ANY agnes/jax import can initialize a backend
# (package __init__ side effects create device arrays) — see
# agnes_tpu/utils/compile_cache.py
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_cpu_parallel_codegen_split_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_cpu_parallel_codegen_split_count=1").strip()

import jax  # noqa: E402

import bench  # noqa: E402
from agnes_tpu.utils.tracing import Tracer  # noqa: E402


def main() -> None:
    args = [a for a in sys.argv[1:] if not a.endswith(".json")]
    trace = next((a for a in sys.argv[1:] if a.endswith(".json")),
                 "/tmp/overlap_trace.json")
    if len(args) not in (0, 3):
        sys.exit("usage: profile_overlap.py [I V heights] [trace.json] "
                 f"— got {len(args)} shape arg(s), need 0 or 3")
    I, V, heights = (int(args[0]), int(args[1]),
                     int(args[2])) if args else (1024, 128, 6)

    sync_rate = bench._pipeline_harness(I, V, heights, bench._native_feeder)
    tracer = Tracer()
    over_rate = bench._pipeline_overlapped(I, V, heights, tracer=tracer)
    tracer.write(trace)
    print(json.dumps({
        "metric": "overlap_speedup",
        "sync_votes_per_sec": round(sync_rate),
        "overlapped_votes_per_sec": round(over_rate),
        "speedup": round(over_rate / sync_rate, 3),
        "trace": trace,
        "shape": {"instances": I, "validators": V, "heights": heights},
    }))


if __name__ == "__main__":
    main()
