"""Profile the Ed25519 verify pipeline stage by stage on the real chip.

Times, per batch of B signatures:
  - straus kernel alone (the double-scalar mult)
  - pow kernel alone (one (p-2) inversion worth)
  - XLA-side decompress (minus its pow), compress (minus its pow), sha512
  - full verify_batch
at several Pallas batch tile sizes.
"""
from __future__ import annotations

import os
import sys
import time

# the XLA:CPU codegen/serialization race workaround must land in
# XLA_FLAGS before ANY agnes/jax import can initialize a backend
# (package __init__ side effects create device arrays) — see
# agnes_tpu/utils/compile_cache.py
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_cpu_parallel_codegen_split_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_cpu_parallel_codegen_split_count=1").strip()

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from agnes_tpu.core import native
from agnes_tpu.crypto import ed25519_jax as E
from agnes_tpu.crypto import pallas_ed25519 as pk
from agnes_tpu.crypto import scalar_jax as S
from agnes_tpu.crypto import sha512_jax as sha
from agnes_tpu.crypto.encoding import vote_signing_bytes
from agnes_tpu.crypto.field_jax import P


def timeit(fn, *args, iters=5):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    B = 16384
    seeds = [i.to_bytes(4, "little") + bytes(28) for i in range(B)]
    msgs = [vote_signing_bytes(1, 0, 0, i % 7) for i in range(B)]
    pks = [native.pubkey(s) for s in seeds]
    sigs = [native.sign(s, m) for s, m in zip(seeds, msgs)]
    pub, sig, blocks = E.pack_verify_inputs_host(pks, msgs, sigs)

    # full pipeline
    dt = timeit(E.verify_batch_jit, pub, sig, blocks)
    print(f"full verify_batch      B={B}: {dt*1e3:8.2f} ms  {B/dt:,.0f}/s")

    # sha512 alone
    f = jax.jit(lambda bl: S.barrett_reduce(
        S.digest_to_limbs(sha.sha512_blocks(bl))))
    dt = timeit(f, blocks)
    print(f"sha512+barrett         B={B}: {dt*1e3:8.2f} ms")

    # decompress (includes 1 pow via pallas)
    f = jax.jit(lambda p: E.decompress(p)[0].x)
    dt = timeit(f, pub)
    print(f"decompress (w/ pow)    B={B}: {dt*1e3:8.2f} ms")

    # pow kernel alone at various tiles
    x = jnp.asarray(np.random.randint(0, 8192, (B, 20), np.int32))
    for tile in (256, 512, 1024, 2048):
        try:
            f = lambda xx: pk.pow_p_pallas(xx, P - 2, b_tile=tile)
            dt = timeit(f, x)
            print(f"pow(p-2) tile={tile:5d}    B={B}: {dt*1e3:8.2f} ms")
        except Exception as e:
            print(f"pow tile={tile}: FAIL {type(e).__name__}: {e}")

    # straus kernel alone at various tiles
    a_pt, _ = E.decompress(pub)
    a_pt = jax.tree.map(lambda v: jax.block_until_ready(v), a_pt)
    s_l = S.scalar_from_bytes32(sig[..., 32:])
    k_l = jax.jit(lambda bl: S.barrett_reduce(
        S.digest_to_limbs(sha.sha512_blocks(bl))))(blocks)
    for tile in (256, 512, 1024, 2048):
        try:
            f = jax.jit(lambda ss, kk, ap: pk.straus_sub_pallas(
                ss, kk, ap, b_tile=tile).x)
            dt = timeit(f, s_l, k_l, a_pt)
            print(f"straus tile={tile:5d}     B={B}: {dt*1e3:8.2f} ms  "
                  f"{B/dt:,.0f}/s")
        except Exception as e:
            print(f"straus tile={tile}: FAIL {type(e).__name__}: {e}")

    # compress alone (includes 1 pow)
    q = E.base_point((B,))
    f = jax.jit(E.compress)
    dt = timeit(f, q)
    print(f"compress (w/ pow)      B={B}: {dt*1e3:8.2f} ms")

    # --- v2 fused kernel (crypto/pallas_verify.py) --------------------------
    from agnes_tpu.crypto import pallas_verify as pv

    dt = timeit(lambda: pv.verify_batch_pallas(pub, sig, blocks))
    print(f"v2 fused kernel        B={B}: {dt*1e3:8.2f} ms  {B/dt:,.0f}/s")

    # v2 with signed 5-bit windows (the r3-queued optimization; pick
    # the faster of the two on hardware)
    dt = timeit(lambda: pv.verify_batch_pallas(pub, sig, blocks, window=5))
    print(f"v2 signed-5 windows    B={B}: {dt*1e3:8.2f} ms  {B/dt:,.0f}/s")

    # v2 host/XLA preprocessing alone (sha, digits, tiling — everything
    # except the pallas_call): bound by subtracting from the full time
    f = jax.jit(lambda s_, bl: (
        pv._digits65(S.barrett_reduce(
            S.digest_to_limbs(sha.sha512_blocks(bl)))),
        pv._digits65(S.scalar_from_bytes32(s_[..., 32:]))))
    dt = timeit(f, sig, blocks)
    print(f"v2 xla-side prep       B={B}: {dt*1e3:8.2f} ms")

    # MSM batch check (production adaptive path)
    from agnes_tpu.crypto import msm_jax as M

    z = M.make_z(B, seed=0)
    dt = timeit(M.verify_batch_msm_jit, pub, sig, blocks, z)
    print(f"msm batch check        B={B}: {dt*1e3:8.2f} ms  {B/dt:,.0f}/s")


if __name__ == "__main__":
    main()
