"""Who (else) holds the single-process TPU claim?  Stdlib-only (safe
to import before jax/agnes — backend init must not be triggered by a
probe helper), shared by bench.py's busy-wait guard and
run_hw_suite.sh's probe loop so BOTH sides defer to a live TPU
process instead of killing hung probes against its claim (a probe
SIGTERM'd mid-claim is a documented cause of hours-long relay
wedges).

Two complementary mechanisms:

* **TpuLease** — the claim PROTOCOL (VERDICT r5 weak #4: two rounds of
  races in the ad-hoc ps-screen/elder-bench tie-break).  A lease file
  guarded by a short fcntl critical section: atomic acquire, pid+
  start-time liveness (guards pid reuse), stale-lease expiry (dead or
  expired holders are overwritten).  Exactly one process can hold the
  lease at a time; whoever holds it probes/claims the TPU, everyone
  else waits.  Cooperating entry points: bench.py (in-process API) and
  run_hw_suite.sh (the `lease-acquire`/`lease-release` CLI below).

* **tpu_holders()** — the ps SCREEN, kept as the backstop for
  processes that predate or bypass the lease protocol (a stray
  profile run, a driver-launched sibling on old code): a process
  counts only when it is a python invocation of a known TPU entry
  point (or a bash/sh/timeout wrapper that itself launches python) —
  an editor or grep with bench.py on its command line does not.
  Callers exclude themselves and their ancestor chain."""

from __future__ import annotations

import errno
import fcntl
import json
import os
import subprocess
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

PATTERNS = ("bench.py", "agnes_tpu.harness.configs", "profile_verify",
            "sweep_pipeline", "timing_check", "agnes_tpu_probe")

# the probe command EVERY cooperating prober must use: the trailing
# comment is a marker that makes an in-flight probe visible to other
# holder checks (closing the window where one side starts probing
# while the other's 120s probe is already mid-claim — killing either
# against the other's claim can wedge the relay).  Both sides check
# holders immediately before probing, so the residual race is the
# few ms between check and spawn, not a 120s window.
PROBE_SNIPPET = "import jax; jax.devices()  # agnes_tpu_probe"


# --- the lease protocol -----------------------------------------------------

#: default lease location; override for tests / parallel sandboxes
DEFAULT_LEASE_PATH = os.environ.get("AGNES_TPU_LEASE_PATH",
                                    "/tmp/agnes_tpu.lease")

#: default time-to-live: a holder that neither refreshes nor exits
#: within this window is considered wedged and its lease expirable
#: (≈ the old busy budget; rivals probing a wedged relay after this
#: long is the pre-lease behavior too)
DEFAULT_LEASE_TTL_S = 3600.0


def _pid_start_ticks(pid: int) -> Optional[int]:
    """start_time of `pid` in clock ticks (/proc/<pid>/stat field 22;
    parsed after the last ')' — comm may contain anything), or None
    when the pid is gone/unreadable.  pid + start ticks identify a
    process immune to pid reuse."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            raw = f.read().decode("ascii", "replace")
        return int(raw[raw.rfind(")") + 1:].split()[19])
    except (OSError, ValueError, IndexError):
        return None


@contextmanager
def _locked(lock_path: str):
    """A short fcntl.flock critical section around lease reads/writes —
    the atomicity primitive: every acquire/refresh/release runs under
    it, so two racers can never both see 'free' and both write."""
    fd = os.open(lock_path, os.O_CREAT | os.O_RDWR, 0o666)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        yield
    finally:
        try:
            fcntl.flock(fd, fcntl.LOCK_UN)
        finally:
            os.close(fd)


class TpuLease:
    """The single-process TPU claim as an on-disk lease.

    Layout: `path` holds JSON {pid, start_ticks, expires_at, note};
    `path + ".lock"` is the flock the critical sections serialize on.
    A lease is VALID while its holder process is alive (same pid AND
    same start ticks) and `expires_at` (epoch seconds) is in the
    future; anything else is stale and free to take.  Writes are
    atomic (tmp + rename) so a reader never sees a torn record.

    Crash safety: a holder that dies without release() is detected
    dead via pid+start-ticks and its lease taken over immediately —
    no waiting out the ttl.  The ttl covers the wedged-but-alive case
    (hung backend init holding the claim forever)."""

    def __init__(self, path: str = None, pid: int = None):
        self.path = path or DEFAULT_LEASE_PATH
        self.pid = pid if pid is not None else os.getpid()

    # -- internals --------------------------------------------------------

    def _read(self) -> Optional[dict]:
        try:
            with open(self.path) as f:
                rec = json.load(f)
            rec["pid"] = int(rec["pid"])
            rec["expires_at"] = float(rec["expires_at"])
            return rec
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def _write(self, rec: dict) -> None:
        tmp = f"{self.path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    @staticmethod
    def _valid(rec: Optional[dict]) -> bool:
        if rec is None:
            return False
        if time.time() >= rec["expires_at"]:
            return False
        ticks = _pid_start_ticks(rec["pid"])
        return ticks is not None and ticks == rec.get("start_ticks")

    def _mine(self, rec: Optional[dict]) -> bool:
        return (rec is not None and rec.get("pid") == self.pid
                and rec.get("start_ticks") == _pid_start_ticks(self.pid))

    # -- protocol ---------------------------------------------------------

    def acquire(self, ttl_s: float = DEFAULT_LEASE_TTL_S,
                note: str = "") -> bool:
        """Take the lease iff it is free, stale (holder dead), expired,
        or already mine (re-acquire extends).  Atomic under the flock.
        True = this process now holds it."""
        with _locked(self.path + ".lock"):
            rec = self._read()
            if self._valid(rec) and not self._mine(rec):
                return False
            self._write({"pid": self.pid,
                         "start_ticks": _pid_start_ticks(self.pid),
                         "expires_at": time.time() + ttl_s,
                         "note": note})
            return True

    def refresh(self, ttl_s: float = DEFAULT_LEASE_TTL_S) -> bool:
        """Extend my lease; False (nothing written) if I no longer
        hold it — the caller lost the claim and must re-acquire."""
        with _locked(self.path + ".lock"):
            rec = self._read()
            if not self._mine(rec) or not self._valid(rec):
                return False
            rec["expires_at"] = time.time() + ttl_s
            self._write(rec)
            return True

    def release(self) -> bool:
        """Drop my lease (no-op on someone else's — a crashed-and-
        superseded holder must not release its successor's claim)."""
        with _locked(self.path + ".lock"):
            rec = self._read()
            if not self._mine(rec):
                return False
            try:
                os.unlink(self.path)
            except OSError as e:
                if e.errno != errno.ENOENT:
                    raise
            return True

    def holder(self) -> Optional[dict]:
        """The current VALID lease record, else None (also purges
        nothing — reads are passive)."""
        with _locked(self.path + ".lock"):
            rec = self._read()
            return rec if self._valid(rec) else None


def process_table() -> Dict[int, Tuple[int, int, str]]:
    """pid -> (ppid, etimes, args) from ps; {} on any failure."""
    try:
        out = subprocess.run(["ps", "-eo", "pid,ppid,etimes,args"],
                             capture_output=True, text=True,
                             timeout=30).stdout
    except Exception:
        return {}
    procs: Dict[int, Tuple[int, int, str]] = {}
    for ln in out.splitlines():
        parts = ln.strip().split(None, 3)
        if (len(parts) >= 4 and parts[0].isdigit()
                and parts[1].isdigit() and parts[2].isdigit()):
            procs[int(parts[0])] = (int(parts[1]), int(parts[2]),
                                    parts[3])
    return procs


def is_tpu_invocation(args: str) -> bool:
    """True iff `args` is a python run of a known TPU entry point
    (directly, or via a bash/sh/timeout wrapper that launches
    python).  Command lines longer than any plausible launcher are
    rejected outright: agent/driver wrapper shells on this box embed
    kilobytes of prompt text in argv that happens to MENTION the
    entry-point names — matching them would make every holder check
    defer forever against a process that holds nothing."""
    if len(args) > 500 or not any(p in args for p in PATTERNS):
        return False
    head, _, rest = args.partition(" ")
    interp = head.rsplit("/", 1)[-1]
    if interp.startswith("python"):
        return True
    return interp in ("bash", "sh", "timeout") and "python" in rest


def ancestor_chain(procs, pid: int) -> set:
    """pid plus every ancestor (a wrapper parent like
    `sh -c 'python bench.py ...'` matches the patterns but is the
    caller's own lineage, not a rival claim)."""
    chain = set()
    while pid in procs and pid not in chain:
        chain.add(pid)
        pid = procs[pid][0]
    return chain


def tpu_holders(procs: Dict[int, Tuple[int, int, str]] = None
                ) -> List[Tuple[int, int, str]]:
    """[(pid, etimes, args)] of other live TPU-entry-point processes,
    self and ancestors excluded, pid-sorted.  Pass `procs` to evaluate
    against ONE ps snapshot shared with other decisions (bench's
    sibling tie-break needs its own age from the same read)."""
    if procs is None:
        procs = process_table()
    skip = ancestor_chain(procs, os.getpid())
    return [(p, age, args) for p, (pp, age, args) in sorted(procs.items())
            if p not in skip and is_tpu_invocation(args)]


def _cli(argv: List[str]) -> int:
    """CLI.

    (no args)          legacy holder check — exit 0 = nobody else
                       running (ps screen AND no live lease held by
                       another process), 1 = held (details on stdout),
                       2 = the check itself failed; callers must treat
                       2 as "unknown", NOT as "held" (a broken helper
                       must never wedge a probe loop into deferring
                       forever)
    lease-acquire [--pid P] [--ttl S] [--note TEXT]
                       take the lease for P (default: the CALLER's
                       parent, so `python tpu_holders.py lease-acquire`
                       from a shell leases to that shell); exit 0 =
                       acquired, 1 = held by someone else
    lease-refresh [--pid P] [--ttl S]    exit 0 = extended, 1 = lost
    lease-release [--pid P]              exit 0 always (idempotent)
    lease-holder                         print the valid lease, exit
                                         0 = free, 1 = held
    """
    if argv and argv[0].startswith("lease-"):
        import argparse

        ap = argparse.ArgumentParser(prog="tpu_holders.py")
        ap.add_argument("cmd")
        ap.add_argument("--pid", type=int, default=None)
        ap.add_argument("--ttl", type=float, default=DEFAULT_LEASE_TTL_S)
        ap.add_argument("--note", default="")
        a = ap.parse_args(argv)
        pid = a.pid if a.pid is not None else os.getppid()
        lease = TpuLease(pid=pid)
        if a.cmd == "lease-acquire":
            return 0 if lease.acquire(a.ttl, a.note) else 1
        if a.cmd == "lease-refresh":
            return 0 if lease.refresh(a.ttl) else 1
        if a.cmd == "lease-release":
            lease.release()
            return 0
        if a.cmd == "lease-holder":
            rec = lease.holder()
            if rec:
                print(json.dumps(rec))
                return 1
            return 0
        ap.error(f"unknown command {a.cmd}")
    try:
        hs = tpu_holders()
        for p, age, args in hs:
            print(f"{p} {args}")
        rec = TpuLease().holder()
        if rec is not None and rec["pid"] not in \
                ancestor_chain(process_table(), os.getpid()):
            print(f"lease held: {json.dumps(rec)}")
            return 1
    except Exception as e:          # noqa: BLE001
        print(f"holder check failed: {e!r}")
        return 2
    return 1 if hs else 0


if __name__ == "__main__":
    import sys

    raise SystemExit(_cli(sys.argv[1:]))
