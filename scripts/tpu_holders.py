"""Who (else) holds the single-process TPU claim?  Stdlib-only (safe
to import before jax/agnes — backend init must not be triggered by a
probe helper), shared by bench.py's busy-wait guard and
run_hw_suite.sh's probe loop so BOTH sides defer to a live TPU
process instead of killing hung probes against its claim (a probe
SIGTERM'd mid-claim is a documented cause of hours-long relay
wedges).

Screens against false positives: a process counts only when it is a
python invocation of a known TPU entry point (or a bash/sh/timeout
wrapper that itself launches python) — an editor or grep with
bench.py on its command line does not.  Callers exclude themselves
and their ancestor chain; sibling-bench tie-breaking stays in
bench.py (it needs the caller's own identity)."""

from __future__ import annotations

import os
import subprocess
from typing import Dict, List, Tuple

PATTERNS = ("bench.py", "agnes_tpu.harness.configs", "profile_verify",
            "sweep_pipeline", "timing_check", "agnes_tpu_probe")

# the probe command EVERY cooperating prober must use: the trailing
# comment is a marker that makes an in-flight probe visible to other
# holder checks (closing the window where one side starts probing
# while the other's 120s probe is already mid-claim — killing either
# against the other's claim can wedge the relay).  Both sides check
# holders immediately before probing, so the residual race is the
# few ms between check and spawn, not a 120s window.
PROBE_SNIPPET = "import jax; jax.devices()  # agnes_tpu_probe"


def process_table() -> Dict[int, Tuple[int, int, str]]:
    """pid -> (ppid, etimes, args) from ps; {} on any failure."""
    try:
        out = subprocess.run(["ps", "-eo", "pid,ppid,etimes,args"],
                             capture_output=True, text=True,
                             timeout=30).stdout
    except Exception:
        return {}
    procs: Dict[int, Tuple[int, int, str]] = {}
    for ln in out.splitlines():
        parts = ln.strip().split(None, 3)
        if (len(parts) >= 4 and parts[0].isdigit()
                and parts[1].isdigit() and parts[2].isdigit()):
            procs[int(parts[0])] = (int(parts[1]), int(parts[2]),
                                    parts[3])
    return procs


def is_tpu_invocation(args: str) -> bool:
    """True iff `args` is a python run of a known TPU entry point
    (directly, or via a bash/sh/timeout wrapper that launches
    python).  Command lines longer than any plausible launcher are
    rejected outright: agent/driver wrapper shells on this box embed
    kilobytes of prompt text in argv that happens to MENTION the
    entry-point names — matching them would make every holder check
    defer forever against a process that holds nothing."""
    if len(args) > 500 or not any(p in args for p in PATTERNS):
        return False
    head, _, rest = args.partition(" ")
    interp = head.rsplit("/", 1)[-1]
    if interp.startswith("python"):
        return True
    return interp in ("bash", "sh", "timeout") and "python" in rest


def ancestor_chain(procs, pid: int) -> set:
    """pid plus every ancestor (a wrapper parent like
    `sh -c 'python bench.py ...'` matches the patterns but is the
    caller's own lineage, not a rival claim)."""
    chain = set()
    while pid in procs and pid not in chain:
        chain.add(pid)
        pid = procs[pid][0]
    return chain


def tpu_holders(procs: Dict[int, Tuple[int, int, str]] = None
                ) -> List[Tuple[int, int, str]]:
    """[(pid, etimes, args)] of other live TPU-entry-point processes,
    self and ancestors excluded, pid-sorted.  Pass `procs` to evaluate
    against ONE ps snapshot shared with other decisions (bench's
    sibling tie-break needs its own age from the same read)."""
    if procs is None:
        procs = process_table()
    skip = ancestor_chain(procs, os.getpid())
    return [(p, age, args) for p, (pp, age, args) in sorted(procs.items())
            if p not in skip and is_tpu_invocation(args)]


if __name__ == "__main__":
    # exit codes: 0 = nobody else running, 1 = holders found (listed
    # on stdout), 2 = the check itself failed — callers must treat 2
    # as "unknown", NOT as "held" (a broken helper must never wedge a
    # probe loop into deferring forever)
    try:
        hs = tpu_holders()
        for p, age, args in hs:
            print(f"{p} {args}")
    except Exception as e:          # noqa: BLE001
        print(f"holder check failed: {e!r}")
        raise SystemExit(2)
    raise SystemExit(1 if hs else 0)
