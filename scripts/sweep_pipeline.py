"""Sweep the end-to-end pipeline over (I, V) shapes on the real chip.

The headline `pipeline_votes_per_sec` is fixed-cost-dominated on the
axon tunnel (~60-70ms per dispatch; scripts/timing_check.py), so the
votes-per-height 2*I*V against the dispatches-per-height (~8) sets the
ceiling.  This sweep measures the synchronous numpy-bridge path and the
overlapped native path at several shapes so bench.py's defaults can be
pinned to measured numbers, not guesses.

Usage: python scripts/sweep_pipeline.py [heights]
"""
import os
import sys
import time

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_cpu_parallel_codegen_split_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_cpu_parallel_codegen_split_count=1").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench


def main():
    heights = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    shapes = [(1024, 128), (2048, 128), (4096, 128), (2048, 256)]
    for I, V in shapes:
        t0 = time.perf_counter()
        try:
            r = bench._pipeline_harness(I, V, heights, bench._numpy_feeder)
            print(f"numpy   I={I:5d} V={V:4d}: {r:>12,.0f} votes/s "
                  f"({time.perf_counter()-t0:.0f}s incl compile)", flush=True)
        except Exception as e:
            print(f"numpy   I={I:5d} V={V:4d}: FAIL {type(e).__name__}: {e}",
                  flush=True)
    for I, V in shapes:
        t0 = time.perf_counter()
        try:
            r = bench._pipeline_overlapped(I, V, heights)
            print(f"overlap I={I:5d} V={V:4d}: {r:>12,.0f} votes/s "
                  f"({time.perf_counter()-t0:.0f}s incl compile)", flush=True)
        except Exception as e:
            print(f"overlap I={I:5d} V={V:4d}: FAIL {type(e).__name__}: {e}",
                  flush=True)


if __name__ == "__main__":
    main()
