#!/usr/bin/env python
"""agnes_lint: the static invariant analyzer CLI (repo shim).

The CLI logic lives in agnes_tpu/analysis/lint_cli.py (importable, so
the `agnes-lint` console entry point resolves from the installed
package); this shim keeps the historical `scripts/agnes_lint.py`
invocation (ci.sh gate [1c], docs, muscle memory) working from a repo
checkout.  Backend env setup runs at import — before jax can load —
exactly as it did when the logic lived here.

Usage:
  scripts/agnes_lint.py --pass all            # the ci.sh gate
  scripts/agnes_lint.py --pass jaxpr --quick  # skip Ed25519-heavy traces
  scripts/agnes_lint.py --pass locks --json   # machine-readable report
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from agnes_tpu.analysis.lint_cli import main, setup_backend_env  # noqa: E402

setup_backend_env()

if __name__ == "__main__":
    sys.exit(main())
