#!/bin/bash
# Wait for the axon TPU claim to clear, then run bench.py, saving the
# JSON + stage log.  Run inside tmux so an interactive-shell timeout
# can never kill the TPU claim mid-flight (a killed claim wedges the
# relay for a long time — .claude/skills/verify/SKILL.md gotchas).
set -u
OUT=${1:-/tmp/bench_r04.json}
LOG=${2:-/tmp/bench_r04.log}
cd /root/repo
echo "[runner] probing for TPU..." >> "$LOG"
while true; do
    if timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
        echo "[runner] TPU alive at $(date)" >> "$LOG"
        break
    fi
    echo "[runner] still wedged at $(date); sleeping 120s" >> "$LOG"
    sleep 120
done
python bench.py > "$OUT" 2>> "$LOG"
echo "[runner] bench rc=$? at $(date)" >> "$LOG"
