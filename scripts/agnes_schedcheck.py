#!/usr/bin/env python
"""agnes_schedcheck: deterministic interleaving explorer for the
threaded serve host (agnes_tpu/analysis/schedcheck.py, ISSUE 19).

Runs the REAL ThreadedVoteService/Inbox/AdmissionQueue/VerifiedCache
code on real OS threads under a cooperative turnstile scheduler —
every lock acquire/release, inbox put/get, condition wait, native
call boundary and clock read is a serialized, explorable yield point
— and exhausts the schedule tree under CHESS-style iterative
preemption bounding with sleep-set pruning, checking vote
conservation, deadlock freedom, runtime lock order and the
`# schedcheck: atomic` span annotations on every schedule.  Pure CPU,
zero jax imports, ZERO XLA compiles: it shares the pre-test ci.sh
gate slot with agnes_lint and agnes_modelcheck.

Usage:
  scripts/agnes_schedcheck.py --scope smoke --json   # the ci.sh gate
  scripts/agnes_schedcheck.py --scope tiny           # seconds-fast
  scripts/agnes_schedcheck.py --self-test            # mutant drill:
                                  # the 3 shipped races re-introduced,
                                  # caught, ddmin-minimized, honest-
                                  # replayed clean
  scripts/agnes_schedcheck.py --scope smoke --no-sleep-sets  # debug

The CLI discovers its enclosing wall budget (AGNES_SCHEDCHECK_DEADLINE_S
or an ancestor `timeout N`) and stops cleanly with complete=false
partials rather than getting SIGKILLed — the same
real-value-or-sentinel contract as the bench gates.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from agnes_tpu.analysis.schedcheck import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
