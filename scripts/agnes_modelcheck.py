#!/usr/bin/env python
"""agnes_modelcheck: exhaustive bounded model checking of the
consensus core (agnes_tpu/analysis/modelcheck.py, ISSUE 6) and the
serve-plane admission layer (agnes_tpu/analysis/admission_mc.py,
ISSUE 7).

Explores EVERY delivery/timeout/partition schedule of the host plane
within a bounded scope — N nodes x fault assignment x weight vector x
depth x rounds — with canonical-state dedup, partial-order reduction,
and SYMMETRY reduction (least-orbit relabeling of interchangeable
honest nodes), checking the spec-level monitors (agreement, validity,
weighted quorum certificates, monotonicity, evidence completeness) on
every reachable state; the admission shards drive the real
AdmissionQueue/VerifiedCache under conservation/starvation/P-bound/
purity monitors.  Pure CPU, zero jax imports, ZERO XLA compiles: it
shares the pre-test ci.sh gate slot with agnes_lint.

Usage:
  scripts/agnes_modelcheck.py --scope smoke --json   # the ci.sh gate
  scripts/agnes_modelcheck.py --scope tiny           # seconds-fast
  scripts/agnes_modelcheck.py --self-test            # mutation drill
  scripts/agnes_modelcheck.py --scope smoke --no-por # debug aids
  scripts/agnes_modelcheck.py --scope smoke --no-sym

The CLI discovers its enclosing wall budget (AGNES_MODELCHECK_DEADLINE_S
or an ancestor `timeout N`) and stops cleanly with complete=false
partials rather than getting SIGKILLed — the same
real-value-or-sentinel contract as the bench gates.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from agnes_tpu.analysis.modelcheck import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
