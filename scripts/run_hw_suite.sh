#!/bin/bash
# Claim-safe hardware measurement suite: wait for the axon TPU to be
# reachable, then run, in one sequence (never concurrently — one TPU
# process at a time):
#   1. bench.py                   -> $OUTDIR/bench.json
#   2. harness configs 4, 2 and 5 -> $OUTDIR/config{4,2,5}.json
#   3. profile_verify.py          -> $OUTDIR/profile_verify.txt
# Run detached (setsid nohup) so an interactive-shell timeout can never
# kill a TPU claim mid-flight (.claude/skills/verify/SKILL.md gotchas).
set -u
OUTDIR=${1:-/tmp/hw_r05}
mkdir -p "$OUTDIR"
LOG="$OUTDIR/runner.log"
cd /root/repo
# Framework-wide compile-cache/codegen policy for every python below
# (incl. `-m agnes_tpu.harness.configs`, whose package import inits the
# backend before any in-module guard could run — compile_cache.py):
unset JAX_COMPILATION_CACHE_DIR
case "${XLA_FLAGS:-}" in
    *xla_cpu_parallel_codegen_split_count*) ;;
    *) export XLA_FLAGS="${XLA_FLAGS:-} --xla_cpu_parallel_codegen_split_count=1" ;;
esac
# the claim is arbitrated by the fcntl lease (scripts/tpu_holders.py
# TpuLease; VERDICT r5 weak #4): acquire it for THIS shell before any
# probe, refresh it between stages, release it on every exit path.
# The ps holder screen stays as a backstop for pre-lease processes.
lease() { python scripts/tpu_holders.py "lease-$1" --pid $$ "${@:2}"; }
trap 'lease release >> "$LOG" 2>&1' EXIT
echo "[runner] probing for TPU from $(date)" >> "$LOG"
while true; do
    if ! lease acquire --note "run_hw_suite $OUTDIR" >> "$LOG" 2>&1; then
        echo "[runner] TPU lease held by another process at $(date); deferring 180s" >> "$LOG"
        sleep 180
        continue
    fi
    # never probe while another agnes TPU process is alive (e.g. a
    # driver-launched round-end bench on pre-lease code, or ITS
    # in-flight marked probe): a second client's jax.devices() hangs
    # by design, and timeout-killing that probe mid-claim can wedge
    # the relay for hours.  Same screen bench.py uses
    # (scripts/tpu_holders.py; exit 0 = clear, 1 = held, 2 = check
    # broken -> probe anyway rather than deferring forever on a
    # broken helper).
    python scripts/tpu_holders.py >> "$LOG" 2>&1
    HRC=$?
    if [ "$HRC" -eq 1 ]; then
        # drop the lease BEFORE deferring: a lease-aware bench we are
        # deferring to would otherwise defer right back to our lease —
        # mutual wait until its busy budget emits a -1 (the exact
        # missing-scoreboard failure this protocol exists to fix)
        lease release >> "$LOG" 2>&1
        echo "[runner] TPU held by another process at $(date); deferring 180s" >> "$LOG"
        sleep 180
        continue
    elif [ "$HRC" -ne 0 ]; then
        echo "[runner] holder check failed rc=$HRC at $(date); probing anyway" >> "$LOG"
    fi
    if timeout 120 python -c "import jax; jax.devices()  # agnes_tpu_probe" >/dev/null 2>&1; then
        echo "[runner] TPU alive at $(date)" >> "$LOG"
        break
    fi
    echo "[runner] unreachable at $(date); sleeping 180s" >> "$LOG"
    sleep 180
done
lease refresh >> "$LOG" 2>&1
echo "[runner] bench.py start $(date)" >> "$LOG"
python bench.py > "$OUTDIR/bench.json" 2>> "$LOG"
echo "[runner] bench rc=$? end $(date)" >> "$LOG"
lease refresh >> "$LOG" 2>&1
echo "[runner] config4 start $(date)" >> "$LOG"
python -m agnes_tpu.harness.configs 4 > "$OUTDIR/config4.json" 2>> "$LOG"
echo "[runner] config4 rc=$? end $(date)" >> "$LOG"
lease refresh >> "$LOG" 2>&1
echo "[runner] config2 start $(date)" >> "$LOG"
python -m agnes_tpu.harness.configs 2 > "$OUTDIR/config2.json" 2>> "$LOG"
echo "[runner] config2 rc=$? end $(date)" >> "$LOG"
lease refresh >> "$LOG" 2>&1
echo "[runner] config5 start $(date)" >> "$LOG"
python -m agnes_tpu.harness.configs 5 > "$OUTDIR/config5.json" 2>> "$LOG"
echo "[runner] config5 rc=$? end $(date)" >> "$LOG"
lease refresh >> "$LOG" 2>&1
echo "[runner] profile_verify start $(date)" >> "$LOG"
python scripts/profile_verify.py > "$OUTDIR/profile_verify.txt" 2>> "$LOG"
echo "[runner] profile_verify rc=$? end $(date)" >> "$LOG"
echo "[runner] ALL DONE $(date)" >> "$LOG"
