"""TPU correctness + honest-timing test for the fused verify kernel."""
import os
import time
import numpy as np

# the XLA:CPU codegen/serialization race workaround must land in
# XLA_FLAGS before ANY agnes/jax import can initialize a backend
# (package __init__ side effects create device arrays) — see
# agnes_tpu/utils/compile_cache.py
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_cpu_parallel_codegen_split_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_cpu_parallel_codegen_split_count=1").strip()

import jax

import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from agnes_tpu.core import native
from agnes_tpu.crypto import ed25519_jax as E
from agnes_tpu.crypto import pallas_verify as pv
from agnes_tpu.crypto.encoding import vote_signing_bytes

B = 16384
print("building fixtures...", flush=True)
seeds = [i.to_bytes(4, "little") + bytes(28) for i in range(B)]
msgs = [vote_signing_bytes(1, 0, 0, i % 7) for i in range(B)]
pks = [native.pubkey(s) for s in seeds]
sigs = [native.sign(s, m) for s, m in zip(seeds, msgs)]
pub, sig, blocks = E.pack_verify_inputs_host(pks, msgs, sigs)
print("compiling kernel...", flush=True)
f = jax.jit(pv.verify_batch_pallas)
t0 = time.time()
ok = f(pub, sig, blocks)
okh = np.asarray(ok)
print(f"compile+run: {time.time()-t0:.1f}s  all_ok={okh.all()} n={okh.sum()}",
      flush=True)
assert okh.all()

sigs2 = [bytearray(s) for s in sigs[:4]]
sigs2[1][5] ^= 4
pub2, sig2, blocks2 = E.pack_verify_inputs_host(
    pks[:4], msgs[:4], [bytes(s) for s in sigs2])
ok2 = np.asarray(f(pub2, sig2, blocks2))
print("negative check:", ok2, flush=True)
assert list(ok2) == [True, False, True, True]

iters = 20
t0 = time.time()
outs = [f(pub, sig, blocks) for _ in range(iters)]
for o in outs:
    _ = np.asarray(o[:1])
dt = (time.time() - t0) / iters
print(f"verify v2: {dt*1e3:.2f} ms/batch of {B} -> {B/dt:,.0f} verifies/s",
      flush=True)
