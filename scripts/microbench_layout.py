"""Microbenchmark: field-mul chain in two Pallas layouts.

A: current [20, B] (limbs on sublanes, batch on lanes)
B: vreg-plane [20, bh, 128] (batch tiled (8,128); each limb = vregs)

Times a chain of N dependent rounds of PAR independent fe_muls.
"""
from __future__ import annotations

import os
import sys
import time

# de-race XLA:CPU codegen before any backend init (compile_cache.py)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_cpu_parallel_codegen_split_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_cpu_parallel_codegen_split_count=1").strip()

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from agnes_tpu.crypto.field_jax import BITS, FOLD, LMASK, NLIMBS, I32

N_CHAIN = 64     # sequential rounds
PAR = 4          # independent muls per round


def _vpass0(r, fold):
    lo = r & LMASK
    hi = r >> BITS
    if fold is None:
        lo = jnp.concatenate([lo[:-1], r[-1:]], axis=0)
        shift = jnp.concatenate([jnp.zeros_like(hi[:1]), hi[:-1]], axis=0)
        return lo + shift
    shift = jnp.concatenate([hi[-1:] * fold, hi[:-1]], axis=0)
    return lo + shift


def _carry0(r, passes=4):
    for _ in range(passes):
        r = _vpass0(r, FOLD)
    return r


def _shift_rows(term, i):
    pad = [(i, NLIMBS - i)] + [(0, 0)] * (term.ndim - 1)
    return jnp.pad(term, pad)


def _fe_mul(a, b):
    cols = _shift_rows(a[0:1] * b, 0)
    for i in range(1, NLIMBS):
        cols = cols + _shift_rows(a[i:i + 1] * b, i)
    lo, hi = cols[:NLIMBS], cols[NLIMBS:]
    for _ in range(3):
        hi = _vpass0(hi, None)
    return _carry0(lo + FOLD * hi)


def _chain_kernel(x_ref, y_ref, out_ref):
    xs = [x_ref[:] + i for i in range(PAR)]
    y = y_ref[:]
    for _ in range(N_CHAIN):
        xs = [_fe_mul(x, y) for x in xs]
    acc = xs[0]
    for x in xs[1:]:
        acc = acc + x
    out_ref[:] = acc


def bench(shape_full, block, label, iters=60):
    """shape_full/block: limbs leading, batch dims trailing; grid over
    the first batch dim."""
    x = jnp.asarray(np.random.randint(0, 8192, shape_full, np.int32))
    y = jnp.asarray(np.random.randint(0, 8192, shape_full, np.int32))
    nb = len(block) - 1
    grid_n = shape_full[1] // block[1]

    def imap(g):
        return (0, g) + (0,) * (nb - 1)

    spec = pl.BlockSpec(block, imap, memory_space=pltpu.VMEM)
    f = pl.pallas_call(
        _chain_kernel, grid=(grid_n,), in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(shape_full, jnp.int32))
    fj = jax.jit(f)
    out = fj(x, y)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fj(x, y)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    total_lanes = int(np.prod(shape_full[1:]))
    n_mul = N_CHAIN * PAR
    ns = dt / (total_lanes * n_mul) * 1e9
    print(f"{label:30s} dt={dt*1e3:7.2f} ms  {ns:.3f} ns/mul/lane"
          f"  ({total_lanes*n_mul/dt/1e9:.2f} G mul-lanes/s)")


def main():
    global N_CHAIN
    T = 16384
    for b in (512, 1024):
        bench((NLIMBS, T), (NLIMBS, b), f"A [20,{b}] sublane")
    for bh in (8, 16):
        bench((NLIMBS, T // 128, 128), (NLIMBS, bh, 128),
              f"B [20,{bh},128] vreg-plane")
    # scaling sanity: double the chain, expect ~2x time
    N_CHAIN = 128
    bench((NLIMBS, T), (NLIMBS, 512), "A [20,512] 2x chain")
    bench((NLIMBS, T // 128, 128), (NLIMBS, 8, 128), "B [20,8,128] 2x chain")


if __name__ == "__main__":
    main()
