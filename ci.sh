#!/bin/bash
# CI for agnes_tpu (SURVEY.md §5 "TSAN/ASAN CI jobs" slot).
#
#   1.  sanitizer pass — rebuild the C++ core with ASan+UBSan and run
#       the C++-vs-Python differential suite plus the adversarial C-ABI
#       fuzz file under it (the raw-pointer ctypes surface, capi.cpp);
#   1b. TSAN pass — the ingest event loop's async worker thread
#       (core/native/ingest.cpp) under ThreadSanitizer via a dedicated
#       fully-instrumented stress binary (tests/native/tsan_stress.cpp:
#       3 producer threads racing the tick protocol).  A binary rather
#       than pytest because TSAN through python drowns findings in
#       uninstrumented jaxlib/Eigen thread-pool noise;
#   2.  full pytest on the virtual 8-device CPU mesh;
#   3.  bench smoke (CI_BENCH=0 skips; the driver runs the real bench
#       on TPU hardware at end of round).
#
# The purity/testability argument the whole design serves (reference
# README.md:8-14) is enforced by (2); memory safety of the native layer
# by (1); freedom from data races in the host-driver concurrency by (1b).
set -euo pipefail
cd "$(dirname "$0")"
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
echo "=== [1/3] ASan+UBSan: native differential + C-ABI fuzz ==="
ASAN_SO="$(g++ -print-file-name=libasan.so)"
UBSAN_SO="$(g++ -print-file-name=libubsan.so)"
# halt_on_error makes sanitizer findings fail CI; leak checking is off
# because the host python itself leaks by design.  Reports go to
# san_report.* files (pytest's capture can swallow the stderr report
# when halt_on_error kills the process mid-test).
SAN_LOG="$(mktemp -d)/san_report"
AGNES_NATIVE_SANITIZE="address,undefined" \
  LD_PRELOAD="$ASAN_SO $UBSAN_SO" \
  ASAN_OPTIONS="detect_leaks=0,halt_on_error=1,log_path=$SAN_LOG" \
  UBSAN_OPTIONS="halt_on_error=1,print_stacktrace=1,log_path=$SAN_LOG" \
  python -m pytest tests/test_native_core.py tests/test_capi_fuzz.py \
    tests/test_native_ingest.py -q -p no:cacheprovider \
  || { cat "$SAN_LOG".* 2>/dev/null; exit 1; }

echo "=== [1b/3] TSAN: ingest worker-thread stress ==="
TSAN_BIN="$(mktemp -d)/tsan_stress"
g++ -fsanitize=thread -O1 -g -std=c++17 -pthread -o "$TSAN_BIN" \
  tests/native/tsan_stress.cpp \
  agnes_tpu/core/native/ingest.cpp agnes_tpu/core/native/core.cpp \
  agnes_tpu/core/native/sha512.cpp agnes_tpu/core/native/ed25519.cpp \
  agnes_tpu/core/native/capi.cpp
TSAN_OPTIONS="halt_on_error=1" "$TSAN_BIN"

echo "=== [2/3] full test suite (virtual 8-device CPU mesh) ==="
# step 1 already ran the native differential + fuzz files under ASan
# (a strict superset of the non-sanitized run) — skip them here
python -m pytest tests/ -q -p no:cacheprovider \
  --ignore=tests/test_native_core.py --ignore=tests/test_capi_fuzz.py \
  --ignore=tests/test_native_ingest.py

if [ "${CI_BENCH:-1}" != "0" ]; then
  echo "=== [3/3] bench ==="
  python bench.py
else
  echo "=== [3/3] bench skipped (CI_BENCH=0) ==="
fi
echo "CI GREEN"
