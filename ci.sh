#!/bin/bash
# CI for agnes_tpu (SURVEY.md §5 "TSAN/ASAN CI jobs" slot).
#
#   1.  sanitizer pass — rebuild the C++ core with ASan+UBSan and run
#       the C++-vs-Python differential suite plus the adversarial C-ABI
#       fuzz file under it (the raw-pointer ctypes surface, capi.cpp);
#   1b. TSAN pass — the ingest event loop's async worker thread
#       (core/native/ingest.cpp) under ThreadSanitizer via a dedicated
#       fully-instrumented stress binary (tests/native/tsan_stress.cpp:
#       3 producer threads racing the tick protocol).  A binary rather
#       than pytest because TSAN through python drowns findings in
#       uninstrumented jaxlib/Eigen thread-pool noise;
#   1d. bounded model checker gate — exhaustive small-scope schedule
#       exploration of the consensus core (agnes_modelcheck --scope
#       smoke): zero XLA compiles, spec-level property monitors,
#       real-value-or-sentinel under the enclosing timeout;
#   1e. interleaving explorer gate — deterministic schedule
#       exploration of the REAL threaded serve host code
#       (agnes_schedcheck --scope smoke): cooperative turnstile over
#       real OS threads, preemption bounding + sleep sets,
#       conservation/deadlock/lock-order/atomic-span monitors;
#   2.  full pytest on the virtual 8-device CPU mesh;
#   2b. the 16 interpret-heavy crypto tests in isolated child
#       interpreters, VERBOSE, so their per-file pass/fail lands in
#       the gate summary instead of hiding behind a skip count
#       (VERDICT r5 weak #5);
#   3.  bench deadline gate: `timeout 60 bench.py` against a
#       forced-dead backend must exit 0 with a parseable -1 JSON
#       record as its last stdout line (VERDICT r5 weak #1 — the
#       crash-safe verdict contract, bench.py module docstring);
#   3b. serve smoke gate (single-device streaming plane, CPU);
#   3c. mesh serve smoke gate (ISSUE 3: threaded host + dense-lane
#       sharded dispatch on a faked 2-device CPU mesh);
#   3f. native admission smoke gate (ISSUE 14: the C++ admission
#       front-end vs the Python queue on the same traffic);
#   3g. multi-host serve smoke gate (ISSUE 15: a 2-process
#       jax.distributed pod — per-host HostShard front-ends over one
#       global-SPMD mesh — spawned under the crash-safe deadline);
#   4.  bench smoke (CI_BENCH=0 skips; the driver runs the real bench
#       on TPU hardware at end of round).
#
# The purity/testability argument the whole design serves (reference
# README.md:8-14) is enforced by (2); memory safety of the native layer
# by (1); freedom from data races in the host-driver concurrency by (1b).
set -euo pipefail
cd "$(dirname "$0")"
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
echo "=== [1/3] ASan+UBSan: native differential + C-ABI fuzz ==="
ASAN_SO="$(g++ -print-file-name=libasan.so)"
UBSAN_SO="$(g++ -print-file-name=libubsan.so)"
# halt_on_error makes sanitizer findings fail CI; leak checking is off
# because the host python itself leaks by design.  Reports go to
# san_report.* files (pytest's capture can swallow the stderr report
# when halt_on_error kills the process mid-test).
SAN_LOG="$(mktemp -d)/san_report"
# tests/test_native_admission.py rides the same sanitized build: the
# ISSUE 14 admission screens (admission.cpp + the sha512.cpp SHA-256
# schedule) get their differential + hostile-record suites under
# ASan/UBSan, wired into the existing native build gate
AGNES_NATIVE_SANITIZE="address,undefined" \
  LD_PRELOAD="$ASAN_SO $UBSAN_SO" \
  ASAN_OPTIONS="detect_leaks=0,halt_on_error=1,log_path=$SAN_LOG" \
  UBSAN_OPTIONS="halt_on_error=1,print_stacktrace=1,log_path=$SAN_LOG" \
  python -m pytest tests/test_native_core.py tests/test_capi_fuzz.py \
    tests/test_native_ingest.py tests/test_native_admission.py \
    -q -p no:cacheprovider \
  || { cat "$SAN_LOG".* 2>/dev/null; exit 1; }

echo "=== [1b/3] TSAN: ingest worker-thread stress ==="
TSAN_BIN="$(mktemp -d)/tsan_stress"
g++ -fsanitize=thread -O1 -g -std=c++17 -pthread -o "$TSAN_BIN" \
  tests/native/tsan_stress.cpp \
  agnes_tpu/core/native/ingest.cpp agnes_tpu/core/native/core.cpp \
  agnes_tpu/core/native/sha512.cpp agnes_tpu/core/native/ed25519.cpp \
  agnes_tpu/core/native/capi.cpp
TSAN_OPTIONS="halt_on_error=1" "$TSAN_BIN"
# ISSUE 19: the admission queue's shared surface under TSAN — the
# native half of the schedcheck story ([1e] below serializes every
# PYTHON-visible yield point, but ag_adm_* release the GIL for their
# whole span; this binary races producers / a dispatch-shaped drainer
# / the observability reader inside that span).  ISSUE 20 adds stage
# 2: producers racing across >= 2 shards through the ag_adms_ fan-in
# while a phase drainer runs the fused k-way merge + zero-copy
# densify (admission_shards.cpp + admission_phases.cpp).
TSAN_ADM_BIN="$(mktemp -d)/tsan_admission_stress"
g++ -fsanitize=thread -O1 -g -std=c++17 -pthread -o "$TSAN_ADM_BIN" \
  tests/native/tsan_admission_stress.cpp \
  agnes_tpu/core/native/admission.cpp \
  agnes_tpu/core/native/admission_phases.cpp \
  agnes_tpu/core/native/admission_shards.cpp \
  agnes_tpu/core/native/sha512.cpp
TSAN_OPTIONS="halt_on_error=1" "$TSAN_ADM_BIN"

echo "=== [1c/4] static invariant analyzer (abstract tracing, no XLA compiles) ==="
# ISSUE 4: the five analysis passes — jaxpr audit (donation honored,
# collective census + verify_chunk invariance, no host callbacks,
# dtype policy), retrace warmup-coverage proof, serve lock-order lint,
# repo lint, and the ISSUE 13 jaxpr op-count CENSUS (hot-entry traced
# op totals vs tests/baselines/jaxpr_census.json, ±10% — the graph
# diet's regression gate; runs last so it reuses the audit's traces)
# — run BEFORE the test gates because they are the cheap proof that a
# TPU round won't stall on a structural regression (the PR 3
# double-compile class).  Budget: < 280s of pure CPU tracing (the
# ISSUE 10 bls_aggregate shard is one ~45s Barrett-field trace, the
# ISSUE 13 bls_pairing_product shard adds ~25s of rolled pairing
# bodies); the enclosing timeout is head-room, not the target.
LINT_JSON="$(mktemp -d)/agnes_lint.json"
timeout -k 10 540 python scripts/agnes_lint.py --pass all \
  > "$LINT_JSON" || {
    echo "static analyzer FAILED:"; tail -5 "$LINT_JSON"; exit 1; }
LINT_NUMS="${LINT_JSON%.json}.nums"
python - "$LINT_JSON" "$LINT_NUMS" <<'PY'
import json, sys
rep = json.loads(open(sys.argv[1]).read().strip().splitlines()[-1])
assert rep["ok"], rep["findings"]
audited = rep["metrics"]["analysis_entries_audited"]
assert audited > 0, rep["metrics"]
census = rep["passes"].get("census", {})
assert census.get("baseline_entries"), census   # the gate ran + compared
per_pass = ", ".join(f"{k}:{v['seconds']}s"
                     for k, v in rep["passes"].items())
print(f"static analyzer OK: {audited} entries audited clean, census "
      f"clean over {len(census['baseline_entries'])} entries in "
      f"{rep['seconds']}s ({per_pass})")
with open(sys.argv[2], "w") as f:
    f.write(f"{census.get('drift_entries', 0)}\n")
PY
read -r CENSUS_DRIFT < "$LINT_NUMS"
# the [3e] bench's verdict record carries the census drift count the
# same way it carries the modelcheck numbers (real-value-or-sentinel)
export AGNES_CENSUS_DRIFT_ENTRIES="${CENSUS_DRIFT:?}"

echo "=== [1d/4] bounded model checker (exhaustive smoke scope, no XLA) ==="
# ISSUE 6 + ISSUE 7: exhaustive bounded model checking of the
# consensus core — every delivery/timeout/partition schedule within
# the smoke bounds, canonical-state dedup + partial-order reduction +
# SYMMETRY reduction (least-orbit digests over interchangeable honest
# nodes; the reported orbit reduction is measured against PR 6's
# unreduced baseline), WEIGHTED-validator scopes (asymmetric power
# vectors moving every +2/3 boundary), EPOCH shards (ISSUE 9:
# validator-set changes at height boundaries, per-epoch symmetry
# groups, epoch-indexed quorum certificates), sleepy-CHURN shards
# (TOB-SVD sleep/wake schedules under a churn budget), the
# serve-plane ADMISSION model shards (AdmissionQueue/batcher/dedup-split soundness monitors,
# analysis/admission_mc.py), and the MEMBERSHIP shards (ISSUE 17:
# host-level sleep/wake + epoch-boundary repartition over the real
# MembershipEpoch — range-partition disjointness/coverage and
# no-decision-loss monitors, analysis/membership_mc.py)
# — agreement/validity/quorum/monotonicity/
# evidence + conservation/starvation/pbound/purity monitors on every
# reachable state.  Pure CPU, zero jax imports, zero compiles; the CLI
# discovers the enclosing timeout and degrades to a complete=false
# partial record instead of getting SIGKILLed (real-value-or-sentinel,
# like [3c]/[3d]).
MC_JSON="$(mktemp -d)/agnes_modelcheck.json"
MC_RC=0
# 540s: the ISSUE 9 epoch + churn shards add ~150k canonical states
# (~100 worker-seconds) on top of the ISSUE 7 envelope; still
# timeout-bounded, and the CLI degrades to a sentinel partial inside it
timeout -k 10 540 python scripts/agnes_modelcheck.py --scope smoke --json \
  > "$MC_JSON" || MC_RC=$?
if [ "$MC_RC" -ne 0 ]; then
  echo "model checker FAILED (rc=$MC_RC):"; tail -5 "$MC_JSON"; exit 1
fi
# one parse, as a standalone step so an assertion failure FAILS the
# gate (a `$(...)` inside a redirect word would have its exit status
# discarded under set -e); the numbers land in a file for the env
# exports the [4/4] bench's verdict records carry alongside
# analysis_entries_audited (utils/metrics.py names, PR 4 pattern)
MC_NUMS="${MC_JSON%.json}.nums"
python - "$MC_JSON" "$MC_NUMS" <<'PY'
import json, sys
rep = json.loads(open(sys.argv[1]).read().strip().splitlines()[-1])
assert rep["ok"], [c["violations"] for c in rep["configs"].values()]
assert rep["states_explored"] > 0, rep
assert rep["violations"] == 0, rep
if rep["complete"]:
    # per-shard acceptance floors (rebalanced for ISSUE 7: the
    # symmetry-reduced consensus sweep visits FEWER states by design,
    # so the old 50k aggregate floor is replaced by per-domain floors
    # sized to the measured envelope — consensus incl. weighted scopes
    # ~301k, admission ~210k).  A COMPLETE run under a floor means
    # someone collapsed an envelope or broke an explorer; a
    # deadline-sentinel partial is exempt (slow box, not a regression).
    assert rep["consensus_states"] >= 200_000, rep["consensus_states"]
    assert rep["admission_states"] >= 150_000, rep["admission_states"]
    # ISSUE 17 floor: the membership shards (host join/leave +
    # epoch-boundary repartition model, analysis/membership_mc.py)
    # must EXHAUST >= 50k canonical states (measured envelope ~226k:
    # mem_churn2 ~205k, mem_pair_deep ~22k)
    assert rep["membership_states"] >= 50_000, rep["membership_states"]
    # ISSUE 9 floors: the epoch + churn shards must EXHAUST >= 100k
    # combined canonical states (measured envelope ~154k: epoch ~71k,
    # churn ~83k), and the PER-EPOCH symmetry groups must bite —
    # reduction > 1 on the epoch shards (measured ~1.98x)
    assert rep["epoch_states"] + rep["churn_states"] >= 100_000, \
        (rep["epoch_states"], rep["churn_states"])
    assert rep["epoch_orbit_reduction"] > 1, rep["epoch_orbit_reduction"]
    # the symmetry reduction must stay real: > 1.5x fewer visited
    # states than PR 6's unreduced baseline on the shared configs
    assert rep["sym_orbit_reduction"] > 1.5, rep["sym_orbit_reduction"]
kind = "EXHAUSTED" if rep["complete"] else "partial (deadline sentinel)"
print(f"model checker OK: {rep['states_explored']} canonical states "
      f"{kind} (consensus {rep['consensus_states']}, admission "
      f"{rep['admission_states']}, epoch {rep['epoch_states']}, churn "
      f"{rep['churn_states']}, membership {rep['membership_states']}, "
      f"orbit reduction "
      f"{rep['sym_orbit_reduction']}x overall / "
      f"{rep['epoch_orbit_reduction']}x per-epoch), 0 violations in "
      f"{rep['seconds']}s ({rep['transitions']} transitions)")
with open(sys.argv[2], "w") as f:
    f.write(f"{rep['states_explored']} {rep['violations']} "
            f"{rep['sym_orbit_reduction']} {rep['admission_states']} "
            f"{rep['epoch_states']} {rep['churn_states']} "
            f"{rep['epoch_orbit_reduction']} "
            f"{rep['membership_states']}\n")
PY
read -r MC_STATES MC_VIOLS MC_SYMRED MC_ADM MC_EPOCH MC_CHURN MC_EPRED \
  MC_MEM < "$MC_NUMS"
export AGNES_MODELCHECK_STATES_EXPLORED="${MC_STATES:?}"
export AGNES_MODELCHECK_VIOLATIONS="${MC_VIOLS:?}"
export AGNES_MODELCHECK_SYM_ORBIT_REDUCTION="${MC_SYMRED:?}"
export AGNES_MODELCHECK_ADMISSION_STATES="${MC_ADM:?}"
export AGNES_MODELCHECK_EPOCH_STATES="${MC_EPOCH:?}"
export AGNES_MODELCHECK_CHURN_STATES="${MC_CHURN:?}"
export AGNES_MODELCHECK_EPOCH_ORBIT_REDUCTION="${MC_EPRED:?}"
export AGNES_MODELCHECK_MEMBERSHIP_STATES="${MC_MEM:?}"

echo "=== [1e/4] interleaving explorer (threaded serve host, no XLA) ==="
# ISSUE 19: CHESS-style deterministic schedule exploration of the REAL
# ThreadedVoteService/Inbox/AdmissionQueue/VerifiedCache code — every
# lock acquire/release, inbox put/get, condition wait, native call
# boundary and clock read serialized under a cooperative turnstile,
# iterative preemption bounding + sleep-set pruning, vote-conservation
# / deadlock / lock-order / atomic-span monitors on every schedule.
# Zero jax imports, zero XLA compiles; the CLI discovers the enclosing
# timeout and degrades to a complete=false partial (real-value-or-
# sentinel, like [1d]).
SCHED_JSON="$(mktemp -d)/agnes_schedcheck.json"
SCHED_RC=0
timeout -k 10 300 python scripts/agnes_schedcheck.py --scope smoke \
  --json > "$SCHED_JSON" || SCHED_RC=$?
if [ "$SCHED_RC" -ne 0 ]; then
  echo "interleaving explorer FAILED (rc=$SCHED_RC):"
  tail -5 "$SCHED_JSON"; exit 1
fi
SCHED_NUMS="${SCHED_JSON%.json}.nums"
python - "$SCHED_JSON" "$SCHED_NUMS" <<'PY'
import json, sys
rep = json.loads(open(sys.argv[1]).read().strip().splitlines()[-1])
assert rep["ok"], [c["violations"] for c in rep["configs"].values()]
assert rep["violations"] == 0, rep
assert rep["schedules_explored"] > 0, rep
if rep["complete"]:
    # acceptance floor: a COMPLETE smoke sweep visits >= 1k distinct
    # schedules (measured envelope well above; a complete run under
    # the floor means someone collapsed a config or broke the DFS) —
    # a deadline-sentinel partial is exempt (slow box, not a
    # regression)
    assert rep["schedules_explored"] >= 1_000, rep["schedules_explored"]
kind = "EXHAUSTED" if rep["complete"] else "partial (deadline sentinel)"
print(f"interleaving explorer OK: {rep['schedules_explored']} "
      f"schedules {kind} across {len(rep['configs'])} configs, "
      f"0 violations in {rep['seconds']}s")
with open(sys.argv[2], "w") as f:
    f.write(f"{rep['schedules_explored']} {rep['violations']}\n")
PY
read -r SCHED_SCHEDS SCHED_VIOLS < "$SCHED_NUMS"
export AGNES_SCHEDCHECK_SCHEDULES_EXPLORED="${SCHED_SCHEDS:?}"
export AGNES_SCHEDCHECK_VIOLATIONS="${SCHED_VIOLS:?}"

echo "=== [2/4] full test suite (virtual 8-device CPU mesh) ==="
# step 1 already ran the native differential + fuzz files under ASan
# (a strict superset of the non-sanitized run) — skip them here; the
# heavy isolated files get their own verbose step 2b below
python -m pytest tests/ -q -p no:cacheprovider \
  --ignore=tests/test_native_core.py --ignore=tests/test_capi_fuzz.py \
  --ignore=tests/test_native_ingest.py \
  --ignore=tests/test_native_admission.py \
  --ignore=tests/test_zz_heavy_isolated.py

echo "=== [2b/4] isolated heavy crypto tests (child interpreters) ==="
# one child process per interpret-heavy file (tests/conftest.py has
# the XLA:CPU segfault history); -v so each file's verdict is a line
# we can lift into the gate summary rather than a bare skip count
HEAVY_LOG="$(mktemp -d)/heavy.log"
python -m pytest tests/test_zz_heavy_isolated.py -v -p no:cacheprovider \
  2>&1 | tee "$HEAVY_LOG"

echo "=== [3/4] bench deadline gate (forced-dead backend) ==="
# the crash-safe verdict contract: with the probe stubbed to hang
# forever and ONLY the enclosing `timeout 60` as its budget, bench
# must exit 0 BEFORE the timeout and its last stdout line must be the
# parseable -1 record the round driver scrapes
DEAD_DIR="$(mktemp -d)"
DEAD_RC=0
AGNES_BENCH_FORCE_DEAD=1 AGNES_TPU_LEASE_PATH="$DEAD_DIR/tpu.lease" \
  timeout 60 python bench.py > "$DEAD_DIR/bench.json" \
  2> "$DEAD_DIR/bench.err" || DEAD_RC=$?
if [ "$DEAD_RC" -ne 0 ]; then
  echo "deadline gate FAILED: bench exited rc=$DEAD_RC (124 = the"
  echo "enclosing timeout killed it — the exact r5 failure mode)"
  tail -5 "$DEAD_DIR/bench.err"
  exit 1
fi
python - "$DEAD_DIR/bench.json" <<'PY'
import json, sys
lines = [l for l in open(sys.argv[1]).read().strip().splitlines() if l]
assert lines, "bench printed no stdout"
rec = json.loads(lines[-1])
assert rec["metric"] == "pipeline_votes_per_sec", rec
assert rec["value"] == -1 and rec["vs_baseline"] == -1, rec
assert rec.get("note"), rec
print(f"deadline gate OK: -1 verdict emitted ({rec['note'][:70]}...)")
PY

echo "=== [3b/4] serve smoke gate (CPU, tiny shape ladder) ==="
# the streaming serve plane (agnes_tpu/serve, ISSUE 2) closed-loop on
# CPU at a tiny shape, bounded by an enclosing timeout that the bench
# discovers (the SAME crash-safe contract as the gate above): on a box
# fast enough to beat the fused-step compile the last stdout line is a
# real pipeline_fused_votes_per_sec record; on a slower box the
# self-armed alarm emits the -1 sentinel BEFORE the timeout kills us.
# Either record passes; rc != 0 (124 = SIGKILLed without a verdict —
# the r5 failure mode) fails.
SERVE_DIR="$(mktemp -d)"
SERVE_RC=0
# ISSUE 8: the smoke runs with the flight recorder's heartbeat ON
# (1 s interval, file in the gate dir) and self-scrapes its /metrics
# endpoint once — the observability asserts below ride this one run
AGNES_BENCH_SERVE_SMOKE=1 AGNES_TPU_LEASE_PATH="$SERVE_DIR/tpu.lease" \
  AGNES_HEARTBEAT_PATH="$SERVE_DIR/heartbeat.ndjson" \
  AGNES_HEARTBEAT_INTERVAL_S=1 AGNES_SERVE_SMOKE_METRICS=1 \
  timeout -k 10 900 python bench.py > "$SERVE_DIR/serve.json" \
  2> "$SERVE_DIR/serve.err" || SERVE_RC=$?
if [ "$SERVE_RC" -ne 0 ]; then
  echo "serve smoke gate FAILED: bench exited rc=$SERVE_RC"
  tail -5 "$SERVE_DIR/serve.err"
  exit 1
fi
python - "$SERVE_DIR/serve.json" <<'PY'
import json, sys
lines = [l for l in open(sys.argv[1]).read().strip().splitlines() if l]
assert lines, "serve smoke printed no stdout"
rec = json.loads(lines[-1])
assert rec["metric"] == "pipeline_fused_votes_per_sec", rec
assert isinstance(rec["value"], (int, float)), rec
assert rec["value"] == -1 or rec["value"] > 0, rec
kind = "-1 sentinel (deadline contract)" if rec["value"] == -1 \
    else f"{rec['value']:.0f} votes/s"
print(f"serve smoke gate OK: {kind}")
PY
echo "=== [3b'/4] observability gate (heartbeat schema + /metrics scrape) ==="
# ISSUE 8: whatever the smoke's outcome (real value or deadline
# sentinel), the flight recorder must have left a heartbeat NDJSON at
# the armed path and EVERY line must pass the schema check (the same
# parser `agnes-metrics` uses on a wedged round's trail); on a real
# (non-sentinel) smoke the record must also prove one clean /metrics
# scrape, the submit->decision p50/p99, and per-entry compile_ms —
# real-value-or-sentinel, like gates [3c]/[3d].
timeout -k 5 60 python scripts/agnes_metrics.py --check \
  "$SERVE_DIR/heartbeat.ndjson"
python - "$SERVE_DIR/serve.json" "$SERVE_DIR/heartbeat.ndjson" <<'PY'
import json, sys
rec = json.loads([l for l in open(sys.argv[1]).read().strip()
                  .splitlines() if l][-1])
assert rec.get("heartbeat_path"), rec
hb = []
for l in open(sys.argv[2]):
    l = l.strip()
    if not l:
        continue
    try:
        hb.append(json.loads(l))
    except ValueError:
        pass   # trailing death-cut line: --check above already vetted
assert hb, "heartbeat file holds no valid line"
if rec["value"] == -1:
    print(f"observability gate OK: {len(hb)} heartbeat line(s); "
          f"scrape/latency asserts skipped (deadline sentinel)")
else:
    assert rec.get("metrics_scrape_ok") is True, rec
    assert rec.get("serve_submit_to_decision_p50_s", 0) > 0, rec
    assert rec.get("serve_submit_to_decision_p99_s", 0) > 0, rec
    comp = [k for k in rec if k.startswith("compile_ms_")]
    assert comp, "verdict record carries no compile_ms_<entry> keys"
    print(f"observability gate OK: {len(hb)} heartbeat line(s), "
          f"clean scrape of {rec['metrics_scrape_series']} series, "
          f"e2e p50 {rec['serve_submit_to_decision_p50_s']:.4f}s / "
          f"p99 {rec['serve_submit_to_decision_p99_s']:.4f}s, "
          f"{len(comp)} compile_ms entries")
PY
# the human postmortem view, straight onto the gate log (what the
# next wedged-round investigation will run against the round's trail)
timeout -k 5 60 python scripts/agnes_metrics.py \
  "$SERVE_DIR/heartbeat.ndjson" || true

echo "=== [3c/4] mesh serve smoke gate (faked 2-device CPU mesh) ==="
# ISSUE 3: the serve plane on a MESH — ThreadedVoteService event loop
# + dense-lane sharded fused dispatch — on a 2-device CPU platform
# faked via --xla_force_host_platform_device_count (bench.py sets the
# flag itself from AGNES_BENCH_SERVE_MESH_SMOKE).  Same crash-safe
# contract as the gates above: a real pipeline_serve_mesh_votes_per_sec
# record or the -1 sentinel, rc 0 either way.
MESH_DIR="$(mktemp -d)"
MESH_RC=0
AGNES_BENCH_SERVE_MESH_SMOKE=1 AGNES_TPU_LEASE_PATH="$MESH_DIR/tpu.lease" \
  timeout -k 10 900 python bench.py > "$MESH_DIR/serve_mesh.json" \
  2> "$MESH_DIR/serve_mesh.err" || MESH_RC=$?
if [ "$MESH_RC" -ne 0 ]; then
  echo "mesh serve smoke gate FAILED: bench exited rc=$MESH_RC"
  tail -5 "$MESH_DIR/serve_mesh.err"
  exit 1
fi
python - "$MESH_DIR/serve_mesh.json" <<'PY'
import json, sys
lines = [l for l in open(sys.argv[1]).read().strip().splitlines() if l]
assert lines, "mesh serve smoke printed no stdout"
rec = json.loads(lines[-1])
assert rec["metric"] == "pipeline_serve_mesh_votes_per_sec", rec
assert isinstance(rec["value"], (int, float)), rec
assert rec["value"] == -1 or rec["value"] > 0, rec
kind = "-1 sentinel (deadline contract)" if rec["value"] == -1 \
    else f"{rec['value']:.0f} votes/s"
print(f"mesh serve smoke gate OK: {kind}")
PY

echo "=== [3d/4] dedup serve smoke gate (duplicated traffic, CPU) ==="
# ISSUE 5: the verified-vote dedup cache + split-rung dispatch under
# duplication factor 8 — the probe runs dedup-on then replays the same
# traffic dedup-off in-process for the speedup ratio.  Same crash-safe
# contract: a real pipeline_serve_dedup_votes_per_sec record (which
# must then show hit rate > 0 and zero unexpected retraces) or the -1
# sentinel, rc 0 either way.
DEDUP_DIR="$(mktemp -d)"
DEDUP_RC=0
AGNES_BENCH_SERVE_DEDUP_SMOKE=1 AGNES_BENCH_SERVE_DUP=8 \
  AGNES_TPU_LEASE_PATH="$DEDUP_DIR/tpu.lease" \
  timeout -k 10 900 python bench.py > "$DEDUP_DIR/serve_dedup.json" \
  2> "$DEDUP_DIR/serve_dedup.err" || DEDUP_RC=$?
if [ "$DEDUP_RC" -ne 0 ]; then
  echo "dedup serve smoke gate FAILED: bench exited rc=$DEDUP_RC"
  tail -5 "$DEDUP_DIR/serve_dedup.err"
  exit 1
fi
python - "$DEDUP_DIR/serve_dedup.json" <<'PY'
import json, sys
lines = [l for l in open(sys.argv[1]).read().strip().splitlines() if l]
assert lines, "dedup serve smoke printed no stdout"
rec = json.loads(lines[-1])
assert rec["metric"] == "pipeline_serve_dedup_votes_per_sec", rec
assert isinstance(rec["value"], (int, float)), rec
assert rec["value"] == -1 or rec["value"] > 0, rec
if rec["value"] == -1:
    print("dedup serve smoke gate OK: -1 sentinel (deadline contract)")
else:
    assert rec["serve_cache_hit_rate"] > 0, rec
    assert rec["retrace_unexpected"] == 0, rec
    # acceptance is >= 3x at dup 8 on an idle box (measured 4x); the
    # gate asserts a conservative floor so a loaded CI box cannot
    # flake, while a split-rung path SLOWER than dedup-off still fails
    assert rec["serve_dedup_speedup"] > 1.5, rec
    print(f"dedup serve smoke gate OK: {rec['value']:.0f} votes/s "
          f"(hit rate {rec['serve_cache_hit_rate']}, "
          f"{rec['serve_dedup_speedup']}x vs dedup-off)")
PY

echo "=== [3e/4] BLS aggregate-lane smoke gate (CPU) ==="
# ISSUE 10 + ISSUE 13: the BLS aggregate-precommit lane — class fold
# at admission, device MSM aggregation on one warmed rung, ALL closed
# classes' pairings in ONE device dispatch (bls_pairing_product),
# unsigned dispatch — then the same traffic per-vote Ed25519
# in-process for bls_agg_speedup AND a host-pairing replay of one
# height for bls_pairing_device_speedup.  Same crash-safe contract as
# [3c]/[3d]: a real pipeline_serve_bls_votes_per_sec record (which
# must then show bls_agg_speedup > 1 AND device_speedup > 1 at a
# >= 64-validator class and zero unexpected retraces) or the -1
# sentinel, rc 0 either way.  The smoke's default class size is 128
# validators: the aggregate trade is asymptotic in committee size,
# and V=64 sits at the measured CPU crossover (~0.99x vs per-vote on
# an idle box — one fused 128-vote Ed25519 dispatch costs about what
# 2 x (MSM + device pairing + fold) does), so the gate measures
# where the win is structural.  1800s: the MSM rung compile (~95s) +
# two pairing class-rung compiles (~130s each) + two Ed25519 rung
# compiles + the host-pairing comparison classes (~1s each of pure
# python).
BLS_DIR="$(mktemp -d)"
BLS_RC=0
AGNES_BENCH_SERVE_BLS_SMOKE=1 AGNES_SERVE_BLS_SMOKE_HEIGHTS=2 \
  AGNES_TPU_LEASE_PATH="$BLS_DIR/tpu.lease" \
  timeout -k 10 1800 python bench.py > "$BLS_DIR/serve_bls.json" \
  2> "$BLS_DIR/serve_bls.err" || BLS_RC=$?
if [ "$BLS_RC" -ne 0 ]; then
  echo "BLS serve smoke gate FAILED: bench exited rc=$BLS_RC"
  tail -5 "$BLS_DIR/serve_bls.err"
  exit 1
fi
python - "$BLS_DIR/serve_bls.json" <<'PY'
import json, sys
lines = [l for l in open(sys.argv[1]).read().strip().splitlines() if l]
assert lines, "BLS serve smoke printed no stdout"
rec = json.loads(lines[-1])
assert rec["metric"] == "pipeline_serve_bls_votes_per_sec", rec
assert isinstance(rec["value"], (int, float)), rec
assert rec["value"] == -1 or rec["value"] > 0, rec
if rec["value"] == -1:
    print("BLS serve smoke gate OK: -1 sentinel (deadline contract)")
else:
    assert rec["bls_class_size"] >= 64, rec
    assert rec["retrace_unexpected"] == 0, rec
    assert rec["serve_bls_fallback_votes"] == 0, rec
    # acceptance: the aggregate lane must beat per-vote Ed25519 on the
    # same traffic (measured 2.8x on an idle 2-CPU box; > 1 is the
    # conservative floor so a loaded CI box cannot flake while an
    # aggregate lane SLOWER than per-vote still fails)
    assert rec["bls_agg_speedup"] > 1, rec
    # ISSUE 13 acceptance: the DEVICE pairing must beat the host
    # oracle per class on the same traffic, and the steady state must
    # actually be device-paired (dispatch counter > 0)
    assert rec["bls_pairing_device_speedup"] > 1, rec
    assert rec["bls_device_pairing_dispatches"] > 0, rec
    # ISSUE 18: the Pallas field-kernel A/B keys must exist as real
    # measurements or honest -1 sentinels (never absent), and the run
    # that dispatched the kernel entries kept a clean retrace slate
    # (the kernel lane is a retrace STATIC — any lane mismatch would
    # have bumped retrace_unexpected above).  No > 1 floor on the
    # speedup HERE: this CPU gate runs the kernels under the Pallas
    # interpreter, so the number proves plumbing + exactness; the
    # throughput claim belongs to the TPU lane.
    for k in ("bls_pallas_speedup", "bls_pallas_compile_ms"):
        assert isinstance(rec.get(k), (int, float)), (k, rec.get(k))
        assert rec[k] == -1 or rec[k] > 0, (k, rec[k])
    print(f"BLS serve smoke gate OK: {rec['value']:.0f} votes/s at a "
          f"{rec['bls_class_size']}-validator class "
          f"({rec['bls_agg_speedup']}x vs per-vote Ed25519 "
          f"{rec['pipeline_serve_bls_ed25519_votes_per_sec']:.0f} "
          f"votes/s; device pairing "
          f"{rec['bls_pairing_device_speedup']}x vs host, per-class "
          f"p50 {rec['bls_pairing_wall_p50_s']}s)")
PY

echo "=== [3f/4] native admission smoke gate (CPU) ==="
# ISSUE 14: the C++ admission front-end — threaded host submitting
# through one GIL-releasing native call per blob (parse/screen/
# fairness/SHA-256 in admission.cpp), then the SAME traffic through
# the Python AdmissionQueue in-process, plus a host-only submit/drain
# A/B for native_admission_speedup.  ISSUE 20 adds the zero-copy
# densify A/B (drain_phases + adopt vs drain + add_arrays +
# build_phases_device) and the sharded-ingest A/B (2 producers vs
# NativeAdmissionShards at the env knob's shard count vs the single
# queue) to the same probe.  Same crash-safe contract as
# [3c]/[3d]: a real pipeline_serve_native_votes_per_sec record (which
# must then show speedup > 1, zero unexpected retraces and ZERO new
# XLA compiles on the Python replay — native admission is host-only)
# or the -1 sentinel, rc 0 either way.
NATIVE_DIR="$(mktemp -d)"
NATIVE_RC=0
AGNES_BENCH_SERVE_NATIVE_SMOKE=1 \
  AGNES_TPU_LEASE_PATH="$NATIVE_DIR/tpu.lease" \
  timeout -k 10 900 python bench.py > "$NATIVE_DIR/serve_native.json" \
  2> "$NATIVE_DIR/serve_native.err" || NATIVE_RC=$?
if [ "$NATIVE_RC" -ne 0 ]; then
  echo "native admission smoke gate FAILED: bench exited rc=$NATIVE_RC"
  tail -5 "$NATIVE_DIR/serve_native.err"
  exit 1
fi
python - "$NATIVE_DIR/serve_native.json" <<'PY'
import json, sys
lines = [l for l in open(sys.argv[1]).read().strip().splitlines() if l]
assert lines, "native admission smoke printed no stdout"
rec = json.loads(lines[-1])
assert rec["metric"] == "pipeline_serve_native_votes_per_sec", rec
assert isinstance(rec["value"], (int, float)), rec
assert rec["value"] == -1 or rec["value"] > 0, rec
if rec["value"] == -1:
    print("native admission smoke gate OK: -1 sentinel "
          "(deadline contract)")
else:
    # acceptance: the native submit/drain path must beat the Python
    # queue on the same wire (measured well above 1 on an idle box;
    # > 1 is the conservative floor so a loaded CI box cannot flake
    # while a native path SLOWER than Python still fails), with zero
    # unexpected retraces and zero new compiles on the Python replay
    assert rec["native_admission_speedup"] > 1, rec
    assert rec["retrace_unexpected"] == 0, rec
    assert rec["native_new_compiles"] == 0, rec
    # ISSUE 20: the zero-copy densify and sharded-ingest A/Bs must
    # have produced real numbers or the explicit -1 sentinel (knob
    # not dividing the shape).  When real: the shard group must beat
    # the single queue on the 2-producer gossip-shaped host (the
    # acceptance floor — per-shard mutexes vs one), and the densify
    # ratio must at least be positive (zero-copy never SLOWER is
    # asserted at > 1 only on the shard axis; the densify arm's win
    # is wall-dependent on CPU device-wrap cost, so the gate pins
    # real-or-sentinel + the key's presence)
    dens = rec["native_densify_speedup"]
    assert dens == -1 or dens > 0, rec
    shard = rec["native_shard_speedup"]
    assert shard == -1 or shard > 1, rec
    assert rec["native_shards"] >= 2, rec
    print(f"native admission smoke gate OK: {rec['value']:.0f} votes/s "
          f"(admission {rec['native_admission_speedup']}x vs Python "
          f"{rec['python_admission_votes_per_sec']:.0f} rec/s; densify "
          f"{dens}x zero-copy; shards x{rec['native_shards']} "
          f"{shard}x vs single; {rec['native_phase_builds']} adopted "
          f"phase builds; submit "
          f"busy frac {rec['serve_submit_busy_frac_native']} native "
          f"vs {rec['serve_submit_busy_frac_python']} python)")
PY

echo "=== [3g/4] multi-host serve smoke gate (2-process pod, CPU) ==="
# ISSUE 15: the multi-host serve plane — bench spawns 2
# jax.distributed worker processes (2 faked CPU devices each, gloo
# collectives), each running a HostShard front-end over ONE
# global-SPMD mesh: barrier-synchronized warmup, lockstep dispatch
# agreement, per-height pod decision gathers, per-host heartbeat.
# Same crash-safe contract as the gates above: a real
# pipeline_serve_multihost_votes_per_sec record (which must then show
# hosts==2, zero unexpected retraces and zero device-rejected
# signatures summed over every host) or the -1 sentinel, rc 0 either
# way; the spawner deadline bounds a wedged pod inside the timeout.
MH_DIR="$(mktemp -d)"
MH_RC=0
AGNES_BENCH_SERVE_MULTIHOST_SMOKE=1 AGNES_MULTIHOST_DIR="$MH_DIR" \
  AGNES_TPU_LEASE_PATH="$MH_DIR/tpu.lease" \
  timeout -k 10 900 python bench.py > "$MH_DIR/serve_multihost.json" \
  2> "$MH_DIR/serve_multihost.err" || MH_RC=$?
if [ "$MH_RC" -ne 0 ]; then
  echo "multihost serve smoke gate FAILED: bench exited rc=$MH_RC"
  tail -5 "$MH_DIR/serve_multihost.err"
  exit 1
fi
python - "$MH_DIR/serve_multihost.json" <<'PY'
import json, sys
lines = [l for l in open(sys.argv[1]).read().strip().splitlines() if l]
assert lines, "multihost serve smoke printed no stdout"
rec = json.loads(lines[-1])
assert rec["metric"] == "pipeline_serve_multihost_votes_per_sec", rec
assert isinstance(rec["value"], (int, float)), rec
assert rec["value"] == -1 or rec["value"] > 0, rec
if rec["value"] == -1:
    print("multihost serve smoke gate OK: -1 sentinel "
          "(deadline contract)")
else:
    assert rec["multihost_hosts"] == 2, rec
    assert rec["multihost_devices_per_host"] == 2, rec
    assert rec["multihost_retrace_unexpected"] == 0, rec
    assert rec["multihost_rejected_signature_device"] == 0, rec
    assert rec["multihost_offladder_builds"] == 0, rec
    assert len(rec["multihost_heartbeat_paths"]) == 2, rec
    print(f"multihost serve smoke gate OK: {rec['value']:.0f} votes/s "
          f"pod-wide ({rec['multihost_hosts']} hosts x "
          f"{rec['multihost_devices_per_host']} devices, "
          f"{rec['multihost_pod_decisions']} pod decisions gathered)")
PY
# one parseable host-id-stamped heartbeat per pod process (real value
# OR sentinel: the workers arm their recorders before the first
# dispatch, so even a deadline-killed pod leaves dated trails when it
# got as far as spawning) + the merged per-host postmortem onto the
# gate log — skipped only if the pod never produced trails
if ls "$MH_DIR"/heartbeat.pod*.ndjson >/dev/null 2>&1; then
  timeout -k 5 60 python scripts/agnes_metrics.py --check \
    "$MH_DIR"/heartbeat.pod*.ndjson
  timeout -k 5 60 python scripts/agnes_metrics.py \
    "$MH_DIR"/heartbeat.pod*.ndjson || true
else
  python - "$MH_DIR/serve_multihost.json" <<'PY'
import json, sys
rec = json.loads([l for l in open(sys.argv[1]).read().strip()
                  .splitlines() if l][-1])
assert rec["value"] == -1, \
    "real multihost record but no per-host heartbeat trails"
print("multihost heartbeat check skipped (sentinel before spawn)")
PY
fi

echo "=== [3h/4] elastic pod serve smoke gate (membership cycle, CPU) ==="
# ISSUE 17: the elastic pod membership plane — the same spawned
# 2-process pod as [3g], driven through ElasticShard's per-tick shape
# negotiation: deliberately heterogeneous per-host traffic (hosts
# close DIFFERENT batch shapes every tick; the per-tick max-merge +
# padding keeps lockstep with ZERO new compiles past warmup) plus one
# host leave + rejoin cycle across membership epoch boundaries (the
# survivor adopts the sleeper's ranges, holds its gossip and
# re-routes it through the readmission boundary's own frame).  Same
# crash-safe contract: a real pipeline_serve_elastic_votes_per_sec
# record — which must then show zero unexpected retraces (padding
# never bought a live compile), a COMPLETED membership cycle
# (boundaries >= 2, readmissions >= 1), matching per-host decision
# rows (the probe raises otherwise), no dropped held gossip and zero
# foreign rejects — or the -1 sentinel, rc 0 either way.
ELA_DIR="$(mktemp -d)"
ELA_RC=0
AGNES_BENCH_SERVE_ELASTIC_SMOKE=1 AGNES_ELASTIC_DIR="$ELA_DIR" \
  AGNES_TPU_LEASE_PATH="$ELA_DIR/tpu.lease" \
  timeout -k 10 900 python bench.py > "$ELA_DIR/serve_elastic.json" \
  2> "$ELA_DIR/serve_elastic.err" || ELA_RC=$?
if [ "$ELA_RC" -ne 0 ]; then
  echo "elastic pod serve smoke gate FAILED: bench exited rc=$ELA_RC"
  tail -5 "$ELA_DIR/serve_elastic.err"
  exit 1
fi
python - "$ELA_DIR/serve_elastic.json" <<'PY'
import json, sys
lines = [l for l in open(sys.argv[1]).read().strip().splitlines() if l]
assert lines, "elastic pod serve smoke printed no stdout"
rec = json.loads(lines[-1])
assert rec["metric"] == "pipeline_serve_elastic_votes_per_sec", rec
assert isinstance(rec["value"], (int, float)), rec
assert rec["value"] == -1 or rec["value"] > 0, rec
if rec["value"] == -1:
    print("elastic pod serve smoke gate OK: -1 sentinel "
          "(deadline contract)")
else:
    assert rec["elastic_hosts"] == 2, rec
    assert rec["elastic_retrace_unexpected"] == 0, rec
    # >= 1 COMPLETED membership epoch: the leave boundary AND the
    # readmission boundary both applied on every host
    assert rec["elastic_boundaries"] >= 2, rec
    assert rec["elastic_readmissions"] >= 1, rec
    assert rec["elastic_membership_epoch"] >= 2, rec
    # heterogeneous shapes were really negotiated + padded, the held
    # gossip really re-routed, and none of it was dropped or rejected
    assert rec["elastic_warmed_shapes"] == 2, rec
    assert rec["elastic_padded_slots"] > 0, rec
    assert rec["elastic_reroute_sent"] > 0, rec
    assert rec["elastic_reroute_received"] > 0, rec
    assert rec["elastic_held_dropped"] == 0, rec
    assert rec["elastic_foreign_rejects"] == 0, rec
    assert len(rec["elastic_heartbeat_paths"]) == 2, rec
    print(f"elastic pod serve smoke gate OK: {rec['value']:.0f} votes/s "
          f"pod-wide ({rec['elastic_boundaries']} boundaries, "
          f"{rec['elastic_readmissions']} readmission(s), epoch "
          f"{rec['elastic_membership_epoch']}, "
          f"{rec['elastic_reroute_received']} re-routed records)")
PY
# the merged per-host postmortem now renders the membership trail
# (epoch per host + boundary/re-lift events) — same skip rule as [3g]
if ls "$ELA_DIR"/heartbeat.pod*.ndjson >/dev/null 2>&1; then
  timeout -k 5 60 python scripts/agnes_metrics.py --check \
    "$ELA_DIR"/heartbeat.pod*.ndjson
  timeout -k 5 60 python scripts/agnes_metrics.py \
    "$ELA_DIR"/heartbeat.pod*.ndjson || true
else
  python - "$ELA_DIR/serve_elastic.json" <<'PY'
import json, sys
rec = json.loads([l for l in open(sys.argv[1]).read().strip()
                  .splitlines() if l][-1])
assert rec["value"] == -1, \
    "real elastic record but no per-host heartbeat trails"
print("elastic heartbeat check skipped (sentinel before spawn)")
PY
fi

echo "=== GATE SUMMARY: heavy isolated files ==="
grep -E "test_isolated_file\[.*\] " "$HEAVY_LOG" \
  | sed -E 's/.*test_isolated_file\[(.*)\] ([A-Z]+).*/  \1: \2/' \
  || echo "  (no heavy results captured)"

if [ "${CI_BENCH:-1}" != "0" ]; then
  echo "=== [4/4] bench ==="
  python bench.py
else
  echo "=== [4/4] bench skipped (CI_BENCH=0) ==="
fi
echo "CI GREEN"
