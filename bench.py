"""Flagship benchmark: fused verify+tally+step throughput on one chip.

Primary metric: votes ingested per second through the fused 7-stage
consensus step at the BASELINE config-4 shape (thousands of parallel
instances, 1000-validator tally) — each vote is deduped, tallied,
threshold-checked and state-machine-applied on device.  vs_baseline is
against the north-star 1M votes/sec/chip target from BASELINE.json
(the reference itself publishes no numbers — SURVEY.md §6).

Extras in the same JSON line: batched Ed25519 verification throughput
(the crypto data plane, north star >= 1M verifies/sec) and the
decisions/sec of the honest-path closed loop.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
"""

from __future__ import annotations

import json
import os
import time

import jax

jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

import jax.numpy as jnp

from agnes_tpu.device.encoding import DeviceState
from agnes_tpu.device.step import ExtEvent, VotePhase, consensus_step_jit
from agnes_tpu.device.tally import TallyConfig, TallyState
from agnes_tpu.types import VoteType

NORTH_STAR = 1_000_000  # votes/sec/chip (BASELINE.json north_star)


def bench_tally(n_instances: int = 4096, n_validators: int = 1024,
                iters: int = 20) -> float:
    I, V = n_instances, n_validators
    cfg = TallyConfig(n_validators=V, n_rounds=4, n_slots=4)

    state = DeviceState.new((I,))
    tally = TallyState.new(I, cfg)
    ext = ExtEvent.none(I)
    powers = jnp.ones((V,), jnp.int32)
    total = jnp.asarray(V, jnp.int32)
    proposer_flag = jnp.ones((I, cfg.n_rounds), bool)
    propose_value = jnp.full(I, 1, jnp.int32)

    voters = jnp.ones((V,), bool)
    phase = VotePhase(
        round=jnp.zeros(I, jnp.int32),
        typ=jnp.full(I, int(VoteType.PREVOTE), jnp.int32),
        slots=jnp.ones((I, V), jnp.int32),
        mask=jnp.broadcast_to(voters[None, :], (I, V)),
        height=jnp.zeros(I, jnp.int32),
    )

    def step(state, tally):
        return consensus_step_jit(state, tally, ext, phase, powers, total,
                                  proposer_flag, propose_value)

    s, t, _ = step(state, tally)   # warmup + compile
    jax.block_until_ready(s)

    t0 = time.perf_counter()
    s, t = state, tally
    for _ in range(iters):
        s, t, _ = step(s, t)
    jax.block_until_ready(s)
    dt = time.perf_counter() - t0
    return I * V * iters / dt


def bench_verify(batch: int = 16384, iters: int = 3) -> float:
    """Batched Ed25519 verifies/sec (signatures fabricated by the C++
    signer; verified by the JAX data plane — the Pallas kernel path on
    TPU, measured ~250k/s at this batch; portable jnp path elsewhere)."""
    from agnes_tpu.core import native
    from agnes_tpu.crypto import ed25519_jax as ejax
    from agnes_tpu.crypto.encoding import vote_signing_bytes

    seeds = [i.to_bytes(4, "little") + bytes(28) for i in range(batch)]
    msgs = [vote_signing_bytes(1, 0, 0, i % 7) for i in range(batch)]
    pks = [native.pubkey(s) for s in seeds]
    sigs = [native.sign(s, m) for s, m in zip(seeds, msgs)]
    pub, sig, blocks = ejax.pack_verify_inputs_host(pks, msgs, sigs)

    ok = ejax.verify_batch_jit(pub, sig, blocks)   # warmup + compile
    ok.block_until_ready()
    assert bool(ok.all())
    t0 = time.perf_counter()
    for _ in range(iters):
        ok = ejax.verify_batch_jit(pub, sig, blocks)
    ok.block_until_ready()
    dt = time.perf_counter() - t0
    return batch * iters / dt


def bench_decisions(n_instances: int = 4096,
                    n_validators: int = 1024) -> float:
    """Honest-path closed loop: decisions/sec at config-4 shape."""
    from agnes_tpu.harness.device_driver import DeviceDriver

    d = DeviceDriver(n_instances, n_validators)
    d.run_honest_round(0)      # warmup + compile all three step shapes
    d.block_until_ready()
    d2 = DeviceDriver(n_instances, n_validators)
    t0 = time.perf_counter()
    d2.run_honest_round(0)
    d2.block_until_ready()
    dt = time.perf_counter() - t0
    assert d2.all_decided()
    return n_instances / dt


def main() -> None:
    import sys
    import traceback

    votes_per_sec = bench_tally()
    try:
        verifies_per_sec = round(bench_verify())
    except Exception:
        traceback.print_exc(file=sys.stderr)
        verifies_per_sec = -1
    try:
        decisions_per_sec = round(bench_decisions())
    except Exception:
        traceback.print_exc(file=sys.stderr)
        decisions_per_sec = -1
    print(json.dumps({
        "metric": "fused_tally_step_votes_per_sec",
        "value": round(votes_per_sec),
        "unit": "votes/sec/chip",
        "vs_baseline": round(votes_per_sec / NORTH_STAR, 3),
        "ed25519_verifies_per_sec": verifies_per_sec,
        "decisions_per_sec": decisions_per_sec,
    }))


if __name__ == "__main__":
    main()
