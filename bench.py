"""Flagship benchmark: fused verify+tally+step throughput on one chip.

Drives the BASELINE config-4 shape — thousands of parallel instances,
1000-validator tally — through the fused 7-stage consensus step and
reports votes ingested (deduped, tallied, threshold-checked, state-
machine-applied) per second.  vs_baseline is measured against the
north-star 1M votes/sec/chip target from BASELINE.json (the reference
itself publishes no numbers — SURVEY.md §6).

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

from agnes_tpu.device.encoding import DeviceState
from agnes_tpu.device.step import ExtEvent, VotePhase, consensus_step_jit
from agnes_tpu.device.tally import TallyConfig, TallyState
from agnes_tpu.types import VoteType

NORTH_STAR = 1_000_000  # votes/sec/chip (BASELINE.json north_star)


def bench(n_instances: int = 4096, n_validators: int = 1024,
          iters: int = 20) -> dict:
    I, V = n_instances, n_validators
    cfg = TallyConfig(n_validators=V, n_rounds=4, n_slots=4)

    state = DeviceState.new((I,))
    tally = TallyState.new(I, cfg)
    ext = ExtEvent.none(I)
    powers = jnp.ones((V,), jnp.int32)
    total = jnp.asarray(V, jnp.int32)
    proposer_flag = jnp.ones((I, cfg.n_rounds), bool)
    propose_value = jnp.full(I, 1, jnp.int32)

    voters = jnp.ones((V,), bool)
    phase = VotePhase(
        round=jnp.zeros(I, jnp.int32),
        typ=jnp.full(I, int(VoteType.PREVOTE), jnp.int32),
        slots=jnp.ones((I, V), jnp.int32),
        mask=jnp.broadcast_to(voters[None, :], (I, V)),
    )

    def step(state, tally):
        return consensus_step_jit(state, tally, ext, phase, powers, total,
                                  proposer_flag, propose_value)

    # warmup + compile
    s, t, _ = step(state, tally)
    jax.block_until_ready(s)

    t0 = time.perf_counter()
    s, t = state, tally
    for _ in range(iters):
        s, t, _ = step(s, t)
    jax.block_until_ready(s)
    dt = time.perf_counter() - t0

    votes_per_iter = I * V
    votes_per_sec = votes_per_iter * iters / dt
    return {
        "metric": "fused_tally_step_votes_per_sec",
        "value": round(votes_per_sec),
        "unit": "votes/sec/chip",
        "vs_baseline": round(votes_per_sec / NORTH_STAR, 3),
    }


if __name__ == "__main__":
    print(json.dumps(bench()))
