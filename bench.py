"""Flagship benchmark: the end-to-end consensus pipeline on one chip.

Headline metric: `pipeline_votes_per_sec` — signed wire votes pushed
through the FULL path (vectorized bridge densify -> batched Ed25519
verify -> fused tally/threshold/state-machine step -> decision ->
on-device height advance), with FRESH votes every iteration (each
iteration is a new consensus height; nothing is ever replayed into the
dedup).  vs_baseline is against the 1M votes/sec/chip north star from
BASELINE.json (the reference publishes no numbers — SURVEY.md §6).

Extras in the same JSON line:
  pipeline_native_votes_per_sec   same end-to-end path fed by the C++
                                  ingestion event loop (ingest.cpp)
  pipeline_fused_votes_per_sec    device-fused verification: ONE
                                  dispatch per height, verdicts mask
                                  on device, zero fetches in the loop
  fused_tally_step_votes_per_sec  device-plane-only ingestion rate,
                                  fresh votes (height-advancing loop)
  ed25519_verifies_per_sec        the fused Pallas verify kernel alone
  ed25519_msm_verifies_per_sec    the MSM batch check (honest stream,
                                  production adaptive path)
  decisions_per_sec               sustained decisions across >= 10
                                  consecutive heights at config-4 shape
  bridge_votes_per_sec            wire -> dense phases densify rate
                                  (no signatures; the pure host cost)

Measurement protocol: `jax.block_until_ready` does NOT actually block
on the axon-tunneled TPU platform (measured: timings stay flat as the
in-kernel work is scaled 4x), so every timed region here forces a tiny
host fetch (`_sync`) of a live output instead — the number includes
real device execution, not dispatch.

DEADLINE CONTRACT (VERDICT r5 weak #1: three rounds of missing
scoreboard data because the probe-retry budget outlived the driver's
`timeout 1800` and the process was SIGKILLed before its JSON line):

* **Enclosing-budget discovery.**  At startup bench learns how long it
  is allowed to live, in preference order: `AGNES_BENCH_DEADLINE_S`
  env; an ancestor `timeout N ...` found by walking /proc cmdlines
  (minus that wrapper's elapsed runtime — the discovery that makes
  `timeout 1800 bash -c '... python bench.py'` visible from inside);
  otherwise unbounded.  (utils/budget.Deadline.discover)

* **Derived caps.**  Probe timeout, retry interval, probe budget and
  busy budget are all clamped so the WORST wedged path ends with
  margin to spare before the deadline; env overrides are honored but
  never past the deadline, and AGNES_BENCH_PROBE_BUDGET_S is
  hard-capped at 1200 s regardless (the driver window is 1800 s).

* **Signal-emission guarantee.**  SIGTERM and SIGALRM are handled
  from before the first probe until exit, and an alarm is scheduled
  `margin` before a finite deadline: whatever kills this process —
  wedged tunnel, dead backend, the enclosing timeout's TERM, or the
  self-armed alarm — a PARSEABLE JSON record is printed as the last
  stdout line (value -1 when the headline never ran; any stage
  results that did complete ride along), and the exit code is 0.
  Only an outright SIGKILL with no preceding signal can suppress the
  record, which is why the caps above keep the process from ever
  meeting the driver's KILL escalation.  Asserted by ci.sh's
  forced-dead gate (`AGNES_BENCH_FORCE_DEAD=1`, a probe stub that
  always hangs) and tests/test_bench_deadline.py.

* **Claim protocol.**  The TPU claim tie-break runs through the
  fcntl lease (scripts/tpu_holders.TpuLease): whoever holds the
  lease probes/claims, everyone else waits — replacing the ad-hoc
  elder-bench ps tie-break (two rounds of races).  The ps screen
  remains as a backstop for non-lease processes.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

# make `from scripts.tpu_holders import ...` resolve regardless of the
# caller's cwd (guarded: __file__ is absent when the probe-guard
# prefix of this file is exec'd standalone)
if "__file__" in globals():
    _here = os.path.dirname(os.path.abspath(__file__))
    if _here not in sys.path:
        sys.path.insert(0, _here)
else:
    _here = os.getcwd()


def _load_stdlib_module(fname: str, alias: str):
    """A utils/*.py module by FILE PATH: importing agnes_tpu.utils
    proper would pull jax via the package __init__ and initialize a
    backend — exactly what the probe guard exists to avoid.  The
    loaded module's top level must be stdlib-only by contract
    (budget.py, flightrec.py)."""
    import importlib.util

    path = os.path.join(_here, "agnes_tpu", "utils", fname)
    spec = importlib.util.spec_from_file_location(alias, path)
    mod = importlib.util.module_from_spec(spec)
    # dataclass creation resolves cls.__module__ through sys.modules
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


_budget = _load_stdlib_module("budget.py", "_agnes_budget")
#: flight recorder + heartbeat (ISSUE 8): armed alongside the deadline
#: watchdog BEFORE the probe guard can hang, so even a wedged-probe or
#: SIGKILLed round leaves an on-disk NDJSON trail whose last line
#: dates the wedge (utils/flightrec.py; stdlib-only like budget)
_flightrec = _load_stdlib_module("flightrec.py", "_agnes_flightrec")

NORTH_STAR = 1_000_000  # votes/sec/chip (BASELINE.json north_star)

#: the enclosing wall-clock budget (see DEADLINE CONTRACT above)
_DEADLINE = _budget.Deadline.discover()

#: stage results completed so far — the sentinel record carries them
#: so a mid-bench kill still delivers every number already measured
_RESULTS: dict = {}
_STAGE = "probe-guard"
_EMITTED = False
_LEASE = None
_PROBE_PROC = None         # in-flight probe child; reaped on any exit

#: the always-on flight recorder: serve probes hand it to their
#: drivers/services (dispatch, tick, reject, retrace, compile events);
#: the heartbeat thread snapshots its per-kind counts every interval
_FLIGHTREC = _flightrec.FlightRecorder(capacity=4096)
#: heartbeat sources — a MUTABLE list the probes append to (e.g. a
#: serve probe registers its Metrics windowed snapshot when its
#: service comes up); read fresh every beat
_HB_SOURCES: list = []
_HEARTBEAT = None          # armed in the __main__ guard below
_PROBE_SOURCE: dict = {"fn": None}


def _set_probe_source(fn) -> None:
    """Install a probe's metrics snapshot as THE live heartbeat
    source: the new probe's source REPLACES the previous probe's, so
    a finished service (and the driver + device buffers its closure
    retains) is released instead of being snapshotted forever — and
    stale dead-probe counters never shadow the live probe's on a
    heartbeat line."""
    old = _PROBE_SOURCE["fn"]
    if old is not None and old in _HB_SOURCES:
        _HB_SOURCES.remove(old)
    _PROBE_SOURCE["fn"] = fn
    if fn is not None:
        _HB_SOURCES.append(fn)

#: retrace-audit counters accumulated by the serve probes (their
#: drivers run with the recompile tripwire armed, ISSUE 4): distinct
#: dispatch signatures vetted + traces outside the expected set.  The
#: final verdict records carry both, so a hardware round's artifact
#: states THAT the audit ran and that it ran clean.  The bounded model
#: checker's gate numbers ride along the same way (ISSUE 6): ci.sh
#: exports the [1d] gate's JSON into these env vars before the bench
#: gates run; -1 means the gate did not run in this process tree.
_ANALYSIS: dict = {"analysis_entries_audited": 0,
                   "retrace_unexpected": 0,
                   "modelcheck_states_explored": int(os.environ.get(
                       "AGNES_MODELCHECK_STATES_EXPLORED", -1)),
                   "modelcheck_violations": int(os.environ.get(
                       "AGNES_MODELCHECK_VIOLATIONS", -1)),
                   # ISSUE 7: measured symmetry orbit reduction vs the
                   # PR 6 unreduced baseline, and the serve-plane
                   # admission model's state total (-1 = gate not run)
                   "modelcheck_sym_orbit_reduction": float(os.environ.get(
                       "AGNES_MODELCHECK_SYM_ORBIT_REDUCTION", -1)),
                   "modelcheck_admission_states": int(os.environ.get(
                       "AGNES_MODELCHECK_ADMISSION_STATES", -1)),
                   # ISSUE 9: the epoch/churn shard state totals and the
                   # per-epoch symmetry groups' measured orbit reduction
                   # (-1 = gate not run), same export path
                   "modelcheck_epoch_states": int(os.environ.get(
                       "AGNES_MODELCHECK_EPOCH_STATES", -1)),
                   "modelcheck_churn_states": int(os.environ.get(
                       "AGNES_MODELCHECK_CHURN_STATES", -1)),
                   "modelcheck_epoch_orbit_reduction": float(os.environ.get(
                       "AGNES_MODELCHECK_EPOCH_ORBIT_REDUCTION", -1)),
                   # ISSUE 13: the jaxpr op-count census gate's drift
                   # count (ci.sh [1c] exports it; -1 = gate not run
                   # in this process tree, 0 = ran clean)
                   "census_drift_entries": int(os.environ.get(
                       "AGNES_CENSUS_DRIFT_ENTRIES", -1)),
                   # ISSUE 19: the interleaving-explorer gate's totals
                   # (ci.sh [1e] exports them; -1 = gate not run in
                   # this process tree, violations 0 = ran clean)
                   "schedcheck_schedules_explored": int(os.environ.get(
                       "AGNES_SCHEDCHECK_SCHEDULES_EXPLORED", -1)),
                   "schedcheck_violations": int(os.environ.get(
                       "AGNES_SCHEDCHECK_VIOLATIONS", -1))}


def _harvest_audit(driver) -> None:
    """Fold a serve probe driver's sentinel counters into _ANALYSIS."""
    sentinel = getattr(driver, "sentinel", None)
    if sentinel is None:
        return
    from agnes_tpu.utils.metrics import (
        ANALYSIS_ENTRIES_AUDITED,
        RETRACE_UNEXPECTED,
    )

    counters = sentinel.metrics.counters
    _ANALYSIS["analysis_entries_audited"] += counters.get(
        ANALYSIS_ENTRIES_AUDITED, 0)
    _ANALYSIS["retrace_unexpected"] += counters.get(
        RETRACE_UNEXPECTED, 0)

#: serve-smoke mode (ci.sh gate): run ONLY the closed-loop serve probe
#: at a tiny shape on CPU, with the same crash-safe verdict contract —
#: the sentinel then speaks in the smoke's headline metric
_SERVE_SMOKE = bool(os.environ.get("AGNES_BENCH_SERVE_SMOKE"))
#: mesh-serve-smoke mode (ci.sh gate, ISSUE 3): ONLY the mesh serve
#: probe — threaded event-loop host + dense sharded dispatch — on a
#: FAKED 2-device CPU mesh (--xla_force_host_platform_device_count),
#: same crash-safe contract
_SERVE_MESH_SMOKE = bool(os.environ.get("AGNES_BENCH_SERVE_MESH_SMOKE"))
#: dedup-smoke mode (ci.sh gate, ISSUE 5): ONLY the duplicated-traffic
#: serve probe — verified-vote dedup cache + split-rung dispatch — on
#: CPU, same crash-safe contract.  AGNES_BENCH_SERVE_DUP sets the
#: duplication factor (default 8)
_SERVE_DEDUP_SMOKE = bool(os.environ.get("AGNES_BENCH_SERVE_DEDUP_SMOKE"))
#: BLS-aggregate-smoke mode (ci.sh gate, ISSUE 10): ONLY the BLS
#: aggregate-lane serve probe — one pairing per vote class instead of
#: one Ed25519 verify per vote — then the SAME traffic per-vote
#: Ed25519 in-process for the bls_agg_speedup ratio; CPU, crash-safe
_SERVE_BLS_SMOKE = bool(os.environ.get("AGNES_BENCH_SERVE_BLS_SMOKE"))
#: native-admission-smoke mode (ci.sh gate, ISSUE 14): ONLY the
#: native-admission serve probe — the threaded host over the C++
#: admission front-end, then the SAME traffic through the Python
#: queue in-process (shared compiles) plus a host-only submit/drain
#: A/B for native_admission_speedup; CPU, crash-safe.  The var's
#: VALUE doubles as the shard knob (ISSUE 20): any integer > 1 sets
#: the shard count of the sharded-ingest A/B (and the closed-loop ON
#: run, when it divides the shape); "1"/non-numeric keeps the
#: default of 2
_SERVE_NATIVE_SMOKE = bool(
    os.environ.get("AGNES_BENCH_SERVE_NATIVE_SMOKE"))


def _native_shard_knob() -> int:
    v = os.environ.get("AGNES_BENCH_SERVE_NATIVE_SMOKE", "")
    return int(v) if v.isdigit() and int(v) > 1 else 2
#: multi-host-smoke mode (ci.sh gate, ISSUE 15): ONLY the pod serve
#: probe — the PARENT spawns 2 jax.distributed worker processes (2
#: faked CPU devices each, gloo collectives) via
#: distributed/smoke.spawn_pod and aggregates their records; the
#: parent itself never builds a backend mesh, so the crash-safe
#: contract bounds the whole pod (a wedged pod is SIGKILLed at the
#: spawner deadline and the sentinel still emits)
_SERVE_MULTIHOST_SMOKE = bool(
    os.environ.get("AGNES_BENCH_SERVE_MULTIHOST_SMOKE"))
#: elastic-pod-smoke mode (ci.sh gate, ISSUE 17): ONLY the elastic pod
#: serve probe — the 2-process pod driven through ElasticShard's
#: per-tick shape negotiation with heterogeneous per-host traffic and
#: ONE host leave + rejoin cycle across membership epoch boundaries;
#: same spawner-deadline crash-safe contract as the multihost gate
_SERVE_ELASTIC_SMOKE = bool(
    os.environ.get("AGNES_BENCH_SERVE_ELASTIC_SMOKE"))
_SENTINEL_METRIC = ("pipeline_serve_elastic_votes_per_sec"
                    if _SERVE_ELASTIC_SMOKE
                    else "pipeline_serve_multihost_votes_per_sec"
                    if _SERVE_MULTIHOST_SMOKE
                    else "pipeline_serve_mesh_votes_per_sec"
                    if _SERVE_MESH_SMOKE
                    else "pipeline_serve_dedup_votes_per_sec"
                    if _SERVE_DEDUP_SMOKE
                    else "pipeline_serve_bls_votes_per_sec"
                    if _SERVE_BLS_SMOKE
                    else "pipeline_serve_native_votes_per_sec"
                    if _SERVE_NATIVE_SMOKE
                    else "pipeline_fused_votes_per_sec" if _SERVE_SMOKE
                    else "pipeline_votes_per_sec")
_SENTINEL_STAGE = ("bench_pipeline_serve_elastic"
                   if _SERVE_ELASTIC_SMOKE
                   else "bench_pipeline_serve_multihost"
                   if _SERVE_MULTIHOST_SMOKE
                   else "bench_pipeline_serve_mesh" if _SERVE_MESH_SMOKE
                   else "bench_pipeline_serve_dedup"
                   if _SERVE_DEDUP_SMOKE
                   else "bench_pipeline_serve_bls"
                   if _SERVE_BLS_SMOKE
                   else "bench_pipeline_serve_native"
                   if _SERVE_NATIVE_SMOKE
                   else "bench_pipeline_serve" if _SERVE_SMOKE
                   else "bench_pipeline")

#: extra keys the in-flight stage wants on its final smoke record
#: (e.g. the dedup probe's hit rate + dedup-off comparison); merged by
#: _smoke_main at emit time
_EXTRA_RECORD: dict = {}

#: every serve smoke is a CPU-only CI gate (no TPU claim/lease/probe)
_ANY_SERVE_SMOKE = (_SERVE_SMOKE or _SERVE_MESH_SMOKE
                    or _SERVE_DEDUP_SMOKE or _SERVE_BLS_SMOKE
                    or _SERVE_NATIVE_SMOKE or _SERVE_MULTIHOST_SMOKE
                    or _SERVE_ELASTIC_SMOKE)


def _emit_sentinel(note: str) -> None:
    """Print the unconditional JSON verdict (idempotent).  The
    headline is whatever the headline stage measured if it got that
    far, else -1; completed stage numbers ride along under
    'partial'."""
    global _EMITTED
    if _EMITTED:
        return
    _EMITTED = True
    value = _RESULTS.get(_SENTINEL_STAGE, -1)
    rec = {"metric": _SENTINEL_METRIC, "value": value,
           "unit": "votes/sec/chip",
           "vs_baseline": round(value / NORTH_STAR, 3) if value > 0
           else -1,
           "note": note}
    if _RESULTS:
        rec["partial"] = dict(_RESULTS)
    rec.update(_heartbeat_record())
    print(json.dumps(rec), flush=True)


def _heartbeat_record() -> dict:
    """Heartbeat keys for every verdict record (real or sentinel): the
    trail's path and its last line's age, so a wedged round's artifact
    points the post-mortem (`agnes-metrics <path>`) at the evidence."""
    if _HEARTBEAT is None:
        return {}
    try:
        age = _HEARTBEAT.last_line_age()
        return {"heartbeat_path": _HEARTBEAT.path,
                "heartbeat_age_s": (round(age, 1) if age is not None
                                    else -1)}
    except Exception:  # noqa: BLE001 — telemetry never blocks a verdict
        return {"heartbeat_path": _HEARTBEAT.path,
                "heartbeat_age_s": -1}


def _compile_record() -> dict:
    """`compile_ms_<entry>` keys for the verdict records (ISSUE 8
    satellite): per-entry first-dispatch walls from the registry.
    Empty before the heavy imports (sentinel paths) — guarded so a
    wedged pre-import process can still emit."""
    try:
        from agnes_tpu.device import registry

        return registry.compile_gauges()
    except Exception:  # noqa: BLE001
        return {}


def _deadline_signal(signum: int) -> None:
    """SIGTERM/SIGALRM: emit the verdict, reap the in-flight probe,
    and exit 0 — the crash-safe last line the driver parses.  The
    lease is left for dead-holder takeover (see below)."""
    _emit_sentinel(
        f"killed by {'SIGALRM (self-armed deadline)' if signum == signal.SIGALRM else 'SIGTERM'} "
        f"during stage '{_STAGE}' with {_DEADLINE.remaining():.0f}s left "
        f"of the discovered budget ({_DEADLINE.source}); emitted from "
        "the signal handler per the deadline contract")
    # deliberately NO _LEASE.release() here: release takes the lease
    # flock, and this signal may have interrupted the main thread
    # INSIDE that same critical section (acquire/refresh run every
    # probe loop and stage) — flock from a second fd of one process
    # still blocks, so releasing here could deadlock the very exit
    # this handler guarantees.  Dying unreleased is safe by design:
    # TpuLease detects a dead holder via pid+start-ticks and rivals
    # take the lease over immediately.
    try:
        _reap_probe()      # a surviving marked probe reads as a claim
    except Exception:  # noqa: BLE001
        pass
    os._exit(0)


#: cancels the deadline watchdog thread when the real verdict is
#: about to print (the thread twin of `signal.alarm(0)`)
_WATCHDOG_CANCEL = None


def _arm_deadline_watchdog(alarm_delay: float) -> None:
    """Backstop for the signal-emission guarantee that SIGNALS cannot
    give: a Python signal handler only runs when the MAIN thread
    re-enters the interpreter, and the main thread can be blocked for
    minutes inside one GIL-releasing C++ call (an XLA trace/compile —
    exactly the serve smoke's first dispatch).  In that window both
    the self-armed SIGALRM and the enclosing timeout's SIGTERM pend
    until the call returns, and the timeout's follow-up SIGKILL wins —
    no record.  A daemon THREAD is immune: it runs while the main
    thread is blocked, emits the sentinel 5 s after the alarm was
    supposed to (so the alarm keeps the job when it can do it), and
    exits 0.  Cancelled alongside `signal.alarm(0)` when the real
    verdict is imminent."""
    global _WATCHDOG_CANCEL
    import threading

    if not alarm_delay:
        return
    _WATCHDOG_CANCEL = threading.Event()

    def watch():
        if _WATCHDOG_CANCEL.wait(timeout=alarm_delay + 5):
            return
        if not _EMITTED:
            _deadline_signal(signal.SIGALRM)

    threading.Thread(target=watch, daemon=True,
                     name="agnes-deadline-watchdog").start()


def _cancel_deadline_watchdog() -> None:
    if _WATCHDOG_CANCEL is not None:
        _WATCHDOG_CANCEL.set()


def _backend_hung_once(timeout_s: int) -> bool:
    """True iff backend init HANGS (wedged axon relay after a client
    died mid-claim): probed in a SUBPROCESS because jax.devices()
    blocks forever in-process — and some agnes module imports below
    create device arrays, so even importing this file would hang.
    A fast nonzero exit (broken jax install, etc.) is NOT a hang —
    the caller proceeds and the real import error surfaces loudly.

    A hung child is shut down GENTLY (SIGINT, grace, then escalate):
    a SIGKILLed probe dies mid-claim, which is itself one of the
    observed causes of hours-long relay wedges.

    AGNES_BENCH_FORCE_DEAD=1 swaps the probe for a stub that always
    hangs — CI's way to drive the wedged path (and every deadline/
    signal guarantee behind it) without any backend at all."""
    # DEVNULL, not PIPE: a killed child's helper processes can hold
    # a captured pipe open and block the post-kill drain forever.
    # PROBE_SNIPPET carries the marker that makes this probe visible
    # to the suite runner's holder check while it is in flight.
    from scripts.tpu_holders import PROBE_SNIPPET

    global _PROBE_PROC
    snippet = PROBE_SNIPPET
    if os.environ.get("AGNES_BENCH_FORCE_DEAD"):
        snippet = ("import time; time.sleep(10**6)"
                   "  # agnes_tpu_probe forced-dead stub")
    def _die_with_parent():
        # PR_SET_PDEATHSIG: the kernel kills the probe when bench
        # dies, HOWEVER bench dies (even SIGKILL, where no handler
        # runs).  An orphaned marked probe is poison: it matches every
        # later bench's holder screen and reads as a live TPU claim.
        import ctypes

        try:
            ctypes.CDLL(None).prctl(1, signal.SIGKILL)
        except Exception:  # noqa: BLE001 — probe still works without
            pass

    p = subprocess.Popen(
        [sys.executable, "-c", snippet],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        preexec_fn=_die_with_parent)
    _PROBE_PROC = p     # visible to the deadline signal handler: a
    try:                # probe orphaned by a mid-wait kill would keep
        p.wait(timeout=timeout_s)     # matching the ps holder screen
        return False                  # and wedge every LATER bench
    except subprocess.TimeoutExpired:
        for sig, grace in ((signal.SIGINT, 15), (signal.SIGTERM, 5)):
            try:
                p.send_signal(sig)
                p.wait(timeout=grace)
                return True
            except subprocess.TimeoutExpired:
                continue
            except OSError:
                return True
        p.kill()
        p.wait()
        return True
    finally:
        _PROBE_PROC = None


def _reap_probe() -> None:
    """Kill an in-flight probe child before this process dies: the
    exiting bench must not leave behind a marked probe that every
    later bench's holder screen mistakes for a live TPU claim.  Gentle
    first (SIGINT — a SIGKILLed probe mid-claim can wedge the relay),
    but only a short grace: the enclosing timeout's own KILL is
    seconds away."""
    p = _PROBE_PROC
    if p is None or p.poll() is not None:
        return
    try:
        p.send_signal(signal.SIGINT)
        p.wait(timeout=2)
    except (subprocess.TimeoutExpired, OSError):
        try:
            p.kill()
            p.wait(timeout=2)
        except (subprocess.TimeoutExpired, OSError):
            pass


def _tpu_holders(lease_rec=None) -> list:
    """Other processes that (may) hold the single-process TPU claim:
    the detached hardware-suite stages and similar non-lease entry
    points.  While one is alive, a hanging jax.devices() in a fresh
    interpreter is EXPECTED (second-client behavior on this platform),
    so probing — and above all killing hung probes — must wait.  The
    detection lives in scripts/tpu_holders.py (stdlib-only;
    run_hw_suite.sh's probe loop uses the SAME screen, so the armed
    runner defers to a driver-launched bench instead of killing probes
    against its claim, and vice versa).

    SIBLING benches: while a VALID lease exists anywhere (mine, an
    ancestor's, a rival's), the fcntl lease arbitrates which bench
    probes and siblings are skipped here — the old elder-bench ps
    tie-break produced a race per round (VERDICT r5 weak #4).  With
    NO lease in play a sibling may be a PRE-lease bench (old code)
    already holding a live claim, so the elder tie-break survives as
    the mixed-version backstop: the elder probes, the younger waits
    (one ps snapshot backs both ages, so the ordering cannot invert
    between two reads)."""
    from scripts.tpu_holders import process_table, tpu_holders

    procs = process_table()
    my_age = procs.get(os.getpid(), (0, 0, ""))[1]
    holders = []
    for p, age, args in tpu_holders(procs):
        if "bench.py" in args and "agnes_tpu" not in args:
            if lease_rec is not None:
                continue       # lease protocol in play: it arbitrates
            if age < my_age or (age == my_age and p > os.getpid()):
                continue       # pre-lease younger sibling: it waits
        holders.append(f"{p} {args}")
    return holders


def _is_ancestor(pid) -> bool:
    """True iff `pid` is this process or one of its ancestors — a
    lease held there was taken by whoever launched us, on our
    behalf."""
    from scripts.tpu_holders import ancestor_chain, process_table

    try:
        return pid in ancestor_chain(process_table(), os.getpid())
    except Exception:  # noqa: BLE001 — ps failure must not wedge
        return False


#: hard cap on the probe-retry budget, whatever the env says: the
#: driver's window is 1800 s and r5 died precisely because an env
#: default (2700 s) outlived it
PROBE_BUDGET_HARD_CAP_S = 1200.0


def _probe_caps():
    """(probe_s, interval, budget, busy_budget) — env-tunable defaults
    (probe 120 s, retry every 60 s, 900 s of hung probes, 1500 s of
    busy waiting; all well under the driver's 1800 s window even
    stacked with the final probe) further clamped so the worst wedged
    path ends before the discovered deadline with margin to spare.
    With no deadline the env/defaults stand as-is."""
    probe_s = int(os.environ.get("AGNES_BENCH_PROBE_TIMEOUT_S", "120"))
    interval = int(os.environ.get("AGNES_BENCH_PROBE_INTERVAL_S", "60"))
    budget = min(float(os.environ.get("AGNES_BENCH_PROBE_BUDGET_S",
                                      "900")),
                 PROBE_BUDGET_HARD_CAP_S)
    busy_budget = float(os.environ.get("AGNES_BENCH_BUSY_BUDGET_S",
                                       "1500"))
    rem = _DEADLINE.remaining()
    if rem != float("inf"):
        margin = _budget.deadline_margin_s(rem)
        probe_s = max(2, min(probe_s, int(rem / 3)))
        interval = max(1, min(interval, int(rem / 6)))
        budget = max(2.0, min(budget, rem - margin - probe_s))
        busy_budget = max(2.0, min(busy_budget, rem - margin - interval))
    return probe_s, interval, budget, busy_budget


def _backend_hung():
    """Bounded probe-RETRY loop (VERDICT r4 weak #1: a single probe
    emitted -1 twice in a row when the driver happened to run bench at
    a transiently-wedged moment).  Axon wedges observed in r3/r4 often
    clear within tens of minutes, so keep probing — every retry
    interval for as long as the probe budget allows — and only report
    a hang after the whole budget is spent.  All four caps derive from
    the discovered deadline (`_probe_caps`), so the loop ALWAYS
    returns in time to print the verdict (VERDICT r5 weak #1).

    Probing is gated on the fcntl TPU lease: while another process
    (sibling bench, armed suite runner) holds it — or a non-lease TPU
    entry point shows in the ps screen — this loop WAITS instead of
    probing: a second client hangs by design on this platform, and
    killing such a probe mid-claim can wedge the relay for real.  On
    success the lease is HELD (and refreshed between stages) until
    exit, so rival probes defer to the running bench.

    Returns None when the backend is reachable, else a short reason
    string ("busy": the TPU was held for the whole busy budget and no
    probe ever ran; "wedged": probes themselves hung for the whole
    probe budget) so the emitted -1 record states the actual cause."""
    global _LEASE
    from scripts.tpu_holders import TpuLease

    probe_s, interval, budget, busy_budget = _probe_caps()
    lease = TpuLease()
    busy_deadline = time.monotonic() + busy_budget
    spent = 0.0
    attempt = 0
    while True:
        rec = lease.holder()
        holders = _tpu_holders(lease_rec=rec)
        claimed = False
        if not holders:
            if lease.acquire(note="bench probe/claim"):
                claimed = True
            else:
                rec = lease.holder()
                if rec and _is_ancestor(rec.get("pid")):
                    # the enclosing suite runner leased the claim to
                    # its own shell and launched this bench as a
                    # stage: its lease COVERS us (same principle as
                    # the ps screen's ancestor exclusion) — probe
                    # under it, don't hold it ourselves
                    pass
                elif rec:
                    holders = [f"lease holder {rec}"]
                else:
                    # holder vanished between acquire and read:
                    # transient — retry the acquire, don't probe
                    # leaseless and don't burn a busy interval
                    time.sleep(0.1)
                    continue
        if holders:
            if time.monotonic() >= busy_deadline:
                print("[bench] TPU still held by another process after "
                      f"{busy_budget:.0f}s; giving up:\n  "
                      + "\n  ".join(holders), file=sys.stderr, flush=True)
                return "busy"
            print(f"[bench] TPU busy ({len(holders)} holder(s)); "
                  f"waiting {interval}s", file=sys.stderr, flush=True)
            time.sleep(interval)
            continue
        if claimed:
            _LEASE = lease                # held from here until exit
        attempt += 1
        t0 = time.monotonic()
        if not _backend_hung_once(probe_s):
            return None
        spent += time.monotonic() - t0 + interval
        if spent >= budget:
            print(f"[bench] backend probe hung {attempt}x over "
                  f"{budget:.0f}s budget; giving up", file=sys.stderr,
                  flush=True)
            return "wedged"
        print(f"[bench] backend probe {attempt} hung; retrying in "
              f"{interval}s", file=sys.stderr, flush=True)
        time.sleep(interval)


def _release_lease() -> None:
    if _LEASE is not None:
        try:
            _LEASE.release()
        except Exception:  # noqa: BLE001
            pass


# the guard must run BEFORE the jax/agnes imports below (they trigger
# backend init at import time)
if __name__ == "__main__":
    import atexit

    atexit.register(_release_lease)
    atexit.register(_reap_probe)
    # arm the emission guarantee BEFORE anything can hang: SIGTERM +
    # a self-alarm `margin` before the discovered deadline, plus the
    # watchdog thread for windows where no signal handler can run
    # (main thread blocked in a single long C++ call)
    _alarm = _budget.install_deadline_signals(_deadline_signal, _DEADLINE)
    _arm_deadline_watchdog(_alarm)
    # the flight recorder's heartbeat arms HERE, with the watchdog —
    # before anything can hang — so a wedged probe, a minutes-long XLA
    # compile or an outright SIGKILL all leave a dated NDJSON trail
    # (the verdict record carries its path; AGNES_HEARTBEAT_PATH
    # overrides for CI gates)
    import tempfile

    _hb_path = os.environ.get("AGNES_HEARTBEAT_PATH") or os.path.join(
        tempfile.gettempdir(),
        f"agnes_bench_heartbeat_{os.getpid()}.ndjson")
    _HB_SOURCES.append(lambda: {
        "stage": _STAGE,
        "deadline_remaining_s": (
            round(_DEADLINE.remaining(), 1)
            if _DEADLINE.remaining() != float("inf") else -1)})
    try:
        _HEARTBEAT = _flightrec.Heartbeat(
            _hb_path,
            interval_s=float(os.environ.get(
                "AGNES_HEARTBEAT_INTERVAL_S", "5")),
            recorder=_FLIGHTREC, sources=_HB_SOURCES).start()
    except Exception:  # noqa: BLE001 — an unwritable heartbeat path
        _HEARTBEAT = None         # must never cost the verdict
    print(f"[bench] heartbeat: "
          f"{_HEARTBEAT.path if _HEARTBEAT else 'DISARMED'}",
          file=sys.stderr, flush=True)
    print(f"[bench] deadline: {_DEADLINE.source}, "
          f"remaining {_DEADLINE.remaining():.0f}s, "
          f"alarm in {_alarm:.0f}s" if _alarm else
          f"[bench] deadline: {_DEADLINE.source} (unbounded; no alarm)",
          file=sys.stderr, flush=True)
    try:
        # serve smokes are CPU-only CI gates: no TPU claim, no lease,
        # no probe — a hung-axon screen would only burn their budget
        _reason = (None if _ANY_SERVE_SMOKE
                   else _backend_hung())
    except SystemExit:
        raise
    except BaseException as e:  # noqa: BLE001 — the guard itself can
        # die (unwritable lease path, malformed cap env, ps failure):
        # the verdict contract outranks the traceback
        import traceback

        traceback.print_exc(file=sys.stderr)
        _emit_sentinel(
            f"probe guard crashed before any stage: "
            f"{type(e).__name__}: {e}")
        sys.exit(0)
    if _reason == "busy":
        _emit_sentinel(
            "TPU held by another process for the full busy budget "
            "(scheduling conflict, NOT a tunnel wedge); no probe or "
            f"stage was run (deadline source: {_DEADLINE.source})")
        sys.exit(0)
    if _reason == "wedged":
        _emit_sentinel(
            "backend init timed out (wedged accelerator tunnel) for "
            "the full probe-retry budget; no stage was run "
            f"(deadline source: {_DEADLINE.source})")
        sys.exit(0)

# the XLA:CPU codegen/serialization race workaround must land in
# XLA_FLAGS before ANY agnes/jax import can initialize a backend
# (package __init__ side effects create device arrays) — see
# agnes_tpu/utils/compile_cache.py
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_cpu_parallel_codegen_split_count" not in _flags:
    _flags = (_flags + " --xla_cpu_parallel_codegen_split_count=1").strip()
# the mesh serve smoke fakes a multi-device platform out of host CPU
# threads — the flag must land before ANY backend initialization
if (_SERVE_MESH_SMOKE
        and "xla_force_host_platform_device_count" not in _flags):
    _n_fake = int(os.environ.get("AGNES_SERVE_MESH_SMOKE_DEVICES", "2"))
    _flags = (_flags
              + f" --xla_force_host_platform_device_count={_n_fake}")
os.environ["XLA_FLAGS"] = _flags

# serve smokes run on CPU by definition; env alone is not enough on
# this platform (sitecustomize forces jax_platforms="axon,cpu"), so
# the in-process config override follows right after the import — the
# same two-step tests/conftest.py uses
if _ANY_SERVE_SMOKE:
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax

if _ANY_SERVE_SMOKE:
    jax.config.update("jax_platforms", "cpu")

from agnes_tpu.utils.compile_cache import disable_persistent_cache
disable_persistent_cache()

import jax.numpy as jnp
import numpy as np

from agnes_tpu.device.encoding import DeviceState
from agnes_tpu.device.tally import TallyConfig, TallyState
from agnes_tpu.types import VoteType


def _sync(x) -> None:
    """Force execution: fetch one element to host (block_until_ready is
    a no-op on the tunneled platform; a fetch cannot complete before
    the producing computation does)."""
    leaf = jax.tree.leaves(x)[0]
    np.asarray(leaf).ravel()[:1]


def bench_tally(n_instances: int = 4096, n_validators: int = 1024,
                heights: int = 8) -> float:
    """Device-plane ingestion rate with FRESH votes: each iteration is
    one honest height (entry + prevote phase + precommit phase); the
    height-advance stage resets for the next — no vote is ever a dedup
    replay (VERDICT r2 weak #3).  All `heights` heights run in ONE
    dispatch (device/step.py honest_heights: lax.scan over heights) —
    phase-at-a-time stepping was ~60-70ms/dispatch tunnel-overhead
    bound, not device bound (scripts/timing_check.py r4)."""
    from agnes_tpu.device.step import honest_heights_jit

    I, V = n_instances, n_validators
    cfg = TallyConfig(n_validators=V, n_rounds=4, n_slots=4)
    state = DeviceState.new((I,))
    tally = TallyState.new(I, cfg)
    powers = jnp.ones((V,), jnp.int32)
    total = jnp.asarray(V, jnp.int32)
    proposer_flag = jnp.ones((I, cfg.n_rounds), bool)
    propose_value = jnp.full(I, 1, jnp.int32)
    slots = jnp.ones((I, V), jnp.int32)
    mask = jnp.ones((I, V), bool)

    def run(state, tally):
        out = honest_heights_jit(state, tally, slots, mask, powers, total,
                                 proposer_flag, propose_value,
                                 heights=heights)
        return out.state, out.tally

    state, tally = run(state, tally)             # warmup + compile
    _sync(state)
    h0 = int(np.asarray(state.height)[0])
    t0 = time.perf_counter()
    state, tally = run(state, tally)
    _sync(state)
    dt = time.perf_counter() - t0
    assert int(np.asarray(state.height)[0]) == h0 + heights
    return 2 * I * V * heights / dt


def _dispatch_phases(d, phases) -> None:
    """Run built phases on the driver: one step for a single phase, one
    fused step_seq dispatch for several (shared by both pipeline
    variants so they cannot diverge)."""
    if len(phases) == 1:
        d.step(phase=phases[0])
    elif phases:
        d.step_seq(phases)


def _sign_height_sigs(seeds, h):
    """{vote class -> [V, 64] signatures} for one honest height — the
    shared fixture (harness/fixtures.py) so the benched signing layout
    is the one the compile check and the differential tests use."""
    from agnes_tpu.harness.fixtures import sign_class

    return {typ: sign_class(seeds, h, typ, 7)
            for typ in (int(VoteType.PREVOTE), int(VoteType.PRECOMMIT))}


def _signed_fixture(batch):
    from agnes_tpu.core import native
    from agnes_tpu.crypto import ed25519_jax as ejax
    from agnes_tpu.crypto.encoding import vote_signing_bytes

    seeds = [i.to_bytes(4, "little") + bytes(28) for i in range(batch)]
    msgs = [vote_signing_bytes(1, 0, 0, i % 7) for i in range(batch)]
    pks = [native.pubkey(s) for s in seeds]
    sigs = [native.sign(s, m) for s, m in zip(seeds, msgs)]
    return ejax.pack_verify_inputs_host(pks, msgs, sigs)


def bench_verify(batch: int = 131072, iters: int = 8) -> float:
    """Batched Ed25519 verifies/sec through the fused Pallas kernel
    (crypto/pallas_verify.py) on TPU, jnp path elsewhere.

    batch=131072 is the measured throughput sweet spot on v5e: per-call
    dispatch over the axon tunnel costs ~60ms regardless of batch, so
    16k batches are overhead-bound (~250k/s) while 128k batches
    amortize it (1.41M/s measured r4; 256k drops back to 1.33M/s as
    the marginal device rate ~1.25M/s takes over)."""
    from agnes_tpu.crypto import ed25519_jax as ejax

    pub, sig, blocks = _signed_fixture(batch)
    ok = ejax.verify_batch_jit(pub, sig, blocks)   # warmup + compile
    assert bool(np.asarray(ok).all())
    t0 = time.perf_counter()
    outs = [ejax.verify_batch_jit(pub, sig, blocks) for _ in range(iters)]
    for o in outs:
        _sync(o)
    dt = time.perf_counter() - t0
    return batch * iters / dt


def bench_verify_msm(batch: int = 16384, iters: int = 4) -> float:
    """Honest-batch verifies/sec through the PRODUCTION MSM path
    (`verify_batch_adaptive`): per iteration a fresh host-drawn z,
    power-of-two padding, the combined Pippenger check, and the
    host fetch of the verdict — exactly what VoteBatcher's msm mode
    pays per tick.  The per-lane kernel (`ed25519_verifies_per_sec`)
    remains the dispute/fallback path."""
    from agnes_tpu.crypto import msm_jax as M

    pub, sig, blocks = _signed_fixture(batch)
    ok = M.verify_batch_adaptive(pub, sig, blocks)   # warmup + compile
    assert bool(ok.all())
    t0 = time.perf_counter()
    for _ in range(iters):
        ok = M.verify_batch_adaptive(pub, sig, blocks)
        assert bool(ok.all())
    dt = time.perf_counter() - t0
    return batch * iters / dt


def bench_decisions(n_instances: int = 10000, n_validators: int = 1024,
                    heights: int = 10) -> float:
    """Sustained decisions/sec across >= `heights` consecutive heights
    at the config-4 shape — the multi-height number VERDICT r2 asked
    for (on-device height advance keeps the loop off the host)."""
    from agnes_tpu.harness.device_driver import DeviceDriver

    d = DeviceDriver(n_instances, n_validators, advance_height=True)
    d.run_heights_fused(heights)   # warmup + compile (same static H)
    _sync(d.state)
    base = d.stats.decisions_total
    t0 = time.perf_counter()
    d.run_heights_fused(heights)   # ONE dispatch for all H heights
    _sync(d.state)
    dt = time.perf_counter() - t0
    assert d.stats.decisions_total - base == n_instances * heights
    return n_instances * heights / dt


def bench_bridge(n_instances: int = 512, n_validators: int = 256,
                 iters: int = 10) -> float:
    """Wire votes -> dense phases densify rate (vectorized batcher, no
    signatures: the pure host-side cost; the signed path's crypto is
    measured by ed25519_verifies_per_sec and the pipeline)."""
    from agnes_tpu.bridge import VoteBatcher

    I, V = n_instances, n_validators
    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)
    n = I * V
    t_total = 0.0
    for _ in range(iters):
        b = VoteBatcher(I, V, n_slots=4)
        t0 = time.perf_counter()
        b.add_arrays(inst, val, np.zeros(n), np.zeros(n),
                     np.full(n, int(VoteType.PREVOTE)),
                     np.full(n, 7))
        phases = b.build_phases()
        t_total += time.perf_counter() - t0
        assert len(phases) == 1 and phases[0][1] == n
    return n * iters / t_total


def bench_value_flood(n_instances: int = 512, n_validators: int = 256,
                      ticks: int = 4, flood: bool = True) -> float:
    """Adversarial many-distinct-values flood (SURVEY §7 hard part 2,
    VERDICT r3 next #7): every validator votes its OWN value, so all
    but S values per instance overflow the slot budget and take the
    host-fallback tally (C++ RoundVotes buckets) instead of the dense
    device path.  Returns votes/sec through the native loop + device
    step under the flood; `flood=False` runs the same shape honestly
    (the baseline for the degradation ratio — asserted bounded in
    tests/test_value_flood.py).

    Memory stays bounded by design: per-validator dedup runs before
    bucket allocation (core.cpp RoundVotes / round_votes.py add_vote),
    so an equivocating flooder cannot grow buckets past one per
    validator per (instance, round, class)."""
    from agnes_tpu.bridge import NativeIngestLoop, pack_wire_votes
    from agnes_tpu.harness.device_driver import DeviceDriver

    I, V = n_instances, n_validators
    d = DeviceDriver(I, V)
    loop = NativeIngestLoop(I, V, n_slots=4)
    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)
    n = I * V
    values = (1000 + np.tile(np.arange(V), I)) if flood \
        else np.full(n, 7)

    d.step()
    loop.sync_device(np.asarray(d.tally.base_round),
                     np.asarray(d.state.height))
    wires = [pack_wire_votes(inst, val, np.zeros(n), np.full(n, t % 2),
                             np.full(n, int(VoteType.PREVOTE)), values)
             for t in range(ticks)]

    t0 = time.perf_counter()
    for t in range(ticks):
        loop.push(wires[t])
        for phase, _ in loop.build_phases():
            d.step(phase=phase)
    d.block_until_ready()
    dt = time.perf_counter() - t0
    if flood:
        # S slots intern per instance; the rest spilled to host buckets
        assert loop.counters["overflow_votes"] > 0
    return n * ticks / dt


def _pipeline_harness(n_instances: int, n_validators: int, heights: int,
                      make_feeder) -> float:
    """Shared END-TO-END measurement: signed wire votes -> feeder
    (verify + densify) -> fused device step -> decisions -> on-device
    height advance, one fresh height per iteration.  Signatures are
    REAL and verified for every wire vote lane; instances share the
    validator set, so each height signs 2V fresh messages outside the
    timed region, while tiling/packing/verify/densify — the actual
    per-tick ingest cost — stay inside it.

    `make_feeder(I, V, pubkeys) -> (sync, feed, rejected)`:
      sync(base_round, heights)     adopt the device window/heights
      feed(h, sigs_by_typ)          ingest BOTH vote classes of height
                                    h; -> [(phase, n)] in deterministic
                                    (round, class, layer) order
      rejected()                    running bad-signature count

    Both classes go through ONE batch verify (2·I·V lanes — the larger
    batch amortizes the fixed per-dispatch tunnel cost, timing_check
    r4) and the resulting phases run as ONE fused step_seq dispatch."""
    from agnes_tpu.bridge.ingest import vote_messages_np
    from agnes_tpu.core import native
    from agnes_tpu.harness.device_driver import DeviceDriver

    I, V = n_instances, n_validators
    seeds = [i.to_bytes(4, "little") + bytes(28) for i in range(V)]
    pubkeys = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                        for s in seeds])
    d = DeviceDriver(I, V, advance_height=True)
    sync, feed, rejected = make_feeder(I, V, pubkeys)
    n = I * V

    def sign_height(h):
        """2V fresh signatures (one per validator per class)."""
        return _sign_height_sigs(seeds, h)

    def run_height(h, sigs_by_typ):
        d.step()                       # entry + self proposal
        sync(np.asarray(d.tally.base_round), np.asarray(d.state.height))
        _dispatch_phases(d, [p for p, _ in feed(h, sigs_by_typ)])

    run_height(0, sign_height(0))      # warmup + compile
    _sync(d.state)
    assert d.stats.decisions_total == I, d.stats.decisions_total
    assert rejected() == 0

    all_sigs = [sign_height(h) for h in range(1, heights + 1)]
    t0 = time.perf_counter()
    for h in range(1, heights + 1):
        run_height(h, all_sigs[h - 1])
    _sync(d.state)
    dt = time.perf_counter() - t0
    assert d.stats.decisions_total == I * (heights + 1)
    assert rejected() == 0
    return 2 * n * heights / dt


def _numpy_feeder(I, V, pubkeys):
    """VoteBatcher (vectorized numpy) feeder."""
    from agnes_tpu.utils.config import RunConfig

    bat = RunConfig(n_validators=V, n_instances=I,
                    n_slots=4).validate().make_batcher()
    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)
    n = I * V

    def feed(h, sigs_by_typ):
        for typ, sigs in sigs_by_typ.items():
            bat.add_arrays(inst, val, np.full(n, h), np.zeros(n),
                           np.full(n, typ), np.full(n, 7), sigs[val])
        return bat.build_phases(pubkeys)   # ONE 2n-lane batch verify

    return bat.sync_device, feed, lambda: bat.rejected_signature


def _native_feeder(I, V, pubkeys):
    """C++ ingestion event loop feeder (core/native/ingest.cpp):
    packed 96-byte wire records -> push/stage -> TPU batch verify ->
    verdict filter -> dedup/layer/intern -> double-buffered dense
    phases — the SURVEY §2.7 host-driver slot doing its job in the
    flagship path, not just in differential tests."""
    from agnes_tpu.bridge.native_ingest import pack_wire_votes
    from agnes_tpu.utils.config import RunConfig

    loop = RunConfig(n_validators=V, n_instances=I,
                     n_slots=4).validate().make_native_loop(pubkeys=pubkeys)
    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)
    n = I * V

    def feed(h, sigs_by_typ):
        for typ, sigs in sigs_by_typ.items():
            loop.push(pack_wire_votes(inst, val, np.full(n, h),
                                      np.zeros(n), np.full(n, typ),
                                      np.full(n, 7), sigs[val]))
        return loop.build_phases()         # ONE 2n-lane batch verify

    return (loop.sync_device, feed,
            lambda: loop.counters["rejected_signature"])


def _pipeline_overlapped(n_instances: int, n_validators: int,
                         heights: int, tracer=None) -> float:
    """END-TO-END with BOTH overlap mechanisms on (VERDICT r3 next #4):

      * push_async — the C++ worker thread parses/screens the next
        phase's wire records while this thread packs more and drives
        the device (core/native/ingest.cpp ingest_worker_main);
      * defer_collect — JAX async dispatch is left to run: the per-step
        message sync is deferred to the end of the run, so host
        pack/push/verify/emit of phase k+1 overlaps device step k.

    Same wire traffic, same assertions as the synchronous native path;
    the rate difference IS the measured overlap.

    `tracer` (utils.tracing.Tracer) wraps each host-side stage in a
    chrome-trace span — scripts/profile_overlap.py uses this to show
    the device time hidden inside the host spans."""
    import contextlib

    from agnes_tpu.bridge.ingest import vote_messages_np
    from agnes_tpu.bridge.native_ingest import pack_wire_votes
    from agnes_tpu.core import native
    from agnes_tpu.harness.device_driver import DeviceDriver
    from agnes_tpu.utils.config import RunConfig

    span = tracer.span if tracer is not None \
        else (lambda name: contextlib.nullcontext())
    I, V = n_instances, n_validators
    seeds = [i.to_bytes(4, "little") + bytes(28) for i in range(V)]
    pubkeys = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                        for s in seeds])
    d = DeviceDriver(I, V, advance_height=True, defer_collect=True)
    loop = RunConfig(n_validators=V, n_instances=I,
                     n_slots=4).validate().make_native_loop(pubkeys=pubkeys)
    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)
    n = I * V

    def sign_height(h):
        return _sign_height_sigs(seeds, h)

    def run_height(h, sigs_by_typ):
        with span("entry_dispatch"):
            d.step()               # entry (async dispatch, not awaited)
        with span("sync"):
            loop.sync_device(np.asarray(d.tally.base_round),
                             np.asarray(d.state.height))
        # queue BOTH classes: the worker parses while we keep packing
        # and while the entry step runs on device
        for typ in (int(VoteType.PREVOTE), int(VoteType.PRECOMMIT)):
            with span("pack"):
                wire = pack_wire_votes(
                    inst, val, np.full(n, h), np.zeros(n),
                    np.full(n, typ), np.full(n, 7), sigs_by_typ[typ][val])
            with span("push_async"):
                loop.push_async(wire)
        # one build emits prevote then precommit phases (deterministic
        # (round, class, layer) order) — ONE fused dispatch for all of
        # them (device/step.py consensus_step_seq)
        with span("build(verify+emit)"):
            phases = [p for p, _ in loop.build_phases()]
        with span("step_dispatch"):
            _dispatch_phases(d, phases)

    run_height(0, sign_height(0))   # warmup + compile
    d.block_until_ready()
    assert d.stats.decisions_total == I, d.stats.decisions_total
    assert loop.counters["rejected_signature"] == 0

    all_sigs = [sign_height(h) for h in range(1, heights + 1)]
    t0 = time.perf_counter()
    for h in range(1, heights + 1):
        run_height(h, all_sigs[h - 1])
    d.block_until_ready()
    dt = time.perf_counter() - t0
    assert d.stats.decisions_total == I * (heights + 1)
    assert loop.counters["rejected_signature"] == 0
    return 2 * n * heights / dt


def _pipeline_fused(n_instances: int, n_validators: int,
                    heights: int) -> float:
    """END-TO-END with DEVICE-FUSED verification (device/step.py
    consensus_step_seq_signed): per height ONE dispatch — entry +
    prevote + precommit, with the batched Ed25519 verdicts masking the
    phases ON device — and ZERO device fetches inside the loop (the
    batcher window state is predicted: honest pipeline -> round 0,
    height h).  Heights queue back-to-back through JAX async dispatch,
    so the ~60-70ms/dispatch tunnel latency amortizes across the queue
    instead of serializing per height — the removal of the per-height
    verdict sync the host-verified paths must pay.  Differential-held
    to the host path by tests/test_step_signed.py."""
    from agnes_tpu.bridge.ingest import vote_messages_np
    from agnes_tpu.core import native
    from agnes_tpu.harness.device_driver import DeviceDriver
    from agnes_tpu.utils.config import RunConfig

    I, V = n_instances, n_validators
    seeds = [i.to_bytes(4, "little") + bytes(28) for i in range(V)]
    pubkeys = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                        for s in seeds])
    d = DeviceDriver(I, V, advance_height=True, defer_collect=True)
    bat = RunConfig(n_validators=V, n_instances=I,
                    n_slots=4).validate().make_batcher()
    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)
    n = I * V

    def sign_height(h):
        return _sign_height_sigs(seeds, h)

    def run_height(h, sigs_by_typ):
        bat.sync_device(np.zeros(I, np.int64), np.full(I, h, np.int64))
        for typ, sigs in sigs_by_typ.items():
            bat.add_arrays(inst, val, np.full(n, h), np.zeros(n),
                           np.full(n, typ), np.full(n, 7), sigs[val])
        phases, lanes = bat.build_phases_device(pubkeys, phase_offset=1)
        d.step_seq_signed([d.empty_phase()] + [p for p, _ in phases],
                          lanes)

    run_height(0, sign_height(0))      # warmup + compile
    d.block_until_ready()
    assert d.stats.decisions_total == I, d.stats.decisions_total
    assert d.rejected_signature_device == 0

    all_sigs = [sign_height(h) for h in range(1, heights + 1)]
    t0 = time.perf_counter()
    for h in range(1, heights + 1):
        run_height(h, all_sigs[h - 1])
    d.block_until_ready()
    dt = time.perf_counter() - t0
    assert d.stats.decisions_total == I * (heights + 1)
    assert d.rejected_signature_device == 0
    return 2 * n * heights / dt


def _pipeline_serve(n_instances: int, n_validators: int,
                    heights: int) -> float:
    """CLOSED-LOOP through the STREAMING SERVE PLANE (agnes_tpu/serve,
    ISSUE 2): per height the wire bytes for both vote classes are
    `submit`ted to the bounded admission queue, the micro-batcher
    closes a full-tick batch, and the double-buffered pipeline
    dispatches the device-fused signed step (donated state/tally
    buffers, deferred collection) while the host densifies the next
    height — the same fused path `_pipeline_fused` measures, but
    through the online subsystem a production deployment would run,
    including admission parse/screen/fairness accounting per vote.
    Window state is predicted (honest pipeline -> round 0, height h),
    so nothing fetches from the device inside the loop."""
    from agnes_tpu.bridge.native_ingest import pack_wire_votes
    from agnes_tpu.core import native
    from agnes_tpu.harness.device_driver import DeviceDriver
    from agnes_tpu.serve import ShapeLadder, VoteService
    from agnes_tpu.utils.config import RunConfig

    I, V = n_instances, n_validators
    seeds = [i.to_bytes(4, "little") + bytes(28) for i in range(V)]
    pubkeys = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                        for s in seeds])
    d = DeviceDriver(I, V, advance_height=True, defer_collect=True,
                     audit=True)
    bat = RunConfig(n_validators=V, n_instances=I,
                    n_slots=4).validate().make_batcher()
    n = I * V
    rung = 1 << (2 * n - 1).bit_length()       # one full tick's lanes
    cur = {"h": 0}
    svc = VoteService(
        d, bat, pubkeys, capacity=4 * n, target_votes=2 * n,
        max_delay_s=1e9,                       # size-closed batches
        ladder=ShapeLadder.plan(I, V, min_rung=rung),
        window_predictor=lambda: (np.zeros(I, np.int64),
                                  np.full(I, cur["h"], np.int64)),
        flightrec=_FLIGHTREC)
    # heartbeat lines now carry the serve registry's windowed rates,
    # gauges and latency quantiles (ISSUE 8: telemetry while it
    # runs).  Own window key: the heartbeat's per-interval consumption
    # must not close the "shared" window under the drain report.
    _set_probe_source(lambda: svc.metrics.snapshot(
        window=True, window_key="heartbeat"))
    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)

    def wire_height(h, sigs_by_typ):
        return b"".join(
            pack_wire_votes(inst, val, np.full(n, h), np.zeros(n),
                            np.full(n, typ), np.full(n, 7), sigs[val])
            for typ, sigs in sigs_by_typ.items())

    def run_height(h, wire):
        cur["h"] = h
        svc.submit(wire)
        svc.pump()          # dispatch height h-1, densify height h

    run_height(0, wire_height(0, _sign_height_sigs(seeds, 0)))
    svc.pump()              # dispatch height 0 (warmup + compile)
    d.block_until_ready()
    assert d.stats.decisions_total == I, d.stats.decisions_total
    assert d.rejected_signature_device == 0

    all_wire = [wire_height(h, _sign_height_sigs(seeds, h))
                for h in range(1, heights + 1)]
    t0 = time.perf_counter()
    for h in range(1, heights + 1):
        run_height(h, all_wire[h - 1])
    svc.pump()              # dispatch the last staged height
    d.block_until_ready()
    dt = time.perf_counter() - t0
    assert d.stats.decisions_total == I * (heights + 1), \
        d.stats.decisions_total
    assert d.rejected_signature_device == 0
    rep = svc.drain()
    assert rep["queue"]["rejected_overflow"] == 0
    assert rep["latency"]["serve_submit_to_decision_s"]["count"] > 0
    if os.environ.get("AGNES_SERVE_SMOKE_METRICS"):
        # ci.sh gate [3b]: prove the /metrics endpoint serves ONE
        # clean scrape over the live registry — parsed, and the
        # headline admission counter round-trips exactly
        from urllib.request import urlopen

        from agnes_tpu.utils.metrics_http import parse_prometheus

        srv = svc.start_metrics_server()
        try:
            text = urlopen(
                f"http://127.0.0.1:{srv.port}/metrics",
                timeout=30).read().decode()
        finally:
            srv.stop()
        parsed = parse_prometheus(text)
        _EXTRA_RECORD.update({
            "metrics_scrape_ok": bool(
                parsed.get("serve_submitted")
                == svc.metrics.counters.get("serve_submitted")
                and parsed.get("serve_submit_to_decision_s_count",
                               0) > 0),
            "metrics_scrape_series": len(parsed),
        })
    _EXTRA_RECORD.update({
        "serve_submit_to_decision_p50_s":
            rep["metrics"]["serve_submit_to_decision_s_p50"],
        "serve_submit_to_decision_p99_s":
            rep["metrics"]["serve_submit_to_decision_s_p99"],
    })
    _harvest_audit(d)
    return 2 * n * heights / dt


def _pipeline_serve_mesh(n_instances: int, n_validators: int,
                         heights: int, n_data: int = 2,
                         n_val: int = 1) -> float:
    """CLOSED-LOOP through the serve plane ON A MESH (ISSUE 3): the
    driver is built over a (data x val) device mesh, every batch
    densifies through VoteBatcher's DENSE builder and dispatches the
    shard_map-sharded fused signed step with donated buffers
    (step_async's mesh path — each device verifies its local cells,
    zero added collectives), and the host side is the FULL concurrent
    production shape: ThreadedVoteService's inbox -> submit thread ->
    bounded admission -> dispatch thread.  Feeding is height-paced —
    wire for height h+1 is submitted once h's dispatch is QUEUED (the
    window predictor must describe the batch being densified), which
    serializes host feed with dispatch queueing but not with device
    execution; collection stays deferred until the end."""
    from agnes_tpu.bridge.native_ingest import pack_wire_votes
    from agnes_tpu.core import native
    from agnes_tpu.harness.device_driver import DeviceDriver
    from agnes_tpu.parallel import make_mesh
    from agnes_tpu.serve import (
        ShapeLadder,
        ThreadedVoteService,
        VoteService,
    )
    from agnes_tpu.utils.config import RunConfig

    I, V = n_instances, n_validators
    need = n_data * n_val
    if len(jax.devices()) < need:
        raise RuntimeError(
            f"mesh serve probe needs {need} devices, "
            f"have {len(jax.devices())}")
    mesh = make_mesh(n_data, n_val, jax.devices()[:need])
    seeds = [i.to_bytes(4, "little") + bytes(28) for i in range(V)]
    pubkeys = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                        for s in seeds])
    d = DeviceDriver(I, V, advance_height=True, defer_collect=True,
                     mesh=mesh, audit=True)
    bat = RunConfig(n_validators=V, n_instances=I,
                    n_slots=4).validate().make_batcher()
    n = I * V
    rung = 1 << (2 * n - 1).bit_length()       # one full tick's votes
    cur = {"h": 0}
    svc = VoteService(
        d, bat, pubkeys, capacity=4 * n, target_votes=2 * n,
        max_delay_s=1e9,                       # size-closed batches
        ladder=ShapeLadder.plan_dense(I, V,
                                      local_shape=d._local_shape(),
                                      min_rung=rung),
        window_predictor=lambda: (np.zeros(I, np.int64),
                                  np.full(I, cur["h"], np.int64)),
        flightrec=_FLIGHTREC)
    _set_probe_source(lambda: svc.metrics.snapshot(
        window=True, window_key="heartbeat"))
    tsvc = ThreadedVoteService(svc, idle_wait_s=1e-4).start()
    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)

    def wire_height(h, sigs_by_typ):
        return b"".join(
            pack_wire_votes(inst, val, np.full(n, h), np.zeros(n),
                            np.full(n, typ), np.full(n, 7), sigs[val])
            for typ, sigs in sigs_by_typ.items())

    def feed(h, wire, spin_timeout_s=3600.0):
        cur["h"] = h
        # side-effecting calls stay STATEMENTS (never bare asserts —
        # python -O would strip the submit and the gate would hang)
        if not tsvc.submit(wire):
            raise RuntimeError("inbox refused the height's wire")
        want = 2 * n * (h + 1)
        t_end = time.monotonic() + spin_timeout_s
        while svc.pipeline.dispatched_votes < want:
            if tsvc.failure is not None:
                # a dead loop thread would otherwise stall the spin
                # until the outer deadline and bury the real traceback
                raise RuntimeError(
                    f"serve loop thread died at height {h}"
                ) from tsvc.failure
            if time.monotonic() > t_end:
                raise RuntimeError(
                    f"mesh serve probe stalled at height {h}: "
                    f"{svc.pipeline.dispatched_votes}/{want} votes "
                    f"dispatched")
            time.sleep(5e-4)

    feed(0, wire_height(0, _sign_height_sigs(seeds, 0)))   # compile
    warm_decisions = tsvc.poll_decisions()     # settles the warm height
    assert len(warm_decisions) == I, warm_decisions
    assert d.rejected_signature_device == 0

    all_wire = [wire_height(h, _sign_height_sigs(seeds, h))
                for h in range(1, heights + 1)]
    t0 = time.perf_counter()
    for h in range(1, heights + 1):
        feed(h, all_wire[h - 1])
    tsvc.poll_decisions()       # the one sync point: collect them all
    dt = time.perf_counter() - t0
    assert d.stats.decisions_total == I * (heights + 1), \
        d.stats.decisions_total
    rep = tsvc.drain()
    assert rep["rejected_signature_device"] == 0
    assert rep["offladder_builds"] == 0
    assert rep["queue"]["rejected_overflow"] == 0
    assert rep["inbox"]["dropped"] == 0
    _harvest_audit(d)
    return 2 * n * heights / dt


def _pipeline_serve_multihost(n_instances: int, n_validators: int,
                              heights: int, n_hosts: int = 2,
                              devices_per_host: int = 2,
                              n_val: int = 2) -> float:
    """CLOSED-LOOP through the MULTI-HOST serve plane (ISSUE 15): the
    parent spawns `n_hosts` jax.distributed worker processes
    (distributed/smoke.py — each with its own faked CPU devices, gloo
    collectives, HostShard front-end over a DistributedDriver,
    barrier-synchronized warmup, per-height pod decision gathers and
    a host-id-stamped heartbeat), waits under a deadline derived from
    the discovered budget, and aggregates the per-host records.  The
    reported rate is the SLOWEST host's pod-wide votes/sec (every
    host measures the same pod throughput; min is the conservative
    read).  Spawner keys land in the verdict record via
    _EXTRA_RECORD: `multihost_hosts`/`multihost_devices_per_host`
    (the ISSUE 15 satellite), the summed retrace/reject counters the
    gate asserts on, and every worker heartbeat path."""
    import tempfile

    from agnes_tpu.distributed.smoke import spawn_pod

    out_dir = os.environ.get("AGNES_MULTIHOST_DIR") or \
        tempfile.mkdtemp(prefix="agnes_multihost_")
    rem = _DEADLINE.remaining()
    timeout_s = 900.0
    if rem != float("inf"):
        timeout_s = max(60.0,
                        rem - _budget.deadline_margin_s(rem) - 15.0)
    res = spawn_pod(n_hosts, instances=n_instances,
                    validators=n_validators, heights=heights,
                    devices_per_host=devices_per_host, n_val=n_val,
                    out_dir=out_dir, timeout_s=timeout_s,
                    heartbeat=True)
    if res["killed"]:
        raise RuntimeError(
            f"multihost pod breached its {timeout_s:.0f}s spawner "
            f"deadline (logs under {out_dir})")
    errors = [r for r in res["pod"] if "error" in r]
    if errors:
        raise RuntimeError(f"pod worker(s) failed: {errors} "
                           f"(logs under {out_dir})")
    _EXTRA_RECORD.update({
        "multihost_hosts": n_hosts,
        "multihost_devices_per_host": devices_per_host,
        "multihost_retrace_unexpected": sum(
            r["retrace_unexpected"] for r in res["pod"]),
        "multihost_rejected_signature_device": sum(
            r["rejected_signature_device"] for r in res["pod"]),
        "multihost_pod_decisions": min(
            r["pod_decisions"] for r in res["pod"]),
        "multihost_foreign_rejects": sum(
            r["foreign_rejects"] for r in res["pod"]),
        "multihost_offladder_builds": sum(
            r["offladder_builds"] for r in res["pod"]),
        "multihost_heartbeat_paths": [
            res["paths"][f"pod{k}"]["heartbeat"]
            for k in range(n_hosts)],
    })
    return min(r["votes_per_sec"] for r in res["pod"])


def _pipeline_serve_elastic(n_instances: int, n_validators: int,
                            heights: int, n_hosts: int = 2,
                            devices_per_host: int = 2,
                            n_val: int = 2) -> float:
    """CLOSED-LOOP through the ELASTIC pod serve plane (ISSUE 17):
    the same spawned 2-process pod as _pipeline_serve_multihost, but
    driven through ElasticShard's negotiated ticks — heterogeneous
    per-host traffic (the hosts deliberately close different batch
    shapes every tick, padded to the per-tick max) plus ONE host
    leave + rejoin cycle across membership epoch boundaries, with
    the departed host's gossip held by the survivor and re-routed
    through the readmission boundary's own frame.  The probe itself
    cross-checks the hosts' height-stamped decision rows (a
    mini-differential: elasticity must not change decisions) and
    surfaces the membership evidence — boundaries, epoch,
    readmissions, re-route counts, zero unexpected retraces — via
    _EXTRA_RECORD for the ci.sh gate's asserts."""
    import tempfile

    from agnes_tpu.distributed.smoke import spawn_pod

    leave_h = int(os.environ.get("AGNES_ELASTIC_LEAVE_HEIGHT", "1"))
    rejoin_h = int(os.environ.get("AGNES_ELASTIC_REJOIN_HEIGHT", "2"))
    out_dir = os.environ.get("AGNES_ELASTIC_DIR") or \
        tempfile.mkdtemp(prefix="agnes_elastic_")
    rem = _DEADLINE.remaining()
    timeout_s = 900.0
    if rem != float("inf"):
        timeout_s = max(60.0,
                        rem - _budget.deadline_margin_s(rem) - 15.0)
    res = spawn_pod(n_hosts, instances=n_instances,
                    validators=n_validators, heights=heights,
                    devices_per_host=devices_per_host, n_val=n_val,
                    out_dir=out_dir, timeout_s=timeout_s,
                    heartbeat=True, elastic=True,
                    leave_height=leave_h, rejoin_height=rejoin_h)
    if res["killed"]:
        raise RuntimeError(
            f"elastic pod breached its {timeout_s:.0f}s spawner "
            f"deadline (logs under {out_dir})")
    errors = [r for r in res["pod"] if "error" in r]
    if errors:
        raise RuntimeError(f"elastic pod worker(s) failed: {errors} "
                           f"(logs under {out_dir})")
    rows = [r["pod_decision_rows"] for r in res["pod"]]
    if any(rw != rows[0] for rw in rows[1:]):
        raise RuntimeError(
            f"elastic pod decision rows diverged across hosts "
            f"(records under {out_dir})")
    _EXTRA_RECORD.update({
        "elastic_hosts": n_hosts,
        "elastic_devices_per_host": devices_per_host,
        "elastic_leave_height": leave_h,
        "elastic_rejoin_height": rejoin_h,
        "elastic_boundaries": min(
            r["boundaries"] for r in res["pod"]),
        "elastic_membership_epoch": min(
            r["membership_epoch"] for r in res["pod"]),
        "elastic_readmissions": max(
            r["readmissions"] for r in res["pod"]),
        "elastic_retrace_unexpected": sum(
            r["retrace_unexpected"] for r in res["pod"]),
        "elastic_foreign_rejects": sum(
            r["foreign_rejects"] for r in res["pod"]),
        "elastic_pod_decisions": min(
            r["pod_decisions"] for r in res["pod"]),
        "elastic_warmed_shapes": min(
            r["warmed_shapes"] for r in res["pod"]),
        "elastic_padded_slots": sum(
            r["padded_slots"] for r in res["pod"]),
        "elastic_reroute_sent": sum(
            r["reroute_sent"] for r in res["pod"]),
        "elastic_reroute_received": sum(
            r["reroute_received"] for r in res["pod"]),
        "elastic_held_dropped": sum(
            r["held_dropped"] for r in res["pod"]),
        "elastic_heartbeat_paths": [
            res["paths"][f"pod{k}"]["heartbeat"]
            for k in range(n_hosts)],
    })
    return min(r["votes_per_sec"] for r in res["pod"])


def _pipeline_serve_dedup(n_instances: int, n_validators: int,
                          heights: int, dup: Optional[int] = None
                          ) -> float:
    """CLOSED-LOOP through the serve plane under DUPLICATED traffic
    (ISSUE 5): gossip delivers each vote O(peers) times, modeled here
    as every height's prevote class arriving `dup` times — first copy
    fresh (device-verified, then cached at settle), the re-deliveries
    dedup-cache hits that the split-rung dispatch routes to the
    verify-free unsigned entries.  Precommits arrive once and decide
    the height (re-deliveries after a decision are stale-height drops
    on EVERY path, so they model no verify work either way).

    Measures dedup-ON, then replays the SAME traffic dedup-OFF in the
    same process — every compiled shape is shared, so the second run
    pays zero compiles and the speedup ratio is apples-to-apples.
    Emits the comparison + hit rate via the smoke record's extra keys
    (_EXTRA_RECORD)."""
    from agnes_tpu.bridge.native_ingest import pack_wire_votes
    from agnes_tpu.core import native
    from agnes_tpu.harness.device_driver import DeviceDriver
    from agnes_tpu.serve import ShapeLadder, VerifiedCache, VoteService
    from agnes_tpu.utils.config import RunConfig

    dup = (int(os.environ.get("AGNES_BENCH_SERVE_DUP", "8"))
           if dup is None else int(dup))
    assert dup >= 2, f"duplication factor must be >= 2: {dup}"
    I, V = n_instances, n_validators
    seeds = [i.to_bytes(4, "little") + bytes(28) for i in range(V)]
    pubkeys = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                        for s in seeds])
    n = I * V
    rung = 1 << (n - 1).bit_length()       # one vote CLASS per tick
    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)
    PV, PC = int(VoteType.PREVOTE), int(VoteType.PRECOMMIT)

    def wire_class(h, typ, sigs):
        return pack_wire_votes(inst, val, np.full(n, h), np.zeros(n),
                               np.full(n, typ), np.full(n, 7),
                               sigs[val])

    all_wire = [
        {typ: wire_class(h, typ, sigs)
         for typ, sigs in _sign_height_sigs(seeds, h).items()}
        for h in range(heights + 1)]

    def run(dedup: bool):
        d = DeviceDriver(I, V, advance_height=True, defer_collect=True,
                         audit=True)
        bat = RunConfig(n_validators=V, n_instances=I,
                        n_slots=4).validate().make_batcher()
        cur = {"h": 0}
        svc = VoteService(
            d, bat, pubkeys, capacity=4 * n, target_votes=n,
            max_delay_s=1e9,                   # size-closed: one class
            ladder=ShapeLadder.plan(I, V, min_rung=rung),
            dedup_cache=VerifiedCache() if dedup else None,
            window_predictor=lambda: (np.zeros(I, np.int64),
                                      np.full(I, cur["h"], np.int64)),
            flightrec=_FLIGHTREC)
        # same telemetry contract as the other serve probes: a wedge
        # inside this probe must leave per-interval serve rates /
        # latency quantiles on the heartbeat trail (the dedup-off
        # replay re-points the source at ITS service)
        _set_probe_source(lambda: svc.metrics.snapshot(
            window=True, window_key="heartbeat"))

        def run_height(h):
            cur["h"] = h
            svc.submit(all_wire[h][PV])
            svc.pump()              # densify the fresh prevotes
            svc.pump()              # dispatch them
            svc.poll_decisions()    # settle: clean verifies -> cache
            for _ in range(dup - 1):         # gossip re-deliveries
                svc.submit(all_wire[h][PV])
                svc.pump()
                svc.pump()
            svc.submit(all_wire[h][PC])      # precommits decide h
            svc.pump()
            svc.pump()

        run_height(0)                        # warmup + compiles
        d.block_until_ready()
        assert d.stats.decisions_total == I, d.stats.decisions_total
        assert d.rejected_signature_device == 0

        t0 = time.perf_counter()
        for h in range(1, heights + 1):
            run_height(h)
        d.block_until_ready()
        dt = time.perf_counter() - t0
        assert d.stats.decisions_total == I * (heights + 1), \
            d.stats.decisions_total
        assert d.rejected_signature_device == 0
        rep = svc.drain()
        assert rep["queue"]["rejected_overflow"] == 0
        assert rep["host_fallback_builds"] == 0
        _harvest_audit(d)
        # throughput = ADMITTED records over the steady heights: the
        # duplication multiplier is the point — dedup absorbs the same
        # offered load with a fraction of the verify lanes
        return (dup + 1) * n * heights / dt, rep

    rate_on, rep_on = run(dedup=True)
    cache = rep_on["serve_cache"]
    assert cache is not None and cache["hits"] > 0, cache
    assert rep_on["preverified_votes"] > 0, rep_on
    rate_off, _ = run(dedup=False)
    _EXTRA_RECORD.update({
        "serve_cache_hit_rate": cache["hit_rate"],
        "serve_dedup_dup_factor": dup,
        "pipeline_serve_dedup_off_votes_per_sec": round(rate_off),
        "serve_dedup_speedup": (round(rate_on / rate_off, 2)
                                if rate_off > 0 else -1),
    })
    return rate_on


def _pipeline_serve_native(n_instances: int, n_validators: int,
                           heights: int) -> float:
    """CLOSED-LOOP through the serve plane behind the NATIVE admission
    front-end (ISSUE 14): the FULL concurrent production shape —
    ThreadedVoteService's inbox -> submit thread -> C++ admission
    (parse/screen/fairness/SHA-256 behind one GIL-releasing call) ->
    dispatch thread — with a dedup cache attached so the digest path
    is exercised.  Then the SAME traffic through the Python
    AdmissionQueue in-process (shared compiles — native admission is
    host-only, so the second run must add ZERO new XLA compiles;
    asserted, exported as `native_new_compiles`), recording
    `serve_submit_busy_frac` from both runs for the before/after the
    verdict record carries.

    The headline `native_admission_speedup` comes from a HOST-ONLY
    submit/drain A/B over the same wire bytes: at smoke shapes the
    end-to-end rate is compile/dispatch-bound and would bury the
    admission delta in device noise, while the submit/drain path is
    exactly what the front-end moved to C++.

    ISSUE 20 extends the probe with two more host-only A/Bs over the
    same wire: `native_densify_speedup` — drain_phases + adopt (the
    zero-copy device-build fill in C) vs plain drain + add_arrays +
    build_phases_device (the Python densify) — and
    `native_shard_speedup` — 2 producer threads hammering the
    gossip-shaped submit path against NativeAdmissionShards
    (per-shard mutexes) vs the single native queue (one mutex); the
    shard count rides the AGNES_BENCH_SERVE_NATIVE_SMOKE value and is
    exported as `native_shards`.  The closed-loop ON run itself goes
    through the shard group + phases path whenever the shard count
    divides the shape, so `native_phase_builds` measures real
    adoption under the threaded host."""
    from agnes_tpu.bridge.native_ingest import pack_wire_votes
    from agnes_tpu.core import native
    from agnes_tpu.harness.device_driver import DeviceDriver
    from agnes_tpu.device import registry as _registry
    from agnes_tpu.serve import (
        AdmissionQueue,
        ShapeLadder,
        ThreadedVoteService,
        VerifiedCache,
        VoteService,
    )
    from agnes_tpu.serve.native_admission import NativeAdmissionQueue
    from agnes_tpu.utils.config import RunConfig
    from agnes_tpu.utils.metrics import (
        SERVE_NATIVE_DRAIN_WALL_S,
    )
    from agnes_tpu.serve.service import SERVE_SUBMIT_BUSY_FRAC

    I, V = n_instances, n_validators
    seeds = [i.to_bytes(4, "little") + bytes(28) for i in range(V)]
    pubkeys = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                        for s in seeds])
    n = I * V
    rung = 1 << (2 * n - 1).bit_length()       # one full tick's votes
    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)

    def wire_height(h, sigs_by_typ):
        return b"".join(
            pack_wire_votes(inst, val, np.full(n, h), np.zeros(n),
                            np.full(n, typ), np.full(n, 7), sigs[val])
            for typ, sigs in sigs_by_typ.items())

    all_wire = [wire_height(h, _sign_height_sigs(seeds, h))
                for h in range(heights + 1)]

    n_shards = _native_shard_knob()
    # the closed-loop ON run rides the shard group + phases path when
    # the knob divides the shape (the construction-time contract)
    run_shards = (n_shards if I % n_shards == 0
                  and (4 * n) % n_shards == 0 else 1)

    def run(native_admission: bool):
        d = DeviceDriver(I, V, advance_height=True, defer_collect=True,
                         audit=True)
        bat = RunConfig(n_validators=V, n_instances=I,
                        n_slots=4).validate().make_batcher()
        cur = {"h": 0}
        svc = VoteService(
            d, bat, pubkeys, capacity=4 * n, target_votes=2 * n,
            max_delay_s=1e9,                   # size-closed batches
            ladder=ShapeLadder.plan(I, V, min_rung=rung),
            dedup_cache=VerifiedCache(),
            native_admission=native_admission,
            native_shards=(run_shards if native_admission else 1),
            window_predictor=lambda: (np.zeros(I, np.int64),
                                      np.full(I, cur["h"], np.int64)),
            flightrec=_FLIGHTREC)
        tsvc = ThreadedVoteService(svc, idle_wait_s=1e-4)
        # the heartbeat source samples the busy gauges on the shared
        # window first (the ISSUE 14 satellite: busy fracs read live
        # under heartbeat, not only when a loop's window rolls)
        def source():
            tsvc.sample_busy_gauges()
            return svc.metrics.snapshot(window=True,
                                        window_key="heartbeat")
        _set_probe_source(source)
        tsvc.start()

        def feed(h, wire, spin_timeout_s=3600.0):
            cur["h"] = h
            if not tsvc.submit(wire):
                raise RuntimeError("inbox refused the height's wire")
            want = 2 * n * (h + 1)
            t_end = time.monotonic() + spin_timeout_s
            while svc.pipeline.dispatched_votes < want:
                if tsvc.failure is not None:
                    raise RuntimeError(
                        f"serve loop thread died at height {h}"
                    ) from tsvc.failure
                if time.monotonic() > t_end:
                    raise RuntimeError(
                        f"native serve probe stalled at height {h}")
                time.sleep(5e-4)

        feed(0, all_wire[0])                   # warmup + compiles
        warm = tsvc.poll_decisions()
        if len(warm) != I:
            raise RuntimeError(f"warm height decided {len(warm)}/{I}")
        busy0 = tsvc.busy_seconds()["submit"]
        t0 = time.perf_counter()
        for h in range(1, heights + 1):
            feed(h, all_wire[h])
        tsvc.poll_decisions()       # the one sync point
        dt = time.perf_counter() - t0
        # whole-measured-span busy fraction (the lifetime totals, not
        # the last gauge window — which is idle by drain time); the
        # windowed SERVE_SUBMIT_BUSY_FRAC gauge stays the live
        # heartbeat view
        busy = (tsvc.busy_seconds()["submit"] - busy0) / dt
        assert d.stats.decisions_total == I * (heights + 1), \
            d.stats.decisions_total
        rep = tsvc.drain()
        assert rep["rejected_signature_device"] == 0
        assert rep["queue"]["rejected_overflow"] == 0
        assert rep["inbox"]["dropped"] == 0
        assert SERVE_SUBMIT_BUSY_FRAC in rep["metrics"], \
            "busy gauges missing from the drain snapshot"
        _harvest_audit(d)
        return 2 * n * heights / dt, busy, rep

    rate_on, busy_on, rep_on = run(native_admission=True)
    assert rep_on["native_admission"]["admitted"] > 0, rep_on
    compiles_after_on = len(_registry.compile_ms())
    rate_off, busy_off, _rep_off = run(native_admission=False)
    # native admission is host-only: the Python replay (and the native
    # run before it) must share every compiled shape
    new_compiles = len(_registry.compile_ms()) - compiles_after_on

    # host-only submit/drain A/B on the same wire (docstring).
    # GOSSIP-SHAPED submits: a real frontend hands over a few records
    # per peer call, so the A/B splits each height's wire into
    # 16-record submits — the shape where per-call Python overhead
    # (vs one GIL-releasing C call) is the workload, not an
    # amortized-away constant
    def admission_votes_per_sec(native: bool) -> float:
        cls_ = NativeAdmissionQueue if native else AdmissionQueue
        q = cls_(I, 4 * n, cache=VerifiedCache())
        chunk = 16 * 96
        per_height = [[w[k:k + chunk] for k in range(0, len(w), chunk)]
                      for w in all_wire]
        per_pass = 2 * n * (heights + 1)
        reps = max(1, 30_000 // per_pass)
        t0 = time.perf_counter()
        for _ in range(reps):
            for height_chunks in per_height:
                for wire in height_chunks:
                    q.submit(wire)
                while q.depth:
                    q.drain(2 * n)
        dt = time.perf_counter() - t0
        assert q.counters["admitted"] == reps * per_pass, q.counters
        return reps * per_pass / dt

    adm_native = admission_votes_per_sec(True)
    adm_python = admission_votes_per_sec(False)

    # -- ISSUE 20 A/B 1: zero-copy densify vs Python densify ------------
    # same wire, same batcher discipline: ON drains phase-filled
    # batches (C wrote the device-build arrays) and adopts them; OFF
    # drains plain columns and pays add_arrays + build_phases_device.
    # Both arms end each drain holding device-shaped phases + lanes,
    # so the delta is exactly the per-record Python densify work.
    def densify_votes_per_sec(native_phases: bool) -> float:
        from agnes_tpu.serve.queue import PhaseBuildState

        bat = RunConfig(n_validators=V, n_instances=I,
                        n_slots=4).validate().make_batcher()
        for i in range(I):
            bat.slots.slot_for(i, 7)       # LUT warm: value 7 interned
        q = NativeAdmissionQueue(I, 4 * n)
        if native_phases:
            state = PhaseBuildState(
                heights=np.zeros(I, np.int64),
                base_round=np.zeros(I, np.int64),
                window=bat.W, slot_lut=bat.slots.dense,
                pubkeys=np.ascontiguousarray(pubkeys, np.uint8),
                n_validators=V, lane_floor=rung, max_votes=rung,
                phase_offset=1)
            q.phase_state = lambda: state
        chunk = 16 * 96
        wire0 = all_wire[0]                # height 0 == batcher window
        chunks = [wire0[k:k + chunk] for k in range(0, len(wire0),
                                                    chunk)]
        per_pass = 2 * n
        reps = max(1, 12_000 // per_pass)
        t0 = time.perf_counter()
        for _ in range(reps):
            for w in chunks:
                q.submit(w)
            while q.depth:
                b = q.drain(2 * n)
                if native_phases:
                    assert b.native_phases is not None, \
                        (q.phase_fill, q.phase_bail)
                    bat.adopt_native_phases(b, b.native_phases,
                                            pubkeys)
                else:
                    bat.add_arrays(b.instance, b.validator, b.height,
                                   b.round_, b.typ, b.value,
                                   b.signatures, verified=b.verified,
                                   digest=b.digest)
                    _phases, lanes = bat.build_phases_device(
                        pubkeys, phase_offset=1, lane_floor=rung,
                        max_votes=rung)
                    # device-verify eligible: no host Ed25519 leaked
                    # into the Python arm (that would inflate the
                    # ratio with work neither arm should pay)
                    assert lanes is not None
        dt = time.perf_counter() - t0
        assert q.counters["admitted"] == reps * per_pass, q.counters
        return reps * per_pass / dt

    dens_native = densify_votes_per_sec(True)
    dens_python = densify_votes_per_sec(False)

    # -- ISSUE 20 A/B 2: sharded ingest vs single native queue ----------
    # the 2-CPU gossip-shaped host: 2 producer threads hammering
    # 16-record submits (each owning its instance half — gossip routed
    # by home host) against ONE concurrent drainer.  shards=1 is the
    # single queue (one mutex on the whole path); shards=N the shard
    # group (per-shard leaf mutexes + a routing fan-in).
    from agnes_tpu.serve.native_admission import NativeAdmissionShards

    def shard_votes_per_sec(shards: int) -> float:
        half = I // 2
        n_half = half * V
        reps = max(1, 20_000 // (2 * n_half))
        total = reps * 2 * n_half
        cap = ((total + shards - 1) // shards) * shards  # no overflow
        if shards == 1:
            q = NativeAdmissionQueue(I, cap, instance_cap=cap)
        else:
            q = NativeAdmissionShards(I, cap, instance_cap=cap,
                                      n_shards=shards)
        chunk = 16 * 96
        wires = []
        for p in range(2):
            ip = np.repeat(np.arange(p * half, (p + 1) * half), V)
            vp = np.tile(np.arange(V), half)
            w = pack_wire_votes(ip, vp, np.zeros(n_half),
                                np.zeros(n_half), np.ones(n_half),
                                np.full(n_half, 7),
                                np.zeros((n_half, 64), np.uint8))
            wires.append([w[k:k + chunk]
                          for k in range(0, len(w), chunk)])
        barrier = threading.Barrier(3)

        def producer(p):
            barrier.wait()
            for _ in range(reps):
                for w in wires[p]:
                    q.submit(w)

        threads = [threading.Thread(target=producer, args=(p,))
                   for p in range(2)]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        drained = 0
        while drained < total:
            b = q.drain(4096)
            if b is None:
                time.sleep(1e-5)
                continue
            drained += len(b.instance)
        dt = time.perf_counter() - t0
        for t in threads:
            t.join()
        c = q.counters
        assert c["admitted"] == total and c["drained"] == total, c
        return total / dt

    if I % n_shards == 0 and (os.cpu_count() or 1) >= 2:
        # best-of-3 per arm so a scheduler hiccup on a loaded CI box
        # lands on one trial, not one ARM — the ratio gate is > 1 and
        # must not flake.  The A/B is only MEANINGFUL with real
        # concurrency: producers and the drainer must be able to run
        # in parallel for per-shard mutexes to buy anything (on a
        # single-core box the measurement is pure scheduler noise
        # over the routing fan-in's overhead — sentinel instead)
        shard_single = max(shard_votes_per_sec(1) for _ in range(3))
        shard_group = max(shard_votes_per_sec(n_shards)
                          for _ in range(3))
        shard_speedup = (round(shard_group / shard_single, 2)
                         if shard_single > 0 else -1)
    else:
        shard_group = shard_single = -1
        shard_speedup = -1      # knob does not divide I, or 1 core

    _EXTRA_RECORD.update({
        "pipeline_serve_native_off_votes_per_sec": round(rate_off),
        "native_admission_speedup": (round(adm_native / adm_python, 2)
                                     if adm_python > 0 else -1),
        "native_admission_votes_per_sec": round(adm_native),
        "python_admission_votes_per_sec": round(adm_python),
        "serve_submit_busy_frac_native": round(busy_on, 4),
        "serve_submit_busy_frac_python": round(busy_off, 4),
        "native_new_compiles": new_compiles,
        "serve_native_drain_wall_p50_s":
            rep_on["metrics"].get(SERVE_NATIVE_DRAIN_WALL_S + "_p50",
                                  -1),
        # ISSUE 20: the two new A/Bs + the closed-loop adoption count
        "native_densify_speedup": (round(dens_native / dens_python, 2)
                                   if dens_python > 0 else -1),
        "native_densify_votes_per_sec": round(dens_native),
        "python_densify_votes_per_sec": round(dens_python),
        "native_shard_speedup": shard_speedup,
        "native_shards": n_shards,
        "native_shard_votes_per_sec": (round(shard_group)
                                       if shard_group > 0 else -1),
        "native_single_votes_per_sec": (round(shard_single)
                                        if shard_single > 0 else -1),
        "native_phase_builds": rep_on.get("native_phase_builds", 0),
    })
    return rate_on


def _pipeline_serve_bls(n_instances: int, n_validators: int,
                        heights: int) -> float:
    """CLOSED-LOOP through the serve plane's BLS AGGREGATE lane
    (ISSUE 10): every height's prevote/precommit class arrives as BLS
    wire shares, folds into one AggregateClass per (height, typ),
    aggregates on device (`bls_aggregate`, one padded ladder rung) and
    clears with ONE pairing-product per class — then dispatches the
    whole class down the verify-free unsigned entries.  Afterwards the
    SAME traffic shape runs per-vote Ed25519 in-process (the
    `_pipeline_serve` path) for the `bls_agg_speedup` ratio —
    PAPERS.md 2302.00418's trade measured end-to-end: BLS is ~10x
    slower per signature but one aggregate check covers the class.

    Bench keys (via _EXTRA_RECORD): `bls_agg_speedup`,
    `pipeline_serve_bls_ed25519_votes_per_sec`, `bls_class_size`,
    `serve_bls_agg_classes`, `serve_bls_fallback_votes`, and the
    ISSUE 18 kernel-lane A/B `bls_pallas_speedup` /
    `bls_pallas_compile_ms` (-1 sentinels if the A/B could not run).

    Fixture keys are THROWAWAY benchmark keys (sk_v = v + 1): shares
    and pubkeys build incrementally (one G2/G1 add per validator), so
    fixture setup stays O(V) python point-adds, not O(V) scalar
    mults.  The registry unlocks them through the trust-root seam
    (`mark_trusted`); the cryptographic PoP path (`register_pop`) is
    covered by tests/test_bls.py — one pairing per validator is an
    admission-time cost, not a steady-state serve cost."""
    from agnes_tpu.bridge.native_ingest import pack_wire_votes
    from agnes_tpu.core import native
    from agnes_tpu.crypto import bls_ref as bref
    from agnes_tpu.crypto.encoding import vote_signing_bytes
    from agnes_tpu.harness.device_driver import DeviceDriver
    from agnes_tpu.serve import ShapeLadder, VoteService
    from agnes_tpu.serve.bls_lane import (
        BlsKeyRegistry,
        BlsLane,
        pack_bls_wire,
    )
    from agnes_tpu.utils.config import RunConfig
    from agnes_tpu.utils.metrics import RETRACE_UNEXPECTED

    I, V = n_instances, n_validators
    n = I * V
    PV, PC = int(VoteType.PREVOTE), int(VoteType.PRECOMMIT)
    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)

    # -- BLS fixtures (incremental multiples of G1 / H(msg)) -----------------
    pk_pts = []
    acc = None
    for _v in range(V):
        acc = bref.point_add(acc, bref.G1)
        pk_pts.append(acc)
    pk_bytes = np.stack([
        np.frombuffer(bref.g1_compress(p), np.uint8) for p in pk_pts])

    def bls_wire(h: int, typ: int) -> bytes:
        base = bref.hash_to_g2(vote_signing_bytes(h, 0, typ, 7))
        sig, shares = None, []
        for _v in range(V):
            sig = bref.point_add(sig, base)
            shares.append(np.frombuffer(bref.g2_to_bytes(sig),
                                        np.uint8))
        shares = np.tile(np.stack(shares), (I, 1))
        return pack_bls_wire(inst, val, np.full(n, h), np.zeros(n),
                             np.full(n, typ), np.full(n, 7), shares)

    all_bls = [{typ: bls_wire(h, typ) for typ in (PV, PC)}
               for h in range(heights + 1)]

    reg = BlsKeyRegistry(pk_bytes)
    reg.mark_trusted(np.arange(V))
    rung = 1 << (V - 1).bit_length()
    lane = BlsLane(reg, I, max_classes=4 * I,
                   target_signers=V, max_delay_s=1e9)
    d = DeviceDriver(I, V, advance_height=True, defer_collect=True,
                     audit=True)
    bat = RunConfig(n_validators=V, n_instances=I,
                    n_slots=4).validate().make_batcher()
    cur = {"h": 0}
    svc = VoteService(
        d, bat, None, bls_lane=lane, capacity=4 * n, target_votes=n,
        max_delay_s=1e9,
        ladder=ShapeLadder.plan(I, V).with_bls(V, min_rung=rung),
        window_predictor=lambda: (np.zeros(I, np.int64),
                                  np.full(I, cur["h"], np.int64)),
        flightrec=_FLIGHTREC)
    _set_probe_source(lambda: svc.metrics.snapshot(
        window=True, window_key="heartbeat"))
    # the census gate's drift count rides the heartbeat as a gauge
    # (ISSUE 13 observability satellite; -1 = gate not run here)
    from agnes_tpu.utils.metrics import CENSUS_DRIFT_ENTRIES

    svc.metrics.gauge(CENSUS_DRIFT_ENTRIES,
                      _ANALYSIS[CENSUS_DRIFT_ENTRIES])
    # warm the unsigned entries, the BLS aggregation rung AND the
    # device pairing class rungs, then arm the retrace tripwire: the
    # whole measured run must dispatch ZERO unplanned compiles (the
    # mixed-mode warmup acceptance)
    svc.pipeline.warmup()

    def run_height(h: int) -> None:
        cur["h"] = h
        for typ in (PV, PC):
            svc.submit_bls(all_bls[h][typ])
            svc.pump()               # close + aggregate + pair + stage
            svc.pump()               # dispatch
        svc.poll_decisions()

    run_height(0)                    # pairing memos cold, shapes warm
    d.block_until_ready()
    assert d.stats.decisions_total == I, d.stats.decisions_total

    t0 = time.perf_counter()
    for h in range(1, heights + 1):
        run_height(h)
    d.block_until_ready()
    dt = time.perf_counter() - t0
    assert d.stats.decisions_total == I * (heights + 1), \
        d.stats.decisions_total
    rate_bls = 2 * n * heights / dt
    rep = svc.drain()
    bls = rep["bls"]
    assert bls["fallback_classes"] == 0, bls
    assert bls["rejected_share_signature"] == 0, bls
    assert bls["bls_pop_missing"] == 0, bls
    assert rep["queue"]["rejected_overflow"] == 0
    _harvest_audit(d)

    # -- the per-vote Ed25519 baseline: same traffic shape -------------------
    seeds = [v.to_bytes(4, "little") + bytes(28) for v in range(V)]
    ed_pubkeys = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                           for s in seeds])
    d2 = DeviceDriver(I, V, advance_height=True, defer_collect=True,
                      audit=True)
    bat2 = RunConfig(n_validators=V, n_instances=I,
                     n_slots=4).validate().make_batcher()
    svc2 = VoteService(
        d2, bat2, ed_pubkeys, capacity=4 * n, target_votes=n,
        max_delay_s=1e9,
        ladder=ShapeLadder.plan(I, V, min_rung=1 << (n - 1).bit_length()),
        window_predictor=lambda: (np.zeros(I, np.int64),
                                  np.full(I, cur["h"], np.int64)),
        flightrec=_FLIGHTREC)
    _set_probe_source(lambda: svc2.metrics.snapshot(
        window=True, window_key="heartbeat"))

    def ed_height(h: int) -> None:
        cur["h"] = h
        sigs = _sign_height_sigs(seeds, h)
        for typ in (PV, PC):
            svc2.submit(pack_wire_votes(
                inst, val, np.full(n, h), np.zeros(n),
                np.full(n, typ), np.full(n, 7), sigs[typ][val]))
            svc2.pump()
            svc2.pump()
        svc2.poll_decisions()

    ed_height(0)
    d2.block_until_ready()
    assert d2.stats.decisions_total == I, d2.stats.decisions_total
    t0 = time.perf_counter()
    for h in range(1, heights + 1):
        ed_height(h)
    d2.block_until_ready()
    rate_ed = 2 * n * heights / (time.perf_counter() - t0)
    assert d2.stats.decisions_total == I * (heights + 1)
    assert d2.rejected_signature_device == 0
    _harvest_audit(d2)

    # -- ISSUE 13: host-pairing comparison on the same traffic ---------------
    # A fresh HOST-pairing lane (device_pairing=False — the PR 10
    # path: per-class MSM fetch + bls_ref oracle) clears a CAPPED
    # slice of the SAME wire bytes in-process, so the record carries
    # an apples-to-apples per-class pairing wall for both modes.
    # Capped because a host pairing costs ~1s of pure python per
    # class: up to 4 classes bound the comparison at seconds while
    # the device lane above cleared every class of the whole run.
    from agnes_tpu.utils.metrics import (
        BLS_DEVICE_PAIRING_DISPATCHES,
        BLS_PAIRING_WALL_S,
        Metrics,
    )

    reg_h = BlsKeyRegistry(pk_bytes)
    reg_h.mark_trusted(np.arange(V))
    lane_h = BlsLane(reg_h, I, max_classes=4 * I, target_signers=V,
                     max_delay_s=1e9, device_pairing=False)
    m_h = Metrics()
    lane_h.bind(d, metrics=m_h)       # rungs match the warmed MSM set
    for typ in (PV, PC):
        lane_h.table.fold(all_bls[0][typ])
    host_classes = lane_h.table.poll(now=time.monotonic() + 2e9,
                                     target_signers=V, max_delay_s=0)
    lane_h.clear_classes(host_classes[:4])
    host_snap = m_h.snapshot()
    host_p50 = host_snap.get(f"{BLS_PAIRING_WALL_S}_p50", 0)

    # -- ISSUE 18: Pallas field-kernel lane vs rolled A/B --------------------
    # Times the fused multiply+reduce KERNEL body against the rolled
    # `reduce_cols(_mul_cols(...))` path on one representative operand
    # batch (1024 field elements — a pairing-product's working set per
    # fori step), and asserts exact limb equality while at it.  On a
    # TPU box the kernel is the compiled Mosaic lowering (the lane the
    # serve plane auto-selects); on this CPU gate it runs under the
    # Pallas INTERPRETER, so the recorded speedup is a plumbing +
    # exactness proof, not a throughput claim — interpret overhead
    # makes < 1x expected and honest there.  -1 sentinels if the A/B
    # dies: the record must survive under the crash-safe contract.
    bls_pallas_speedup = bls_pallas_compile_ms = -1.0
    try:
        from agnes_tpu.crypto import bls_field_jax as _BF
        from agnes_tpu.crypto import pallas_field as _PF

        interp = jax.default_backend() != "tpu"
        rng_ab = np.random.default_rng(5)
        xa, ya = (jnp.asarray(rng_ab.integers(
            0, _BF.LMASK + 1, size=(1024, _BF.NLIMBS),
            dtype=np.int64).astype(np.int32)) for _ in range(2))
        t0 = time.perf_counter()
        kern_out = _PF.mul_pairs_call(xa, ya, interpret=interp)
        jax.block_until_ready(kern_out)
        bls_pallas_compile_ms = round(
            (time.perf_counter() - t0) * 1e3, 1)
        rolled_fn = jax.jit(lambda a, b: _BF.reduce_cols(
            _BF._mul_cols(a, b),
            _BF.NLIMBS * _BF._ELEM_LIMB * _BF._ELEM_LIMB))
        rolled_out = rolled_fn(xa, ya)
        np.testing.assert_array_equal(np.asarray(kern_out),
                                      np.asarray(rolled_out))

        def _best_wall(fn, reps=5):
            best = float("inf")
            for _ in range(reps):
                t = time.perf_counter()
                jax.block_until_ready(fn())
                best = min(best, time.perf_counter() - t)
            return best

        t_kern = _best_wall(
            lambda: _PF.mul_pairs_call(xa, ya, interpret=interp))
        t_roll = _best_wall(lambda: rolled_fn(xa, ya))
        if t_kern > 0:
            bls_pallas_speedup = round(t_roll / t_kern, 3)
    except Exception as e:  # noqa: BLE001 — sentinel, not a crash
        print(f"[bench] pallas field A/B failed: {e!r}",
              file=sys.stderr, flush=True)

    snap = rep["metrics"]
    dev_p50 = snap.get("bls_pairing_wall_s_p50", 0)
    _EXTRA_RECORD.update({
        "bls_pallas_speedup": bls_pallas_speedup,
        "bls_pallas_compile_ms": bls_pallas_compile_ms,
        "bls_class_size": V,
        "pipeline_serve_bls_ed25519_votes_per_sec": round(rate_ed),
        "bls_agg_speedup": (round(rate_bls / rate_ed, 2)
                            if rate_ed > 0 else -1),
        "serve_bls_agg_classes": bls["agg_classes"],
        "serve_bls_fallback_votes": bls["fallback_votes"],
        # per-class DEVICE pairing wall quantiles (the histogram now
        # times the batched pairing dispatch divided over its
        # classes) + the host-oracle comparison (ISSUE 13 acceptance:
        # device_speedup > 1)
        "bls_pairing_wall_p50_s": dev_p50,
        "bls_pairing_wall_p99_s": snap.get("bls_pairing_wall_s_p99"),
        "bls_host_pairing_wall_p50_s": round(host_p50, 4),
        "bls_pairing_device_speedup": (round(host_p50 / dev_p50, 2)
                                       if dev_p50 and host_p50 > 0
                                       else -1),
        BLS_DEVICE_PAIRING_DISPATCHES:
            bls[BLS_DEVICE_PAIRING_DISPATCHES],
        "bls_memo_evictions": bls["bls_memo_evictions"],
    })
    assert bls[BLS_DEVICE_PAIRING_DISPATCHES] > 0, bls
    assert _ANALYSIS.get(RETRACE_UNEXPECTED, 0) == 0, _ANALYSIS
    return rate_bls


def bench_pipeline(n_instances: int = 1024, n_validators: int = 128,
                   heights: int = 6) -> float:
    """The flagship headline: end-to-end through the numpy bridge."""
    return _pipeline_harness(n_instances, n_validators, heights,
                             _numpy_feeder)


def bench_pipeline_native(n_instances: int = 1024, n_validators: int = 128,
                          heights: int = 6) -> float:
    """End-to-end with the C++ event loop as the feeder (synchronous
    tick protocol — the overlap baseline)."""
    return _pipeline_harness(n_instances, n_validators, heights,
                             _native_feeder)


def bench_pipeline_overlapped(n_instances: int = 1024,
                              n_validators: int = 128,
                              heights: int = 6) -> float:
    """End-to-end, C++ worker thread + deferred collection."""
    return _pipeline_overlapped(n_instances, n_validators, heights)


def bench_pipeline_fused(n_instances: int = 1024, n_validators: int = 128,
                         heights: int = 6) -> float:
    """End-to-end, device-fused verification (one dispatch/height)."""
    return _pipeline_fused(n_instances, n_validators, heights)


def bench_pipeline_serve(n_instances: int = 1024, n_validators: int = 128,
                         heights: int = 6) -> float:
    """End-to-end through the streaming serve plane (wire admission ->
    micro-batch -> double-buffered fused dispatch)."""
    return _pipeline_serve(n_instances, n_validators, heights)


def bench_pipeline_serve_mesh(n_instances: int = 1024,
                              n_validators: int = 128,
                              heights: int = 6) -> float:
    """End-to-end through the serve plane on a 2-device mesh: threaded
    event-loop host + dense-lane sharded fused dispatch (raises — and
    reports -1 through the stage guard — on single-device backends)."""
    return _pipeline_serve_mesh(n_instances, n_validators, heights)


def bench_pipeline_serve_multihost(n_instances: int = 8,
                                   n_validators: int = 8,
                                   heights: int = 2) -> float:
    """End-to-end through the multi-host serve plane: 2 spawned
    jax.distributed processes x 2 faked CPU devices, per-host
    HostShard front-ends over ONE global-SPMD mesh (ISSUE 15).  A
    CPU-resident probe by construction (the workers pin
    JAX_PLATFORMS=cpu): it measures the pod PROTOCOL overhead —
    lockstep agreement, per-host densify, decision gathers — not
    accelerator throughput, so the default shape stays tiny even in
    hardware rounds."""
    return _pipeline_serve_multihost(n_instances, n_validators,
                                     heights)


def bench_pipeline_serve_elastic(n_instances: int = 8,
                                 n_validators: int = 8,
                                 heights: int = 2) -> float:
    """End-to-end through the ELASTIC pod serve plane: the 2-process
    jax.distributed pod of bench_pipeline_serve_multihost driven
    through ElasticShard's per-tick shape negotiation, heterogeneous
    per-host traffic and one host leave + rejoin cycle across
    membership epoch boundaries (ISSUE 17).  Like the multihost
    probe it measures pod PROTOCOL overhead — negotiation allgather,
    padding, boundary re-lifts — on CPU by construction, so the
    default shape stays tiny even in hardware rounds."""
    return _pipeline_serve_elastic(n_instances, n_validators, heights)


def bench_pipeline_serve_dedup(n_instances: int = 1024,
                               n_validators: int = 128,
                               heights: int = 6) -> float:
    """End-to-end through the serve plane under duplicated gossip
    traffic (AGNES_BENCH_SERVE_DUP copies of each prevote, default 8):
    verified-vote dedup cache + split-rung dispatch (ISSUE 5), with a
    dedup-off replay of the same traffic for the speedup ratio."""
    return _pipeline_serve_dedup(n_instances, n_validators, heights)


def bench_pipeline_serve_native(n_instances: int = 1024,
                                n_validators: int = 128,
                                heights: int = 6) -> float:
    """End-to-end through the serve plane behind the C++ native
    admission front-end (ISSUE 14): threaded host, GIL-releasing
    submit/drain, dedup digests hashed natively — with an in-process
    Python-admission replay of the same traffic and a host-only
    submit/drain A/B for `native_admission_speedup`."""
    return _pipeline_serve_native(n_instances, n_validators, heights)


def bench_pipeline_serve_bls(n_instances: int = 64,
                             n_validators: int = 128,
                             heights: int = 6) -> float:
    """End-to-end through the serve plane's BLS aggregate-precommit
    lane (ISSUE 10): one device MSM + one host pairing per vote class
    instead of one Ed25519 verify per vote, with a per-vote Ed25519
    run of the same traffic in-process for `bls_agg_speedup`."""
    return _pipeline_serve_bls(n_instances, n_validators, heights)


def _smoke_main(stage: str, metric: str, value_key: str, unit: str,
                env_prefix: str, bench_fn, what: str) -> None:
    """ONE crash-safe smoke entry shared by every ci.sh serve gate:
    runs ONLY `bench_fn` at a tiny CPU shape (I/V/HEIGHTS from
    `{env_prefix}_{I,V,HEIGHTS}`), then emits the gate's record —
    stage naming, alarm/watchdog cancellation and the JSON verdict
    structure live HERE so the deadline contract cannot drift between
    smoke modes (each mode's sentinel metric is wired separately via
    _SENTINEL_METRIC/_SENTINEL_STAGE at module scope, before any
    stage can hang).  `metric` is the headline the gate parser
    asserts on; `value_key` carries the measured rate under its own
    name too (for the serve smoke the two differ — the historical
    ISSUE-2 record shape)."""
    global _STAGE, _EMITTED
    _STAGE = stage
    i = int(os.environ.get(f"{env_prefix}_I", "8"))
    v = int(os.environ.get(f"{env_prefix}_V", "8"))
    h = int(os.environ.get(f"{env_prefix}_HEIGHTS", "2"))
    print(f"[bench] {what}: I={i} V={v} heights={h} on "
          f"{len(jax.devices())} CPU device(s)",
          file=sys.stderr, flush=True)
    t0 = time.perf_counter()
    rate = round(bench_fn(i, v, h))
    _RESULTS[stage] = rate
    signal.alarm(0)
    _cancel_deadline_watchdog()
    print(json.dumps({
        "metric": metric,
        "value": rate,
        "unit": unit,
        "vs_baseline": round(rate / NORTH_STAR, 3) if rate > 0 else -1,
        value_key: rate,
        "note": (f"{what} at I={i} V={v} x{h} heights on CPU in "
                 f"{time.perf_counter() - t0:.0f}s"),
        **_EXTRA_RECORD,
        **_ANALYSIS,
        **_compile_record(),
        **_heartbeat_record(),
    }), flush=True)
    _EMITTED = True


def main_serve_smoke() -> None:
    """The ci.sh serve gate's entry: ONLY the closed-loop serve probe,
    tiny shape, CPU — proving the streaming plane drives the fused
    path end-to-end inside the crash-safe deadline contract.  The
    headline key is pipeline_fused_votes_per_sec (the serve plane IS
    the fused path's online frontend; ISSUE 2 acceptance): a real
    number when the box beats the enclosing timeout's compile budget,
    else the -1 sentinel — either way a parseable record is the last
    stdout line."""
    _smoke_main("bench_pipeline_serve", "pipeline_fused_votes_per_sec",
                "pipeline_serve_votes_per_sec", "votes/sec/chip",
                "AGNES_SERVE_SMOKE", bench_pipeline_serve,
                "serve smoke: closed-loop streaming plane")


def main_serve_dedup_smoke() -> None:
    """The ci.sh dedup gate's entry (ISSUE 5): ONLY the duplicated-
    traffic serve probe — dedup cache + split-rung dispatch, dedup-off
    replay for the ratio — tiny shape, CPU, under the same crash-safe
    contract.  The record carries serve_cache_hit_rate and the
    dedup-off comparison via _EXTRA_RECORD."""
    _smoke_main("bench_pipeline_serve_dedup",
                "pipeline_serve_dedup_votes_per_sec",
                "pipeline_serve_dedup_votes_per_sec", "votes/sec/chip",
                "AGNES_SERVE_DEDUP_SMOKE", bench_pipeline_serve_dedup,
                "dedup smoke: duplicated-traffic streaming plane")


def main_serve_bls_smoke() -> None:
    """The ci.sh BLS gate's entry (ISSUE 10 + 13): ONLY the
    aggregate-lane serve probe — BLS class fold -> device MSM -> ALL
    classes' pairings in one device dispatch -> unsigned dispatch,
    plus the per-vote Ed25519 comparison and the host-pairing replay
    — tiny-I/full-V shape, CPU, same crash-safe contract.  The record
    carries `bls_agg_speedup` + `bls_pairing_device_speedup` + the
    lane counters + the ISSUE 18 `bls_pallas_speedup` /
    `bls_pallas_compile_ms` kernel A/B via _EXTRA_RECORD.  Default shape I=1, V=128: the
    aggregation win is per-CLASS (2302.00418's trade is asymptotic in
    committee size), and a 64-validator class sits at the measured
    CPU crossover — one fused 128-vote Ed25519 dispatch costs about
    what 2 x (MSM + device pairing + fold) does on the 2-CPU box
    (~0.99x) — so the gate measures at 128 validators, a realistic
    committee size where the lane's win is structural (~1.7x), not a
    box-load artifact.  The >= 64-class acceptance floor is
    unchanged."""
    os.environ.setdefault("AGNES_SERVE_BLS_SMOKE_I", "1")
    os.environ.setdefault("AGNES_SERVE_BLS_SMOKE_V", "128")
    _smoke_main("bench_pipeline_serve_bls",
                "pipeline_serve_bls_votes_per_sec",
                "pipeline_serve_bls_votes_per_sec", "votes/sec/chip",
                "AGNES_SERVE_BLS_SMOKE", bench_pipeline_serve_bls,
                "bls smoke: aggregate-precommit lane vs per-vote "
                "Ed25519")


def main_serve_native_smoke() -> None:
    """The ci.sh native-admission gate's entry (ISSUE 14): ONLY the
    native-admission serve probe — threaded host over the C++
    front-end, Python-admission replay for the busy-frac before/after,
    host-only submit/drain A/B for the speedup — tiny shape, CPU, same
    crash-safe contract.  The record carries
    `native_admission_speedup`, both `serve_submit_busy_frac_*`
    gauges and `native_new_compiles` via _EXTRA_RECORD."""
    _smoke_main("bench_pipeline_serve_native",
                "pipeline_serve_native_votes_per_sec",
                "pipeline_serve_native_votes_per_sec",
                "votes/sec/chip",
                "AGNES_SERVE_NATIVE_SMOKE", bench_pipeline_serve_native,
                "native admission smoke: C++ ingest front-end vs "
                "Python admission")


def main_serve_multihost_smoke() -> None:
    """The ci.sh multi-host gate's entry (ISSUE 15): ONLY the pod
    serve probe — 2 spawned jax.distributed worker processes under
    the spawner deadline — with the same crash-safe contract as the
    other serve gates.  The record carries `multihost_hosts`/
    `multihost_devices_per_host`, the summed per-host retrace/reject
    counters and every worker's heartbeat path via _EXTRA_RECORD."""
    _smoke_main("bench_pipeline_serve_multihost",
                "pipeline_serve_multihost_votes_per_sec",
                "pipeline_serve_multihost_votes_per_sec", "votes/sec",
                "AGNES_SERVE_MULTIHOST_SMOKE",
                bench_pipeline_serve_multihost,
                "multihost serve smoke: 2-process pod over "
                "jax.distributed")


def main_serve_elastic_smoke() -> None:
    """The ci.sh elastic gate's entry (ISSUE 17): ONLY the elastic
    pod serve probe — 2 spawned jax.distributed worker processes
    through ElasticShard's negotiated ticks, heterogeneous traffic,
    one leave + rejoin cycle — same crash-safe contract as the
    multihost gate.  The record carries the membership evidence
    (`elastic_boundaries`/`elastic_readmissions`/`elastic_epoch`...),
    the summed retrace/re-route counters and every worker's heartbeat
    path via _EXTRA_RECORD."""
    _smoke_main("bench_pipeline_serve_elastic",
                "pipeline_serve_elastic_votes_per_sec",
                "pipeline_serve_elastic_votes_per_sec", "votes/sec",
                "AGNES_SERVE_ELASTIC_SMOKE",
                bench_pipeline_serve_elastic,
                "elastic pod smoke: negotiated ticks + membership "
                "epoch cycle over jax.distributed")


def main_serve_mesh_smoke() -> None:
    """The ci.sh mesh-serve gate's entry (ISSUE 3): ONLY the mesh
    serve probe — ThreadedVoteService event loop + dense sharded
    dispatch — on a faked 2-device CPU mesh
    (--xla_force_host_platform_device_count), under the same contract
    as main_serve_smoke."""
    _smoke_main("bench_pipeline_serve_mesh",
                "pipeline_serve_mesh_votes_per_sec",
                "pipeline_serve_mesh_votes_per_sec", "votes/sec",
                "AGNES_SERVE_MESH_SMOKE", bench_pipeline_serve_mesh,
                "mesh serve smoke: threaded host + dense sharded "
                "dispatch")


def main() -> None:
    import traceback

    def guarded(fn):
        global _STAGE
        name = fn.__name__
        _STAGE = name          # the sentinel names the in-flight stage
        if _LEASE is not None:
            _LEASE.refresh()   # rival probes keep deferring to us
        print(f"[bench] {name} ...", file=sys.stderr, flush=True)
        t0 = time.perf_counter()
        try:
            out = round(fn())
            _RESULTS[name] = out   # rides along in a sentinel verdict
        except Exception:
            traceback.print_exc(file=sys.stderr)
            out = -1
        finally:
            # the finished stage's heartbeat source goes with it: a
            # dead probe's service (and its device buffers) must not
            # be retained — or keep reporting stale counters — through
            # the remaining stages
            _set_probe_source(None)
        print(f"[bench] {name} -> {out} ({time.perf_counter()-t0:.0f}s)",
              file=sys.stderr, flush=True)
        return out

    pipeline = guarded(bench_pipeline)
    pipeline_native = guarded(bench_pipeline_native)
    pipeline_overlapped = guarded(bench_pipeline_overlapped)
    pipeline_fused = guarded(bench_pipeline_fused)
    pipeline_serve = guarded(bench_pipeline_serve)
    # multichip serve: real number on >= 2-device backends, -1 (via
    # the stage guard's exception containment) on a single chip
    pipeline_serve_mesh = guarded(bench_pipeline_serve_mesh)
    # multi-host pod serve: 2 spawned jax.distributed CPU processes
    # (protocol-overhead probe — bench_pipeline_serve_multihost doc)
    pipeline_serve_multihost = guarded(bench_pipeline_serve_multihost)
    # elastic pod serve: negotiated ticks + membership epoch cycle
    pipeline_serve_elastic = guarded(bench_pipeline_serve_elastic)
    # duplicated-traffic serve: dedup cache + split-rung dispatch
    pipeline_serve_dedup = guarded(bench_pipeline_serve_dedup)
    # native admission front-end: C++ submit/drain + Python replay
    pipeline_serve_native = guarded(bench_pipeline_serve_native)
    # BLS aggregate lane: one pairing per vote class
    pipeline_serve_bls = guarded(bench_pipeline_serve_bls)
    tally = guarded(bench_tally)
    verifies = guarded(bench_verify)
    msm = guarded(bench_verify_msm)
    decisions = guarded(bench_decisions)
    bridge = guarded(bench_bridge)
    flood = guarded(bench_value_flood)
    # headline = the ONE fixed flagship path (numpy bridge); the native
    # feeder is reported alongside, never max()ed in (a max of two
    # noisy samples is upward-biased and switches meaning run-to-run)
    global _EMITTED
    # the final record is imminent: cancel the self-armed deadline
    # alarm and its watchdog-thread twin; a TERM in this window still
    # gets a sentinel (carrying every stage result), since _EMITTED
    # flips only AFTER the real verdict is fully printed
    signal.alarm(0)
    _cancel_deadline_watchdog()
    print(json.dumps({
        "metric": "pipeline_votes_per_sec",
        "value": pipeline,
        "unit": "votes/sec/chip",
        "vs_baseline": round(pipeline / NORTH_STAR, 3) if pipeline > 0
        else -1,
        "pipeline_native_votes_per_sec": pipeline_native,
        "pipeline_overlapped_votes_per_sec": pipeline_overlapped,
        "pipeline_fused_votes_per_sec": pipeline_fused,
        "pipeline_serve_votes_per_sec": pipeline_serve,
        "pipeline_serve_mesh_votes_per_sec": pipeline_serve_mesh,
        "pipeline_serve_multihost_votes_per_sec":
            pipeline_serve_multihost,
        "pipeline_serve_elastic_votes_per_sec":
            pipeline_serve_elastic,
        "pipeline_serve_dedup_votes_per_sec": pipeline_serve_dedup,
        "pipeline_serve_native_votes_per_sec": pipeline_serve_native,
        "pipeline_serve_bls_votes_per_sec": pipeline_serve_bls,
        **_EXTRA_RECORD,
        "fused_tally_step_votes_per_sec": tally,
        "ed25519_verifies_per_sec": verifies,
        "ed25519_msm_verifies_per_sec": msm,
        "decisions_per_sec": decisions,
        "bridge_votes_per_sec": bridge,
        "value_flood_votes_per_sec": flood,
        **_ANALYSIS,
        **_compile_record(),
        **_heartbeat_record(),
    }), flush=True)
    _EMITTED = True        # real verdict delivered; sentinel stands down


if __name__ == "__main__":
    try:
        (main_serve_elastic_smoke() if _SERVE_ELASTIC_SMOKE
         else main_serve_multihost_smoke() if _SERVE_MULTIHOST_SMOKE
         else main_serve_mesh_smoke() if _SERVE_MESH_SMOKE
         else main_serve_dedup_smoke() if _SERVE_DEDUP_SMOKE
         else main_serve_bls_smoke() if _SERVE_BLS_SMOKE
         else main_serve_native_smoke() if _SERVE_NATIVE_SMOKE
         else main_serve_smoke() if _SERVE_SMOKE else main())
    except BaseException as e:  # noqa: BLE001 — the contract: a
        # parseable record is the LAST stdout line no matter how this
        # process ends; stage exceptions are already contained by
        # guarded(), so reaching here means harness plumbing died
        import traceback

        traceback.print_exc(file=sys.stderr)
        _emit_sentinel(
            f"bench harness crashed outside any stage guard during "
            f"stage '{_STAGE}': {type(e).__name__}: {e}")
        raise SystemExit(0 if not isinstance(e, SystemExit)
                         else (e.code or 0))
