"""Native admission front-end (ISSUE 14) — the C++ twin of
serve/queue.AdmissionQueue, differential-tested leaf-for-leaf:

* queue-level conformance: identical AdmitResults, counters, drained
  WireColumns (all columns + digests), wait-histogram records, depth /
  oldest_ts / canonical queue content, under both overload policies,
  hostile records (out-of-range instances, truncated tails, negative
  rounds/values, nil flags) and a dedup cache on both sides;
* the native SHA-256 schedule against hashlib;
* the BLS class-bucket header screen against the Python fold's pass-1
  taxonomy (including fold(native_screen=True) == fold(False));
* serve-level conformance: the admission model checker's corpus and
  randomized submit/pump/settle schedules through native-ON vs
  native-OFF VoteService with registry-stubbed dispatch — identical
  dispatch streams, reject taxonomy, cache hit/miss counters;
* the threaded host over a native queue: admission-lock ELISION
  (runtime instrumented locks prove the submit path never takes it),
  N-producer conservation, drain report parity;
* the LOCK005 / LINT004 static rules: bite on synthetic fixtures,
  clean on the repo.

Zero XLA compiles (dispatch stubbed; conftest._CHEAP).  ci.sh [1/3]
re-runs this file under the ASan/UBSan build of admission.cpp.
"""

import hashlib
import threading

import numpy as np
import pytest

from agnes_tpu.bridge.native_ingest import REC_SIZE, pack_wire_votes
from agnes_tpu.serve.cache import VerifiedCache
from agnes_tpu.serve.native_admission import (
    NativeAdmissionQueue,
    bls_screen,
)
from agnes_tpu.serve.queue import AdmissionQueue

I = 4


def make_clock(step: float = 1.0):
    v = {"t": 0.0}

    def clock():
        v["t"] += step
        return v["t"]

    return clock


def rand_wire(rng, n, hostile=False):
    """Packed records; `hostile` mixes out-of-range instances,
    negative rounds, and a truncated tail."""
    inst = rng.integers(0, I + (3 if hostile else 0), n)
    val = rng.integers(0, 8, n)
    h = rng.integers(0, 3, n)
    r = rng.integers(-2 if hostile else 0, 4, n)
    t = rng.integers(0, 2, n)
    v = rng.integers(-1, 9, n)
    sig = rng.integers(0, 256, (n, 64)).astype(np.uint8)
    w = pack_wire_votes(inst, val, h, r, t, v, sig)
    if hostile and n > 2:
        w = w + bytes(rng.integers(0, 256, int(rng.integers(1, 95))))
    return w


class _Hist:
    def __init__(self):
        self.recs = []

    def record(self, v, n=1):
        self.recs.append((round(float(v), 9), int(n)))


def _assert_batches_equal(ba, bb):
    if ba is None or bb is None:
        assert ba is None and bb is None
        return
    for i in range(9):          # 8 columns + digest
        fa, fb = ba[i], bb[i]
        if fa is None or fb is None:
            assert fa is None and fb is None, i
        else:
            assert np.array_equal(np.asarray(fa), np.asarray(fb)), i
    assert ba.t_first == bb.t_first


def _pair(policy="reject_newest", capacity=20, instance_cap=7,
          cache=False):
    cA = VerifiedCache() if cache else None
    cB = VerifiedCache() if cache else None
    qa = AdmissionQueue(I, capacity, instance_cap=instance_cap,
                        policy=policy, cache=cA, clock=make_clock())
    qb = NativeAdmissionQueue(I, capacity, instance_cap=instance_cap,
                              policy=policy, cache=cB,
                              clock=make_clock())
    return qa, qb


# ---------------------------------------------------------------------------
# native SHA-256
# ---------------------------------------------------------------------------


def test_native_sha256_matches_hashlib():
    """The digest column IS the dedup-cache key: the C schedule must
    agree with hashlib byte-for-byte (covered here via the drain
    column over random records — every length-96 one-shot)."""
    rng = np.random.default_rng(7)
    wire = rand_wire(rng, 16)
    cache = VerifiedCache()
    q = NativeAdmissionQueue(I, 64, cache=cache)
    q.submit(wire)
    b = q.drain()
    mv = memoryview(wire)
    k = 0
    for j in range(16):
        rec = bytes(mv[j * REC_SIZE:(j + 1) * REC_SIZE])
        inst = int(np.frombuffer(rec[:4], np.uint32)[0])
        if inst >= I:
            continue            # malformed-screened, never hashed
        want = hashlib.sha256(rec).digest()
        assert bytes(b.digest[k]) == want, j
        k += 1
    assert k == len(b)


# ---------------------------------------------------------------------------
# queue-level conformance
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("policy", ["reject_newest", "drop_oldest"])
@pytest.mark.parametrize("cache", [False, True])
def test_submit_drain_differential(policy, cache):
    """Randomized hostile submit/drain schedules: results, counters,
    columns, digests, wait-hist records and canonical queue content
    identical between the Python queue and the native front-end."""
    rng = np.random.default_rng(3)
    qa, qb = _pair(policy=policy, cache=cache)
    qa.wait_hist, qb.wait_hist = _Hist(), _Hist()
    if cache:
        # seed BOTH caches with digests of a known record set so the
        # pre-verified path exercises on re-delivery
        seedw = rand_wire(rng, 6)
        mvs = memoryview(seedw)
        digs = np.stack([np.frombuffer(
            hashlib.sha256(mvs[k * 96:(k + 1) * 96]).digest(),
            np.uint8) for k in range(6)])
        for c in (qa.cache, qb.cache):
            c.insert(digs, np.zeros(6, np.int64), np.zeros(6, np.int64))
    else:
        seedw = None
    for k in range(20):
        if seedw is not None and k % 5 == 4:
            w = seedw                     # cache-hit re-delivery
        else:
            w = rand_wire(rng, int(rng.integers(1, 14)),
                          hostile=(k % 2 == 0))
        ra, rb = qa.submit(w), qb.submit(w)
        assert ra == rb, (k, ra, rb)
        assert qa.depth == qb.depth
        assert qa.oldest_ts == qb.oldest_ts
        for i in range(I):
            assert qa.instance_depth(i) == qb.instance_depth(i), i
        if k % 4 == 3:
            take = int(rng.integers(1, 9))
            _assert_batches_equal(qa.drain(take), qb.drain(take))
        if k == 10:
            # zero/negative caps pop nothing on BOTH implementations
            # (review regression: the Python queue raised from min()
            # over no chunks, the native queue returned None)
            assert qa.drain(0) is None and qb.drain(0) is None
            assert qa.drain(-3) is None and qb.drain(-3) is None
            assert qa.counters == qb.counters and qa.depth == qb.depth
    assert qa.mc_canonical()[0] == qb.mc_canonical()[0]
    while qa.depth:
        _assert_batches_equal(qa.drain(6), qb.drain(6))
    assert qb.drain() is None
    assert qa.counters == qb.counters
    assert qa.wait_hist.recs == qb.wait_hist.recs
    if cache:
        assert qa.cache.counters == qb.cache.counters
    # the taxonomy actually exercised: every cause moved
    c = qa.counters
    assert c["rejected_malformed"] > 0 and c["rejected_fairness"] > 0
    assert c["admitted"] > 0 and c["drained"] > 0


def test_drop_oldest_eviction_parity():
    """One submit larger than capacity: newest-kept trimming + oldest
    eviction math must match record-for-record."""
    rng = np.random.default_rng(11)
    qa, qb = _pair(policy="drop_oldest", capacity=6, instance_cap=100)
    w_small = rand_wire(rng, 3)
    w_big = rand_wire(rng, 10)
    for q in (qa, qb):
        q.submit(w_small)
        q.submit(w_big)
    assert qa.counters == qb.counters
    assert qa.counters["evicted"] > 0
    _assert_batches_equal(qa.drain(), qb.drain())


def test_threaded_drain_clamp_stress_drop_oldest():
    """Producer + TWO racing drainers over a drop_oldest queue: the C
    side clamps each drain to the live queue size under its mutex
    AFTER the wrapper's unlocked depth read, so the wrapper must size
    its batch from the native RETURN value (review regression:
    trailing np.empty garbage rows reached VoteBatcher and the
    Python-side record count diverged from the native `drained`
    counter).  Every drained row must be an initialized record and the
    record totals must reconcile exactly."""
    rng = np.random.default_rng(29)
    q = NativeAdmissionQueue(I, 8, instance_cap=100,
                             policy="drop_oldest")
    wires = [rand_wire(rng, n) for n in (2, 3, 5, 8)]
    # overflow the 8-record capacity single-threaded FIRST (18 records
    # submitted) so drop_oldest provably bites even when the producer
    # thread is starved by a loaded box — the eviction assertion below
    # must not depend on OS scheduling winning a race
    for w in wires:
        q.submit(w)
    stop = threading.Event()
    errs = []
    drained = [0, 0]

    def producer():
        k = 0
        while not stop.is_set():
            q.submit(wires[k % len(wires)])
            k += 1

    def consumer(slot):
        try:
            for _ in range(1500):
                b = q.drain(6)
                if b is None:
                    continue
                # a clamped drain returns a SHORT batch, never a
                # garbage-padded one: every row initialized
                assert 1 <= len(b) <= 6, len(b)
                inst = np.asarray(b.instance)
                assert inst.min() >= 0 and inst.max() < I, inst
                assert np.isfinite(b.t_first) and b.t_first > 0.0
                drained[slot] += len(b)
        except Exception as e:          # pragma: no cover - fail path
            errs.append(e)

    threads = [threading.Thread(target=producer)] + \
        [threading.Thread(target=consumer, args=(s,)) for s in (0, 1)]
    for t in threads[1:]:
        t.start()
    threads[0].start()
    for t in threads[1:]:
        t.join()
    stop.set()
    threads[0].join()
    assert not errs, errs
    total = sum(drained)
    while (b := q.drain(16)) is not None:   # quiesce single-threaded
        total += len(b)
    c = q.counters
    # Python-side record count == native drained counter, and the
    # full taxonomy reconciles (evicted records never count drained)
    assert c["drained"] == total, (c, total)
    assert c["admitted"] == c["drained"] + c["evicted"]
    assert c["evicted"] > 0                 # drop_oldest actually bit
    assert q.depth == 0


def test_wrapper_validation_parity():
    with pytest.raises(ValueError):
        NativeAdmissionQueue(I, 0)
    with pytest.raises(ValueError):
        NativeAdmissionQueue(I, 8, policy="nope")
    with pytest.raises(ValueError):
        NativeAdmissionQueue(I, 8, instance_cap=-1)
    q = NativeAdmissionQueue(I, 8)
    with pytest.raises(ValueError):
        q.submit_bls(b"")
    # the digest flag is frozen into the native handle: attaching a
    # cache to a digest-less queue must fail loudly, not hand lookup
    # uninitialized digest bytes (review regression)
    with pytest.raises(ValueError):
        q.cache = VerifiedCache()
    qc = NativeAdmissionQueue(I, 8, cache=VerifiedCache())
    qc.cache = None                  # detach: fine, C keeps hashing
    qc.cache = VerifiedCache()       # re-attach on a digest handle


def test_noncanonical_nil_flag_byte_drains_identically():
    """unpack_wire_votes treats ANY nonzero flag byte as non-nil
    (`rec[:, 21] != 0`, not bit0) — a hostile flag byte of 2 must
    drain with its real value on BOTH implementations (review
    regression: the native drain read only bit0)."""
    rng = np.random.default_rng(23)
    w = bytearray(rand_wire(rng, 3))
    w[1 * REC_SIZE + 21] = 2          # non-canonical non-nil flag
    w[2 * REC_SIZE + 21] = 0          # canonical nil
    w = bytes(w)
    qa, qb = _pair()
    assert qa.submit(w) == qb.submit(w)
    ba, bb = qa.drain(), qb.drain()
    _assert_batches_equal(ba, bb)
    assert ba.value[2] == -1          # flag 0 -> nil both ways


def test_degenerate_submits():
    """Empty + pure-tail submits count exactly like the Python queue
    (submitted/malformed discipline of the n_whole == 0 early path)."""
    qa, qb = _pair()
    for w in (b"", b"\x01\x02\x03", bytes(95)):
        assert qa.submit(w) == qb.submit(w)
    assert qa.counters == qb.counters
    assert qb.drain() is None


# ---------------------------------------------------------------------------
# BLS header screen
# ---------------------------------------------------------------------------


def _bls_fold_pair(V=6):
    """Two BlsClassTables over one registry-shaped stub (no jax): the
    screen needs only I/V/pop_ok/quarantined/powers."""
    from agnes_tpu.serve.bls_lane import BlsClassTable

    class _Reg:
        def __init__(self):
            self.V = V
            self.pop_ok = np.zeros(V, bool)
            self.pop_ok[:4] = True
            self.quarantined = np.zeros(V, bool)
            self.quarantined[2] = True
            self.powers = np.ones(V, np.int64)

    reg = _Reg()
    ta = BlsClassTable(reg, I, clock=make_clock())
    tb = BlsClassTable(reg, I, clock=make_clock())
    tb.native_screen = True
    return reg, ta, tb


def _bls_wire(rng, n, V, hostile=True):
    from agnes_tpu.serve.bls_lane import pack_bls_wire

    inst = rng.integers(0, I + (2 if hostile else 0), n)
    val = rng.integers(0, V + (2 if hostile else 0), n)
    h = rng.integers(0, 3, n)
    r = rng.integers(0, 2, n)
    t = rng.integers(0, 3 if hostile else 2, n)
    v = rng.integers(0, 4, n)
    shares = rng.integers(0, 256, (n, 192)).astype(np.uint8)
    w = pack_bls_wire(inst, val, h, r, t, v, shares)
    return w + (b"\xff" * 7 if hostile else b"")


def test_bls_screen_codes_match_python_taxonomy():
    rng = np.random.default_rng(5)
    reg, _ta, _tb = _bls_fold_pair()
    wire = _bls_wire(rng, 32, reg.V)
    from agnes_tpu.serve.bls_lane import unpack_bls_wire

    codes = bls_screen(wire, I, reg.V, reg.pop_ok, reg.quarantined)
    inst, val, _h, _r, typ, _v, _s = unpack_bls_wire(wire)
    assert len(codes) == len(inst)
    for j in range(len(inst)):
        i, v = int(inst[j]), int(val[j])
        if not (0 <= i < I and 0 <= typ[j] <= 1):
            want = 1
        elif not 0 <= v < reg.V:
            want = 2
        elif not reg.pop_ok[v]:
            want = 3
        elif reg.quarantined[v]:
            want = 4
        else:
            want = 0
        assert codes[j] == want, (j, codes[j], want)


def test_bls_fold_native_screen_differential():
    """fold(native_screen=True) == fold(False): identical per-cause
    counts, counters and folded class content (decode=False keeps the
    suite compile- and oracle-free; the screens are the native part)."""
    rng = np.random.default_rng(9)
    reg, ta, tb = _bls_fold_pair()
    for k in range(6):
        wire = _bls_wire(rng, int(rng.integers(2, 12)), reg.V,
                         hostile=(k % 2 == 0))
        ra = ta.fold(wire, decode=False)
        rb = tb.fold(wire, decode=False)
        assert ra == rb, (k, ra, rb)
    assert ta.counters == tb.counters
    assert ta.mc_canonical() == tb.mc_canonical()
    # every screen cause exercised at least once
    for key in ("bls_malformed", "bls_unknown_validator",
                "bls_pop_missing", "bls_quarantined",
                "bls_shares_folded"):
        assert ta.counters[key] > 0, (key, ta.counters)


def test_bls_fold_native_screen_with_real_decode():
    """decode=True ordering: the native screen rejects headers FIRST,
    then the shared on-curve decode classifies survivors — a garbage
    share from a PoP-verified signer counts malformed identically in
    both modes, and a real G2 point folds in both."""
    from agnes_tpu.crypto import bls_ref as ref
    from agnes_tpu.serve.bls_lane import pack_bls_wire

    reg, ta, tb = _bls_fold_pair()
    good = np.frombuffer(ref.g2_to_bytes(ref.G2), np.uint8)
    bad = np.arange(192, dtype=np.uint8)
    shares = np.stack([good, bad, good])
    # signer 0/1 PoP-verified; third row an unknown validator so every
    # class of outcome appears in one submit
    wire = pack_bls_wire([0, 0, 0], [0, 1, reg.V + 1], [1, 1, 1],
                         [0, 0, 0], [1, 1, 1], [7, 7, 7], shares)
    ra = ta.fold(wire, decode=True)
    rb = tb.fold(wire, decode=True)
    assert ra == rb == {"folded": 1, "malformed": 1,
                        "unknown_validator": 1, "pop_missing": 0,
                        "duplicate": 0, "overflow": 0,
                        "quarantined": 0}, (ra, rb)
    assert ta.mc_canonical() == tb.mc_canonical()


# ---------------------------------------------------------------------------
# serve-level conformance: corpus + randomized schedules, ON vs OFF
# ---------------------------------------------------------------------------


def _serve_pair(cfg):
    """native-ON and native-OFF services over the model checker's
    replay harness (tests/test_admission_mc.py)."""
    from tests.test_admission_mc import _real_service

    return (_real_service(cfg, native_admission=False),
            _real_service(cfg, native_admission=True))


def _drive(svc, window, sys_model, actions):
    from agnes_tpu.analysis import admission_mc as am

    for a in actions:
        act = am.AdmissionSystem.action_from_json(a) \
            if a and a[0] in am._ACT_CODES else tuple(a)
        if act[0] == "s":
            svc.submit(sys_model._wire[act[1]])
        elif act[0] == "b":
            svc._pump_batch(svc._close_batch())
            svc.pipeline.dispatch_staged()
        elif act[0] == "v":
            svc.poll_decisions()
        elif act[0] == "w":
            window["base"][:] = window["base"] + 1


def _corpus_entries():
    import os

    from agnes_tpu.analysis import modelcheck as mc

    return mc.load_corpus(os.path.join(os.path.dirname(__file__),
                                       "corpus", "admission"))


@pytest.mark.parametrize("entry", _corpus_entries(),
                         ids=lambda e: e["name"])
def test_corpus_replays_identical_native_on_vs_off(entry):
    """The admission conformance differential (the checker's corpus
    already SPECIFIES admission behavior — PR 7): native-ON serve ==
    native-OFF serve, dispatch streams bit-identical, reject taxonomy
    / cache counters / queue content leaf-for-leaf."""
    from agnes_tpu.analysis import admission_mc as am

    cfg = am.AdmissionMCConfig.from_json(entry["config"])
    sys_model = am.AdmissionSystem(cfg)
    (svc_off, win_off, disp_off), (svc_on, win_on, disp_on) = \
        _serve_pair(cfg)
    _drive(svc_off, win_off, sys_model, entry["actions"])
    _drive(svc_on, win_on, sys_model, entry["actions"])
    assert disp_on == disp_off, entry["name"]
    assert svc_on.queue.counters == svc_off.queue.counters
    assert svc_on.queue.mc_canonical()[0] == \
        svc_off.queue.mc_canonical()[0]
    if svc_on.cache is not None:
        assert svc_on.cache.counters == svc_off.cache.counters
    assert svc_on.pipeline.dispatched_votes == \
        svc_off.pipeline.dispatched_votes
    assert svc_on.pipeline.preverified_votes == \
        svc_off.pipeline.preverified_votes


def test_randomized_schedules_identical_native_on_vs_off():
    """Beyond the corpus: seeded random submit/pump/settle/window
    schedules (with hostile submits the model never generates mixed
    in) drive both services identically."""
    from agnes_tpu.analysis import admission_mc as am

    cfg = am.ADMISSION_SMOKE[0]
    sys_model = am.AdmissionSystem(cfg)
    rng = np.random.default_rng(17)
    hostile = rand_wire(rng, 5, hostile=True)
    (svc_off, win_off, disp_off), (svc_on, win_on, disp_on) = \
        _serve_pair(cfg)
    actions = []
    for _ in range(60):
        kind = rng.integers(0, 10)
        if kind < 5:
            actions.append(("s", int(rng.integers(
                0, len(sys_model._wire)))))
        elif kind < 8:
            actions.append(("b",))
        elif kind < 9:
            actions.append(("v",))
        else:
            actions.append(("w",))
    for svc, win in ((svc_off, win_off), (svc_on, win_on)):
        for k, a in enumerate(actions):
            if a[0] == "s" and k % 7 == 3:
                svc.submit(hostile)       # hostile bytes ride along
            _drive(svc, win, sys_model, [a])
    assert disp_on == disp_off
    assert svc_on.queue.counters == svc_off.queue.counters
    assert svc_on.queue.counters["rejected_malformed"] > 0
    rep_on, rep_off = svc_on.drain(), svc_off.drain()
    assert rep_on["queue"] == rep_off["queue"]
    assert rep_on["dispatched_votes"] == rep_off["dispatched_votes"]
    assert rep_on["native_admission"] is not None
    assert rep_off["native_admission"] is None
    assert rep_on["native_admission"]["depth"] == 0


# ---------------------------------------------------------------------------
# threaded host: lock elision + conservation
# ---------------------------------------------------------------------------


def test_threaded_native_elides_admission_lock_and_conserves():
    """The threaded host over a native service: N producer threads,
    loss-free conservation, and the instrumented admission lock is
    NEVER acquired by the submit path (the GIL-release contract) —
    only drain's quiescent section touches it."""
    from agnes_tpu.analysis import admission_mc as am
    from agnes_tpu.analysis.lockcheck import instrument
    from agnes_tpu.serve.threaded import ThreadedVoteService
    from tests.test_admission_mc import _real_service

    cfg = am.ADMISSION_SMOKE[0]
    sys_model = am.AdmissionSystem(cfg)
    svc, _window, _disp = _real_service(cfg, native_admission=True)
    tsvc = ThreadedVoteService(svc, inbox_capacity=4096,
                               idle_wait_s=1e-4)
    state = instrument(tsvc)

    class _Counting:
        """Count ADMISSION acquisitions only (the shared recorder
        counts both instrumented locks)."""

        def __init__(self, inner):
            self.inner, self.n = inner, 0

        def __enter__(self):
            self.n += 1
            return self.inner.__enter__()

        def __exit__(self, *exc):
            return self.inner.__exit__(*exc)

    adm = tsvc._admission = _Counting(tsvc._admission)
    tsvc.start()
    wires = list(sys_model._wire)
    n_threads, per_thread = 4, 12

    def producer(seed):
        for k in range(per_thread):
            tsvc.submit(wires[(seed + k) % len(wires)])

    threads = [threading.Thread(target=producer, args=(s,))
               for s in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    adm_before_drain = adm.n
    rep = tsvc.drain()
    assert not state.violations, state.violations
    assert rep["thread_failure"] is None
    assert rep["inbox"]["dropped"] == 0
    # every blob enqueued was admitted or rejected through the native
    # queue — nothing lost between the inbox and the C++ front-end
    q = rep["queue"]
    n_records = sum(len(w) // REC_SIZE for w in wires)
    assert q["submitted"] >= n_threads * per_thread  # >=: per-wire recs
    assert q["admitted"] + q["rejected_overflow"] \
        + q["rejected_fairness"] + q["rejected_malformed"] \
        == q["submitted"]
    assert n_records > 0
    # the submit path never took the admission lock: the only
    # admission-lock acquisition is drain's quiescent section —
    # with the Python queue this would be one per submitted blob
    assert adm_before_drain == 0, adm_before_drain
    assert adm.n == 1, adm.n
    # the busy-frac satellite: the shared-window sampler flushed the
    # final partial window at drain, so the gauges exist even for a
    # service shorter-lived than one gauge interval
    assert "serve_submit_busy_frac" in svc.metrics.gauges
    assert "serve_dispatch_busy_frac" in svc.metrics.gauges


# ---------------------------------------------------------------------------
# static rules: LOCK005 / LINT004
# ---------------------------------------------------------------------------


def test_lock005_flags_native_call_under_admission_lock():
    from agnes_tpu.analysis import lockcheck

    bad = (
        "class H:\n"
        "    def f(self):\n"
        "        with self._admission:\n"
        "            self.L.ag_adm_submit(0)\n"
        "    def g(self):\n"
        "        with self._admission:\n"
        "            self.L.ag_ing_push(0)  # lockcheck: allow (t)\n"
        "    def h(self):\n"
        "        self.L.ag_adm_drain(0)\n")
    codes = [f.code for f in lockcheck.check_source(bad)]
    assert codes == ["LOCK005"], codes


def test_lint004_flags_raw_capi_outside_wrappers(tmp_path):
    from agnes_tpu.analysis import lint

    pkg = tmp_path / "agnes_tpu" / "serve"
    pkg.mkdir(parents=True)
    (pkg / "rogue.py").write_text(
        "def f(L):\n"
        "    L.ag_adm_submit(None)\n"
        "    L.ag_ing_push(None)  # lint: allow (t)\n")
    (tmp_path / "agnes_tpu" / "core").mkdir()
    (tmp_path / "agnes_tpu" / "core" / "native.py").write_text(
        "def f(L):\n"
        "    L.ag_adm_submit(None)\n")   # audited module: sanctioned
    findings = lint.check_capi_wrappers(str(tmp_path))
    assert [f.code for f in findings] == ["LINT004"], findings
    assert "rogue.py:2" in findings[0].where


def test_lock_and_capi_rules_clean_on_repo():
    import os

    from agnes_tpu.analysis import lint, lockcheck

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    locks = lockcheck.check_paths(lockcheck.default_paths(repo))
    assert not locks, locks
    capi = lint.check_capi_wrappers(repo)
    assert not capi, capi
