"""Native admission front-end (ISSUE 14) — the C++ twin of
serve/queue.AdmissionQueue, differential-tested leaf-for-leaf:

* queue-level conformance: identical AdmitResults, counters, drained
  WireColumns (all columns + digests), wait-histogram records, depth /
  oldest_ts / canonical queue content, under both overload policies,
  hostile records (out-of-range instances, truncated tails, negative
  rounds/values, nil flags) and a dedup cache on both sides;
* the native SHA-256 schedule against hashlib;
* the BLS class-bucket header screen against the Python fold's pass-1
  taxonomy (including fold(native_screen=True) == fold(False));
* serve-level conformance: the admission model checker's corpus and
  randomized submit/pump/settle schedules through native-ON vs
  native-OFF VoteService with registry-stubbed dispatch — identical
  dispatch streams, reject taxonomy, cache hit/miss counters;
* the threaded host over a native queue: admission-lock ELISION
  (runtime instrumented locks prove the submit path never takes it),
  N-producer conservation, drain report parity;
* the LOCK005 / LINT004 static rules: bite on synthetic fixtures,
  clean on the repo;
* the ISSUE 20 perf layers: zero-copy densify FILL-path conformance
  (dispatch leaf-identical to native-OFF with `add_arrays` provably
  never entered on the adopt tick) and the sharded ingest group —
  shard grid {1, 2, 4} byte-identical to the single queue, N-producer
  conservation summed across shards, the `oldest_ts` guarded-min NaN
  fix, construction validation, and the ag_adms_* static-rule teeth.

Zero XLA compiles (dispatch stubbed; conftest._CHEAP).  ci.sh [1/3]
re-runs this file under the ASan/UBSan build of admission.cpp.
"""

import hashlib
import threading

import numpy as np
import pytest

from agnes_tpu.bridge.native_ingest import REC_SIZE, pack_wire_votes
from agnes_tpu.serve.cache import VerifiedCache
from agnes_tpu.serve.native_admission import (
    NativeAdmissionQueue,
    NativeAdmissionShards,
    bls_screen,
)
from agnes_tpu.serve.queue import AdmissionQueue

I = 4


def make_clock(step: float = 1.0):
    v = {"t": 0.0}

    def clock():
        v["t"] += step
        return v["t"]

    return clock


def rand_wire(rng, n, hostile=False):
    """Packed records; `hostile` mixes out-of-range instances,
    negative rounds, and a truncated tail."""
    inst = rng.integers(0, I + (3 if hostile else 0), n)
    val = rng.integers(0, 8, n)
    h = rng.integers(0, 3, n)
    r = rng.integers(-2 if hostile else 0, 4, n)
    t = rng.integers(0, 2, n)
    v = rng.integers(-1, 9, n)
    sig = rng.integers(0, 256, (n, 64)).astype(np.uint8)
    w = pack_wire_votes(inst, val, h, r, t, v, sig)
    if hostile and n > 2:
        w = w + bytes(rng.integers(0, 256, int(rng.integers(1, 95))))
    return w


class _Hist:
    def __init__(self):
        self.recs = []

    def record(self, v, n=1):
        self.recs.append((round(float(v), 9), int(n)))


def _assert_batches_equal(ba, bb):
    if ba is None or bb is None:
        assert ba is None and bb is None
        return
    for i in range(9):          # 8 columns + digest
        fa, fb = ba[i], bb[i]
        if fa is None or fb is None:
            assert fa is None and fb is None, i
        else:
            assert np.array_equal(np.asarray(fa), np.asarray(fb)), i
    assert ba.t_first == bb.t_first


def _pair(policy="reject_newest", capacity=20, instance_cap=7,
          cache=False):
    cA = VerifiedCache() if cache else None
    cB = VerifiedCache() if cache else None
    qa = AdmissionQueue(I, capacity, instance_cap=instance_cap,
                        policy=policy, cache=cA, clock=make_clock())
    qb = NativeAdmissionQueue(I, capacity, instance_cap=instance_cap,
                              policy=policy, cache=cB,
                              clock=make_clock())
    return qa, qb


# ---------------------------------------------------------------------------
# native SHA-256
# ---------------------------------------------------------------------------


def test_native_sha256_matches_hashlib():
    """The digest column IS the dedup-cache key: the C schedule must
    agree with hashlib byte-for-byte (covered here via the drain
    column over random records — every length-96 one-shot)."""
    rng = np.random.default_rng(7)
    wire = rand_wire(rng, 16)
    cache = VerifiedCache()
    q = NativeAdmissionQueue(I, 64, cache=cache)
    q.submit(wire)
    b = q.drain()
    mv = memoryview(wire)
    k = 0
    for j in range(16):
        rec = bytes(mv[j * REC_SIZE:(j + 1) * REC_SIZE])
        inst = int(np.frombuffer(rec[:4], np.uint32)[0])
        if inst >= I:
            continue            # malformed-screened, never hashed
        want = hashlib.sha256(rec).digest()
        assert bytes(b.digest[k]) == want, j
        k += 1
    assert k == len(b)


# ---------------------------------------------------------------------------
# queue-level conformance
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("policy", ["reject_newest", "drop_oldest"])
@pytest.mark.parametrize("cache", [False, True])
def test_submit_drain_differential(policy, cache):
    """Randomized hostile submit/drain schedules: results, counters,
    columns, digests, wait-hist records and canonical queue content
    identical between the Python queue and the native front-end."""
    rng = np.random.default_rng(3)
    qa, qb = _pair(policy=policy, cache=cache)
    qa.wait_hist, qb.wait_hist = _Hist(), _Hist()
    if cache:
        # seed BOTH caches with digests of a known record set so the
        # pre-verified path exercises on re-delivery
        seedw = rand_wire(rng, 6)
        mvs = memoryview(seedw)
        digs = np.stack([np.frombuffer(
            hashlib.sha256(mvs[k * 96:(k + 1) * 96]).digest(),
            np.uint8) for k in range(6)])
        for c in (qa.cache, qb.cache):
            c.insert(digs, np.zeros(6, np.int64), np.zeros(6, np.int64))
    else:
        seedw = None
    for k in range(20):
        if seedw is not None and k % 5 == 4:
            w = seedw                     # cache-hit re-delivery
        else:
            w = rand_wire(rng, int(rng.integers(1, 14)),
                          hostile=(k % 2 == 0))
        ra, rb = qa.submit(w), qb.submit(w)
        assert ra == rb, (k, ra, rb)
        assert qa.depth == qb.depth
        assert qa.oldest_ts == qb.oldest_ts
        for i in range(I):
            assert qa.instance_depth(i) == qb.instance_depth(i), i
        if k % 4 == 3:
            take = int(rng.integers(1, 9))
            _assert_batches_equal(qa.drain(take), qb.drain(take))
        if k == 10:
            # zero/negative caps pop nothing on BOTH implementations
            # (review regression: the Python queue raised from min()
            # over no chunks, the native queue returned None)
            assert qa.drain(0) is None and qb.drain(0) is None
            assert qa.drain(-3) is None and qb.drain(-3) is None
            assert qa.counters == qb.counters and qa.depth == qb.depth
    assert qa.mc_canonical()[0] == qb.mc_canonical()[0]
    while qa.depth:
        _assert_batches_equal(qa.drain(6), qb.drain(6))
    assert qb.drain() is None
    assert qa.counters == qb.counters
    assert qa.wait_hist.recs == qb.wait_hist.recs
    if cache:
        assert qa.cache.counters == qb.cache.counters
    # the taxonomy actually exercised: every cause moved
    c = qa.counters
    assert c["rejected_malformed"] > 0 and c["rejected_fairness"] > 0
    assert c["admitted"] > 0 and c["drained"] > 0


def test_drop_oldest_eviction_parity():
    """One submit larger than capacity: newest-kept trimming + oldest
    eviction math must match record-for-record."""
    rng = np.random.default_rng(11)
    qa, qb = _pair(policy="drop_oldest", capacity=6, instance_cap=100)
    w_small = rand_wire(rng, 3)
    w_big = rand_wire(rng, 10)
    for q in (qa, qb):
        q.submit(w_small)
        q.submit(w_big)
    assert qa.counters == qb.counters
    assert qa.counters["evicted"] > 0
    _assert_batches_equal(qa.drain(), qb.drain())


def test_threaded_drain_clamp_stress_drop_oldest():
    """Producer + TWO racing drainers over a drop_oldest queue: the C
    side clamps each drain to the live queue size under its mutex
    AFTER the wrapper's unlocked depth read, so the wrapper must size
    its batch from the native RETURN value (review regression:
    trailing np.empty garbage rows reached VoteBatcher and the
    Python-side record count diverged from the native `drained`
    counter).  Every drained row must be an initialized record and the
    record totals must reconcile exactly."""
    rng = np.random.default_rng(29)
    q = NativeAdmissionQueue(I, 8, instance_cap=100,
                             policy="drop_oldest")
    wires = [rand_wire(rng, n) for n in (2, 3, 5, 8)]
    # overflow the 8-record capacity single-threaded FIRST (18 records
    # submitted) so drop_oldest provably bites even when the producer
    # thread is starved by a loaded box — the eviction assertion below
    # must not depend on OS scheduling winning a race
    for w in wires:
        q.submit(w)
    stop = threading.Event()
    errs = []
    drained = [0, 0]

    def producer():
        k = 0
        while not stop.is_set():
            q.submit(wires[k % len(wires)])
            k += 1

    def consumer(slot):
        try:
            for _ in range(1500):
                b = q.drain(6)
                if b is None:
                    continue
                # a clamped drain returns a SHORT batch, never a
                # garbage-padded one: every row initialized
                assert 1 <= len(b) <= 6, len(b)
                inst = np.asarray(b.instance)
                assert inst.min() >= 0 and inst.max() < I, inst
                assert np.isfinite(b.t_first) and b.t_first > 0.0
                drained[slot] += len(b)
        except Exception as e:          # pragma: no cover - fail path
            errs.append(e)

    threads = [threading.Thread(target=producer)] + \
        [threading.Thread(target=consumer, args=(s,)) for s in (0, 1)]
    for t in threads[1:]:
        t.start()
    threads[0].start()
    for t in threads[1:]:
        t.join()
    stop.set()
    threads[0].join()
    assert not errs, errs
    total = sum(drained)
    while (b := q.drain(16)) is not None:   # quiesce single-threaded
        total += len(b)
    c = q.counters
    # Python-side record count == native drained counter, and the
    # full taxonomy reconciles (evicted records never count drained)
    assert c["drained"] == total, (c, total)
    assert c["admitted"] == c["drained"] + c["evicted"]
    assert c["evicted"] > 0                 # drop_oldest actually bit
    assert q.depth == 0


def test_wrapper_validation_parity():
    with pytest.raises(ValueError):
        NativeAdmissionQueue(I, 0)
    with pytest.raises(ValueError):
        NativeAdmissionQueue(I, 8, policy="nope")
    with pytest.raises(ValueError):
        NativeAdmissionQueue(I, 8, instance_cap=-1)
    q = NativeAdmissionQueue(I, 8)
    with pytest.raises(ValueError):
        q.submit_bls(b"")
    # the digest flag is frozen into the native handle: attaching a
    # cache to a digest-less queue must fail loudly, not hand lookup
    # uninitialized digest bytes (review regression)
    with pytest.raises(ValueError):
        q.cache = VerifiedCache()
    qc = NativeAdmissionQueue(I, 8, cache=VerifiedCache())
    qc.cache = None                  # detach: fine, C keeps hashing
    qc.cache = VerifiedCache()       # re-attach on a digest handle


def test_noncanonical_nil_flag_byte_drains_identically():
    """unpack_wire_votes treats ANY nonzero flag byte as non-nil
    (`rec[:, 21] != 0`, not bit0) — a hostile flag byte of 2 must
    drain with its real value on BOTH implementations (review
    regression: the native drain read only bit0)."""
    rng = np.random.default_rng(23)
    w = bytearray(rand_wire(rng, 3))
    w[1 * REC_SIZE + 21] = 2          # non-canonical non-nil flag
    w[2 * REC_SIZE + 21] = 0          # canonical nil
    w = bytes(w)
    qa, qb = _pair()
    assert qa.submit(w) == qb.submit(w)
    ba, bb = qa.drain(), qb.drain()
    _assert_batches_equal(ba, bb)
    assert ba.value[2] == -1          # flag 0 -> nil both ways


def test_degenerate_submits():
    """Empty + pure-tail submits count exactly like the Python queue
    (submitted/malformed discipline of the n_whole == 0 early path)."""
    qa, qb = _pair()
    for w in (b"", b"\x01\x02\x03", bytes(95)):
        assert qa.submit(w) == qb.submit(w)
    assert qa.counters == qb.counters
    assert qb.drain() is None


# ---------------------------------------------------------------------------
# BLS header screen
# ---------------------------------------------------------------------------


def _bls_fold_pair(V=6):
    """Two BlsClassTables over one registry-shaped stub (no jax): the
    screen needs only I/V/pop_ok/quarantined/powers."""
    from agnes_tpu.serve.bls_lane import BlsClassTable

    class _Reg:
        def __init__(self):
            self.V = V
            self.pop_ok = np.zeros(V, bool)
            self.pop_ok[:4] = True
            self.quarantined = np.zeros(V, bool)
            self.quarantined[2] = True
            self.powers = np.ones(V, np.int64)

    reg = _Reg()
    ta = BlsClassTable(reg, I, clock=make_clock())
    tb = BlsClassTable(reg, I, clock=make_clock())
    tb.native_screen = True
    return reg, ta, tb


def _bls_wire(rng, n, V, hostile=True):
    from agnes_tpu.serve.bls_lane import pack_bls_wire

    inst = rng.integers(0, I + (2 if hostile else 0), n)
    val = rng.integers(0, V + (2 if hostile else 0), n)
    h = rng.integers(0, 3, n)
    r = rng.integers(0, 2, n)
    t = rng.integers(0, 3 if hostile else 2, n)
    v = rng.integers(0, 4, n)
    shares = rng.integers(0, 256, (n, 192)).astype(np.uint8)
    w = pack_bls_wire(inst, val, h, r, t, v, shares)
    return w + (b"\xff" * 7 if hostile else b"")


def test_bls_screen_codes_match_python_taxonomy():
    rng = np.random.default_rng(5)
    reg, _ta, _tb = _bls_fold_pair()
    wire = _bls_wire(rng, 32, reg.V)
    from agnes_tpu.serve.bls_lane import unpack_bls_wire

    codes = bls_screen(wire, I, reg.V, reg.pop_ok, reg.quarantined)
    inst, val, _h, _r, typ, _v, _s = unpack_bls_wire(wire)
    assert len(codes) == len(inst)
    for j in range(len(inst)):
        i, v = int(inst[j]), int(val[j])
        if not (0 <= i < I and 0 <= typ[j] <= 1):
            want = 1
        elif not 0 <= v < reg.V:
            want = 2
        elif not reg.pop_ok[v]:
            want = 3
        elif reg.quarantined[v]:
            want = 4
        else:
            want = 0
        assert codes[j] == want, (j, codes[j], want)


def test_bls_fold_native_screen_differential():
    """fold(native_screen=True) == fold(False): identical per-cause
    counts, counters and folded class content (decode=False keeps the
    suite compile- and oracle-free; the screens are the native part)."""
    rng = np.random.default_rng(9)
    reg, ta, tb = _bls_fold_pair()
    for k in range(6):
        wire = _bls_wire(rng, int(rng.integers(2, 12)), reg.V,
                         hostile=(k % 2 == 0))
        ra = ta.fold(wire, decode=False)
        rb = tb.fold(wire, decode=False)
        assert ra == rb, (k, ra, rb)
    assert ta.counters == tb.counters
    assert ta.mc_canonical() == tb.mc_canonical()
    # every screen cause exercised at least once
    for key in ("bls_malformed", "bls_unknown_validator",
                "bls_pop_missing", "bls_quarantined",
                "bls_shares_folded"):
        assert ta.counters[key] > 0, (key, ta.counters)


def test_bls_fold_native_screen_with_real_decode():
    """decode=True ordering: the native screen rejects headers FIRST,
    then the shared on-curve decode classifies survivors — a garbage
    share from a PoP-verified signer counts malformed identically in
    both modes, and a real G2 point folds in both."""
    from agnes_tpu.crypto import bls_ref as ref
    from agnes_tpu.serve.bls_lane import pack_bls_wire

    reg, ta, tb = _bls_fold_pair()
    good = np.frombuffer(ref.g2_to_bytes(ref.G2), np.uint8)
    bad = np.arange(192, dtype=np.uint8)
    shares = np.stack([good, bad, good])
    # signer 0/1 PoP-verified; third row an unknown validator so every
    # class of outcome appears in one submit
    wire = pack_bls_wire([0, 0, 0], [0, 1, reg.V + 1], [1, 1, 1],
                         [0, 0, 0], [1, 1, 1], [7, 7, 7], shares)
    ra = ta.fold(wire, decode=True)
    rb = tb.fold(wire, decode=True)
    assert ra == rb == {"folded": 1, "malformed": 1,
                        "unknown_validator": 1, "pop_missing": 0,
                        "duplicate": 0, "overflow": 0,
                        "quarantined": 0}, (ra, rb)
    assert ta.mc_canonical() == tb.mc_canonical()


# ---------------------------------------------------------------------------
# serve-level conformance: corpus + randomized schedules, ON vs OFF
# ---------------------------------------------------------------------------


def _serve_pair(cfg):
    """native-ON and native-OFF services over the model checker's
    replay harness (tests/test_admission_mc.py)."""
    from tests.test_admission_mc import _real_service

    return (_real_service(cfg, native_admission=False),
            _real_service(cfg, native_admission=True))


def _drive(svc, window, sys_model, actions):
    from agnes_tpu.analysis import admission_mc as am

    for a in actions:
        act = am.AdmissionSystem.action_from_json(a) \
            if a and a[0] in am._ACT_CODES else tuple(a)
        if act[0] == "s":
            svc.submit(sys_model._wire[act[1]])
        elif act[0] == "b":
            svc._pump_batch(svc._close_batch())
            svc.pipeline.dispatch_staged()
        elif act[0] == "v":
            svc.poll_decisions()
        elif act[0] == "w":
            window["base"][:] = window["base"] + 1


def _corpus_entries():
    import os

    from agnes_tpu.analysis import modelcheck as mc

    return mc.load_corpus(os.path.join(os.path.dirname(__file__),
                                       "corpus", "admission"))


@pytest.mark.parametrize("entry", _corpus_entries(),
                         ids=lambda e: e["name"])
def test_corpus_replays_identical_native_on_vs_off(entry):
    """The admission conformance differential (the checker's corpus
    already SPECIFIES admission behavior — PR 7): native-ON serve ==
    native-OFF serve, dispatch streams bit-identical, reject taxonomy
    / cache counters / queue content leaf-for-leaf."""
    from agnes_tpu.analysis import admission_mc as am

    cfg = am.AdmissionMCConfig.from_json(entry["config"])
    sys_model = am.AdmissionSystem(cfg)
    (svc_off, win_off, disp_off), (svc_on, win_on, disp_on) = \
        _serve_pair(cfg)
    _drive(svc_off, win_off, sys_model, entry["actions"])
    _drive(svc_on, win_on, sys_model, entry["actions"])
    assert disp_on == disp_off, entry["name"]
    assert svc_on.queue.counters == svc_off.queue.counters
    assert svc_on.queue.mc_canonical()[0] == \
        svc_off.queue.mc_canonical()[0]
    if svc_on.cache is not None:
        assert svc_on.cache.counters == svc_off.cache.counters
    assert svc_on.pipeline.dispatched_votes == \
        svc_off.pipeline.dispatched_votes
    assert svc_on.pipeline.preverified_votes == \
        svc_off.pipeline.preverified_votes


def test_randomized_schedules_identical_native_on_vs_off():
    """Beyond the corpus: seeded random submit/pump/settle/window
    schedules (with hostile submits the model never generates mixed
    in) drive both services identically."""
    from agnes_tpu.analysis import admission_mc as am

    cfg = am.ADMISSION_SMOKE[0]
    sys_model = am.AdmissionSystem(cfg)
    rng = np.random.default_rng(17)
    hostile = rand_wire(rng, 5, hostile=True)
    (svc_off, win_off, disp_off), (svc_on, win_on, disp_on) = \
        _serve_pair(cfg)
    actions = []
    for _ in range(60):
        kind = rng.integers(0, 10)
        if kind < 5:
            actions.append(("s", int(rng.integers(
                0, len(sys_model._wire)))))
        elif kind < 8:
            actions.append(("b",))
        elif kind < 9:
            actions.append(("v",))
        else:
            actions.append(("w",))
    for svc, win in ((svc_off, win_off), (svc_on, win_on)):
        for k, a in enumerate(actions):
            if a[0] == "s" and k % 7 == 3:
                svc.submit(hostile)       # hostile bytes ride along
            _drive(svc, win, sys_model, [a])
    assert disp_on == disp_off
    assert svc_on.queue.counters == svc_off.queue.counters
    assert svc_on.queue.counters["rejected_malformed"] > 0
    rep_on, rep_off = svc_on.drain(), svc_off.drain()
    assert rep_on["queue"] == rep_off["queue"]
    assert rep_on["dispatched_votes"] == rep_off["dispatched_votes"]
    assert rep_on["native_admission"] is not None
    assert rep_off["native_admission"] is None
    assert rep_on["native_admission"]["depth"] == 0


# ---------------------------------------------------------------------------
# threaded host: lock elision + conservation
# ---------------------------------------------------------------------------


def test_threaded_native_elides_admission_lock_and_conserves():
    """The threaded host over a native service: N producer threads,
    loss-free conservation, and the instrumented admission lock is
    NEVER acquired by the submit path (the GIL-release contract) —
    only drain's quiescent section touches it."""
    from agnes_tpu.analysis import admission_mc as am
    from agnes_tpu.analysis.lockcheck import instrument
    from agnes_tpu.serve.threaded import ThreadedVoteService
    from tests.test_admission_mc import _real_service

    cfg = am.ADMISSION_SMOKE[0]
    sys_model = am.AdmissionSystem(cfg)
    svc, _window, _disp = _real_service(cfg, native_admission=True)
    tsvc = ThreadedVoteService(svc, inbox_capacity=4096,
                               idle_wait_s=1e-4)
    state = instrument(tsvc)

    class _Counting:
        """Count ADMISSION acquisitions only (the shared recorder
        counts both instrumented locks)."""

        def __init__(self, inner):
            self.inner, self.n = inner, 0

        def __enter__(self):
            self.n += 1
            return self.inner.__enter__()

        def __exit__(self, *exc):
            return self.inner.__exit__(*exc)

    adm = tsvc._admission = _Counting(tsvc._admission)
    tsvc.start()
    wires = list(sys_model._wire)
    n_threads, per_thread = 4, 12

    def producer(seed):
        for k in range(per_thread):
            tsvc.submit(wires[(seed + k) % len(wires)])

    threads = [threading.Thread(target=producer, args=(s,))
               for s in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    adm_before_drain = adm.n
    rep = tsvc.drain()
    assert not state.violations, state.violations
    assert rep["thread_failure"] is None
    assert rep["inbox"]["dropped"] == 0
    # every blob enqueued was admitted or rejected through the native
    # queue — nothing lost between the inbox and the C++ front-end
    q = rep["queue"]
    n_records = sum(len(w) // REC_SIZE for w in wires)
    assert q["submitted"] >= n_threads * per_thread  # >=: per-wire recs
    assert q["admitted"] + q["rejected_overflow"] \
        + q["rejected_fairness"] + q["rejected_malformed"] \
        == q["submitted"]
    assert n_records > 0
    # the submit path never took the admission lock: the only
    # admission-lock acquisition is drain's quiescent section —
    # with the Python queue this would be one per submitted blob
    assert adm_before_drain == 0, adm_before_drain
    assert adm.n == 1, adm.n
    # the busy-frac satellite: the shared-window sampler flushed the
    # final partial window at drain, so the gauges exist even for a
    # service shorter-lived than one gauge interval
    assert "serve_submit_busy_frac" in svc.metrics.gauges
    assert "serve_dispatch_busy_frac" in svc.metrics.gauges


# ---------------------------------------------------------------------------
# zero-copy densify: the FILL path, proven (ISSUE 20)
# ---------------------------------------------------------------------------


def _fill_pair(native_shards=1):
    """A native-OFF / native-ON serve pair over the smoke config, plus
    the model that mints its wire records.  The smoke templates put
    two same-value round-0 votes on instance 0, so one warm round
    interns the value into the SlotMap LUT and the NEXT round's drain
    is densify-eligible."""
    from agnes_tpu.analysis import admission_mc as am
    from tests.test_admission_mc import _real_service

    cfg = am.ADMISSION_SMOKE[0]
    sys_model = am.AdmissionSystem(cfg)
    return (sys_model,
            _real_service(cfg, native_admission=False),
            _real_service(cfg, native_admission=True,
                          native_shards=native_shards))


@pytest.mark.parametrize("native_shards", [1, 2])
def test_densify_fill_leaf_identical_and_skips_add_arrays(
        native_shards):
    """The acceptance property: a steady-state serve tick on the
    phases path performs NO per-record Python work between submit and
    dispatch — `VoteBatcher.add_arrays` is instrumented and provably
    never entered on the adopt tick — while the dispatch stream stays
    leaf-for-leaf identical to native-OFF.  Round 1 bails (the vote
    value is not in the SlotMap LUT yet — the Python fallback IS the
    interning path), round 2 fills."""
    from agnes_tpu.utils.metrics import SERVE_NATIVE_DENSIFY_WALL_S

    sys_model, (svc_off, win_off, disp_off), \
        (svc_on, win_on, disp_on) = _fill_pair(native_shards)
    warm = [("s", 0), ("s", 1), ("b",)]
    for svc, win in ((svc_off, win_off), (svc_on, win_on)):
        _drive(svc, win, sys_model, warm)
    assert svc_on.queue.phase_fill == 0
    assert svc_on.queue.phase_bail == 1
    assert svc_on.pipeline.native_phase_builds == 0
    # round 2: the value is interned now — instrument add_arrays
    # BEFORE driving, so any per-record Python work would be counted
    calls = {"n": 0}
    real_add = svc_on.pipeline.batcher.add_arrays

    def counting_add(*a, **k):
        calls["n"] += 1
        return real_add(*a, **k)

    svc_on.pipeline.batcher.add_arrays = counting_add
    for svc, win in ((svc_off, win_off), (svc_on, win_on)):
        _drive(svc, win, sys_model, warm)
    assert svc_on.queue.phase_fill == 1, (
        svc_on.queue.phase_fill, svc_on.queue.phase_bail)
    assert svc_on.pipeline.native_phase_builds == 1
    assert calls["n"] == 0, (
        "add_arrays entered on the native adopt path")
    # ... and nothing about the stream moved: dispatches, queue
    # taxonomy, and dispatched-vote counts are native-OFF's, exactly
    assert disp_on == disp_off
    assert len(disp_on) > 0
    assert svc_on.queue.counters == svc_off.queue.counters
    assert svc_on.pipeline.dispatched_votes == \
        svc_off.pipeline.dispatched_votes
    # observability satellite: the densify wall histogram saw the fill
    h = svc_on.metrics.hists[SERVE_NATIVE_DENSIFY_WALL_S]
    assert h.snapshot()["count"] >= 1
    rep = svc_on.drain()
    assert rep["native_phase_builds"] == 1
    assert rep["native_admission"]["phase_fill"] == 1


def test_densify_metrics_mirrored_at_settle():
    """The settle-path registry mirrors (ISSUE 20): adopted builds
    land on the serve_native_phase_builds counter; a sharded service
    also carries per-shard depth gauges keyed by shard index."""
    from agnes_tpu.utils.metrics import (
        SERVE_NATIVE_PHASE_BUILDS,
        SERVE_NATIVE_SHARD_DEPTH_PREFIX,
    )

    sys_model, _off, (svc_on, win_on, _disp) = _fill_pair(2)
    warm = [("s", 0), ("s", 1), ("b",)]
    _drive(svc_on, win_on, sys_model, warm + warm + [("v",)])
    assert svc_on.metrics.counters[SERVE_NATIVE_PHASE_BUILDS] == 1
    for s in range(2):
        assert (SERVE_NATIVE_SHARD_DEPTH_PREFIX + str(s)
                in svc_on.metrics.gauges)


# ---------------------------------------------------------------------------
# sharded native ingest: shard grid + conservation + oldest_ts
# ---------------------------------------------------------------------------


def _shard_pair(n_shards, policy="reject_newest", cache=False):
    """Single native queue vs N-shard group, identical dimensions
    (capacity 40 keeps every instance below the per-shard ceiling at
    any grid point, so admission decisions must agree exactly)."""
    cA = VerifiedCache() if cache else None
    cB = VerifiedCache() if cache else None
    qa = NativeAdmissionQueue(I, 40, instance_cap=7, policy=policy,
                              cache=cA, clock=make_clock())
    qb = NativeAdmissionShards(I, 40, instance_cap=7, policy=policy,
                               cache=cB, clock=make_clock(),
                               n_shards=n_shards)
    return qa, qb


@pytest.mark.parametrize("n_shards", [1, 2, 4])
@pytest.mark.parametrize("policy", ["reject_newest", "drop_oldest"])
def test_shard_grid_byte_identical_to_single_queue(n_shards, policy):
    """The shard-count grid {1, 2, 4}: per-submit AdmitResults,
    counters, canonical queue content, and every drained batch
    (columns + digests + t_first) byte-identical to the single
    native queue, under hostile traffic and a dedup cache — and the
    per-shard counter breakdown sums to the group aggregate."""
    qa, qb = _shard_pair(n_shards, policy=policy, cache=True)
    rng = np.random.default_rng(100 + n_shards)
    for k in range(40):
        w = rand_wire(rng, int(rng.integers(1, 6)),
                      hostile=(k % 5 == 4))
        ra, rb = qa.submit(w), qb.submit(w)
        assert ra == rb, (k, ra, rb)
        assert qa.depth == qb.depth
        assert qa.oldest_ts == qb.oldest_ts
        for i in range(I):
            assert qa.instance_depth(i) == qb.instance_depth(i)
        if k % 4 == 3:
            _assert_batches_equal(qa.drain(5), qb.drain(5))
    assert qa.mc_canonical() == qb.mc_canonical()
    _assert_batches_equal(qa.drain(), qb.drain())
    assert qa.counters == qb.counters
    assert qa.cache.counters == qb.cache.counters
    assert qb.depth == 0
    # the per-shard taxonomy is a partition of the aggregate
    agg = {k: 0 for k in qb.counters}
    for s in range(n_shards):
        for key, v in qb.shard_counters(s).items():
            agg[key] += v
    assert agg == qb.counters
    snap = qb.native_snapshot()
    assert snap["n_shards"] == n_shards
    assert len(snap["shards"]) == n_shards


def test_shards_construction_validation():
    """The fail-closed screens: shard count must divide both the
    instance range (the HostPlan equal-range contract) and the
    capacity (integer per-shard ceiling)."""
    with pytest.raises(ValueError, match="not divisible"):
        NativeAdmissionShards(I, 40, n_shards=3)
    with pytest.raises(ValueError, match="not divisible"):
        NativeAdmissionShards(I, 42, n_shards=4)
    with pytest.raises(ValueError, match="n_shards"):
        NativeAdmissionShards(I, 40, n_shards=0)
    # the frozen-digest contract carries over from the single queue
    q = NativeAdmissionShards(I, 40, n_shards=2)
    with pytest.raises(ValueError, match="cannot attach"):
        q.cache = VerifiedCache()
    qc = NativeAdmissionShards(I, 40, n_shards=2,
                               cache=VerifiedCache())
    qc.cache = None          # detach is fine
    qc.cache = VerifiedCache()   # re-attach on a digest handle too


def test_oldest_ts_none_until_stamped():
    """The ISSUE 20 oldest_ts fix: a record admitted by the lock-free
    submit but not yet clock-stamped must surface as None (guarded
    min over STAMPED records), never NaN — MicroBatcher's deadline
    close arithmetic would propagate NaN into every close decision.
    Driven at the raw C API (the wrapper stamps immediately, so the
    transient is only visible between the two calls)."""
    from agnes_tpu.serve import native_admission as na

    # instances 0 and 2: with n_shards=2 over I=4 (L=2) the chunk
    # spans BOTH shards, so the group min really is a cross-shard min
    w = pack_wire_votes(np.array([0, 2]), np.arange(2),
                        np.zeros(2, np.int64), np.zeros(2, np.int64),
                        np.zeros(2, np.int64), np.zeros(2, np.int64),
                        np.zeros((2, 64), np.uint8))
    L = na._lib()
    counts = np.zeros(5, np.int64)
    # single queue
    q = NativeAdmissionQueue(I, 40, clock=make_clock())
    seq = L.ag_adm_submit(q._h, w, len(w), counts.ctypes.data, None)
    assert int(counts[0]) == 2
    assert q.oldest_ts is None          # admitted, unstamped: no NaN
    L.ag_adm_set_chunk_ts(q._h, seq, 7.5)
    assert q.oldest_ts == 7.5
    # shard group (records of one chunk live on different shards)
    g = NativeAdmissionShards(I, 40, clock=make_clock(), n_shards=2)
    counts[:] = 0
    seq = L.ag_adms_submit(g._h, w, len(w), counts.ctypes.data, None)
    assert int(counts[0]) == 2
    assert g.oldest_ts is None
    L.ag_adms_set_chunk_ts(g._h, seq, 9.25)
    assert g.oldest_ts == 9.25
    assert g.shard_depth(0) == 1 and g.shard_depth(1) == 1


def test_serve_randomized_identical_sharded_vs_single():
    """The serve-level shard differential: randomized schedules
    through native_shards=2 match native_shards=1 (and hence, by the
    ISSUE 14 differentials, the Python path) dispatch-for-dispatch.
    Every ≤2 submits are followed by a pump, keeping resident depth
    below the per-shard ceiling — the regime where the shard group's
    admission decisions provably agree with the single queue's."""
    from agnes_tpu.analysis import admission_mc as am
    from tests.test_admission_mc import _real_service

    cfg = am.ADMISSION_SMOKE[0]
    sys_model = am.AdmissionSystem(cfg)
    rng = np.random.default_rng(23)
    actions = []
    for _ in range(30):
        for _ in range(int(rng.integers(1, 3))):
            actions.append(("s", int(rng.integers(
                0, len(sys_model._wire)))))
        actions.append(("b",))
        if rng.integers(0, 3) == 0:
            actions.append(("v",))
        if rng.integers(0, 6) == 0:
            actions.append(("w",))
    svc1, win1, disp1 = _real_service(cfg, native_admission=True)
    svc2, win2, disp2 = _real_service(cfg, native_admission=True,
                                      native_shards=2)
    _drive(svc1, win1, sys_model, actions)
    _drive(svc2, win2, sys_model, actions)
    assert disp2 == disp1
    assert svc2.queue.counters == svc1.queue.counters
    rep1, rep2 = svc1.drain(), svc2.drain()
    assert rep2["dispatched_votes"] == rep1["dispatched_votes"]
    assert rep2["native_phase_builds"] == rep1["native_phase_builds"]
    assert rep2["native_admission"]["n_shards"] == 2


def test_threaded_sharded_conservation_and_elision():
    """N producer threads through the threaded host over the SHARD
    group: loss-free conservation summed across shards (admitted ==
    drained + evicted + depth, per shard and in aggregate) and the
    admission-lock elision the single native queue earned — the shard
    group's `native = True` marker keeps the submit path lock-free."""
    from agnes_tpu.analysis import admission_mc as am
    from agnes_tpu.analysis.lockcheck import instrument
    from agnes_tpu.serve.threaded import ThreadedVoteService
    from tests.test_admission_mc import _real_service

    cfg = am.ADMISSION_SMOKE[0]
    sys_model = am.AdmissionSystem(cfg)
    svc, _window, _disp = _real_service(cfg, native_admission=True,
                                        native_shards=2)
    tsvc = ThreadedVoteService(svc, inbox_capacity=4096,
                               idle_wait_s=1e-4)
    state = instrument(tsvc)
    acquired = {"n": 0}

    class _Counting:
        def __init__(self, inner):
            self.inner = inner

        def __enter__(self):
            acquired["n"] += 1
            return self.inner.__enter__()

        def __exit__(self, *exc):
            return self.inner.__exit__(*exc)

    tsvc._admission = _Counting(tsvc._admission)
    tsvc.start()
    wires = list(sys_model._wire)
    n_threads, per_thread = 4, 12

    def producer(seed):
        for k in range(per_thread):
            tsvc.submit(wires[(seed + k) % len(wires)])

    threads = [threading.Thread(target=producer, args=(s,))
               for s in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    adm_before_drain = acquired["n"]
    rep = tsvc.drain()
    assert not state.violations, state.violations
    assert rep["thread_failure"] is None
    assert rep["inbox"]["dropped"] == 0
    assert adm_before_drain == 0, adm_before_drain
    na = rep["queue"]
    assert na["admitted"] == na["drained"] + na["evicted"]
    snap = rep["native_admission"]
    assert snap["n_shards"] == 2
    # conservation PER SHARD, and the shard partition sums to the
    # aggregate — records neither lost nor duplicated in the fan-in
    for c in snap["shards"]:
        assert c["admitted"] == c["drained"] + c["evicted"] \
            + c["depth"]
    for key in ("submitted", "admitted", "drained", "evicted"):
        assert sum(c[key] for c in snap["shards"]) == na[key]


# ---------------------------------------------------------------------------
# static rules: LOCK005 / LINT004
# ---------------------------------------------------------------------------


def test_lock005_flags_native_call_under_admission_lock():
    from agnes_tpu.analysis import lockcheck

    bad = (
        "class H:\n"
        "    def f(self):\n"
        "        with self._admission:\n"
        "            self.L.ag_adm_submit(0)\n"
        "    def g(self):\n"
        "        with self._admission:\n"
        "            self.L.ag_ing_push(0)  # lockcheck: allow (t)\n"
        "    def h(self):\n"
        "        self.L.ag_adm_drain(0)\n")
    codes = [f.code for f in lockcheck.check_source(bad)]
    assert codes == ["LOCK005"], codes


def test_lint004_flags_raw_capi_outside_wrappers(tmp_path):
    from agnes_tpu.analysis import lint

    pkg = tmp_path / "agnes_tpu" / "serve"
    pkg.mkdir(parents=True)
    (pkg / "rogue.py").write_text(
        "def f(L):\n"
        "    L.ag_adm_submit(None)\n"
        "    L.ag_ing_push(None)  # lint: allow (t)\n")
    (tmp_path / "agnes_tpu" / "core").mkdir()
    (tmp_path / "agnes_tpu" / "core" / "native.py").write_text(
        "def f(L):\n"
        "    L.ag_adm_submit(None)\n")   # audited module: sanctioned
    findings = lint.check_capi_wrappers(str(tmp_path))
    assert [f.code for f in findings] == ["LINT004"], findings
    assert "rogue.py:2" in findings[0].where


def test_lock005_and_lint004_cover_shard_group_calls(tmp_path):
    """The ag_adms_* shard-group C API is covered by the same teeth
    as ag_adm_*: a group call under the admission lock is LOCK005
    (the group synchronizes internally — holding the Python lock
    across it is the elision-defeating nesting), and a raw group call
    outside the audited wrappers is LINT004."""
    from agnes_tpu.analysis import lint, lockcheck

    bad = (
        "class H:\n"
        "    def f(self):\n"
        "        with self._admission:\n"
        "            self.L.ag_adms_submit(0)\n")
    codes = [f.code for f in lockcheck.check_source(bad)]
    assert codes == ["LOCK005"], codes
    pkg = tmp_path / "agnes_tpu" / "serve"
    pkg.mkdir(parents=True)
    (pkg / "rogue.py").write_text(
        "def f(L):\n"
        "    L.ag_adms_drain_phases(None)\n")
    findings = lint.check_capi_wrappers(str(tmp_path))
    assert [f.code for f in findings] == ["LINT004"], findings


def test_native_lock_order_registry_matches_source():
    """The NATIVE_LOCK_ORDER doc registry (lockcheck) doesn't drift
    from the C++ it documents: every named mutex member exists in the
    native admission sources, and both are leaf-ranked — the basis
    for LOCK005's demand that Python hold NOTHING across ag_adms_*
    calls."""
    import os

    from agnes_tpu.analysis import lockcheck

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    srcs = ""
    for rel in ("agnes_tpu/core/native/admission.hpp",
                "agnes_tpu/core/native/admission_shards.cpp"):
        with open(os.path.join(repo, rel)) as fh:
            srcs += fh.read()
    assert len(lockcheck.NATIVE_LOCK_ORDER) == 2
    for name, rank, note in lockcheck.NATIVE_LOCK_ORDER:
        member = name.split("::")[1]
        assert member in srcs, name
        assert rank == 2, (name, rank)      # leaf, like cache._mu
        assert note
    names = {n for n, _, _ in lockcheck.NATIVE_LOCK_ORDER}
    assert names == {"AdmQ::mu", "AdmShards::route_mu"}


def test_lock_and_capi_rules_clean_on_repo():
    import os

    from agnes_tpu.analysis import lint, lockcheck

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    locks = lockcheck.check_paths(lockcheck.default_paths(repo))
    assert not locks, locks
    capi = lint.check_capi_wrappers(repo)
    assert not capi, capi
