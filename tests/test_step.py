"""Fused consensus-step tests: batches of instances driven to decision
through the 7-stage device pipeline (BASELINE config 1 via the device
path — the minimum end-to-end slice of SURVEY.md §7)."""

import numpy as np
import jax.numpy as jnp

from agnes_tpu.core.state_machine import EventTag, MsgTag, Step, TimeoutStep
from agnes_tpu.device.encoding import DeviceState
from agnes_tpu.device.step import (
    ExtEvent,
    N_STAGES,
    VotePhase,
    consensus_step_jit,
)
from agnes_tpu.device.tally import TallyConfig, TallyState
from agnes_tpu.types import VoteType

I, V = 8, 4
CFG = TallyConfig(n_validators=V, n_rounds=4, n_slots=4)
POWERS = jnp.ones((V,), jnp.int32)
TOTAL = jnp.asarray(V, jnp.int32)
VAL = 2  # value slot this height's proposals use


def _empty_phase():
    return VotePhase(jnp.zeros(I, jnp.int32), jnp.zeros(I, jnp.int32),
                     jnp.full((I, V), -1, jnp.int32),
                     jnp.zeros((I, V), bool),
                     jnp.zeros(I, jnp.int32))


def _phase(round_, typ, votes):
    slots = np.full((I, V), -1, np.int32)
    mask = np.zeros((I, V), bool)
    for v, s in votes.items():
        slots[:, v] = s
        mask[:, v] = True
    return VotePhase(jnp.full(I, round_, jnp.int32),
                     jnp.full(I, int(typ), jnp.int32),
                     jnp.asarray(slots), jnp.asarray(mask),
                     jnp.zeros(I, jnp.int32))


def _step(state, tally, ext=None, phase=None, proposer=True):
    return consensus_step_jit(
        state, tally,
        ext if ext is not None else ExtEvent.none(I),
        phase if phase is not None else _empty_phase(),
        POWERS, TOTAL,
        jnp.full((I, CFG.n_rounds), proposer, bool),
        jnp.full(I, VAL, jnp.int32))


def _msgs_at(msgs, stage):
    return {f: np.asarray(getattr(msgs, f))[stage] for f in msgs._fields}


def test_proposer_decides_in_three_steps():
    """Happy path: this node proposes; peers echo votes; decision."""
    state = DeviceState.new((I,))
    tally = TallyState.new(I, CFG)

    # step 1: round entry -> proposal -> self-prevote
    state, tally, msgs = _step(state, tally)
    entry = _msgs_at(msgs, 5)
    assert (entry["tag"] == int(MsgTag.PROPOSAL)).all()
    assert (entry["value"] == VAL).all()
    selfp = _msgs_at(msgs, 6)
    assert (selfp["tag"] == int(MsgTag.VOTE)).all()
    assert (selfp["aux"] == int(VoteType.PREVOTE)).all()
    assert (np.asarray(state.step) == int(Step.PREVOTE)).all()

    # step 2: deliver everyone's prevotes (incl. our own, validator 0)
    state, tally, msgs = _step(state, tally,
                               phase=_phase(0, VoteType.PREVOTE,
                                            {0: VAL, 1: VAL, 2: VAL}))
    polka = _msgs_at(msgs, 1)
    assert (polka["tag"] == int(MsgTag.VOTE)).all()
    assert (polka["aux"] == int(VoteType.PRECOMMIT)).all()
    assert (polka["value"] == VAL).all()
    assert (np.asarray(state.step) == int(Step.PRECOMMIT)).all()
    assert (np.asarray(state.locked_round) == 0).all()

    # step 3: deliver precommits -> decision
    state, tally, msgs = _step(state, tally,
                               phase=_phase(0, VoteType.PRECOMMIT,
                                            {0: VAL, 1: VAL, 2: VAL}))
    dec = _msgs_at(msgs, 1)
    assert (dec["tag"] == int(MsgTag.DECISION)).all()
    assert (dec["value"] == VAL).all()
    assert (np.asarray(state.step) == int(Step.COMMIT)).all()


def test_non_proposer_times_out_to_nil_and_skips_round():
    """Liveness path: no proposal arrives; timeouts drive nil votes and a
    round skip into round 1 (spec lines 57/61/65)."""
    state = DeviceState.new((I,))
    tally = TallyState.new(I, CFG)

    # round entry as non-proposer -> schedule timeout propose
    state, tally, msgs = _step(state, tally, proposer=False)
    entry = _msgs_at(msgs, 5)
    assert (entry["tag"] == int(MsgTag.TIMEOUT)).all()
    assert (entry["aux"] == int(TimeoutStep.PROPOSE)).all()

    # timeout fires (harness timer wheel) -> prevote nil
    ext = ExtEvent(jnp.full(I, int(EventTag.TIMEOUT_PROPOSE), jnp.int32),
                   jnp.zeros(I, jnp.int32), jnp.zeros(I, jnp.int32),
                   jnp.full(I, -1, jnp.int32))
    state, tally, msgs = _step(state, tally, ext=ext, proposer=False)
    m = _msgs_at(msgs, 0)
    assert (m["tag"] == int(MsgTag.VOTE)).all()
    assert (m["value"] == -1).all()  # nil

    # everyone prevotes nil -> polka nil -> precommit nil
    state, tally, msgs = _step(
        state, tally, phase=_phase(0, VoteType.PREVOTE, {0: -1, 1: -1, 2: -1}),
        proposer=False)
    m = _msgs_at(msgs, 1)
    assert (m["tag"] == int(MsgTag.VOTE)).all()
    assert (m["aux"] == int(VoteType.PRECOMMIT)).all()
    assert (m["value"] == -1).all()

    # everyone precommits nil: no value event (vote_executor.rs:33), but
    # the PrecommitAny edge (stage 1) schedules timeout precommit; the
    # requery stages stay silent — the state hasn't moved since (spec
    # line 47 "for the first time")
    state, tally, msgs = _step(
        state, tally,
        phase=_phase(0, VoteType.PRECOMMIT, {0: -1, 1: -1, 2: -1}),
        proposer=False)
    m = _msgs_at(msgs, 1)
    assert (m["tag"] == int(MsgTag.TIMEOUT)).all()
    assert (m["aux"] == int(TimeoutStep.PRECOMMIT)).all()
    # a further idle step re-emits nothing
    state, tally, msgs = _step(state, tally, proposer=False)
    all_msgs = np.asarray(msgs.tag)
    assert (all_msgs == int(MsgTag.NONE)).all()

    # timeout precommit -> round 1, re-entry as non-proposer
    ext = ExtEvent(jnp.full(I, int(EventTag.TIMEOUT_PRECOMMIT), jnp.int32),
                   jnp.zeros(I, jnp.int32), jnp.zeros(I, jnp.int32),
                   jnp.full(I, -1, jnp.int32))
    state, tally, msgs = _step(state, tally, ext=ext, proposer=False)
    assert (np.asarray(state.round) == 1).all()
    entry = _msgs_at(msgs, 5)
    assert (entry["tag"] == int(MsgTag.TIMEOUT)).all()
    assert (np.asarray(state.step) == int(Step.PROPOSE)).all()


def test_round_skip_via_higher_round_votes():
    """+1/3 of voters on round 2 pulls a lagging instance forward."""
    state = DeviceState.new((I,))
    tally = TallyState.new(I, CFG)
    state, tally, _ = _step(state, tally, proposer=False)  # enter round 0

    state, tally, msgs = _step(
        state, tally, phase=_phase(2, VoteType.PREVOTE, {1: VAL, 2: VAL}),
        proposer=False)
    m = _msgs_at(msgs, 2)
    assert (m["tag"] == int(MsgTag.NEW_ROUND)).all()
    assert (np.asarray(state.round) == 2).all()
    # entry stage re-enters the new round in the same step
    entry = _msgs_at(msgs, 5)
    assert (entry["tag"] == int(MsgTag.TIMEOUT)).all()


def test_missed_edge_recovered_by_requery():
    """Polka crosses while the proposal is still in flight (state at
    Propose ignores it); the re-query stage delivers it after the
    proposal advances the step — the liveness hazard of edge-triggering,
    closed (see device/tally.py docstring)."""
    state = DeviceState.new((I,))
    tally = TallyState.new(I, CFG)
    state, tally, _ = _step(state, tally, proposer=False)  # Propose step

    # prevotes arrive BEFORE the proposal: edge fires, state ignores it
    state, tally, msgs = _step(
        state, tally, phase=_phase(0, VoteType.PREVOTE, {1: VAL, 2: VAL, 3: VAL}),
        proposer=False)
    assert (np.asarray(state.step) == int(Step.PROPOSE)).all()  # still waiting

    # proposal finally arrives -> prevote stage, then requery delivers the
    # polka in the SAME step -> precommit + lock
    ext = ExtEvent(jnp.full(I, int(EventTag.PROPOSAL), jnp.int32),
                   jnp.zeros(I, jnp.int32), jnp.full(I, VAL, jnp.int32),
                   jnp.full(I, -1, jnp.int32))
    state, tally, msgs = _step(state, tally, ext=ext, proposer=False)
    assert (np.asarray(state.step) == int(Step.PRECOMMIT)).all()
    assert (np.asarray(state.locked_round) == 0).all()
    m = _msgs_at(msgs, 3)
    assert (m["tag"] == int(MsgTag.VOTE)).all()
    assert (m["aux"] == int(VoteType.PRECOMMIT)).all()
    assert (m["value"] == VAL).all()


def test_exactly_one_timeout_precommit_per_round():
    """A standing precommit quorum must schedule TimeoutPrecommit exactly
    once per round, however many step changes follow (spec line 47 "for
    the first time"; regression: the requery stages used to re-schedule
    it on every intra-round state change)."""
    state = DeviceState.new((I,))
    tally = TallyState.new(I, CFG)
    state, tally, _ = _step(state, tally, proposer=False)  # Propose step

    n_tp = np.zeros(I, int)

    def count(msgs):
        m = np.asarray(msgs.tag) == int(MsgTag.TIMEOUT)
        a = np.asarray(msgs.aux) == int(TimeoutStep.PRECOMMIT)
        return (m & a).sum(axis=0)

    # precommit-nil quorum lands while still in Propose
    state, tally, msgs = _step(
        state, tally,
        phase=_phase(0, VoteType.PRECOMMIT, {0: -1, 1: -1, 2: -1}),
        proposer=False)
    n_tp += count(msgs)

    # proposal arrives (Propose->Prevote), then a nil polka
    # (Prevote->Precommit), then idle steps: no re-schedules
    ext = ExtEvent(jnp.full(I, int(EventTag.PROPOSAL), jnp.int32),
                   jnp.zeros(I, jnp.int32), jnp.full(I, VAL, jnp.int32),
                   jnp.full(I, -1, jnp.int32))
    state, tally, msgs = _step(state, tally, ext=ext, proposer=False)
    n_tp += count(msgs)
    state, tally, msgs = _step(
        state, tally, phase=_phase(0, VoteType.PREVOTE, {0: -1, 1: -1, 2: -1}),
        proposer=False)
    n_tp += count(msgs)
    for _ in range(3):
        state, tally, msgs = _step(state, tally, proposer=False)
        n_tp += count(msgs)

    assert (n_tp == 1).all(), n_tp

    # the NEXT round gets its own (single) schedule
    ext = ExtEvent(jnp.full(I, int(EventTag.TIMEOUT_PRECOMMIT), jnp.int32),
                   jnp.zeros(I, jnp.int32), jnp.zeros(I, jnp.int32),
                   jnp.full(I, -1, jnp.int32))
    state, tally, msgs = _step(state, tally, ext=ext, proposer=False)
    assert (np.asarray(state.round) == 1).all()
    n_tp2 = count(msgs)
    state, tally, msgs = _step(
        state, tally,
        phase=_phase(1, VoteType.PRECOMMIT, {0: -1, 1: -1, 2: -1}),
        proposer=False)
    n_tp2 += count(msgs)
    for _ in range(2):
        state, tally, msgs = _step(state, tally, proposer=False)
        n_tp2 += count(msgs)
    assert (n_tp2 == 1).all(), n_tp2


def test_device_plane_bitwise_deterministic():
    """SURVEY §5 race-detection slot: the device plane is functionally
    updated, so the same phase stream must produce BITWISE-identical
    state/tally across independent runs (determinism is the purity
    invariant's observable)."""
    from agnes_tpu.harness.device_driver import DeviceDriver

    def run():
        d = DeviceDriver(8, 16, advance_height=True)
        d.run_heights(2)
        d.run_nil_round(int(np.asarray(d.state.round)[0]))
        return d

    a, b = run(), run()
    for name in a.state._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a.state, name)),
            np.asarray(getattr(b.state, name)), err_msg=f"state.{name}")
    for name in a.tally._fields:
        np.testing.assert_array_equal(
            np.asarray(getattr(a.tally, name)),
            np.asarray(getattr(b.tally, name)), err_msg=f"tally.{name}")
    np.testing.assert_array_equal(a.stats.decided, b.stats.decided)
    np.testing.assert_array_equal(a.stats.decision_value,
                                  b.stats.decision_value)
