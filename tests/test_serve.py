"""Serve plane host-side stages: wire codec, admission queue policy,
shape ladder, micro-batcher deadlines, and the DEGENERATE pipeline
ticks (zero-vote / all-held / all-rejected) — everything here is
numpy/stdlib + un-jitted driver construction, NO device dispatch and
NO XLA compile (tier-1 cheap; the dispatching suite lives in
tests/test_serve_pipeline.py, compile-heavy cases marked slow)."""

import numpy as np
import pytest

from agnes_tpu.bridge import VoteBatcher
from agnes_tpu.bridge.native_ingest import (
    REC_SIZE,
    pack_wire_votes,
    unpack_wire_votes,
)
from agnes_tpu.serve import (
    AdmissionQueue,
    DROP_OLDEST,
    MicroBatcher,
    ShapeLadder,
    VoteService,
)
from agnes_tpu.utils.budget import BudgetError, GIB


# -- wire codec ---------------------------------------------------------------

def test_wire_codec_roundtrip():
    """unpack_wire_votes is the exact inverse of pack_wire_votes,
    including nil normalization (any negative value -> -1)."""
    inst = np.array([0, 3, 2], np.int64)
    val = np.array([1, 0, 5], np.int64)
    h = np.array([7, 7, 8], np.int64)
    rnd = np.array([0, 2, 1], np.int64)
    typ = np.array([0, 1, 0], np.int64)
    value = np.array([9, -1, -5], np.int64)
    sigs = np.arange(3 * 64, dtype=np.uint8).reshape(3, 64)
    cols = unpack_wire_votes(pack_wire_votes(inst, val, h, rnd, typ,
                                             value, sigs))
    expect = (inst, val, h, rnd, typ, np.array([9, -1, -1]), sigs)
    for a, b in zip(cols, expect):
        np.testing.assert_array_equal(a, b)


def test_wire_codec_truncated_tail_dropped():
    w = pack_wire_votes([0], [1], [0], [0], [0], [7])
    cols = unpack_wire_votes(w + b"\x01\x02")     # 2 stray bytes
    assert len(cols[0]) == 1


# -- admission queue ----------------------------------------------------------

def _wire(inst, value=7, height=0, round_=0, typ=0):
    inst = np.asarray(inst, np.int64)
    n = len(inst)
    return pack_wire_votes(inst, np.arange(n) % 4, np.full(n, height),
                           np.full(n, round_), np.full(n, typ),
                           np.full(n, value))


def test_queue_fifo_and_depth():
    q = AdmissionQueue(4, capacity=10)
    q.submit(_wire([0, 1]))
    q.submit(_wire([2]))
    assert q.depth == 3
    b = q.drain(2)
    np.testing.assert_array_equal(b.instance, [0, 1])
    b = q.drain()
    np.testing.assert_array_equal(b.instance, [2])
    assert q.depth == 0 and q.drain() is None
    assert q.counters["drained"] == 3


def test_queue_reject_newest_overflow():
    """Default overload policy: a full queue refuses the NEW records
    (prefix of the submit fills remaining room) and counts them."""
    q = AdmissionQueue(4, capacity=3, instance_cap=10)
    res = q.submit(_wire([0, 1, 2, 3, 0]))
    assert res.accepted == 3 and res.rejected_overflow == 2
    assert q.depth == 3
    # queue still full: everything new rejected
    res = q.submit(_wire([1]))
    assert res.accepted == 0 and res.rejected_overflow == 1
    # draining opens room again
    q.drain(2)
    assert q.submit(_wire([1])).accepted == 1


def test_queue_drop_oldest_overflow():
    """drop_oldest sheds admitted work instead: freshest votes win."""
    q = AdmissionQueue(4, capacity=3, instance_cap=10,
                       policy=DROP_OLDEST)
    q.submit(_wire([0, 1, 2]))
    res = q.submit(_wire([3], value=8))
    assert res.accepted == 1 and res.evicted == 1
    assert q.depth == 3
    b = q.drain()
    np.testing.assert_array_equal(b.instance, [1, 2, 3])  # 0 evicted
    assert q.counters["evicted"] == 1


def test_queue_fairness_cap_contains_flooded_instance():
    """One flooded instance may not starve the rest: its records cap
    at instance_cap whatever the order, and other instances' records
    still admit."""
    q = AdmissionQueue(4, capacity=100, instance_cap=3)
    res = q.submit(_wire([0] * 10))
    assert res.accepted == 3 and res.rejected_fairness == 7
    assert q.instance_depth(0) == 3
    # instance 1 is unaffected by the flood
    res = q.submit(_wire([1, 0, 1]))
    assert res.accepted == 2 and res.rejected_fairness == 1
    # draining instance-0 records frees its cap
    q.drain(3)
    assert q.instance_depth(0) < 3
    assert q.submit(_wire([0])).accepted == 1


def test_queue_fairness_within_one_submit_interleaved():
    """The cap binds per record in arrival order, not per submit: an
    interleaved flood admits exactly cap from the flooder."""
    q = AdmissionQueue(2, capacity=100, instance_cap=2)
    res = q.submit(_wire([0, 1, 0, 1, 0, 1, 0]))
    assert res.accepted == 4           # 2 of each
    assert res.rejected_fairness == 3  # flooder's surplus
    b = q.drain()
    np.testing.assert_array_equal(b.instance, [0, 1, 0, 1])


def test_queue_malformed_screens():
    q = AdmissionQueue(2, capacity=10)
    # truncated tail + out-of-range instance id
    res = q.submit(_wire([0, 5]) + b"\xff" * 7)
    assert res.accepted == 1 and res.rejected_malformed == 2
    assert q.counters["rejected_malformed"] == 2
    assert q.submit(b"").accepted == 0


def test_queue_validates_config():
    with pytest.raises(ValueError):
        AdmissionQueue(2, capacity=0)
    with pytest.raises(ValueError):
        AdmissionQueue(2, capacity=4, policy="evict_random")
    with pytest.raises(ValueError):
        AdmissionQueue(2, capacity=4, instance_cap=0)


# -- shape ladder -------------------------------------------------------------

def test_ladder_rungs_and_rung_for():
    lad = ShapeLadder.plan(4, 4, min_rung=8)   # full tick = 32 lanes
    assert lad.rungs == (8, 16, 32)
    assert lad.rung_for(1) == 8 and lad.rung_for(9) == 16
    assert lad.rung_for(32) == 32
    with pytest.raises(ValueError):
        lad.rung_for(33)


def test_ladder_rejects_non_pow2_and_empty():
    with pytest.raises(ValueError):
        ShapeLadder(rungs=(8, 12))
    with pytest.raises(ValueError):
        ShapeLadder(rungs=())
    with pytest.raises(ValueError):
        ShapeLadder(rungs=(16, 8))


def test_ladder_budget_caps_top_rung():
    """A rung whose resident verify operands cannot fit the HBM budget
    is dropped; a budget too small for even min_rung raises."""
    full = ShapeLadder.plan(1024, 1024, min_rung=256,
                            hbm_bytes=16 * GIB)
    tiny = ShapeLadder.plan(1024, 1024, min_rung=256,
                            hbm_bytes=GIB // 1024)  # 1 MiB
    assert tiny.max_rung < full.max_rung
    with pytest.raises(BudgetError):
        ShapeLadder.plan(1024, 1024, min_rung=1 << 20,
                         hbm_bytes=GIB // 1024)


def test_ladder_max_votes_clamp():
    lad = ShapeLadder.plan(1024, 1024, max_votes=1000, min_rung=64)
    assert lad.max_rung == 1024


def test_ladder_plan_dense_validates_per_device_budget():
    """Dense (mesh) mode: rungs only pace votes per batch — the budget
    gate is the dense verify plan of the PER-DEVICE local shape, which
    must fit at least chunked or the service fails at plan time."""
    lad = ShapeLadder.plan_dense(1024, 1024, local_shape=(256, 512),
                                 min_rung=256, hbm_bytes=16 * GIB)
    assert lad.min_rung == 256 and lad.max_rung == 1 << 21  # 2*I*V
    with pytest.raises(BudgetError):
        ShapeLadder.plan_dense(1024, 1024, local_shape=(1024, 1024),
                               hbm_bytes=GIB // 1024)       # 1 MiB
    clamped = ShapeLadder.plan_dense(1024, 1024,
                                     local_shape=(256, 512),
                                     max_votes=4096, min_rung=256,
                                     hbm_bytes=16 * GIB)
    assert clamped.max_rung == 4096


# -- micro-batcher ------------------------------------------------------------

def _fake_clock():
    state = {"t": 100.0}

    def clock():
        return state["t"]

    return state, clock


def test_micro_batcher_closes_on_size():
    state, clock = _fake_clock()
    q = AdmissionQueue(4, capacity=100, clock=clock)
    mb = MicroBatcher(q, ShapeLadder.plan(4, 4, min_rung=8),
                      target_votes=4, max_delay_s=10.0, clock=clock)
    q.submit(_wire([0, 1, 2]))
    assert mb.poll() is None           # under target, under deadline
    q.submit(_wire([3]))
    b = mb.poll()
    assert b is not None and len(b) == 4
    assert mb.closed_by_size == 1 and mb.closed_by_deadline == 0


def test_micro_batcher_closes_on_deadline():
    state, clock = _fake_clock()
    q = AdmissionQueue(4, capacity=100, clock=clock)
    mb = MicroBatcher(q, ShapeLadder.plan(4, 4, min_rung=8),
                      target_votes=100, max_delay_s=0.5, clock=clock)
    q.submit(_wire([0, 1]))
    assert mb.poll() is None
    state["t"] += 0.6                  # oldest record's deadline passes
    b = mb.poll()
    assert b is not None and len(b) == 2
    assert mb.closed_by_deadline == 1
    # deadline anchors on the OLDEST record: a later submit does not
    # reset it
    q.submit(_wire([0]))
    state["t"] += 0.1
    assert mb.poll() is None
    state["t"] += 0.5
    assert mb.poll() is not None


def test_micro_batcher_flush_ignores_policy():
    state, clock = _fake_clock()
    q = AdmissionQueue(4, capacity=100, clock=clock)
    mb = MicroBatcher(q, ShapeLadder.plan(4, 4, min_rung=8),
                      target_votes=100, max_delay_s=100.0, clock=clock)
    q.submit(_wire([0]))
    assert mb.poll() is None and mb.flush() is not None
    assert mb.flush() is None          # empty
    assert 0.0 < mb.fill(3) <= 1.0


# -- degenerate service ticks (no dispatch, no compile) -----------------------

def _service(I=2, V=4, **kw):
    from agnes_tpu.harness.device_driver import DeviceDriver

    d = DeviceDriver(I, V)
    bat = VoteBatcher(I, V, n_slots=4)
    kw.setdefault("ladder", ShapeLadder.plan(I, V, min_rung=16))
    kw.setdefault("capacity", 64)
    kw.setdefault("max_delay_s", 0.0)  # close immediately when queued
    return VoteService(d, bat, None, **kw), d, bat


def test_service_zero_vote_tick_is_noop():
    """An idle pump must not crash, dispatch, or trigger a compile."""
    svc, d, _ = _service()
    for _ in range(3):
        out = svc.pump()
        assert out == {"batch_votes": 0, "dispatched": 0,
                       "staged": False}
    assert d.stats.steps == 0


def test_service_all_held_future_rounds_is_noop():
    """A batch made entirely of future-round votes is held back by the
    batcher (pre-verification window discipline) and must produce a
    counted no-op tick — NOT an empty device step or a crash."""
    svc, d, bat = _service()
    n = 4
    svc.submit(pack_wire_votes(np.zeros(n), np.arange(n), np.zeros(n),
                               np.full(n, 50), np.zeros(n),
                               np.full(n, 7)))
    out = svc.pump()
    assert out["batch_votes"] == n and not out["staged"]
    assert bat.held_votes == n
    assert svc.pipeline.noop_ticks == 1
    assert d.stats.steps == 0
    # drain: ONE device-synced re-entry pass; still-future votes are
    # reported, never spun on, and still nothing was dispatched
    rep = svc.drain()
    assert rep["held_remaining"] == n and rep["held_flushed"] == 0
    assert rep["dispatched_batches"] == 0
    assert d.stats.steps == 0


def test_service_all_stale_heights_is_noop():
    """Votes for a height the instances already left densify to
    nothing (dropped_stale_height) — a no-op tick, no dispatch."""
    svc, d, bat = _service()
    n = 4
    svc.submit(pack_wire_votes(np.zeros(n), np.arange(n),
                               np.full(n, 99), np.zeros(n),
                               np.zeros(n), np.full(n, 7)))
    out = svc.pump()
    assert out["batch_votes"] == n and not out["staged"]
    assert bat.dropped_stale_height == n
    assert d.stats.steps == 0


def test_service_all_rejected_admission_is_noop():
    """A submit the queue fully rejects (flood past the fairness cap
    of a full queue) leaves nothing to batch: pump is a zero-vote
    tick."""
    svc, d, _ = _service(capacity=2, instance_cap=1)
    res = svc.submit(pack_wire_votes(
        np.zeros(6), np.arange(6), np.zeros(6), np.zeros(6),
        np.zeros(6), np.full(6, 7)))
    assert res.accepted == 1           # fairness cap: one record
    assert res.rejected == 5
    svc.queue.drain()                  # empty it behind the service
    out = svc.pump()
    assert out["batch_votes"] == 0 and d.stats.steps == 0
    snap = svc.metrics.snapshot()
    assert snap["serve_rejected_fairness"] == 5


def test_service_drain_on_empty_service():
    svc, d, _ = _service()
    rep = svc.drain()
    assert rep["decisions_total"] == 0
    assert rep["decided_instances"] == 0
    assert rep["dispatched_votes"] == 0
    assert d.stats.steps == 0
    # a draining service fails closed — and its rejects keep the
    # submitted == admitted + rejected counter invariant (truncated
    # tails classified malformed, not overflow)
    res = svc.submit(_wire([0]) + b"\x01")
    assert res.accepted == 0 and res.rejected_overflow == 1
    assert res.rejected_malformed == 1
    snap = svc.metrics.snapshot()
    assert snap["serve_submitted"] == (
        snap.get("serve_admitted", 0) + snap["serve_rejected_overflow"]
        + snap["serve_rejected_malformed"]
        + snap.get("serve_rejected_fairness", 0))


def test_service_decision_decode_survives_height_advance():
    """sync_device rebuilds an advanced instance's slot map, and the
    double buffer stages h+1 before h's decisions are collected — the
    polled decision must decode against the FIRST-advance snapshot,
    not whatever a later height interned into the same slot."""
    svc, d, bat = _service()
    # height 0 interns value 42 into slot 0 of instance 0
    svc.submit(pack_wire_votes([0], [0], [0], [0], [0], [42]))
    svc.pump()                       # densify (stages, no dispatch)
    assert bat.decode_slot(0, 0) == 42
    # the device plane decides slot 0 at height 0 (simulated latch:
    # exercising the decode path without a compile-heavy dispatch)
    d.stats.decided[0] = True
    d.stats.decision_value[0] = 0
    d.stats.decision_round[0] = 0
    # window moves to height 1 BEFORE the decision is polled; a new
    # value now claims slot 0
    svc.pipeline.window_predictor = lambda: (np.zeros(2, np.int64),
                                             np.array([1, 0], np.int64))
    svc.pipeline._staged.clear()     # drop the stale staged builds
    svc.pipeline._sync_window()
    bat.add_arrays([0], [1], [1], [0], [0], [99])
    bat.build_phases()
    assert bat.decode_slot(0, 0) == 99   # the live table moved on
    decs = svc.poll_decisions()
    assert len(decs) == 1 and decs[0].value_id == 42   # snapshot wins


def test_pipeline_offladder_split_held_reentry():
    """ISSUE 3 off-ladder fix: a held future-round burst re-entering
    the window in the same tick as a full fresh batch must build
    SEPARATELY (window-aware split), every build capped at the
    ladder's top rung — `offladder_builds` stays 0 and no vote is
    lost.  Dispatch is stubbed (the build/ladder logic under test is
    host-side; the real dispatch path is covered by the slow suite),
    so this runs with zero XLA compiles."""
    from agnes_tpu.harness.device_driver import DeviceDriver
    from agnes_tpu.harness.fixtures import (
        deterministic_seeds,
        validator_pubkeys,
    )

    I, V = 2, 8
    seeds = deterministic_seeds(V)
    pubkeys = validator_pubkeys(seeds)
    d = DeviceDriver(I, V, advance_height=True, defer_collect=True)
    bat = VoteBatcher(I, V, n_slots=4)
    box = {"base": 0}
    ladder = ShapeLadder.plan(I, V, max_votes=16, min_rung=8)
    assert ladder.max_rung == 16
    svc = VoteService(
        d, bat, pubkeys, capacity=64, target_votes=16, max_delay_s=0.0,
        ladder=ladder,
        window_predictor=lambda: (np.full(I, box["base"], np.int64),
                                  np.zeros(I, np.int64)))
    lanes_seen = []
    d.step_async = (lambda phases, lanes=None, exts=None, donate=True,
                    tick=None: lanes_seen.append(lanes))

    def wire(val_lo, round_):
        """Both classes of a half-tick: validators [val_lo, val_lo+4)
        vote 7 in `round_` for every instance (8 votes per class)."""
        inst = np.repeat(np.arange(I), 4)
        val = np.tile(np.arange(val_lo, val_lo + 4), I)
        n = len(inst)
        return b"".join(
            pack_wire_votes(inst, val, np.zeros(n), np.full(n, round_),
                            np.full(n, typ), np.full(n, 7))
            for typ in (0, 1))

    # tick 1: a 16-vote burst for round 4 — outside the W=4 window at
    # base 0, so the batcher holds it back (a counted no-op tick)
    assert svc.submit(wire(0, 4)).accepted == 16
    svc.pump()
    assert bat.held_votes == 16 and svc.pipeline.noop_ticks == 1

    # tick 2: the window rotates to base 4 AND a full fresh batch for
    # round 4 arrives — the old pipeline drained burst + batch into
    # ONE 32-lane build above the top rung (offladder_builds == 1, a
    # live compile stall); the split builds them separately
    box["base"] = 4
    assert svc.submit(wire(4, 4)).accepted == 16
    svc.pump()                         # stages the split builds
    svc.pump()                         # dispatches them
    rep = svc.drain()

    assert svc.pipeline.offladder_builds == 0
    assert rep["dispatched_batches"] == 2
    assert rep["dispatched_votes"] == 32           # no vote lost
    assert bat.held_votes == 0
    assert len(lanes_seen) == 2
    for lanes in lanes_seen:
        assert lanes is not None                   # device-eligible
        assert int(lanes.pub.shape[0]) <= ladder.max_rung
    assert sum(int(np.asarray(ln.real).sum()) for ln in lanes_seen) == 32


def test_pipeline_dispatch_failure_restores_staged_builds():
    """A dispatch that raises must put the failing build AND every
    later staged build back on the FIFO — a caller that catches the
    transient error and retries loses no staged vote."""
    from agnes_tpu.harness.device_driver import DeviceDriver
    from agnes_tpu.harness.fixtures import (
        deterministic_seeds,
        validator_pubkeys,
    )

    I, V = 2, 8
    seeds = deterministic_seeds(V)
    d = DeviceDriver(I, V, advance_height=True, defer_collect=True)
    bat = VoteBatcher(I, V, n_slots=4)
    ladder = ShapeLadder.plan(I, V, max_votes=16, min_rung=8)
    svc = VoteService(
        d, bat, validator_pubkeys(seeds), capacity=64, target_votes=16,
        max_delay_s=0.0, ladder=ladder,
        window_predictor=lambda: (np.zeros(I, np.int64),
                                  np.zeros(I, np.int64)))
    calls = {"n": 0}

    def flaky(phases, lanes=None, exts=None, donate=True, tick=None):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient dispatch error")

    d.step_async = flaky
    # two half-tick submits -> one 32-vote batch... the cap splits it
    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)
    n = I * V
    svc.submit(b"".join(
        pack_wire_votes(inst, val, np.zeros(n), np.zeros(n),
                        np.full(n, typ), np.full(n, 7))
        for typ in (0, 1)))
    # stage the WHOLE 32-vote tick at once: the max_rung=16 cap
    # splits it into two staged builds
    assert svc.pipeline.stage(svc.queue.drain())
    assert len(svc.pipeline._staged) == 2
    with pytest.raises(RuntimeError):
        svc.pipeline.dispatch_staged()     # first dispatch raises
    assert len(svc.pipeline._staged) == 2  # nothing lost
    assert svc.pipeline.dispatched_votes == 0
    assert svc.pipeline.dispatch_staged() == 32   # retry conserves all
    assert svc.pipeline.dispatched_batches == 2


def test_service_gauges_and_windowed_rates():
    """The serve gauges use WINDOWED rates (satellite: lifetime rates
    trend to zero on a long-lived service)."""
    svc, d, _ = _service()
    svc.submit(_wire([0, 1]))
    svc.pump()
    svc.poll_decisions()
    snap = svc.metrics.snapshot()
    assert snap["serve_queue_depth"] == 0.0
    assert "serve_admit_rate_per_sec_window" in snap
    assert snap["serve_admitted"] == 2
