"""Pure-core state machine tests.

`test_happy_case` is the exact parity anchor for the reference's shipped
test (state_machine.rs:331-345); the rest pin down the subtleties listed
in SURVEY.md §2.2 that the differential tests against the device plane and
the native core rely on.
"""

from agnes_tpu.core.state_machine import (
    Event,
    Message,
    MsgTag,
    State,
    Step,
    TimeoutStep,
    apply,
)

VAL = 7
OTHER = 9


def test_happy_case():
    """Parity anchor: state_machine.rs:331-345 — proposer drives one height
    to decision in 4 events."""
    s = State.new(1)
    s, m = apply(s, 0, Event.new_round_proposer(VAL))
    assert m == Message.proposal_msg(0, VAL, -1)
    s, m = apply(s, 0, Event.proposal(-1, VAL))
    assert m == Message.prevote(0, VAL)
    s, m = apply(s, 0, Event.polka_value(VAL))
    assert m == Message.precommit(0, VAL)
    s, m = apply(s, 0, Event.precommit_value(VAL))
    assert m == Message.decision_msg(0, VAL)
    assert s.step == Step.COMMIT


def test_non_proposer_schedules_timeout_propose():
    """state_machine.rs:188, 278-281 (spec 11/20)."""
    s = State.new(1)
    s, m = apply(s, 0, Event.new_round())
    assert s.step == Step.PROPOSE
    assert m == Message.timeout_msg(0, TimeoutStep.PROPOSE)


def test_wrong_round_events_ignored():
    """Most arms are guarded by eqr (state_machine.rs:184)."""
    s = State.new(1)
    for ev in (Event.new_round(), Event.new_round_proposer(VAL)):
        s2, m = apply(s, 1, ev)
        assert (s2, m) == (s, None)


def test_invalid_pol_round_rejected():
    """Proposal guard requires -1 <= vr < round (state_machine.rs:170-172,
    191)."""
    s = State.new(1)
    s, _ = apply(s, 0, Event.new_round())
    assert s.step == Step.PROPOSE
    # vr = 0 == round → invalid; vr = -2 → invalid
    for vr in (0, 5, -2):
        s2, m = apply(s, 0, Event.proposal(vr, VAL))
        assert (s2, m) == (s, None)
    s2, m = apply(s, 0, Event.proposal(-1, VAL))
    assert m == Message.prevote(0, VAL)


def test_proposal_invalid_and_timeout_prevote_nil():
    """state_machine.rs:192-193 (spec 22/25, 57)."""
    for ev in (Event.proposal_invalid(), Event.timeout_propose()):
        s = State.new(1)
        s, _ = apply(s, 0, Event.new_round())
        s, m = apply(s, 0, ev)
        assert s.step == Step.PREVOTE
        assert m == Message.prevote(0, None)


def _to_prevote_step(round=0):
    s = State.new(1)
    s, _ = apply(s, 0, Event.new_round())
    s, _ = apply(s, 0, Event.proposal(-1, VAL))
    return s


def test_polka_any_schedules_timeout_without_step_change():
    """state_machine.rs:196, 287-289: no step advance (spec 34)."""
    s = _to_prevote_step()
    s2, m = apply(s, 0, Event.polka_any())
    assert s2.step == Step.PREVOTE
    assert m == Message.timeout_msg(0, TimeoutStep.PREVOTE)


def test_polka_nil_and_timeout_precommit_nil():
    """state_machine.rs:197,199 (spec 44, 61)."""
    for ev in (Event.polka_nil(), Event.timeout_prevote()):
        s = _to_prevote_step()
        s2, m = apply(s, 0, ev)
        assert s2.step == Step.PRECOMMIT
        assert m == Message.precommit(0, None)


def test_polka_value_locks_and_precommits():
    """precommit sets BOTH locked and valid (state_machine.rs:261-264)."""
    s = _to_prevote_step()
    s2, m = apply(s, 0, Event.polka_value(VAL))
    assert s2.step == Step.PRECOMMIT
    assert s2.locked is not None and s2.locked.value == VAL and s2.locked.round == 0
    assert s2.valid is not None and s2.valid.value == VAL and s2.valid.round == 0
    assert m == Message.precommit(0, VAL)


def test_polka_value_at_precommit_sets_valid_only_no_message():
    """set_valid_value: valid only, no message (state_machine.rs:304-306)."""
    s = _to_prevote_step()
    s, _ = apply(s, 0, Event.timeout_prevote())  # now Precommit, no lock
    s2, m = apply(s, 0, Event.polka_value(VAL))
    assert m is None
    assert s2.valid.value == VAL
    assert s2.locked is None


def test_commit_from_any_round_and_any_step():
    """PrecommitValue has no round guard (state_machine.rs:211, spec 49)."""
    s = State.new(1)  # NewRound step, round 0
    s2, m = apply(s, 5, Event.precommit_value(VAL))
    assert s2.step == Step.COMMIT
    assert s2.round == 0  # commit does not touch the round field
    assert m == Message.decision_msg(5, VAL)  # decision carries event round


def test_commit_step_absorbs_everything():
    """state_machine.rs:205."""
    s = State.new(1)
    s, _ = apply(s, 0, Event.precommit_value(VAL))
    assert s.step == Step.COMMIT
    for r in (0, 1):
        for ev in (Event.new_round(), Event.precommit_value(OTHER),
                   Event.round_skip(), Event.timeout_precommit()):
            s2, m = apply(s, r, ev)
            assert (s2, m) == (s, None)


def test_precommit_any_schedules_timeout_from_any_noncommit_step():
    """state_machine.rs:208 (spec 47)."""
    s = State.new(1)  # NewRound
    s2, m = apply(s, 0, Event.precommit_any())
    assert s2.step == Step.NEW_ROUND
    assert m == Message.timeout_msg(0, TimeoutStep.PRECOMMIT)


def test_timeout_precommit_advances_round():
    """round_skip to round+1, step back to NewRound (state_machine.rs:209,
    314-316, spec 65)."""
    s = _to_prevote_step()
    s2, m = apply(s, 0, Event.timeout_precommit())
    assert s2.round == 1
    assert s2.step == Step.NEW_ROUND
    assert m == Message.new_round(1)


def test_round_skip_requires_higher_round():
    """state_machine.rs:210 (spec 55)."""
    s = State.new(1)
    s2, m = apply(s, 0, Event.round_skip())  # same round: no-op
    assert (s2, m) == (s, None)
    s2, m = apply(s, 3, Event.round_skip())
    assert s2.round == 3 and s2.step == Step.NEW_ROUND
    assert m == Message.new_round(3)


def test_lock_rule():
    """The four-way lock rule (state_machine.rs:239-244)."""
    # lock VAL at round 0, then reach Propose at round 1
    s = _to_prevote_step()
    s, _ = apply(s, 0, Event.polka_value(VAL))       # locked=(0, VAL)
    s, _ = apply(s, 0, Event.timeout_precommit())    # round 1, NewRound
    s, _ = apply(s, 1, Event.new_round())            # Propose

    # (a) locked.round (0) <= vr (0) → unlock, prevote proposed
    s2, m = apply(s, 1, Event.proposal(0, OTHER))
    assert m == Message.prevote(1, OTHER)
    # (b) locked on same value at higher round than vr → prevote value
    s2, m = apply(s, 1, Event.proposal(-1, VAL))
    assert m == Message.prevote(1, VAL)
    # (c) locked on different value, vr < locked.round → prevote nil
    s2, m = apply(s, 1, Event.proposal(-1, OTHER))
    assert m == Message.prevote(1, None)


def test_proposer_reuses_valid_value():
    """propose uses (valid.value, valid.round) when set
    (state_machine.rs:222-229)."""
    s = _to_prevote_step()
    s, _ = apply(s, 0, Event.polka_value(VAL))       # valid=(0, VAL)
    s, _ = apply(s, 0, Event.timeout_precommit())    # round 1, NewRound
    s2, m = apply(s, 1, Event.new_round_proposer(OTHER))
    assert m == Message.proposal_msg(1, VAL, 0)      # not OTHER


def test_decision_in_later_round():
    """Full two-round run: round 0 fails, round 1 decides."""
    s = State.new(1)
    s, m = apply(s, 0, Event.new_round())
    assert m.tag == MsgTag.TIMEOUT
    s, m = apply(s, 0, Event.timeout_propose())
    assert m == Message.prevote(0, None)
    s, m = apply(s, 0, Event.polka_any())
    s, m = apply(s, 0, Event.timeout_prevote())
    assert m == Message.precommit(0, None)
    s, m = apply(s, 0, Event.precommit_any())
    s, m = apply(s, 0, Event.timeout_precommit())
    assert m == Message.new_round(1)
    s, m = apply(s, 1, Event.new_round())
    s, m = apply(s, 1, Event.proposal(-1, VAL))
    assert m == Message.prevote(1, VAL)
    s, m = apply(s, 1, Event.polka_value(VAL))
    assert m == Message.precommit(1, VAL)
    s, m = apply(s, 1, Event.precommit_value(VAL))
    assert m == Message.decision_msg(1, VAL)
    assert s.step == Step.COMMIT
