"""Sharded step == single-device step, on a 2x4 virtual CPU mesh.

Exercises the dp(instances) x tp(validators) layout of
parallel/sharded.py: validator-axis quorum reductions become psums, and
the whole happy path must produce bitwise-identical states, tallies and
messages to the unsharded fused step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agnes_tpu.device.encoding import DeviceState
from agnes_tpu.device.step import ExtEvent, VotePhase, consensus_step_jit
from agnes_tpu.device.tally import TallyConfig, TallyState
from agnes_tpu.parallel import make_mesh, make_sharded_step, shard_step_args
from agnes_tpu.types import VoteType

I, V = 8, 4
CFG = TallyConfig(n_validators=V, n_rounds=4, n_slots=4)
POWERS = jnp.ones((V,), jnp.int32)
TOTAL = jnp.asarray(V, jnp.int32)
VAL = 2

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual CPU mesh")


def _phase(round_, typ, votes):
    slots = np.full((I, V), -1, np.int32)
    mask = np.zeros((I, V), bool)
    for v, s in votes.items():
        slots[:, v] = s
        mask[:, v] = True
    return VotePhase(jnp.full(I, round_, jnp.int32),
                     jnp.full(I, int(typ), jnp.int32),
                     jnp.asarray(slots), jnp.asarray(mask),
                     jnp.zeros(I, jnp.int32))


def _empty_phase():
    return VotePhase(jnp.zeros(I, jnp.int32), jnp.zeros(I, jnp.int32),
                     jnp.full((I, V), -1, jnp.int32), jnp.zeros((I, V), bool),
                     jnp.zeros(I, jnp.int32))


def _args(state, tally, phase):
    return (state, tally, ExtEvent.none(I), phase, POWERS, TOTAL,
            jnp.ones((I, CFG.n_rounds), bool), jnp.full(I, VAL, jnp.int32))


def _assert_trees_equal(a, b):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_sharded_matches_unsharded_happy_path():
    mesh = make_mesh(2, 4)
    step = make_sharded_step(mesh)

    phases = [
        _empty_phase(),                                        # entry+proposal
        _phase(0, VoteType.PREVOTE, {0: VAL, 1: VAL, 2: VAL}),  # polka
        _phase(0, VoteType.PRECOMMIT, {0: VAL, 1: VAL, 2: VAL}),  # decision
    ]

    s_ref, t_ref = DeviceState.new((I,)), TallyState.new(I, CFG)
    s_sh, t_sh = DeviceState.new((I,)), TallyState.new(I, CFG)
    for ph in phases:
        s_ref, t_ref, m_ref = consensus_step_jit(*_args(s_ref, t_ref, ph))
        sharded = shard_step_args(mesh, *_args(s_sh, t_sh, ph))
        s_sh, t_sh, m_sh = step(*sharded)
        _assert_trees_equal(s_ref, s_sh)
        _assert_trees_equal(t_ref, t_sh)
        _assert_trees_equal(m_ref, m_sh)

    from agnes_tpu.core.state_machine import Step
    assert (np.asarray(s_sh.step) == int(Step.COMMIT)).all()


def test_sharded_round_skip_psum():
    """The round-skip reduction crosses validator shards: 2 voters on
    round 2 live on different val-shard devices; only their psum
    reaches +1/3."""
    mesh = make_mesh(2, 4)
    step = make_sharded_step(mesh)
    s, t = DeviceState.new((I,)), TallyState.new(I, CFG)

    sharded = shard_step_args(
        mesh, *_args(s, t, _phase(2, VoteType.PREVOTE, {1: VAL, 3: VAL})))
    s, t, _ = step(*sharded)
    assert (np.asarray(s.round) == 2).all()


def _ext(tag, round_):
    from agnes_tpu.types import NIL_ID
    return ExtEvent(tag=jnp.full(I, tag, jnp.int32),
                    round=jnp.full(I, round_, jnp.int32),
                    value=jnp.full(I, NIL_ID, jnp.int32),
                    pol_round=jnp.full(I, -1, jnp.int32))


def _args_ext(state, tally, phase, ext, proposer=True,
              heights=None):
    ph = phase
    if heights is not None:
        ph = ph._replace(height=heights)
    return (state, tally, ext, ph, POWERS, TOTAL,
            jnp.full((I, CFG.n_rounds), proposer, bool),
            jnp.full(I, VAL, jnp.int32))


def _run_both(mesh, step, scenario, advance=False):
    """Drive the same (ext, phase) script through the sharded and
    unsharded steps, asserting bitwise equality after every call.
    scenario: list of (ext, phase, proposer) tuples; phases carry the
    CURRENT state height (so multi-height scripts stay fenced)."""
    s_ref, t_ref = DeviceState.new((I,)), TallyState.new(I, CFG)
    s_sh, t_sh = DeviceState.new((I,)), TallyState.new(I, CFG)
    for ext, ph, proposer in scenario:
        a_ref = _args_ext(s_ref, t_ref, ph, ext, proposer,
                          heights=s_ref.height)
        s_ref, t_ref, m_ref = consensus_step_jit(
            *a_ref, advance_height=advance)
        a_sh = _args_ext(s_sh, t_sh, ph, ext, proposer,
                         heights=s_sh.height)
        s_sh, t_sh, m_sh = step(*shard_step_args(mesh, *a_sh))
        _assert_trees_equal(s_ref, s_sh)
        _assert_trees_equal(t_ref, t_sh)
        _assert_trees_equal(m_ref, m_sh)
    return s_sh, t_sh


def test_sharded_matches_unsharded_nil_timeout_round():
    """VERDICT r2 weak #6 scenario 1: a full nil/timeout round then a
    deciding round — timeouts, nil quorums and the PRECOMMIT_ANY
    mapping must psum identically."""
    from agnes_tpu.core.state_machine import EventTag, Step
    mesh = make_mesh(2, 4)
    step = make_sharded_step(mesh)
    none = ExtEvent.none(I)
    nilv = {v: -1 for v in range(V)}
    allv = {v: VAL for v in range(V)}
    scenario = [
        (none, _empty_phase(), False),                      # entry
        (_ext(int(EventTag.TIMEOUT_PROPOSE), 0), _empty_phase(), False),
        (none, _phase(0, VoteType.PREVOTE, nilv), False),   # polka nil
        (none, _phase(0, VoteType.PRECOMMIT, nilv), False),
        (_ext(int(EventTag.TIMEOUT_PRECOMMIT), 0), _empty_phase(), False),
        (none, _empty_phase(), True),                       # round 1 entry
        (none, _phase(1, VoteType.PREVOTE, allv), True),
        (none, _phase(1, VoteType.PRECOMMIT, allv), True),
    ]
    s, _t = _run_both(mesh, step, scenario)
    assert (np.asarray(s.step) == int(Step.COMMIT)).all()
    assert (np.asarray(s.round) == 1).all()


def test_sharded_matches_unsharded_equivocation():
    """Scenario 2: conflicting votes from validators on different
    val-shards; the sharded equiv plane must match the unsharded one
    bitwise (each shard records its own validators' conflicts)."""
    mesh = make_mesh(2, 4)
    step = make_sharded_step(mesh)
    none = ExtEvent.none(I)
    scenario = [
        (none, _phase(0, VoteType.PREVOTE, {0: VAL, 3: VAL}), True),
        # validators 0 (shard 0) and 3 (shard 3) flip to a new value
        (none, _phase(0, VoteType.PREVOTE, {0: VAL + 1, 3: VAL + 1}), True),
    ]
    _s, t = _run_both(mesh, step, scenario)
    equiv = np.asarray(t.equiv)
    assert (equiv[:, [0, 3]]).all() and not equiv[:, [1, 2]].any()


def test_sharded_matches_unsharded_window_rotation():
    """Scenario 3: instances pushed past the W=4 window edge (skips to
    round 5 via +1/3 weight, then TimeoutPrecommit chains) — the
    per-instance base_round roll must be identical under sharding."""
    from agnes_tpu.core.state_machine import EventTag
    mesh = make_mesh(2, 4)
    step = make_sharded_step(mesh)
    none = ExtEvent.none(I)
    scenario = [
        # +1/3 on round 2 -> RoundSkip to 2; rotation moves base to 1
        (none, _phase(2, VoteType.PREVOTE, {1: VAL, 3: VAL}), False),
        # timeout chain walks rounds 3..5; base follows
        (_ext(int(EventTag.TIMEOUT_PRECOMMIT), 2), _empty_phase(), False),
        (_ext(int(EventTag.TIMEOUT_PRECOMMIT), 3), _empty_phase(), False),
        (_ext(int(EventTag.TIMEOUT_PRECOMMIT), 4), _empty_phase(), False),
        # votes for round 5 (window row 5-base) land after rotation
        (none, _phase(5, VoteType.PREVOTE, {v: VAL for v in range(V)}),
         False),
    ]
    s, t = _run_both(mesh, step, scenario)
    assert (np.asarray(s.round) == 5).all()
    assert (np.asarray(t.base_round) == 4).all()


def test_sharded_matches_unsharded_multi_height():
    """Two consecutive decided heights with the on-device height
    advance enabled under shard_map."""
    mesh = make_mesh(2, 4)
    step = make_sharded_step(mesh, advance_height=True)
    none = ExtEvent.none(I)
    allv = {v: VAL for v in range(V)}
    height = [
        (none, _empty_phase(), True),
        (none, _phase(0, VoteType.PREVOTE, allv), True),
        (none, _phase(0, VoteType.PRECOMMIT, allv), True),
    ]
    s, t = _run_both(mesh, step, height * 2, advance=True)
    assert (np.asarray(s.height) == 2).all()
    assert (np.asarray(t.base_round) == 0).all()


# --- hierarchical (multi-slice) mesh ----------------------------------------


def test_hierarchical_mesh_matches_unsharded_nil_and_decide():
    """The (slice=2, data=2, val=2) hierarchical mesh must be bitwise
    identical to the unsharded step on the nil-timeout-then-decide
    scenario: instances shard across the DCN-like slice axis, quorum
    psums stay on the intra-slice val axis."""
    from agnes_tpu.core.state_machine import EventTag, Step
    from agnes_tpu.parallel import make_hierarchical_mesh
    mesh = make_hierarchical_mesh(2, 2, 2)
    step = make_sharded_step(mesh)
    none = ExtEvent.none(I)
    nilv = {v: -1 for v in range(V)}
    allv = {v: VAL for v in range(V)}
    scenario = [
        (none, _empty_phase(), False),
        (_ext(int(EventTag.TIMEOUT_PROPOSE), 0), _empty_phase(), False),
        (none, _phase(0, VoteType.PREVOTE, nilv), False),
        (none, _phase(0, VoteType.PRECOMMIT, nilv), False),
        (_ext(int(EventTag.TIMEOUT_PRECOMMIT), 0), _empty_phase(), False),
        (none, _empty_phase(), True),
        (none, _phase(1, VoteType.PREVOTE, allv), True),
        (none, _phase(1, VoteType.PRECOMMIT, allv), True),
    ]
    s, _t = _run_both(mesh, step, scenario)
    assert (np.asarray(s.step) == int(Step.COMMIT)).all()
    assert (np.asarray(s.round) == 1).all()


def test_hierarchical_mesh_equivocation_and_skip():
    """Equivocation flags and the round-skip psum cross val shards
    inside each slice; the slice axis itself must carry nothing."""
    from agnes_tpu.parallel import make_hierarchical_mesh
    mesh = make_hierarchical_mesh(2, 2, 2)
    step = make_sharded_step(mesh)
    none = ExtEvent.none(I)
    scenario = [
        (none, _phase(0, VoteType.PREVOTE, {0: VAL, 3: VAL}), True),
        (none, _phase(0, VoteType.PREVOTE, {0: VAL + 1, 3: VAL + 1}), True),
        (none, _phase(2, VoteType.PREVOTE, {1: VAL, 2: VAL}), True),
    ]
    s, t = _run_both(mesh, step, scenario)
    equiv = np.asarray(t.equiv)
    assert (equiv[:, [0, 3]]).all() and not equiv[:, [1, 2]].any()
    assert (np.asarray(s.round) == 2).all()


def test_sharded_fused_seq_and_heights_match_unsharded():
    """The fused-sequence paths under shard_map (r4): step_seq and
    run_heights_fused on the flat 2x4 and hierarchical 2x2x2 meshes
    must match the single-device fused driver bitwise — the sequence
    scan and the per-phase quorum psums must commute."""
    from agnes_tpu.harness.device_driver import DeviceDriver
    from agnes_tpu.parallel import make_hierarchical_mesh

    def drive_seq(mesh):
        d = DeviceDriver(8, 8, mesh=mesh)
        d.step_seq([d.phase(0, VoteType.PREVOTE, 1),
                    d.phase(0, VoteType.PREVOTE, 2),
                    d.phase(0, VoteType.PRECOMMIT, 1)])
        d.block_until_ready()
        return d

    def drive_heights(mesh):
        d = DeviceDriver(8, 8, advance_height=True, mesh=mesh)
        d.run_heights_fused(3)
        d.block_until_ready()
        return d

    for drive in (drive_seq, drive_heights):
        ref = drive(None)
        for mesh in (make_mesh(2, 4), make_hierarchical_mesh(2, 2, 2)):
            dm = drive(mesh)
            _assert_trees_equal(ref.state, dm.state)
            _assert_trees_equal(ref.tally, dm.tally)
            assert dm.stats.decisions_total == ref.stats.decisions_total
            np.testing.assert_array_equal(dm.stats.decision_value,
                                          ref.stats.decision_value)


def test_sharded_closed_loop_config3_shape():
    """VERDICT r3 weak #5: a full DRIVER loop (not a one-step smoke)
    under sharding, at the config-3 small shape (8 x 64): nil round
    with timeouts, then a proposed round to decision, on both the flat
    2x4 and the hierarchical 2x2x2 mesh — decisions and final state
    must match the single-device closed loop exactly."""
    from agnes_tpu.harness.device_driver import DeviceDriver
    from agnes_tpu.parallel import make_hierarchical_mesh

    def drive(mesh):
        d = DeviceDriver(8, 64, proposer_is_self=False, mesh=mesh)
        d.run_nil_round(0)
        d.run_proposed_round(1, slot=1)
        d.block_until_ready()
        return d

    ref = drive(None)
    assert ref.all_decided()
    for mesh in (make_mesh(2, 4), make_hierarchical_mesh(2, 2, 2)):
        dm = drive(mesh)
        assert dm.all_decided()
        np.testing.assert_array_equal(dm.stats.decision_value,
                                      ref.stats.decision_value)
        np.testing.assert_array_equal(dm.stats.decision_round,
                                      ref.stats.decision_round)
        _assert_trees_equal(ref.state, dm.state)
        _assert_trees_equal(ref.tally, dm.tally)
