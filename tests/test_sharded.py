"""Sharded step == single-device step, on a 2x4 virtual CPU mesh.

Exercises the dp(instances) x tp(validators) layout of
parallel/sharded.py: validator-axis quorum reductions become psums, and
the whole happy path must produce bitwise-identical states, tallies and
messages to the unsharded fused step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agnes_tpu.device.encoding import DeviceState
from agnes_tpu.device.step import ExtEvent, VotePhase, consensus_step_jit
from agnes_tpu.device.tally import TallyConfig, TallyState
from agnes_tpu.parallel import make_mesh, make_sharded_step, shard_step_args
from agnes_tpu.types import VoteType

I, V = 8, 4
CFG = TallyConfig(n_validators=V, n_rounds=4, n_slots=4)
POWERS = jnp.ones((V,), jnp.int32)
TOTAL = jnp.asarray(V, jnp.int32)
VAL = 2

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual CPU mesh")


def _phase(round_, typ, votes):
    slots = np.full((I, V), -1, np.int32)
    mask = np.zeros((I, V), bool)
    for v, s in votes.items():
        slots[:, v] = s
        mask[:, v] = True
    return VotePhase(jnp.full(I, round_, jnp.int32),
                     jnp.full(I, int(typ), jnp.int32),
                     jnp.asarray(slots), jnp.asarray(mask),
                     jnp.zeros(I, jnp.int32))


def _empty_phase():
    return VotePhase(jnp.zeros(I, jnp.int32), jnp.zeros(I, jnp.int32),
                     jnp.full((I, V), -1, jnp.int32), jnp.zeros((I, V), bool),
                     jnp.zeros(I, jnp.int32))


def _args(state, tally, phase):
    return (state, tally, ExtEvent.none(I), phase, POWERS, TOTAL,
            jnp.ones((I, CFG.n_rounds), bool), jnp.full(I, VAL, jnp.int32))


def _assert_trees_equal(a, b):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_sharded_matches_unsharded_happy_path():
    mesh = make_mesh(2, 4)
    step = make_sharded_step(mesh)

    phases = [
        _empty_phase(),                                        # entry+proposal
        _phase(0, VoteType.PREVOTE, {0: VAL, 1: VAL, 2: VAL}),  # polka
        _phase(0, VoteType.PRECOMMIT, {0: VAL, 1: VAL, 2: VAL}),  # decision
    ]

    s_ref, t_ref = DeviceState.new((I,)), TallyState.new(I, CFG)
    s_sh, t_sh = DeviceState.new((I,)), TallyState.new(I, CFG)
    for ph in phases:
        s_ref, t_ref, m_ref = consensus_step_jit(*_args(s_ref, t_ref, ph))
        sharded = shard_step_args(mesh, *_args(s_sh, t_sh, ph))
        s_sh, t_sh, m_sh = step(*sharded)
        _assert_trees_equal(s_ref, s_sh)
        _assert_trees_equal(t_ref, t_sh)
        _assert_trees_equal(m_ref, m_sh)

    from agnes_tpu.core.state_machine import Step
    assert (np.asarray(s_sh.step) == int(Step.COMMIT)).all()


def test_sharded_round_skip_psum():
    """The round-skip reduction crosses validator shards: 2 voters on
    round 2 live on different val-shard devices; only their psum
    reaches +1/3."""
    mesh = make_mesh(2, 4)
    step = make_sharded_step(mesh)
    s, t = DeviceState.new((I,)), TallyState.new(I, CFG)

    sharded = shard_step_args(
        mesh, *_args(s, t, _phase(2, VoteType.PREVOTE, {1: VAL, 3: VAL})))
    s, t, _ = step(*sharded)
    assert (np.asarray(s.round) == 2).all()
