"""Fused-sequence stepping (consensus_step_seq / honest_heights) must
be bit-identical to phase-at-a-time stepping — the seq paths exist to
cut per-dispatch overhead (one dispatch per sequence instead of one per
phase), never to change semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agnes_tpu.device.encoding import I32
from agnes_tpu.harness.device_driver import DeviceDriver
from agnes_tpu.types import VoteType


def _tree_equal(a, b):
    ok = jax.tree.map(lambda x, y: bool((np.asarray(x) == np.asarray(y))
                                        .all()), a, b)
    return all(jax.tree.leaves(ok))


def _random_phases(d, rng, n):
    phases = []
    for _ in range(n):
        typ = int(rng.choice([int(VoteType.PREVOTE),
                              int(VoteType.PRECOMMIT)]))
        slot = int(rng.integers(-1, d.cfg.n_slots))
        frac = float(rng.uniform(0.3, 1.0))
        phases.append(d.phase(int(rng.integers(0, 2)), typ, slot, frac))
    return phases


@pytest.mark.parametrize("advance", [False, True])
def test_step_seq_matches_sequential(advance):
    rng = np.random.default_rng(7)
    I, V = 5, 8
    d_seq = DeviceDriver(I, V, advance_height=advance)
    d_one = DeviceDriver(I, V, advance_height=advance)
    phases = _random_phases(d_seq, rng, 6)

    msgs_seq = d_seq.step_seq(phases)
    outs = [d_one.step(phase=p) for p in phases]

    assert _tree_equal(d_seq.state, d_one.state)
    assert _tree_equal(d_seq.tally, d_one.tally)
    # stacked messages equal the per-step messages, in order
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *outs)
    assert _tree_equal(msgs_seq, stacked)
    # stats agree (decisions_total, latched values)
    assert d_seq.stats.decisions_total == d_one.stats.decisions_total
    assert (d_seq.stats.decided == d_one.stats.decided).all()
    assert (d_seq.stats.decision_value == d_one.stats.decision_value).all()
    assert d_seq.stats.votes_ingested == d_one.stats.votes_ingested


def test_honest_heights_fused_matches_loop():
    I, V, H = 4, 8, 3
    d_f = DeviceDriver(I, V, advance_height=True)
    d_l = DeviceDriver(I, V, advance_height=True)
    d_f.run_heights_fused(H)
    d_l.run_heights(H)
    assert _tree_equal(d_f.state, d_l.state)
    assert _tree_equal(d_f.tally, d_l.tally)
    assert d_f.stats.decisions_total == I * H
    assert d_l.stats.decisions_total == I * H
    assert (d_f.stats.decided == d_l.stats.decided).all()
    assert (d_f.stats.decision_value == d_l.stats.decision_value).all()
    assert int(np.asarray(d_f.state.height)[0]) == H
    assert d_f.stats.votes_ingested == d_l.stats.votes_ingested


def test_honest_heights_fused_partial_quorum():
    # 3/4 of validators voting still crosses 2/3+: decisions proceed
    I, V, H = 3, 8, 2
    d = DeviceDriver(I, V, advance_height=True)
    d.run_heights_fused(H, frac=0.75)
    assert d.stats.decisions_total == I * H
    # under 2/3: no decisions, heights never advance
    d2 = DeviceDriver(I, V, advance_height=True)
    d2.run_heights_fused(H, frac=0.5)
    assert d2.stats.decisions_total == 0
    assert int(np.asarray(d2.state.height)[0]) == 0


def test_step_seq_defer_collect():
    I, V = 4, 8
    d = DeviceDriver(I, V, advance_height=True, defer_collect=True)
    d.step_seq([d.phase(0, VoteType.PREVOTE, 1),
                d.phase(0, VoteType.PRECOMMIT, 1)])
    assert d.stats.decisions_total == 0          # not yet collected
    d.collect()
    assert d.stats.decisions_total == I
