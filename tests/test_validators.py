"""Validator set + proposer rotation tests (validators.rs intent,
SURVEY.md §2.6)."""

import numpy as np

from agnes_tpu.core.validators import (
    ProposerRotation,
    Validator,
    ValidatorSet,
    proposer_table,
)


def _v(key_byte: int, power: int = 1) -> Validator:
    return Validator(bytes([key_byte]) + bytes(31), power)


def test_sorted_by_address():
    vs = ValidatorSet([_v(3), _v(1), _v(2)])
    assert [v.public_key[0] for v in vs] == [1, 2, 3]


def test_dedup_by_address_keeps_latest():
    vs = ValidatorSet([_v(1, 10), _v(1, 20)])
    assert len(vs) == 1
    assert vs[0].voting_power == 20


def test_add_update_remove():
    vs = ValidatorSet([_v(1, 1), _v(2, 2)])
    vs.add(_v(3, 3))
    assert len(vs) == 3 and vs.total_power == 6
    vs.update(_v(2, 5))
    assert vs.total_power == 9
    vs.remove(_v(1).address)
    assert len(vs) == 2
    assert vs.index_of(_v(3).address) == 1


def test_hash_changes_with_set():
    vs = ValidatorSet([_v(1), _v(2)])
    h1 = vs.hash()
    vs.add(_v(3))
    assert vs.hash() != h1


def test_device_arrays():
    vs = ValidatorSet([_v(2, 5), _v(1, 3)])
    keys, powers = vs.device_arrays()
    assert keys.shape == (2, 32) and keys.dtype == np.uint8
    assert powers.tolist() == [3, 5]  # address-sorted
    assert keys[0, 0] == 1 and keys[1, 0] == 2


def test_rotation_proportional_to_power():
    vs = ValidatorSet([_v(1, 1), _v(2, 2), _v(3, 3)])
    rot = ProposerRotation(vs)
    counts = [0, 0, 0]
    for _ in range(600):
        counts[rot.step()] += 1
    assert counts == [100, 200, 300]


def test_rotation_deterministic_and_table_aligned():
    vs = ValidatorSet([_v(1, 1), _v(2, 2)])
    t1 = proposer_table(vs, 4, 3)
    t2 = proposer_table(vs, 4, 3)
    assert (t1 == t2).all()
    # start_height offsets into the same global sequence
    t3 = proposer_table(vs, 2, 3, start_height=2)
    assert (t1[2:] == t3).all()


def test_validator_key_length_enforced():
    import pytest
    with pytest.raises(ValueError):
        Validator(b"\x01" * 33, 1)
    with pytest.raises(ValueError):
        Validator(b"\x01" * 31, 1)
    with pytest.raises(ValueError):
        Validator(b"\x01" * 32, -1)


def test_rotation_survives_set_mutation():
    vs = ValidatorSet([_v(1, 1), _v(2, 1)])
    rot = ProposerRotation(vs)
    rot.step()
    vs.add(_v(3, 1))
    assert 0 <= rot.step() < 3  # no IndexError; new validator joins rotation
    vs.remove(_v(1).address)
    assert 0 <= rot.step() < 2
