"""Static invariant analyzer (agnes_tpu/analysis, ISSUE 4) — the
analyzer ANALYZED: every pass must demonstrably catch its seeded
negative fixture and run clean on the real repo.

Everything here is CPU-cheap by construction: abstract tracing only
(jax .trace()/.lower(), never .compile()), registry-stubbed device
dispatch for the pipeline tests, and AST fixtures as source strings —
the heavy Ed25519-bearing traces are exercised by the ci.sh analyzer
gate (scripts/agnes_lint.py --pass all), not here."""

import ast
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agnes_tpu.analysis import jaxpr_audit, lint, lockcheck, retrace
from agnes_tpu.device import registry
from agnes_tpu.device.encoding import I32, DeviceMessage
from agnes_tpu.serve.batcher import ShapeLadder
from agnes_tpu.utils.metrics import (
    ANALYSIS_ENTRIES_AUDITED,
    RETRACE_UNEXPECTED,
    Metrics,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- registry -----------------------------------------------------------------

def test_registry_enumerates_every_entry():
    """The single name -> entry table the driver, warmup, auditor and
    tripwire all share: the canonical entries are present, donated
    twins declare their donate_argnums, sharded entries carry a
    factory."""
    specs = {s.name: s for s in registry.entries()}
    for name in ("consensus_step", "consensus_step_seq",
                 "consensus_step_seq_donated",
                 "consensus_step_seq_signed",
                 "consensus_step_seq_signed_donated",
                 "consensus_step_seq_signed_dense",
                 "consensus_step_seq_signed_dense_donated",
                 "honest_heights", "sharded_step", "sharded_step_seq",
                 "sharded_step_seq_signed", "sharded_honest_heights"):
        assert name in specs, name
    assert specs["consensus_step_seq_donated"].donated == (0, 1)
    assert specs["consensus_step_seq"].donated == ()
    assert specs["sharded_step_seq_signed"].sharded
    assert specs["sharded_step_seq_signed"].factory is not None
    # aux import-time jits are registered too (the LINT002 contract)
    for name in ("add_votes", "apply_batch", "verify_batch",
                 "verify_batch_msm", "pallas_verify"):
        assert name in specs, name
        assert not specs[name].hot


def test_registry_override_restores():
    stub = object()
    orig = registry.get("consensus_step").jit
    with registry.override("consensus_step", jit=stub):
        assert registry.jit_entry("consensus_step") is stub
    assert registry.jit_entry("consensus_step") is orig


# -- jaxpr audit: donation ----------------------------------------------------

def test_donation_audit_clean_on_donated_seq():
    """The donated unsigned sequence entry lowers with one aliasing
    attr per state/tally leaf (17)."""
    rep = jaxpr_audit.audit(names=["consensus_step_seq_donated"])
    assert rep.ok, [str(f) for f in rep.findings]
    (entry,) = [e for e in rep.entries
                if e.entry == "consensus_step_seq_donated"]
    assert entry.aliased == 17


def test_donation_audit_catches_undonated_twin():
    """A twin REGISTERED as donated whose jit silently lost its
    donate_argnums (here: deliberately swapped for the non-donated
    jit) must be flagged — zero aliasing attrs in the lowered text."""
    undonated = registry.get("consensus_step_seq").jit
    with registry.override("consensus_step_seq_donated",
                           jit=undonated):
        rep = jaxpr_audit.audit(names=["consensus_step_seq_donated"])
    assert not rep.ok
    assert any(f.code == "AUD001" for f in rep.findings), \
        [str(f) for f in rep.findings]


# -- jaxpr audit: collective census ------------------------------------------

def test_collective_census_counts_quorum_psums():
    """The sharded step's only communication is the tally's quorum
    reductions — a nonzero, known-small psum census over the val
    axis."""
    m = Metrics()
    rep = jaxpr_audit.audit(names=["sharded_step"], metrics=m)
    assert rep.ok, [str(f) for f in rep.findings]
    (entry,) = rep.entries
    assert sum(entry.collectives.values()) > 0
    assert m.counters[ANALYSIS_ENTRIES_AUDITED] == 1


def _evil_signed_factory(mesh, advance_height=False, verify_chunk=None,
                         donate=False):
    """A sharded-signed stand-in that ADDS a collective when chunked —
    the exact regression AUD002 (zero-added-collectives per chunk)
    exists to catch."""
    from jax.sharding import PartitionSpec as P

    from agnes_tpu.parallel.mesh import VAL_AXIS
    from agnes_tpu.parallel.sharded import _shard_map

    def inner(p):
        s = jax.lax.psum(p, VAL_AXIS)
        if verify_chunk:
            s = s + jax.lax.psum(p * 2, VAL_AXIS)   # the injected one
        return s

    sm = _shard_map(inner, mesh=mesh, in_specs=P(VAL_AXIS),
                    out_specs=P(), check_vma=False)

    def fn(state, tally, exts, phases, dense, powers, total, pf, pv):
        return sm(powers)

    return jax.jit(fn)


def test_census_catches_injected_collective(monkeypatch):
    """Chunking the fused verify must add ZERO collectives; a factory
    whose chunked build psums once more is flagged (AUD002)."""
    monkeypatch.setitem(
        jaxpr_audit.ENTRY_STATICS, "sharded_step_seq_signed",
        {"advance_height": False, "verify_chunk": None,
         "donate": False})
    with registry.override("sharded_step_seq_signed",
                           factory=_evil_signed_factory):
        rep = jaxpr_audit.audit(names=["sharded_step_seq_signed"])
    assert any(f.code == "AUD002" for f in rep.findings), \
        [str(f) for f in rep.findings]


# -- jaxpr audit: host callbacks + dtype policy -------------------------------

def test_audit_catches_host_callback():
    """A stray jax.debug.callback in a hot-path entry is a host
    round-trip per dispatch — AUD003."""
    def leaky(state, tally, ext, phase, powers, total, pf, pv,
              axis_name=None, advance_height=False):
        jax.debug.callback(lambda x: None, state.round)
        return state

    with registry.override("consensus_step",
                           jit=jax.jit(leaky, static_argnames=(
                               "axis_name", "advance_height"))):
        rep = jaxpr_audit.audit(names=["consensus_step"])
    assert any(f.code == "AUD003" for f in rep.findings), \
        [str(f) for f in rep.findings]


def test_audit_catches_float64_leak():
    """A float64 aval anywhere in an entry's graph violates the dtype
    policy (x64 is off by design; a wide float means an accidental
    promotion upstream) — AUD004."""
    from jax.experimental import enable_x64

    def leaky(state, tally, ext, phase, powers, total, pf, pv,
              axis_name=None, advance_height=False):
        return state.round.astype(jnp.float64) * 2.0

    with enable_x64(), registry.override(
            "consensus_step",
            jit=jax.jit(leaky, static_argnames=(
                "axis_name", "advance_height"))):
        rep = jaxpr_audit.audit(names=["consensus_step"])
    assert any(f.code == "AUD004" for f in rep.findings), \
        [str(f) for f in rep.findings]


# -- retrace tripwire ---------------------------------------------------------

def test_sentinel_armed_fires_on_unexpected_signature():
    m = Metrics()
    s = retrace.RetraceSentinel(metrics=m)
    a = np.zeros((4, 2), np.int32)
    sig = retrace.signature((a,), statics=(False, 8))
    s.observe("e", sig)                 # learning: becomes expected
    s.arm()
    s.observe("e", sig)                 # expected: silent
    off = retrace.signature((np.zeros((24, 2), np.int32),),
                            statics=(False, 8))
    with pytest.raises(retrace.RetraceError):
        s.observe("e", off)
    assert m.counters[RETRACE_UNEXPECTED] == 1
    assert m.counters[ANALYSIS_ENTRIES_AUDITED] == 1
    assert s.report()["unexpected"] == 1


def test_sentinel_catches_sharding_variant_double_compile():
    """The PR 3 class: SAME shapes dispatched under two different
    shardings keys two jit cache entries for one graph.  The sentinel
    fails on the second variant even UNARMED."""
    m = Metrics()
    s = retrace.RetraceSentinel(metrics=m)
    host = np.zeros((4,), np.int32)          # sharding key "host"
    dev = jnp.zeros((4,), jnp.int32)         # SingleDeviceSharding
    s.observe("e", retrace.signature((host,)))
    with pytest.raises(retrace.RetraceError) as ei:
        s.observe("e", retrace.signature((dev,)))
    assert "double-compile" in str(ei.value)
    assert m.counters[RETRACE_UNEXPECTED] == 1


def test_warmup_coverage_proof():
    """Static no-live-compile proof: the default warmup plan (P in
    {2, 3} x every rung) covers every dispatchable signed shape; a
    plan missing P=2 (deadline-closed single-class batches) does
    not."""
    ladder = ShapeLadder.plan(4, 8, min_rung=8, max_votes=64)
    assert retrace.warmup_covers(ladder, n_phases=(2, 3))
    assert retrace.warmup_covers(ladder, n_phases=(2, 3), dense=True)
    assert not retrace.warmup_covers(ladder, n_phases=(3,))
    findings = retrace.coverage_findings(ladder, n_phases=(3,))
    assert findings and findings[0].code == "RET001"
    # ISSUE 5 split-rung dispatch: a dedup-enabled service also
    # dispatches the UNSIGNED sequence entries (one shape per P) for
    # its pre-verified stream — covered by the cache-enabled warmup
    assert retrace.warmup_covers(ladder, n_phases=(2, 3), dedup=True)
    assert ("unsigned", 2) in retrace.dispatchable_shapes(ladder,
                                                          dedup=True)
    assert not retrace.warmup_covers(ladder, n_phases=(3,), dedup=True)


def _stub_signed_jit(state, tally, exts, phases, lanes, powers, total,
                     pf, pv, advance_height=False, verify_chunk=None):
    """Shape-faithful stand-in for the fused signed step: returns the
    carried state/tally untouched and all-NONE messages — zero XLA
    compiles, so the retrace test runs inside the cheap tier."""
    from agnes_tpu.device.step import N_STAGES, SignedStepOutputs

    P, I = phases.mask.shape[:2]
    z = jnp.zeros((P, N_STAGES, I), I32)
    return SignedStepOutputs(
        state=state, tally=tally,
        msgs=DeviceMessage(tag=z, round=z, value=z, aux=z),
        n_rejected=jnp.zeros((), I32))


def test_retrace_silent_across_warmup_and_serve_tick():
    """DeviceDriver(audit=True) + ServePipeline.warmup(): the armed
    sentinel stays silent across a full warmup + a real serve tick
    (every dispatched signature was warmed), then fires on an
    off-ladder lane shape.  Dispatch is registry-stubbed: the
    machinery under test is the signature discipline, not XLA."""
    from agnes_tpu.bridge import VoteBatcher
    from agnes_tpu.bridge.native_ingest import pack_wire_votes
    from agnes_tpu.device.step import SignedLanes
    from agnes_tpu.harness.device_driver import DeviceDriver
    from agnes_tpu.harness.fixtures import (
        deterministic_seeds,
        validator_pubkeys,
    )
    from agnes_tpu.serve import VoteService

    I, V = 2, 8
    pubkeys = validator_pubkeys(deterministic_seeds(V))
    d = DeviceDriver(I, V, advance_height=True, defer_collect=True,
                     audit=True)
    bat = VoteBatcher(I, V, n_slots=4)
    ladder = ShapeLadder.plan(I, V, max_votes=16, min_rung=8)
    svc = VoteService(
        d, bat, pubkeys, capacity=64, target_votes=16, max_delay_s=0.0,
        ladder=ladder,
        window_predictor=lambda: (np.zeros(I, np.int64),
                                  np.zeros(I, np.int64)))
    with registry.override("consensus_step_seq_signed_donated",
                           jit=_stub_signed_jit):
        warmed = svc.pipeline.warmup()
        assert warmed == 2 * len(ladder.rungs)     # P in {2,3} x rungs
        assert d.sentinel.armed
        expected = len(d.sentinel.expected)

        # one real tick: 8 prevotes + 8 precommits -> ONE build
        # (entry + both classes = P 3) padded onto rung 16 — warmed
        inst = np.repeat(np.arange(I), 4)
        val = np.tile(np.arange(4), I)
        n = len(inst)
        wire = b"".join(
            pack_wire_votes(inst, val, np.zeros(n), np.zeros(n),
                            np.full(n, typ), np.full(n, 7))
            for typ in (0, 1))
        assert svc.submit(wire).accepted == 16
        svc.pump()                     # stages the build
        svc.pump()                     # dispatches it — must be silent
        assert svc.pipeline.dispatched_batches == 1
        assert d.sentinel.report()["unexpected"] == 0
        assert len(d.sentinel.expected) == expected  # nothing new

        # off-ladder shape: 24 lanes is no rung — fails LOUDLY before
        # any dispatch, and bumps the counter
        r = 24
        lanes = SignedLanes(
            pub=jnp.zeros((r, 32), jnp.int32),
            sig=jnp.zeros((r, 64), jnp.int32),
            blocks=jnp.zeros((r, 1, 32), jnp.uint32),
            phase_idx=jnp.full(r, 3, jnp.int32),
            inst=jnp.zeros(r, jnp.int32), val=jnp.zeros(r, jnp.int32),
            real=jnp.zeros(r, bool))
        phases = [svc.pipeline._entry_phase(np.zeros(I, np.int64))] * 3
        with pytest.raises(retrace.RetraceError):
            d.step_async(phases, lanes)
    assert d.sentinel.metrics.counters[RETRACE_UNEXPECTED] == 1


def _stub_seq_jit(state, tally, exts, phases, powers, total, pf, pv,
                  advance_height=False, axis_name=None):
    """Shape-faithful stand-in for the UNSIGNED fused sequence (the
    split-rung dispatch's pre-verified entry) — zero XLA compiles."""
    from agnes_tpu.device.step import N_STAGES, StepOutputs

    P, I = phases.mask.shape[:2]
    z = jnp.zeros((P, N_STAGES, I), I32)
    return StepOutputs(
        state=state, tally=tally,
        msgs=DeviceMessage(tag=z, round=z, value=z, aux=z))


def test_retrace_dedup_warmup_arms_unsigned_entries():
    """ISSUE 5 acceptance (static half): a dedup-enabled service's
    warmup precompiles AND tripwire-arms the unsigned sequence
    entries alongside the signed rungs, so a burst of dedup-cache
    hits (pre-verified ticks riding `consensus_step_seq_donated`)
    dispatches inside the armed expected set — silently.  Registry-
    stubbed: zero compiles."""
    from agnes_tpu.bridge import VoteBatcher
    from agnes_tpu.bridge.native_ingest import pack_wire_votes
    from agnes_tpu.harness.device_driver import DeviceDriver
    from agnes_tpu.harness.fixtures import (
        deterministic_seeds,
        validator_pubkeys,
    )
    from agnes_tpu.serve import VerifiedCache, VoteService

    I, V = 2, 8
    pubkeys = validator_pubkeys(deterministic_seeds(V))
    d = DeviceDriver(I, V, advance_height=True, defer_collect=True,
                     audit=True)
    bat = VoteBatcher(I, V, n_slots=4)
    ladder = ShapeLadder.plan(I, V, max_votes=16, min_rung=8)
    svc = VoteService(
        d, bat, pubkeys, capacity=64, target_votes=16, max_delay_s=0.0,
        ladder=ladder, dedup_cache=VerifiedCache(),
        window_predictor=lambda: (np.zeros(I, np.int64),
                                  np.zeros(I, np.int64)))
    with registry.override("consensus_step_seq_signed_donated",
                           jit=_stub_signed_jit), \
            registry.override("consensus_step_seq_donated",
                              jit=_stub_seq_jit):
        warmed = svc.pipeline.warmup()
        # signed P in {2,3} x rungs PLUS unsigned P in {2,3}
        assert warmed == 2 * len(ladder.rungs) + 2
        assert d.sentinel.armed

        inst = np.repeat(np.arange(I), 4)
        val = np.tile(np.arange(4), I)
        n = len(inst)
        wire = b"".join(
            pack_wire_votes(inst, val, np.zeros(n), np.zeros(n),
                            np.full(n, typ), np.full(n, 7))
            for typ in (0, 1))
        # fresh tick: signed dispatch (warmed), then settle -> cached
        assert svc.submit(wire).accepted == 16
        svc.pump()
        svc.pump()
        svc.poll_decisions()
        assert len(svc.cache) == 16
        # the gossip re-delivery: pre-verified tick on the UNSIGNED
        # entry — in the armed set, so the sentinel stays silent
        assert svc.submit(wire).pre_verified == 16
        svc.pump()
        svc.pump()
        assert svc.pipeline.preverified_builds == 1
        assert d.sentinel.report()["unexpected"] == 0
    assert d.sentinel.metrics.counters.get(RETRACE_UNEXPECTED, 0) == 0


# -- lockcheck ----------------------------------------------------------------

def test_lockcheck_clean_on_repo():
    findings = lockcheck.check_paths(lockcheck.default_paths(REPO))
    assert findings == [], [str(f) for f in findings]


def test_scan_roots_derived_from_package_tree():
    """ISSUE 9 satellite: the repo-wide passes derive their scan roots
    from the package tree — no hand-maintained list to rot.  The
    post-PR4 modules the old lockcheck list missed must be covered,
    and a BRAND-NEW module dropped anywhere in the package must be
    scanned the moment the file exists (both by the shared derivation
    and by the lockcheck rules themselves)."""
    mods = lint.package_modules(REPO)
    for required in ("agnes_tpu/analysis/admission_mc.py",
                     "agnes_tpu/utils/flightrec.py",
                     "agnes_tpu/utils/metrics_http.py",
                     # ISSUE 19 satellite: the distributed plane
                     # (PRs 15/17) landed after this test was written
                     # — pin that the derivation keeps covering it
                     "agnes_tpu/distributed/elastic.py",
                     "agnes_tpu/distributed/membership.py",
                     "agnes_tpu/distributed/pod.py",
                     "agnes_tpu/analysis/schedcheck.py"):
        assert required in mods, required
    assert [os.path.join(REPO, m) for m in mods] == \
        lockcheck.default_paths(REPO)

    new_mod = os.path.join(REPO, "agnes_tpu", "utils",
                           "_scanroot_probe_delete_me.py")
    assert not os.path.exists(new_mod)
    try:
        with open(new_mod, "w") as fh:
            fh.write("import threading\n"
                     "lock = threading.Lock()\n"
                     "def f():\n"
                     "    lock.acquire()\n")
        rel = os.path.relpath(new_mod, REPO)
        assert rel in lint.package_modules(REPO)
        findings = lockcheck.check_paths(lockcheck.default_paths(REPO))
        assert any(f.code == "LOCK001" and rel in f.where
                   for f in findings), [str(f) for f in findings]
    finally:
        os.remove(new_mod)


def test_hot_path_map_rot_is_a_finding():
    """A HOT_PATHS key naming a vanished module is reported, not
    silently skipped (the drift the old `continue` hid)."""
    findings = lint.check_hot_paths(
        REPO, {"agnes_tpu/serve/_no_such_module.py": {"stage"}})
    assert len(findings) == 1 and findings[0].code == "LINT001"
    assert "rotted" in findings[0].message


_BARE_ACQUIRE = """
import threading
lock = threading.Lock()
def f():
    lock.acquire()
    work()
    lock.release()
"""

_INVERSION = """
class S:
    def good(self):
        with self._admission:
            close()
        with self._device:
            pump()
    def bad(self):
        with self._device:
            with self._admission:      # device -> admission: inverted
                close()
"""

_ADMISSION_DISPATCH = """
class S:
    def bad(self):
        with self._admission:
            self.driver.step_async(phases)
"""

_NESTED_HOLD = """
class S:
    def bad(self):
        with self._admission:
            with self._device:
                pump()
"""

_NESTED_HOLD_PRAGMA = """
class S:
    def quiescent(self):
        with self._admission, self._device:  # lockcheck: allow (threads joined)
            pump()
"""


def test_lockcheck_flags_synthetic_fixtures():
    codes = [f.code for f in lockcheck.check_source(_BARE_ACQUIRE)]
    assert codes == ["LOCK001", "LOCK001"]
    codes = [f.code for f in lockcheck.check_source(_INVERSION)]
    assert codes == ["LOCK002"]
    codes = [f.code for f in lockcheck.check_source(_ADMISSION_DISPATCH)]
    assert codes == ["LOCK003"]
    codes = [f.code for f in lockcheck.check_source(_NESTED_HOLD)]
    assert codes == ["LOCK004"]
    assert lockcheck.check_source(_NESTED_HOLD_PRAGMA) == []


def test_instrumented_lock_order():
    """Runtime twin of LOCK002/LOCK004: acquiring out of rank order
    raises and records."""
    st = lockcheck.LockOrderState()
    adm = lockcheck.InstrumentedLock("adm", 0, st)
    dev = lockcheck.InstrumentedLock("dev", 1, st)
    with adm:
        pass
    with dev:                          # in isolation: fine
        with pytest.raises(AssertionError):
            with adm:                  # inversion: caught live
                pass
    assert len(st.violations) == 1
    assert st.acquisitions == 2


# -- repo lint ----------------------------------------------------------------

def test_lint_clean_on_repo():
    findings = lint.check_repo(REPO)
    assert findings == [], [str(f) for f in findings]


_HOT_SYNC = """
class P:
    def stage(self, batch):
        x = np.asarray(self.driver.state.height)
        self.driver.block_until_ready()
        return float(x)
    def cold(self):
        return np.asarray(self.anything)    # not a hot function
"""

_HOT_SYNC_PRAGMA = """
class P:
    def stage(self, batch):
        x = np.asarray(batch.cols)  # lint: allow (host-built columns)
        return x
"""


def test_lint_hot_path_sync_fixture(tmp_path):
    rel = "agnes_tpu/serve/pipeline.py"
    target = tmp_path / rel
    target.parent.mkdir(parents=True)
    target.write_text(_HOT_SYNC)
    # scope to the fixture's one file: the other default HOT_PATHS
    # keys don't exist under tmp_path and would (correctly) report rot
    one = {rel: lint.HOT_PATHS[rel]}
    findings = lint.check_hot_paths(str(tmp_path), hot_paths=one)
    assert [f.code for f in findings] == ["LINT001"] * 3
    target.write_text(_HOT_SYNC_PRAGMA)
    assert lint.check_hot_paths(str(tmp_path), hot_paths=one) == []


_ROGUE_JIT = """
import jax
def f(x):
    return x
rogue_jit = jax.jit(f)
"""


def test_lint_catches_unregistered_import_time_jit(tmp_path):
    pkg = tmp_path / "agnes_tpu"
    pkg.mkdir()
    (pkg / "rogue.py").write_text(_ROGUE_JIT)

    class FakeMod:
        rogue_jit = object()

    importer = lambda name: FakeMod()      # noqa: E731
    findings = lint.check_import_time_jits(
        str(tmp_path), registered_check=lambda obj: False,
        importer=importer)
    assert [f.code for f in findings] == ["LINT002"]
    # the same jit, "registered": sanctioned
    assert lint.check_import_time_jits(
        str(tmp_path), registered_check=lambda obj: True,
        importer=importer) == []


_UNHASHABLE_STATIC = """
def f():
    return entry(x, verify_chunk=[1, 2])
"""


def test_lint_catches_unhashable_static_literal(tmp_path):
    pkg = tmp_path / "agnes_tpu"
    pkg.mkdir()
    (pkg / "m.py").write_text(_UNHASHABLE_STATIC)
    findings = lint.check_static_kwargs(str(tmp_path))
    assert [f.code for f in findings] == ["LINT003"]


# -- CLI ----------------------------------------------------------------------

def test_cli_locks_and_retrace_passes():
    """scripts/agnes_lint.py end-to-end on its two cheap passes: exit
    0, parseable JSON report, both marked clean."""
    import json
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "agnes_lint.py"),
         "--pass", "locks", "--json"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-800:]
    rep = json.loads(out.stdout.strip().splitlines()[-1])
    assert rep["ok"] and rep["passes"]["locks"]["findings"] == 0

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "agnes_lint.py"),
         "--pass", "retrace", "--json"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-800:]
    rep = json.loads(out.stdout.strip().splitlines()[-1])
    assert rep["ok"] and rep["passes"]["retrace"]["covered"]
