"""Cross-plane differential fuzz: host Network vs bridge+device replay.

One seeded randomized Byzantine schedule (honest/silent/equivocator/
nil-flood mixes, partition/heal) drives the host plane; every node's
exact processing stream is then replayed through the production device
plane (VoteBatcher -> fused device step, harness/replay.py).  The
invariant: identical decisions per (node, height) — the reference's
testability argument (README.md:8-14) applied across the two planes,
which share the state machine but NOT the tally/event ordering
(device/step.py stages 3-4 re-query cursor vs core/executor.py
_requery) — exactly where a divergence would hide.
"""

import os

import numpy as np
import pytest

from agnes_tpu.harness import Network, NodeSpec, replay_trace, trace_network

N_SEEDS = 100

_SEED_CACHE = {}


def _run_seed(seed: int):
    """Generate + run one schedule on the host plane; return the net,
    the per-node traces, and the scenario descriptor.  Cached per seed
    (deterministic) so the coverage test reuses the runs the
    parametrized differential already paid for."""
    if seed in _SEED_CACHE:
        return _SEED_CACHE[seed]
    rng = np.random.default_rng(seed)
    n = int(rng.choice([4, 4, 4, 7]))
    f_max = (n - 1) // 3
    behaviors = ["honest"] * n
    for i in rng.choice(n, size=int(rng.integers(0, f_max + 1)),
                        replace=False):
        behaviors[i] = str(rng.choice(["silent", "equivocator",
                                       "nil_flood"]))
    net = Network(n=n, specs=[NodeSpec(behavior=b) for b in behaviors])
    traces = trace_network(net)
    scenario = "plain"
    net.start()
    if rng.random() < 0.35:
        # random split (groups need not lack quorum: a 3/1 split decides
        # on the majority side mid-partition, the 2/2 split stalls)
        perm = rng.permutation(n)
        cut = int(rng.integers(1, n))
        g1, g2 = [int(x) for x in perm[:cut]], [int(x) for x in perm[cut:]]
        net.partition(g1, g2)
        scenario = f"partition{g1}|{g2}"
        try:
            net.run_until(lambda: net.decided(0), max_iters=25)
        except AssertionError as e:
            assert "predicate" in str(e), e   # stall, not a crash
        net.heal()
    net.run_until(lambda: net.decided(0))
    _SEED_CACHE[seed] = (net, traces, scenario)
    return _SEED_CACHE[seed]


def _compare(net, traces, scenario, seed):
    # behaviors are indexed like nodes (Network sorts specs with the set)
    for j, node in enumerate(net.nodes):
        rep = replay_trace(traces[j], n_validators=net.n)
        host = node.decided.get(0)
        ctx = (f"seed={seed} node={j} "
               f"behavior={net.specs[j].behavior} scenario={scenario}")
        if host is None:
            assert not rep.decided, f"{ctx}: device decided, host did not"
            continue
        assert rep.decided, f"{ctx}: host decided {host}, device did not"
        assert rep.value == host.value, (
            f"{ctx}: value {rep.value} != host {host.value}")
        assert rep.round == host.round, (
            f"{ctx}: round {rep.round} != host {host.round}")
        # evidence: the device must never flag a validator the host
        # plane has no equivocation evidence for (slashing must not
        # rest on a plane-specific artifact).  The device may MISS
        # equivocations the host catches (e.g. conflicting votes that
        # arrive after its window rotated past the round).
        host_ev = {e.validator for e in node.all_equivocations()}
        assert rep.equivocators <= host_ev, (
            f"{ctx}: device flagged {rep.equivocators - host_ev} "
            f"without host evidence")


# -- regression corpus (ISSUE 6): model-checker schedules FIRST ------------
#
# tests/corpus/*.json holds ddmin-minimized schedules the bounded model
# checker (analysis/modelcheck.py) flagged as coverage milestones or
# mutation counterexamples.  They replay deterministically — unlike the
# random fuzz below, a corpus failure bisects to one short, named
# schedule — and they run BEFORE the seeds (definition order) so a
# cross-plane regression surfaces in the cheapest, most attributable
# case available.

_CORPUS_DIR = os.path.join(os.path.dirname(__file__), "corpus")


def _load_corpus():
    from agnes_tpu.analysis import modelcheck as mc

    return mc.load_corpus(_CORPUS_DIR)


@pytest.mark.parametrize("entry", _load_corpus(),
                         ids=lambda e: e["name"])
def test_corpus_schedule_replays_identically_on_device_plane(entry):
    """Each corpus schedule runs on the SIGNED, verifying host plane
    under trace taps, then every node's exact processing stream goes
    through the production device path (VoteBatcher -> fused step).
    Decisions must agree per (node, height) — the epoch-boundary
    milestones (ISSUE 9) decide at heights 0 AND 1 across a real
    `set_validators` set change, so the equality here IS the
    host==device-through-an-epoch-boundary acceptance; device evidence
    must be a subset of host evidence (same rule as _compare below)."""
    from agnes_tpu.analysis import modelcheck as mc

    net, results = mc.device_replay_entry(entry)
    exp = entry["expect"]["decided"]
    exp_heights = entry["expect"].get("decided_heights")
    for j, host_decs, rep in results:
        ctx = f"corpus={entry['name']} node={j}"
        # the signed replay must also match the stamped (unsigned,
        # model-checker-time) expectation — crypto must be transparent
        if 0 in host_decs:
            assert [host_decs[0].round, host_decs[0].value] == \
                exp[str(j)], (
                    f"{ctx}: signed host replay diverged from corpus "
                    f"stamp")
        else:
            assert str(j) not in exp, (
                f"{ctx}: corpus stamped a height-0 decision the "
                f"signed host replay did not reach")
        host_hr = {h: [d.round, d.value]
                   for h, d in sorted(host_decs.items())}
        if exp_heights is not None:
            assert host_hr == {int(h): rv for h, rv in
                               exp_heights.get(str(j), {}).items()}, (
                f"{ctx}: signed host per-height decisions diverged "
                f"from corpus stamp")
        dev_hr = {h: [r, v] for h, (r, v) in rep.decisions.items()}
        assert dev_hr == host_hr, (
            f"{ctx}: device decisions {dev_hr} != host {host_hr}")
        host_ev = {e.validator
                   for e in net.nodes[j].all_equivocations()}
        assert rep.equivocators <= host_ev, (
            f"{ctx}: device flagged {rep.equivocators - host_ev} "
            f"without host evidence")


@pytest.mark.parametrize("seed", range(N_SEEDS))
def test_cross_plane_decisions_agree(seed):
    net, traces, scenario = _run_seed(seed)
    _compare(net, traces, scenario, seed)


def test_cross_plane_exercises_all_behaviors_and_partitions():
    """The seed range must actually cover the fault space (a generator
    regression that collapses to all-honest would pass the
    differential vacuously)."""
    rng_hits = {"silent": 0, "equivocator": 0, "nil_flood": 0,
                "partition": 0, "multi_round": 0}
    for seed in range(N_SEEDS):
        net, _, scenario = _run_seed(seed)
        for spec in net.specs:
            if spec.behavior != "honest":
                rng_hits[spec.behavior] += 1
        if scenario.startswith("partition"):
            rng_hits["partition"] += 1
        if any(d.round >= 1 for node in net.nodes
               for d in [node.decided.get(0)] if d is not None):
            rng_hits["multi_round"] += 1
    assert all(v >= 5 for v in rng_hits.values()), rng_hits


def test_cross_plane_commit_from_any_round_via_host_fallback():
    """Force the one path the random fuzz doesn't reach (coverage probe:
    0/496 fallback decisions): the node ROUND_SKIPs to round 2, its
    device tally window rotates past round 0, and only THEN does a +2/3
    precommit quorum for round 0 arrive.  The host executor commits
    from any round (spec line 49); the device plane must reach the same
    decision through the batcher's host fallback -> PRECOMMIT_VALUE ext
    injection (bridge/ingest.py drain_host_events)."""
    from agnes_tpu.core.executor import ConsensusExecutor, WireTimeout
    from agnes_tpu.core.state_machine import TimeoutStep
    from agnes_tpu.core.validators import Validator, ValidatorSet
    from agnes_tpu.crypto import ed25519_ref as ed
    from agnes_tpu.types import Vote, VoteType

    n = 4
    seeds = [bytes([i + 1]) * 32 for i in range(n)]
    vset = ValidatorSet([Validator(ed.keypair(s)[1], 1) for s in seeds])
    # pick a node that does NOT propose rounds 0-2 (its own proposal
    # would change the script; any non-proposer index works the same)
    probe = ConsensusExecutor(vset, index=None, seed=None,
                              get_value=lambda h: 7,
                              verify_signatures=False)
    me = next(i for i in range(n)
              if all(probe.proposer(0, r) != i for r in range(3)))
    ex = ConsensusExecutor(vset, index=me, seed=None,
                           get_value=lambda h: 7,
                           verify_signatures=False)
    trace = []
    orig = ex.execute
    ex.execute = lambda msg: (trace.append(msg), orig(msg))[1]
    ex.start()

    others = [i for i in range(n) if i != me]

    def vote(validator, round_, typ, value):
        ex.execute(Vote(typ=typ, round=round_, value=value,
                        validator=validator, height=0))

    # rounds 0 and 1 die by ROUND_SKIP: f+1 prevotes from the next round
    ex.execute(WireTimeout(0, 0, TimeoutStep.PROPOSE))   # -> own nil prevote
    for v in others[:2]:
        vote(v, 1, VoteType.PREVOTE, 77)                 # skip to round 1
    assert ex.state.round == 1
    for v in others[:2]:
        vote(v, 2, VoteType.PREVOTE, 77)                 # skip to round 2
    assert ex.state.round == 2
    # now the round-0 precommit quorum lands (validators who never
    # precommitted round 0, so nothing is deduped away)
    for v in others:
        vote(v, 0, VoteType.PRECOMMIT, 7)
    host = ex.decided.get(0)
    assert host is not None and host.value == 7 and host.round == 0

    rep = replay_trace(trace, n_validators=n)
    assert rep.decided and rep.value == 7 and rep.round == 0
    assert rep.host_fallback_decisions == 1, (
        "decision must have come through the host-fallback path "
        "(round 0 is outside the rotated device window)")


def test_cross_plane_epoch_table_threading_is_load_bearing():
    """ISSUE 9: the replay must install validator-set epochs through
    the real `set_validators` boundary calls — and the table must
    MATTER.  Height 0 decides under the equal genesis set; at height 1
    the epoch shifts weight 3 onto one peer, so three weight-1
    precommits that would be a head-count quorum hold only 3/6 of the
    live power.  The epoch-aware host does NOT decide height 1 and the
    epoch-threaded device agrees — while the same trace replayed
    WITHOUT the table (the pre-epoch replay) decides height 1, proving
    the threading is load-bearing, not decorative."""
    from agnes_tpu.core.executor import ConsensusExecutor, WireProposal
    from agnes_tpu.core.validators import Validator, ValidatorSet
    from agnes_tpu.crypto import ed25519_ref as ed
    from agnes_tpu.types import Vote, VoteType

    n = 4
    seeds = [bytes([i + 1]) * 32 for i in range(n)]
    vset = ValidatorSet([Validator(ed.keypair(s)[1], 1) for s in seeds])
    probe = ConsensusExecutor(vset, index=None, seed=None,
                              get_value=lambda h: 7,
                              verify_signatures=False)
    p0, p1 = probe.proposer(0, 0), probe.proposer(1, 0)
    me = next(i for i in range(n) if i not in (p0, p1))
    heavy = next(i for i in range(n) if i != me)
    epochs = {1: tuple(3 if i == heavy else 1 for i in range(n))}
    ex = ConsensusExecutor(vset, index=me, seed=None,
                           get_value=lambda h: 7,
                           verify_signatures=False, epochs=epochs)
    trace = []
    orig = ex.execute
    ex.execute = lambda msg: (trace.append(msg), orig(msg))[1]
    ex.start()
    peers = [i for i in range(n) if i != me]
    lights = [i for i in peers if i != heavy]

    def vote(validator, height, round_, typ, value):
        ex.execute(Vote(typ=typ, round=round_, value=value,
                        validator=validator, height=height))

    # height 0: a 3/4 equal-weight peer precommit quorum decides
    # (commit-from-any-round — the decider needs no polka of its own)
    for v in peers:
        vote(v, 0, 0, VoteType.PRECOMMIT, 7)
    assert ex.decided.get(0) is not None and ex.height == 1

    # height 1: proposal + all-peer prevotes (own prevote follows the
    # proposal; the polka is 6/6) -> ex precommits; then only the two
    # LIGHT peers precommit: own 1 + 2 = 3 of the live 6 — no quorum
    ex.execute(WireProposal(height=1, round=0, value=9, pol_round=-1,
                            proposer=p1))
    for v in peers:
        vote(v, 1, 0, VoteType.PREVOTE, 9)
    for v in lights:
        vote(v, 1, 0, VoteType.PRECOMMIT, 9)
    assert ex.decided.get(1) is None

    rep = replay_trace(trace, n_validators=n,
                       epochs={h: list(pw) for h, pw in epochs.items()})
    host_hr = {h: [d.round, d.value] for h, d in ex.decided.items()}
    assert {h: [r, v] for h, (r, v) in rep.decisions.items()} == host_hr
    assert 0 in rep.decisions and 1 not in rep.decisions

    blind = replay_trace(trace, n_validators=n)     # table withheld
    assert 1 in blind.decisions, (
        "without the epoch table the head-count quorum decides height "
        "1 — the set_validators threading is what keeps host == device")


def test_rounds_width_boundary_all_planes_agree():
    """VERDICT r4 next #7: device rounds are int32 while the oracle and
    the C++ core are int64 — prove no plane disagrees on screened-in
    inputs at the 2^31 boundary.  The framework rounds domain is
    [-1, MAX_ROUND] (types.py) and the skip target saturates there on
    every plane: at round == MAX_ROUND a TIMEOUT_PRECOMMIT must PARK
    the instance at MAX_ROUND (int32 +1 would wrap negative, int64
    would widen to 2^31 — either divergence is a consensus fork), and
    commit-from-any-round must still fire at the edge."""
    from agnes_tpu.core import native
    from agnes_tpu.core import state_machine as sm
    from agnes_tpu.core.state_machine import Event, EventTag, Step
    from agnes_tpu.device.encoding import (
        decode_message,
        decode_state,
        encode_event,
        encode_state,
        stack_pytree,
    )
    from agnes_tpu.device.state_machine import apply_batch
    from agnes_tpu.types import MAX_ROUND

    VAL = 7
    cases = []
    for s_round in (MAX_ROUND - 2, MAX_ROUND - 1, MAX_ROUND):
        for step in (Step.PREVOTE, Step.PRECOMMIT):
            state = sm.State(height=1, round=s_round, step=step,
                             locked=None, valid=None)
            # the +1 site: skip target saturates at MAX_ROUND
            cases.append((state, s_round, Event(EventTag.TIMEOUT_PRECOMMIT)))
            # explicit jump straight to the edge
            cases.append((state, MAX_ROUND, Event(EventTag.ROUND_SKIP)))
            # spec line 49 at the edge: decision carries the event round
            cases.append((state, MAX_ROUND,
                          Event(EventTag.PRECOMMIT_VALUE, value=VAL)))
            # lock at the edge round (PolkaValue at Prevote step, eqr)
            cases.append((state, s_round,
                          Event(EventTag.POLKA_VALUE, value=VAL)))

    oracle = [sm.apply(s, r, ev) for (s, r, ev) in cases]
    cpp = [native.native_apply(s, r, ev) for (s, r, ev) in cases]

    batch_state = stack_pytree([encode_state(s) for (s, _, _) in cases])
    batch_event = stack_pytree([encode_event(r, ev) for (_, r, ev) in cases])
    out_state, out_msg = apply_batch(batch_state, batch_event)
    os_ = [np.asarray(x) for x in out_state]
    om_ = [np.asarray(x) for x in out_msg]

    for i, ((s0, r, ev), (exp_s, exp_m)) in enumerate(zip(cases, oracle)):
        assert cpp[i] == (exp_s, exp_m), (
            f"C++ diverges at case {i}: {s0.round=} {ev.tag=}: "
            f"{cpp[i]} != {(exp_s, exp_m)}")
        dev_s = decode_state(
            type(out_state)(*[leaf[i] for leaf in os_]), height=1)
        dev_m = decode_message(type(out_msg)(*[leaf[i] for leaf in om_]))
        exp_cmp = sm.State(height=1, round=exp_s.round, step=exp_s.step,
                           locked=exp_s.locked, valid=exp_s.valid)
        assert dev_s == exp_cmp and dev_m == exp_m, (
            f"device diverges at case {i}: {s0.round=} {ev.tag=}: "
            f"{(dev_s, dev_m)} != {(exp_cmp, exp_m)}")
        # domain invariant: no plane ever leaves [-1, MAX_ROUND]
        assert -1 <= exp_s.round <= MAX_ROUND
        assert -1 <= dev_s.round <= MAX_ROUND

    # the defining case, spelled out: parked at the edge, not wrapped
    edge = sm.State(height=1, round=MAX_ROUND, step=Step.PRECOMMIT,
                    locked=None, valid=None)
    for plane in (sm.apply, native.native_apply):
        s1, m1 = plane(edge, MAX_ROUND, Event(EventTag.TIMEOUT_PRECOMMIT))
        assert s1.round == MAX_ROUND and s1.step == Step.NEW_ROUND
        assert m1 == sm.Message.new_round(MAX_ROUND)
