// ThreadSanitizer stress for the native admission queue (ISSUE 19)
// and the sharded group + phase drain on top of it (ISSUE 20).
//
// The schedule checker (analysis/schedcheck.py) serializes every
// PYTHON-visible yield point of the threaded serve host, but the
// ag_adm_* calls release the GIL for their whole span — their inner
// interleavings are exactly what the cooperative scheduler cannot
// see.  This binary is that other half: the admission queue's shared
// surface (core/native/admission.cpp) under real concurrency, fully
// TSAN-instrumented, in the production threaded-host topology:
//
//   producer threads   ag_adm_submit batches (well-formed + one
//                      malformed lane), then race a mark_verified
//                      back-annotation for their own submit — the
//                      wrapper's dedup-cache flow, which the C side
//                      documents as racing concurrent drains safely
//   drainer thread     the dispatch loop's shape: unlocked depth
//                      read, then a drain sized from it — the C side
//                      must clamp to the live size (the PR 14
//                      review-fix contract: got <= asked, and only
//                      rows [0, got) are real)
//   cold reader        counters / oldest_ts / instance_depth /
//                      capped export, racing everything — the
//                      observability path a bench heartbeat takes
//
// Stage 2 repeats the topology over the ISSUE-20 shard group
// (admission_shards.cpp + admission_phases.cpp): producers fan 96-byte
// records across >= 2 shards through ag_adms_submit (racing
// set_chunk_ts + mark_verified route consumption), while the drainer
// runs the PHASES drain — the fused k-way merge + zero-copy densify
// (ag_adms_drain_phases) — and the cold reader hits the per-shard
// observability surface (shard_depth / shard_counters / oldest_ts /
// export).
//
// Exit 0 = no data race AND the admission taxonomy balances:
// submitted = admitted + rejected, admitted = drained + evicted, and
// the drainer's accumulated row count equals the drained counter
// (no phantom or lost records) — summed across shards in stage 2.
// ci.sh builds this with -fsanitize=thread and runs it as step 1b;
// the plain (uninstrumented) build doubles as a cheap correctness
// test in the python suite.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
void* ag_adm_new(int64_t I, int64_t capacity, int64_t instance_cap,
                 int32_t policy, int32_t with_digests);
void ag_adm_free(void* h);
int64_t ag_adm_submit(void* h, const uint8_t* buf, int64_t nbytes,
                      int64_t* out_counts, uint8_t* out_digests);
void ag_adm_mark_verified(void* h, int64_t seq, const uint8_t* ver,
                          int64_t n);
int64_t ag_adm_depth(void* h);
int64_t ag_adm_instance_depth(void* h, int64_t i);
double ag_adm_oldest_ts(void* h);
void ag_adm_counters(void* h, int64_t* out7);
int64_t ag_adm_drain(void* h, int64_t n, int64_t* inst, int64_t* val,
                     int64_t* hts, int64_t* rnd, int64_t* typ,
                     int64_t* value, uint8_t* sigs, uint8_t* ver,
                     uint8_t* out_dig, double* ts);
int64_t ag_adm_export(void* h, uint8_t* raw, uint8_t* ver, int64_t cap);

// the ISSUE-20 shard group (admission_shards.cpp)
void* ag_adms_new(int64_t n_shards, int64_t I, int64_t capacity,
                  int64_t instance_cap, int32_t policy,
                  int32_t with_digests);
void ag_adms_free(void* h);
int64_t ag_adms_submit(void* h, const uint8_t* buf, int64_t nbytes,
                       int64_t* out_counts, uint8_t* out_digests);
void ag_adms_set_chunk_ts(void* h, int64_t seq, double ts);
void ag_adms_mark_verified(void* h, int64_t seq, const uint8_t* ver,
                           int64_t n);
int64_t ag_adms_depth(void* h);
int64_t ag_adms_shard_depth(void* h, int64_t s);
int64_t ag_adms_instance_depth(void* h, int64_t i);
double ag_adms_oldest_ts(void* h);
void ag_adms_counters(void* h, int64_t* out7);
void ag_adms_shard_counters(void* h, int64_t s, int64_t* out7);
int64_t ag_adms_export(void* h, uint8_t* raw, uint8_t* ver,
                       int64_t cap);
int64_t ag_adms_drain_phases(
    void* h, int64_t n, int64_t* inst, int64_t* val, int64_t* hts,
    int64_t* rnd, int64_t* typ, int64_t* value, uint8_t* sigs,
    uint8_t* ver, uint8_t* out_dig, double* ts,
    const int64_t* win_heights, const int64_t* win_base, int64_t W,
    const int64_t* slot_lut, int64_t S, int64_t V,
    const uint8_t* pubkeys, int64_t lane_floor, int64_t max_votes,
    int64_t phase_offset, int64_t pad_cap, int32_t* ph_slots,
    uint8_t* ph_mask, int64_t* ph_typ, int64_t* ph_counts,
    int32_t* ln_pub, int32_t* ln_sig, uint32_t* ln_blocks,
    int32_t* ln_phase_idx, int32_t* ln_inst, int32_t* ln_val,
    uint8_t* ln_real, int64_t* ln_rows, int64_t* out_meta);
}

namespace {

constexpr int kRecSize = 96;
constexpr int64_t I = 4;
constexpr int64_t kCapacity = 128;
constexpr int64_t kInstanceCap = 64;     // python default: 2*cap/I
constexpr int kProducers = 3;
constexpr int kBatches = 300;
constexpr int kPerBatch = 16;            // 15 well-formed + 1 malformed
constexpr int64_t kDrainMax = 32;

// wire-record packer (the module-top layout of ingest.cpp)
void pack(uint8_t* p, uint32_t inst, uint32_t val, int64_t height,
          int32_t round, uint8_t typ, int64_t value) {
  std::memset(p, 0, kRecSize);
  std::memcpy(p + 0, &inst, 4);
  std::memcpy(p + 4, &val, 4);
  std::memcpy(p + 8, &height, 8);
  std::memcpy(p + 16, &round, 4);
  p[20] = typ;
  p[21] = 1;
  std::memcpy(p + 24, &value, 8);
}

// one drain in the dispatch loop's exact shape: size from an UNLOCKED
// depth read, then trust only the return value
int64_t drain_once(void* h) {
  int64_t n0 = ag_adm_depth(h);
  if (n0 <= 0) return 0;
  int64_t ask = std::min(n0, kDrainMax);
  std::vector<int64_t> inst(ask), val(ask), hts(ask), rnd(ask),
      typ(ask), value(ask);
  std::vector<uint8_t> sigs(ask * 64), ver(ask), dig(ask * 32);
  std::vector<double> ts(ask);
  int64_t got = ag_adm_drain(h, ask, inst.data(), val.data(),
                             hts.data(), rnd.data(), typ.data(),
                             value.data(), sigs.data(), ver.data(),
                             dig.data(), ts.data());
  if (got < 0 || got > ask) {
    std::fprintf(stderr, "drain clamp broken: asked %lld got %lld\n",
                 static_cast<long long>(ask),
                 static_cast<long long>(got));
    std::abort();
  }
  // rows [0, got) must be real records, never uninitialized tail
  for (int64_t k = 0; k < got; ++k) {
    if (inst[k] < 0 || inst[k] >= I) {
      std::fprintf(stderr, "phantom row: inst=%lld at %lld\n",
                   static_cast<long long>(inst[k]),
                   static_cast<long long>(k));
      std::abort();
    }
  }
  return got;
}

}  // namespace

static int run_single() {
  void* h = ag_adm_new(I, kCapacity, kInstanceCap, /*drop_oldest=*/1,
                       /*with_digests=*/1);
  if (!h) { std::fprintf(stderr, "ag_adm_new failed\n"); return 2; }

  std::atomic<int> done{0};
  std::atomic<int64_t> drained_rows{0};

  auto producer = [&](int id) {
    std::vector<uint8_t> buf(kPerBatch * kRecSize);
    std::vector<uint8_t> dig(kPerBatch * 32);
    int64_t counts[5];
    std::vector<uint8_t> mark(kPerBatch);
    for (int b = 0; b < kBatches; ++b) {
      for (int k = 0; k < kPerBatch - 1; ++k) {
        uint32_t inst = static_cast<uint32_t>((b + k) % I);
        uint32_t val = static_cast<uint32_t>((id * 17 + k) % 64);
        pack(buf.data() + k * kRecSize, inst, val, 0, 0, 1, 5);
      }
      // one malformed lane per batch (out-of-range instance id)
      pack(buf.data() + (kPerBatch - 1) * kRecSize, 0xFFFF, 0, 0, 0, 1, 5);
      int64_t seq = ag_adm_submit(h, buf.data(), kPerBatch * kRecSize,
                                  counts, dig.data());
      // dedup-cache back-annotation, racing the drainer — the C side's
      // documented contract: already-drained records are skipped
      if (counts[0] > 0) {
        std::fill(mark.begin(), mark.begin() + counts[0],
                  static_cast<uint8_t>(b & 1));
        ag_adm_mark_verified(h, seq, mark.data(), counts[0]);
      }
    }
    done.fetch_add(1);
  };

  std::vector<std::thread> threads;
  threads.reserve(kProducers + 1);
  for (int p = 0; p < kProducers; ++p) threads.emplace_back(producer, p);

  // cold reader: the observability surface, racing everything
  threads.emplace_back([&] {
    int64_t counters[7];
    std::vector<uint8_t> raw(kCapacity * kRecSize), ver(kCapacity);
    while (done.load() < kProducers) {
      ag_adm_counters(h, counters);
      (void)ag_adm_oldest_ts(h);
      for (int64_t i = 0; i < I; ++i) (void)ag_adm_instance_depth(h, i);
      // export sized from a racy depth read; the C side clamps writes
      int64_t cap = std::min(ag_adm_depth(h), kCapacity);
      if (cap > 0) (void)ag_adm_export(h, raw.data(), ver.data(), cap);
    }
  });

  // drainer on the main thread, racing the producers
  while (done.load() < kProducers) drained_rows += drain_once(h);
  for (auto& t : threads) t.join();
  // residue: everything still queued must drain exactly once
  for (int64_t got; (got = drain_once(h)) > 0;) drained_rows += got;

  int64_t c[7];  // [submitted, admitted, rej_overflow, rej_fairness,
                 //  rej_malformed, evicted, drained]
  ag_adm_counters(h, c);
  const int64_t want_submitted =
      int64_t{kProducers} * kBatches * kPerBatch;
  const int64_t want_malformed = int64_t{kProducers} * kBatches;
  int rc = 0;
  if (c[0] != want_submitted) {
    std::fprintf(stderr, "submitted=%lld want %lld\n",
                 static_cast<long long>(c[0]),
                 static_cast<long long>(want_submitted));
    rc = 1;
  }
  if (c[4] != want_malformed) {
    std::fprintf(stderr, "malformed=%lld want %lld\n",
                 static_cast<long long>(c[4]),
                 static_cast<long long>(want_malformed));
    rc = 1;
  }
  if (c[1] != c[0] - c[2] - c[3] - c[4]) {
    std::fprintf(stderr, "admission taxonomy unbalanced\n");
    rc = 1;
  }
  if (drained_rows.load() != c[6]) {
    std::fprintf(stderr, "drained rows %lld != drained counter %lld "
                 "(phantom/lost records)\n",
                 static_cast<long long>(drained_rows.load()),
                 static_cast<long long>(c[6]));
    rc = 1;
  }
  if (c[1] != c[6] + c[5] || ag_adm_depth(h) != 0) {
    std::fprintf(stderr, "conservation: admitted %lld != drained %lld "
                 "+ evicted %lld (+ depth %lld)\n",
                 static_cast<long long>(c[1]),
                 static_cast<long long>(c[6]),
                 static_cast<long long>(c[5]),
                 static_cast<long long>(ag_adm_depth(h)));
    rc = 1;
  }
  ag_adm_free(h);
  if (rc == 0)
    std::printf("tsan_admission_stress ok: submitted=%lld drained=%lld "
                "evicted=%lld\n",
                static_cast<long long>(c[0]),
                static_cast<long long>(c[6]),
                static_cast<long long>(c[5]));
  return rc;
}

// -- stage 2: the shard group under the PHASES drain (ISSUE 20) --------------

namespace {

constexpr int64_t kShards = 2;
constexpr int64_t V = 64;                // validator-id space
constexpr int64_t S = 4;                 // value slots per instance
constexpr int64_t kPadCap = 32;          // pow2 >= kDrainMax, >= floor

// one phases drain in the dispatch loop's exact shape: ask sized from
// an unlocked group-depth read, both the clamp and the permutation
// validated on the return
int64_t drain_phases_once(void* g, const int64_t* win_h,
                          const int64_t* win_b, const int64_t* lut,
                          const uint8_t* pks) {
  int64_t n0 = ag_adms_depth(g);
  if (n0 <= 0) return 0;
  int64_t ask = std::min(n0, kDrainMax);
  std::vector<int64_t> inst(ask), val(ask), hts(ask), rnd(ask),
      typ(ask), value(ask), rows(kPadCap), meta(8);
  std::vector<uint8_t> sigs(ask * 64), ver(ask), dig(ask * 32);
  std::vector<double> ts(ask);
  std::vector<int32_t> ph_slots(2 * I * V);
  std::vector<uint8_t> ph_mask(2 * I * V);
  std::vector<int64_t> ph_typ(2), ph_counts(2);
  std::vector<int32_t> l_pub(kPadCap * 32), l_sig(kPadCap * 64),
      l_pidx(kPadCap), l_inst(kPadCap), l_val(kPadCap);
  std::vector<uint32_t> l_blocks(kPadCap * 32);
  std::vector<uint8_t> l_real(kPadCap);
  int64_t got = ag_adms_drain_phases(
      g, ask, inst.data(), val.data(), hts.data(), rnd.data(),
      typ.data(), value.data(), sigs.data(), ver.data(), dig.data(),
      ts.data(), win_h, win_b, /*W=*/1, lut, S, V, pks,
      /*lane_floor=*/4, /*max_votes=*/kDrainMax, /*phase_offset=*/1,
      kPadCap, ph_slots.data(), ph_mask.data(), ph_typ.data(),
      ph_counts.data(), l_pub.data(), l_sig.data(), l_blocks.data(),
      l_pidx.data(), l_inst.data(), l_val.data(), l_real.data(),
      rows.data(), meta.data());
  if (got < 0 || got > ask) {
    std::fprintf(stderr, "phases drain clamp broken: asked %lld got "
                 "%lld\n", static_cast<long long>(ask),
                 static_cast<long long>(got));
    std::abort();
  }
  for (int64_t k = 0; k < got; ++k) {
    if (inst[k] < 0 || inst[k] >= I) {
      std::fprintf(stderr, "phantom merged row: inst=%lld at %lld\n",
                   static_cast<long long>(inst[k]),
                   static_cast<long long>(k));
      std::abort();
    }
  }
  if (got > 0 && meta[0] == 1) {
    // a FILLED phase build: counts cover every drained row and the
    // lane permutation stays inside the drained range
    int64_t covered = ph_counts[0] + ph_counts[1];
    if (covered != got || meta[2] != got) {
      std::fprintf(stderr, "phase counts %lld+%lld != drained %lld\n",
                   static_cast<long long>(ph_counts[0]),
                   static_cast<long long>(ph_counts[1]),
                   static_cast<long long>(got));
      std::abort();
    }
    for (int64_t j = 0; j < got; ++j) {
      if (rows[j] < 0 || rows[j] >= got) {
        std::fprintf(stderr, "lane_rows[%lld]=%lld out of [0,%lld)\n",
                     static_cast<long long>(j),
                     static_cast<long long>(rows[j]),
                     static_cast<long long>(got));
        std::abort();
      }
    }
  }
  return got;
}

}  // namespace

static int run_sharded() {
  void* g = ag_adms_new(kShards, I, kCapacity, kInstanceCap,
                        /*drop_oldest=*/1, /*with_digests=*/1);
  if (!g) { std::fprintf(stderr, "ag_adms_new failed\n"); return 2; }

  // a static window every drained record is eligible under: height 0,
  // base round 0, W=1, value 5 interned at slot 0 of every instance
  std::vector<int64_t> win_h(I, 0), win_b(I, 0), lut(I * S, -1);
  for (int64_t i = 0; i < I; ++i) lut[i * S] = 5;
  std::vector<uint8_t> pks(V * 32, 0x42);

  std::atomic<int> done{0};
  std::atomic<int64_t> drained_rows{0};

  auto producer = [&](int id) {
    std::vector<uint8_t> buf(kPerBatch * kRecSize);
    std::vector<uint8_t> dig(kPerBatch * 32);
    int64_t counts[5];
    std::vector<uint8_t> mark(kPerBatch);
    for (int b = 0; b < kBatches; ++b) {
      for (int k = 0; k < kPerBatch - 1; ++k) {
        // spread across BOTH shards (home = inst / (I / kShards))
        uint32_t inst = static_cast<uint32_t>((b + k) % I);
        uint32_t val = static_cast<uint32_t>((id * 17 + k) % V);
        pack(buf.data() + k * kRecSize, inst, val, 0, 0, 1, 5);
      }
      pack(buf.data() + (kPerBatch - 1) * kRecSize, 0xFFFF, 0, 0, 0, 1,
           5);
      int64_t seq = ag_adms_submit(g, buf.data(),
                                   kPerBatch * kRecSize, counts,
                                   dig.data());
      if (counts[0] > 0) {
        ag_adms_set_chunk_ts(g, seq, 1.0 + b);
        // route consumption racing the merged drain (the wrapper's
        // ALWAYS-mark contract)
        std::fill(mark.begin(), mark.begin() + counts[0],
                  static_cast<uint8_t>(0));
        ag_adms_mark_verified(g, seq, mark.data(), counts[0]);
      }
    }
    done.fetch_add(1);
  };

  std::vector<std::thread> threads;
  threads.reserve(kProducers + 1);
  for (int p = 0; p < kProducers; ++p) threads.emplace_back(producer, p);

  // cold reader: the per-shard observability surface, racing everything
  threads.emplace_back([&] {
    int64_t counters[7];
    std::vector<uint8_t> raw(kCapacity * kRecSize), ver(kCapacity);
    while (done.load() < kProducers) {
      ag_adms_counters(g, counters);
      for (int64_t s = 0; s < kShards; ++s) {
        (void)ag_adms_shard_depth(g, s);
        ag_adms_shard_counters(g, s, counters);
      }
      (void)ag_adms_oldest_ts(g);
      for (int64_t i = 0; i < I; ++i)
        (void)ag_adms_instance_depth(g, i);
      int64_t cap = std::min(ag_adms_depth(g), kCapacity);
      if (cap > 0) (void)ag_adms_export(g, raw.data(), ver.data(), cap);
    }
  });

  // phase drainer on the main thread: the fused k-way merge + densify
  while (done.load() < kProducers)
    drained_rows += drain_phases_once(g, win_h.data(), win_b.data(),
                                      lut.data(), pks.data());
  for (auto& t : threads) t.join();
  for (int64_t got; (got = drain_phases_once(
           g, win_h.data(), win_b.data(), lut.data(),
           pks.data())) > 0;)
    drained_rows += got;

  int64_t c[7];
  ag_adms_counters(g, c);
  const int64_t want_submitted =
      int64_t{kProducers} * kBatches * kPerBatch;
  const int64_t want_malformed = int64_t{kProducers} * kBatches;
  int rc = 0;
  if (c[0] != want_submitted) {
    std::fprintf(stderr, "sharded submitted=%lld want %lld\n",
                 static_cast<long long>(c[0]),
                 static_cast<long long>(want_submitted));
    rc = 1;
  }
  if (c[4] != want_malformed) {
    std::fprintf(stderr, "sharded malformed=%lld want %lld\n",
                 static_cast<long long>(c[4]),
                 static_cast<long long>(want_malformed));
    rc = 1;
  }
  if (c[1] != c[0] - c[2] - c[3] - c[4]) {
    std::fprintf(stderr, "sharded admission taxonomy unbalanced\n");
    rc = 1;
  }
  if (drained_rows.load() != c[6]) {
    std::fprintf(stderr, "sharded drained rows %lld != drained "
                 "counter %lld (phantom/lost records)\n",
                 static_cast<long long>(drained_rows.load()),
                 static_cast<long long>(c[6]));
    rc = 1;
  }
  if (c[1] != c[6] + c[5] || ag_adms_depth(g) != 0) {
    std::fprintf(stderr, "sharded conservation: admitted %lld != "
                 "drained %lld + evicted %lld (+ depth %lld)\n",
                 static_cast<long long>(c[1]),
                 static_cast<long long>(c[6]),
                 static_cast<long long>(c[5]),
                 static_cast<long long>(ag_adms_depth(g)));
    rc = 1;
  }
  // per-shard counters must SUM to the group's (the wrapper's
  // shard_counters gauges report against this)
  int64_t sum7[7] = {0, 0, 0, 0, 0, 0, 0};
  for (int64_t s = 0; s < kShards; ++s) {
    int64_t sc[7];
    ag_adms_shard_counters(g, s, sc);
    for (int j = 0; j < 7; ++j) sum7[j] += sc[j];
  }
  for (int j = 0; j < 7; ++j) {
    if (sum7[j] != c[j]) {
      std::fprintf(stderr, "shard counter %d sums %lld != group "
                   "%lld\n", j, static_cast<long long>(sum7[j]),
                   static_cast<long long>(c[j]));
      rc = 1;
      break;
    }
  }
  ag_adms_free(g);
  if (rc == 0)
    std::printf("tsan_admission_stress sharded ok: submitted=%lld "
                "drained=%lld evicted=%lld\n",
                static_cast<long long>(c[0]),
                static_cast<long long>(c[6]),
                static_cast<long long>(c[5]));
  return rc;
}

int main() {
  int rc = run_single();
  if (rc != 0) return rc;
  return run_sharded();
}
