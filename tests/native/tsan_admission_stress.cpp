// ThreadSanitizer stress for the native admission queue (ISSUE 19).
//
// The schedule checker (analysis/schedcheck.py) serializes every
// PYTHON-visible yield point of the threaded serve host, but the
// ag_adm_* calls release the GIL for their whole span — their inner
// interleavings are exactly what the cooperative scheduler cannot
// see.  This binary is that other half: the admission queue's shared
// surface (core/native/admission.cpp) under real concurrency, fully
// TSAN-instrumented, in the production threaded-host topology:
//
//   producer threads   ag_adm_submit batches (well-formed + one
//                      malformed lane), then race a mark_verified
//                      back-annotation for their own submit — the
//                      wrapper's dedup-cache flow, which the C side
//                      documents as racing concurrent drains safely
//   drainer thread     the dispatch loop's shape: unlocked depth
//                      read, then a drain sized from it — the C side
//                      must clamp to the live size (the PR 14
//                      review-fix contract: got <= asked, and only
//                      rows [0, got) are real)
//   cold reader        counters / oldest_ts / instance_depth /
//                      capped export, racing everything — the
//                      observability path a bench heartbeat takes
//
// Exit 0 = no data race AND the admission taxonomy balances:
// submitted = admitted + rejected, admitted = drained + evicted, and
// the drainer's accumulated row count equals the drained counter
// (no phantom or lost records).  ci.sh builds this with
// -fsanitize=thread and runs it as step 1b; the plain (uninstrumented)
// build doubles as a cheap correctness test in the python suite.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
void* ag_adm_new(int64_t I, int64_t capacity, int64_t instance_cap,
                 int32_t policy, int32_t with_digests);
void ag_adm_free(void* h);
int64_t ag_adm_submit(void* h, const uint8_t* buf, int64_t nbytes,
                      int64_t* out_counts, uint8_t* out_digests);
void ag_adm_mark_verified(void* h, int64_t seq, const uint8_t* ver,
                          int64_t n);
int64_t ag_adm_depth(void* h);
int64_t ag_adm_instance_depth(void* h, int64_t i);
double ag_adm_oldest_ts(void* h);
void ag_adm_counters(void* h, int64_t* out7);
int64_t ag_adm_drain(void* h, int64_t n, int64_t* inst, int64_t* val,
                     int64_t* hts, int64_t* rnd, int64_t* typ,
                     int64_t* value, uint8_t* sigs, uint8_t* ver,
                     uint8_t* out_dig, double* ts);
int64_t ag_adm_export(void* h, uint8_t* raw, uint8_t* ver, int64_t cap);
}

namespace {

constexpr int kRecSize = 96;
constexpr int64_t I = 4;
constexpr int64_t kCapacity = 128;
constexpr int64_t kInstanceCap = 64;     // python default: 2*cap/I
constexpr int kProducers = 3;
constexpr int kBatches = 300;
constexpr int kPerBatch = 16;            // 15 well-formed + 1 malformed
constexpr int64_t kDrainMax = 32;

// wire-record packer (the module-top layout of ingest.cpp)
void pack(uint8_t* p, uint32_t inst, uint32_t val, int64_t height,
          int32_t round, uint8_t typ, int64_t value) {
  std::memset(p, 0, kRecSize);
  std::memcpy(p + 0, &inst, 4);
  std::memcpy(p + 4, &val, 4);
  std::memcpy(p + 8, &height, 8);
  std::memcpy(p + 16, &round, 4);
  p[20] = typ;
  p[21] = 1;
  std::memcpy(p + 24, &value, 8);
}

// one drain in the dispatch loop's exact shape: size from an UNLOCKED
// depth read, then trust only the return value
int64_t drain_once(void* h) {
  int64_t n0 = ag_adm_depth(h);
  if (n0 <= 0) return 0;
  int64_t ask = std::min(n0, kDrainMax);
  std::vector<int64_t> inst(ask), val(ask), hts(ask), rnd(ask),
      typ(ask), value(ask);
  std::vector<uint8_t> sigs(ask * 64), ver(ask), dig(ask * 32);
  std::vector<double> ts(ask);
  int64_t got = ag_adm_drain(h, ask, inst.data(), val.data(),
                             hts.data(), rnd.data(), typ.data(),
                             value.data(), sigs.data(), ver.data(),
                             dig.data(), ts.data());
  if (got < 0 || got > ask) {
    std::fprintf(stderr, "drain clamp broken: asked %lld got %lld\n",
                 static_cast<long long>(ask),
                 static_cast<long long>(got));
    std::abort();
  }
  // rows [0, got) must be real records, never uninitialized tail
  for (int64_t k = 0; k < got; ++k) {
    if (inst[k] < 0 || inst[k] >= I) {
      std::fprintf(stderr, "phantom row: inst=%lld at %lld\n",
                   static_cast<long long>(inst[k]),
                   static_cast<long long>(k));
      std::abort();
    }
  }
  return got;
}

}  // namespace

int main() {
  void* h = ag_adm_new(I, kCapacity, kInstanceCap, /*drop_oldest=*/1,
                       /*with_digests=*/1);
  if (!h) { std::fprintf(stderr, "ag_adm_new failed\n"); return 2; }

  std::atomic<int> done{0};
  std::atomic<int64_t> drained_rows{0};

  auto producer = [&](int id) {
    std::vector<uint8_t> buf(kPerBatch * kRecSize);
    std::vector<uint8_t> dig(kPerBatch * 32);
    int64_t counts[5];
    std::vector<uint8_t> mark(kPerBatch);
    for (int b = 0; b < kBatches; ++b) {
      for (int k = 0; k < kPerBatch - 1; ++k) {
        uint32_t inst = static_cast<uint32_t>((b + k) % I);
        uint32_t val = static_cast<uint32_t>((id * 17 + k) % 64);
        pack(buf.data() + k * kRecSize, inst, val, 0, 0, 1, 5);
      }
      // one malformed lane per batch (out-of-range instance id)
      pack(buf.data() + (kPerBatch - 1) * kRecSize, 0xFFFF, 0, 0, 0, 1, 5);
      int64_t seq = ag_adm_submit(h, buf.data(), kPerBatch * kRecSize,
                                  counts, dig.data());
      // dedup-cache back-annotation, racing the drainer — the C side's
      // documented contract: already-drained records are skipped
      if (counts[0] > 0) {
        std::fill(mark.begin(), mark.begin() + counts[0],
                  static_cast<uint8_t>(b & 1));
        ag_adm_mark_verified(h, seq, mark.data(), counts[0]);
      }
    }
    done.fetch_add(1);
  };

  std::vector<std::thread> threads;
  threads.reserve(kProducers + 1);
  for (int p = 0; p < kProducers; ++p) threads.emplace_back(producer, p);

  // cold reader: the observability surface, racing everything
  threads.emplace_back([&] {
    int64_t counters[7];
    std::vector<uint8_t> raw(kCapacity * kRecSize), ver(kCapacity);
    while (done.load() < kProducers) {
      ag_adm_counters(h, counters);
      (void)ag_adm_oldest_ts(h);
      for (int64_t i = 0; i < I; ++i) (void)ag_adm_instance_depth(h, i);
      // export sized from a racy depth read; the C side clamps writes
      int64_t cap = std::min(ag_adm_depth(h), kCapacity);
      if (cap > 0) (void)ag_adm_export(h, raw.data(), ver.data(), cap);
    }
  });

  // drainer on the main thread, racing the producers
  while (done.load() < kProducers) drained_rows += drain_once(h);
  for (auto& t : threads) t.join();
  // residue: everything still queued must drain exactly once
  for (int64_t got; (got = drain_once(h)) > 0;) drained_rows += got;

  int64_t c[7];  // [submitted, admitted, rej_overflow, rej_fairness,
                 //  rej_malformed, evicted, drained]
  ag_adm_counters(h, c);
  const int64_t want_submitted =
      int64_t{kProducers} * kBatches * kPerBatch;
  const int64_t want_malformed = int64_t{kProducers} * kBatches;
  int rc = 0;
  if (c[0] != want_submitted) {
    std::fprintf(stderr, "submitted=%lld want %lld\n",
                 static_cast<long long>(c[0]),
                 static_cast<long long>(want_submitted));
    rc = 1;
  }
  if (c[4] != want_malformed) {
    std::fprintf(stderr, "malformed=%lld want %lld\n",
                 static_cast<long long>(c[4]),
                 static_cast<long long>(want_malformed));
    rc = 1;
  }
  if (c[1] != c[0] - c[2] - c[3] - c[4]) {
    std::fprintf(stderr, "admission taxonomy unbalanced\n");
    rc = 1;
  }
  if (drained_rows.load() != c[6]) {
    std::fprintf(stderr, "drained rows %lld != drained counter %lld "
                 "(phantom/lost records)\n",
                 static_cast<long long>(drained_rows.load()),
                 static_cast<long long>(c[6]));
    rc = 1;
  }
  if (c[1] != c[6] + c[5] || ag_adm_depth(h) != 0) {
    std::fprintf(stderr, "conservation: admitted %lld != drained %lld "
                 "+ evicted %lld (+ depth %lld)\n",
                 static_cast<long long>(c[1]),
                 static_cast<long long>(c[6]),
                 static_cast<long long>(c[5]),
                 static_cast<long long>(ag_adm_depth(h)));
    rc = 1;
  }
  ag_adm_free(h);
  if (rc == 0)
    std::printf("tsan_admission_stress ok: submitted=%lld drained=%lld "
                "evicted=%lld\n",
                static_cast<long long>(c[0]),
                static_cast<long long>(c[6]),
                static_cast<long long>(c[5]));
  return rc;
}
