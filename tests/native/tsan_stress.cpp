// ThreadSanitizer stress for the ingest event loop's worker thread.
//
// Why a dedicated binary: running TSAN through the python test suite
// drowns real findings in uninstrumented third-party noise (jaxlib's
// Eigen thread pools, libgcc unwind locks).  This binary links the
// native sources directly, fully instrumented, and exercises the
// exact shared surface of core/native/ingest.cpp's async path:
// producer threads stream push_async buffers (well-formed + malformed)
// while the consumer thread runs the full tick protocol
// (sync/stage/verdicts/emit/phase reads/counters) against it.
//
// Exit 0 = no data race AND conservation holds (every well-formed
// record reaches the evidence log exactly once; every malformed one
// is counted).  ci.sh builds this with -fsanitize=thread and runs it
// as step 1b.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
void* ag_ing_new(int64_t I, int64_t V, int64_t W, int64_t S,
                 const uint8_t* pubkeys, const int64_t* powers);
void ag_ing_free(void* h);
void ag_ing_sync(void* h, const int64_t* base_round, const int64_t* heights);
int64_t ag_ing_push_async(void* h, const uint8_t* buf, int64_t n);
void ag_ing_flush(void* h);
int64_t ag_ing_async_depth(void* h);
int64_t ag_ing_stage(void* h);
int64_t ag_ing_apply_verdicts(void* h, const uint8_t* ok);
int64_t ag_ing_emit(void* h);
int64_t ag_ing_phase(void* h, int64_t k, int32_t* out_round,
                     int32_t* out_typ, int64_t* out_n,
                     const int32_t** out_slots, const uint8_t** out_mask);
void ag_ing_counters(void* h, int64_t* out);
}

namespace {

constexpr int kRecSize = 96;
constexpr int64_t I = 4, V = 16;

// wire-record packer (the module-top layout of ingest.cpp)
void pack(uint8_t* p, uint32_t inst, uint32_t val, int64_t height,
          int32_t round, uint8_t typ, int64_t value) {
  std::memset(p, 0, kRecSize);
  std::memcpy(p + 0, &inst, 4);
  std::memcpy(p + 4, &val, 4);
  std::memcpy(p + 8, &height, 8);
  std::memcpy(p + 16, &round, 4);
  p[20] = typ;
  p[21] = 1;
  std::memcpy(p + 24, &value, 8);
}

}  // namespace

int main() {
  void* h = ag_ing_new(I, V, /*W=*/4, /*S=*/4, nullptr, nullptr);
  if (!h) { std::fprintf(stderr, "ag_ing_new failed\n"); return 2; }
  std::vector<int64_t> base(I, 0), heights(I, 0);
  ag_ing_sync(h, base.data(), heights.data());

  constexpr int kProducers = 3;
  constexpr int kBatches = 400;
  constexpr int kPerBatch = 32;  // 31 well-formed + 1 malformed
  std::atomic<int> done{0};

  auto producer = [&](int id) {
    std::vector<uint8_t> buf(kPerBatch * kRecSize);
    for (int b = 0; b < kBatches; ++b) {
      for (int k = 0; k < kPerBatch - 1; ++k) {
        uint32_t inst = static_cast<uint32_t>((b + k) % I);
        uint32_t val = static_cast<uint32_t>((id + k) % V);
        pack(buf.data() + k * kRecSize, inst, val, 0, 0, 0, 7);
      }
      // one malformed lane per batch (hostile validator index)
      pack(buf.data() + (kPerBatch - 1) * kRecSize, 0, 9999, 0, 0, 0, 7);
      ag_ing_push_async(h, buf.data(), kPerBatch);
    }
    done.fetch_add(1);
  };

  std::vector<std::thread> threads;
  threads.reserve(kProducers);
  for (int p = 0; p < kProducers; ++p) threads.emplace_back(producer, p);

  // consumer: full ticks racing the producers
  int64_t counters[7];
  while (done.load() < kProducers) {
    if (ag_ing_stage(h) > 0) {
      ag_ing_apply_verdicts(h, nullptr);
      int64_t n_ph = ag_ing_emit(h);
      for (int64_t k = 0; k < n_ph; ++k) {
        int32_t rnd, typ;
        int64_t nv;
        const int32_t* slots;
        const uint8_t* mask;
        ag_ing_phase(h, k, &rnd, &typ, &nv, &slots, &mask);
        // touch the buffers the way the device boundary would
        int64_t sum = 0;
        for (int64_t c = 0; c < I * V; ++c) sum += slots[c] + mask[c];
        (void)sum;
      }
    }
    ag_ing_counters(h, counters);   // cold observability path, racing
    (void)ag_ing_async_depth(h);
  }
  for (auto& t : threads) t.join();

  // drain: everything queued must land exactly once
  ag_ing_flush(h);
  if (ag_ing_stage(h) > 0) {
    ag_ing_apply_verdicts(h, nullptr);
    ag_ing_emit(h);
  }
  ag_ing_counters(h, counters);
  const int64_t want_good = int64_t{kProducers} * kBatches * (kPerBatch - 1);
  const int64_t want_bad = int64_t{kProducers} * kBatches;
  int rc = 0;
  if (counters[5] != want_good) {
    std::fprintf(stderr, "log=%lld want %lld\n",
                 static_cast<long long>(counters[5]),
                 static_cast<long long>(want_good));
    rc = 1;
  }
  if (counters[0] != want_bad) {
    std::fprintf(stderr, "malformed=%lld want %lld\n",
                 static_cast<long long>(counters[0]),
                 static_cast<long long>(want_bad));
    rc = 1;
  }
  if (ag_ing_async_depth(h) != 0) {
    std::fprintf(stderr, "async_depth nonzero after flush\n");
    rc = 1;
  }
  ag_ing_free(h);
  if (rc == 0) std::printf("tsan_stress ok: log=%lld malformed=%lld\n",
                           static_cast<long long>(want_good),
                           static_cast<long long>(want_bad));
  return rc;
}
