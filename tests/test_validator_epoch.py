"""Validator-set change at a height boundary, exercised on the device
plane (VERDICT r3 next #8; reference validators.rs:38-46 intent —
add/update/remove, which doesn't even compile there — and SURVEY §2.6
"re-uploaded on set changes").

The device shape [V] is static: an epoch re-uploads the power table
(0 = removed) and, on the signed native loop, the pubkey table (key
rotation).  All three surfaces are covered: DeviceDriver quorum math,
NativeIngestLoop verification + host-fallback quorum, VoteBatcher
host-fallback quorum.
"""

import numpy as np
import pytest

from agnes_tpu.bridge import NativeIngestLoop, VoteBatcher, pack_wire_votes
from agnes_tpu.core import native
from agnes_tpu.harness.device_driver import DeviceDriver
from agnes_tpu.types import VoteType

PV, PC = int(VoteType.PREVOTE), int(VoteType.PRECOMMIT)


def test_device_power_epoch_changes_quorum():
    """Height 0 decides under uniform powers; the epoch re-upload
    [5, 1, 1, 0] then governs height 1: the old 3-of-4 uniform quorum
    (now weight 2 of 7, validator 3 removed) must NOT decide, and
    {0, 1, 2} (weight 7) must."""
    I, V = 4, 4
    d = DeviceDriver(I, V, advance_height=True)
    d.run_honest_round(0, slot=1)
    assert d.all_decided()
    assert (np.asarray(d.state.height) == 1).all()

    d.set_validators([5, 1, 1, 0])

    # height 1, round 0: validators {1, 2, 3} vote — weight 1+1+0 = 2
    # of total 7; under the OLD uniform set this was a +2/3 quorum
    d.step()
    d.step(phase=d.phase(0, VoteType.PREVOTE, 1, frac=0.75, offset=1))
    d.step(phase=d.phase(0, VoteType.PRECOMMIT, 1, frac=0.75, offset=1))
    d.collect()
    assert d.stats.decisions_total == I          # nothing new decided

    # validators {0, 1, 2}: weight 5+1+1 = 7 > 2/3 * 7 — decides
    d.step(phase=d.phase(0, VoteType.PREVOTE, 1, frac=0.75, offset=0))
    d.step(phase=d.phase(0, VoteType.PRECOMMIT, 1, frac=0.75, offset=0))
    d.collect()
    assert d.stats.decisions_total == 2 * I
    assert (np.asarray(d.state.height) == 2).all()


def _signed_wire(seeds, inst, val, h, rnd, typ, value, signer_seeds=None):
    from agnes_tpu.bridge.ingest import vote_messages_np

    h = np.asarray(h, np.int64)
    rnd = np.asarray(rnd, np.int64)
    typ = np.asarray(typ, np.int64)
    value = np.asarray(value, np.int64)
    msgs = vote_messages_np(h, rnd, typ, value)
    signers = signer_seeds if signer_seeds is not None else \
        [seeds[v] for v in val]
    sigs = np.stack([np.frombuffer(
        native.sign(signers[k], msgs[k].tobytes()), np.uint8)
        for k in range(len(val))])
    return pack_wire_votes(np.asarray(inst, np.int64),
                           np.asarray(val, np.int64), h, rnd, typ,
                           value, sigs)


def test_native_loop_key_rotation_and_power_epoch():
    """Epoch on the signed C++ loop: after the height boundary the
    rotated validator's OLD key must be rejected and the NEW key
    accepted, and the host-fallback precommit quorum must use the new
    powers."""
    V = 4
    old_seeds = [bytes([i + 1]) * 32 for i in range(V)]
    new_seed2 = bytes([77]) * 32                 # validator 2 rotates
    old_pub = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                        for s in old_seeds])
    loop = NativeIngestLoop(1, V, n_slots=4, pubkeys=old_pub)
    loop.sync_device(np.zeros(1, np.int64), np.zeros(1, np.int64))

    loop.push(_signed_wire(old_seeds, [0], [2], [0], [0], [PV], [7]))
    loop.build_phases()
    assert loop.counters["rejected_signature"] == 0

    # height boundary: sync to height 1, then the epoch
    loop.sync_device(np.zeros(1, np.int64), np.ones(1, np.int64))
    new_seeds = list(old_seeds)
    new_seeds[2] = new_seed2
    new_pub = old_pub.copy()
    new_pub[2] = np.frombuffer(native.pubkey(new_seed2), np.uint8)
    loop.set_validators(pubkeys=new_pub, powers=[5, 1, 1, 0])

    # old key for validator 2 must now fail; new key must pass
    loop.push(_signed_wire(old_seeds, [0], [2], [1], [0], [PV], [7]))
    loop.build_phases()
    assert loop.counters["rejected_signature"] == 1
    loop.push(_signed_wire(new_seeds, [0], [2], [1], [0], [PV], [7]))
    loop.build_phases()
    assert loop.counters["rejected_signature"] == 1

    # host-fallback quorum under the NEW powers: the window moved past
    # round 0; precommits from {1, 2} weigh 2 of 7 (no event), adding
    # validator 0 (weight 5) crosses and fires commit-from-any-round
    loop.sync_device(np.full(1, 3, np.int64), np.ones(1, np.int64))
    loop.push(_signed_wire(new_seeds, [0, 0], [1, 2], [1, 1], [0, 0],
                           [PC, PC], [9, 9]))
    loop.build_phases()
    assert loop.drain_host_events() == []
    loop.push(_signed_wire(new_seeds, [0], [0], [1], [0], [PC], [9]))
    loop.build_phases()
    assert loop.drain_host_events() == [(0, 1, 0, 9)]


def test_batcher_power_epoch_matches_native():
    """VoteBatcher.set_validators drives the same host-fallback quorum
    decision as the native loop epoch (differential on the one surface
    the batcher owns powers for)."""
    V = 4
    bat = VoteBatcher(1, V, n_slots=4)
    bat.sync_device(np.full(1, 3, np.int64), np.zeros(1, np.int64))
    bat.set_validators([5, 1, 1, 0])
    bat.add_arrays([0, 0], [1, 2], [0, 0], [0, 0], [PC, PC], [9, 9])
    bat.build_phases()
    assert bat.drain_host_events() == []         # weight 2 of 7
    bat.add_arrays([0], [0], [0], [0], [PC], [9])
    bat.build_phases()
    assert bat.drain_host_events() == [(0, 0, 0, 9)]


def test_epoch_rejections():
    """Pubkey upload on an unsigned loop and wrong shapes fail fast."""
    loop = NativeIngestLoop(1, 4, n_slots=4)
    with pytest.raises(ValueError, match="unsigned"):
        loop.set_validators(pubkeys=np.zeros((4, 32), np.uint8))
    with pytest.raises(ValueError, match="powers"):
        loop.set_validators(powers=np.ones(3, np.int64))
    bat = VoteBatcher(1, 4, n_slots=4)
    with pytest.raises(ValueError, match="powers"):
        bat.set_validators(np.ones(5, np.int64))
