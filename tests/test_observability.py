"""ISSUE 8 observability plane: log-bucket latency histograms
(quantile correctness vs a numpy reference, N-thread merge
conservation), the bounded tracer ring + stable thread ids + flow
events, the flight recorder's ring bounds and its crash-surviving
heartbeat (a SIGKILLed child leaves a fresh parseable last line —
the test_bench_deadline child-process pattern), the /metrics
Prometheus endpoint (scrape parses, counters round-trip), tick-id
correlation across submit -> dispatch -> settle on a stubbed serve
tick, and the registry's first-dispatch compile-wall recording.

Everything here runs with ZERO XLA compiles (device dispatch is
stubbed; tier-1 cheap, conftest _CHEAP)."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from agnes_tpu.utils import flightrec as fr
from agnes_tpu.utils.metrics import (
    Histogram,
    Metrics,
    SERVE_ADMIT_WAIT_S,
    SERVE_BATCH_CLOSE_AGE_S,
    SERVE_DISPATCH_WALL_S,
    SERVE_E2E_DECISION_S,
    SERVE_SETTLE_WALL_S,
)
from agnes_tpu.utils.metrics_http import (
    MetricsServer,
    parse_prometheus,
    render_prometheus,
)
from agnes_tpu.utils.tracing import Tracer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: one bucket's relative width (quantile error bound of the fixed
#: log-bucket table) with a little slack for the numpy interpolation
_BUCKET_RATIO = 2 ** (1.0 / Histogram.SUB) * 1.05


# -- histogram ----------------------------------------------------------------

def test_histogram_quantiles_vs_numpy_reference():
    rng = np.random.default_rng(7)
    vals = rng.lognormal(mean=-6.0, sigma=1.5, size=8000)
    h = Histogram("lat")
    for v in vals:
        h.record(float(v))
    assert h.n == len(vals)
    assert h.vmax == float(vals.max())
    assert abs(h.total - float(vals.sum())) < 1e-9 * vals.sum()
    for q in (0.5, 0.9, 0.99):
        ref = float(np.quantile(vals, q))
        got = h.quantile(q)
        assert 1 / _BUCKET_RATIO < got / ref < _BUCKET_RATIO, \
            (q, ref, got)
    assert h.quantile(1.0) == float(vals.max())     # exact max
    snap = h.snapshot()
    assert snap["count"] == len(vals) and snap["p99"] >= snap["p50"]


def test_histogram_edge_values_clamp_not_lost():
    h = Histogram()
    h.record(0.0)                      # <= 0 clamps to bucket 0
    h.record(1e-30)
    h.record(1e9)                      # clamps to the top bucket
    assert h.n == 3
    buckets, total, n = h.prom_buckets()
    assert n == 3 and buckets[-1][1] == 3        # cumulative reaches n


def test_histogram_n_thread_merge_conservation():
    """Per-thread histograms merged == one histogram fed everything:
    bucket-for-bucket, plus count/sum/max — nothing lost or doubled."""
    rng = np.random.default_rng(3)
    chunks = [rng.lognormal(-5, 1.0, 500) for _ in range(4)]
    parts = [Histogram(f"t{i}") for i in range(4)]

    def worker(h, vals):
        for v in vals:
            h.record(float(v))

    ts = [threading.Thread(target=worker, args=(h, c))
          for h, c in zip(parts, chunks)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    merged = Histogram("merged")
    for h in parts:
        merged.merge(h)
    ref = Histogram("ref")
    for c in chunks:
        for v in c:
            ref.record(float(v))
    assert merged.counts == ref.counts
    assert merged.n == ref.n == 2000
    assert merged.vmax == ref.vmax
    assert abs(merged.total - ref.total) < 1e-9


def test_histogram_shared_across_threads_conserves():
    h = Histogram()
    ts = [threading.Thread(
        target=lambda: [h.record(0.001) for _ in range(1000)])
        for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert h.n == 4000


# -- Metrics: windowed snapshot (satellite) -----------------------------------

def test_snapshot_window_uses_shared_interval_window():
    m = Metrics()
    m.count("x", 100)
    m.snapshot(window=True)            # close the first window
    m.count("x", 50)
    s2 = m.snapshot(window=True)
    assert s2["x"] == 150              # counters stay lifetime totals
    assert s2["x_per_sec"] > 0         # rate covers the 50-delta window
    s3 = m.snapshot(window=True)       # empty window right after
    assert s3["x_per_sec"] == 0.0
    # lifetime semantics unchanged (what bench's records rely on)
    assert m.snapshot()["x_per_sec"] > 0


def test_snapshot_window_keys_are_independent():
    """Two periodic consumers (drain report vs heartbeat) must not
    close each other's windows: the heartbeat's per-interval
    consumption on its own key leaves the shared window covering the
    whole run."""
    m = Metrics()
    m.count("x", 10)
    hb = m.snapshot(window=True, window_key="heartbeat")
    assert hb["x_per_sec"] > 0
    s = m.snapshot(window=True)        # shared window: still intact
    assert s["x_per_sec"] > 0
    # and vice versa: the shared close did not reset the hb window
    m.count("x", 5)
    assert m.snapshot(window=True,
                      window_key="heartbeat")["x_per_sec"] > 0


def test_metrics_histogram_registry_and_snapshot_keys():
    m = Metrics()
    m.observe("lat_s", 0.01, 3)
    assert m.histogram("lat_s").n == 3
    snap = m.snapshot()
    assert snap["lat_s_count"] == 3
    for q in ("p50", "p90", "p99", "max"):
        assert snap[f"lat_s_{q}"] > 0


# -- tracer (satellite): ring, stable tids, flows -----------------------------

def test_tracer_ring_bound_and_dropped_counter():
    tr = Tracer(max_events=8)
    for i in range(20):
        with tr.span(f"s{i}"):
            pass
    assert len(tr.spans) == 8
    assert tr.dropped_events == 12


def test_tracer_stable_tids_and_thread_name_metadata(tmp_path):
    tr = Tracer()
    with tr.span("main-span"):
        pass

    def side():
        tr.name_thread("serve-submit")
        with tr.span("side-span"):
            pass

    t = threading.Thread(target=side)
    t.start()
    t.join()
    tr.flow("tick", 5, "s")
    tr.flow("tick", 5, "t")
    tr.flow("tick", 5, "f")
    path = str(tmp_path / "t.json")
    tr.write(path)
    doc = json.load(open(path))
    meta = {e["tid"]: e["args"]["name"]
            for e in doc["traceEvents"] if e["ph"] == "M"}
    # SMALL sequential ids, not hashed idents
    assert set(meta) == {1, 2}
    assert "serve-submit" in meta.values()
    flows = [e for e in doc["traceEvents"] if e["ph"] in "stf"]
    assert {e["ph"] for e in flows} == {"s", "t", "f"}
    assert all(e["id"] == 5 for e in flows)
    assert [e for e in flows if e["ph"] == "f"][0]["bp"] == "e"
    assert tr.flow_phases(5) == {"s", "t", "f"}


# -- flight recorder ----------------------------------------------------------

def test_flightrec_ring_bounds_and_monotone_counts():
    rec = fr.FlightRecorder(capacity=8)
    for i in range(20):
        rec.event("tick_open", tick=i)
    rec.event("reject", overflow=3)
    assert len(rec) == 8
    assert rec.dropped == 13
    assert rec.counts() == {"tick_open": 20, "reject": 1}
    assert rec.last("tick_open")["tick"] == 19
    assert [e["tick"] for e in rec.tail(kind="tick_open")] == \
        list(range(13, 20))
    with pytest.raises(ValueError):
        fr.FlightRecorder(capacity=0)


def test_heartbeat_lines_schema_and_sources(tmp_path):
    path = str(tmp_path / "hb.ndjson")
    rec = fr.FlightRecorder()
    rec.event("compile", entry="e", ms=12.0)
    m = Metrics()
    m.count("serve_submitted", 5)
    hb = fr.Heartbeat(path, interval_s=0.5, recorder=rec,
                      sources=[lambda: m.snapshot(window=True),
                               lambda: {"stage": "probe"}])
    hb.beat()
    hb.beat()
    lines, bad = fr.read_heartbeat(path)
    assert bad == [] and len(lines) == 2
    last = lines[-1]
    assert fr.validate_heartbeat_line(last) == []
    assert last["seq"] == 1 and last["events"] == {"compile": 1}
    assert last["serve_submitted"] == 5 and last["stage"] == "probe"
    # a raising source is counted, never fatal
    hb.sources.append(lambda: 1 / 0)
    hb.beat()
    lines, bad = fr.read_heartbeat(path)
    assert bad == [] and lines[-1]["source_errors"] == 1


def test_heartbeat_schema_rejects_malformed():
    assert fr.validate_heartbeat_line([]) != []
    assert any("missing" in p for p in
               fr.validate_heartbeat_line({"v": 1}))
    good = {"v": 1, "kind": "hb", "seq": 0, "t": 1.0, "pid": 1,
            "uptime_s": 0.0}
    assert fr.validate_heartbeat_line(good) == []
    assert fr.validate_heartbeat_line({**good, "seq": "zero"}) != []
    assert fr.validate_heartbeat_line({**good, "v": 99}) != []


def test_heartbeat_atomic_rotation(tmp_path):
    path = str(tmp_path / "hb.ndjson")
    hb = fr.Heartbeat(path, interval_s=1.0, max_bytes=200)
    for _ in range(8):
        hb.beat()
    assert os.path.exists(path + ".1")
    lines, bad = fr.read_heartbeat(path)       # both halves parse
    lines1, bad1 = fr.read_heartbeat(path + ".1")
    assert bad == bad1 == [] and lines and lines1


def test_heartbeat_survives_sigkill_with_fresh_last_line(tmp_path):
    """The acceptance criterion: SIGKILL the process mid-run; the
    heartbeat NDJSON's last line must be schema-valid and no older
    than two heartbeat intervals (the child-process pattern of
    tests/test_bench_deadline.py)."""
    interval = 0.25
    path = str(tmp_path / "hb.ndjson")
    child = (
        "import sys, time\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from agnes_tpu.utils.flightrec import FlightRecorder, "
        "Heartbeat\n"
        "rec = FlightRecorder()\n"
        f"hb = Heartbeat({path!r}, interval_s={interval}, "
        "recorder=rec, sources=[lambda: {'stage': 'spin'}])\n"
        "hb.start()\n"
        "while True:\n"
        "    rec.event('tick_open', tick=1)\n"
        "    time.sleep(0.01)\n")
    p = subprocess.Popen([sys.executable, "-c", child],
                         stderr=subprocess.DEVNULL)
    try:
        # wait until the heartbeat is demonstrably alive (>= 2 lines),
        # then catch it FRESH so the age assert below is about the
        # recorder's guarantee, not this test's polling latency
        deadline = time.monotonic() + 30
        fresh = False
        while time.monotonic() < deadline:
            if os.path.exists(path):
                lines, _ = fr.read_heartbeat(path)
                age = fr.last_line_age_s(path)
                if len(lines) >= 2 and age is not None \
                        and age < interval:
                    fresh = True
                    break
            time.sleep(0.02)
        assert fresh, "heartbeat never became fresh"
        t_kill = time.time()
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=30)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
    lines, bad = fr.read_heartbeat(path)
    assert lines, "no valid heartbeat lines survived the kill"
    # at most one trailing casualty (a line the kill cut mid-write)
    assert len(bad) <= 1, bad
    last = lines[-1]
    assert fr.validate_heartbeat_line(last) == []
    assert last["stage"] == "spin" and last["events"]["tick_open"] > 0
    assert t_kill - last["t"] <= 2 * interval, \
        f"last line {t_kill - last['t']:.2f}s stale at kill time"
    # the postmortem renderer reads the same trail
    post = fr.render_postmortem(path)
    assert "stage at last beat: spin" in post


def test_pod_postmortem_renders_membership_trail(tmp_path):
    """ISSUE 17: the elastic membership story must be readable
    straight off the `agnes-metrics` pod post-mortem — per-host epoch
    in the ranked header, and the boundary / re-lift / hold-overflow
    event counts by name in each host's summary."""
    paths = []
    for host in (0, 1):
        rec = fr.FlightRecorder()
        rec.event("membership_boundary", epoch=2, alive=[0, 1],
                  joined=[1], left=[])
        rec.event("membership_relift", src=0, dst=1, lo=4, hi=8,
                  epoch=2)
        path = str(tmp_path / f"hb{host}.ndjson")
        fr.Heartbeat(path, interval_s=1e9, recorder=rec,
                     host_id=host,
                     sources=[lambda: {"pod_membership_epoch": 2,
                                       "pod_host_readmissions": 1}],
                     ).beat()
        paths.append(path)
    post = fr.render_postmortem(paths[0])
    assert "elastic membership:" in post
    assert "epoch 2" in post
    assert "1 readmission(s)" in post
    assert "membership_boundary=1" in post
    assert "membership_relift=1" in post
    assert "HELD GOSSIP DROPPED" not in post
    pod = fr.render_pod_postmortem(paths)
    assert "host 0" in pod and "host 1" in pod
    assert pod.count("epoch 2)") == 2      # both header rows carry it
    # a hold overflow — dropped held gossip — flags loudly
    rec2 = fr.FlightRecorder()
    rec2.event("membership_hold_overflow", dropped=3)
    p3 = str(tmp_path / "hb_overflow.ndjson")
    fr.Heartbeat(p3, interval_s=1e9, recorder=rec2).beat()
    post3 = fr.render_postmortem(p3)
    assert "membership_hold_overflow=1" in post3
    assert "HELD GOSSIP DROPPED" in post3
    # a membership-free trail renders no membership section at all
    p4 = str(tmp_path / "hb_plain.ndjson")
    fr.Heartbeat(p4, interval_s=1e9,
                 recorder=fr.FlightRecorder()).beat()
    assert "elastic membership:" not in fr.render_postmortem(p4)


# -- /metrics endpoint --------------------------------------------------------

def test_metrics_endpoint_scrape_parses_and_roundtrips(tmp_path):
    m = Metrics()
    m.count("serve_submitted", 42)
    m.count("serve_admitted", 40)
    m.gauge("serve_queue_depth", 3.0)
    h = m.histogram(SERVE_E2E_DECISION_S)
    for v in (0.001, 0.002, 0.004, 0.4):
        h.record(v)
    srv = MetricsServer(m, extra_sources=(
        lambda: {"compile_ms_consensus_step": 1234.5},))
    port = srv.start()
    try:
        from urllib.request import urlopen

        text = urlopen(f"http://127.0.0.1:{port}/metrics",
                       timeout=10).read().decode()
    finally:
        srv.stop()
    parsed = parse_prometheus(text)
    assert parsed["serve_submitted"] == 42.0
    assert parsed["serve_admitted"] == 40.0
    assert parsed["serve_queue_depth"] == 3.0
    assert parsed["compile_ms_consensus_step"] == 1234.5
    assert parsed[f"{SERVE_E2E_DECISION_S}_count"] == 4.0
    assert parsed[f'{SERVE_E2E_DECISION_S}_bucket{{le="+Inf"}}'] == 4.0
    # cumulative bucket counts are monotone and end at _count
    cum = [v for k, v in parsed.items()
           if k.startswith(f"{SERVE_E2E_DECISION_S}_bucket")]
    assert cum == sorted(cum) and cum[-1] == 4.0
    # renderer emits TYPE lines for every family
    assert "# TYPE serve_submitted counter" in text
    assert f"# TYPE {SERVE_E2E_DECISION_S} histogram" in text


def test_metrics_endpoint_404_off_path():
    m = Metrics()
    srv = MetricsServer(m)
    port = srv.start()
    try:
        from urllib.error import HTTPError
        from urllib.request import urlopen

        with pytest.raises(HTTPError):
            urlopen(f"http://127.0.0.1:{port}/other", timeout=10)
    finally:
        srv.stop()


# -- tick correlation through a stubbed serve tick ----------------------------

def _stub_service(tracer=None, rec=None):
    from agnes_tpu.bridge import VoteBatcher
    from agnes_tpu.harness.device_driver import DeviceDriver
    from agnes_tpu.serve import ShapeLadder, VoteService

    I, V = 2, 4
    d = DeviceDriver(I, V)
    bat = VoteBatcher(I, V, n_slots=4)
    svc = VoteService(
        d, bat, None, ladder=ShapeLadder.plan(I, V, min_rung=8),
        capacity=64, target_votes=8, max_delay_s=0.0,
        window_predictor=lambda: (np.zeros(I, np.int64),
                                  np.zeros(I, np.int64)),
        tracer=tracer, flightrec=rec)
    ticks = []

    def stub(phases, lanes=None, exts=None, donate=True, tick=None):
        ticks.append(tick)

    d.step_async = stub
    return svc, d, ticks


def _honest_wire(I=2, V=4):
    from agnes_tpu.bridge.native_ingest import pack_wire_votes

    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)
    n = I * V
    return pack_wire_votes(inst, val, np.zeros(n), np.zeros(n),
                           np.zeros(n), np.full(n, 7))


def test_tick_id_correlates_submit_dispatch_settle():
    """One serve tick, registry-level dispatch stubbed: the SAME
    monotonic tick id must appear in the submit-side flow start, the
    dispatch-side flow step, the settle-side flow end, the
    step_async call, and the flight recorder's tick_open/tick_close
    events — one connected lifecycle (ISSUE 8 tentpole)."""
    tracer = Tracer()
    rec = fr.FlightRecorder()
    svc, d, ticks = _stub_service(tracer=tracer, rec=rec)
    svc.submit(_honest_wire())
    svc.pump()                         # stage (tick 1 opens)
    svc.pump()                         # dispatch tick 1
    svc.poll_decisions()               # settle tick 1
    assert ticks == [1]                # step_async saw the tick id
    assert tracer.flow_phases(1) == {"s", "t", "f"}
    opens = rec.tail(kind="tick_open")
    closes = rec.tail(kind="tick_close")
    assert [e["tick"] for e in opens] == [1]
    assert [e["tick"] for e in closes] == [1]
    assert closes[0]["votes"] == 8 and closes[0]["e2e_s"] >= 0
    # a second tick gets the NEXT id
    svc.submit(_honest_wire())
    svc.pump()
    svc.pump()
    svc.poll_decisions()
    assert ticks == [1, 2]
    assert tracer.flow_phases(2) == {"s", "t", "f"}


def test_serve_latency_histograms_populate_and_drain_reports_them():
    svc, d, _ = _stub_service()
    svc.submit(_honest_wire())
    svc.pump()
    svc.pump()
    svc.poll_decisions()
    m = svc.metrics
    for name in (SERVE_ADMIT_WAIT_S, SERVE_BATCH_CLOSE_AGE_S,
                 SERVE_DISPATCH_WALL_S, SERVE_SETTLE_WALL_S,
                 SERVE_E2E_DECISION_S):
        assert m.histogram(name).n > 0, name
    # admission wait weighted per record: all 8 admitted records
    assert m.histogram(SERVE_ADMIT_WAIT_S).n == 8
    assert m.histogram(SERVE_E2E_DECISION_S).n == 8
    rep = svc.drain()
    lat = rep["latency"]
    assert lat[SERVE_E2E_DECISION_S]["count"] == 8
    assert lat[SERVE_E2E_DECISION_S]["p99"] >= 0
    # drain metrics are the WINDOWED snapshot (the satellite): its
    # per_sec keys mirror into serve_rates_window from the same dict
    assert rep["serve_rates_window"] == {
        k: v for k, v in rep["metrics"].items()
        if k.endswith("_per_sec")}
    # quantile keys ride the snapshot for scrapes/heartbeats
    assert f"{SERVE_E2E_DECISION_S}_p50" in rep["metrics"]


def test_rejects_and_thread_failures_land_in_flight_ring():
    rec = fr.FlightRecorder()
    svc, d, _ = _stub_service(rec=rec)
    # overflow: capacity 64 -> a 96-record submit rejects 32
    big = b"".join(_honest_wire() for _ in range(12))
    res = svc.submit(big)
    assert res.rejected > 0
    ev = rec.last("reject")
    assert ev is not None and ev["overflow"] == res.rejected_overflow


def test_compile_observer_single_and_weakly_held():
    """The whole process registers exactly ONE registry compile
    observer however many services come and go; recorders are held
    WEAKLY (a discarded service's recorder is not retained), events
    reach every live recorder exactly once."""
    import gc

    from agnes_tpu.device import registry
    from agnes_tpu.serve import service as svc_mod

    rec = fr.FlightRecorder()
    n0 = len(registry._COMPILE_CBS)
    _stub_service(rec=rec)
    _stub_service(rec=rec)
    dead = fr.FlightRecorder()
    _stub_service(rec=dead)
    assert len(registry._COMPILE_CBS) <= n0 + 1
    n_live = len(svc_mod._COMPILE_RECORDERS)
    del dead
    gc.collect()
    assert len(svc_mod._COMPILE_RECORDERS) == n_live - 1
    saved = registry.compile_ms()
    registry.reset_compile_ms()
    try:
        registry.record_compile_ms("__obs_test__", 7.0)
        ev = rec.last("compile")
        assert ev is not None and ev["entry"] == "__obs_test__"
        assert rec.counts()["compile"] == 1        # exactly once
    finally:
        registry.reset_compile_ms()
        for k, v in saved.items():
            registry.record_compile_ms(k, v)


# -- registry compile-wall recording (satellite) ------------------------------

def test_registry_records_first_dispatch_wall_once():
    from agnes_tpu.device import registry

    name = "consensus_step_seq"
    calls = []
    with registry.override(name, jit=lambda *a, **kw: calls.append(1)):
        saved = registry.compile_ms()
        registry.reset_compile_ms()
        try:
            got = {}
            registry.on_compile(lambda n, ms: got.setdefault(n, ms))
            fn = registry.timed_entry(name)
            fn()
            assert name in registry.compile_ms()
            assert got[name] == registry.compile_ms()[name]
            first = registry.compile_ms()[name]
            fn()                       # second call: no re-record
            assert registry.compile_ms()[name] == first
            # once recorded, timed_entry returns the RAW jit
            assert registry.timed_entry(name) is registry.get(name).jit
            # jit_entry stays identity-preserving (the lint/override
            # seam) — never a wrapper
            assert registry.jit_entry(name) is registry.get(name).jit
            assert registry.compile_gauges()[
                f"compile_ms_{name}"] == round(first, 1)
        finally:
            registry.reset_compile_ms()
            for k, v in saved.items():
                registry.record_compile_ms(k, v)
    assert len(calls) == 2


def test_step_async_emits_dispatch_event_with_tick_and_entry():
    import jax.numpy as jnp

    from agnes_tpu.device import registry
    from agnes_tpu.device.encoding import DeviceMessage, I32
    from agnes_tpu.device.step import N_STAGES, StepOutputs
    from agnes_tpu.harness.device_driver import DeviceDriver

    def stub_seq(state, tally, exts, phases, powers, total, pf, pv,
                 advance_height=False):
        P, I = phases.mask.shape[:2]
        z = jnp.zeros((P, N_STAGES, I), I32)
        return StepOutputs(state=state, tally=tally,
                           msgs=DeviceMessage(tag=z, round=z, value=z,
                                              aux=z))

    d = DeviceDriver(2, 4)
    rec = fr.FlightRecorder()
    d.flightrec = rec
    with registry.override("consensus_step_seq_donated", jit=stub_seq):
        d.step_async([d.empty_phase()], tick=42)
    ev = rec.last("dispatch")
    assert ev is not None
    assert ev["tick"] == 42
    assert ev["entry"] == "consensus_step_seq_donated"


# -- agnes-metrics CLI --------------------------------------------------------

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "agnes_metrics.py"), *args],
        capture_output=True, text=True, timeout=60)


def test_agnes_metrics_cli_check_and_postmortem(tmp_path):
    path = str(tmp_path / "hb.ndjson")
    hb = fr.Heartbeat(path, interval_s=0.5,
                      sources=[lambda: {"stage": "bench_pipeline"}])
    hb.beat()
    hb.beat()
    r = _run_cli("--check", path)
    assert r.returncode == 0, r.stderr
    assert "heartbeat check OK" in r.stdout
    r = _run_cli(path)
    assert r.returncode == 0, r.stderr
    assert "stage at last beat: bench_pipeline" in r.stdout
    r = _run_cli("--json", path)
    assert r.returncode == 0
    assert json.loads(r.stdout)["valid_lines"] == 2
    # ONE TRAILING bad line is the abrupt-death artifact (a line the
    # kill cut mid-write) — tolerated, the trail still checks out
    with open(path, "a") as f:
        f.write("not json at all\n")
    r = _run_cli("--check", path)
    assert r.returncode == 0, r.stderr
    assert "tolerated" in r.stdout
    # an INTERIOR bad line is corruption, not a death cut: FAIL
    hb.beat()
    r = _run_cli("--check", path)
    assert r.returncode == 1
    assert "BAD line" in r.stderr
    # missing file: distinct error code
    assert _run_cli("--check", str(tmp_path / "nope")).returncode == 2
