"""Host-side units of the ISSUE 13 surface — zero XLA, zero
pairings (sweeps stubbed): lane memo pruning on epoch advance, the
pairing class-rung ladder extension, and the jaxpr op-count census
gate's compare/baseline machinery.  Listed in conftest._CHEAP."""

import json
import os

import numpy as np
import pytest

from agnes_tpu.serve.batcher import ShapeLadder


# ---------------------------------------------------------------------------
# ShapeLadder.bls_class_rungs
# ---------------------------------------------------------------------------


def test_ladder_bls_class_rungs():
    lad = ShapeLadder.plan(2, 4).with_bls(4, min_rung=4)
    assert lad.bls_class_rungs == (1, 4)          # the default set
    assert lad.bls_class_rung_for(1) == 1
    assert lad.bls_class_rung_for(2) == 4
    assert lad.bls_class_rung_for(4) == 4
    # above the top rung: callers CHUNK (top rung returned)
    assert lad.bls_class_rung_for(9) == 4
    assert "bls classes: 1 4" in lad.describe()
    with pytest.raises(ValueError):
        ShapeLadder(rungs=(4,), bls_class_rungs=(3,))   # not pow2
    with pytest.raises(ValueError):
        ShapeLadder(rungs=(4,), bls_class_rungs=(4, 2))  # not ascending
    bare = ShapeLadder.plan(2, 4)
    assert bare.bls_class_rungs == ()
    with pytest.raises(ValueError):
        bare.bls_class_rung_for(1)


# ---------------------------------------------------------------------------
# BlsLane: epoch memo pruning + mode resolution
# ---------------------------------------------------------------------------


def _lane(V=2):
    from agnes_tpu.crypto import bls_ref as ref
    from agnes_tpu.serve.bls_lane import BlsKeyRegistry, BlsLane

    pts, acc = [], None
    for _ in range(V):
        acc = ref.point_add(acc, ref.G1)
        pts.append(acc)
    pk = np.stack([np.frombuffer(ref.g1_compress(p), np.uint8)
                   for p in pts])
    reg = BlsKeyRegistry(pk)
    reg.mark_trusted(np.arange(V))
    lane = BlsLane(reg, 1, target_signers=V, max_delay_s=1e9)
    # stub BOTH crypto sweeps: these units test memo lifecycle, not
    # pairings
    lane._host_pairing_sweep = lambda pending: {
        mk: True for mk, *_ in pending}
    lane._class_msg_point = lambda key: object()
    return lane


def _submit_class(lane, h=0):
    from agnes_tpu.serve.bls_lane import pack_bls_wire

    V = lane.registry.V
    shares = np.zeros((V, 192), np.uint8)
    lane.table.fold(pack_bls_wire(
        [0] * V, list(range(V)), [h] * V, [0] * V, [1] * V, [7] * V,
        shares), decode=False)


def test_memo_pruned_on_epoch_advance():
    lane = _lane()
    assert lane.uses_device_pairing is False      # auto: no ladder
    _submit_class(lane, h=0)
    lane.clear_classes(lane.poll())
    assert len(lane._pair_memo) == 1
    # replay: memo hit, no new sweep
    lane._host_pairing_sweep = lambda pending: (_ for _ in ()).throw(
        AssertionError("sweep on a memoized class"))
    _submit_class(lane, h=0)
    lane.clear_classes(lane.poll())
    assert lane.counters["pairing_memo_hits"] == 1
    # epoch advance: BOTH memos pruned and counted, the same class
    # re-pairs under the new epoch
    lane._share_memo[("sentinel",)] = True
    lane.registry.set_powers([3, 1])
    lane._host_pairing_sweep = lambda pending: {
        mk: True for mk, *_ in pending}
    _submit_class(lane, h=0)
    lane.clear_classes(lane.poll())
    assert lane.counters["bls_memo_evictions"] == 2
    assert len(lane._share_memo) == 0
    assert len(lane._pair_memo) == 1              # new-epoch verdict
    assert lane.counters["pairing_memo_hits"] == 1


def test_memo_hit_survives_capacity_clear_mid_batch():
    """Regression (review finding): the 4096-entry _pair_memo
    capacity clear can fire while THIS batch's verdicts are being
    memoized — a memo-HIT class in the same batch must still clear
    as aggregated (its verdict was resolved at lookup time), never
    take a spurious per-share fallback because a later re-read found
    an emptied memo."""
    lane = _lane()
    _submit_class(lane, h=0)
    lane.clear_classes(lane.poll())           # memoize class @ h=0
    assert lane.counters["agg_classes"] == 1
    # pack the memo to one under the cap: inserting the NEXT verdict
    # trips the clear
    for i in range(4095 - len(lane._pair_memo)):
        lane._pair_memo[("dummy", i)] = True
    _submit_class(lane, h=0)                  # memo hit
    _submit_class(lane, h=1)                  # pending -> insert
    lane.clear_classes(lane.poll())
    assert lane.counters["pairing_memo_hits"] == 1
    assert lane.counters["agg_classes"] == 3  # BOTH cleared as agg
    assert lane.counters["fallback_classes"] == 0


def test_device_pairing_mode_resolution():
    from agnes_tpu.serve.bls_lane import BlsLane

    lane = _lane()
    assert lane.uses_device_pairing is False
    lane.ladder = ShapeLadder.plan(2, 4).with_bls(4)
    assert lane.uses_device_pairing is True       # auto: rungs planned
    lane.device_pairing = False                   # forced host
    assert lane.uses_device_pairing is False
    lane2 = BlsLane(lane.registry, 1, device_pairing=True)
    assert lane2.uses_device_pairing is True      # forced device
    # forced device WITHOUT planned pairing rungs fails LOUDLY at
    # first use (review finding: the alternative is a live
    # multi-minute compile + a retrace trip mid-serve)
    with pytest.raises(ValueError, match="bls_class_rungs"):
        lane2._device_pairing_sweep([(("k",), None, None, None)])


# ---------------------------------------------------------------------------
# census gate machinery (analysis/jaxpr_audit.py — no jax import)
# ---------------------------------------------------------------------------


def test_census_findings_drift_and_missing():
    from agnes_tpu.analysis.jaxpr_audit import census_findings

    base = {"a": 1000, "b": 2000, "gone": 50}
    measured = {"a": 1050, "b": 2500}             # a in, b +25%, gone absent
    f = census_findings(measured, base)
    codes = sorted((x.code, x.where) for x in f)
    assert codes == [("AUD007", "b"), ("AUD008", "gone")], codes
    assert census_findings({"a": 1099, "b": 1801, "gone": 45},
                           base) == []            # all inside ±10%


def test_census_baseline_roundtrip(tmp_path):
    from agnes_tpu.analysis import jaxpr_audit as JA

    path = str(tmp_path / "census.json")
    JA.write_census_baseline(path, {"x": 123, "y": 456})
    assert JA.load_census_baseline(path) == {"x": 123, "y": 456}
    data = json.load(open(path))
    assert data["tolerance"] == JA.CENSUS_TOLERANCE
    assert data["dims"] == JA.AUDIT_DIMS


def test_checked_in_census_baseline_shape():
    """The repo's baseline file exists, parses, and pins the two BLS
    entries the diet is about (plus at least one fused-step entry)."""
    from agnes_tpu.analysis import jaxpr_audit as JA

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = JA.census_baseline_path(repo)
    assert os.path.exists(path), path
    base = JA.load_census_baseline(path)
    assert "bls_aggregate" in base
    assert "bls_pairing_product" in base
    assert all(isinstance(v, int) and v > 0 for v in base.values())


def test_census_coverage_flags_unbaselined_planned_entry(monkeypatch):
    """A census-planned entry missing from the baseline is AUD010 —
    a newly registered hot entry can never sit silently ungated
    (review finding)."""
    from agnes_tpu.analysis import jaxpr_audit as JA

    monkeypatch.setattr(JA, "census_planned_names",
                        lambda: ["old_entry", "brand_new_entry"])
    f = JA.census_coverage_findings({"old_entry": 10})
    assert len(f) == 1 and f[0].code == "AUD010"
    assert "brand_new_entry" in f[0].where
    assert JA.census_coverage_findings(
        {"old_entry": 10, "brand_new_entry": 5}) == []
