"""CLI shim drift tripwire (ISSUE 9 satellite).

scripts/agnes_{modelcheck,lint,metrics}.py are thin repo shims over
the packaged CLIs (the `agnes-*` console entry points in
pyproject.toml).  Two copies of a dispatch are two chances to drift:
a shim importing a stale symbol, or pyproject pointing at a renamed
function, fails only at invocation time — usually inside a CI gate.
These tests pin both sides to the SAME packaged `main` callable,
cheaply (AST on the shims, importlib on the package; no subprocess,
no jax for the jax-free CLIs — asserted)."""

import ast
import importlib
import os
import re
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: shim basename -> (packaged module, console-script name)
SHIMS = {
    "agnes_modelcheck.py": ("agnes_tpu.analysis.modelcheck",
                            "agnes-modelcheck"),
    "agnes_lint.py": ("agnes_tpu.analysis.lint_cli", "agnes-lint"),
    "agnes_metrics.py": ("agnes_tpu.utils.metrics_cli",
                         "agnes-metrics"),
    "agnes_schedcheck.py": ("agnes_tpu.analysis.schedcheck",
                            "agnes-schedcheck"),
}


def _shim_main_import(path):
    """(module, names) of the `from X import main[, ...]` statement a
    shim forwards through, plus whether __main__ calls main()."""
    tree = ast.parse(open(path).read(), filename=path)
    imports = [node for node in ast.walk(tree)
               if isinstance(node, ast.ImportFrom)
               and any(a.name == "main" for a in node.names)]
    calls_main = any(
        isinstance(node, ast.If)
        and isinstance(node.test, ast.Compare)
        and getattr(node.test.left, "id", "") == "__name__"
        and any(isinstance(c, ast.Call)
                and getattr(c.func, "id", "") == "main"
                for b in node.body for c in ast.walk(b))
        for node in tree.body)
    return imports, calls_main


@pytest.mark.parametrize("shim", sorted(SHIMS), ids=lambda s: s)
def test_shim_forwards_to_packaged_main(shim):
    mod_name, _ = SHIMS[shim]
    path = os.path.join(REPO, "scripts", shim)
    imports, calls_main = _shim_main_import(path)
    assert imports, f"{shim} has no `from ... import main`"
    assert imports[0].module == mod_name, (
        f"{shim} forwards to {imports[0].module!r}, pyproject points "
        f"the console script at {mod_name!r} — the two dispatches "
        f"drifted")
    assert calls_main, f"{shim} never calls main() under __main__"
    # the forwarded-to symbol really exists and is callable
    assert callable(getattr(importlib.import_module(mod_name), "main"))


def test_console_scripts_match_shims():
    """pyproject's [project.scripts] names the same module:main pairs
    the shims forward to."""
    text = open(os.path.join(REPO, "pyproject.toml")).read()
    entries = dict(re.findall(
        r'^(agnes-[\w-]+)\s*=\s*"([\w.]+):main"', text, re.M))
    for shim, (mod_name, script) in SHIMS.items():
        assert entries.get(script) == mod_name, (script, entries)


def test_jax_free_shims_stay_jax_free():
    """The modelcheck and metrics CLIs must be importable (and the
    shims' forwarded mains resolvable) without jax entering the
    interpreter — the ci.sh gate slot and the wedged-box postmortem
    path both depend on it."""
    import subprocess

    code = (
        "import importlib, sys\n"
        "for m in ('agnes_tpu.analysis.modelcheck',"
        " 'agnes_tpu.utils.metrics_cli',"
        " 'agnes_tpu.analysis.schedcheck'):\n"
        "    assert callable(importlib.import_module(m).main)\n"
        "assert 'jax' not in sys.modules, 'jax leaked into the CLIs'\n"
        "print('SHIM-JAXFREE-OK')\n")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0 and "SHIM-JAXFREE-OK" in out.stdout, (
        out.stdout, out.stderr)
