"""Differential tests: C++ native core vs the Python oracle core.

The exhaustive state-machine sweep is the §4(b) test from SURVEY.md:
the Step x Event x guard space is tiny, so every reachable-or-not
combination is checked for byte-identical (state', message) output.
"""

import itertools
import random

import pytest

from agnes_tpu.core import native as N
from agnes_tpu.core import state_machine as sm
from agnes_tpu.core.round_votes import RoundVotes, ThreshKind
from agnes_tpu.crypto import ed25519_ref as ed
from agnes_tpu.types import Vote, VoteType

rng = random.Random(7)


def _all_events():
    evs = []
    for tag in sm.EventTag:
        if tag in (sm.EventTag.NEW_ROUND_PROPOSER, sm.EventTag.POLKA_VALUE,
                   sm.EventTag.PRECOMMIT_VALUE):
            evs += [sm.Event(tag, value=v) for v in (1, 2)]
        elif tag == sm.EventTag.PROPOSAL:
            evs += [sm.Event(tag, value=v, pol_round=pr)
                    for v in (1, 2) for pr in (-2, -1, 0, 1, 2)]
        else:
            evs.append(sm.Event(tag))
    return evs


def _all_states():
    states = []
    for step in sm.Step:
        for round in (0, 1, 2):
            for locked in (None, sm.RoundValue(0, 1), sm.RoundValue(1, 2),
                           sm.RoundValue(2, 1)):
                for valid in (None, sm.RoundValue(0, 1),
                              sm.RoundValue(1, 2)):
                    states.append(sm.State(height=5, round=round, step=step,
                                           locked=locked, valid=valid))
    return states


def test_exhaustive_state_machine_parity():
    """Every (state, round, event) pair: C++ == Python, field for field."""
    events = _all_events()
    count = 0
    for s in _all_states():
        for round in (0, 1, 2, 3):
            for e in events:
                py_s, py_m = sm.apply(s, round, e)
                c_s, c_m = N.native_apply(s, round, e)
                assert c_s == py_s, (s, round, e, c_s, py_s)
                assert c_m == py_m, (s, round, e, c_m, py_m)
                count += 1
    assert count == len(_all_states()) * 4 * len(_all_events())


def test_tally_differential_fuzz():
    """Random identified/anonymous vote streams: thresholds, skip weight
    and equivocation evidence agree at every single step."""
    for trial in range(20):
        total = rng.randrange(4, 30)
        py = RoundVotes(height=1, round=0, total=total)
        cc = N.NativeRoundVotes(height=1, round=0, total=total)
        for _ in range(80):
            vote = Vote(
                typ=rng.choice([VoteType.PREVOTE, VoteType.PRECOMMIT]),
                round=0,
                value=rng.choice([None, 1, 2, 3]),
                validator=rng.choice([None] + list(range(8))))
            w = rng.randrange(1, 4)
            t_py = py.add_vote(vote, w)
            t_cc = cc.add_vote(vote, w)
            assert t_cc == t_py, (trial, vote, w, t_cc, t_py)
            assert cc.skip_weight() == py.skip_weight()
        eq_py = [(e.round, e.typ, e.validator, e.first_value, e.second_value)
                 for e in py.equivocations]
        eq_cc = [(e.round, e.typ, e.validator, e.first_value, e.second_value)
                 for e in cc.equivocations]
        assert eq_cc == eq_py


def test_tally_thresh_ladder_reference_parity():
    """The reference's own add_votes test ladder (round_votes.rs:107-132):
    Init -> Init -> Any -> Value with total weight 4, identity-free."""
    cc = N.NativeRoundVotes(height=1, round=0, total=4)
    v = Vote(typ=VoteType.PREVOTE, round=0, value=None, validator=None)
    assert cc.add_vote(v, 1).kind == ThreshKind.INIT
    assert cc.add_vote(v, 1).kind == ThreshKind.INIT  # duplicate counts!
    w = Vote(typ=VoteType.PREVOTE, round=0, value=7, validator=None)
    assert cc.add_vote(w, 1).kind == ThreshKind.ANY   # 3*3 > 2*4 mixed
    t = cc.add_vote(w, 1)
    # nil=2, value7=2: seen 4 -> Any stays (no single bucket has quorum)
    assert t.kind == ThreshKind.ANY
    t = cc.add_vote(w, 1)
    assert t.kind == ThreshKind.VALUE and t.value == 7  # 3*3 > 2*4


def test_validator_set_parity():
    keys = [ed.keypair(bytes([i]) * 32)[1] for i in range(6)]
    entries = [(keys[i], i + 1) for i in range(6)]
    shuffled = entries[:]
    rng.shuffle(shuffled)
    cc = N.NativeValidatorSet(shuffled + [shuffled[0]])  # dup dropped
    assert len(cc) == 6
    assert cc.total_power == sum(p for _, p in entries)
    # sorted by pubkey
    vals = cc.validators()
    assert [pk for pk, _ in vals] == sorted(keys)
    for pk, p in entries:
        assert vals[cc.index_of(pk)] == (pk, p)
    assert cc.index_of(b"\x00" * 32) == -1
    # mutations
    assert cc.update(keys[0], 100)
    assert cc.total_power == sum(p for _, p in entries) - dict(entries)[keys[0]] + 100
    assert cc.remove(keys[0])
    assert len(cc) == 5
    assert not cc.remove(keys[0])
    cc.add(keys[0], 3)
    assert len(cc) == 6
    # hash changes with content, stable across construction order
    h1 = cc.hash()
    cc2 = N.NativeValidatorSet(cc.validators())
    assert cc2.hash() == h1
    cc2.update(keys[1], 50)
    assert cc2.hash() != h1


def test_proposer_rotation_parity():
    """The C++ rotation must reproduce the Python ProposerRotation
    sequence step for step — all planes must name the same proposer."""
    from agnes_tpu.core.validators import ProposerRotation, Validator, \
        ValidatorSet

    keys = [ed.keypair(bytes([i + 30]) * 32)[1] for i in range(5)]
    powers = [1, 2, 5, 1, 3]
    py_set = ValidatorSet([Validator(pk, p) for pk, p in zip(keys, powers)])
    py_rot = ProposerRotation(py_set)
    cc_set = N.NativeValidatorSet(list(zip(keys, powers)))
    cc_rot = N.NativeProposerRotation(cc_set)
    seq_py = [py_rot.step() for _ in range(60)]
    seq_cc = [cc_rot.step() for _ in range(60)]
    assert seq_cc == seq_py
    # weighted fairness over a full cycle
    total = sum(powers)
    counts = [0] * 5
    for i in seq_py[:2 * total]:
        counts[i] += 1
    sorted_powers = [p for _, p in cc_set.validators()]
    assert counts == [2 * p for p in sorted_powers]


def test_duplicate_add_latest_wins():
    """add() of an existing pubkey replaces the power (Python parity,
    deterministic across libstdc++ versions)."""
    keys = [ed.keypair(bytes([i + 50]) * 32)[1] for i in range(3)]
    cc = N.NativeValidatorSet([(keys[0], 1), (keys[1], 2), (keys[2], 3)])
    cc.add(keys[1], 99)
    assert len(cc) == 3
    assert dict(cc.validators())[keys[1]] == 99
    # construction-time duplicates: last entry wins too
    cc2 = N.NativeValidatorSet([(keys[0], 1), (keys[0], 7)])
    assert cc2.validators() == [(keys[0], 7)]


def test_equivocation_no_truncation():
    """More than 1024 equivocating validators: every record survives."""
    n = 1500
    cc = N.NativeRoundVotes(height=1, round=0, total=n)
    for v in range(n):
        cc.add_vote(Vote(typ=VoteType.PREVOTE, round=0, value=1,
                         validator=v), 1)
        cc.add_vote(Vote(typ=VoteType.PREVOTE, round=0, value=2,
                         validator=v), 1)
    eq = cc.equivocations
    assert len(eq) == n
    assert {e.validator for e in eq} == set(range(n))


@pytest.mark.parametrize("i", range(3))
def test_native_ed25519_rfc_vectors(i):
    from tests.test_ed25519_ref import VECTORS
    seed_h, pub_h, msg_h, sig_h = VECTORS[i]
    seed, pub = bytes.fromhex(seed_h), bytes.fromhex(pub_h)
    msg, sig = bytes.fromhex(msg_h), bytes.fromhex(sig_h)
    assert N.pubkey(seed) == pub
    assert N.sign(seed, msg) == sig
    assert N.verify(pub, msg, sig)


def test_native_verify_batch_and_edge_cases():
    seeds = [bytes([i + 1]) * 32 for i in range(6)]
    msgs = [bytes([i]) * 45 for i in range(6)]
    pks = [N.pubkey(s) for s in seeds]
    sigs = [N.sign(s, m) for s, m in zip(seeds, msgs)]
    # corrupt lane 2 (sig), lane 4 (wrong key)
    sigs[2] = sigs[2][:3] + bytes([sigs[2][3] ^ 0x40]) + sigs[2][4:]
    pks[4] = N.pubkey(b"\x99" * 32)
    ok = N.verify_batch(pks, msgs, sigs)
    assert ok == [True, True, False, True, False, True]
    # oracle agreement on every lane
    for i in range(6):
        assert ok[i] == ed.verify(pks[i], msgs[i], sigs[i])
    # malleable S rejected
    s = int.from_bytes(sigs[0][32:], "little")
    bad = sigs[0][:32] + (s + ed.L).to_bytes(32, "little")
    assert not N.verify(pks[0], msgs[0], bad)
    # empty batch
    assert N.verify_batch([], [], []) == []


def test_native_cross_verifies_python_and_jax_signatures():
    """All three implementations interoperate on the same bytes."""
    seed = bytes(range(32))
    msg = b"m" * 45
    assert N.verify(ed.keypair(seed)[1], msg, ed.sign(seed, msg))
    assert ed.verify(N.pubkey(seed), msg, N.sign(seed, msg))
