"""Tally window rotation + on-device height advance.

VERDICT r2 items 2/3: the device tally tracks a W-round window that
must rotate with the instance's round (the reference tallies *any*
round via its per-round map, round_votes.rs:74-97), and a decision must
install State::new(h+1) (README.md:43-44) so multi-height throughput
never leaves the device.

The long-nil-round scenario is parity-checked against the pure host
state machine (core.state_machine, the oracle that is itself pinned to
the reference line-by-line).
"""

import jax.numpy as jnp
import numpy as np

from agnes_tpu.core import state_machine as sm
from agnes_tpu.core.state_machine import EventTag, Step
from agnes_tpu.device.tally import TallyConfig, TallyState, rotate_window
from agnes_tpu.harness.device_driver import DeviceDriver
from agnes_tpu.types import NIL_ID, VoteType


def host_nil_rounds_then_decide(n_nil: int, slot: int) -> sm.State:
    """Oracle: drive one host state machine through n_nil nil rounds and
    a deciding round, mirroring the driver's schedule."""
    s = sm.State.new(0)
    for r in range(n_nil):
        s, _ = s.apply(r, sm.Event(EventTag.NEW_ROUND))
        s, _ = s.apply(r, sm.Event(EventTag.TIMEOUT_PROPOSE))
        s, _ = s.apply(r, sm.Event(EventTag.POLKA_NIL))
        # precommit-nil quorum maps to PRECOMMIT_ANY (device/tally.py)
        s, _ = s.apply(r, sm.Event(EventTag.PRECOMMIT_ANY))
        s, _ = s.apply(r, sm.Event(EventTag.TIMEOUT_PRECOMMIT))
    r = n_nil
    s, _ = s.apply(r, sm.Event(EventTag.NEW_ROUND))
    s, m = s.apply(r, sm.Event(EventTag.PROPOSAL, value=slot, pol_round=-1))
    s, m = s.apply(r, sm.Event(EventTag.POLKA_VALUE, value=slot))
    s, m = s.apply(r, sm.Event(EventTag.PRECOMMIT_VALUE, value=slot))
    assert s.step == Step.COMMIT and m.tag == sm.MsgTag.DECISION
    return s, m


def test_six_nil_rounds_then_round6_decision():
    """W=4 window, decision at round 6 — impossible without rotation
    (rounds >= 4 were silently dropped before)."""
    I, V, slot = 3, 4, 1
    d = DeviceDriver(I, V, n_rounds=4, n_slots=4, proposer_is_self=False)
    for r in range(6):
        d.run_nil_round(r)
    # after six nil rounds every instance sits at round 6, window rotated
    assert (np.asarray(d.state.round) == 6).all()
    assert (np.asarray(d.tally.base_round) == 5).all()
    d.run_proposed_round(6, slot)
    assert d.all_decided(value=slot)
    assert (d.stats.decision_round == 6).all()
    # parity with the pure host machine
    s_host, m_host = host_nil_rounds_then_decide(6, slot)
    assert (np.asarray(d.state.step) == int(s_host.step)).all()
    assert (np.asarray(d.state.round) == s_host.round).all()
    assert (d.stats.decision_value == m_host.decision.value).all()
    assert (d.stats.decision_round == m_host.decision.round).all()


def test_rotate_window_preserves_kept_rows():
    cfg = TallyConfig(n_validators=3, n_rounds=4, n_slots=2)
    t = TallyState.new(2, cfg)
    # mark round-2 (row 2) and round-3 (row 3) with distinct data
    t = t._replace(
        weights=t.weights.at[:, 2, 0, 1].set(7).at[:, 3, 1, 2].set(9),
        skip_w=t.skip_w.at[:, 2].set(5),
        skipped=t.skipped.at[:, 3].set(True))
    t2 = rotate_window(t, jnp.asarray([2, 0]))
    # instance 0: base 2 -> old row 2 is new row 0, old row 3 is new row 1
    assert int(t2.weights[0, 0, 0, 1]) == 7
    assert int(t2.weights[0, 1, 1, 2]) == 9
    assert int(t2.skip_w[0, 0]) == 5
    assert bool(t2.skipped[0, 1])
    # rows 2..3 are fresh
    assert int(t2.weights[0, 2].sum()) == 0 and int(t2.weights[0, 3].sum()) == 0
    assert not bool(t2.skipped[0, 2]) and not bool(t2.skipped[0, 3])
    # instance 1: base unchanged -> identical rows
    assert np.array_equal(np.asarray(t2.weights[1]), np.asarray(t.weights[1]))
    assert int(t2.base_round[0]) == 2 and int(t2.base_round[1]) == 0


def test_late_vote_for_rotated_out_round_is_dropped_on_device():
    """Past-window votes must not tally (the host fallback owns them)."""
    I, V = 2, 4
    d = DeviceDriver(I, V, proposer_is_self=False)
    for r in range(4):
        d.run_nil_round(r)
    assert (np.asarray(d.tally.base_round) == 3).all()
    w_before = np.asarray(d.tally.weights).copy()
    # a full prevote phase for round 1 (< base): silently dropped
    d.step(phase=d.phase(1, VoteType.PREVOTE, 1))
    assert np.array_equal(np.asarray(d.tally.weights), w_before)


def test_height_advance_runs_ten_heights():
    I, V, H = 4, 4, 10
    d = DeviceDriver(I, V, advance_height=True)
    d.run_heights(H)
    assert (np.asarray(d.state.height) == H).all()
    assert (np.asarray(d.state.step) == int(Step.NEW_ROUND)).all()
    assert (np.asarray(d.state.round) == 0).all()
    assert (np.asarray(d.state.locked_round) == -1).all()
    assert d.stats.decisions_total == I * H
    # tally fully reset for the next height
    assert int(np.asarray(d.tally.weights).sum()) == 0
    assert (np.asarray(d.tally.base_round) == 0).all()


def test_height_advance_resets_slots_and_redecides_same_value():
    """Across heights the same slot decides again — the voted/emitted
    rows must really have been cleared or dedup would eat the votes."""
    I, V = 2, 4
    d = DeviceDriver(I, V, advance_height=True)
    for h in range(3):
        d.run_honest_round(0, slot=2)
        assert d.stats.decisions_total == (h + 1) * I
    assert (np.asarray(d.state.height) == 3).all()


def test_stale_height_phase_is_fenced():
    """A replayed phase of prior-height votes must not tally after the
    on-device height advance (VotePhase.height fencing)."""
    I, V, slot = 2, 4, 1
    d = DeviceDriver(I, V, advance_height=True)
    d.step()
    pv = d.phase(0, VoteType.PREVOTE, slot)     # height-0 phases
    pc = d.phase(0, VoteType.PRECOMMIT, slot)
    d.step(phase=pv)
    d.step(phase=pc)
    assert d.stats.decisions_total == I         # height 0 decided
    assert (np.asarray(d.state.height) == 1).all()
    # replay the identical height-0 quorum phases at height 1
    d.step(phase=pv)
    d.step(phase=pc)
    assert d.stats.decisions_total == I         # no bogus h+1 decision
    assert int(np.asarray(d.tally.weights).sum()) == 0


def test_equiv_evidence_survives_height_advance():
    I, V = 2, 8
    d = DeviceDriver(I, V, advance_height=True)
    d.run_equivocation_phase(0, VoteType.PREVOTE, 1, 2, frac=0.25)
    flagged = d.equivocators_detected()
    assert (flagged == 2).all()
    d.run_honest_round(0, slot=1)
    assert (np.asarray(d.state.height) == 1).all()
    assert (d.equivocators_detected() == flagged).all()
