"""Bounded model checker (analysis/modelcheck.py, ISSUE 6) — checker
soundness, mutation detection, minimization, corpus determinism, CLI.

Everything here is pure CPU with ZERO XLA compiles (the checker never
imports jax — asserted below), so the file sits in conftest._CHEAP.
The device-plane half of the story — corpus schedules replayed through
VoteBatcher -> fused step — lives in tests/test_cross_plane.py, which
already owns the compile-bearing replay path.
"""

import json
import os
import subprocess
import sys
import time

import pytest

from agnes_tpu.analysis import modelcheck as mc
from agnes_tpu.harness.simulator import Network

CORPUS_DIR = os.path.join(os.path.dirname(__file__), "corpus")


# ---------------------------------------------------------------------------
# zero-jax / zero-compile guarantee
# ---------------------------------------------------------------------------


def test_checker_import_is_jax_free():
    """The ci.sh gate slot (pre-test, beside agnes_lint) depends on the
    checker never touching jax: importing and RUNNING an exploration
    must not pull jax into the interpreter."""
    code = (
        "import sys\n"
        "from agnes_tpu.analysis import modelcheck as mc\n"
        "rep = mc.explore(mc.MCConfig(name='t', depth=3))\n"
        "assert rep.states > 1 and not rep.violations\n"
        "assert 'jax' not in sys.modules, 'jax leaked into the checker'\n"
        "print('JAXFREE-OK')\n")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0 and "JAXFREE-OK" in out.stdout, (
        out.stdout, out.stderr)


# ---------------------------------------------------------------------------
# step-mode determinism + schedule serialization
# ---------------------------------------------------------------------------


def _walk(cfg, seed, steps):
    import random

    rng = random.Random(seed)
    net = mc.build_network(cfg)
    sched = []
    for _ in range(steps):
        acts = net.mc_enabled(max_round=cfg.max_round)
        if not acts:
            break
        a = rng.choice(acts)
        assert net.mc_apply(a)
        sched.append(a)
    return net, sched


def test_schedule_replay_is_deterministic():
    cfg = mc.MCConfig(name="det", depth=0, max_round=2)
    net, sched = _walk(cfg, seed=7, steps=60)
    for _ in range(2):
        net2 = mc.build_network(cfg)
        assert all(net2.run_schedule(sched))
        assert net2.mc_digest() == net.mc_digest()
        assert [nd.decided.get(0) for nd in net2.nodes] == \
            [nd.decided.get(0) for nd in net.nodes]


def test_schedule_json_roundtrip():
    cfg = mc.MCConfig(name="json", depth=0, max_round=2)
    net, sched = _walk(cfg, seed=3, steps=40)
    js = [Network.action_to_json(a) for a in sched]
    assert [Network.action_from_json(a) for a in js] == sched
    net2 = mc.build_network(cfg)
    net2.run_schedule(json.loads(json.dumps(js)))   # through real JSON
    assert net2.mc_digest() == net.mc_digest()


def test_run_schedule_skips_unenabled_actions():
    """The ddmin tolerance contract: a not-currently-enabled action is
    a recorded no-op, leaving the state untouched."""
    cfg = mc.MCConfig(name="skip", depth=0)
    net = mc.build_network(cfg)
    d0 = net.mc_digest()
    flags = net.run_schedule([("d", 2, 3), ("h",),
                              ("t", 0, 0, 0, 2)])
    assert flags == [False, False, False]
    assert net.mc_digest() == d0


# ---------------------------------------------------------------------------
# digest hardening (ISSUE 7 satellite): canonical encoding, not repr
# ---------------------------------------------------------------------------


def test_digest_insensitive_to_container_insertion_order():
    """mc_digest must hash a SORTED canonical encoding: rebuilding the
    network's dicts in permuted insertion order (channels, proposed
    values, tally rounds/weights) must not change the digest."""
    cfg = mc.MCConfig(name="perm", depth=0, max_round=2)
    net, _sched = _walk(cfg, seed=5, steps=50)
    d0 = net.mc_digest()
    # permute every dict the canonical form walks
    net._channels = dict(reversed(list(net._channels.items())))
    net._proposed = {h: set(v) for h, v in
                     reversed(list(net._proposed.items()))}
    for nd in net.nodes:
        hv = nd.votes.votes
        hv.rounds = dict(reversed(list(hv.rounds.items())))
        for rv in hv.rounds.values():
            rv.prevotes.weights = dict(
                reversed(list(rv.prevotes.weights.items())))
            rv.seen = dict(reversed(list(rv.seen.items())))
    assert net.mc_digest() == d0
    # and across independent constructions of the same state
    net2 = mc.build_network(cfg)
    net2.run_schedule(_sched)
    assert net2.mc_digest() == d0


# ---------------------------------------------------------------------------
# symmetry reduction (ISSUE 7 tentpole): orbit equivalence, caps, POR
# composition
# ---------------------------------------------------------------------------


SYM_CONFIGS = (
    mc.MCConfig(name="sym_honest", depth=6, max_round=1),
    mc.MCConfig(name="sym_part", depth=5, max_round=1,
                partition=((0, 1), (2, 3))),
    mc.MCConfig(name="sym_n7", n=7, depth=3, max_round=1,
                behaviors=("honest",) * 7),
    # ISSUE 9: the per-epoch group (weight shifts onto a pinned
    # proposer slot at the height-1 boundary — nodes {2, 3} stay
    # interchangeable in BOTH epochs) and the churn alphabet must
    # preserve the orbit-set-equality contract too
    mc.MCConfig(name="sym_epoch", depth=6, max_round=1,
                epochs=((1, (3, 1, 1, 1)),)),
    mc.MCConfig(name="sym_churn", depth=5, max_round=1,
                churn_budget=1),
)


@pytest.mark.parametrize("cfg", SYM_CONFIGS, ids=lambda c: c.name)
def test_symmetry_reaches_identical_orbit_set(cfg):
    """The reduced search must visit EXACTLY the canonical orbits of
    the full search — fewer states, same coverage (and both clean)."""
    a = mc.explore(cfg, sym=True, por=True, collect_digests=True)
    b = mc.explore(cfg, sym=False, por=True, collect_orbit_digests=True)
    assert a.complete and b.complete
    assert a.sym_perms > 1
    assert a.digests == b.orbit_digests
    assert a.states < b.states              # the reduction is real
    assert a.states == len(b.orbit_digests)
    assert not a.violations and not b.violations


def test_symmetry_exploration_is_deterministic():
    cfg = mc.MCConfig(name="sym_det", depth=5, max_round=1)
    a = mc.explore(cfg, sym=True, collect_digests=True)
    b = mc.explore(cfg, sym=True, collect_digests=True)
    assert (a.states, a.transitions, a.digests) == \
        (b.states, b.transitions, b.digests)


def test_symmetry_group_shape():
    """n=4 equal-power honest: proposer slots pin nodes {0, 1}, nodes
    {2, 3} swap (|G| = 2).  n=7 at a depth below the decision bound:
    only height-0 proposers {0, 1} pin, five nodes permute (capped at
    24 perms).  Weighted n4: the asymmetric rotation pins everything."""
    s4 = mc.build_symmetry(mc.MCConfig(name="g4", depth=10, max_round=1))
    assert len(s4.perms) == 2 and s4.h_cap == 1
    assert s4.perms[1] == (0, 1, 3, 2)
    s7 = mc.build_symmetry(mc.MCConfig(
        name="g7", n=7, depth=5, max_round=1,
        behaviors=("honest",) * 7))
    assert len(s7.perms) == 24 and s7.h_cap == 0
    sw = mc.build_symmetry(mc.MCConfig(
        name="gw", depth=10, max_round=1, powers=(1, 1, 1, 3)))
    assert len(sw.perms) == 1


def test_symmetry_cap_tripwire_fires_loud(monkeypatch):
    """If a state escapes the envelope the group was built for, the
    exploration must RAISE (merges would be unsound), not silently
    report reduced numbers."""
    import dataclasses as dc

    cfg = mc.MCConfig(name="cap", depth=4, max_round=1)
    real = mc.build_symmetry

    def doctored(c, executor_cls=None, max_perms=24):
        return dc.replace(real(c, executor_cls, max_perms), h_cap=-1)

    monkeypatch.setattr(mc, "build_symmetry", doctored)
    with pytest.raises(mc.SymmetryCapError):
        mc.explore(cfg, sym=True)


def test_por_x_symmetry_flags_same_violations_as_full():
    """Composition soundness on the mutant configs: POR x symmetry
    must flag the same property as the full (no-POR, no-sym)
    exploration, while visiting strictly fewer states on the honest
    configs (the mutants stop at first violation, so only coverage —
    not counts — is comparable there)."""
    for name, (mut_cls, prop, cfg) in mc.MUTANTS.items():
        reduced = mc.explore(cfg, executor_cls=mut_cls, por=True,
                             sym=True)
        full = mc.explore(cfg, executor_cls=mut_cls, por=False,
                          sym=False)
        assert any(c.violation.property == prop
                   for c in reduced.violations), name
        assert any(c.violation.property == prop
                   for c in full.violations), name
    cfg = mc.MCConfig(name="porsym", depth=5, max_round=1)
    reduced = mc.explore(cfg, por=True, sym=True)
    full = mc.explore(cfg, por=False, sym=False)
    assert not reduced.violations and not full.violations
    assert reduced.states < full.states
    assert reduced.transitions < full.transitions


def test_sym_baseline_covers_shared_smoke_configs():
    """The orbit-reduction metric's baseline names exactly the
    baselined smoke configs still present in the scope: PR 6's six
    plus the ISSUE 9 epoch/churn shards (the weighted additions
    remain unbaselined); the per-epoch metric needs at least one
    EPOCH shard in the baseline."""
    names = {c.name for c in mc.SMOKE_SCOPE}
    assert set(mc.SYM_BASELINE_STATES) <= names
    assert len(mc.SYM_BASELINE_STATES) == 9
    by_name = {c.name: c for c in mc.SMOKE_SCOPE}
    assert any(by_name[n].epochs is not None
               for n in mc.SYM_BASELINE_STATES)


def test_per_epoch_symmetry_group_shape():
    """ISSUE 9 soundness boundary: interchangeable nodes must share
    their power in EVERY epoch window live inside the envelope, and
    their sleepy-churn eligibility.  Weight rotating onto a PINNED
    proposer slot (original 0 -> sorted 1) keeps {2, 3} swappable;
    onto a swap node (original 2 -> sorted 3) it pins the whole group;
    a churnable-set split across the bucket pins it too."""
    s = mc.build_symmetry(mc.MCConfig(
        name="ge", depth=10, max_round=1, epochs=((1, (3, 1, 1, 1)),)))
    assert len(s.perms) == 2 and s.perms[1] == (0, 1, 3, 2)
    s2 = mc.build_symmetry(mc.MCConfig(
        name="ge2", depth=10, max_round=1, epochs=((1, (1, 1, 3, 1)),)))
    assert len(s2.perms) == 1
    s3 = mc.build_symmetry(mc.MCConfig(
        name="gc", depth=10, max_round=1, churn_budget=1,
        churnable=(2,)))
    assert len(s3.perms) == 1
    s4 = mc.build_symmetry(mc.MCConfig(
        name="gc2", depth=10, max_round=1, churn_budget=1))
    assert len(s4.perms) == 2


# ---------------------------------------------------------------------------
# weighted validator power (ISSUE 7 tentpole)
# ---------------------------------------------------------------------------


def test_weighted_config_roundtrips_and_moves_quorum():
    cfg = mc.MCConfig(name="w", powers=(1, 1, 1, 3), depth=6)
    assert mc.MCConfig.from_json(cfg.to_json()) == cfg
    net = mc.build_network(cfg)
    assert net.vset.total_power == 6
    assert sorted(v.voting_power for v in net.vset) == [1, 1, 1, 3]
    # the three weight-1 validators are a head-count majority but NOT
    # a weighted quorum — the boundary the weight-blind mutant trips
    from agnes_tpu.core.round_votes import is_quorum
    lights = sum(v.voting_power for v in net.vset
                 if v.voting_power == 1)
    assert not is_quorum(lights, net.vset.total_power)
    assert is_quorum(lights + 3, net.vset.total_power)


def test_weight_blind_mutant_caught_minimized_and_honest_clean():
    name = "decide_weight_blind_quorum"
    mut_cls, prop, cfg = mc.MUTANTS[name]
    rep = mc.explore(cfg, executor_cls=mut_cls)
    caught = [c for c in rep.violations if c.violation.property == prop]
    assert caught, f"monitors missed the {name} mutant"
    small = mc.minimize(cfg, caught[0].schedule, prop,
                        executor_cls=mut_cls)
    assert mc.reproduces(cfg, small, prop, executor_cls=mut_cls)
    # the minimized schedule is clean under CORRECT weighting: the
    # violation is the head-count tally's, not the checker's
    _, honest = mc.run_with_monitors(cfg, small)
    assert not honest
    # the cert monitor saw the real arithmetic: weight below +2/3
    detail = caught[0].violation.detail
    assert "< +2/3" in detail or "weight" in detail


def test_weighted_smoke_slice_explores_clean():
    cfg = mc.MCConfig(name="w_slice", powers=(1, 1, 1, 3), depth=6,
                      max_round=1)
    rep = mc.explore(cfg)
    assert rep.complete and not rep.violations
    assert rep.states > 500


# ---------------------------------------------------------------------------
# validator-set epochs + sleepy churn (ISSUE 9 tentpole)
# ---------------------------------------------------------------------------


def test_epoch_config_roundtrips_and_moves_quorum_per_height():
    cfg = mc.MCConfig(name="e", epochs=((1, (3, 1, 1, 1)),), depth=6,
                      churn_budget=1, churnable=(0, 2))
    assert mc.MCConfig.from_json(cfg.to_json()) == cfg
    net = mc.build_network(cfg)
    # genesis below the boundary, the rotated set at and past it
    assert net.epoch_total_at(0) == 4
    assert net.epoch_total_at(1) == 6 and net.epoch_total_at(5) == 6
    assert sorted(net.epoch_powers_at(1)) == [1, 1, 1, 3]
    # the height-1 quorum boundary falls between vote counts: the
    # three weight-1 validators are a head-count majority with 3/6
    from agnes_tpu.core.round_votes import is_quorum
    assert not is_quorum(3, net.epoch_total_at(1))
    assert is_quorum(5, net.epoch_total_at(1))


def test_pre_epoch_config_json_is_bit_stable():
    """The three ISSUE 9 knobs serialize ONLY when non-default —
    every pre-epoch corpus entry must regenerate byte-identical."""
    d = mc.MCConfig(name="w", powers=(1, 1, 1, 3), depth=6).to_json()
    assert "epochs" not in d and "churn_budget" not in d \
        and "churnable" not in d


def test_churn_budget_bounds_the_sleep_alphabet():
    """Sleeps are budgeted exactly like faults; an asleep node gets
    no deliveries and fires no timers until its wake."""
    cfg = mc.MCConfig(name="cb", depth=0, churn_budget=1)
    net = mc.build_network(cfg)
    acts0 = net.mc_enabled(max_round=1)
    sleeps = [a for a in acts0 if a[0] == "s"]
    assert len(sleeps) == 4            # every honest node may nap
    # nap a node that has traffic waiting, so the hold is observable
    j = next(a[2] for a in acts0 if a[0] == "d")
    assert net.mc_apply(("s", j))
    acts = net.mc_enabled(max_round=1)
    assert not any(a[0] == "s" for a in acts)      # budget spent
    assert [a for a in acts if a[0] == "w"] == [("w", j)]
    assert not any(a[0] == "d" and a[2] == j for a in acts)
    assert not any(a[0] == "t" and a[1] == j for a in acts)
    assert net.mc_apply(("w", j))
    assert any(a[0] == "d" and a[2] == j
               for a in net.mc_enabled(max_round=1))


def test_churn_schedule_serializes_and_replays_deterministically():
    cfg = mc.MCConfig(name="chd", depth=0, max_round=2, churn_budget=2)
    net, sched = _walk(cfg, seed=11, steps=90)
    assert any(a[0] in ("s", "w") for a in sched), sched
    js = [Network.action_to_json(a) for a in sched]
    assert [Network.action_from_json(a) for a in js] == sched
    net2 = mc.build_network(cfg)
    net2.run_schedule(json.loads(json.dumps(js)))
    assert net2.mc_digest() == net.mc_digest()


def test_epoch_decisions_carry_epoch_denominated_certs():
    """Positive monitor coverage ACROSS a set change: the milestone
    schedule decides at heights 0 and 1, and each decision's
    certificate is denominated in the total of the epoch live at ITS
    height (4 at genesis, 6 past the boundary) — the invariant the
    stale-epoch mutants break."""
    cfg, pred, seed, bias = \
        mc.CORPUS_GOALS["mc_epoch_set_change_decides"]
    sched = mc._walk_until(cfg, pred, seed, max_steps=1500,
                           deliver_bias=bias)
    net, viols = mc.run_with_monitors(cfg, sched)
    assert not viols
    for nd in net.nodes:
        totals = {c.height: c.total for c in nd.decision_certs}
        assert totals == {0: 4, 1: 6}
        for c in nd.decision_certs:
            assert 3 * c.weight > 2 * c.total


def test_stale_epoch_mutant_caught_minimized_and_honest_clean():
    name = "decide_stale_epoch_quorum"
    mut_cls, prop, cfg = mc.MUTANTS[name]
    rep = mc.explore(cfg, executor_cls=mut_cls)
    caught = [c for c in rep.violations if c.violation.property == prop]
    assert caught, f"monitors missed the {name} mutant"
    small = mc.minimize(cfg, caught[0].schedule, prop,
                        executor_cls=mut_cls)
    assert mc.reproduces(cfg, small, prop, executor_cls=mut_cls)
    _, honest = mc.run_with_monitors(cfg, small)
    assert not honest
    # the epoch-indexed cert monitor named the real defect: a quorum
    # denominated against the wrong validator-set epoch
    assert "stale validator-set epoch" in caught[0].violation.detail


def test_wake_reset_mutant_caught_minimized_and_honest_clean():
    name = "wake_resets_round_state"
    mut_cls, prop, cfg = mc.MUTANTS[name]
    rep = mc.explore(cfg, executor_cls=mut_cls)
    caught = [c for c in rep.violations if c.violation.property == prop]
    assert caught, f"monitors missed the {name} mutant"
    small = mc.minimize(cfg, caught[0].schedule, prop,
                        executor_cls=mut_cls)
    assert mc.reproduces(cfg, small, prop, executor_cls=mut_cls)
    _, honest = mc.run_with_monitors(cfg, small)
    assert not honest
    # the minimized schedule is the sleep/wake cycle itself
    assert {a[0] for a in small} <= {"s", "w", "d", "t"}
    assert any(a[0] == "w" for a in small)


def test_deep_stale_epoch_mutant_bites_across_the_boundary():
    """The cross-boundary drill: the violation lives at height 1 —
    past any exhaustively explorable depth — so it is walk-discovered
    on the doctored executor, then minimized and honest-replayed like
    every explored mutant."""
    mut_cls, prop, cfg, goal, seed, bias = \
        mc.DEEP_MUTANTS["stale_epoch_across_boundary"]
    sched = mc._walk_until(cfg, goal, seed, max_steps=1500,
                           deliver_bias=bias, executor_cls=mut_cls)
    assert sched is not None
    assert mc.reproduces(cfg, sched, prop, executor_cls=mut_cls)
    small = mc.minimize(cfg, sched, prop, executor_cls=mut_cls)
    assert mc.reproduces(cfg, small, prop, executor_cls=mut_cls)
    _, honest = mc.run_with_monitors(cfg, small)
    assert not honest
    # the caught certificate is PAST the boundary: replaying the
    # minimized schedule on the mutant shows a height-1 cert
    # denominated against the genesis total
    net, viols = mc.run_with_monitors(cfg, small,
                                      executor_cls=mut_cls)
    stale = [v for v in viols if v.property == prop]
    assert stale and "stale validator-set epoch" in stale[0].detail
    assert any(c.height == 1 and c.total == 4
               for nd in net.nodes for c in nd.decision_certs)


# ---------------------------------------------------------------------------
# exploration: determinism, POR soundness, clean smoke slices
# ---------------------------------------------------------------------------


POR_CONFIGS = (
    mc.MCConfig(name="por_honest", depth=6, max_round=1),
    mc.MCConfig(name="por_equiv", depth=5, max_round=1,
                behaviors=("equivocator", "honest", "honest", "honest")),
    mc.MCConfig(name="por_part", depth=5, max_round=1,
                partition=((0, 1), (2, 3))),
)


@pytest.mark.parametrize("cfg", POR_CONFIGS, ids=lambda c: c.name)
def test_por_reaches_exactly_the_full_state_set(cfg):
    """Partial-order reduction must prune TRANSITIONS, never states:
    the por and no-por explorations visit the identical canonical
    state set (and both run violation-free)."""
    a = mc.explore(cfg, por=True, collect_digests=True)
    b = mc.explore(cfg, por=False, collect_digests=True)
    assert a.complete and b.complete
    assert a.digests == b.digests
    assert a.states == b.states
    assert a.transitions < b.transitions     # the reduction is real
    assert not a.violations and not b.violations


def test_exploration_is_deterministic():
    cfg = mc.MCConfig(name="det2", depth=5, max_round=1)
    a = mc.explore(cfg, collect_digests=True)
    b = mc.explore(cfg, collect_digests=True)
    assert (a.states, a.transitions, a.digests) == \
        (b.states, b.transitions, b.digests)


def test_deadline_yields_clean_partial():
    cfg = mc.MCConfig(name="dl", depth=10, max_round=1)
    rep = mc.explore(cfg, deadline_at=time.time() - 1.0)
    assert not rep.complete
    assert rep.states > 0 and not rep.violations


def test_max_states_cap_yields_clean_partial():
    cfg = mc.MCConfig(name="cap", depth=10, max_round=1)
    rep = mc.explore(cfg, max_states=500)
    assert not rep.complete and 500 <= rep.states <= 600


def test_honest_decisions_carry_quorum_certs():
    """Positive monitor coverage: a real decision's DecisionCert shows
    +2/3 precommit weight (the thing the quorumless mutant breaks)."""
    cfg, pred, seed, bias = mc.CORPUS_GOALS["mc_n4_honest_decides"]
    sched = mc._walk_until(cfg, pred, seed, deliver_bias=bias)
    net, viols = mc.run_with_monitors(cfg, sched)
    assert not viols
    for nd in net.nodes:
        assert 0 in nd.decided
        (cert,) = nd.decision_certs
        assert 3 * cert.weight > 2 * cert.total


# ---------------------------------------------------------------------------
# mutation tests: the monitors must have teeth
# ---------------------------------------------------------------------------


def test_mutation_decide_without_quorum_is_caught_and_minimized():
    name = "decide_without_quorum"
    mut_cls, prop, cfg = mc.MUTANTS[name]
    rep = mc.explore(cfg, executor_cls=mut_cls)
    caught = [c for c in rep.violations if c.violation.property == prop]
    assert caught, f"monitors missed the {name} mutant"
    ce = caught[0]
    small = mc.minimize(cfg, ce.schedule, prop, executor_cls=mut_cls)
    assert len(small) <= len(ce.schedule)
    assert mc.reproduces(cfg, small, prop, executor_cls=mut_cls)
    # 1-minimality: every action in the minimized schedule is load-bearing
    for i in range(len(small)):
        trial = small[:i] + small[i + 1:]
        assert not trial or not mc.reproduces(cfg, trial, prop,
                                              executor_cls=mut_cls)
    # the violation belongs to the mutation, not the checker: the same
    # schedule on the honest executor runs clean
    _, honest = mc.run_with_monitors(cfg, small)
    assert not honest


def test_mutation_drop_evidence_is_caught_and_minimized():
    name = "drop_equivocation_evidence"
    mut_cls, prop, cfg = mc.MUTANTS[name]
    rep = mc.explore(cfg, executor_cls=mut_cls)
    caught = [c for c in rep.violations if c.violation.property == prop]
    assert caught, f"monitors missed the {name} mutant"
    small = mc.minimize(cfg, caught[0].schedule, prop,
                        executor_cls=mut_cls)
    assert mc.reproduces(cfg, small, prop, executor_cls=mut_cls)
    _, honest = mc.run_with_monitors(cfg, small)
    assert not honest
    # the honest replay SURFACES the evidence the mutant dropped
    net, _ = mc.run_with_monitors(cfg, small)
    assert any(nd.all_equivocations() for nd in net.nodes)


def test_mutation_detection_survives_por():
    """POR must not prune the violating interleavings away."""
    for name, (mut_cls, prop, cfg) in mc.MUTANTS.items():
        rep = mc.explore(cfg, executor_cls=mut_cls, por=True)
        assert any(c.violation.property == prop
                   for c in rep.violations), name


def test_self_test_end_to_end():
    out = mc.self_test()
    assert set(out) == set(mc.MUTANTS) | set(mc.DEEP_MUTANTS)
    for name, r in out.items():
        assert r["minimized_len"] <= r["schedule_len"]
        ce = r["counterexample"]
        assert ce["schedule"], name
        # the counterexample serializes as a corpus-replayable entry
        cfg = mc.MCConfig.from_json(ce["config"])
        acts = [Network.action_from_json(a) for a in ce["schedule"]]
        entry = mc.corpus_entry(f"tmp_{name}", cfg, acts, origin="test")
        assert entry["expect"]["violations"] == []   # honest: near-miss


# ---------------------------------------------------------------------------
# regression corpus (tests/corpus/*.json)
# ---------------------------------------------------------------------------


def test_corpus_exists_and_covers_the_fault_space():
    entries = mc.load_corpus(CORPUS_DIR)
    names = {e["name"] for e in entries}
    assert len(entries) >= 17, names
    behaviors = {b for e in entries for b in e["config"]["behaviors"]}
    assert {"equivocator", "nil_flood"} <= behaviors
    assert any(e["config"]["partition"] for e in entries)
    assert any(e["config"]["n"] == 7 for e in entries)
    assert any(e["expect"]["evidence"] for e in entries)
    assert any(any(r >= 1 for r, _v in e["expect"]["decided"].values())
               for e in entries if e["expect"]["decided"])
    # weighted milestones (ISSUE 7): asymmetric power vectors whose
    # +2/3 boundary falls between vote counts, with decisions
    weighted = [e for e in entries
                if e["config"].get("powers")
                and len(set(e["config"]["powers"])) > 1]
    assert len(weighted) >= 2, names
    assert any(e["expect"]["decided"] for e in weighted)
    # epoch milestones (ISSUE 9): a validator-set change at a height
    # boundary with decisions stamped on BOTH sides of it
    epoch = [e for e in entries if e["config"].get("epochs")]
    assert len(epoch) >= 2, names
    assert any("decided_heights" in e["expect"]
               and all(set(hs) == {"0", "1"}
                       for hs in e["expect"]["decided_heights"].values())
               for e in epoch), names
    # churn milestone (ISSUE 9): a serialized sleep/wake cycle rides
    # the corpus codec, and the schedule still fully decides
    churn = [e for e in entries if e["config"].get("churn_budget")]
    assert len(churn) >= 2, names
    sleepy = [e for e in churn
              if {"sleep", "wake"} <=
              {a[0] for a in e["actions"]}]
    assert any(len(e["expect"]["decided"]) == e["config"]["n"]
               for e in sleepy), names
    assert {n for n in names if n.startswith("mc_mut_")} == {
        "mc_mut_decide_without_quorum",
        "mc_mut_drop_equivocation_evidence",
        "mc_mut_decide_weight_blind_quorum",
        "mc_mut_decide_stale_epoch_quorum",
        "mc_mut_wake_resets_round_state",
        "mc_mut_stale_epoch_across_boundary"}


@pytest.mark.parametrize("entry", mc.load_corpus(CORPUS_DIR),
                         ids=lambda e: e["name"])
def test_corpus_replays_deterministically_on_host(entry):
    """Every corpus entry replays bit-stable on the (unsigned) host
    plane: decisions, evidence counts and property verdicts must match
    the stamped expectations.  The signed + device-plane replay of the
    same entries runs in test_cross_plane.py."""
    net, viols = mc.replay_corpus_entry(entry)
    net2, _ = mc.replay_corpus_entry(entry)
    assert net.mc_digest() == net2.mc_digest()


# ---------------------------------------------------------------------------
# CLI (scripts/agnes_modelcheck.py)
# ---------------------------------------------------------------------------


def _run_cli(*args, timeout=240):
    script = os.path.join(os.path.dirname(__file__), os.pardir,
                          "scripts", "agnes_modelcheck.py")
    out = subprocess.run([sys.executable, script, *args],
                         capture_output=True, text=True, timeout=timeout)
    lines = [ln for ln in out.stdout.strip().splitlines() if ln]
    assert lines, (out.stdout, out.stderr)
    return out.returncode, json.loads(lines[-1])


def test_cli_tiny_scope_json():
    from agnes_tpu.analysis.admission_mc import ADMISSION_TINY
    from agnes_tpu.analysis.membership_mc import MEMBERSHIP_TINY

    rc, rep = _run_cli("--scope", "tiny", "--json", "--workers", "1")
    assert rc == 0
    assert rep["ok"] and rep["complete"]
    assert rep["violations"] == 0
    assert rep["states_explored"] > 1000
    assert rep["metrics"]["modelcheck_states_explored"] == \
        rep["states_explored"]
    assert rep["metrics"]["modelcheck_violations"] == 0
    # ISSUE 7 + ISSUE 17: the scope sweeps ALL THREE domains and
    # reports their splits
    assert rep["admission_states"] > 1000
    assert rep["membership_states"] > 0
    assert (rep["consensus_states"] + rep["admission_states"]
            + rep["membership_states"]) == rep["states_explored"]
    assert rep["metrics"]["modelcheck_admission_states"] == \
        rep["admission_states"]
    assert rep["metrics"]["modelcheck_membership_states"] == \
        rep["membership_states"]
    assert "modelcheck_sym_orbit_reduction" in rep["metrics"]
    assert set(rep["configs"]) == {c.name for c in mc.TINY_SCOPE} \
        | {c.name for c in ADMISSION_TINY} \
        | {c.name for c in MEMBERSHIP_TINY}


def test_cli_self_test():
    from agnes_tpu.analysis.admission_mc import ADMISSION_MUTANTS
    from agnes_tpu.analysis.membership_mc import MEMBERSHIP_MUTANTS

    rc, rep = _run_cli("--self-test", timeout=360)
    assert rc == 0 and rep["ok"]
    assert set(rep["self_test"]) == set(mc.MUTANTS) | set(mc.DEEP_MUTANTS)
    assert set(rep["self_test_admission"]) == set(ADMISSION_MUTANTS)
    assert set(rep["self_test_membership"]) == set(MEMBERSHIP_MUTANTS)


def test_cli_deadline_sentinel():
    """The real-value-or-sentinel contract: with an impossible budget
    the CLI still exits 0 with a parseable record, complete=false."""
    rc, rep = _run_cli("--scope", "tiny", "--json", "--workers", "1",
                       "--deadline-s", "0.01")
    assert rc == 0 and rep["ok"]
    assert not rep["complete"]
    assert rep["violations"] == 0
