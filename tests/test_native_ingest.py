"""NativeIngestLoop (C++ event loop) vs VoteBatcher differential suite.

The C++ pipeline in core/native/ingest.cpp must produce bit-identical
dense phases to the vectorized-numpy VoteBatcher for the same vote
stream: same screens, same window discipline, same dedup/layering,
same slot interning order, same host-fallback events, same evidence.
(The reference's analogue of this surface is the executor's inbound
alphabet, consensus_executor.rs:16-20 — SURVEY §2.5.)
"""

import numpy as np
import pytest

from agnes_tpu.bridge import NativeIngestLoop, VoteBatcher, pack_wire_votes
from agnes_tpu.core import native
from agnes_tpu.types import VoteType

PV, PC = int(VoteType.PREVOTE), int(VoteType.PRECOMMIT)


def _phases_np(phases):
    """[(VotePhase, n)] -> comparable numpy tuples."""
    out = []
    for ph, n in phases:
        out.append((int(np.asarray(ph.round)[0]),
                    int(np.asarray(ph.typ)[0]),
                    n,
                    np.asarray(ph.slots),
                    np.asarray(ph.mask)))
    return out


def _assert_same(native_phases, batcher_phases):
    a, b = _phases_np(native_phases), _phases_np(batcher_phases)
    assert len(a) == len(b), (len(a), len(b))
    for (ra, ta, na, sa, ma), (rb, tb, nb, sb, mb) in zip(a, b):
        assert (ra, ta, na) == (rb, tb, nb)
        np.testing.assert_array_equal(sa, sb)
        np.testing.assert_array_equal(ma, mb)


def _pair(I, V, n_slots=4, W=4):
    loop = NativeIngestLoop(I, V, n_slots=n_slots, n_rounds=W)
    bat = VoteBatcher(I, V, n_slots=n_slots, n_rounds=W)
    return loop, bat


def _feed(loop, bat, cols):
    inst, val, h, rnd, typ, value = (np.asarray(c) for c in cols)
    loop.push(pack_wire_votes(inst, val, h, rnd, typ, value))
    bat.add_arrays(inst, val, h, rnd, typ, value)
    return loop.build_phases(), bat.build_phases()


def test_honest_dense_tick_parity():
    I, V = 8, 16
    loop, bat = _pair(I, V)
    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)
    n = I * V
    a, b = _feed(loop, bat, (inst, val, np.zeros(n), np.zeros(n),
                             np.full(n, PV), np.full(n, 7)))
    _assert_same(a, b)
    assert len(a) == 1 and a[0][1] == n


def test_layering_and_dedup_parity():
    I, V = 4, 8
    loop, bat = _pair(I, V)
    # equivocating validator 2 (two values), duplicate from validator 3,
    # nil from validator 4, mixed rounds/classes
    inst = np.array([0, 0, 0, 0, 0, 1, 1, 2, 0])
    val = np.array([2, 2, 3, 3, 4, 5, 5, 6, 2])
    h = np.zeros(9)
    rnd = np.array([0, 0, 0, 0, 0, 1, 1, 0, 0])
    typ = np.array([PV, PV, PV, PV, PV, PC, PC, PV, PV])
    value = np.array([7, 9, 7, 7, -1, 8, 8, -1, 7])
    a, b = _feed(loop, bat, (inst, val, h, rnd, typ, value))
    _assert_same(a, b)
    # validator 2's second value must land in layer 1 => extra phase
    assert len(a) >= 2


def test_mixed_value_two_class_intern_order_parity():
    """ADVICE r4 (medium): a same-round build carrying DIFFERENT new
    values in the two classes must intern slots combined-ascending by
    (instance, value) — the C++ intern_ascending / numpy general-path
    order — not in class processing order.  Before the fix the numpy
    fast path gave prevote value 9 slot 0 and precommit value 3 slot 1,
    breaking native parity (and its own general-path consistency)."""
    I, V = 4, 4
    loop, bat = _pair(I, V)
    # prevote (inst0, value 9) + precommit (inst0, value 3): ascending
    # order is 3 then 9 even though the prevote class emits first;
    # cross-instance: prevote (inst1, value 5) vs precommit (inst1,
    # value 2) exercises the same inversion on a second instance
    inst = np.array([0, 0, 1, 1])
    val = np.array([0, 1, 2, 3])
    h = np.zeros(4)
    rnd = np.zeros(4)
    typ = np.array([PV, PC, PV, PC])
    value = np.array([9, 3, 5, 2])
    a, b = _feed(loop, bat, (inst, val, h, rnd, typ, value))
    _assert_same(a, b)
    # slot numbering is ascending-by-value per instance ...
    assert bat.slots.slot_for(0, 3) == 0 and bat.slots.slot_for(0, 9) == 1
    assert bat.slots.slot_for(1, 2) == 0 and bat.slots.slot_for(1, 5) == 1
    # ... so the (earlier-emitted) prevote phase carries the HIGHER slot
    phases = _phases_np(b)
    assert [p[1] for p in phases] == [PV, PC]
    assert phases[0][3][0, 0] == 1 and phases[0][3][1, 2] == 1
    assert phases[1][3][0, 1] == 0 and phases[1][3][1, 3] == 0


def test_mixed_value_two_class_matches_general_path():
    """The numpy fast path must agree with the numpy GENERAL path on
    slot numbering for the same same-round mixed-value two-class
    traffic (the general path is forced by appending one extra
    round-1 vote, which cannot disturb round-0 interning order)."""
    I, V = 4, 4
    fast = VoteBatcher(I, V, n_slots=4, n_rounds=4)
    gen = VoteBatcher(I, V, n_slots=4, n_rounds=4)
    inst = np.array([0, 0])
    val = np.array([0, 1])
    typ = np.array([PV, PC])
    value = np.array([9, 3])
    fast.add_arrays(inst, val, np.zeros(2), np.zeros(2), typ, value)
    fast_phases = _phases_np(fast.build_phases())
    gen.add_arrays(np.array([0, 0, 1]), np.array([0, 1, 2]),
                   np.zeros(3), np.array([0, 0, 1]),
                   np.array([PV, PC, PV]), np.array([9, 3, 8]))
    gen_phases = _phases_np(gen.build_phases())
    for i in range(2):       # compare the two round-0 phases
        assert fast_phases[i][0] == gen_phases[i][0] == 0
        assert fast_phases[i][1] == gen_phases[i][1]
        np.testing.assert_array_equal(fast_phases[i][3][0],
                                      gen_phases[i][3][0])
        np.testing.assert_array_equal(fast_phases[i][4][0],
                                      gen_phases[i][4][0])
    assert fast.slots.slot_for(0, 3) == gen.slots.slot_for(0, 3) == 0
    assert fast.slots.slot_for(0, 9) == gen.slots.slot_for(0, 9) == 1


def test_malformed_and_stale_screen_parity():
    I, V = 4, 4
    loop, bat = _pair(I, V)
    inst = np.array([0, 99, 1, 2, 3])
    val = np.array([0, 1, 99, 2, 3])
    h = np.array([0, 0, 0, 5, 0])          # 5 = stale height
    rnd = np.zeros(5)
    typ = np.array([PV, PV, PV, PV, 9])    # 9 = hostile class
    value = np.full(5, 7)
    a, b = _feed(loop, bat, (inst, val, h, rnd, typ, value))
    _assert_same(a, b)
    c = loop.counters
    assert c["rejected_malformed"] == 3 == bat.rejected_malformed
    assert c["dropped_stale_height"] == 1 == bat.dropped_stale_height


def test_future_holdback_and_rotation_reentry_parity():
    I, V = 2, 4
    loop, bat = _pair(I, V, W=4)
    inst = np.zeros(4, np.int64)
    val = np.arange(4)
    # round 6 is outside the W=4 window at base 0 -> held
    a, b = _feed(loop, bat, (inst, val, np.zeros(4), np.full(4, 6),
                             np.full(4, PV), np.full(4, 7)))
    _assert_same(a, b)
    assert a == [] and loop.counters["held"] == 4
    # rotation arrives: base 4 -> the held votes re-enter
    base = np.full(I, 4, np.int64)
    hts = np.zeros(I, np.int64)
    loop.sync_device(base, hts)
    bat.sync_device(base, hts)
    a, b = loop.build_phases(), bat.build_phases()
    _assert_same(a, b)
    assert len(a) == 1 and a[0][1] == 4
    assert loop.counters["held"] == 0


def test_past_round_host_fallback_event_parity():
    I, V = 2, 4
    loop, bat = _pair(I, V)
    base = np.array([2, 0], np.int64)      # instance 0's window moved on
    hts = np.zeros(I, np.int64)
    loop.sync_device(base, hts)
    bat.sync_device(base, hts)
    # +2/3 precommits for value 9 at (instance 0, round 1 < base) —
    # must surface as a commit-from-any-round host event
    inst = np.zeros(3, np.int64)
    val = np.arange(3)
    a, b = _feed(loop, bat, (inst, val, np.zeros(3), np.ones(3),
                             np.full(3, PC), np.full(3, 9)))
    _assert_same(a, b)
    assert a == []
    ev_l = loop.drain_host_events()
    ev_b = bat.drain_host_events()
    assert ev_l == [(0, 0, 1, 9)] == ev_b
    assert loop.drain_host_events() == []


def test_slot_overflow_spills_to_host_parity():
    I, V = 1, 8
    loop, bat = _pair(I, V, n_slots=2)
    # 4 distinct values: slots 0,1 allocated, values 30/40 overflow
    inst = np.zeros(8, np.int64)
    val = np.arange(8)
    value = np.array([10, 10, 20, 20, 30, 30, 40, 40])
    a, b = _feed(loop, bat, (inst, val, np.zeros(8), np.zeros(8),
                             np.full(8, PV), value))
    _assert_same(a, b)
    assert loop.counters["overflow_votes"] == 4 == bat.overflow_votes
    assert loop.decode_slot(0, 0) == 10 and loop.decode_slot(0, 1) == 20
    assert loop.decode_slot(0, 3) is None


def test_height_advance_resets_slots():
    I, V = 2, 4
    loop, _ = _pair(I, V, n_slots=2)
    loop.push(pack_wire_votes([0], [0], [0], [0], [PV], [10]))
    loop.build_phases()
    assert loop.decode_slot(0, 0) == 10
    loop.sync_device(np.zeros(I, np.int64), np.array([1, 0], np.int64))
    assert loop.decode_slot(0, 0) is None          # instance 0 advanced
    loop.push(pack_wire_votes([0], [0], [1], [0], [PV], [50]))
    loop.build_phases()
    assert loop.decode_slot(0, 0) == 50


def test_signed_path_verify_and_evidence():
    I, V = 2, 4
    seeds = [bytes([i + 1]) * 32 for i in range(V)]
    pubkeys = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                        for s in seeds])
    from agnes_tpu.bridge.ingest import vote_messages_np

    loop = NativeIngestLoop(I, V, n_slots=4, pubkeys=pubkeys)
    bat = VoteBatcher(I, V, n_slots=4)

    # validator 1 equivocates (7 then 9); validator 3's signature is
    # forged (signed by the wrong key)
    inst = np.array([0, 0, 0, 0, 0], np.int64)
    val = np.array([0, 1, 1, 2, 3], np.int64)
    h = np.zeros(5, np.int64)
    rnd = np.zeros(5, np.int64)
    typ = np.full(5, PV, np.int64)
    value = np.array([7, 7, 9, 7, 7], np.int64)
    msgs = vote_messages_np(h, rnd, typ, value)
    sigs = np.zeros((5, 64), np.uint8)
    for k in range(5):
        signer = seeds[0] if k == 4 else seeds[val[k]]   # k=4: forged
        sigs[k] = np.frombuffer(
            native.sign(signer, msgs[k].tobytes()), np.uint8)

    loop.push(pack_wire_votes(inst, val, h, rnd, typ, value, sigs))
    bat.add_arrays(inst, val, h, rnd, typ, value, sigs)
    a = loop.build_phases()
    b = bat.build_phases(pubkeys)
    _assert_same(a, b)
    assert loop.counters["rejected_signature"] == 1 == bat.rejected_signature

    # device flags (0, 1) as an equivocator: both signed votes recovered
    ev = loop.signed_evidence(0, 1)
    assert ev is not None
    r1, r2 = ev
    v1 = int.from_bytes(r1[24:32].tobytes(), "little")
    v2 = int.from_bytes(r2[24:32].tobytes(), "little")
    assert {v1, v2} == {7, 9}
    for r in (r1, r2):
        sig = r[32:96].tobytes()
        vmsg = vote_messages_np(
            np.array([0]), np.array([0]), np.array([PV]),
            np.array([int.from_bytes(r[24:32].tobytes(), "little")
                      if r[21] & 1 else -1]))[0].tobytes()
        assert native.verify(seeds_pk(seeds, 1), vmsg, sig)
    assert loop.signed_evidence(0, 0) is None      # honest validator


def seeds_pk(seeds, i):
    return native.pubkey(seeds[i])


def test_wrapper_screens_pubkey_and_power_lengths():
    """Short pubkeys/powers buffers must be rejected in the wrapper —
    the C side copies V*32 / V*8 bytes blind (OOB read otherwise)."""
    with pytest.raises(ValueError):
        NativeIngestLoop(2, 4, n_slots=4,
                         pubkeys=np.zeros((3, 32), np.uint8))
    with pytest.raises(ValueError):
        NativeIngestLoop(2, 4, n_slots=4,
                         pubkeys=np.zeros((4, 31), np.uint8))
    with pytest.raises(ValueError):
        NativeIngestLoop(2, 4, n_slots=4,
                         powers=np.ones(3, np.int64))
    NativeIngestLoop(2, 4, n_slots=4,
                     pubkeys=np.zeros((4, 32), np.uint8),
                     powers=np.ones(4, np.int64))     # exact: fine


def test_unsigned_loop_rejects_missing_verdicts():
    """A loop built WITH pubkeys must refuse the unsigned emit path."""
    pub = np.zeros((4, 32), np.uint8)
    loop = NativeIngestLoop(2, 4, n_slots=4, pubkeys=pub)
    loop.push(pack_wire_votes([0], [0], [0], [0], [PV], [7]))
    # build_phases routes through the verify path by itself; driving
    # the raw ABI with NULL verdicts must fail
    from agnes_tpu.bridge.native_ingest import _lib

    L = _lib()
    n = L.ag_ing_stage(loop._h)
    assert n == 1
    assert L.ag_ing_apply_verdicts(loop._h, None) == -1


def test_double_buffer_stability():
    """Phases from emit k stay intact while emit k+1 is built (the
    double-buffer contract the device consumer relies on)."""
    import ctypes

    from agnes_tpu.bridge.native_ingest import _lib

    I, V = 2, 2
    loop = NativeIngestLoop(I, V, n_slots=4)
    L = _lib()

    def raw_phase_view():
        rnd, typ = ctypes.c_int32(), ctypes.c_int32()
        nv = ctypes.c_int64()
        sp = ctypes.POINTER(ctypes.c_int32)()
        mp = ctypes.POINTER(ctypes.c_uint8)()
        L.ag_ing_phase(loop._h, 0, ctypes.byref(rnd), ctypes.byref(typ),
                       ctypes.byref(nv), ctypes.byref(sp),
                       ctypes.byref(mp))
        return np.ctypeslib.as_array(sp, shape=(I, V))

    loop.push(pack_wire_votes([0], [0], [0], [0], [PV], [7]))
    loop.build_phases()
    first = raw_phase_view().copy()
    view = raw_phase_view()                       # live view, set A
    loop.push(pack_wire_votes([1], [1], [0], [0], [PC], [8]))
    loop.build_phases()                           # fills set B
    np.testing.assert_array_equal(view, first)    # set A untouched

def test_early_next_height_vote_survives_sync_parity():
    """A vote for height h+1 pushed just before the device advances
    must NOT be dropped at push time: both paths screen heights at
    build time against the last-synced state, so after sync(h+1) the
    vote emits (the height-boundary case that a push-time screen
    loses)."""
    I, V = 2, 4
    loop, bat = _pair(I, V)
    inst = np.zeros(3, np.int64)
    val = np.arange(3)
    # votes for height 1 while both paths still believe height 0
    loop.push(pack_wire_votes(inst, val, np.ones(3), np.zeros(3),
                              np.full(3, PV), np.full(3, 7)))
    bat.add_arrays(inst, val, np.ones(3), np.zeros(3),
                   np.full(3, PV), np.full(3, 7))
    # device advances instance 0 and 1 to height 1, then the tick builds
    base = np.zeros(I, np.int64)
    hts = np.ones(I, np.int64)
    loop.sync_device(base, hts)
    bat.sync_device(base, hts)
    a, b = loop.build_phases(), bat.build_phases()
    _assert_same(a, b)
    assert len(a) == 1 and a[0][1] == 3
    assert loop.counters["dropped_stale_height"] == 0
    assert bat.dropped_stale_height == 0


def test_stale_height_still_dropped_at_build_parity():
    """Votes for a height the instance is NOT at when the tick builds
    are dropped and counted — deferring the screen to build time must
    not let genuinely stale votes through."""
    I, V = 2, 4
    loop, bat = _pair(I, V)
    a, b = _feed(loop, bat, (np.zeros(2, np.int64), np.arange(2),
                             np.array([5, 0]), np.zeros(2),
                             np.full(2, PV), np.full(2, 7)))
    _assert_same(a, b)
    assert loop.counters["dropped_stale_height"] == 1
    assert bat.dropped_stale_height == 1


def test_held_cap_bounds_future_flood_parity():
    """The pre-verification hold-back queue is capped: a flood of
    future-round votes beyond the cap is dropped and counted, not
    accumulated without bound (unauthenticated memory exhaustion)."""
    I, V = 2, 4
    loop = NativeIngestLoop(I, V, n_slots=4, held_cap=5)
    bat = VoteBatcher(I, V, n_slots=4, held_cap=5)
    n = 12
    inst = np.arange(n, dtype=np.int64) % 2
    val = (np.arange(n) // 2) % V       # first 5 cells are distinct
    rnd = np.full(n, 9)                    # far future at base 0, W 4
    loop.push(pack_wire_votes(inst, val, np.zeros(n), rnd,
                              np.full(n, PV), np.full(n, 7)))
    bat.add_arrays(inst, val, np.zeros(n), rnd,
                   np.full(n, PV), np.full(n, 7))
    assert loop.build_phases() == [] and bat.build_phases() == []
    assert loop.counters["held"] == 5
    assert loop.counters["dropped_held_overflow"] == 7
    assert bat.dropped_held_overflow == 7
    # the capped survivors still re-enter when the window arrives
    base = np.full(I, 6, np.int64)
    hts = np.zeros(I, np.int64)
    loop.sync_device(base, hts)
    bat.sync_device(base, hts)
    a, b = loop.build_phases(), bat.build_phases()
    _assert_same(a, b)
    assert len(a) == 1 and a[0][1] == 5


def test_sync_device_screens_array_lengths():
    """Short base_round/heights arrays must be rejected in the wrapper
    (the C side reads I int64s from each blind)."""
    loop = NativeIngestLoop(8, 4, n_slots=4)
    with pytest.raises(ValueError):
        loop.sync_device(np.zeros(1, np.int64), np.zeros(8, np.int64))
    with pytest.raises(ValueError):
        loop.sync_device(np.zeros(8, np.int64), np.zeros(3, np.int64))
    loop.sync_device(np.zeros(8, np.int64), np.zeros(8, np.int64))


def test_hostile_dims_rejected_in_wrapper():
    with pytest.raises(ValueError):
        NativeIngestLoop(-1, 4, n_slots=4)
    with pytest.raises(ValueError):
        NativeIngestLoop(4, 4, n_slots=0)
    with pytest.raises(ValueError):
        NativeIngestLoop(2**40, 2**40, n_slots=4)

def test_push_chunking_invariance():
    """Within one tick, the dense phases are a function of the record
    stream, not of how it was chunked across push() calls."""
    I, V = 4, 8
    rng = np.random.default_rng(12)
    n = 64
    inst = rng.integers(0, I, n)
    val = rng.integers(0, V, n)
    rnd = rng.integers(0, 2, n)
    typ = rng.integers(0, 2, n)
    value = rng.integers(-1, 3, n)
    wire = pack_wire_votes(inst, val, np.zeros(n), rnd, typ, value)

    loop1 = NativeIngestLoop(I, V, n_slots=4)
    loop1.push(wire)
    a = loop1.build_phases()

    loop2 = NativeIngestLoop(I, V, n_slots=4)
    for lo, hi in ((0, 7), (7, 40), (40, 64)):
        loop2.push(wire[lo * 96:hi * 96])
    b = loop2.build_phases()
    _assert_same(a, b)

def test_native_loop_checkpoint_roundtrip(tmp_path):
    """Slot decode, slashing evidence, counters and window survive a
    snapshot/restore of the C++ loop (same durability contract as
    VoteBatcher's save_batcher/load_batcher)."""
    from agnes_tpu.bridge.ingest import vote_messages_np
    from agnes_tpu.utils.checkpoint import (load_native_loop,
                                            save_native_loop)

    I, V = 2, 4
    seeds = [bytes([i + 1]) * 32 for i in range(V)]
    pubkeys = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                        for s in seeds])
    loop = NativeIngestLoop(I, V, n_slots=4, pubkeys=pubkeys)
    loop.sync_device(np.zeros(I, np.int64), np.zeros(I, np.int64))

    # validator 1 double-signs (7 then 9); validator 3 forges
    inst = np.array([0, 0, 0, 0], np.int64)
    val = np.array([0, 1, 1, 3], np.int64)
    h = np.zeros(4, np.int64)
    rnd = np.zeros(4, np.int64)
    typ = np.full(4, PV, np.int64)
    value = np.array([7, 7, 9, 7], np.int64)
    msgs = vote_messages_np(h, rnd, typ, value)
    sigs = np.zeros((4, 64), np.uint8)
    for k in range(4):
        signer = seeds[0] if k == 3 else seeds[val[k]]
        sigs[k] = np.frombuffer(
            native.sign(signer, msgs[k].tobytes()), np.uint8)
    loop.push(pack_wire_votes(inst, val, h, rnd, typ, value, sigs))
    loop.build_phases()
    assert loop.decode_slot(0, 0) == 7 and loop.decode_slot(0, 1) == 9

    p = str(tmp_path / "loop.npz")
    save_native_loop(loop, p)
    fresh = load_native_loop(p, pubkeys=pubkeys)
    assert fresh.decode_slot(0, 0) == 7 and fresh.decode_slot(0, 1) == 9
    c = fresh.counters
    assert c["rejected_signature"] == 1 and c["log"] == 3
    ev = fresh.signed_evidence(0, 1)
    assert ev is not None
    r1, r2 = ev
    v1 = int.from_bytes(r1[24:32].tobytes(), "little")
    v2 = int.from_bytes(r2[24:32].tobytes(), "little")
    assert {v1, v2} == {7, 9}
    # restored evidence re-verifies against the validator's pubkey
    for r in (r1, r2):
        m = vote_messages_np(
            np.array([0]), np.array([0]), np.array([PV]),
            np.array([int.from_bytes(r[24:32].tobytes(), "little")]))[0]
        assert native.verify(native.pubkey(seeds[1]), m.tobytes(),
                             r[32:96].tobytes())
    # signature screen still enforced after restore (pubkeys rewired)
    with pytest.raises(ValueError):
        load_native_loop(p)              # signed snapshot, no pubkeys

def test_native_loop_checkpoint_powers_heldcap_and_stale_slots(tmp_path):
    """(a) Voting powers and held_cap restore from the snapshot (host
    quorum math must not silently reset to weight 1); (b) slots
    cleared by a height advance must NOT resurrect on restore; (c) a
    corrupt log leaf shape is screened in the wrapper."""
    from agnes_tpu.utils.checkpoint import (load_native_loop,
                                            save_native_loop)

    I, V = 2, 4
    # quorum (2/3 of 11 = 7.33) crosses only at the SECOND vote (5+4)
    powers = np.array([5, 4, 1, 1], np.int64)
    loop = NativeIngestLoop(I, V, n_slots=4, powers=powers, held_cap=99)
    loop.sync_device(np.zeros(I, np.int64), np.zeros(I, np.int64))
    loop.push(pack_wire_votes([0, 0], [0, 1], [0, 0], [0, 0],
                              [PV, PV], [7, 9]))
    loop.build_phases()
    assert loop.decode_slot(0, 0) == 7 and loop.decode_slot(0, 1) == 9
    # height advance clears instance 0's slots
    loop.sync_device(np.zeros(I, np.int64), np.array([1, 0], np.int64))
    assert loop.decode_slot(0, 0) is None

    p = str(tmp_path / "loop2.npz")
    save_native_loop(loop, p)
    fresh = load_native_loop(p)
    assert fresh.decode_slot(0, 0) is None      # no resurrection
    assert fresh.held_cap == 99
    # restored powers drive the host-tally quorum: 5+4 of 11 = +2/3
    # precommits for value 5 at a past round fire the host event
    # exactly once (weight-1 powers would need a third vote)
    fresh.sync_device(np.array([2, 0], np.int64),
                      np.array([1, 0], np.int64))
    fresh.push(pack_wire_votes([0, 0], [0, 1], [1, 1], [0, 0],
                               [PC, PC], [5, 5]))
    fresh.build_phases()
    assert fresh.drain_host_events() == [(0, 1, 0, 5)]

    # corrupt snapshot: flat log leaf must be rejected, not OOB-read
    # (target must be fresh — a live loop is refused before the shape
    # screen even runs, see test_import_state_requires_fresh_loop)
    st = fresh.export_state()
    st["log"] = np.zeros(96 * 3, np.uint8)       # wrong shape
    blank = NativeIngestLoop(I, V, n_slots=4, powers=powers)
    with pytest.raises(ValueError):
        blank.import_state(st)


def test_import_state_requires_fresh_loop():
    """import_state must refuse a loop that already holds verified
    votes: merging a snapshot's evidence log into live state would
    duplicate records and inflate every log counter."""
    loop = NativeIngestLoop(1, 4, n_slots=4)
    loop.sync_device(np.zeros(1, np.int64), np.zeros(1, np.int64))
    loop.push(pack_wire_votes(np.array([0]), np.array([1]),
                              np.array([0]), np.array([0]),
                              np.array([PV]), np.array([7])))
    loop.build_phases()
    st = loop.export_state()
    assert loop.counters["log"] == 1
    with pytest.raises(RuntimeError, match="fresh"):
        loop.import_state(st)
    # the refused import must leave live state untouched
    assert loop.counters["log"] == 1


def test_import_state_refuses_even_empty_snapshot_log():
    """The fresh-loop guard must not depend on the SNAPSHOT's log being
    non-empty: importing a fresh loop's (empty-log) snapshot into a
    live loop would merge states just as silently."""
    fresh = NativeIngestLoop(1, 4, n_slots=4)
    st = fresh.export_state()                  # empty log snapshot
    live = NativeIngestLoop(1, 4, n_slots=4)
    live.sync_device(np.zeros(1, np.int64), np.zeros(1, np.int64))
    live.push(pack_wire_votes(np.array([0]), np.array([1]),
                              np.array([0]), np.array([0]),
                              np.array([PV]), np.array([7])))
    live.build_phases()
    with pytest.raises(RuntimeError, match="fresh"):
        live.import_state(st)
    assert live.counters["log"] == 1


def test_import_state_refuses_pushed_unbuilt_loop():
    """The freshness guard must trip on ANY prior interaction, not just
    a non-empty evidence log: pushed-but-unbuilt votes leave the log
    empty but would merge into the restored state at the next build."""
    live = NativeIngestLoop(1, 4, n_slots=4)
    live.push(pack_wire_votes(np.array([0]), np.array([1]),
                              np.array([0]), np.array([0]),
                              np.array([PV]), np.array([7])))
    st = NativeIngestLoop(1, 4, n_slots=4).export_state()
    with pytest.raises(RuntimeError, match="fresh"):
        live.import_state(st)


# --- async (worker-thread) ingestion ----------------------------------------


def test_push_async_parity_with_sync():
    """push_async + build must be bit-identical to synchronous push for
    the same record stream: same phases, same counters, same slots —
    the worker thread changes WHEN parsing happens, never the result."""
    I, V = 4, 8
    loop_s = NativeIngestLoop(I, V, n_slots=4)
    loop_a = NativeIngestLoop(I, V, n_slots=4)
    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)
    n = I * V
    wire = pack_wire_votes(inst, val, np.zeros(n), np.zeros(n),
                           np.full(n, PV), np.full(n, 7))
    # plus one malformed record (hostile validator) and an equivocation
    bad = pack_wire_votes([0], [99], [0], [0], [PV], [7])
    eqv = pack_wire_votes([0, 0], [2, 2], [0, 0], [0, 0], [PV, PV],
                          [9, 11])
    loop_s.push(wire); loop_s.push(bad); loop_s.push(eqv)
    a = loop_s.build_phases()
    loop_a.push_async(wire); loop_a.push_async(bad); loop_a.push_async(eqv)
    b = loop_a.build_phases()          # implies flush
    _assert_same(a, b)
    assert loop_a.counters == loop_s.counters
    assert loop_a.async_depth == 0
    for s in range(4):
        assert loop_a.decode_slot(0, s) == loop_s.decode_slot(0, s)


def test_push_async_overlaps_and_flush_synchronizes():
    """flush() must make every queued buffer visible to the next stage;
    a large queued backlog must land exactly once (no loss, no dup)."""
    I, V = 2, 4
    loop = NativeIngestLoop(I, V, n_slots=4)
    loop.sync_device(np.zeros(I, np.int64), np.zeros(I, np.int64))
    chunks = 50
    for k in range(chunks):
        # duplicate votes: layering/dedup stress across async chunks
        loop.push_async(pack_wire_votes(
            [0, 1], [k % V, (k + 1) % V], [0, 0], [0, 0], [PV, PV],
            [7, 7]))
    loop.flush()
    assert loop.async_depth == 0
    phases = loop.build_phases()
    total = sum(n for _, n in phases)
    # within ONE build, duplicate (instance, validator) lanes dedup to
    # layers; V distinct validators voted per instance row
    assert total == 2 * V
    c = loop.counters
    assert c["rejected_malformed"] == 0
    # conservation: every accepted record landed in the evidence log
    # exactly once (the log retains pre-dedup verified votes)
    assert c["log"] == 2 * chunks


def test_push_async_concurrent_with_ticks():
    """A producer thread streams wire buffers while the main thread
    runs the tick protocol (sync/build) — the actual overlap shape.
    Conservation: every record is exactly one of emitted / deduped /
    held / dropped-by-screen, and the final drain sees the rest."""
    import threading

    I, V = 2, 8
    loop = NativeIngestLoop(I, V, n_slots=4)
    loop.sync_device(np.zeros(I, np.int64), np.zeros(I, np.int64))
    BATCHES, N = 200, 16

    def producer():
        rng = np.random.default_rng(7)
        for _ in range(BATCHES):
            inst = rng.integers(0, I, N)
            val = rng.integers(0, V, N)
            loop.push_async(pack_wire_votes(
                inst, val, np.zeros(N), np.zeros(N),
                np.full(N, PV), np.full(N, 7)))

    t = threading.Thread(target=producer)
    t.start()
    emitted = 0
    for _ in range(40):                    # ticks racing the producer
        emitted += sum(n for _, n in loop.build_phases())
    t.join()
    loop.flush()
    emitted += sum(n for _, n in loop.build_phases())
    # per-build dedup bounds each build at I*V lanes; across racing
    # builds re-pushed (inst, val) cells may emit again (the device
    # tally's voted record absorbs replays).  The hard conservation
    # property: NOTHING is lost or duplicated — every one of the
    # BATCHES*N well-formed records is in the evidence log exactly
    # once, and emissions cover every distinct cell at least once.
    assert I * V <= emitted <= BATCHES * N, emitted
    c = loop.counters
    assert c["log"] == BATCHES * N
    assert c["rejected_malformed"] == 0
    assert c["dropped_stale_height"] == 0
    assert loop.async_depth == 0


def test_overlapped_pipeline_small_shape():
    """The overlapped end-to-end path (bench._pipeline_overlapped:
    push_async worker + deferred collection) must reach the same
    decisions as the synchronous native path at a small shape."""
    import bench                  # repo root is on sys.path (conftest)

    rate = bench._pipeline_overlapped(8, 8, heights=2)
    assert rate > 0        # asserts decisions + zero rejects internally


def test_push_after_push_async_preserves_arrival_order():
    """push() must drain the async inbox before stamping arrivals, so a
    mixed push_async-then-push sequence keeps first-vote-wins dedup and
    evidence order identical to the all-synchronous sequence."""
    loop = NativeIngestLoop(1, 4, n_slots=4)
    loop.sync_device(np.zeros(1, np.int64), np.zeros(1, np.int64))
    # async: validator 2 votes 9 FIRST; then sync push: votes 11
    loop.push_async(pack_wire_votes([0], [2], [0], [0], [PV], [9]))
    loop.push(pack_wire_votes([0], [2], [0], [0], [PV], [11]))
    phases = loop.build_phases()
    # first-vote-wins: layer 0 carries 9, layer 1 the conflicting 11
    assert len(phases) == 2
    assert loop.decode_slot(0, int(np.asarray(phases[0][0].slots)[0, 2])) \
        == 9
