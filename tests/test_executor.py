"""ConsensusExecutor: multi-node simulation through the public API.

A toy in-memory router replaces the network (the reference's testing
philosophy: the consumer fabricates the message stream, README.md:8-14)
— no cluster needed to exercise multi-node consensus, timeouts, round
skips, height advance, and Byzantine rejection.
"""

import pytest

from agnes_tpu.core import state_machine as sm
from agnes_tpu.core.executor import (
    ConsensusExecutor,
    TimeoutConfig,
    WireProposal,
    WireTimeout,
)
from agnes_tpu.core.validators import Validator, ValidatorSet
from agnes_tpu.crypto import ed25519_ref as ed
from agnes_tpu.types import Vote


def make_net(n=4, verify=True, start_height=0):
    seeds = [bytes([i + 1]) * 32 for i in range(n)]
    pairs = sorted(zip([ed.keypair(s)[1] for s in seeds], seeds))
    vset = ValidatorSet([Validator(pk, 1) for pk, _ in pairs])
    nodes = []
    for i, (pk, seed) in enumerate(pairs):
        nodes.append(ConsensusExecutor(
            vset, index=i, seed=seed,
            get_value=lambda h: 100 + h,
            start_height=start_height,
            verify_signatures=verify))
    return nodes


def route(nodes, drop=lambda sender, msg: False, max_iters=200,
          until=lambda: False):
    """Deliver every outbox message to every *other* node until the
    network is quiescent or `until()` holds.  (A healthy network never
    quiesces on its own — each decision starts the next height.)"""
    delivered = [0] * len(nodes)
    for _ in range(max_iters):
        if until():
            return
        progress = False
        for i, node in enumerate(nodes):
            while delivered[i] < len(node.outbox):
                msg = node.outbox[delivered[i]]
                delivered[i] += 1
                progress = True
                if drop(i, msg):
                    continue
                for j, other in enumerate(nodes):
                    if j != i:
                        other.execute(msg)
        if not progress:
            return
    raise AssertionError("network did not quiesce")


def test_happy_path_multi_height():
    nodes = make_net(4)
    for node in nodes:
        node.start()
    # drive until three consecutive heights decided everywhere
    route(nodes, until=lambda: all(2 in n.decided for n in nodes))
    for target_height in range(3):
        for node in nodes:
            d = node.decided[target_height]
            assert d.value == 100 + target_height
            assert d.round == 0
    assert all(n.height >= 3 for n in nodes)


def test_unsigned_and_forged_votes_rejected():
    nodes = make_net(4)
    for node in nodes:
        node.start()
    victim = nodes[0]
    before = victim.votes.votes.round(0).prevotes.seen_weight()
    # unsigned vote claiming validator 2
    victim.execute(Vote.new_prevote(0, 55, validator=2, height=0))
    # forged: signed by the wrong key
    wrong_seed = b"\xAA" * 32
    from agnes_tpu.crypto.encoding import vote_signing_bytes
    sig = ed.sign(wrong_seed, vote_signing_bytes(0, 0, 0, 55))
    victim.execute(Vote.new_prevote(0, 55, validator=2, height=0,
                                    signature=sig))
    after = victim.votes.votes.round(0).prevotes.seen_weight()
    assert after == before  # neither vote reached the tally


def test_identity_free_votes_dropped_when_verifying():
    """A verifying executor must not tally anonymous weight-1 votes —
    they would let an attacker forge a quorum for any value."""
    nodes = make_net(4)
    node = nodes[0]
    node.start()
    for typ_ctor in (Vote.new_prevote, Vote.new_precommit):
        for _ in range(4):
            node.execute(typ_ctor(0, 666, height=0))
    assert 0 not in node.decided
    assert node.votes.votes.round(0).prevotes.seen_weight() <= 1  # own vote


def test_malformed_wire_fields_do_not_crash():
    """Out-of-range ints from Byzantine peers are dropped, not raised."""
    nodes = make_net(4)
    node = nodes[0]
    node.start()
    bad_votes = [
        Vote.new_prevote(0, -1, validator=0, height=0, signature=b"x" * 64),
        Vote.new_prevote(0, 2**256, validator=0, height=0,
                         signature=b"x" * 64),
        Vote.new_prevote(-5, 1, validator=0, height=0, signature=b"x" * 64),
        Vote.new_prevote(2**40, 1, validator=0, height=0,
                         signature=b"x" * 64),
    ]
    for v in bad_votes:
        node.execute(v)  # must not raise
    node.execute(WireProposal(height=0, round=0, value=-7, pol_round=-1,
                              proposer=1, signature=b"x" * 64))
    node.execute(WireProposal(height=0, round=2**40, value=1, pol_round=-9,
                              proposer=99, signature=b"x" * 64))
    assert 0 not in node.decided


def test_config_cli_rejects_bad_args():
    from agnes_tpu.harness.configs import main
    for bad in ([], ["12"], ["0"], ["x"]):
        with pytest.raises(SystemExit):
            main(bad)


def test_byzantine_proposer_prevotes_nil():
    """A proposal from the wrong claimed proposer (or with a bad sig)
    produces ProposalInvalid -> the node prevotes nil."""
    nodes = make_net(4)
    node = nodes[0]
    node.start()
    r0_proposer = node.proposer(0, 0)
    wrong = (r0_proposer + 1) % 4
    if node.index == r0_proposer:
        node = nodes[1]
        node.start()
    node.execute(WireProposal(height=0, round=0, value=55, pol_round=-1,
                              proposer=wrong, signature=b"\x00" * 64))
    nil_prevotes = [m for m in node.outbox
                    if isinstance(m, Vote) and m.value is None]
    assert len(nil_prevotes) == 1


def test_timeout_round_advances_and_decides_in_round_1():
    """Silent proposer in round 0: everyone times out propose, prevotes
    nil, precommits nil, times out precommit, moves to round 1 and
    decides there."""
    nodes = make_net(4)
    for node in nodes:
        node.start()
    r0_proposer_idx = nodes[0].proposer(0, 0)

    def drop(sender, msg):
        # proposer is mute in round 0 (its proposal AND its votes)
        if isinstance(msg, WireProposal):
            return msg.round == 0
        if isinstance(msg, Vote):
            return msg.validator == r0_proposer_idx and msg.round == 0
        return False

    # nobody hears a proposal; drive clocks until decision
    silent = nodes[r0_proposer_idx]
    done = lambda: all(0 in n.decided for n in nodes  # noqa: E731
                       if n is not silent)
    for t in (5.0, 10.0, 20.0, 40.0):
        for i, node in enumerate(nodes):
            if node is not silent:
                node.advance_time(t)
        route(nodes, drop=drop, until=done)
        if done():
            break
    for node in nodes:
        if node is silent:
            continue
        d = node.decided[0]
        assert d.round >= 1
        assert d.value == 100


def test_decision_is_unanimous_and_consistent_under_reordering():
    """Shuffled delivery order still yields one decision value."""
    import random
    rng = random.Random(3)
    nodes = make_net(4)
    for node in nodes:
        node.start()
    # collect and deliver in random order, repeatedly
    for _ in range(50):
        pending = []
        for i, node in enumerate(nodes):
            for msg in node.outbox:
                pending.append((i, msg))
        rng.shuffle(pending)
        for i, msg in pending:
            for j, other in enumerate(nodes):
                if j != i:
                    other.execute(msg)
        if all(0 in n.decided for n in nodes):
            break
    values = {n.decided[0].value for n in nodes}
    assert values == {100}


def test_timer_wheel_ordering():
    from agnes_tpu.core.executor import TimerWheel
    w = TimerWheel()
    t1 = WireTimeout(0, 0, sm.TimeoutStep.PROPOSE)
    t2 = WireTimeout(0, 1, sm.TimeoutStep.PREVOTE)
    w.schedule(5.0, t2)
    w.schedule(1.0, t1)
    assert w.next_deadline() == 1.0
    assert w.advance(0.5) == []
    assert w.advance(1.0) == [t1]
    assert w.advance(10.0) == [t2]
    assert w.next_deadline() is None


def test_timeout_config_escalates():
    cfg = TimeoutConfig(propose=3.0, delta=0.5)
    assert cfg.duration(sm.TimeoutStep.PROPOSE, 0) == 3.0
    assert cfg.duration(sm.TimeoutStep.PROPOSE, 4) == 5.0
