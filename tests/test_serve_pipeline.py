"""Serve plane THROUGH the device: bit-identity vs the offline fused
path, burst/overload/forged-lane closed loop, and a byzantine
equivocation flood — every test here dispatches real fused steps, so
each distinct (P, lanes) shape costs a multi-minute XLA:CPU trace with
the persistent cache off: ALL marked slow (tier-1 runs the host-side
suite in tests/test_serve.py; ci.sh runs these)."""

import numpy as np
import pytest

from agnes_tpu.bridge import VoteBatcher
from agnes_tpu.bridge.native_ingest import pack_wire_votes
from agnes_tpu.harness.device_driver import DeviceDriver
from agnes_tpu.harness.fixtures import (
    deterministic_seeds,
    full_mesh_cols,
    validator_pubkeys,
)
from agnes_tpu.serve import ShapeLadder, VoteService
from agnes_tpu.types import VoteType

PV, PC = int(VoteType.PREVOTE), int(VoteType.PRECOMMIT)

I, V = 3, 4
N = I * V
SEEDS = deterministic_seeds(V)
PUBKEYS = validator_pubkeys(SEEDS)
RUNG = 1 << (2 * N - 1).bit_length()        # one full tick's lanes


def _serve_service(donate, capacity=None, heights_box=None, pubkeys=PUBKEYS,
                   **kw):
    d = DeviceDriver(I, V, advance_height=True, defer_collect=True)
    bat = VoteBatcher(I, V, n_slots=4)
    predictor = None
    if heights_box is not None:
        predictor = lambda: (np.zeros(I, np.int64),             # noqa: E731
                             np.full(I, heights_box["h"], np.int64))
    svc = VoteService(
        d, bat, pubkeys,
        capacity=capacity if capacity is not None else 4 * 2 * N,
        target_votes=2 * N, max_delay_s=0.0,
        ladder=ShapeLadder.plan(I, V, min_rung=RUNG),
        window_predictor=predictor, donate=donate)
    return svc, d, bat


def _wire_height(h, forge_validator=None):
    """Both vote classes of one honest height as wire bytes."""
    out = b""
    for typ in (PV, PC):
        cols = full_mesh_cols(I, V, SEEDS, h, typ, 7,
                              forge_validator=(forge_validator
                                               if typ == PV else None))
        out += pack_wire_votes(*cols)
    return out


@pytest.mark.slow
def test_serve_bit_identical_to_offline_fused():
    """ISSUE 2 acceptance: decisions served through the streaming
    plane are BIT-identical to the offline VoteBatcher ->
    consensus_step_seq_signed path — same traffic, leaf-for-leaf equal
    state/tally and identical decision stats.  donate=False so both
    loops share one jit entry (one compile for the whole test; the
    donated entry is exercised by the tests below)."""
    heights = 3

    # offline reference: the bench._pipeline_fused shape
    dA = DeviceDriver(I, V, advance_height=True, defer_collect=True)
    bA = VoteBatcher(I, V, n_slots=4)
    for h in range(heights):
        bA.sync_device(np.zeros(I, np.int64), np.full(I, h, np.int64))
        for typ in (PV, PC):
            bA.add_arrays(*full_mesh_cols(I, V, SEEDS, h, typ, 7))
        phases, lanes = bA.build_phases_device(PUBKEYS, phase_offset=1,
                                               lane_floor=RUNG)
        dA.step_seq_signed([dA.empty_phase()] + [p for p, _ in phases],
                           lanes)
    dA.block_until_ready()
    assert dA.stats.decisions_total == I * heights

    # streaming plane, same wire traffic height by height
    box = {"h": 0}
    svc, dB, bB = _serve_service(donate=False, heights_box=box)
    for h in range(heights):
        box["h"] = h
        svc.submit(_wire_height(h))
        svc.pump()                    # dispatch h-1, densify h
    rep = svc.drain()                 # dispatch the last + settle

    assert rep["decisions_total"] == I * heights
    assert rep["rejected_signature_device"] == 0
    for a, b in zip(dA.state, dB.state):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(dA.tally, dB.tally):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(dA.stats.decision_value,
                                  dB.stats.decision_value)
    np.testing.assert_array_equal(dA.stats.decision_round,
                                  dB.stats.decision_round)
    assert bool(dB.stats.decided.all())


@pytest.mark.slow
def test_serve_burst_overload_forged_and_drain():
    """The closed loop under stress, on the DONATED entry: a burst
    twice the queue capacity is admitted up to the bound and the rest
    rejected-newest; a forged prevote lane is rejected ON DEVICE
    without losing the height; warmup precompiles the ladder rung the
    traffic then reuses (cache-size assertion = the no-recompile
    invariant); drain returns a coherent report."""
    from agnes_tpu.device.step import consensus_step_seq_signed_donated_jit

    box = {"h": 0}
    svc, d, bat = _serve_service(donate=True, capacity=2 * N,
                                 heights_box=box)
    warmed = svc.pipeline.warmup(n_phases=3)
    assert warmed == 1                 # single-rung ladder

    # burst: height 0 twice — the queue holds exactly one full tick,
    # so the second copy is rejected-newest at admission
    wire = _wire_height(0)
    assert svc.submit(wire).accepted == 2 * N
    res = svc.submit(wire)
    assert res.accepted == 0 and res.rejected_overflow == 2 * N
    svc.pump()                         # densify h0
    svc.pump()                         # dispatch h0
    decisions = svc.poll_decisions()
    assert len(decisions) == I
    assert all(dec.value_id == 7 for dec in decisions)

    # height 1 with validator 0's prevote forged: the fused verify
    # masks I lanes on device; 3 of 4 prevotes still quorum -> decide
    box["h"] = 1
    svc.submit(_wire_height(1, forge_validator=0))
    svc.pump()
    rep = svc.drain()

    assert rep["decisions_total"] == 2 * I
    assert rep["decided_instances"] == I
    assert rep["rejected_signature_device"] == I
    assert rep["queue"]["rejected_overflow"] == 2 * N
    assert rep["dispatched_batches"] == 2
    assert rep["dispatched_votes"] == 4 * N
    assert rep["held_remaining"] == 0
    snap = rep["metrics"]
    assert snap["serve_e2e_latency_s"] > 0
    assert snap["serve_votes_dispatched"] == 4 * N
    # warmup + two heights of traffic share ONE compiled shape
    assert consensus_step_seq_signed_donated_jit._cache_size() == 1


@pytest.mark.slow
def test_serve_mesh_threaded_bit_identical_to_single_and_offline():
    """ISSUE 3 acceptance: decisions served through ThreadedVoteService
    on a >= 2-device (faked CPU) mesh — dense-lane sharded dispatch —
    are BIT-identical to the single-device serve path and to the
    offline step_seq_signed_dense path.  The offline reference runs on
    the SAME mesh with donate=False, so it and the serve loop share
    one memoized sharded jit entry (parallel/sharded._FACTORY_CACHE):
    the mesh pair costs ONE sharded compile."""
    import time as _time

    import jax

    from agnes_tpu.parallel import make_mesh
    from agnes_tpu.serve import ThreadedVoteService

    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual multi-device CPU mesh")
    I2, V2 = 4, 4                      # shards (data=2, val=2)
    N2 = I2 * V2
    RUNG2 = 1 << (2 * N2 - 1).bit_length()
    heights = 2
    mesh = make_mesh(2, 2)

    def wire_height2(h):
        return b"".join(
            pack_wire_votes(*full_mesh_cols(I2, V2, SEEDS, h, typ, 7))
            for typ in (PV, PC))

    # offline dense reference, on the mesh
    dA = DeviceDriver(I2, V2, advance_height=True, defer_collect=True,
                      mesh=mesh)
    bA = VoteBatcher(I2, V2, n_slots=4)
    for h in range(heights):
        bA.sync_device(np.zeros(I2, np.int64), np.full(I2, h, np.int64))
        for typ in (PV, PC):
            bA.add_arrays(*full_mesh_cols(I2, V2, SEEDS, h, typ, 7))
        phases, dense = bA.build_phases_device_dense(PUBKEYS)
        assert dense is not None
        dA.step_seq_signed_dense([dA.empty_phase()]
                                 + [p for p, _ in phases], dense)
    dA.block_until_ready()
    assert dA.stats.decisions_total == I2 * heights

    # the same wire traffic through the THREADED mesh serve plane
    box = {"h": 0}
    dB = DeviceDriver(I2, V2, advance_height=True, defer_collect=True,
                      mesh=mesh)
    bB = VoteBatcher(I2, V2, n_slots=4)
    svcB = VoteService(
        dB, bB, PUBKEYS, capacity=4 * 2 * N2, target_votes=2 * N2,
        max_delay_s=1e9,
        ladder=ShapeLadder.plan_dense(I2, V2,
                                      local_shape=dB._local_shape(),
                                      min_rung=RUNG2),
        window_predictor=lambda: (np.zeros(I2, np.int64),
                                  np.full(I2, box["h"], np.int64)),
        donate=False)
    assert svcB.pipeline.dense
    tsvc = ThreadedVoteService(svcB, idle_wait_s=0.0005).start()
    for h in range(heights):
        box["h"] = h
        assert tsvc.submit(wire_height2(h))
        want = 2 * N2 * (h + 1)
        t_end = _time.monotonic() + 900
        while svcB.pipeline.dispatched_votes < want:
            assert _time.monotonic() < t_end, \
                f"mesh serve stalled at height {h}"
            _time.sleep(0.005)
    rep = tsvc.drain()
    assert rep["decisions_total"] == I2 * heights
    assert rep["offladder_builds"] == 0
    assert rep["host_fallback_builds"] == 0
    assert rep["rejected_signature_device"] == 0
    assert rep["inbox"]["dropped"] == 0

    # the same traffic through the SINGLE-device (packed-lane) serve
    boxC = {"h": 0}
    dC = DeviceDriver(I2, V2, advance_height=True, defer_collect=True)
    bC = VoteBatcher(I2, V2, n_slots=4)
    svcC = VoteService(
        dC, bC, PUBKEYS, capacity=4 * 2 * N2, target_votes=2 * N2,
        max_delay_s=0.0,
        ladder=ShapeLadder.plan(I2, V2, min_rung=RUNG2),
        window_predictor=lambda: (np.zeros(I2, np.int64),
                                  np.full(I2, boxC["h"], np.int64)),
        donate=False)
    for h in range(heights):
        boxC["h"] = h
        svcC.submit(wire_height2(h))
        svcC.pump()
    repC = svcC.drain()
    assert repC["decisions_total"] == I2 * heights

    # bit-identity: mesh serve == offline dense == single-device serve
    for tag, dX in (("offline-dense", dA), ("single-serve", dC)):
        for a, b in zip(dX.state, dB.state):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=f"state vs {tag}")
        for a, b in zip(dX.tally, dB.tally):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=f"tally vs {tag}")
        np.testing.assert_array_equal(dX.stats.decision_value,
                                      dB.stats.decision_value)
        np.testing.assert_array_equal(dX.stats.decision_round,
                                      dB.stats.decision_round)
        np.testing.assert_array_equal(dX.stats.decided, dB.stats.decided)


@pytest.mark.slow
def test_serve_dedup_bit_identical_and_rejected_replay():
    """ISSUE 5 acceptance: decisions served with the verified-vote
    dedup cache ON are BIT-identical — state/tally leaf-for-leaf and
    identical decision stats — to a dedup-OFF run and to the offline
    fused path, on traffic that includes gossip re-deliveries AND an
    adversarial replay of a REJECTED signature.

    Per height: fresh prevotes (validator 0's signature FORGED at
    height 0), a settle, then the exact same prevote wire re-delivered
    (height 0: the forged batch cached nothing, so the replay — forged
    record included — re-pays the signed path and is rejected again;
    height 1: a clean cache hit riding the verify-free unsigned
    entry), then fresh precommits deciding the height.  donate=False
    everywhere so the three runs share each jit entry."""
    from agnes_tpu.serve import VerifiedCache

    heights = 2
    RUNG1 = 1 << (N - 1).bit_length()      # single-class ticks

    def wire_class(h, typ, forge=None):
        return pack_wire_votes(*full_mesh_cols(
            I, V, SEEDS, h, typ, 7, forge_validator=forge))

    def forge_for(h):
        return 0 if h == 0 else None

    # offline fused reference: the same three ticks per height, built
    # and dispatched by hand (no cache — offline IS dedup-off)
    dA = DeviceDriver(I, V, advance_height=True, defer_collect=True)
    bA = VoteBatcher(I, V, n_slots=4)
    for h in range(heights):
        bA.sync_device(np.zeros(I, np.int64), np.full(I, h, np.int64))
        for typ, forge in ((PV, forge_for(h)), (PV, forge_for(h)),
                           (PC, None)):
            bA.add_arrays(*full_mesh_cols(I, V, SEEDS, h, typ, 7,
                                          forge_validator=forge))
            phases, lanes = bA.build_phases_device(
                PUBKEYS, phase_offset=1, lane_floor=RUNG1)
            dA.step_seq_signed(
                [dA.empty_phase()] + [p for p, _ in phases], lanes)
    dA.block_until_ready()
    assert dA.stats.decisions_total == I * heights
    assert dA.rejected_signature_device == 2 * I    # forged tick + replay

    def run_serve(dedup):
        box = {"h": 0}
        d = DeviceDriver(I, V, advance_height=True, defer_collect=True)
        bat = VoteBatcher(I, V, n_slots=4)
        svc = VoteService(
            d, bat, PUBKEYS, capacity=4 * 2 * N, target_votes=N,
            max_delay_s=0.0,
            ladder=ShapeLadder.plan(I, V, min_rung=RUNG1),
            dedup_cache=VerifiedCache() if dedup else None,
            window_predictor=lambda: (np.zeros(I, np.int64),
                                      np.full(I, box["h"], np.int64)),
            donate=False)
        for h in range(heights):
            box["h"] = h
            svc.submit(wire_class(h, PV, forge_for(h)))   # fresh
            svc.pump()
            svc.pump()
            svc.poll_decisions()       # settle: clean verifies cache
            svc.submit(wire_class(h, PV, forge_for(h)))   # re-delivery
            svc.pump()
            svc.pump()
            svc.submit(wire_class(h, PC))                 # decide h
            svc.pump()
            svc.pump()
        rep = svc.drain()
        return d, rep

    dON, repON = run_serve(dedup=True)
    dOFF, repOFF = run_serve(dedup=False)

    # the dedup layer did real work — and only the safe part of it
    cache = repON["serve_cache"]
    assert cache["hits"] == N                # height-1 replay only
    assert cache["insert_skipped_rejected"] == 2   # h0 forged + replay
    assert repON["preverified_votes"] == N
    assert repOFF["preverified_votes"] == 0 and repOFF["serve_cache"] is None
    # the adversarial replay of the rejected signature re-paid the
    # device verify in BOTH modes: forged tick + its replay, per mode
    for rep in (repON, repOFF):
        assert rep["rejected_signature_device"] == 2 * I
        assert rep["decisions_total"] == I * heights
        assert rep["host_fallback_builds"] == 0
        assert rep["offladder_builds"] == 0

    # bit-identity: dedup-on == dedup-off == offline fused
    for tag, dX in (("offline", dA), ("dedup-off", dOFF)):
        for a, b in zip(dX.state, dON.state):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=f"state vs {tag}")
        for a, b in zip(dX.tally, dON.tally):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=f"tally vs {tag}")
        np.testing.assert_array_equal(dX.stats.decision_value,
                                      dON.stats.decision_value)
        np.testing.assert_array_equal(dX.stats.decision_round,
                                      dON.stats.decision_round)
        np.testing.assert_array_equal(dX.stats.decided,
                                      dON.stats.decided)


@pytest.mark.slow
def test_serve_unsigned_equivocation_flood():
    """A byzantine equivocation flood through the queue on an UNSIGNED
    service: validator 0 double-votes in every instance, the batcher
    layers the conflict (device-verify ineligible -> host build), the
    donated plain sequence dispatches it, and the device tally flags
    the equivocator — the serve plane survives hostile traffic without
    a request-dependent compile shape."""
    d = DeviceDriver(I, V)             # single height, no advance
    bat = VoteBatcher(I, V, n_slots=4)
    svc = VoteService(d, bat, None, capacity=8 * N, target_votes=8 * N,
                      max_delay_s=0.0,
                      ladder=ShapeLadder.plan(I, V, min_rung=RUNG),
                      donate=True)
    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)
    n = I * V
    # honest prevotes for 7 ... plus validator 0 re-voting 8 everywhere
    svc.submit(pack_wire_votes(inst, val, np.zeros(n), np.zeros(n),
                               np.full(n, PV), np.full(n, 7)))
    svc.submit(pack_wire_votes(np.arange(I), np.zeros(I), np.zeros(I),
                               np.zeros(I), np.full(I, PV),
                               np.full(I, 8)))
    out = svc.pump()                   # densify (layered, host build)
    assert out["staged"]
    svc.pump()                         # dispatch
    rep = svc.drain()

    assert rep["dispatched_batches"] == 1
    assert rep["host_fallback_builds"] == 0   # unsigned: not a fallback
    assert np.asarray(d.equivocators_detected()).sum() == I
    ev = bat.signed_evidence(0, 0)
    assert ev is not None and {ev[0].value, ev[1].value} == {7, 8}
