"""MSM batch verification (crypto/msm_jax.py) vs the per-lane oracle.

Scalar arithmetic is oracled by plain Python bignums; the segmented-
scan Pippenger MSM by ref-implementation point arithmetic; the batch
check end-to-end by `ed25519_ref.verify` / `ed25519_jax.verify_batch`
on honest, forged, and structurally-invalid lanes.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from agnes_tpu.core import native
from agnes_tpu.crypto import ed25519_jax as E
from agnes_tpu.crypto import ed25519_ref as ref
from agnes_tpu.crypto import field_jax as F
from agnes_tpu.crypto import msm_jax as M
from agnes_tpu.crypto import scalar_jax as S
from agnes_tpu.crypto.encoding import vote_signing_bytes


def _limbs_of(x: int, n: int) -> jnp.ndarray:
    return jnp.asarray([(x >> (F.BITS * i)) & F.LMASK for i in range(n)],
                       F.I32)


def _int_of(limbs) -> int:
    arr = np.asarray(limbs)
    return sum(int(v) << (F.BITS * i) for i, v in enumerate(arr))


def test_mul_mod_L_oracle():
    rng = np.random.default_rng(7)
    for _ in range(10):
        a = int(rng.integers(0, 1 << 63)) << 64 | int(
            rng.integers(0, 1 << 63))                    # ~127 bits
        b = int(rng.integers(0, 1 << 63)) << 190         # ~253 bits
        got = M.mul_mod_L(_limbs_of(a, M.Z_LIMBS)[None],
                          _limbs_of(b, 20)[None])[0]
        assert _int_of(got) == a * b % S.L


def test_sum_mod_L_oracle():
    rng = np.random.default_rng(8)
    vals = [int(rng.integers(0, 1 << 62)) * int(rng.integers(0, 1 << 62))
            for _ in range(33)]
    x = jnp.stack([_limbs_of(v, 20) for v in vals])
    assert _int_of(M.sum_mod_L(x)) == sum(vals) % S.L


def test_window_digits_oracle():
    v = 0x1234_5678_9ABC_DEF0_1357
    d = M.window_digits(_limbs_of(v, 20)[None], 10)
    for w in range(10):
        assert int(d[w, 0]) == (v >> (8 * w)) & 0xFF


def _point_limbs(pt) -> E.Point:
    """ref projective point -> single-lane extended Point limbs."""
    zi = ref._inv(pt[2])
    x, y = pt[0] * zi % ref.P, pt[1] * zi % ref.P
    return E.Point(F.to_limbs(x), F.to_limbs(y), F.to_limbs(1),
                   F.to_limbs(x * y % ref.P))


def test_msm_oracle_small():
    """Σ [sᵢ]Pᵢ over 8 points vs ref arithmetic (16-bit scalars so the
    two-window graph stays small on CPU)."""
    rng = np.random.default_rng(9)
    ms = [int(rng.integers(1, 1 << 30)) for _ in range(8)]
    ss = [int(rng.integers(0, 1 << 16)) for _ in range(8)]
    pts = [ref._mul(m, ref.BASE) for m in ms]
    points = E.Point(*[jnp.stack(c) for c in zip(
        *[tuple(_point_limbs(p)) for p in pts])])
    scalars = jnp.stack([_limbs_of(s, 20) for s in ss])
    got = M.msm(points, scalars, n_windows=2)
    want = ref._mul(sum(s * m for s, m in zip(ss, ms)) % S.L, ref.BASE)
    assert bool(E.point_equal(got, _point_limbs(want)))


def _signed_batch(n, forge=()):
    seeds = [i.to_bytes(4, "little") + bytes(28) for i in range(n)]
    msgs = [vote_signing_bytes(1, 0, 0, i % 5) for i in range(n)]
    pks = [native.pubkey(s) for s in seeds]
    sigs = [bytearray(native.sign(s, m)) for s, m in zip(seeds, msgs)]
    for i in forge:
        sigs[i][0] ^= 1
    return E.pack_verify_inputs_host(pks, msgs, [bytes(s) for s in sigs])


def test_batch_msm_honest():
    pub, sig, blocks = _signed_batch(32)
    z = M.make_z(32, seed=1)
    batch_ok, lane_ok = M.verify_batch_msm_jit(pub, sig, blocks, z)
    assert bool(batch_ok)
    assert bool(np.asarray(lane_ok).all())


def test_batch_msm_detects_forgery_and_invalid_lanes():
    pub, sig, blocks = _signed_batch(32, forge=(5,))
    z = M.make_z(32, seed=2)
    batch_ok, _ = M.verify_batch_msm_jit(pub, sig, blocks, z)
    assert not bool(batch_ok)

    # structurally invalid lanes are EXCLUDED (z zeroed), so the batch
    # still passes and lane_ok pinpoints them: S >= L on lane 3
    pub, sig, blocks = _signed_batch(32)
    sig = np.asarray(sig).copy()
    sig[3, 32:] = 0xFF                       # S way above L
    batch_ok, lane_ok = M.verify_batch_msm_jit(
        pub, jnp.asarray(sig), blocks, M.make_z(32, seed=3))
    assert bool(batch_ok)
    lane_ok = np.asarray(lane_ok)
    assert not lane_ok[3] and lane_ok.sum() == 31


def test_adaptive_matches_per_lane_oracle():
    pub, sig, blocks = _signed_batch(64, forge=(7, 40))
    got = M.verify_batch_adaptive(pub, sig, blocks, seed=4, leaf=33)
    want = np.asarray(E.verify_batch_jit(pub, sig, blocks))
    np.testing.assert_array_equal(got, want)
    assert not want[7] and not want[40] and want.sum() == 62
