"""Fused verify kernel (crypto/pallas_verify.py) vs the RFC oracle.

All adversarial cases are packed into ONE batch so interpret mode
compiles the kernel once (the compile is cached persistently).  Oracle:
ed25519_ref.verify — itself pinned to the RFC 8032 vectors in
test_ed25519_ref.py.  The reference engine has no signatures at all
(SURVEY.md §2.1); this is the TPU-added surface.
"""

import numpy as np
import pytest

from agnes_tpu.crypto import ed25519_jax as E
from agnes_tpu.crypto import ed25519_ref as ref
from agnes_tpu.crypto import pallas_verify as pv


def _cases():
    """Returns (pubs, msgs, sigs) lists covering good + adversarial."""
    rng = np.random.RandomState(42)
    pubs, msgs, sigs = [], [], []

    def add(pub, msg, sig):
        pubs.append(pub)
        msgs.append(msg)
        sigs.append(sig)

    keys = [ref.keypair(bytes([i + 1]) * 32) for i in range(4)]
    base_msgs = [bytes([i]) * 45 for i in range(4)]
    base_sigs = [ref.sign(sk, m) for (sk, _), m in zip(keys, base_msgs)]

    # 0-3: honest signatures
    for (sk, pk), m, s in zip(keys, base_msgs, base_sigs):
        add(pk, m, s)
    # 4: corrupted R
    s = bytearray(base_sigs[0])
    s[3] ^= 1
    add(keys[0][1], base_msgs[0], bytes(s))
    # 5: corrupted S
    s = bytearray(base_sigs[1])
    s[40] ^= 1
    add(keys[1][1], base_msgs[1], bytes(s))
    # 6: wrong message
    add(keys[2][1], b"\x77" * 45, base_sigs[2])
    # 7: wrong public key
    add(keys[3][1], base_msgs[0], base_sigs[0])
    # 8: non-canonical S (S + L), same point — malleability check
    s_int = int.from_bytes(base_sigs[0][32:], "little")
    s_mall = base_sigs[0][:32] + (s_int + ref.L).to_bytes(32, "little")
    add(keys[0][1], base_msgs[0], s_mall)
    # 9: non-canonical R encoding (y >= p)
    bad_r = (ref.P + 1).to_bytes(32, "little")
    add(keys[0][1], base_msgs[0], bad_r + base_sigs[0][32:])
    # 10: pubkey not on curve (y = 2 has no valid x for most signs)
    bad_pub = (2).to_bytes(32, "little")
    add(bad_pub, base_msgs[0], base_sigs[0])
    # 11: R sign bit flipped
    r = bytearray(base_sigs[2])
    r[31] ^= 0x80
    add(keys[2][1], base_msgs[2], bytes(r))
    # 12-15: random garbage
    for _ in range(4):
        add(rng.bytes(32), rng.bytes(45), rng.bytes(64))
    # 16: x = 0 with sign = 1 (y = 1 encodes the identity; sign bit set
    # makes it non-canonical)
    enc_id = bytearray((1).to_bytes(32, "little"))
    enc_id[31] |= 0x80
    add(bytes(enc_id), base_msgs[0], base_sigs[0])
    # 17: torsion-defect signature (R' = [r]B + tau, tau of order 4,
    # S solved for R') — ACCEPTED under the framework's cofactored
    # policy by every verifier alike (the agreement property; see
    # ed25519_ref.verify)
    add(*torsioned_sig(bytes([9]) * 32, base_msgs[0]))
    # pad all messages to the fixed length
    msgs = [m[:45].ljust(45, b"\0") for m in msgs]
    return pubs, msgs, sigs


def torsioned_sig(seed, msg):
    """(pub, sig) whose verification defect is a pure small-order
    torsion point: fails the exact equation, satisfies the x8 one."""
    h = ref._sha512(seed)
    a = ref._clamp(h)
    pub = ref._compress(ref._mul(a, ref.BASE))
    r = ref._sha512_int(h[32:] + b"torsion" + msg) % ref.L
    tau = ref._decompress(bytes(32))       # y = 0: order-4 point
    rp = ref._add(ref._mul(r, ref.BASE), tau)
    rb = ref._compress(rp)
    k = ref._sha512_int(rb + pub + msg) % ref.L
    s = (r + k * a) % ref.L
    return pub, msg, rb + s.to_bytes(32, "little")


def test_fused_kernel_matches_oracle():
    pubs, msgs, sigs = _cases()
    pub, sig, blocks = E.pack_verify_inputs_host(pubs, msgs, sigs)
    got = np.asarray(
        pv.verify_batch_pallas(pub, sig, blocks, interpret=True))
    want = np.asarray([ref.verify(p, m, s)
                       for p, m, s in zip(pubs, msgs, sigs)])
    assert (got == want).all(), (got.tolist(), want.tolist())
    assert want[:4].all()          # sanity: honest lanes verify
    assert not want[4:12].any()    # adversarial lanes all rejected
    assert want[17]                # torsion defect: cofactored-accepted


def test_digits65_roundtrip():
    import jax.numpy as jnp
    rng = np.random.RandomState(3)
    vals = [int.from_bytes(rng.bytes(32), "little") % (1 << 253)
            for _ in range(5)]
    limbs = jnp.stack([jnp.asarray([(v >> (13 * i)) & 0x1FFF
                                    for i in range(20)], jnp.int32)
                       for v in vals])
    digs = np.asarray(pv._digits65(limbs))     # [65, B] msb-first
    for b, v in enumerate(vals):
        got = 0
        for j in range(65):
            got = (got << 4) | int(digs[j, b])
        assert got == v


def test_btable_is_multiples_of_base():
    tab = pv._btable()
    for e in range(1, 16):
        pt = ref._mul(e, ref.BASE)
        zi = ref._inv(pt[2])
        x, y = pt[0] * zi % ref.P, pt[1] * zi % ref.P
        ypx = sum(v << (13 * i) for i, v in enumerate(tab[e][0]))
        ymx = sum(v << (13 * i) for i, v in enumerate(tab[e][1]))
        t2d = sum(v << (13 * i) for i, v in enumerate(tab[e][2]))
        assert ypx == (y + x) % ref.P
        assert ymx == (y - x) % ref.P
        assert t2d == 2 * ref.D * x * y % ref.P


def test_fused_kernel_signed5_matches_oracle():
    """The signed 5-bit window variant (window=5) must agree with the
    RFC oracle on the same packed good+adversarial batch — including
    the torsion lane (cofactored policy) and non-canonical encodings."""
    pubs, msgs, sigs = _cases()
    pub, sig, blocks = E.pack_verify_inputs_host(pubs, msgs, sigs)
    got = np.asarray(
        pv.verify_batch_pallas(pub, sig, blocks, interpret=True, window=5))
    want = np.asarray([ref.verify(p, m, s)
                       for p, m, s in zip(pubs, msgs, sigs)])
    assert (got == want).all(), (got.tolist(), want.tolist())
    assert want[:4].all() and want[17]


def test_digits52_signed_roundtrip_and_range():
    import jax.numpy as jnp
    rng = np.random.RandomState(5)
    # includes bit-255-set values: attacker-controlled S reaches the
    # recoder before the canonicity screen, and the top window must
    # absorb raw[51] <= 1 plus the incoming carry
    vals = [int.from_bytes(rng.bytes(32), "little") % (1 << 253)
            for _ in range(8)] + [0, 1, (1 << 253) - 1, ref.L - 1,
                                  1 << 255, (1 << 256) - 1]
    limbs = jnp.stack([jnp.asarray([(v >> (13 * i)) & 0x1FFF
                                    for i in range(20)], jnp.int32)
                       for v in vals])
    digs = np.asarray(pv._digits52_signed(limbs))   # [52, B] msb-first
    assert digs.min() >= -16 and digs.max() <= 15
    for b, v in enumerate(vals):
        got = 0
        for j in range(52):
            got = got * 32 + int(digs[j, b])
        assert got == v, (b, v, got)
