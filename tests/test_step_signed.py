"""Device-fused signature verification (consensus_step_seq_signed)
vs the host-verified build: same wire traffic, bit-identical outcomes.

The fused path moves the bulk Ed25519 check inside the step dispatch
(device/step.py) so no device->host verdict sync separates densify
from tally; these tests hold it to the host path's exact semantics —
the same decisions, the same tally state, and the same treatment of
forged lanes and host-fallback subsets.  (Reference anchor: the
verify responsibility stubbed at consensus_executor.rs:38-41.)
"""

import numpy as np
import pytest

from agnes_tpu.bridge import VoteBatcher
from agnes_tpu.bridge.ingest import vote_messages_np
from agnes_tpu.core import native
from agnes_tpu.harness.device_driver import DeviceDriver
from agnes_tpu.harness.fixtures import (
    deterministic_seeds,
    full_mesh_cols,
    validator_pubkeys,
)
from agnes_tpu.types import VoteType

PV, PC = int(VoteType.PREVOTE), int(VoteType.PRECOMMIT)

I, V = 3, 4
SEEDS = deterministic_seeds(V)
PUBKEYS = validator_pubkeys(SEEDS)


def _signed_cols(h, typ, value, forge_validator=None):
    """Full-mesh (every instance x validator) columns + signatures —
    the shared fixture, so the tested signing layout is the one the
    compile check and the bench use."""
    return full_mesh_cols(I, V, SEEDS, h, typ, value,
                          forge_validator=forge_validator)


def _drive(device_verify: bool, forge_validator=None):
    d = DeviceDriver(I, V)
    bat = VoteBatcher(I, V, n_slots=4)
    d.step()                     # entry + self proposal
    bat.sync_device(np.asarray(d.tally.base_round),
                    np.asarray(d.state.height))
    for typ in (PV, PC):
        bat.add_arrays(*_signed_cols(0, typ, 7,
                                     forge_validator=forge_validator))
    if device_verify:
        phases, lanes = bat.build_phases_device(PUBKEYS)
        d.step_seq_signed([p for p, _ in phases], lanes)
        d.collect()
    else:
        phases = bat.build_phases(PUBKEYS)
        for p, _ in phases:
            d.step(phase=p)
    return d, bat


def test_fused_matches_host_honest():
    dh, bh = _drive(False)
    df, bf = _drive(True)
    assert dh.all_decided() and df.all_decided()
    np.testing.assert_array_equal(np.asarray(dh.stats.decision_value),
                                  np.asarray(df.stats.decision_value))
    for leaf_h, leaf_f in zip(dh.tally, df.tally):
        np.testing.assert_array_equal(np.asarray(leaf_h),
                                      np.asarray(leaf_f))
    for leaf_h, leaf_f in zip(dh.state, df.state):
        np.testing.assert_array_equal(np.asarray(leaf_h),
                                      np.asarray(leaf_f))
    assert bh.rejected_signature == 0 and bf.rejected_signature == 0
    assert df.rejected_signature_device == 0


def test_fused_matches_host_forged_lane():
    """Validator 0's signatures are forged in both classes: the host
    path filters at build, the fused path masks on device — identical
    post-step state, and the quorum of the 3 honest validators still
    decides (3*3 > 2*4)."""
    dh, bh = _drive(False, forge_validator=0)
    df, bf = _drive(True, forge_validator=0)
    assert dh.all_decided() and df.all_decided()
    for leaf_h, leaf_f in zip(dh.tally, df.tally):
        np.testing.assert_array_equal(np.asarray(leaf_h),
                                      np.asarray(leaf_f))
    for leaf_h, leaf_f in zip(dh.state, df.state):
        np.testing.assert_array_equal(np.asarray(leaf_h),
                                      np.asarray(leaf_f))
    # host path counts at the batcher; fused path at the driver
    assert bh.rejected_signature == 2 * I
    assert bf.rejected_signature == 0
    assert df.rejected_signature_device == 2 * I


def test_fused_entry_offset_and_queued_heights():
    """The pipelined flagship shape: entry phase prepended
    (phase_offset=1), heights advanced on device, predicted sync —
    nothing fetches from the device inside the loop."""
    heights = 3
    d = DeviceDriver(I, V, advance_height=True, defer_collect=True)
    bat = VoteBatcher(I, V, n_slots=4)
    for h in range(heights):
        bat.sync_device(np.zeros(I, np.int64), np.full(I, h, np.int64))
        for typ in (PV, PC):
            bat.add_arrays(*_signed_cols(h, typ, 7))
        phases, lanes = bat.build_phases_device(PUBKEYS, phase_offset=1)
        assert len(phases) == 2
        d.step_seq_signed([d.empty_phase()] + [p for p, _ in phases],
                          lanes)
    d.block_until_ready()
    assert d.stats.decisions_total == I * heights
    assert d.rejected_signature_device == 0
    assert int(np.asarray(d.state.height)[0]) == heights


def test_fused_past_round_spill_is_host_verified():
    """A rotated-out past-round vote in device-verify mode must be
    verified HOST-side before it can reach the fallback buckets: a
    forged past vote is rejected (and counted at the batcher), an
    honest one tallies."""
    d = DeviceDriver(I, V)
    bat = VoteBatcher(I, V, n_slots=4)
    d.step()
    # pretend the window rotated: base_round 2, so round-0 votes are past
    bat.sync_device(np.full(I, 2, np.int64), np.asarray(d.state.height))
    cols = _signed_cols(0, PC, 7, forge_validator=1)
    bat.add_arrays(*cols)
    phases, lanes = bat.build_phases_device(PUBKEYS)
    assert phases == [] and lanes is None
    # V-1 honest precommits per instance reached the host buckets; the
    # forged validator-1 lane was screened out and counted
    assert bat.rejected_signature == I
    events = bat.drain_host_events()
    assert len(events) == I          # +2/3 of 4 = 3 honest precommits
    for inst, hgt, rnd, vid in events:
        assert (hgt, rnd, vid) == (0, 0, 7)


def test_device_build_falls_back_on_mixed_values():
    """A build carrying two distinct values for one instance is NOT
    device-verify eligible (forged traffic could otherwise intern
    slots before verdicts exist): build_phases_device host-verifies
    instead — lanes is None and the forged value never touches the
    slot map."""
    bat = VoteBatcher(I, V, n_slots=4)
    d = DeviceDriver(I, V)
    d.step()
    bat.sync_device(np.asarray(d.tally.base_round),
                    np.asarray(d.state.height))
    bat.add_arrays(*_signed_cols(0, PV, 7))         # honest, value 7
    # forged extra vote: validator 0 "votes" value 3 on instance 0
    # with a garbage signature — the mixed-value gate must trip
    bat.add_arrays(np.array([0]), np.array([0]), np.zeros(1),
                   np.zeros(1), np.array([PV]), np.array([3]),
                   np.arange(64, dtype=np.uint8)[None, :])
    phases, lanes = bat.build_phases_device(PUBKEYS)
    assert lanes is None                 # host-verified fallback
    assert bat.rejected_signature >= 1   # the forged lane died here
    # value 3 was never interned for instance 0
    assert bat.slots.value_for(0, 0) == 7
    assert bat.slots.value_for(0, 1) is None
    for p, _ in phases:
        d.step(phase=p)


def test_evidence_screens_forged_votes_in_device_mode():
    """Device-verify builds log votes PRE-verdict; signed_evidence
    must not let a forged vote shadow or fabricate equivocation
    evidence — it re-verifies candidates host-side and skips
    unprovable ones."""
    bat = VoteBatcher(I, V, n_slots=4)
    d = DeviceDriver(I, V)
    d.step()
    bat.sync_device(np.asarray(d.tally.base_round),
                    np.asarray(d.state.height))
    # build 1: everyone votes 7, but validator 1's signature is FORGED
    bat.add_arrays(*_signed_cols(0, PV, 7, forge_validator=1))
    phases, lanes = bat.build_phases_device(PUBKEYS)
    assert lanes is not None
    d.step_seq_signed([p for p, _ in phases], lanes)
    d.collect()
    assert d.rejected_signature_device == I   # v1 forged in each instance
    # build 2: everyone REALLY signs value 9 (a second eligible build)
    bat.add_arrays(*_signed_cols(0, PV, 9))
    bat.build_phases_device(PUBKEYS)
    # v1's only provable votes are for 9: the forged 7 must neither
    # fabricate a (7, 9) pair nor shadow anything
    assert bat.signed_evidence(0, 1) is None
    # build 3: v1 (everyone) really signs 7 too -> provable double-sign
    bat.add_arrays(*_signed_cols(0, PV, 7))
    bat.build_phases_device(PUBKEYS)
    ev = bat.signed_evidence(0, 1)
    assert ev is not None
    first, second = ev
    assert {first.value, second.value} == {9, 7}
    # both returned votes verify to a third party
    from agnes_tpu.crypto.encoding import vote_signing_bytes
    for w in (first, second):
        msg = vote_signing_bytes(w.height, w.round, int(w.typ), w.value)
        assert native.verify(bytes(PUBKEYS[1]), msg, w.signature)


def test_evidence_survives_key_rotation_epochs(tmp_path):
    """A double-sign whose two votes were logged under DIFFERENT
    device-verify pubkey epochs must still prove: each candidate
    re-verifies against ITS build's table (_log_pk), not the latest
    one — and the epoch association survives a checkpoint roundtrip."""
    from agnes_tpu.utils.checkpoint import load_batcher, save_batcher

    bat = VoteBatcher(I, V, n_slots=4)
    d = DeviceDriver(I, V)
    d.step()
    bat.sync_device(np.asarray(d.tally.base_round),
                    np.asarray(d.state.height))
    bat.add_arrays(*_signed_cols(0, PV, 7))        # epoch-1 keys, value 7
    phases, lanes = bat.build_phases_device(PUBKEYS)
    assert lanes is not None
    # rotate validator 2's key for the next build (epoch 2)
    new_seeds = list(SEEDS)
    new_seeds[2] = bytes([99]) + bytes(31)
    new_pub = PUBKEYS.copy()
    new_pub[2] = np.frombuffer(native.pubkey(new_seeds[2]), np.uint8)
    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)
    n = I * V
    msgs = vote_messages_np(np.zeros(V), np.zeros(V, np.int64),
                            np.full(V, PV), np.full(V, 9))
    sigs = np.stack([np.frombuffer(
        native.sign(new_seeds[v], msgs[v].tobytes()), np.uint8)
        for v in range(V)])
    bat.add_arrays(inst, val, np.zeros(n), np.zeros(n), np.full(n, PV),
                   np.full(n, 9), sigs[val])
    phases2, lanes2 = bat.build_phases_device(new_pub)
    assert lanes2 is not None
    # validator 2 double-signed: 7 under the old key, 9 under the new —
    # both provable only against their own epoch tables
    ev = bat.signed_evidence(0, 2)
    assert ev is not None and {ev[0].value, ev[1].value} == {7, 9}
    # and the pairing survives persistence
    p = str(tmp_path / "bat.npz")
    save_batcher(bat, p)
    bat2 = load_batcher(p)
    ev2 = bat2.signed_evidence(0, 2)
    assert ev2 is not None and {ev2[0].value, ev2[1].value} == {7, 9}


def test_dense_matches_lane_path():
    """The dense per-cell layout (the shardable one) must agree with
    the packed-lane layout bit-for-bit, honest and forged."""
    for forge in (None, 0):
        d1, b1 = DeviceDriver(I, V), VoteBatcher(I, V, n_slots=4)
        d2, b2 = DeviceDriver(I, V), VoteBatcher(I, V, n_slots=4)
        for d, b in ((d1, b1), (d2, b2)):
            d.step()
            b.sync_device(np.asarray(d.tally.base_round),
                          np.asarray(d.state.height))
            for typ in (PV, PC):
                b.add_arrays(*_signed_cols(0, typ, 7,
                                           forge_validator=forge))
        ph1, lanes = b1.build_phases_device(PUBKEYS)
        assert lanes is not None
        d1.step_seq_signed([p for p, _ in ph1], lanes)
        d1.collect()
        ph2, dense = b2.build_phases_device_dense(PUBKEYS)
        assert dense is not None
        d2.step_seq_signed_dense([p for p, _ in ph2], dense)
        d2.collect()
        for a, c in zip(d1.tally, d2.tally):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
        for a, c in zip(d1.state, d2.state):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
        assert (d1.rejected_signature_device
                == d2.rejected_signature_device)
        assert d1.all_decided() and d2.all_decided()


def _drive_dense(I2, V2, seeds, pubs, mesh=None, verify_chunk=None,
                 hbm_budget_bytes=None, forge_validator=1):
    """One full dense signed sequence (entry + both vote classes,
    forged lanes included) — the shared body for every differential
    below."""
    from agnes_tpu.harness.fixtures import full_mesh_cols

    d = DeviceDriver(I2, V2, mesh=mesh, verify_chunk=verify_chunk,
                     hbm_budget_bytes=hbm_budget_bytes)
    b = VoteBatcher(I2, V2, n_slots=4)
    d.step()
    b.sync_device(np.asarray(d.tally.base_round),
                  np.asarray(d.state.height))
    for typ in (PV, PC):
        b.add_arrays(*full_mesh_cols(I2, V2, seeds, 0, typ, 7,
                                     forge_validator=forge_validator))
    phases, dense = b.build_phases_device_dense(pubs)
    assert dense is not None
    d.step_seq_signed_dense([p for p, _ in phases], dense)
    d.collect()
    return d


def _assert_bitwise_equal(da, db):
    for a, c in zip(da.tally, db.tally):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
    for a, c in zip(da.state, db.state):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
    assert (da.rejected_signature_device
            == db.rejected_signature_device)


# chunk grid vs I=3: 1 = one-row tiles, 2 = ragged last tile,
# 3 = full batch in one tile, 8 = chunk >= I and 0 = "no chunking"
# (both normalized to the single-call path — they share its compile,
# so they stay in tier-1; the real chunked cases each pay a fresh
# multi-minute verify-kernel compile and are tier-1-excluded via
# `slow`, run by ci.sh)
@pytest.mark.parametrize("chunk", [
    pytest.param(1, marks=pytest.mark.slow),
    pytest.param(2, marks=pytest.mark.slow),
    pytest.param(3, marks=pytest.mark.slow),
    8,
    0,
])
def test_dense_chunked_matches_unchunked(chunk):
    """The HBM-chunked dense verify (verify_chunk instance rows per
    lax.map microbatch, utils/budget.py) must be BIT-identical to the
    historical single-batch call — decisions, tally, state, and the
    per-lane reject verdicts, forged lanes included (ISSUE 1
    acceptance criterion)."""
    seeds = deterministic_seeds(V)
    pubs = validator_pubkeys(seeds)
    dc = _drive_dense(I, V, seeds, pubs, verify_chunk=chunk)
    du = _drive_dense(I, V, seeds, pubs, verify_chunk=None)
    _assert_bitwise_equal(dc, du)
    assert dc.rejected_signature_device == 2 * I
    assert dc.all_decided() and du.all_decided()


@pytest.mark.slow
def test_dense_auto_chunk_matches_unchunked():
    """verify_chunk="auto" under a tiny simulated HBM budget must pick
    a real multi-chunk plan (planner math, no device introspection)
    and still match the unchunked path bitwise."""
    from agnes_tpu.utils.budget import plan_dense_verify

    seeds = deterministic_seeds(V)
    pubs = validator_pubkeys(seeds)
    budget = 256_000          # forces tile < I at the Ps=2, 3x4 shape
    plan = plan_dense_verify(2, I, V, hbm_bytes=budget)
    assert plan.chunked       # the premise: auto must actually chunk
    da = _drive_dense(I, V, seeds, pubs, verify_chunk="auto",
                      hbm_budget_bytes=budget)
    du = _drive_dense(I, V, seeds, pubs, verify_chunk=None)
    _assert_bitwise_equal(da, du)
    assert da.all_decided()


@pytest.mark.parametrize("chunk", [
    pytest.param(1, marks=pytest.mark.slow),
    pytest.param(2, marks=pytest.mark.slow),
    24,
])
def test_lane_chunked_matches_unchunked(chunk):
    """The packed-lane fused path (step_seq_signed) with a chunked
    verify: driver rows scale to lanes (chunk * V per microbatch);
    chunk=2 leaves a ragged tail on the 24-lane batch, chunk=24 is
    normalized to the single-call path (compile shared — tier-1-safe).
    Bitwise against unchunked."""
    def run(vc):
        d = DeviceDriver(I, V, verify_chunk=vc)
        b = VoteBatcher(I, V, n_slots=4)
        d.step()
        b.sync_device(np.asarray(d.tally.base_round),
                      np.asarray(d.state.height))
        for typ in (PV, PC):
            b.add_arrays(*_signed_cols(0, typ, 7, forge_validator=0))
        phases, lanes = b.build_phases_device(PUBKEYS)
        assert lanes is not None
        d.step_seq_signed([p for p, _ in phases], lanes)
        d.collect()
        return d

    dc, du = run(chunk), run(None)
    _assert_bitwise_equal(dc, du)
    assert dc.rejected_signature_device == 2 * I
    assert dc.all_decided() and du.all_decided()


# (hier, I2, V2, verify_chunk) — the static-guarantee shape grid that
# replaces check_vma on the sharded signed wrapper (VERDICT r5 weak
# #6): flat + hierarchical meshes x unchunked / 1-row tiles / ragged
# local tiles.  chunk counts LOCAL rows: flat I2=6 shards to 3
# rows/device so chunk=2 leaves a ragged last tile; hier I2=8 shards
# to 2 rows/device.
@pytest.mark.parametrize("hier,I2,V2,chunk", [
    (False, 4, 4, None),
    (True, 4, 4, None),
    pytest.param(False, 4, 4, 1, marks=pytest.mark.slow),
    pytest.param(True, 8, 4, 1, marks=pytest.mark.slow),
    pytest.param(False, 6, 4, 2, marks=pytest.mark.slow),
])
def test_dense_sharded_matches_unsharded(hier, I2, V2, chunk):
    """The SHARDED fused signed step (each device verifying its local
    (instance, validator) cells; quorum psums unchanged) must be
    bitwise-identical to the single-device dense path — the standing
    sharded-vs-unsharded contract extended to fused verification,
    forged lanes included, chunked and unchunked (the chunk loop is a
    shard-local lax.map: zero added collectives per chunk)."""
    from agnes_tpu.parallel import make_hierarchical_mesh, make_mesh

    mesh = make_hierarchical_mesh(2, 2, 2) if hier else make_mesh(2, 4)
    seeds = deterministic_seeds(V2)
    pubs = validator_pubkeys(seeds)
    ds = _drive_dense(I2, V2, seeds, pubs, mesh=mesh,
                      verify_chunk=chunk)
    du = _drive_dense(I2, V2, seeds, pubs, mesh=None, verify_chunk=None)
    _assert_bitwise_equal(ds, du)
    # validator 1 forged in both classes across all instances
    assert ds.rejected_signature_device == 2 * I2
    assert du.rejected_signature_device == 2 * I2
    assert ds.all_decided() and du.all_decided()
