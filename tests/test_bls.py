"""BLS aggregate lane (ISSUE 10): reference-crypto self-tests, wire /
registry / class-table admission units, the generalized Pippenger
digit/bucket math against python ints, and the jax-vs-ref
differentials — cheap cases run eager or pure-python (pairings cost
~2s each on this box, so they are rationed); compile-heavy cases
(anything dispatching `bls_aggregate` or a fused verify) are marked
slow per the 870s tier-1 budget.

The flagship slow test proves the acceptance differential: decisions
served through the AGGREGATE lane == the per-vote Ed25519 serve plane
== the offline fused path, state/tally leaf-for-leaf — including a
forged-share class that must fall back to per-share verification
without poisoning the honest shares."""

import numpy as np
import pytest

from agnes_tpu.crypto import bls_ref as ref
from agnes_tpu.crypto.encoding import vote_signing_bytes
from agnes_tpu.serve.bls_lane import (
    BLS_REC_SIZE,
    BlsClassTable,
    BlsKeyRegistry,
    pack_bls_wire,
    unpack_bls_wire,
)

PV, PC = 0, 1


def _incremental_keys(V):
    """Throwaway fixture keys sk_v = v + 1: pubkeys by cumulative G1
    adds (no per-validator scalar mult), shares by cumulative adds of
    the message point."""
    pts, acc = [], None
    for _ in range(V):
        acc = ref.point_add(acc, ref.G1)
        pts.append(acc)
    pk = np.stack([np.frombuffer(ref.g1_compress(p), np.uint8)
                   for p in pts])
    return pts, pk


def _class_shares(V, msg_pt):
    out, acc = [], None
    for _ in range(V):
        acc = ref.point_add(acc, msg_pt)
        out.append(np.frombuffer(ref.g2_to_bytes(acc), np.uint8))
    return np.stack(out)


# ---------------------------------------------------------------------------
# reference crypto (pure python; each pairing product ~2s)
# ---------------------------------------------------------------------------


def test_ref_sign_verify_and_reject():
    sk, pk = ref.keygen(b"\x07" * 16)
    sig = ref.sign(sk, b"agnes vote")
    assert ref.verify(pk, b"agnes vote", sig)
    assert not ref.verify(pk, b"other vote", sig)


def test_ref_weighted_aggregate_and_forged_share():
    V = 3
    pts, _pk = _incremental_keys(V)
    msg_pt = ref.hash_to_g2(b"class message")
    sigs = [ref.point_mul(v + 1, msg_pt) for v in range(V)]
    w = [2, 1, 5]
    agg = None
    for s, wi in zip(sigs, w):
        agg = ref.point_add(agg, ref.point_mul(wi, s))
    assert ref.aggregate_verify_weighted(pts, w, msg_pt, agg)
    # one forged share in the aggregate must fail the ONE pairing
    forged = ref.point_add(agg, msg_pt)
    assert not ref.aggregate_verify_weighted(pts, w, msg_pt, forged)


def test_ref_pop_domain_separation():
    sk, pk = ref.keygen(b"\x21" * 16)
    pop = ref.pop_prove(sk, pk)
    assert ref.pop_verify(pk, pop)
    # a vote signature over the pubkey bytes must NOT pass as a PoP:
    # the PoP hash is domain-separated (rogue-key threat model)
    assert not ref.pop_verify(pk, ref.sign(sk, pk))


def test_g1_codec_roundtrip_and_rejects():
    pt = ref.point_mul(7, ref.G1)
    assert ref.g1_decompress(ref.g1_compress(pt)) == pt
    assert ref.g1_decompress(ref.g1_compress(None)) is None
    with pytest.raises(ValueError):
        ref.g1_decompress(b"\x00" * 48)          # no compression flag
    with pytest.raises(ValueError):
        ref.g1_decompress(b"\xff" * 48)          # x out of range
    with pytest.raises(ValueError):
        ref.g1_decompress(b"\x00" * 47)          # wrong length


def test_g2_codec_roundtrip_and_rejects():
    pt = ref.point_mul(5, ref.G2)
    assert ref.g2_from_bytes(ref.g2_to_bytes(pt)) == pt
    assert ref.g2_from_bytes(bytes(192)) is None      # identity
    with pytest.raises(ValueError):
        ref.g2_from_bytes(bytes(191))
    bad = bytearray(ref.g2_to_bytes(pt))
    bad[-1] ^= 1
    with pytest.raises(ValueError):                   # off the twist
        ref.g2_from_bytes(bytes(bad))


# ---------------------------------------------------------------------------
# wire codec + key registry + class table (numpy/stdlib; no pairings)
# ---------------------------------------------------------------------------


def test_bls_wire_roundtrip_and_truncation():
    n = 3
    shares = np.arange(n * 192, dtype=np.uint8).reshape(n, 192)
    wire = pack_bls_wire([0, 1, 0], [2, 0, 1], [5, 5, 6], [0, 1, 0],
                         [PV, PC, PV], [7, -1, 9], shares)
    assert len(wire) == n * BLS_REC_SIZE
    inst, val, h, r, typ, value, sh = unpack_bls_wire(wire)
    assert inst.tolist() == [0, 1, 0]
    assert val.tolist() == [2, 0, 1]
    assert h.tolist() == [5, 5, 6]
    assert r.tolist() == [0, 1, 0]
    assert typ.tolist() == [PV, PC, PV]
    assert value.tolist() == [7, -1, 9]       # nil survives
    np.testing.assert_array_equal(sh, shares)
    # a trailing partial record is dropped by the codec (counted as
    # malformed by the fold)
    assert len(unpack_bls_wire(wire + b"\x01\x02")[0]) == n


def _registry(V=3, powers=None):
    _pts, pk = _incremental_keys(V)
    return BlsKeyRegistry(pk, powers=powers)


def test_key_registry_pop_gating_and_epochs():
    reg = _registry(V=3)
    assert not reg.pop_ok.any()
    # a wrong proof flips nothing
    assert not reg.register_pop(0, bytes(192))
    assert not reg.register_pop(99, bytes(192))       # out of range
    assert not reg.pop_ok.any()
    pop = ref.pop_prove(1, bytes(reg.pk_bytes[0]))    # sk_0 = 1
    assert reg.register_pop(0, pop)
    assert reg.pop_ok[0] and not reg.pop_ok[1:].any()
    reg.mark_trusted([2])
    assert reg.pop_ok[2]
    # epoch advance invalidates memoized pairing verdicts by key
    e0 = reg.epoch
    reg.set_powers([3, 1, 1])
    assert reg.epoch == e0 + 1
    # the weight WIDTH is fixed at construction (the MSM window count
    # is a warmed compile-key component)
    with pytest.raises(ValueError):
        reg.set_powers([1 << 10, 1, 1])
    with pytest.raises(ValueError):
        _registry(V=2, powers=[1 << 30, 1])   # W_BITS screen


def _wire_one(inst, val, typ, share, h=0, value=7):
    return pack_bls_wire([inst], [val], [h], [0], [typ], [value],
                         share[None])


def test_class_table_fold_taxonomy_and_poll():
    reg = _registry(V=3)
    reg.mark_trusted([0, 1])                  # validator 2 has no PoP
    t = BlsClassTable(reg, n_instances=2, max_classes=1,
                      clock=lambda: 0.0)
    share = np.zeros(192, np.uint8)           # opaque (decode=False)
    r = t.fold(_wire_one(0, 0, PV, share), decode=False)
    assert r["folded"] == 1
    r = t.fold(_wire_one(0, 0, PV, share), decode=False)
    assert r["duplicate"] == 1                # one share per signer
    r = t.fold(_wire_one(0, 2, PV, share), decode=False)
    assert r["pop_missing"] == 1              # rogue-key defense
    r = t.fold(_wire_one(0, 9, PV, share), decode=False)
    assert r["unknown_validator"] == 1
    r = t.fold(_wire_one(9, 0, PV, share), decode=False)
    assert r["malformed"] == 1                # instance out of range
    r = t.fold(_wire_one(0, 0, PC, share), decode=False)
    assert r["overflow"] == 1                 # max_classes=1
    # decode=True screens a non-point share as malformed
    r = t.fold(_wire_one(0, 1, PV, share), decode=True)
    assert r["malformed"] == 1 and r["folded"] == 0
    # size-close at target, not below
    assert t.poll(now=0.0, target_signers=2, max_delay_s=1e9) == []
    r = t.fold(_wire_one(0, 1, PV, share), decode=False)
    assert r["folded"] == 1
    closed = t.poll(now=0.0, target_signers=2, max_delay_s=1e9)
    assert len(closed) == 1 and closed[0].n_signers == 2
    assert closed[0].weight == 2
    assert t.open_classes == 0
    # deadline-close: a lone share older than the deadline leaves too
    t.fold(_wire_one(1, 0, PV, share), decode=False)
    assert t.poll(now=99.0, target_signers=2, max_delay_s=0.5)
    c = t.snapshot()
    assert c["bls_shares_folded"] == 3
    assert c["bls_duplicate_share"] == 1
    assert c["bls_pop_missing"] == 1


def test_lane_forged_share_memo_and_quarantine():
    """Fallback liveness defenses, host-only (device aggregation
    stubbed with the oracle sum): a forged class replayed
    byte-identically is served from the memos (zero pairings), and a
    validator proven forged `quarantine_after` times has further
    folds refused at admission."""
    from agnes_tpu.serve.bls_lane import BlsLane

    V, I = 2, 1
    _pts, pk = _incremental_keys(V)
    reg = _registry(V=V)
    reg.mark_trusted(np.arange(V))
    lane = BlsLane(reg, I, target_signers=V, max_delay_s=1e9,
                   quarantine_after=2)

    def oracle_agg(cls, signers):
        apk = asig = None
        for v in signers:
            apk = ref.point_add(apk, ref.g1_decompress(bytes(pk[v])))
            asig = ref.point_add(asig,
                                 ref.g2_from_bytes(cls.shares[v]))
        return apk, asig

    lane._aggregate_device = oracle_agg

    def submit_class(h, forged_share):
        msg_pt = ref.hash_to_g2(vote_signing_bytes(h, 0, PV, 7))
        shares = _class_shares(V, msg_pt)
        shares[1] = np.frombuffer(forged_share, np.uint8)
        return lane.table.fold(pack_bls_wire(
            [0] * V, list(range(V)), [h] * V, [0] * V, [PV] * V,
            [7] * V, shares))

    bad1 = ref.g2_to_bytes(ref.point_mul(77, ref.G2))
    assert submit_class(0, bad1)["folded"] == V
    lane.clear_classes(lane.poll())
    assert lane.counters["rejected_share_signature"] == 1
    assert reg.forged_strikes[1] == 1 and not reg.quarantined[1]
    # byte-identical replay: memos, no new strike
    assert submit_class(0, bad1)["folded"] == V
    lane.clear_classes(lane.poll())
    assert lane.counters["pairing_memo_hits"] == 1
    assert reg.forged_strikes[1] == 1
    # FRESH garbage at a new height: second strike -> quarantined
    bad2 = ref.g2_to_bytes(ref.point_mul(78, ref.G2))
    assert submit_class(1, bad2)["folded"] == V
    lane.clear_classes(lane.poll())
    assert reg.forged_strikes[1] == 2 and reg.quarantined[1]
    # further folds from the proven forger are refused at admission
    res = submit_class(2, bad2)
    assert res["quarantined"] == 1 and res["folded"] == V - 1
    assert lane.table.counters["bls_quarantined"] == 1


# ---------------------------------------------------------------------------
# generalized Pippenger digit/bucket math vs python ints (tiny eager
# graphs only — the "curve" is integer addition)
# ---------------------------------------------------------------------------


def _to_limbs(x, bits, nl):
    return [(x >> (bits * i)) & ((1 << bits) - 1) for i in range(nl)]


def test_window_digits_generalized_against_ints():
    import jax.numpy as jnp

    from agnes_tpu.crypto import msm_jax as M

    rng = np.random.default_rng(0)
    for bits, c, nl in ((13, 8, 20), (12, 4, 2), (12, 6, 3)):
        n_windows = -(-(bits * nl) // c)
        xs = [int(rng.integers(0, 1 << min(bits * nl, 63)))
              for _ in range(4)]
        limbs = jnp.asarray([_to_limbs(x, bits, nl) for x in xs],
                            jnp.int32)
        digits = np.asarray(M.window_digits(limbs, n_windows, c=c,
                                            bits=bits))
        for j, x in enumerate(xs):
            for w in range(n_windows):
                assert digits[w, j] == (x >> (c * w)) & ((1 << c) - 1)
    with pytest.raises(AssertionError):
        M.window_digits(limbs, 2, c=13, bits=12)      # c > bits


def test_generic_bucket_machinery_and_msm_over_ints():
    import jax
    import jax.numpy as jnp

    from agnes_tpu.crypto import msm_jax as M

    add = lambda a, b: a + b                            # noqa: E731
    idn = lambda shape: jnp.zeros(shape, jnp.int64)     # noqa: E731

    rng = np.random.default_rng(1)
    pts = jnp.asarray(rng.integers(1, 1 << 20, size=8), jnp.int64)
    digits = jnp.asarray(rng.integers(0, 16, size=8), jnp.int32)
    buckets = M.bucket_sums_seq(pts, digits, point_add=add,
                                identity=idn, n_buckets=16)
    buckets = np.asarray(buckets)
    for d in range(16):
        want = int(np.asarray(pts)[np.asarray(digits) == d].sum())
        assert buckets[d] == want, d
    total = M.bucket_aggregate_merged(jnp.asarray(buckets),
                                      point_add=add, identity=idn,
                                      n_buckets=16)
    assert int(total) == sum(d * int(buckets[d]) for d in range(16))
    # rolled vs merged aggregate agree
    assert int(M.bucket_aggregate_generic(
        jnp.asarray(buckets), point_add=add, identity=idn,
        n_buckets=16)) == int(total)

    # full generic MSM over the integer "curve": Σ wᵢ xᵢ, zero-weight
    # lanes dropped by the 0-bucket exclusion
    bits, c, nl = 12, 4, 2
    w_int = [0, 1, 255, 77, 0, 13, 200, 5]
    limbs = jnp.asarray([_to_limbs(w, bits, nl) for w in w_int],
                        jnp.int32)
    got = M.msm_generic(pts, limbs, n_windows=2, point_add=add,
                        identity=idn, window_c=c, bits=bits)
    want = sum(w * int(p) for w, p in zip(w_int, np.asarray(pts)))
    assert int(jax.device_get(got)) == want


def test_n_windows_for_widths():
    from agnes_tpu.crypto import bls_jax as BJ

    assert BJ.n_windows_for(1) == 1        # uniform stake: one window
    assert BJ.n_windows_for(4) == 1
    assert BJ.n_windows_for(5) == 2
    assert BJ.n_windows_for(BJ.W_BITS) == BJ.N_WINDOWS
    assert BJ.n_windows_for(99) == BJ.N_WINDOWS    # clamped


# ---------------------------------------------------------------------------
# jax-vs-ref differentials (eager; the field/curve grid is minutes of
# eager dispatch — slow-marked per the tier-1 budget)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_field_differential_grid():
    import jax.numpy as jnp

    from agnes_tpu.crypto import bls_field_jax as BF

    P = ref.P
    vals = [0, 1, 2, P - 1, P - 2, (1 << 381) - 1, 4 * P - 1,
            0x1234567890ABCDEF << 200]

    def fv(x):
        return BF.fv_in(jnp.asarray(BF.to_limbs(x))[None],
                        max(x, 1))

    for x in vals:
        for y in vals[:5]:
            for op, pyop in ((BF.fv_add, lambda a, b: a + b),
                             (BF.fv_sub, lambda a, b: a - b),
                             (BF.fv_mul, lambda a, b: a * b)):
                got = BF.from_limbs(np.asarray(op(fv(x), fv(y)).a)) % P
                assert got == pyop(x, y) % P, (op.__name__, x, y)
    # small-constant multiply
    got = BF.from_limbs(np.asarray(
        BF.fv_mul_small(fv(P - 1), 12).a)) % P
    assert got == (P - 1) * 12 % P


@pytest.mark.slow
def test_curve_ops_differential():
    import jax.numpy as jnp

    from agnes_tpu.crypto import bls_field_jax as BF
    from agnes_tpu.crypto import bls_jax as BJ

    def g1_dev(pt):
        if pt is None:
            return BJ.g1_identity(())
        return BJ.G1P(x=jnp.asarray(BF.to_limbs(pt[0])),
                      y=jnp.asarray(BF.to_limbs(pt[1])),
                      z=jnp.asarray(BF.to_limbs(1)))

    def g2_dev(pt):
        if pt is None:
            return BJ.g2_identity(())
        (x, y) = pt
        st = lambda c: jnp.stack(                       # noqa: E731
            [jnp.asarray(BF.to_limbs(c.c[0])),
             jnp.asarray(BF.to_limbs(c.c[1]))])
        return BJ.G2P(x=st(x), y=st(y),
                      z=jnp.stack([jnp.asarray(BF.to_limbs(1)),
                                   jnp.asarray(BF.to_limbs(0))]))

    # identity, doubling, inverse pairs and generic adds all route
    # through the ONE complete RCB formula — exactly what the bucket
    # accumulators feed it
    g1s = [None, ref.G1, ref.point_mul(7, ref.G1),
           ref.point_neg(ref.G1)]
    for a in g1s:
        for b in g1s:
            got = BJ.g1_from_device(BJ.g1_add(g1_dev(a), g1_dev(b)))
            assert got == ref.point_add(a, b), (a, b)
    g2s = [None, ref.G2, ref.point_mul(5, ref.G2),
           ref.point_neg(ref.G2)]
    for a in g2s:
        for b in g2s:
            got = BJ.g2_from_device(BJ.g2_add(g2_dev(a), g2_dev(b)))
            assert got == ref.point_add(a, b)


@pytest.mark.slow
def test_weighted_msm_differential_eager():
    """Multi-window weighted MSM vs the reference — eager (no rung
    compile): N=3 lanes, weights spanning two 4-bit windows, both
    groups in one bls_aggregate call."""
    import jax.numpy as jnp

    from agnes_tpu.crypto import bls_jax as BJ

    V = 3
    pts, _pk = _incremental_keys(V)
    msg_pt = ref.hash_to_g2(b"msm diff")
    sigs = [ref.point_mul(v + 1, msg_pt) for v in range(V)]
    w = [255, 0, 17]
    agg_pk, agg_sig = BJ.bls_aggregate(
        jnp.asarray(BJ.pack_g1_rows(pts)),
        jnp.asarray(BJ.pack_g2_rows(sigs)),
        jnp.asarray(BJ.pack_weights(np.asarray(w))), n_windows=2)
    want_pk = want_sig = None
    for p, s, wi in zip(pts, sigs, w):
        want_pk = ref.point_add(want_pk, ref.point_mul(wi, p))
        want_sig = ref.point_add(want_sig, ref.point_mul(wi, s))
    assert BJ.g1_from_device(agg_pk) == want_pk
    assert BJ.g2_from_device(agg_sig) == want_sig
    # and the pairing oracle accepts exactly this weighted aggregate
    assert ref.aggregate_verify_weighted(pts, w, msg_pt, want_sig)


# ---------------------------------------------------------------------------
# the acceptance differential: DEVICE-pairing aggregate lane ==
# HOST-pairing aggregate lane == per-vote Ed25519 == offline fused,
# leaf-for-leaf, incl. the forged-share fallback (ISSUE 10 + 13)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_serve_bls_differential_and_forged_fallback():
    from agnes_tpu.bridge import VoteBatcher
    from agnes_tpu.bridge.native_ingest import pack_wire_votes
    from agnes_tpu.harness.device_driver import DeviceDriver
    from agnes_tpu.harness.fixtures import (
        deterministic_seeds,
        full_mesh_cols,
        validator_pubkeys,
    )
    from agnes_tpu.serve import ShapeLadder, VoteService
    from agnes_tpu.serve.bls_lane import BlsLane
    from agnes_tpu.types import VoteType

    I, V = 2, 4
    N = I * V
    heights = 3
    FORGED_H, FORGED_V = 1, 1     # height 1's prevote class carries a
    #                               forged share from validator 1
    pv, pc = int(VoteType.PREVOTE), int(VoteType.PRECOMMIT)
    seeds = deterministic_seeds(V)
    ed_pubkeys = validator_pubkeys(seeds)
    rung = 1 << (2 * N - 1).bit_length()
    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)

    def ed_cols(h, typ):
        return full_mesh_cols(
            I, V, seeds, h, typ, 7,
            forge_validator=(FORGED_V if (h, typ) == (FORGED_H, pv)
                             else None))

    # -- offline fused reference --------------------------------------------
    dA = DeviceDriver(I, V, advance_height=True, defer_collect=True)
    bA = VoteBatcher(I, V, n_slots=4)
    for h in range(heights):
        bA.sync_device(np.zeros(I, np.int64), np.full(I, h, np.int64))
        for typ in (pv, pc):
            bA.add_arrays(*ed_cols(h, typ))
        phases, lanes = bA.build_phases_device(ed_pubkeys,
                                               phase_offset=1,
                                               lane_floor=rung)
        dA.step_seq_signed([dA.empty_phase()] + [p for p, _ in phases],
                           lanes)
    dA.block_until_ready()
    assert dA.stats.decisions_total == I * heights

    # -- per-vote Ed25519 serve ---------------------------------------------
    box = {"h": 0}
    dB = DeviceDriver(I, V, advance_height=True, defer_collect=True)
    svcB = VoteService(
        dB, VoteBatcher(I, V, n_slots=4), ed_pubkeys,
        capacity=4 * 2 * N, target_votes=2 * N, max_delay_s=0.0,
        ladder=ShapeLadder.plan(I, V, min_rung=rung), donate=False,
        window_predictor=lambda: (np.zeros(I, np.int64),
                                  np.full(I, box["h"], np.int64)))
    for h in range(heights):
        box["h"] = h
        wire = b"".join(pack_wire_votes(*ed_cols(h, typ))
                        for typ in (pv, pc))
        svcB.submit(wire)
        svcB.pump()
    repB = svcB.drain()
    assert repB["decisions_total"] == I * heights
    # one forged prevote lane per instance at the forged height
    assert repB["rejected_signature_device"] == I

    # -- BLS aggregate-lane serves: DEVICE pairing and HOST pairing ----------
    bls_pts, bls_pk = _incremental_keys(V)

    def bls_serve(device_pairing, pallas_field=False):
        reg = BlsKeyRegistry(bls_pk)
        reg.mark_trusted(np.arange(V))
        lane = BlsLane(reg, I, target_signers=V, max_delay_s=1e9,
                       device_pairing=device_pairing,
                       pallas_field=pallas_field)
        dX = DeviceDriver(I, V, advance_height=True,
                          defer_collect=True, audit=True)
        svcX = VoteService(
            dX, VoteBatcher(I, V, n_slots=4), None, bls_lane=lane,
            capacity=4 * 2 * N, target_votes=2 * N, max_delay_s=1e9,
            ladder=ShapeLadder.plan(I, V).with_bls(
                V, min_rung=4, class_rungs=(1,)),
            window_predictor=lambda: (np.zeros(I, np.int64),
                                      np.full(I, box["h"], np.int64)))
        svcX.pipeline.warmup()   # bls + pairing rungs + unsigned; arms
        for h in range(heights):
            box["h"] = h
            for typ in (pv, pc):
                msg_pt = ref.hash_to_g2(
                    vote_signing_bytes(h, 0, typ, 7))
                shares = _class_shares(V, msg_pt)
                if (h, typ) == (FORGED_H, pv):
                    # validator 1's share signs the WRONG message:
                    # the class pairing must fail and fall back
                    # per-share
                    wrong = ref.hash_to_g2(b"forged")
                    shares[FORGED_V] = np.frombuffer(
                        ref.g2_to_bytes(ref.point_mul(FORGED_V + 1,
                                                      wrong)),
                        np.uint8)
                svcX.submit_bls(pack_bls_wire(
                    inst, val, np.full(N, h), np.zeros(N),
                    np.full(N, typ), np.full(N, 7),
                    np.tile(shares, (I, 1))))
                svcX.pump()
                svcX.pump()
            svcX.poll_decisions()
        repX = svcX.drain()
        assert repX["decisions_total"] == I * heights
        bls = repX["bls"]
        # the forged class fell back: I classes (one per instance)
        # at the forged height, each dropping exactly the forged
        # share and dispatching the honest remainder — identically
        # in BOTH pairing modes (the device pairing is
        # reject-equivalent on forged classes)
        assert bls["fallback_classes"] == I, bls
        assert bls["rejected_share_signature"] == I, bls
        assert bls["fallback_votes"] == I * (V - 1), bls
        assert bls["agg_classes"] == 2 * heights * I - I, bls
        assert repX["metrics"].get("retrace_unexpected", 0) == 0
        if device_pairing:
            # the steady state really was device-paired
            assert bls["bls_device_pairing_dispatches"] > 0, bls
        else:
            assert bls["bls_device_pairing_dispatches"] == 0, bls
        return dX

    dC = bls_serve(device_pairing=True)
    dD = bls_serve(device_pairing=False)
    # ISSUE 18: the same serve, MSM + pairing on the Pallas field-
    # kernel lane (CPU interpret) — warmup compiles the kernel-lane
    # variants, the armed sentinel proves zero unwarmed dispatches,
    # and the decisions must stay leaf-for-leaf identical
    dE = bls_serve(device_pairing=True, pallas_field="interpret")

    # -- leaf-for-leaf equality across all planes ---------------------------
    for name, dX in (("ed_serve", dB), ("bls_serve_device", dC),
                     ("bls_serve_host", dD),
                     ("bls_serve_pallas", dE)):
        for a, b in zip(dA.state, dX.state):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b), err_msg=name)
        for a, b in zip(dA.tally, dX.tally):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b), err_msg=name)
        np.testing.assert_array_equal(dA.stats.decision_value,
                                      dX.stats.decision_value)
        np.testing.assert_array_equal(dA.stats.decision_round,
                                      dX.stats.decision_round)
