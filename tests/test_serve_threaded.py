"""ThreadedVoteService host event loop — CHEAP side (tier-1): inbox
bounds, concurrent submit conservation (no lost/duplicated votes or
decisions across threads), clean drain, per-thread gauges, and the
Metrics registry's thread-safety.  Device dispatch is STUBBED
throughout — the machinery under test is the host threading layer;
the real mesh dispatch path is covered by the slow differential in
tests/test_serve_pipeline.py — so nothing here compiles."""

import threading
import time

import numpy as np
import pytest

from agnes_tpu.bridge import VoteBatcher
from agnes_tpu.bridge.native_ingest import pack_wire_votes
from agnes_tpu.harness.device_driver import DeviceDriver
from agnes_tpu.serve import (
    Inbox,
    ShapeLadder,
    ThreadedVoteService,
    VoteService,
)
from agnes_tpu.serve.service import (
    SERVE_DISPATCH_BUSY_FRAC,
    SERVE_INBOX_DROPPED,
    SERVE_SUBMIT_BUSY_FRAC,
)
from agnes_tpu.utils.metrics import Metrics


def _wait(pred, timeout_s=20.0, what="condition"):
    t_end = time.monotonic() + timeout_s
    while not pred():
        if time.monotonic() > t_end:
            raise AssertionError(f"timed out waiting for {what}")
        time.sleep(0.002)


def _stubbed_service(I=4, V=8, **kw):
    """Unsigned service whose device dispatch is replaced by a
    recording stub (votes counted off the phase masks — exactly what
    the device would tally for honest unsigned traffic)."""
    d = DeviceDriver(I, V)
    bat = VoteBatcher(I, V, n_slots=4)
    kw.setdefault("ladder", ShapeLadder.plan(I, V, min_rung=8))
    kw.setdefault("capacity", 4 * I * V)
    kw.setdefault("target_votes", 8)
    kw.setdefault("max_delay_s", 0.0)
    svc = VoteService(d, bat, None, **kw)
    dispatched = []

    def stub(phases, lanes=None, exts=None, donate=True, tick=None):
        dispatched.append(sum(int(np.asarray(p.mask).sum())
                              for p in phases))

    d.step_async = stub
    return svc, d, dispatched


# -- inbox --------------------------------------------------------------------

def test_inbox_bounded_fifo_and_dropped():
    box = Inbox(2)
    assert box.put(b"a") and box.put(b"b")
    assert not box.put(b"c")            # full: fail closed, counted
    assert box.dropped == 1 and box.enqueued == 2
    assert box.get() == b"a" and box.get() == b"b"   # FIFO
    assert box.get(timeout=0.01) is None             # empty: timeout
    box.close()
    assert not box.put(b"d") and box.dropped == 2    # closed: refused
    with pytest.raises(ValueError):
        Inbox(0)


def test_threaded_drain_flushes_inbox_residue():
    """A blob the inbox ACCEPTED (put returned True) before the close
    must reach admission even if no loop ever drained it — the
    loss-free-drain contract that closes the submit/stop race (drain
    flushes the residue itself after closing the inbox)."""
    svc, d, _ = _stubbed_service()
    tsvc = ThreadedVoteService(svc)           # threads never started
    assert tsvc.submit(pack_wire_votes([0], [0], [0], [0], [0], [7]))
    rep = tsvc.drain()
    assert rep["dispatched_votes"] == 1       # accepted blob NOT lost
    assert rep["inbox"]["depth_at_drain"] == 0
    assert tsvc.inbox.closed
    assert not tsvc.submit(b"\x00" * 96)      # after drain: refused


# -- concurrent submit conservation -------------------------------------------

def test_threaded_submit_no_lost_no_duplicated_votes():
    """N submitter threads race the event loop; every admitted vote is
    dispatched exactly once (conservation at the device boundary: the
    sum of dispatched phase-mask cells equals the admitted count)."""
    I, V = 4, 8
    svc, d, dispatched = _stubbed_service(I, V)
    tsvc = ThreadedVoteService(svc, idle_wait_s=0.0005,
                               gauge_interval_s=0.01).start()
    n_threads, per_thread = 4, 8       # 32 votes = one per (I, V) cell

    def submitter(t):
        for k in range(per_thread):
            inst, val = (t * per_thread + k) // V, (t * per_thread + k) % V
            w = pack_wire_votes([inst], [val], [0], [0], [0], [7])
            assert tsvc.submit(w)

    threads = [threading.Thread(target=submitter, args=(t,))
               for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    total = n_threads * per_thread
    _wait(lambda: svc.pipeline.dispatched_votes >= total,
          what="all votes dispatched")
    rep = tsvc.drain()
    assert rep["dispatched_votes"] == total
    assert rep["inbox"]["enqueued"] == total
    assert rep["inbox"]["dropped"] == 0
    assert rep["metrics"]["serve_admitted"] == total
    assert sum(dispatched) == total    # each vote dispatched EXACTLY once
    assert svc.pipeline.offladder_builds == 0
    assert d.stats.steps == 0          # the stub never touched XLA


def test_threaded_poll_decisions_exactly_once():
    """Decisions latched while the dispatch thread runs are reported
    exactly once across concurrent-era polls and the final drain."""
    I, V = 4, 8
    svc, d, _ = _stubbed_service(I, V)
    bat = svc.batcher

    def deciding_stub(phases, lanes=None, exts=None, donate=True,
                      tick=None):
        d.stats.decided[:] = True      # the device latched everyone
        d.stats.decision_value[:] = 0
        d.stats.decision_round[:] = 0
        d.stats.decisions_total = I

    d.driver_stub = deciding_stub
    d.step_async = deciding_stub
    tsvc = ThreadedVoteService(svc, idle_wait_s=0.0005).start()
    inst = np.arange(I)
    assert tsvc.submit(pack_wire_votes(inst, np.zeros(I), np.zeros(I),
                                       np.zeros(I), np.zeros(I),
                                       np.full(I, 7)))
    _wait(lambda: svc.pipeline.dispatched_votes >= I,
          what="the tick's dispatch")
    decs = tsvc.poll_decisions()
    assert len(decs) == I
    assert all(dec.value_id == 7 for dec in decs)    # slot 0 -> 7
    assert tsvc.poll_decisions() == []               # no duplicates
    rep = tsvc.drain()
    assert rep["final_decisions"] == []              # still none new
    assert rep["decisions_total"] == I


def test_threaded_drain_rejects_late_submits_and_reports_gauges():
    svc, d, _ = _stubbed_service()
    tsvc = ThreadedVoteService(svc, idle_wait_s=0.0005,
                               gauge_interval_s=0.005).start()
    assert tsvc.submit(pack_wire_votes([0], [0], [0], [0], [0], [7]))
    _wait(lambda: svc.pipeline.dispatched_votes >= 1, what="dispatch")
    time.sleep(0.03)                   # let a gauge window elapse
    rep = tsvc.drain()
    # fail closed after drain: the blob is refused and counted
    assert not tsvc.submit(b"\x00" * 96)
    assert svc.metrics.counters[SERVE_INBOX_DROPPED] >= 1
    snap = rep["metrics"]
    assert SERVE_SUBMIT_BUSY_FRAC in snap
    assert SERVE_DISPATCH_BUSY_FRAC in snap
    assert 0.0 <= snap[SERVE_DISPATCH_BUSY_FRAC] <= 1.0


def test_threaded_loop_failure_fails_closed():
    """A loop thread killed by a runtime error (XLA OOM, densify bug)
    must not leave a zombie service silently accepting work: the
    guard records the failure, refuses new submits, and drain
    surfaces the exception in its report."""
    svc, d, _ = _stubbed_service()

    def boom(phases, lanes=None, exts=None, donate=True, tick=None):
        raise RuntimeError("synthetic XLA death")

    d.step_async = boom
    tsvc = ThreadedVoteService(svc, idle_wait_s=0.0005).start()
    tsvc.submit(pack_wire_votes([0], [0], [0], [0], [0], [7]))
    _wait(lambda: tsvc.failure is not None, what="loop failure")
    assert not tsvc.submit(pack_wire_votes([1], [0], [0], [0], [0],
                                           [7]))      # fail closed
    rep = tsvc.drain()
    assert rep["thread_failure"] is not None
    assert "synthetic XLA death" in rep["thread_failure"]
    assert rep["metrics"]["serve_thread_failures"] == 1
    # the dying dispatch call cleared its in-flight marker (finally):
    # a dead thread must not read 100% busy in every later window
    assert tsvc._busy_inflight == {"submit": None, "dispatch": None}


# -- metrics thread-safety ----------------------------------------------------

def test_metrics_concurrent_counts_are_exact():
    """The ISSUE-3 satellite: submit and dispatch threads race one
    registry — counter read-modify-writes and first-touch gauge
    registration must be exact under concurrency."""
    m = Metrics()
    n_threads, per_thread = 8, 5000

    def worker(t):
        for k in range(per_thread):
            m.count("x")
            if k % 100 == 0:
                m.gauge(f"g{t}", float(k))
                m.count(f"c{t}")

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for th in threads:
        th.start()
    # a concurrent scraper must never crash or corrupt the windows
    for _ in range(20):
        m.interval_rates()
        m.snapshot()
        time.sleep(0.001)
    for th in threads:
        th.join()
    assert m.counters["x"] == n_threads * per_thread
    for t in range(n_threads):
        assert m.counters[f"c{t}"] == per_thread // 100
    snap = m.snapshot()
    assert snap["x"] == n_threads * per_thread


# -- instrumented lock order (analysis/lockcheck.py runtime mode) -------------

def test_threaded_lock_order_instrumented():
    """The runtime twin of the static lock-order lint: run a real
    concurrent submit/dispatch/poll/drain scenario over
    InstrumentedLock-wrapped admission/device locks — every actual
    acquisition must respect the global admission -> device order, and
    none may be a bare acquire.  (The drain path's combined hold is
    in-order, so it passes here too — the static pass needs its
    quiescence pragma only because it cannot see that the loops are
    joined.)"""
    from agnes_tpu.analysis import lockcheck

    I, V = 4, 8
    svc, d, dispatched = _stubbed_service(I, V)
    tsvc = ThreadedVoteService(svc, idle_wait_s=0.0005)
    state = lockcheck.instrument(tsvc)         # BEFORE start()
    tsvc.start()
    n = I * V
    for k in range(n):
        w = pack_wire_votes([k // V], [k % V], [0], [0], [0], [7])
        _wait(lambda: tsvc.submit(w), what="inbox accepts")
    _wait(lambda: sum(dispatched) == n, what="all votes dispatched")
    tsvc.poll_decisions()                      # caller-thread device lock
    rep = tsvc.drain()
    assert rep["thread_failure"] is None
    assert state.violations == [], state.violations
    assert state.acquisitions > 0


def test_busy_gauges_attribute_inflight_spans_and_clamp():
    """A loop sitting in one long device call is BUSY for every sample
    window the call spans: mid-call samples must read ~1.0 (not 0) and
    the first sample after completion must not publish the whole span
    into one short window (review regression: a 60 s compile under a
    1 s heartbeat read idle 60x then busy_frac = 60)."""

    class _Svc:                           # threads never started
        queue = object()
        metrics = Metrics()

    t = {"now": 100.0}
    tsvc = ThreadedVoteService(_Svc(), clock=lambda: t["now"])
    g = _Svc.metrics.gauges
    tsvc.sample_busy_gauges()             # open the shared window
    # the dispatch loop enters a long call at t=100
    with tsvc._busy_mu:
        tsvc._busy_inflight["dispatch"] = t["now"]
    for k in range(3):                    # heartbeat samples mid-call
        t["now"] += 1.0
        tsvc.sample_busy_gauges()
        assert g[SERVE_DISPATCH_BUSY_FRAC] == pytest.approx(1.0), k
        assert g[SERVE_SUBMIT_BUSY_FRAC] == pytest.approx(0.0), k
    # the call completes at t=104 (4 s busy total)
    t["now"] += 1.0
    with tsvc._busy_mu:
        tsvc._busy_totals["dispatch"] += t["now"] - 100.0
        tsvc._busy_inflight["dispatch"] = None
    t["now"] += 1.0                       # one idle second
    tsvc.sample_busy_gauges()             # window covers [103, 105]
    assert 0.0 <= g[SERVE_DISPATCH_BUSY_FRAC] <= 1.0
    assert g[SERVE_DISPATCH_BUSY_FRAC] == pytest.approx(0.5)
    # lifetime totals stay the probe's whole-run source
    assert tsvc.busy_seconds()["dispatch"] == pytest.approx(4.0)
