"""Verified-vote dedup layer (ISSUE 5): VerifiedCache unit behavior
(insert-after-verify only, rejected batches never cached, decided-
height pruning, LRU byte bound, N-thread conservation) and the serve
plane's split-rung dispatch — admission marks cache hits pre-verified,
the pipeline builds them UNSIGNED while fresh traffic keeps the signed
fused path, and settle() populates the cache only from clean device
verifies.  Dispatch is stubbed throughout (the machinery under test is
host-side), so everything here runs with ZERO XLA compiles (tier-1
cheap; the dispatching differential lives in tests/test_serve_pipeline
.py, slow-marked)."""

import hashlib
import threading

import numpy as np
import pytest

from agnes_tpu.bridge import VoteBatcher
from agnes_tpu.bridge.native_ingest import REC_SIZE, pack_wire_votes
from agnes_tpu.serve import (
    AdmissionQueue,
    ShapeLadder,
    VerifiedCache,
    VoteService,
)
from agnes_tpu.serve.cache import ENTRY_BYTES


def _digests(wire: bytes) -> np.ndarray:
    n = len(wire) // REC_SIZE
    out = np.empty((n, 32), np.uint8)
    for k in range(n):
        out[k] = np.frombuffer(hashlib.sha256(
            wire[k * REC_SIZE:(k + 1) * REC_SIZE]).digest(), np.uint8)
    return out


# -- cache unit ---------------------------------------------------------------

def test_cache_insert_then_hit_and_counters():
    c = VerifiedCache()
    dig = np.arange(3 * 32, dtype=np.uint8).reshape(3, 32)
    assert not c.lookup(dig).any()             # nothing cached yet
    c.insert(dig[:2], np.array([0, 1]), np.array([5, 5]))
    hits = c.lookup(dig)
    np.testing.assert_array_equal(hits, [True, True, False])
    assert len(c) == 2 and c.bytes == 2 * ENTRY_BYTES
    assert c.counters["hits"] == 2 and c.counters["misses"] == 4
    assert c.counters["inserted"] == 2
    assert 0 < c.hit_rate < 1
    snap = c.snapshot()
    assert snap["entries"] == 2 and snap["hit_rate"] == round(2 / 6, 4)


def test_cache_lru_byte_bound_evicts_oldest():
    c = VerifiedCache(max_bytes=4 * ENTRY_BYTES)
    dig = np.random.default_rng(0).integers(
        0, 256, (6, 32)).astype(np.uint8)
    c.insert(dig[:4], np.zeros(4), np.zeros(4))
    c.lookup(dig[:1])                   # refresh entry 0 -> MRU
    c.insert(dig[4:], np.zeros(2), np.zeros(2))   # evicts 2 LRU (1, 2)
    assert len(c) == 4
    assert c.counters["evicted"] == 2
    hits = c.lookup(dig)
    np.testing.assert_array_equal(
        hits, [True, False, False, True, True, True])
    with pytest.raises(ValueError):
        VerifiedCache(max_bytes=1)


def test_cache_prune_decided_heights():
    c = VerifiedCache()
    dig = np.arange(4 * 32, dtype=np.uint8).reshape(4, 32)
    #                    inst    height
    c.insert(dig, np.array([0, 0, 1, 1]), np.array([3, 5, 3, 5]))
    pruned = c.prune_decided(np.array([5, 4]))   # inst0 at h5, inst1 h4
    assert pruned == 2                  # (0, h3) and (1, h3) die
    hits = c.lookup(dig)
    np.testing.assert_array_equal(hits, [False, True, False, True])
    assert c.counters["pruned_height"] == 2


def test_cache_thread_conservation():
    """N threads hammering lookup/insert: counters conserve (every
    lookup row lands in hits or misses), size respects the budget, no
    deadlock."""
    budget_entries = 64
    c = VerifiedCache(max_bytes=budget_entries * ENTRY_BYTES)
    rng = np.random.default_rng(7)
    keyspace = rng.integers(0, 256, (128, 32)).astype(np.uint8)
    lookups = {"n": 0}
    mu = threading.Lock()

    def worker(seed):
        r = np.random.default_rng(seed)
        total = 0
        for _ in range(50):
            idx = r.integers(0, len(keyspace), 8)
            sub = keyspace[idx]
            c.lookup(sub)
            total += len(sub)
            c.insert(sub, idx, np.zeros(len(sub)))
        with mu:
            lookups["n"] += total

    ts = [threading.Thread(target=worker, args=(s,)) for s in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.counters["hits"] + c.counters["misses"] == lookups["n"]
    assert len(c) <= budget_entries
    assert c.bytes <= c.max_bytes


# -- admission integration ----------------------------------------------------

def _wire(inst, value=7, height=0, round_=0, typ=0):
    inst = np.asarray(inst, np.int64)
    n = len(inst)
    return pack_wire_votes(inst, np.arange(n) % 4, np.full(n, height),
                           np.full(n, round_), np.full(n, typ),
                           np.full(n, value))


def test_queue_marks_cache_hits_pre_verified():
    cache = VerifiedCache()
    q = AdmissionQueue(4, capacity=16, cache=cache)
    wire = _wire([0, 1, 2])
    res = q.submit(wire)
    assert res.accepted == 3 and res.pre_verified == 0
    b = q.drain()
    assert b.digest is not None and not b.verified.any()
    np.testing.assert_array_equal(b.digest, _digests(wire))
    # simulate the settle-side insertion, then re-deliver
    cache.insert(b.digest, b.instance, b.height)
    res = q.submit(wire)
    assert res.accepted == 3 and res.pre_verified == 3
    assert q.drain().verified.all()
    # hits + misses == admitted: rejected records are never hashed
    full = AdmissionQueue(4, capacity=2, cache=VerifiedCache())
    r = full.submit(_wire([0, 1, 2]))
    assert r.accepted == 2
    assert (full.cache.counters["hits"]
            + full.cache.counters["misses"]) == 2


def test_queue_without_cache_has_no_digest_column():
    q = AdmissionQueue(4, capacity=16)
    q.submit(_wire([0, 1]))
    b = q.drain()
    assert b.digest is None and not b.verified.any()


# -- split-rung dispatch through the (stubbed) service ------------------------

def _service(I=2, V=4, cache=True, **kw):
    from agnes_tpu.harness.device_driver import DeviceDriver
    from agnes_tpu.harness.fixtures import (
        deterministic_seeds,
        validator_pubkeys,
    )

    d = DeviceDriver(I, V, advance_height=True, defer_collect=True)
    bat = VoteBatcher(I, V, n_slots=4)
    kw.setdefault("ladder", ShapeLadder.plan(I, V, min_rung=16))
    kw.setdefault("capacity", 256)
    kw.setdefault("max_delay_s", 0.0)
    kw.setdefault("window_predictor",
                  lambda: (np.zeros(I, np.int64), np.zeros(I, np.int64)))
    svc = VoteService(d, bat, validator_pubkeys(deterministic_seeds(V)),
                      dedup_cache=VerifiedCache() if cache else None,
                      **kw)
    dispatches = []

    def stub(phases, lanes=None, exts=None, donate=True, tick=None):
        dispatches.append(lanes)
        # mimic the real entry: rejected-lane handle per dispatch
        # (None for unsigned), overridable via d._forced_rejects
        d.last_step_rejects = (None if lanes is None
                               else getattr(d, "_forced_rejects",
                                            np.zeros((), np.int64)))

    d.step_async = stub
    return svc, d, bat, dispatches


def _honest_wire(I, V, typ=0, round_=0):
    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)
    n = I * V
    return pack_wire_votes(inst, val, np.zeros(n), np.full(n, round_),
                           np.full(n, typ), np.full(n, 7))


def test_split_dispatch_duplicates_ride_unsigned_entries():
    """The tentpole behavior, host-side: a fresh tick dispatches
    signed (lanes != None); after settle its digests are cached; the
    SAME bytes re-delivered admit pre-verified and dispatch on the
    unsigned entries (lanes None) — and insertion strictly follows the
    device verify (a pre-settle duplicate still goes signed)."""
    I, V = 2, 4
    n = I * V
    svc, d, bat, dispatches = _service(I, V)
    wire = _honest_wire(I, V)

    assert svc.submit(wire).pre_verified == 0
    svc.pump()                          # stage fresh build
    svc.pump()                          # dispatch: signed
    assert len(dispatches) == 1 and dispatches[0] is not None

    # insert-after-verify ONLY: nothing settled yet, so an immediate
    # duplicate is NOT a cache hit and dispatches signed again
    assert svc.submit(wire).pre_verified == 0
    svc.pump()
    svc.pump()
    assert len(dispatches) == 2 and dispatches[1] is not None

    svc.poll_decisions()                # settle: clean verify -> cache
    assert len(svc.cache) == n
    res = svc.submit(wire)
    assert res.pre_verified == n
    svc.pump()
    svc.pump()
    assert len(dispatches) == 3 and dispatches[2] is None   # unsigned!
    assert svc.pipeline.preverified_builds == 1
    assert svc.pipeline.preverified_votes == n
    assert svc.pipeline.host_fallback_builds == 0
    assert svc.pipeline.offladder_builds == 0

    rep = svc.drain()
    assert rep["dispatched_votes"] == 3 * n     # both streams counted
    assert rep["preverified_votes"] == n
    assert rep["serve_cache"]["hits"] == n
    snap = rep["metrics"]
    assert snap["serve_cache_hits"] == n
    assert snap["serve_cache_misses"] == 2 * n
    assert snap["serve_preverified_votes_dispatched"] == n
    assert snap["serve_cache_bytes"] > 0


def test_rejected_dispatch_never_populates_cache():
    """Poisoning safety: a dispatch whose device verify rejected ANY
    lane caches nothing, so an adversarial replay of a rejected
    signature stays a cache miss (and re-pays the signed path)
    forever."""
    I, V = 2, 4
    svc, d, bat, dispatches = _service(I, V)
    d._forced_rejects = np.asarray(1, np.int64)   # device saw a forgery
    wire = _honest_wire(I, V)
    svc.submit(wire)
    svc.pump()
    svc.pump()
    svc.poll_decisions()                # settle: rejects > 0 -> skip
    assert len(svc.cache) == 0
    assert svc.cache.counters["insert_skipped_rejected"] == 1
    # the replay misses and dispatches signed again
    assert svc.submit(wire).pre_verified == 0
    svc.pump()
    svc.pump()
    assert len(dispatches) == 2
    assert all(ln is not None for ln in dispatches)
    assert svc.pipeline.preverified_builds == 0


def test_held_preverified_votes_build_unsigned_on_reentry():
    """Held future-round votes keep their pre-verified flag through
    the hold-back queue: when the window rotates them in (the same
    path VoteService.drain's held-vote flush takes), they build
    UNSIGNED instead of paying a signed-rung dispatch."""
    I, V = 2, 4
    n = I * V
    box = {"base": 0}
    svc, d, bat, dispatches = _service(
        I, V, window_predictor=lambda: (np.full(I, box["base"],
                                                np.int64),
                                        np.zeros(I, np.int64)))
    wire = _honest_wire(I, V, round_=4)           # outside W=4 at base 0
    # pre-populate the cache as a settled verify of these bytes would
    svc.cache.insert(_digests(wire), np.repeat(np.arange(I), V),
                     np.zeros(n))
    assert svc.submit(wire).pre_verified == n
    svc.pump()
    assert bat.held_votes == n                    # held, still verified
    box["base"] = 4                               # window rotates in
    # a fresh tick triggers the sync that re-enters the held burst:
    # the held (pre-verified) rows build UNSIGNED, the fresh precommit
    # class builds signed — the window-aware split per stream
    svc.submit(_honest_wire(I, V, typ=1, round_=4))
    svc.pump()                                    # re-enter + stage
    svc.pump()                                    # dispatch both
    assert bat.held_votes == 0
    assert len(dispatches) == 2
    assert dispatches[0] is None                  # held burst: unsigned
    assert dispatches[1] is not None              # fresh tick: signed
    assert svc.pipeline.preverified_votes == n


def test_preverified_multi_round_burst_chunks_to_warmed_shapes():
    """A cache-hit burst spanning several rounds densifies to one
    phase per (round, class) — an uncapped unsigned dispatch would
    carry a step-sequence length outside the warmed {2, 3} set (a
    live compile stall).  _stage_preverified chunks to <= 2 vote
    phases per dispatch, entry prepended on each."""
    I, V = 2, 2
    n = I * V
    svc, d, bat, _ = _service(I, V)
    shapes = []
    d.step_async = (lambda phases, lanes=None, exts=None, donate=True,
                    tick=None: shapes.append((len(phases), lanes)))
    inst = np.repeat(np.arange(I), V)
    val = np.tile(np.arange(V), I)
    wire = b"".join(
        pack_wire_votes(inst, val, np.zeros(n), np.full(n, r),
                        np.zeros(n), np.full(n, 7))
        for r in (0, 1, 2))             # 3 rounds, all in the window
    svc.cache.insert(_digests(wire), np.tile(inst, 3), np.zeros(3 * n))
    assert svc.submit(wire).pre_verified == 3 * n
    svc.pump()
    svc.pump()
    # 3 phase groups -> chunks of (2, 1) vote phases, each + entry
    assert [p for p, _ in shapes] == [3, 2]
    assert all(lanes is None for _, lanes in shapes)
    assert svc.pipeline.preverified_builds == 2
    assert svc.pipeline.preverified_votes == 3 * n


def test_dedup_cache_requires_signed_deployment():
    from agnes_tpu.harness.device_driver import DeviceDriver

    d = DeviceDriver(2, 4)
    bat = VoteBatcher(2, 4, n_slots=4)
    with pytest.raises(ValueError):
        VoteService(d, bat, None, dedup_cache=True,
                    ladder=ShapeLadder.plan(2, 4, min_rung=16))


def test_cache_pruned_on_poll_cadence():
    """_settle prunes entries for heights the instances have left
    (their records are stale-height drops on every path)."""
    I, V = 2, 4
    n = I * V
    heights = np.zeros(I, np.int64)
    svc, d, bat, dispatches = _service(
        I, V, window_predictor=lambda: (np.zeros(I, np.int64),
                                        heights.copy()))
    wire = _honest_wire(I, V)
    svc.submit(wire)
    svc.pump()
    svc.pump()
    svc.poll_decisions()
    assert len(svc.cache) == n
    heights[:] = 1                      # instances advance to height 1
    # a fresh height-1 tick syncs the batcher onto the new heights
    svc.submit(pack_wire_votes([0], [0], [1], [0], [0], [7]))
    svc.pump()
    svc.pump()
    svc.poll_decisions()                # poll-cadence prune
    assert svc.cache.counters["pruned_height"] == n
    assert len(svc.cache) == 1          # only the height-1 record left
