"""The TPU-claim holder screen and lease protocol
(scripts/tpu_holders.py) — what keeps bench.py and the armed
hardware-suite runner from killing probes against each other's live
claims.  Pure stdlib; these pin the classification rules and the
lease's mutual-exclusion / expiry semantics (VERDICT r5 weak #4: the
ad-hoc ps tie-break raced two rounds running; the fcntl lease is its
replacement and this file is its proof)."""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from scripts.tpu_holders import (
    TpuLease,
    ancestor_chain,
    is_tpu_invocation,
    tpu_holders,
)


def test_counts_python_entry_points():
    assert is_tpu_invocation("python bench.py")
    assert is_tpu_invocation("/usr/bin/python3 bench.py")
    assert is_tpu_invocation("python -m agnes_tpu.harness.configs 4")
    assert is_tpu_invocation("python scripts/profile_verify.py")


def test_counts_wrappers_that_launch_python():
    assert is_tpu_invocation("timeout 600 python bench.py")
    assert is_tpu_invocation("sh -c 'python bench.py --x'")
    assert is_tpu_invocation("bash -c python\\ bench.py")


def test_counts_marked_probes_in_flight():
    # the cooperative probe marker (PROBE_SNIPPET): an in-flight probe
    # must be visible to the other side's holder check so nobody
    # starts a second client against its claim
    from scripts.tpu_holders import PROBE_SNIPPET

    assert is_tpu_invocation(f"python -c {PROBE_SNIPPET}")
    assert is_tpu_invocation(
        f'timeout 120 python -c "{PROBE_SNIPPET}"')


def test_rejects_non_runs():
    # editors/pagers/greps mentioning the names are not claims
    assert not is_tpu_invocation("vim bench.py")
    assert not is_tpu_invocation("tail -f /tmp/hw/bench.py.log")
    assert not is_tpu_invocation("grep -c votes bench.py")
    # wrapper without python is not a claim either
    assert not is_tpu_invocation("timeout 600 grep -c votes bench.py")
    # the suite RUNNER shell itself must not count: while probing a
    # dead tunnel it holds nothing (its stages match on their own)
    assert not is_tpu_invocation("bash scripts/run_hw_suite.sh /tmp/x")


def test_rejects_agent_wrapper_argv_novels():
    # driver/agent shells embed kilobytes of prompt text in argv that
    # MENTIONS bench.py and python; they must never count as holders
    args = ("bash -c 'set -o pipefail; claude -p --append-system-prompt "
            + "x" * 2000 + " bench.py python'")
    assert not is_tpu_invocation(args)


def test_self_and_ancestors_excluded():
    procs = {1: (0, 99, "init"),
             10: (1, 50, "bash scripts/run_hw_suite.sh /tmp/x"),
             20: (10, 40, "python bench.py"),
             30: (20, 30, "python -c import jax"),
             40: (1, 20, "python bench.py")}
    # from the perspective of pid 30 (a probe child of bench 20):
    # its own bench ancestor is excluded, the unrelated bench is not
    chain = ancestor_chain(procs, 30)
    assert chain == {30, 20, 10, 1}
    rivals = [p for p, (pp, age, a) in procs.items()
              if p not in chain and is_tpu_invocation(a)]
    assert rivals == [40]


def test_live_call_runs_clean():
    # in the test environment no rival TPU entry points should be
    # running; mostly asserts the ps plumbing does not throw
    out = tpu_holders()
    assert isinstance(out, list)
    for p, age, args in out:
        assert isinstance(p, int) and isinstance(args, str)


# --- the lease protocol ------------------------------------------------------


def _spawn_holder():
    """A live child process to lease TO — a real pid with real /proc
    start ticks, killable on demand (simulating a rival bench)."""
    return subprocess.Popen([sys.executable, "-c",
                             "import time; time.sleep(600)"])


def test_lease_acquire_release_cycle(tmp_path):
    path = str(tmp_path / "tpu.lease")
    lease = TpuLease(path=path)
    assert lease.holder() is None
    assert lease.acquire(note="me")
    rec = lease.holder()
    assert rec is not None and rec["pid"] == os.getpid()
    assert rec["note"] == "me"
    assert lease.acquire()          # re-acquire by the holder extends
    assert lease.refresh()
    assert lease.release()
    assert lease.holder() is None
    assert not lease.release()      # idempotent: nothing left to drop


def test_lease_excludes_live_rival(tmp_path):
    path = str(tmp_path / "tpu.lease")
    rival = _spawn_holder()
    try:
        theirs = TpuLease(path=path, pid=rival.pid)
        assert theirs.acquire(note="rival bench")
        mine = TpuLease(path=path)
        assert not mine.acquire()           # held by a live process
        assert not mine.refresh()           # and I can't extend theirs
        assert not mine.release()           # nor drop theirs
        assert mine.holder()["pid"] == rival.pid
    finally:
        rival.kill()
        rival.wait()


def test_lease_dead_holder_taken_over_immediately(tmp_path):
    """Crash safety: a holder that died without release() is detected
    via pid+start-ticks and overwritten at once — no ttl wait."""
    path = str(tmp_path / "tpu.lease")
    rival = _spawn_holder()
    theirs = TpuLease(path=path, pid=rival.pid)
    assert theirs.acquire(ttl_s=3600)
    rival.kill()
    rival.wait()
    mine = TpuLease(path=path)
    assert mine.holder() is None            # dead lease reads as free
    assert mine.acquire()
    assert mine.holder()["pid"] == os.getpid()
    mine.release()


def test_lease_ttl_expiry(tmp_path):
    """The wedged-but-alive case: a live holder whose ttl lapsed is
    expirable by anyone."""
    path = str(tmp_path / "tpu.lease")
    rival = _spawn_holder()
    try:
        theirs = TpuLease(path=path, pid=rival.pid)
        assert theirs.acquire(ttl_s=0.2)
        mine = TpuLease(path=path)
        assert not mine.acquire()
        time.sleep(0.3)
        assert mine.acquire()               # expired -> free to take
        mine.release()
    finally:
        rival.kill()
        rival.wait()


def test_lease_survives_torn_and_garbage_files(tmp_path):
    path = str(tmp_path / "tpu.lease")
    for garbage in (b"", b"not json", b'{"pid": "x"}',
                    b'{"pid": 1}'):       # missing expires_at
        with open(path, "wb") as f:
            f.write(garbage)
        lease = TpuLease(path=path)
        assert lease.holder() is None
        assert lease.acquire()
        lease.release()


_STRESS_CHILD = r"""
import os, sys, time
sys.path.insert(0, sys.argv[4])
from scripts.tpu_holders import TpuLease

path, crit, dur = sys.argv[1], sys.argv[2], float(sys.argv[3])
lease = TpuLease(path=path)
wins = 0
end = time.monotonic() + dur
while time.monotonic() < end:
    if lease.acquire(ttl_s=30, note="stress"):
        # inside the critical section: record entry, dwell, verify the
        # lease is STILL mine (a second winner would have overwritten
        # it), record exit.  O_APPEND single-line writes are atomic.
        with open(crit, "a") as f:
            f.write(f"enter {os.getpid()}\n")
        time.sleep(0.005)
        rec = lease.holder()
        ok = rec is not None and rec["pid"] == os.getpid()
        with open(crit, "a") as f:
            f.write(f"exit {os.getpid()} {int(ok)}\n")
        wins += 1
        lease.release()
        time.sleep(0.001)
    else:
        time.sleep(0.002)
print(wins)
"""


def test_lease_multiprocess_stress(tmp_path):
    """The race the ad-hoc tie-break kept losing, made a test: N real
    processes hammer acquire/release on one lease file for ~2s.  Mutual
    exclusion holds iff the enter/exit trace is strictly alternating
    (every enter is closed by the SAME pid before the next enter) and
    every holder still owned the lease mid-section."""
    path = str(tmp_path / "tpu.lease")
    crit = str(tmp_path / "crit.log")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [subprocess.Popen(
        [sys.executable, "-c", _STRESS_CHILD, path, crit, "2.0", repo],
        stdout=subprocess.PIPE, text=True) for _ in range(6)]
    wins = []
    for p in procs:
        out, _ = p.communicate(timeout=60)
        assert p.returncode == 0
        wins.append(int(out.strip()))
    assert sum(wins) > 0                    # somebody got work done
    assert sum(1 for w in wins if w) >= 2   # and not just one process
    inside = None
    entries = 0
    with open(crit) as f:
        for line in f:
            parts = line.split()
            if parts[0] == "enter":
                assert inside is None, \
                    f"pid {parts[1]} entered while {inside} was inside"
                inside = parts[1]
                entries += 1
            else:
                assert parts[0] == "exit" and inside == parts[1]
                assert parts[2] == "1", \
                    f"pid {parts[1]} lost the lease mid-section"
                inside = None
    assert inside is None
    assert entries == sum(wins)


def test_lease_cli_roundtrip(tmp_path):
    """The shell entry points run_hw_suite.sh drives: lease-acquire /
    lease-holder / lease-release against an explicit --pid."""
    path = str(tmp_path / "tpu.lease")
    env = dict(os.environ, AGNES_TPU_LEASE_PATH=path)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "scripts", "tpu_holders.py")

    def cli(*args):
        return subprocess.run([sys.executable, script, *args],
                              env=env, capture_output=True, text=True,
                              timeout=30)

    rival = _spawn_holder()
    try:
        assert cli("lease-holder").returncode == 0        # free
        assert cli("lease-acquire", "--pid", str(rival.pid),
                   "--note", "hw suite").returncode == 0
        r = cli("lease-holder")
        assert r.returncode == 1                          # held
        assert json.loads(r.stdout)["pid"] == rival.pid
        # a different pid cannot steal it
        assert cli("lease-acquire", "--pid",
                   str(os.getpid())).returncode == 1
        assert cli("lease-refresh", "--pid",
                   str(rival.pid)).returncode == 0
        assert cli("lease-release", "--pid",
                   str(rival.pid)).returncode == 0
        assert cli("lease-holder").returncode == 0        # free again
    finally:
        rival.kill()
        rival.wait()
