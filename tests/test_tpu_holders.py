"""The TPU-claim holder screen (scripts/tpu_holders.py) — the
protocol that keeps bench.py and the armed hardware-suite runner from
killing probes against each other's live claims.  Pure stdlib; these
pin the classification rules the two sides rely on."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from scripts.tpu_holders import (
    ancestor_chain,
    is_tpu_invocation,
    tpu_holders,
)


def test_counts_python_entry_points():
    assert is_tpu_invocation("python bench.py")
    assert is_tpu_invocation("/usr/bin/python3 bench.py")
    assert is_tpu_invocation("python -m agnes_tpu.harness.configs 4")
    assert is_tpu_invocation("python scripts/profile_verify.py")


def test_counts_wrappers_that_launch_python():
    assert is_tpu_invocation("timeout 600 python bench.py")
    assert is_tpu_invocation("sh -c 'python bench.py --x'")
    assert is_tpu_invocation("bash -c python\\ bench.py")


def test_counts_marked_probes_in_flight():
    # the cooperative probe marker (PROBE_SNIPPET): an in-flight probe
    # must be visible to the other side's holder check so nobody
    # starts a second client against its claim
    from scripts.tpu_holders import PROBE_SNIPPET

    assert is_tpu_invocation(f"python -c {PROBE_SNIPPET}")
    assert is_tpu_invocation(
        f'timeout 120 python -c "{PROBE_SNIPPET}"')


def test_rejects_non_runs():
    # editors/pagers/greps mentioning the names are not claims
    assert not is_tpu_invocation("vim bench.py")
    assert not is_tpu_invocation("tail -f /tmp/hw/bench.py.log")
    assert not is_tpu_invocation("grep -c votes bench.py")
    # wrapper without python is not a claim either
    assert not is_tpu_invocation("timeout 600 grep -c votes bench.py")
    # the suite RUNNER shell itself must not count: while probing a
    # dead tunnel it holds nothing (its stages match on their own)
    assert not is_tpu_invocation("bash scripts/run_hw_suite.sh /tmp/x")


def test_rejects_agent_wrapper_argv_novels():
    # driver/agent shells embed kilobytes of prompt text in argv that
    # MENTIONS bench.py and python; they must never count as holders
    args = ("bash -c 'set -o pipefail; claude -p --append-system-prompt "
            + "x" * 2000 + " bench.py python'")
    assert not is_tpu_invocation(args)


def test_self_and_ancestors_excluded():
    procs = {1: (0, 99, "init"),
             10: (1, 50, "bash scripts/run_hw_suite.sh /tmp/x"),
             20: (10, 40, "python bench.py"),
             30: (20, 30, "python -c import jax"),
             40: (1, 20, "python bench.py")}
    # from the perspective of pid 30 (a probe child of bench 20):
    # its own bench ancestor is excluded, the unrelated bench is not
    chain = ancestor_chain(procs, 30)
    assert chain == {30, 20, 10, 1}
    rivals = [p for p, (pp, age, a) in procs.items()
              if p not in chain and is_tpu_invocation(a)]
    assert rivals == [40]


def test_live_call_runs_clean():
    # in the test environment no rival TPU entry points should be
    # running; mostly asserts the ps plumbing does not throw
    out = tpu_holders()
    assert isinstance(out, list)
    for p, age, args in out:
        assert isinstance(p, int) and isinstance(args, str)
