"""Tally tests: reference parity anchor + the two documented fixes
(per-value buckets, per-validator dedup / equivocation)."""

from agnes_tpu.core.round_votes import (
    RoundVotes,
    Thresh,
    is_one_third,
    is_quorum,
)
from agnes_tpu.types import Vote

VAL = 7
OTHER = 9


def test_add_votes_parity():
    """Parity anchor: round_votes.rs:107-132.  Identity-free votes are not
    deduplicated, exactly like the reference (which double-counts the
    repeated vote; only the threshold outcome is observable)."""
    total = 4
    rv = RoundVotes(height=1, round=0, total=total)
    weight = 1

    vote = Vote.new_prevote(0, VAL)
    assert rv.add_vote(vote, weight) == Thresh.init()
    # add it again — reference accumulates weight but threshold unchanged
    assert rv.add_vote(vote, weight) == Thresh.init()
    # a nil vote: combined weight 3 of 4 → 9 > 8 → Any
    assert rv.add_vote(Vote.new_prevote(0, None), weight) == Thresh.any()
    # another value vote: value weight 3 → Value
    assert rv.add_vote(vote, weight) == Thresh.for_value(VAL)


def test_quorum_predicate():
    """3v > 2t, strict (round_votes.rs:31-33)."""
    assert not is_quorum(2, 3)
    assert is_quorum(3, 4)
    assert not is_quorum(66, 100)
    assert is_quorum(67, 100)
    assert not is_one_third(1, 3)
    assert is_one_third(2, 4)


def test_nil_quorum():
    rv = RoundVotes(height=1, round=0, total=3)
    assert rv.add_vote(Vote.new_prevote(0, None), 1) == Thresh.init()
    assert rv.add_vote(Vote.new_prevote(0, None), 1) == Thresh.init()
    assert rv.add_vote(Vote.new_prevote(0, None), 1) == Thresh.nil()


def test_prevotes_and_precommits_tallied_separately():
    """round_votes.rs:92-97 dispatches on vote type."""
    rv = RoundVotes(height=1, round=0, total=3)
    rv.add_vote(Vote.new_prevote(0, VAL), 2)
    assert rv.add_vote(Vote.new_precommit(0, VAL), 1) == Thresh.init()
    assert rv.add_vote(Vote.new_precommit(0, VAL), 2) == Thresh.for_value(VAL)


def test_multi_value_buckets_not_conflated():
    """Fix 1 (SURVEY.md §2.3): votes for different values must not pool
    into one bucket.  4 of 6 split 2/2 across values → Init, not Value."""
    rv = RoundVotes(height=1, round=0, total=6)
    rv.add_vote(Vote.new_prevote(0, VAL), 2)
    t = rv.add_vote(Vote.new_prevote(0, OTHER), 2)
    assert t == Thresh.init()  # no single value has quorum
    # one more for VAL (4/6 seen) → 3*4 > 2*6 false... add nil to reach Any
    t = rv.add_vote(Vote.new_prevote(0, None), 1)
    assert t == Thresh.any()  # 5 of 6 seen, mixed
    t = rv.add_vote(Vote.new_prevote(0, VAL), 3)
    assert t == Thresh.for_value(VAL)  # VAL bucket now 5 of 6


def test_validator_dedup():
    """Fix 2: a validator's weight counts once per (round, type)."""
    rv = RoundVotes(height=1, round=0, total=3)
    v = Vote.new_prevote(0, VAL, validator=0)
    assert rv.add_vote(v, 1) == Thresh.init()
    assert rv.add_vote(v, 1) == Thresh.init()  # duplicate ignored
    assert rv.add_vote(v, 1) == Thresh.init()  # still 1 of 3
    assert rv.prevotes.value_weight(VAL) == 1
    rv.add_vote(Vote.new_prevote(0, VAL, validator=1), 1)
    assert rv.add_vote(Vote.new_prevote(0, VAL, validator=2), 1) \
        == Thresh.for_value(VAL)


def test_equivocation_detected_first_vote_counts():
    """Conflicting vote = evidence; the first vote keeps counting."""
    rv = RoundVotes(height=1, round=0, total=3)
    rv.add_vote(Vote.new_prevote(0, VAL, validator=0), 1)
    rv.add_vote(Vote.new_prevote(0, OTHER, validator=0), 1)
    assert len(rv.equivocations) == 1
    ev = rv.equivocations[0]
    assert ev.validator == 0
    assert ev.first_value == VAL and ev.second_value == OTHER
    assert rv.prevotes.value_weight(VAL) == 1
    assert rv.prevotes.value_weight(OTHER) == 0
    # same validator, other vote TYPE is not equivocation
    rv.add_vote(Vote.new_precommit(0, VAL, validator=0), 1)
    assert len(rv.equivocations) == 1


def test_skip_weight_counts_distinct_voters():
    rv = RoundVotes(height=1, round=2, total=4)
    rv.add_vote(Vote.new_prevote(2, VAL, validator=0), 1)
    rv.add_vote(Vote.new_precommit(2, VAL, validator=0), 1)
    assert rv.skip_weight() == 1  # same voter, both types
    rv.add_vote(Vote.new_prevote(2, None, validator=1), 1)
    assert rv.skip_weight() == 2


def test_equivocation_evidence_not_duplicated_on_redelivery():
    """Redelivered conflicting votes must not grow the evidence list."""
    rv = RoundVotes(height=1, round=0, total=3)
    rv.add_vote(Vote.new_prevote(0, VAL, validator=0), 1)
    for _ in range(5):
        rv.add_vote(Vote.new_prevote(0, OTHER, validator=0), 1)
    assert len(rv.equivocations) == 1


def test_skip_weight_mixed_identity_and_anon():
    """Identity-free weight still counts toward RoundSkip when identified
    votes are present in the same round."""
    rv = RoundVotes(height=1, round=2, total=6)
    rv.add_vote(Vote.new_prevote(2, VAL, validator=0), 1)
    rv.add_vote(Vote.new_prevote(2, VAL), 2)  # anonymous
    assert rv.skip_weight() == 3
