"""Bridge: value interning, slot maps, and the vote-batch ingestion ABI."""

import numpy as np
import pytest

from agnes_tpu.bridge import SlotMap, ValueTable, VoteBatcher, WireVote
from agnes_tpu.core import native
from agnes_tpu.crypto.encoding import vote_signing_bytes
from agnes_tpu.harness.device_driver import DeviceDriver
from agnes_tpu.types import VoteType


def test_value_table_roundtrip_and_determinism():
    t1, t2 = ValueTable(), ValueTable()
    payloads = [b"block-7", b"block-8", b"x" * 100]
    ids1 = [t1.intern(p) for p in payloads]
    ids2 = [t2.intern(p) for p in payloads]
    assert ids1 == ids2                      # content-derived: hosts agree
    assert len(set(ids1)) == 3
    for vid, p in zip(ids1, payloads):
        assert t1.payload(vid) == p
    assert t1.intern(b"block-7") == ids1[0]  # idempotent
    assert all(0 <= v < 2**31 for v in ids1)


def test_slot_map_allocation_and_overflow():
    sm = SlotMap(n_instances=2, n_slots=2)
    assert sm.slot_for(0, 111) == 0
    assert sm.slot_for(0, 222) == 1
    assert sm.slot_for(0, 111) == 0          # stable
    assert sm.slot_for(0, 333) is None       # overflow -> host fallback
    assert sm.overflowed == 1
    assert sm.slot_for(1, 333) == 0          # instances independent
    assert sm.value_for(0, 1) == 222
    sm.reset_instance(0)
    assert sm.slot_for(0, 333) == 0


def _signed_vote(seeds, inst, val_idx, height, rnd, typ, value):
    sig = native.sign(seeds[val_idx],
                      vote_signing_bytes(height, rnd, int(typ), value))
    return WireVote(instance=inst, validator=val_idx, height=height,
                    round=rnd, typ=typ, value=value, signature=sig)


def test_batcher_end_to_end_signed_consensus():
    """Signed wire votes -> verified dense phases -> device decision."""
    I, V = 2, 4
    seeds = [bytes([i + 1]) * 32 for i in range(V)]
    pubkeys = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                        for s in seeds])
    value_id = ValueTable().intern(b"the-block")

    b = VoteBatcher(I, V, n_slots=4)
    for inst in range(I):
        for v in range(V):
            b.add(_signed_vote(seeds, inst, v, 0, 0, VoteType.PREVOTE,
                               value_id))
    # one forged prevote (wrong key signs validator 3's vote)
    forged_sig = native.sign(b"\xBB" * 32,
                             vote_signing_bytes(0, 0, 0, value_id))
    b.add(WireVote(instance=0, validator=3, height=0, round=0,
                   typ=VoteType.PREVOTE, value=value_id,
                   signature=forged_sig))
    # and a malformed one
    b.add(WireVote(instance=0, validator=99, height=0, round=0,
                   typ=VoteType.PREVOTE, value=value_id, signature=None))

    phases = b.build_phases(pubkeys)
    assert b.rejected_signature == 1
    assert b.rejected_malformed == 1
    # layering: the forged vote was dropped, so one layer only
    assert len(phases) == 1
    phase, n = phases[0]
    assert n == I * V

    d = DeviceDriver(I, V)
    d.step()                       # entry + self-proposal
    d.step(phase=phase)            # everyone prevotes the value
    for inst in range(I):
        for v in range(V):
            b.add(_signed_vote(seeds, inst, v, 0, 0, VoteType.PRECOMMIT,
                               value_id))
    (pc_phase, n2), = b.build_phases(pubkeys)
    assert n2 == I * V
    d.step(phase=pc_phase)
    assert d.all_decided()
    # decision slot decodes back to the interned value id
    slot = int(d.stats.decision_value[0])
    assert b.decode_slot(0, slot) == value_id


def test_batcher_layers_equivocating_votes():
    """Two conflicting votes from one validator land in two layers and
    the device flags the equivocation."""
    I, V = 1, 4
    b = VoteBatcher(I, V, n_slots=4)
    for v in range(V):
        b.add(WireVote(0, v, 0, 0, VoteType.PREVOTE, value=100 + v % 2))
    b.add(WireVote(0, 0, 0, 0, VoteType.PREVOTE, value=999))  # conflict
    phases = b.build_phases()      # unverified path (no pubkeys)
    assert len(phases) == 2        # base layer + conflict layer
    d = DeviceDriver(I, V)
    d.step()
    for phase, _ in phases:
        d.step(phase=phase)
    assert int(d.equivocators_detected()[0]) == 1


def test_batcher_dedupes_exact_duplicates():
    """Gossip redelivery: 10 copies of one vote -> one layer, one slot."""
    b = VoteBatcher(1, 4, n_slots=4)
    for _ in range(10):
        b.add(WireVote(0, 2, 0, 0, VoteType.PREVOTE, value=7))
    b.add(WireVote(0, 1, 0, 0, VoteType.PREVOTE, value=7))
    phases = b.build_phases()
    assert len(phases) == 1
    _, n = phases[0]
    assert n == 2  # two distinct (validator) votes


def test_batcher_drops_cross_height_votes():
    b = VoteBatcher(2, 4, n_slots=4,
                    heights=np.asarray([5, 6], np.int64))
    b.add(WireVote(0, 1, 5, 0, VoteType.PREVOTE, 1))   # right height
    b.add(WireVote(1, 1, 5, 0, VoteType.PREVOTE, 1))   # wrong height
    phases = b.build_phases()
    assert b.dropped_stale_height == 1
    assert sum(n for _, n in phases) == 1


def test_batcher_rejects_wrong_length_signature():
    """A signature of any length other than 64 must be counted as
    malformed, not crash the packer (ADVICE r1: one hostile vote could
    DoS the whole ingestion tick)."""
    seeds = [bytes([i + 1]) * 32 for i in range(4)]
    pub = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                    for s in seeds])
    b = VoteBatcher(1, 4, n_slots=4)
    b.add(WireVote(0, 0, 0, 0, VoteType.PREVOTE, 1, signature=b"\x01" * 10))
    b.add(WireVote(0, 1, 0, 0, VoteType.PREVOTE, 1, signature=b"\x01" * 65))
    phases = b.build_phases(pubkeys=pub)
    assert b.rejected_malformed == 2
    assert phases == []


def test_batcher_holds_back_future_rounds_until_rotation():
    """Votes beyond the device window [base, base+W) are held and
    re-emitted after sync_device reports the rotated window (VERDICT r2
    missing #1: no silent drop)."""
    I, V = 1, 4
    b = VoteBatcher(I, V, n_slots=4, n_rounds=4)
    for v in range(V):
        b.add(WireVote(0, v, 0, 10, VoteType.PREVOTE, value=5))
    assert b.build_phases() == []          # round 10 outside [0, 4)
    # device rotates its window to base 9
    b.sync_device(base_round=np.asarray([9]), heights=np.asarray([0]))
    phases = b.build_phases()
    assert len(phases) == 1
    phase, n = phases[0]
    assert n == V and int(phase.round[0]) == 10


def test_batcher_host_tallies_rotated_out_rounds():
    """A late +2/3 precommit-value quorum for a round below the window
    base surfaces as a host event (commit-from-any-round,
    state_machine.rs:211)."""
    I, V = 1, 4
    b = VoteBatcher(I, V, n_slots=4, n_rounds=4)
    b.sync_device(base_round=np.asarray([7]), heights=np.asarray([0]))
    for v in range(3):                     # 3 of 4 = +2/3
        b.add(WireVote(0, v, 0, 2, VoteType.PRECOMMIT, value=42))
    assert b.build_phases() == []          # nothing reaches the device
    assert b.drain_host_events() == [(0, 0, 2, 42)]
    assert b.drain_host_events() == []     # drained


def test_host_tally_never_mixes_heights():
    """Code-review r3 finding: the host fallback must key by height —
    2 height-0 precommits + 1 height-1 precommit for the same (round,
    value) must NOT form a quorum."""
    I, V = 1, 4
    b = VoteBatcher(I, V, n_slots=4, n_rounds=4)
    b.sync_device(base_round=np.asarray([7]), heights=np.asarray([0]))
    for v in range(2):                     # 2 of 4: no quorum
        b.add(WireVote(0, v, 0, 2, VoteType.PRECOMMIT, value=42))
    b.build_phases()
    assert b.drain_host_events() == []
    # instance advances to height 1; its height-0 tallies are dropped
    b.sync_device(base_round=np.asarray([0]), heights=np.asarray([1]))
    b.sync_device(base_round=np.asarray([7]), heights=np.asarray([1]))
    b.add(WireVote(0, 2, 1, 2, VoteType.PRECOMMIT, value=42))
    b.build_phases()
    assert b.drain_host_events() == []     # 1 vote at height 1: no quorum


def test_unsigned_votes_fail_when_verification_requested():
    """Code-review r3 finding: an all-unsigned tick must not bypass
    signature verification when pubkeys are supplied."""
    seeds = [bytes([i + 1]) * 32 for i in range(4)]
    pub = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                    for s in seeds])
    b = VoteBatcher(1, 4, n_slots=4)
    for v in range(4):
        b.add(WireVote(0, v, 0, 0, VoteType.PREVOTE, 7))  # no signature
    assert b.build_phases(pubkeys=pub) == []
    assert b.rejected_signature == 4


def test_invalid_typ_is_malformed():
    b = VoteBatcher(1, 4, n_slots=4)
    b.add_arrays([0], [1], [0], [0], [2], [7])     # typ 2: invalid
    b.add_arrays([0], [2], [0], [0], [-1], [7])    # typ -1: invalid
    assert b.build_phases() == []
    assert b.rejected_malformed == 2


def test_held_votes_are_not_relogged_each_tick():
    """Code-review r3 finding: far-future votes must not be re-verified
    or duplicated into the evidence log every tick they sit held."""
    b = VoteBatcher(1, 4, n_slots=4, n_rounds=4)
    b.add(WireVote(0, 1, 0, 50, VoteType.PREVOTE, 5))
    for _ in range(3):
        assert b.build_phases() == []
        b.sync_device(base_round=np.asarray([0]), heights=np.asarray([0]))
    assert len(b._log) == 0                # held votes never logged
    b.sync_device(base_round=np.asarray([49]), heights=np.asarray([0]))
    phases = b.build_phases()
    assert len(phases) == 1 and phases[0][1] == 1
    assert len(b._log) == 1                # logged exactly once


def test_slot_overflow_spills_to_host_tally():
    """Code-review r3 finding: values beyond the slot budget must reach
    the host tally (quorums on them still commit), not vanish."""
    I, V = 1, 4
    b = VoteBatcher(I, V, n_slots=2, n_rounds=4)
    # values 1,2 fill the slots; 3 of 4 validators then precommit a
    # third value -> untrackable on device, quorum must surface on host
    b.add(WireVote(0, 0, 0, 0, VoteType.PREVOTE, 1))
    b.add(WireVote(0, 1, 0, 0, VoteType.PREVOTE, 2))
    for v in range(3):
        b.add(WireVote(0, v, 0, 0, VoteType.PRECOMMIT, 30303))
    phases = b.build_phases()
    assert sum(n for _, n in phases) == 2  # the two tracked prevotes
    assert b.overflow_votes == 3
    assert b.drain_host_events() == [(0, 0, 0, 30303)]


def test_batcher_signed_evidence_reconstructs_double_sign():
    """Device equiv flag -> the two conflicting SIGNED votes (VERDICT
    r2 weak #7: device evidence must be slashable)."""
    I, V = 1, 4
    seeds = [bytes([i + 1]) * 32 for i in range(V)]
    pub = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                    for s in seeds])
    b = VoteBatcher(I, V, n_slots=4)
    for v in range(V):
        b.add(_signed_vote(seeds, 0, v, 0, 0, VoteType.PREVOTE, 7))
    # validator 2 double-signs a conflicting value
    b.add(_signed_vote(seeds, 0, 2, 0, 0, VoteType.PREVOTE, 9))
    phases = b.build_phases(pub)
    assert len(phases) == 2                # conflict lands in layer 1
    d = DeviceDriver(I, V)
    d.step()
    for phase, _ in phases:
        d.step(phase=phase)
    flagged = np.nonzero(np.asarray(d.tally.equiv)[0])[0]
    assert list(flagged) == [2]
    ev = b.signed_evidence(0, 2)
    assert ev is not None
    first, second = ev
    assert {first.value, second.value} == {7, 9}
    assert first.round == second.round == 0
    assert first.typ == second.typ == VoteType.PREVOTE
    # the signatures really are that validator's, over those values —
    # provable to any third party with only the pubkey
    from agnes_tpu.crypto import ed25519_ref as ref
    for w in (first, second):
        msg = vote_signing_bytes(w.height, w.round, int(w.typ), w.value)
        assert ref.verify(native.pubkey(seeds[2]), msg, w.signature)
    # an honest validator yields no evidence
    assert b.signed_evidence(0, 1) is None


def test_batcher_add_arrays_bulk_path():
    """The array-native fast path produces the same phases as add()."""
    I, V = 2, 4
    b1 = VoteBatcher(I, V, n_slots=4)
    b2 = VoteBatcher(I, V, n_slots=4)
    insts, vals, rnds, typs, vids = [], [], [], [], []
    for inst in range(I):
        for v in range(V):
            b1.add(WireVote(inst, v, 0, 1, VoteType.PREVOTE, value=33))
            insts.append(inst)
            vals.append(v)
            rnds.append(1)
            typs.append(int(VoteType.PREVOTE))
            vids.append(33)
    b2.add_arrays(insts, vals, np.zeros(len(insts)), rnds, typs, vids)
    p1 = b1.build_phases()
    p2 = b2.build_phases()
    assert len(p1) == len(p2) == 1
    (ph1, n1), (ph2, n2) = p1[0], p2[0]
    assert n1 == n2 == I * V
    assert np.array_equal(np.asarray(ph1.slots), np.asarray(ph2.slots))
    assert np.array_equal(np.asarray(ph1.mask), np.asarray(ph2.mask))


def test_batcher_two_class_build_matches_per_class_builds():
    """Both vote classes of a round batched into ONE build (the r4
    pipeline shape: a single 2n-lane verify) must emit the same phases,
    in the same (prevote, precommit) order, as two per-class builds —
    whether the combined batch takes the no-sort fast path (honest
    cells) or the general lexsort path (duplicates present)."""
    I, V = 2, 4
    for dup in (False, True):
        b1 = VoteBatcher(I, V, n_slots=4)
        for typ in (VoteType.PREVOTE, VoteType.PRECOMMIT):
            for inst in range(I):
                for v in range(V):
                    b1.add(WireVote(inst, v, 0, 0, typ, value=7))
        if dup:   # a replayed lane forces the general path
            b1.add(WireVote(0, 0, 0, 0, VoteType.PREVOTE, value=7))
        combined = b1.build_phases()
        # the reference point is per-class adds built separately:
        b3 = VoteBatcher(I, V, n_slots=4)
        per_class = []
        for typ in (VoteType.PREVOTE, VoteType.PRECOMMIT):
            for inst in range(I):
                for v in range(V):
                    b3.add(WireVote(inst, v, 0, 0, typ, value=7))
            if dup and typ == VoteType.PREVOTE:
                b3.add(WireVote(0, 0, 0, 0, VoteType.PREVOTE, value=7))
            per_class += b3.build_phases()
        assert len(combined) == len(per_class) == 2
        for (pa, na), (pb, nb) in zip(combined, per_class):
            assert na == nb
            assert np.array_equal(np.asarray(pa.typ), np.asarray(pb.typ))
            assert np.array_equal(np.asarray(pa.slots),
                                  np.asarray(pb.slots))
            assert np.array_equal(np.asarray(pa.mask), np.asarray(pb.mask))


def test_vote_messages_np_matches_scalar_encoding():
    from agnes_tpu.bridge.ingest import vote_messages_np
    cases = [(0, 0, 0, 7), (3, 9, 1, None), (2**40, 2**20, 1, 2**30)]
    h = np.asarray([c[0] for c in cases], np.int64)
    r = np.asarray([c[1] for c in cases], np.int64)
    t = np.asarray([c[2] for c in cases], np.int64)
    v = np.asarray([-1 if c[3] is None else c[3] for c in cases], np.int64)
    got = vote_messages_np(h, r, t, v)
    for i, (hh, rr, tt, vv) in enumerate(cases):
        assert got[i].tobytes() == vote_signing_bytes(hh, rr, tt, vv)


def test_native_verify_rejects_wrong_length_inputs():
    """ADVICE r1: short pk/sig must return a clean False from the C ABI
    wrapper, never reach the unconditional 32/64-byte reads in C++."""
    seed = b"\x07" * 32
    pk = native.pubkey(seed)
    msg = b"hello"
    sig = native.sign(seed, msg)
    assert native.verify(pk, msg, sig)
    assert not native.verify(pk[:16], msg, sig)
    assert not native.verify(pk, msg, sig[:10])
    assert not native.verify(pk + b"\x00", msg, sig)
    assert not native.verify(pk, msg, sig + b"\x00")
    # batch path: misaligned entries report False without disturbing
    # well-formed neighbours
    res = native.verify_batch([pk, pk[:5], pk], [msg, msg, msg],
                              [sig, sig, sig[:5]])
    assert res == [True, False, False]

def test_batcher_msm_mode_matches_lane_mode():
    """verify_mode='msm' (batch random-linear-combination fast path
    with per-lane bisection fallback) must produce identical phases
    and rejection counters to the per-lane mode.  The batch is sized
    above msm_leaf so the MSM path actually executes: the forged lane
    fails the combined equation and bisection settles the halves on
    the per-lane verifier."""
    I, V = 8, 4
    seeds = [bytes([i + 1]) * 32 for i in range(V)]
    pubkeys = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                        for s in seeds])

    def run(mode):
        b = VoteBatcher(I, V, n_slots=4, verify_mode=mode, msm_leaf=33)
        for inst in range(I):
            for v in range(V):
                b.add(_signed_vote(seeds, inst, v, 0, 0,
                                   VoteType.PREVOTE, 7))
        forged = native.sign(b"\xBB" * 32,
                             vote_signing_bytes(0, 0, 0, 7))
        b.add(WireVote(instance=1, validator=3, height=0, round=0,
                       typ=VoteType.PREVOTE, value=9, signature=forged))
        phases = b.build_phases(pubkeys)
        return phases, b.rejected_signature

    (ph_l, rej_l), (ph_m, rej_m) = run("lanes"), run("msm")
    assert rej_l == 1 == rej_m
    assert len(ph_l) == len(ph_m)
    for (pa, na), (pb, nb) in zip(ph_l, ph_m):
        assert na == nb
        np.testing.assert_array_equal(np.asarray(pa.slots),
                                      np.asarray(pb.slots))
        np.testing.assert_array_equal(np.asarray(pa.mask),
                                      np.asarray(pb.mask))
    with pytest.raises(ValueError):
        VoteBatcher(I, V, n_slots=4, verify_mode="nope")

def test_collect_device_evidence_joins_flags_to_proofs():
    """The production join: device equivocation flags + either bridge's
    retained log -> third-party-verifiable signed double-sign proofs."""
    from agnes_tpu.bridge import NativeIngestLoop, pack_wire_votes
    from agnes_tpu.bridge.evidence import (collect_device_evidence,
                                           verify_evidence)
    from agnes_tpu.bridge.ingest import vote_messages_np

    I, V = 2, 4
    seeds = [bytes([i + 1]) * 32 for i in range(V)]
    pubkeys = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                        for s in seeds])

    def double_sign_feed(bridge, use_wire):
        inst = np.array([0, 0, 1], np.int64)
        val = np.array([2, 2, 1], np.int64)
        h = np.zeros(3, np.int64)
        rnd = np.zeros(3, np.int64)
        typ = np.zeros(3, np.int64)
        value = np.array([7, 9, 7], np.int64)
        msgs = vote_messages_np(h, rnd, typ, value)
        sigs = np.stack([np.frombuffer(
            native.sign(seeds[val[k]], msgs[k].tobytes()), np.uint8)
            for k in range(3)])
        if use_wire:
            bridge.push(pack_wire_votes(inst, val, h, rnd, typ, value,
                                        sigs))
            bridge.build_phases()
        else:
            bridge.add_arrays(inst, val, h, rnd, typ, value, sigs)
            bridge.build_phases(pubkeys)

    flags = np.zeros((I, V), bool)
    flags[0, 2] = True          # the double-signer
    flags[1, 1] = True          # honest: flag with single vote -> no pair

    bat = VoteBatcher(I, V, n_slots=4)
    double_sign_feed(bat, use_wire=False)
    ev = collect_device_evidence(flags, bat)
    assert len(ev) == 1 and (ev[0].instance, ev[0].validator) == (0, 2)
    assert {ev[0].first.value, ev[0].second.value} == {7, 9}
    assert verify_evidence(ev[0], native.pubkey(seeds[2]))
    assert not verify_evidence(ev[0], native.pubkey(seeds[1]))

    loop = NativeIngestLoop(I, V, n_slots=4, pubkeys=pubkeys)
    loop.sync_device(np.zeros(I, np.int64), np.zeros(I, np.int64))
    double_sign_feed(loop, use_wire=True)
    ev2 = collect_device_evidence(flags, loop)
    assert len(ev2) == 1
    assert {ev2[0].first.value, ev2[0].second.value} == {7, 9}
    assert verify_evidence(ev2[0], native.pubkey(seeds[2]))

def test_collect_device_evidence_skips_unsigned_pairs():
    """Conflicting votes ingested WITHOUT signatures prove nothing to
    a third party — they must not be packaged as 'signed proofs'."""
    from agnes_tpu.bridge.evidence import collect_device_evidence

    b = VoteBatcher(1, 4, n_slots=4)
    b.add(WireVote(0, 2, 0, 0, VoteType.PREVOTE, 7))   # no signature
    b.add(WireVote(0, 2, 0, 0, VoteType.PREVOTE, 9))
    b.build_phases()                                    # unverified path
    flags = np.zeros((1, 4), bool)
    flags[0, 2] = True
    assert collect_device_evidence(flags, b) == []
