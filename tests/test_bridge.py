"""Bridge: value interning, slot maps, and the vote-batch ingestion ABI."""

import numpy as np

from agnes_tpu.bridge import SlotMap, ValueTable, VoteBatcher, WireVote
from agnes_tpu.core import native
from agnes_tpu.crypto.encoding import vote_signing_bytes
from agnes_tpu.harness.device_driver import DeviceDriver
from agnes_tpu.types import VoteType


def test_value_table_roundtrip_and_determinism():
    t1, t2 = ValueTable(), ValueTable()
    payloads = [b"block-7", b"block-8", b"x" * 100]
    ids1 = [t1.intern(p) for p in payloads]
    ids2 = [t2.intern(p) for p in payloads]
    assert ids1 == ids2                      # content-derived: hosts agree
    assert len(set(ids1)) == 3
    for vid, p in zip(ids1, payloads):
        assert t1.payload(vid) == p
    assert t1.intern(b"block-7") == ids1[0]  # idempotent
    assert all(0 <= v < 2**31 for v in ids1)


def test_slot_map_allocation_and_overflow():
    sm = SlotMap(n_instances=2, n_slots=2)
    assert sm.slot_for(0, 111) == 0
    assert sm.slot_for(0, 222) == 1
    assert sm.slot_for(0, 111) == 0          # stable
    assert sm.slot_for(0, 333) is None       # overflow -> host fallback
    assert sm.overflowed == 1
    assert sm.slot_for(1, 333) == 0          # instances independent
    assert sm.value_for(0, 1) == 222
    sm.reset_instance(0)
    assert sm.slot_for(0, 333) == 0


def _signed_vote(seeds, inst, val_idx, height, rnd, typ, value):
    sig = native.sign(seeds[val_idx],
                      vote_signing_bytes(height, rnd, int(typ), value))
    return WireVote(instance=inst, validator=val_idx, height=height,
                    round=rnd, typ=typ, value=value, signature=sig)


def test_batcher_end_to_end_signed_consensus():
    """Signed wire votes -> verified dense phases -> device decision."""
    I, V = 2, 4
    seeds = [bytes([i + 1]) * 32 for i in range(V)]
    pubkeys = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                        for s in seeds])
    value_id = ValueTable().intern(b"the-block")

    b = VoteBatcher(I, V, n_slots=4)
    for inst in range(I):
        for v in range(V):
            b.add(_signed_vote(seeds, inst, v, 0, 0, VoteType.PREVOTE,
                               value_id))
    # one forged prevote (wrong key signs validator 3's vote)
    forged_sig = native.sign(b"\xBB" * 32,
                             vote_signing_bytes(0, 0, 0, value_id))
    b.add(WireVote(instance=0, validator=3, height=0, round=0,
                   typ=VoteType.PREVOTE, value=value_id,
                   signature=forged_sig))
    # and a malformed one
    b.add(WireVote(instance=0, validator=99, height=0, round=0,
                   typ=VoteType.PREVOTE, value=value_id, signature=None))

    phases = b.build_phases(pubkeys)
    assert b.rejected_signature == 1
    assert b.rejected_malformed == 1
    # layering: the forged vote was dropped, so one layer only
    assert len(phases) == 1
    phase, n = phases[0]
    assert n == I * V

    d = DeviceDriver(I, V)
    d.step()                       # entry + self-proposal
    d.step(phase=phase)            # everyone prevotes the value
    for inst in range(I):
        for v in range(V):
            b.add(_signed_vote(seeds, inst, v, 0, 0, VoteType.PRECOMMIT,
                               value_id))
    (pc_phase, n2), = b.build_phases(pubkeys)
    assert n2 == I * V
    d.step(phase=pc_phase)
    assert d.all_decided()
    # decision slot decodes back to the interned value id
    slot = int(d.stats.decision_value[0])
    assert b.decode_slot(0, slot) == value_id


def test_batcher_layers_equivocating_votes():
    """Two conflicting votes from one validator land in two layers and
    the device flags the equivocation."""
    I, V = 1, 4
    b = VoteBatcher(I, V, n_slots=4)
    for v in range(V):
        b.add(WireVote(0, v, 0, 0, VoteType.PREVOTE, value=100 + v % 2))
    b.add(WireVote(0, 0, 0, 0, VoteType.PREVOTE, value=999))  # conflict
    phases = b.build_phases()      # unverified path (no pubkeys)
    assert len(phases) == 2        # base layer + conflict layer
    d = DeviceDriver(I, V)
    d.step()
    for phase, _ in phases:
        d.step(phase=phase)
    assert int(d.equivocators_detected()[0]) == 1


def test_batcher_dedupes_exact_duplicates():
    """Gossip redelivery: 10 copies of one vote -> one layer, one slot."""
    b = VoteBatcher(1, 4, n_slots=4)
    for _ in range(10):
        b.add(WireVote(0, 2, 0, 0, VoteType.PREVOTE, value=7))
    b.add(WireVote(0, 1, 0, 0, VoteType.PREVOTE, value=7))
    phases = b.build_phases()
    assert len(phases) == 1
    _, n = phases[0]
    assert n == 2  # two distinct (validator) votes


def test_batcher_drops_cross_height_votes():
    b = VoteBatcher(2, 4, n_slots=4,
                    heights=np.asarray([5, 6], np.int64))
    b.add(WireVote(0, 1, 5, 0, VoteType.PREVOTE, 1))   # right height
    b.add(WireVote(1, 1, 5, 0, VoteType.PREVOTE, 1))   # wrong height
    phases = b.build_phases()
    assert b.rejected_malformed == 1
    assert sum(n for _, n in phases) == 1


def test_batcher_rejects_wrong_length_signature():
    """A signature of any length other than 64 must be counted as
    malformed, not crash the packer (ADVICE r1: one hostile vote could
    DoS the whole ingestion tick)."""
    seeds = [bytes([i + 1]) * 32 for i in range(4)]
    pub = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                    for s in seeds])
    b = VoteBatcher(1, 4, n_slots=4)
    b.add(WireVote(0, 0, 0, 0, VoteType.PREVOTE, 1, signature=b"\x01" * 10))
    b.add(WireVote(0, 1, 0, 0, VoteType.PREVOTE, 1, signature=b"\x01" * 65))
    phases = b.build_phases(pubkeys=pub)
    assert b.rejected_malformed == 2
    assert phases == []


def test_native_verify_rejects_wrong_length_inputs():
    """ADVICE r1: short pk/sig must return a clean False from the C ABI
    wrapper, never reach the unconditional 32/64-byte reads in C++."""
    seed = b"\x07" * 32
    pk = native.pubkey(seed)
    msg = b"hello"
    sig = native.sign(seed, msg)
    assert native.verify(pk, msg, sig)
    assert not native.verify(pk[:16], msg, sig)
    assert not native.verify(pk, msg, sig[:10])
    assert not native.verify(pk + b"\x00", msg, sig)
    assert not native.verify(pk, msg, sig + b"\x00")
    # batch path: misaligned entries report False without disturbing
    # well-formed neighbours
    res = native.verify_batch([pk, pk[:5], pk], [msg, msg, msg],
                              [sig, sig, sig[:5]])
    assert res == [True, False, False]
