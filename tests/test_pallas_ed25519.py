"""Pallas Ed25519 kernels vs the jnp path and the RFC oracle.

CPU runs the kernels in interpreter mode (tiny tile); the real TPU
lowering is exercised by bench.py on hardware.  Backend state is
restored after each test so the rest of the suite stays on jnp.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from agnes_tpu.crypto import ed25519_jax as E
from agnes_tpu.crypto import ed25519_ref as ref
from agnes_tpu.crypto import field_jax as F
from agnes_tpu.crypto import pallas_ed25519 as pk


@pytest.fixture(autouse=True)
def _restore_backend():
    yield
    E.set_backend(None)


def _scalars(vals):
    return jnp.stack([jnp.asarray([(v >> (13 * i)) & 0x1FFF
                                   for i in range(20)], jnp.int32)
                      for v in vals])


def test_pow_kernel_matches_oracle():
    xs = [3, 12345, F.P - 1, 2**200 + 17]
    x = jnp.stack([F.to_limbs(v) for v in xs])
    for e in (2, 65537, (F.P - 5) // 8):
        out = pk.pow_p_pallas(x, e, interpret=True, b_tile=128)
        for i, v in enumerate(xs):
            assert F.from_limbs(F.freeze(out)[i]) == pow(v, e, F.P), (e, i)


def test_straus_kernel_matches_jnp_path():
    rng = np.random.RandomState(7)
    B = 3
    s = [int.from_bytes(rng.bytes(32), "little") % ref.L for _ in range(B)]
    k = [int.from_bytes(rng.bytes(32), "little") % ref.L for _ in range(B)]
    pts = [ref._mul(i + 5, ref.BASE) for i in range(B)]
    enc = jnp.asarray(np.stack([np.frombuffer(ref._compress(p), np.uint8)
                                for p in pts]), jnp.int32)
    a_point, ok = E.decompress(enc)
    assert bool(ok.all())
    q_ref = E.compress(E.straus_sub(_scalars(s), _scalars(k), a_point))
    q_pal = E.compress(pk.straus_sub_pallas(
        _scalars(s), _scalars(k), a_point, interpret=True, b_tile=128))
    assert jnp.array_equal(q_ref, q_pal)
    # and against the plain-int oracle: Q = [s]B - [k]A
    for i in range(B):
        expect = ref._add(ref._mul(s[i], ref.BASE),
                          ref._mul(ref.L - k[i] % ref.L, pts[i]))
        assert bytes(np.asarray(q_pal[i], np.uint8).tobytes()) == \
            ref._compress(expect)


def test_verify_batch_full_pallas_backend():
    """End-to-end verify with the pallas backend (interpret mode):
    same verdicts as the oracle, including a forged lane."""
    pk_mod = pk  # noqa: F841  (imported for the backend)
    E.set_backend("pallas", interpret=True)
    seeds = [bytes([i + 9]) * 32 for i in range(3)]
    keys = [ref.keypair(s) for s in seeds]
    msgs = [bytes([i]) * 45 for i in range(3)]
    sigs = [ref.sign(sk, m) for (sk, _), m in zip(keys, msgs)]
    sigs[1] = sigs[1][:7] + bytes([sigs[1][7] ^ 2]) + sigs[1][8:]
    pub, sig, blocks = E.pack_verify_inputs_host(
        [pk_ for _, pk_ in keys], msgs, sigs)
    ok = E.verify_batch(pub, sig, blocks)   # not the cached jit
    assert ok.tolist() == [True, False, True]
    for i in range(3):
        assert bool(ok[i]) == ref.verify(keys[i][1], msgs[i], sigs[i])
