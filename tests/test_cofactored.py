"""The cofactored-verification agreement property, cross-implementation.

The framework's policy (rationale: ed25519_ref.verify) is that every
verifier — Python oracle, C++ host, jnp batch, Pallas kernel, MSM
batch check — applies the COFACTORED equation [8]([S]B - [k]A) ==
[8]R, so a signature's validity is a pure function of its bytes under
every verification strategy.  The discriminating input is a
torsion-defect signature (R offset by a small-order point): it fails
the exact equation, satisfies the x8 one, and under a mixed policy
would be accepted by some verifiers and rejected by others — exactly
the divergence a consensus engine cannot tolerate.  (Pallas-kernel
agreement on the same input is covered by tests/test_pallas_verify.py
lane 17.)
"""

import numpy as np

from agnes_tpu.core import native
from agnes_tpu.crypto import ed25519_jax as E
from agnes_tpu.crypto import ed25519_ref as ref
from agnes_tpu.crypto import msm_jax as M
from tests.test_pallas_verify import torsioned_sig

MSG = b"\x05" * 45


def _batch(entries):
    pubs = [p for p, _, _ in entries]
    msgs = [m for _, m, _ in entries]
    sigs = [s for _, _, s in entries]
    return E.pack_verify_inputs_host(pubs, msgs, sigs)


def test_torsion_defect_is_pure_torsion():
    """Sanity on the fixture itself: exact equation fails, x8 holds."""
    pub, msg, sig = torsioned_sig(bytes([7]) * 32, MSG)
    A = ref._decompress(pub)
    R = ref._decompress(sig[:32])
    s = int.from_bytes(sig[32:], "little")
    k = ref._sha512_int(sig[:32] + pub + MSG) % ref.L
    lhs = ref._mul(s, ref.BASE)
    rhs = ref._add(R, ref._mul(k, A))
    assert not ref.point_equal(lhs, rhs)           # exact: fails
    assert ref.point_equal(ref._mul(8, lhs), ref._mul(8, rhs))


def test_all_verifiers_agree_on_torsion_defect():
    honest_seed = bytes([1]) * 32
    sk, pk = ref.keypair(honest_seed)
    honest = (pk, MSG, ref.sign(sk, MSG))
    tors = torsioned_sig(bytes([7]) * 32, MSG)
    forged = (pk, MSG, bytes([honest[2][0] ^ 1]) + honest[2][1:])
    entries = [honest, tors, forged]
    want = [True, True, False]

    # python oracle
    assert [ref.verify(p, m, s) for p, m, s in entries] == want
    # C++ host verifier
    assert [native.verify(p, m, s) for p, m, s in entries] == want
    # jnp batch path
    pub, sig, blocks = _batch(entries)
    assert np.asarray(E.verify_batch_jit(pub, sig, blocks)).tolist() == want
    # MSM batch check: torsion lane is structurally valid and the x8
    # combined equation holds for it, so with the forged lane removed
    # the batch accepts; with it, the adaptive path localizes it
    pub2, sig2, blocks2 = _batch(entries[:2])
    batch_ok, lane_ok = M.verify_batch_msm_jit(
        pub2, sig2, blocks2, M.make_z(2, seed=11))
    assert bool(batch_ok) and np.asarray(lane_ok).all()
    got = M.verify_batch_adaptive(pub, sig, blocks, seed=12, leaf=2)
    np.testing.assert_array_equal(got, want)
