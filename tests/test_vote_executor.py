"""Event-producer tests: the to_event table (vote_executor.rs:26-36),
multi-round tracking, edge-triggered emission, round-skip detection."""

from agnes_tpu.core import state_machine as sm
from agnes_tpu.core.round_votes import Thresh
from agnes_tpu.core.vote_executor import VoteExecutor, to_event
from agnes_tpu.types import Vote, VoteType

VAL = 7


def test_to_event_table():
    """Exact mapping, incl. the Precommit+Nil → None asymmetry
    (vote_executor.rs:33)."""
    E, T = sm.EventTag, Thresh
    assert to_event(VoteType.PREVOTE, T.init()) is None
    assert to_event(VoteType.PRECOMMIT, T.init()) is None
    assert to_event(VoteType.PREVOTE, T.any()).tag == E.POLKA_ANY
    assert to_event(VoteType.PREVOTE, T.nil()).tag == E.POLKA_NIL
    ev = to_event(VoteType.PREVOTE, T.for_value(VAL))
    assert ev.tag == E.POLKA_VALUE and ev.value == VAL
    assert to_event(VoteType.PRECOMMIT, T.any()).tag == E.PRECOMMIT_ANY
    # pure-nil precommit quorum triggers the spec line 47 timeout path —
    # documented deviation from vote_executor.rs:33 (see to_event docstring)
    assert to_event(VoteType.PRECOMMIT, T.nil()).tag == E.PRECOMMIT_ANY
    ev = to_event(VoteType.PRECOMMIT, T.for_value(VAL))
    assert ev.tag == E.PRECOMMIT_VALUE and ev.value == VAL


def test_apply_reference_refire_mode():
    """edge_triggered=False reproduces the reference's level-triggered
    re-fire on every vote after crossing (vote_executor.rs:20-23)."""
    ve = VoteExecutor(height=1, total_weight=4)  # level-triggered default
    assert ve.apply(Vote.new_prevote(0, VAL), 1) is None
    assert ve.apply(Vote.new_prevote(0, VAL), 1) is None
    assert ve.apply(Vote.new_prevote(0, VAL), 1).tag == sm.EventTag.POLKA_VALUE
    # re-fires
    assert ve.apply(Vote.new_prevote(0, VAL), 1).tag == sm.EventTag.POLKA_VALUE


def test_apply_edge_triggered():
    """Default mode fires each distinct threshold once (SURVEY.md §2.4)."""
    ve = VoteExecutor(height=1, total_weight=4, edge_triggered=True)
    ve.apply(Vote.new_prevote(0, VAL), 1)
    ve.apply(Vote.new_prevote(0, VAL), 1)
    ev = ve.apply(Vote.new_prevote(0, VAL), 1)
    assert ev.tag == sm.EventTag.POLKA_VALUE
    assert ve.apply(Vote.new_prevote(0, VAL), 1) is None  # no re-fire


def test_multi_round_tallies_independent():
    """The reference's "TODO more rounds" (vote_executor.rs:9,14) done."""
    ve = VoteExecutor(height=1, total_weight=3)
    ve.apply(Vote.new_precommit(0, VAL), 2)
    # round 1 votes don't inherit round 0 weight
    assert ve.apply(Vote.new_precommit(1, VAL), 1) is None
    ev = ve.apply(Vote.new_precommit(0, VAL), 1)
    assert ev.tag == sm.EventTag.PRECOMMIT_VALUE


def test_round_skip_detection():
    """+1/3 of weight on a higher round triggers RoundSkip, once."""
    ve = VoteExecutor(height=1, total_weight=6)
    ve.apply(Vote.new_prevote(3, VAL, validator=0), 2)
    assert ve.check_round_skip(0) is None  # 2 of 6 is not > 1/3
    ve.apply(Vote.new_prevote(3, None, validator=1), 1)
    assert ve.check_round_skip(0) == 3     # 3 of 6 > 1/3... (3*3 > 6)
    assert ve.check_round_skip(0) is None  # fires once
    # rounds at or below current never trigger
    ve2 = VoteExecutor(height=1, total_weight=3)
    ve2.apply(Vote.new_prevote(2, VAL, validator=0), 3)
    assert ve2.check_round_skip(2) is None


def test_cross_height_votes_ignored():
    """A vote stamped with another height must not count here."""
    ve = VoteExecutor(height=1, total_weight=3)
    assert ve.apply(Vote.new_precommit(0, VAL, height=2), 3) is None
    assert ve.votes.round(0).precommits.value_weight(VAL) == 0
    # un-stamped and same-height votes count
    ve.apply(Vote.new_precommit(0, VAL, height=1), 2)
    assert ve.apply(Vote.new_precommit(0, VAL), 1).tag \
        == sm.EventTag.PRECOMMIT_VALUE


def test_threshold_events_requery_after_missed_edge():
    """Edge-triggered consumers re-query reached thresholds on state
    change, so an event consumed in the wrong step is not lost."""
    ve = VoteExecutor(height=1, total_weight=3, edge_triggered=True)
    for i in range(3):
        ev = ve.apply(Vote.new_prevote(0, VAL, validator=i), 1)
    assert ev.tag == sm.EventTag.POLKA_VALUE     # fired once...
    assert ve.apply(Vote.new_prevote(0, VAL, validator=0), 1) is None
    # ...but remains queryable for a consumer whose step just advanced
    evs = ve.threshold_events(0)
    assert [e.tag for e in evs] == [sm.EventTag.POLKA_VALUE]
    assert ve.threshold_events(5) == []


def test_precommit_any_fires_once_across_any_then_nil_threshold():
    """ANY and NIL precommit thresholds both map to PRECOMMIT_ANY; the
    edge-trigger must not re-fire it when the code rises ANY -> NIL
    (spec line 47 'for the first time')."""
    ve = VoteExecutor(height=1, total_weight=100, edge_triggered=True)
    ve.apply(Vote.new_precommit(0, VAL, validator=0), 40)
    ev = ve.apply(Vote.new_precommit(0, None, validator=1), 30)
    assert ev.tag == sm.EventTag.PRECOMMIT_ANY  # mixed quorum: 70 of 100
    ev = ve.apply(Vote.new_precommit(0, None, validator=2), 40)
    assert ev is None  # nil alone now has quorum; same event, no re-fire
