"""Deterministic interleaving explorer (ISSUE 19): the checker's own
tripwires.

The explorer's value rests on four properties that are easy to break
silently while refactoring the serve host: (1) replay determinism —
the same forced schedule must reproduce the identical execution, or
minimized repros are fiction; (2) pruning soundness — sleep sets must
not hide terminal states the full tree reaches; (3) bite — the
shipped (or review-caught) races, resurrected as mutants, must still
be caught, their schedules ddmin-minimized, and the minimized
schedules must replay
CLEAN on the honest build (a checker that flags honest code is worse
than none); (4) jax-freedom — the ci.sh [1e] gate slot budget assumes
zero XLA compiles.  The TSan harness's plain build rides along as a
cheap correctness test of the native half, and the LINT005 /
lock-registry satellites are pinned here too.

Everything in this file is pure CPU and compile-free (conftest _CHEAP
tier).
"""

import os
import subprocess
import sys
import textwrap

import pytest

from agnes_tpu.analysis import lint, lockcheck, schedcheck

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = schedcheck.SCOPES["tiny"][0]


# -- (1) replay determinism ---------------------------------------------------

def test_replay_is_deterministic():
    """The same forced schedule reproduces the identical execution:
    same choices, same decision points, same digest, same trace."""
    base = schedcheck.run_once(TINY)
    assert base.completed and not base.violations
    # perturb: force the lexicographically-next sibling at the first
    # multi-enabled decision, then replay THAT schedule twice
    forced = list(base.choices)
    for res in (schedcheck.run_once(TINY, forced=forced),
                schedcheck.run_once(TINY, forced=forced)):
        assert res.choices == base.choices
        assert res.digest == base.digest
        assert res.trace == base.trace
        assert len(res.decisions) == len(base.decisions)


def test_distinct_schedules_reach_distinct_traces():
    """Exploration is not a no-op: the tiny scope's schedule tree has
    more than one execution and at least one real interleaving fork."""
    r = schedcheck.explore(TINY)
    assert r.complete
    assert r.schedules > 100
    assert r.max_decisions > 1
    assert not r.violations


# -- (2) pruning soundness ----------------------------------------------------

def test_sleep_set_pruning_preserves_terminal_states():
    """Sleep-set pruning must visit every terminal state the full
    tree visits (fewer schedules, same digest SET) — the standard
    soundness argument, checked by brute force on the tiny scope."""
    full = schedcheck.explore(TINY, sleep_sets=False)
    pruned = schedcheck.explore(TINY, sleep_sets=True)
    assert full.complete and pruned.complete
    assert pruned.schedules <= full.schedules
    assert pruned.digests == full.digests
    assert not full.violations and not pruned.violations


# -- (3) bite: the shipped (or review-caught) races, resurrected --------------

def test_self_test_catches_minimizes_and_exonerates():
    """Every mutant caught, its schedule ddmin-minimized, and the
    minimized schedule replaying clean on the honest build."""
    rep = schedcheck.self_test()
    assert rep["ok"], rep
    for name, kinds in (("inbox_close_toctou",
                         ("conservation", "atomicity")),
                        ("native_drain_shrink", ("conservation",)),
                        ("shard_route_lost", ("conservation",)),
                        ("busy_frac_inflight", ("busy_frac",))):
        rec = rep[name]
        assert rec["caught"], (name, rec)
        assert rec["honest_clean"], (name, rec)
        assert rec["minimized_len"] <= rec["schedule_len"], (name, rec)
        assert set(rec["kinds"]) & set(kinds), (name, rec)
        # the minimized schedule still reproduces ON DEMAND — the
        # repro a regression investigation would actually run
        res = schedcheck.run_once(schedcheck.MUTANTS[name][0], name,
                                  forced=rec["minimized"])
        assert any(v.kind in kinds for v in res.violations), (name, res)


def test_smoke_scope_runs_clean():
    """One pass of the cheapest smoke config end-to-end through
    run_scope (the ci.sh [1e] shape) — bounded so the full sweep
    stays in the gate, not the test suite."""
    rep = schedcheck.run_scope("tiny")
    assert rep["ok"] and rep["complete"], rep
    assert rep["violations"] == 0
    assert rep["schedules_explored"] > 100


# -- (4) jax-freedom + atomic annotations -------------------------------------

def test_schedcheck_import_is_jax_free():
    code = (
        "import sys, agnes_tpu.analysis.schedcheck as sc\n"
        "r = sc.run_once(sc.SCOPES['tiny'][0])\n"
        "assert r.completed and not r.violations, r.violations\n"
        "assert 'jax' not in sys.modules, 'jax leaked into schedcheck'\n"
        "print('SCHEDCHECK-JAXFREE-OK')\n")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=120,
                         cwd=REPO)
    assert out.returncode == 0 and "SCHEDCHECK-JAXFREE-OK" in out.stdout, (
        out.stdout, out.stderr)


def test_atomic_annotations_match_registry():
    """Every `# schedcheck: atomic` marker in the serve tree has a
    registry entry and vice versa — a moved/renamed span fails here,
    not silently in the monitor."""
    assert schedcheck.check_atomic_annotations(REPO) == []


# -- satellite: TSan harness plain build --------------------------------------

def test_tsan_admission_harness_plain_build(tmp_path):
    """The ci.sh [1b] admission stress binary, built WITHOUT
    -fsanitize=thread, doubles as a cheap correctness test: the
    admission taxonomy must balance under real producer/drainer/reader
    concurrency (exit 0 prints the ok line)."""
    import shutil
    if shutil.which("g++") is None:
        pytest.skip("no g++ on this box (ci.sh [1b] covers it)")
    binary = tmp_path / "tsan_admission_stress"
    build = subprocess.run(
        ["g++", "-O1", "-std=c++17", "-pthread", "-o", str(binary),
         os.path.join(REPO, "tests/native/tsan_admission_stress.cpp"),
         os.path.join(REPO, "agnes_tpu/core/native/admission.cpp"),
         os.path.join(REPO,
                      "agnes_tpu/core/native/admission_phases.cpp"),
         os.path.join(REPO,
                      "agnes_tpu/core/native/admission_shards.cpp"),
         os.path.join(REPO, "agnes_tpu/core/native/sha512.cpp")],
        capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr
    run = subprocess.run([str(binary)], capture_output=True, text=True,
                         timeout=300)
    assert run.returncode == 0, (run.stdout, run.stderr)
    assert "tsan_admission_stress ok" in run.stdout, run.stdout


# -- satellite: registry-derived lock instrumentation -------------------------

def test_lock_registry_names_and_ranks():
    """The instrumented lock set is registry-derived (not hand-listed
    in instrument()); the serve pair keeps admission(0) -> device(1),
    leaf mutexes are rank 2."""
    reg = {name: rank for name, rank, _ in lockcheck.LOCK_REGISTRY}
    assert reg == {"_admission": 0, "_device": 1, "cache._mu": 2,
                   "bls_table._mu": 2, "flightrec._mu": 2}


def test_instrument_skips_absent_leaves():
    """Resolvers are getattr-safe: a deployment without a cache / BLS
    table / flight recorder instruments only the locks it has."""
    class Bare:
        pass

    t = Bare()
    t._admission = None
    t._device = None
    state = lockcheck.instrument(t, strict=True)
    assert isinstance(t._admission, lockcheck.InstrumentedLock)
    assert isinstance(t._device, lockcheck.InstrumentedLock)
    assert state.violations == []
    # none of the leaf resolvers invented an attribute
    assert not hasattr(t, "service")


# -- satellite: LINT005 (bare thread construction) ----------------------------

def _lint_tmp_repo(tmp_path, body):
    pkg = tmp_path / "agnes_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(textwrap.dedent(body))
    return str(tmp_path)


def test_lint005_flags_bare_thread(tmp_path):
    root = _lint_tmp_repo(tmp_path, """\
        import threading

        def spawn(fn):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            return t
        """)
    findings = lint.check_threads(root)
    assert len(findings) == 1
    assert findings[0].code == "LINT005"
    assert "agnes_tpu/mod.py:4" in findings[0].where.replace(os.sep, "/")


def test_lint005_span_pragma_clears_multiline_call(tmp_path):
    root = _lint_tmp_repo(tmp_path, """\
        import threading

        def spawn(fn):
            t = threading.Thread(
                target=fn,
                daemon=True)  # lint: allow-thread (owns containment)
            t.start()
            return t
        """)
    assert lint.check_threads(root) == []


def test_lint005_wrapper_modules_exempt(tmp_path):
    pkg = tmp_path / "agnes_tpu" / "serve"
    pkg.mkdir(parents=True)
    (pkg / "threaded.py").write_text(
        "import threading\n"
        "def spawn(fn):\n"
        "    return threading.Thread(target=fn)\n")
    assert lint.check_threads(str(tmp_path)) == []


def test_lint005_repo_is_clean():
    """Every bare threading.Thread in the real tree is in a wrapper
    module or pragma-annotated — the rule holds on the code it was
    written for."""
    assert lint.check_threads(REPO) == []
