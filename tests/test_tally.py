"""Device tally tests: scenario tests for thresholds/dedup/equivocation/
round-skip, plus a randomized differential against the Python tally."""

import numpy as np
import jax.numpy as jnp

from agnes_tpu.core.round_votes import RoundVotes
from agnes_tpu.core.state_machine import EventTag
from agnes_tpu.device.tally import (
    NO_EVENT,
    NOT_VOTED,
    TH_ANY,
    TH_INIT,
    TH_NIL,
    TH_VALUE,
    TallyConfig,
    TallyState,
    add_votes_jit,
    current_threshold,
)
from agnes_tpu.types import Vote, VoteType

CFG = TallyConfig(n_validators=4, n_rounds=3, n_slots=3)
POWERS = jnp.asarray([1, 1, 1, 1], jnp.int32)
TOTAL = jnp.asarray(4, jnp.int32)


def _phase(tally, round_, typ, votes, cur_round=0, n=1):
    """votes: {validator: slot} (-1 = nil); returns (tally, events)."""
    slots = np.full((n, CFG.n_validators), -1, np.int32)
    mask = np.zeros((n, CFG.n_validators), bool)
    for v, s in votes.items():
        slots[:, v] = s
        mask[:, v] = True
    return add_votes_jit(
        tally, POWERS, TOTAL,
        jnp.full((n,), round_, jnp.int32), jnp.full((n,), int(typ), jnp.int32),
        jnp.asarray(slots), jnp.asarray(mask),
        jnp.full((n,), cur_round, jnp.int32))


def test_value_quorum_event():
    t = TallyState.new(1, CFG)
    t, ev = _phase(t, 0, VoteType.PREVOTE, {0: 2, 1: 2, 2: 2})
    assert int(ev.tag[0]) == int(EventTag.POLKA_VALUE)
    assert int(ev.value_slot[0]) == 2
    assert int(ev.round[0]) == 0
    # weights: slot 2 -> column 3
    assert int(t.weights[0, 0, 0, 3]) == 3


def test_edge_triggered_and_dedup():
    t = TallyState.new(1, CFG)
    t, ev = _phase(t, 0, VoteType.PREVOTE, {0: 1, 1: 1, 2: 1})
    assert int(ev.tag[0]) == int(EventTag.POLKA_VALUE)
    # same votes again: deduped (no weight growth) and no re-fire
    t, ev = _phase(t, 0, VoteType.PREVOTE, {0: 1, 1: 1, 2: 1})
    assert int(ev.tag[0]) == NO_EVENT
    assert int(t.weights[0, 0, 0, 2]) == 3
    # re-query path still reports the reached threshold
    code, vslot = current_threshold(
        t, jnp.zeros(1, jnp.int32), jnp.zeros(1, jnp.int32), TOTAL)
    assert int(code[0]) == TH_VALUE and int(vslot[0]) == 1


def test_any_then_nil_then_value_ladder():
    t = TallyState.new(1, CFG)
    # 2 for slot 0, 1 nil: 3 of 4 seen -> Any
    t, ev = _phase(t, 0, VoteType.PREVOTE, {0: 0, 1: 0, 2: -1})
    assert int(ev.tag[0]) == int(EventTag.POLKA_ANY)
    # one more for slot 0 -> Value (3 of 4)
    t, ev = _phase(t, 0, VoteType.PREVOTE, {3: 0})
    assert int(ev.tag[0]) == int(EventTag.POLKA_VALUE)
    assert int(ev.value_slot[0]) == 0


def test_nil_quorum():
    t = TallyState.new(1, CFG)
    t, ev = _phase(t, 1, VoteType.PREVOTE, {0: -1, 1: -1, 2: -1})
    assert int(ev.tag[0]) == int(EventTag.POLKA_NIL)


def test_precommit_nil_maps_to_precommit_any():
    """No PrecommitNil event exists (vote_executor.rs:33 parity); a
    pure-nil precommit quorum fires PRECOMMIT_ANY so the spec line 47
    timeout path triggers (see core.vote_executor.to_event)."""
    t = TallyState.new(1, CFG)
    t, ev = _phase(t, 0, VoteType.PRECOMMIT, {0: -1, 1: -1, 2: -1})
    assert int(ev.tag[0]) == int(EventTag.PRECOMMIT_ANY)
    # but the threshold itself is recorded (for TimeoutPrecommit flows)
    code, _ = current_threshold(
        t, jnp.zeros(1, jnp.int32), jnp.ones(1, jnp.int32), TOTAL)
    assert int(code[0]) == TH_NIL


def test_precommit_any_event():
    t = TallyState.new(1, CFG)
    t, ev = _phase(t, 0, VoteType.PRECOMMIT, {0: 0, 1: 1, 2: -1})
    assert int(ev.tag[0]) == int(EventTag.PRECOMMIT_ANY)


def test_equivocation_detection():
    t = TallyState.new(1, CFG)
    t, _ = _phase(t, 0, VoteType.PREVOTE, {0: 1})
    t, _ = _phase(t, 0, VoteType.PREVOTE, {0: 2})  # conflict!
    assert bool(t.equiv[0, 0])
    assert not bool(t.equiv[0, 1])
    # first vote kept, second not counted
    assert int(t.weights[0, 0, 0, 2]) == 1  # slot 1
    assert int(t.weights[0, 0, 0, 3]) == 0  # slot 2
    # same validator voting the other CLASS is not equivocation
    t2 = TallyState.new(1, CFG)
    t2, _ = _phase(t2, 0, VoteType.PREVOTE, {0: 1})
    t2, _ = _phase(t2, 0, VoteType.PRECOMMIT, {0: 2})
    assert not bool(t2.equiv[0, 0])


def test_round_skip_fires_once():
    t = TallyState.new(1, CFG)
    # 2 of 4 voters (not > 1/3) at round 2: no skip
    t, ev = _phase(t, 2, VoteType.PREVOTE, {0: 1}, cur_round=0)
    assert int(ev.skip_round[0]) == -1
    # third distinct voter pushes past 1/3 (3*2 > 4)
    t, ev = _phase(t, 2, VoteType.PREVOTE, {1: 1}, cur_round=0)
    assert int(ev.skip_round[0]) == 2
    # fires once
    t, ev = _phase(t, 2, VoteType.PRECOMMIT, {2: 1}, cur_round=0)
    assert int(ev.skip_round[0]) == -1
    # rounds at/below current never skip
    t2 = TallyState.new(1, CFG)
    t2, ev = _phase(t2, 1, VoteType.PREVOTE, {0: 1, 1: 1}, cur_round=1)
    assert int(ev.skip_round[0]) == -1


def test_differential_vs_python_tally():
    """Random dense phases through both tallies; final weights, threshold
    codes and equivocation sets must agree exactly."""
    rng = np.random.default_rng(42)
    I, V, W, S = 6, 5, 3, 3
    cfg = TallyConfig(n_validators=V, n_rounds=W, n_slots=S)
    powers_np = rng.integers(1, 4, size=V).astype(np.int32)
    total = int(powers_np.sum())
    powers = jnp.asarray(powers_np)

    dev = TallyState.new(I, cfg)
    py = [{(w, t): RoundVotes(height=0, round=w, total=total)
           for w in range(W) for t in range(2)} for _ in range(I)]

    for _ in range(12):
        round_ = rng.integers(0, W, size=I).astype(np.int32)
        typ = rng.integers(0, 2, size=I).astype(np.int32)
        slots = rng.integers(-1, S, size=(I, V)).astype(np.int32)
        mask = rng.random((I, V)) < 0.6
        dev, _ = add_votes_jit(
            dev, powers, jnp.asarray(total, jnp.int32), jnp.asarray(round_),
            jnp.asarray(typ), jnp.asarray(slots), jnp.asarray(mask),
            jnp.zeros(I, jnp.int32))
        for i in range(I):
            rv = py[i][(int(round_[i]), int(typ[i]))]
            for v in range(V):
                if not mask[i, v]:
                    continue
                value = None if slots[i, v] < 0 else int(slots[i, v])
                vt = VoteType(int(typ[i]))
                vote = (Vote.new_prevote if vt == VoteType.PREVOTE
                        else Vote.new_precommit)(int(round_[i]), value,
                                                 validator=v)
                rv.add_vote(vote, int(powers_np[v]))

    wts = np.asarray(dev.weights)
    eqv = np.asarray(dev.equiv)
    kind_to_code = {0: TH_INIT, 1: TH_ANY, 2: TH_NIL, 3: TH_VALUE}
    for i in range(I):
        equivocators = set()
        for (w, t), rv in py[i].items():
            count = rv.prevotes if t == 0 else rv.precommits
            assert wts[i, w, t, 0] == count.nil, (i, w, t)
            for s in range(S):
                assert wts[i, w, t, s + 1] == count.value_weight(s), (i, w, t, s)
            code, vslot = current_threshold(
                dev, jnp.full(I, w, jnp.int32), jnp.full(I, t, jnp.int32),
                jnp.asarray(total, jnp.int32))
            th = count.thresh()
            assert int(code[i]) == kind_to_code[int(th.kind)], (i, w, t)
            if th.value is not None:
                assert int(vslot[i]) == th.value
            equivocators |= {e.validator for e in rv.equivocations}
        assert set(np.nonzero(eqv[i])[0]) == equivocators, i


def test_device_precommit_any_fires_once_across_any_then_nil():
    """Device mirror of the ANY->NIL no-refire rule (spec line 47):
    a mixed precommit quorum fires PRECOMMIT_ANY; when nil alone later
    crosses 2/3 (threshold code rises ANY->NIL) the same event must NOT
    fire again."""
    cfg = TallyConfig(n_validators=4, n_rounds=2, n_slots=2)
    powers = jnp.asarray([40, 30, 40, 40], jnp.int32)
    total = jnp.asarray(150, jnp.int32)  # quorum needs weight > 100
    t = TallyState.new(1, cfg)

    def ph(t, votes):
        slots = np.full((1, 4), -1, np.int32)
        mask = np.zeros((1, 4), bool)
        for v, s in votes.items():
            slots[:, v] = s
            mask[:, v] = True
        return add_votes_jit(t, powers, total, jnp.zeros(1, jnp.int32),
                             jnp.ones(1, jnp.int32), jnp.asarray(slots),
                             jnp.asarray(mask), jnp.zeros(1, jnp.int32))

    # mixed: value 40 + nil 70 = 110 > 100 seen, nil 70 <= 100 -> ANY
    t, ev = ph(t, {0: 0, 1: -1, 2: -1})
    assert int(ev.tag[0]) == int(EventTag.PRECOMMIT_ANY)
    # nil now 110 > 100: code rises to NIL, event is the same -> silent
    t, ev = ph(t, {3: -1})
    assert int(ev.tag[0]) == NO_EVENT


def test_out_of_window_round_is_dropped_entirely():
    """Votes for a round outside the tracked window [0, W) must not
    tally, fire events, or flag honest validators as equivocators
    (regression: the all-false row-selector used to read garbage that
    pattern-matched as a conflicting prior vote)."""
    cfg = TallyConfig(n_validators=4, n_rounds=4, n_slots=2)
    powers = jnp.ones((4,), jnp.int32)
    total = jnp.asarray(4, jnp.int32)
    t0 = TallyState.new(1, cfg)

    slots = np.full((1, 4), 1, np.int32)
    mask = np.ones((1, 4), bool)
    for bad_round in (5, -1, 4):
        t, ev = add_votes_jit(t0, powers, total,
                              jnp.full(1, bad_round, jnp.int32),
                              jnp.zeros(1, jnp.int32), jnp.asarray(slots),
                              jnp.asarray(mask), jnp.zeros(1, jnp.int32))
        assert not np.asarray(t.equiv).any(), bad_round
        assert (np.asarray(t.weights) == 0).all(), bad_round
        assert (np.asarray(t.voted) == -2).all(), bad_round
        assert int(ev.tag[0]) == NO_EVENT, bad_round
        assert int(ev.skip_round[0]) == -1, bad_round


def test_invalid_slot_votes_are_dropped():
    """Votes carrying a slot outside [-1, S) must not tally — clipping
    them into a real bucket would manufacture a quorum for a value
    nobody voted for, which the commit arm would decide on
    (regression)."""
    cfg = TallyConfig(n_validators=4, n_rounds=2, n_slots=2)
    powers = jnp.ones((4,), jnp.int32)
    total = jnp.asarray(4, jnp.int32)
    t = TallyState.new(1, cfg)

    for bad in (5, 2, -2, -7):
        slots = np.full((1, 4), bad, np.int32)
        mask = np.ones((1, 4), bool)
        t2, ev = add_votes_jit(t, powers, total, jnp.zeros(1, jnp.int32),
                               jnp.zeros(1, jnp.int32), jnp.asarray(slots),
                               jnp.asarray(mask), jnp.zeros(1, jnp.int32))
        assert (np.asarray(t2.weights) == 0).all(), bad
        assert int(ev.tag[0]) == NO_EVENT, bad
