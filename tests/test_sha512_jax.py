"""Batched device SHA-512 vs hashlib (the host truth)."""

import hashlib

import jax
import jax.numpy as jnp
import pytest

from agnes_tpu.crypto import sha512_jax as sj


@pytest.mark.parametrize("msg_len", [0, 1, 3, 45, 109, 111, 112, 127, 128,
                                     200, 256])
def test_matches_hashlib(msg_len):
    msgs = [bytes((i * 7 + j) % 256 for j in range(msg_len))
            for i in range(4)]
    blocks = sj.pack_padded_host(msgs)
    digests = jax.jit(sj.sha512_blocks)(blocks)
    for i, m in enumerate(msgs):
        assert sj.digest_to_le_bytes_host(digests[i]) == \
            hashlib.sha512(m).digest()


def test_vote_path_is_single_block():
    """R || A || M with M <= 47 bytes must pad to exactly one block —
    the one-compression-per-signature design invariant."""
    n_blocks, _ = sj.pad_message(32 + 32 + 45)
    assert n_blocks == 1


def test_multi_batch_dims():
    """[D, L, n_blocks, 32] layouts (mesh-sharded lanes) must work."""
    msgs = [bytes([i]) * 109 for i in range(4)]
    blocks = sj.pack_padded_host(msgs)          # [4, 1, 32]
    nested = blocks.reshape(2, 2, 1, 32)
    digests = sj.sha512_blocks(nested)
    assert digests.shape == (2, 2, 16)
    for i, m in enumerate(msgs):
        assert sj.digest_to_le_bytes_host(digests[i // 2, i % 2]) == \
            hashlib.sha512(m).digest()


def test_batch_vmap_consistency():
    msgs = [bytes([i]) * 109 for i in range(8)]
    blocks = sj.pack_padded_host(msgs)
    batched = sj.sha512_blocks(blocks)
    for i in range(8):
        single = sj.sha512_blocks(blocks[i][None])[0]
        assert jnp.array_equal(batched[i], single)
