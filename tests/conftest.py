"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths
are exercised without TPU hardware (the driver separately dry-runs the
real multi-chip path via __graft_entry__.dryrun_multichip).

This environment's sitecustomize registers an `axon` TPU backend in
every interpreter and forces jax_platforms="axon,cpu", so setting env
vars alone is not enough: we must also override the config in-process
*before any backend is initialized* (importing jax here, first, does
that — pytest imports conftest before any test module).
"""

import os
import sys

# XLA_FLAGS is read when the CPU client is created (first backend use),
# which is after this file runs — env assignment here is early enough.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

try:
    import jax
    jax.config.update("jax_platforms", "cpu")
    # persistent compile cache: the crypto scan bodies cost minutes to
    # compile on this toolchain; cache them across test runs
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.path.dirname(os.path.dirname(
                          os.path.abspath(__file__))), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
except ImportError:  # pure-core tests don't need jax
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
