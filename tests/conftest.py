"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths
are exercised without TPU hardware (the driver separately dry-runs the
real multi-chip path via __graft_entry__.dryrun_multichip).

This environment's sitecustomize registers an `axon` TPU backend in
every interpreter and forces jax_platforms="axon,cpu", so setting env
vars alone is not enough: we must also override the config in-process
*before any backend is initialized* (importing jax here, first, does
that — pytest imports conftest before any test module).
"""

import os
import sys

# XLA_FLAGS is read when the CPU client is created (first backend use),
# which is after this file runs — env assignment here is early enough.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_cpu_parallel_codegen_split_count" not in flags:
    # this jaxlib's XLA:CPU has a data race between its parallel
    # codegen threads and executable serialization (TSAN-confirmed in
    # ThunkEmitter::ConsumeKernels; intermittent segfaults in the
    # persistent-cache read/write paths, r4).  Single-threaded codegen
    # removes the racing threads; see utils/compile_cache.py.
    flags = (flags + " --xla_cpu_parallel_codegen_split_count=1").strip()
os.environ["XLA_FLAGS"] = flags
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

try:
    import jax

    jax.config.update("jax_platforms", "cpu")
    # NO persistent compile cache: it segfaulted four different ways
    # in this environment (utils/compile_cache.py module docstring has
    # the post-mortem); every run pays its own compiles.  Enforced, not
    # just omitted — a leftover JAX_COMPILATION_CACHE_DIR env var from
    # the pre-r4 workflow must not silently re-enable it.
    from agnes_tpu.utils.compile_cache import disable_persistent_cache
    disable_persistent_cache()
except ImportError:  # pure-core tests don't need jax
    pass
