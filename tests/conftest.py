"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths
are exercised without TPU hardware (the driver separately dry-runs the
real multi-chip path via __graft_entry__.dryrun_multichip).

This environment's sitecustomize registers an `axon` TPU backend in
every interpreter and forces jax_platforms="axon,cpu", so setting env
vars alone is not enough: we must also override the config in-process
*before any backend is initialized* (importing jax here, first, does
that — pytest imports conftest before any test module).
"""

import os
import sys

# XLA_FLAGS is read when the CPU client is created (first backend use),
# which is after this file runs — env assignment here is early enough.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_cpu_parallel_codegen_split_count" not in flags:
    # this jaxlib's XLA:CPU has a data race between its parallel
    # codegen threads and executable serialization (TSAN-confirmed in
    # ThunkEmitter::ConsumeKernels; intermittent segfaults in the
    # persistent-cache read/write paths, r4).  Single-threaded codegen
    # removes the racing threads; see utils/compile_cache.py.
    flags = (flags + " --xla_cpu_parallel_codegen_split_count=1").strip()
os.environ["XLA_FLAGS"] = flags
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

try:
    import jax

    jax.config.update("jax_platforms", "cpu")
    # NO persistent compile cache: it segfaulted four different ways
    # in this environment (utils/compile_cache.py module docstring has
    # the post-mortem); every run pays its own compiles.  Enforced, not
    # just omitted — a leftover JAX_COMPILATION_CACHE_DIR env var from
    # the pre-r4 workflow must not silently re-enable it.
    from agnes_tpu.utils.compile_cache import disable_persistent_cache
    disable_persistent_cache()
except ImportError:  # pure-core tests don't need jax
    pass


# Files whose interpret-mode Pallas kernels compile ~100k-op XLA:CPU
# graphs.  A big compile segfaults inside backend_compile_and_load once
# the process has already done a few hundred compiles (r4: full-suite
# runs died twice — first at test_sharded's shard_map compile after the
# heavy files, then, reordered, inside test_pallas_verify's own compile
# after ~340 small ones; every file passes standalone in a fresh
# process.  Same XLA:CPU family as the compile-cache post-mortem,
# utils/compile_cache.py).  The only reliable mitigation found is
# process isolation: in a full-suite run these files are skipped
# in-process and re-run each in a FRESH child interpreter by
# tests/test_zz_heavy_isolated.py (ordered last).  Set
# AGNES_HEAVY_DIRECT=1 to run them inline (what the child does).
_ISOLATED = (
    "test_ed25519_jax.py",
    "test_cofactored.py",
    "test_pallas_ed25519.py",
    "test_pallas_verify.py",
)
_WRAPPER = "test_zz_heavy_isolated.py"


# Deadline-bounded graceful degradation for the suite itself (the
# same contract bench.py honors, ISSUE 1): the tier-1 gate runs
# `timeout 870 pytest tests/ -m 'not slow'`, and with the persistent
# compile cache deliberately off (utils/compile_cache.py) a single
# fused-verify trace costs minutes of XLA:CPU compile on a small box.
# Alphabetical order front-loads those compiles (test_bridge is file
# #2), so the timeout used to discard the cheap majority of the suite
# unrun.  Ordering by compile weight — stdlib/numpy/ctypes files
# first, light-jit files next, multi-minute-trace files after —
# degrades a timeout to "expensive tail cut", not "most of the suite
# never ran".  Files keep their internal order; sort is stable.
_CHEAP = (          # no XLA compiles (stdlib / numpy / ctypes / refs)
    "test_admission_mc.py",
    "test_analysis.py",
    "test_bench_deadline.py", "test_bls_pairing_host.py",
    "test_budget.py", "test_capi_fuzz.py",
    "test_cli_shims.py", "test_distributed.py",
    "test_ed25519_ref.py", "test_elastic.py", "test_executor.py",
    "test_membership_mc.py", "test_modelcheck.py",
    "test_native_admission.py",
    "test_native_core.py",
    "test_native_ingest.py", "test_observability.py",
    "test_pallas_field.py",       # kernel differentials: small
    #                               interpret compiles, seconds total
    "test_round_votes.py",
    "test_schedcheck.py",
    "test_serve.py", "test_serve_cache.py", "test_serve_threaded.py",
    "test_state_machine.py",
    "test_tpu_holders.py",
    "test_validators.py", "test_value_flood.py",
    "test_vote_executor.py",
)
_HEAVY = (          # multi-minute verify/sharded traces per test
    "test_bridge.py", "test_harness.py", "test_msm.py",
    "test_serve_pipeline.py", "test_sharded.py", "test_step.py",
    "test_step_seq.py", "test_step_signed.py", "test_utils.py",
)


def pytest_collection_modifyitems(config, items):
    import pytest

    def group(item):
        name = item.fspath.basename
        if name == _WRAPPER:
            return (9, 0)           # child-interpreter re-runs: last
        try:
            return (8, _ISOLATED.index(name))
        except ValueError:
            pass
        if name in _CHEAP:
            return (0, 0)
        return (2, 0) if name in _HEAVY else (1, 0)

    items.sort(key=group)   # stable: original order within each group
    wrapper_collected = any(it.fspath.basename == _WRAPPER
                            for it in items)
    # Only swap inline runs for child runs when the wrapper is actually
    # in this run — a targeted `pytest tests/test_pallas_verify.py`
    # (fresh process, no prior compiles) runs inline and stays covered.
    if wrapper_collected and not os.environ.get("AGNES_HEAVY_DIRECT"):
        skip = pytest.mark.skip(
            reason="interpret-heavy: re-run in a fresh child process by "
                   "test_zz_heavy_isolated.py (AGNES_HEAVY_DIRECT=1 "
                   "runs it inline)")
        for it in items:
            if it.fspath.basename in _ISOLATED:
                it.add_marker(skip)
