"""Run the interpret-heavy crypto test files in FRESH child
interpreters, one per file.

Why: a ~100k-op interpret-mode Pallas compile segfaults XLA:CPU once
the process has already performed a few hundred compiles (conftest.py
has the incident history; utils/compile_cache.py the wider post-mortem)
— so the full suite skips those files in-process (conftest marks them)
and this wrapper, ordered last, re-runs each in a clean process, where
they are reliably green.  Each child pays its own compiles; the skip +
child pair keeps `pytest tests/ -x -q` deterministic in ONE invocation.
"""

import os
import subprocess
import sys

import pytest

from conftest import _ISOLATED   # pytest puts tests/ on sys.path

_HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.mark.skipif(bool(os.environ.get("AGNES_HEAVY_DIRECT")),
                    reason="AGNES_HEAVY_DIRECT=1: heavy files already "
                           "ran inline; don't run them twice")
@pytest.mark.parametrize("fname", _ISOLATED)
def test_isolated_file(fname):
    env = dict(os.environ, AGNES_HEAVY_DIRECT="1")
    try:
        r = subprocess.run(
            [sys.executable, "-m", "pytest", os.path.join(_HERE, fname),
             "-x", "-q", "-p", "no:cacheprovider"],
            env=env, capture_output=True, text=True,
            cwd=os.path.dirname(_HERE), timeout=3600)
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"")[-2000:] if e.stdout else b""
        pytest.fail(f"[{fname}] child timed out after 3600s "
                    f"(hung backend init? see conftest import order); "
                    f"tail: {out!r}")
    tail = r.stdout[-3000:] + ("\n--- stderr:\n" + r.stderr[-1500:]
                               if r.returncode else "")
    sys.stdout.write(f"[{fname}] rc={r.returncode}\n{tail}\n")
    assert r.returncode == 0
