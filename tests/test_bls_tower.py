"""Fp2/Fp6/Fp12 tower + device pairing differentials vs `bls_ref`
(ISSUE 13).

Cheap tests run one eager op each (seconds: the stacked limb kernel
makes an eager Fp12 multiply ONE batched Barrett dispatch); the
random+edge grids and the Miller/final-exponentiation pins are
slow-marked per the tier-1 budget — the flagship serve-level
differential (device pairing == host pairing == per-vote Ed25519,
leaf-for-leaf, forged fallback included) lives in test_bls.py."""

import numpy as np
import pytest

from agnes_tpu.crypto import bls_ref as ref

P = ref.P


def _rnd12(rng):
    return ref.FQ12([int.from_bytes(rng.bytes(47), "big")
                     for _ in range(12)])


def _dev(e):
    import jax.numpy as jnp

    from agnes_tpu.crypto import bls_tower_jax as T

    return T.fv12_in(jnp.asarray(T.pack_fq12(e)))


def _host(x):
    from agnes_tpu.crypto import bls_tower_jax as T

    return T.unpack_fq12(np.asarray(T.fv12_out(x)))


def _unitary(e):
    """A cyclotomic-subgroup element without a full final exp:
    t = e^(p^6-1) (conj * inv), then t^(p^2+1) (frob^2 * mul) — the
    subgroup the csq formulas and the hard part live in."""
    t = (e ** (P ** 6)) * e.inv()
    return (t ** (P ** 2)) * t


def test_pack_unpack_roundtrip_and_edges():
    from agnes_tpu.crypto import bls_tower_jax as T

    rng = np.random.default_rng(3)
    for e in (_rnd12(rng), ref.FQ12.one(), ref.FQ12.zero(),
              ref.FQ12([P - 1] * 12)):
        assert T.unpack_fq12(T.pack_fq12(e)) == e


def test_fv12_mul_conj_frob_inverse_cheap():
    from agnes_tpu.crypto import bls_tower_jax as T

    rng = np.random.default_rng(5)
    e1, e2 = _rnd12(rng), _rnd12(rng)
    assert _host(T.fv12_mul(_dev(e1), _dev(e2))) == e1 * e2
    assert _host(T.fv12_conj(_dev(e1))) == e1 ** (P ** 6)
    assert _host(T.fv12_frob(_dev(e1))) == e1 ** P
    assert _host(T.fv12_inv(_dev(e1))) == e1.inv()
    # zero maps to zero through the Fermat chain (reject-safe, never
    # a crash)
    assert _host(T.fv12_inv(_dev(ref.FQ12.zero()))) == ref.FQ12.zero()
    # verdict helper
    assert bool(T.fv12_eq_one(_dev(ref.FQ12.one())))
    assert not bool(T.fv12_eq_one(_dev(e1)))


def test_fv2_helpers_vs_ref():
    """The Fp2 helpers the tower is built from: square (complex
    trick, 2 products), inverse (norm + Fermat chain), conjugation."""
    import jax.numpy as jnp

    from agnes_tpu.crypto import bls_field_jax as BF

    rng = np.random.default_rng(9)
    a, b = (int.from_bytes(rng.bytes(47), "big") for _ in range(2))
    x2 = ref.fq2(a, b)
    fv2 = BF.FV2(BF.fv_in(jnp.asarray(BF.to_limbs(a))),
                 BF.fv_in(jnp.asarray(BF.to_limbs(b))))

    def out(v):
        return (BF.from_limbs(np.asarray(v.c0.a)) % P,
                BF.from_limbs(np.asarray(v.c1.a)) % P)

    assert out(BF.fv2_square(fv2)) == (x2 * x2).c
    assert out(BF.fv2_inv(fv2)) == x2.inv().c
    assert out(BF.fv2_conj(fv2)) == (a % P, (-b) % P)
    zero = BF.FV2(BF.fv_in(jnp.zeros(BF.NLIMBS, jnp.int32), 1),
                  BF.fv_in(jnp.zeros(BF.NLIMBS, jnp.int32), 1))
    assert out(BF.fv2_inv(zero)) == (0, 0)        # 0 -> 0, no crash


def test_cyclotomic_square_on_unitary():
    from agnes_tpu.crypto import bls_tower_jax as T

    rng = np.random.default_rng(6)
    u = _unitary(_rnd12(rng))
    assert _host(T.fv12_cyclotomic_square(_dev(u))) == u * u
    # conj == inverse exactly on the subgroup (the chain's unitary
    # inverses rest on this)
    assert (u ** (P ** 6)) * u == ref.FQ12.one()


def test_karatsuba_vs_schoolbook_measured_choice():
    """The towering choice is MEASURED, not folklore: Karatsuba's
    runtime base-product count must beat schoolbook's at the Fp6
    level (18 vs 27 pairs), and the two recombinations must agree on
    a random product (so the cheaper one is substitutable, i.e. the
    choice is real)."""
    import jax.numpy as jnp

    from agnes_tpu.crypto import bls_field_jax as BF
    from agnes_tpu.crypto import bls_tower_jax as T

    rng = np.random.default_rng(7)
    e1, e2 = _rnd12(rng), _rnd12(rng)
    x = T.fv12_in(jnp.asarray(T.pack_fq12(e1)))
    y = T.fv12_in(jnp.asarray(T.pack_fq12(e2)))
    d0, _ = T._split(x)
    e0, _ = T._split(y)
    kar = T._fp6_mul_expand(d0, e0)
    sch = T._fp6_mul_expand_schoolbook(d0, e0)
    assert len(kar) == 18 and len(sch) == 27
    got_k = T._fp6_mul_combine(BF.fv_mul_pairs(kar))
    got_s = T._fp6_mul_combine_schoolbook(BF.fv_mul_pairs(sch))
    for a, b in zip(got_k, got_s):
        for ca, cb in zip((a.c0, a.c1), (b.c0, b.c1)):
            va = BF.from_limbs(np.asarray(ca.a)) % P
            vb = BF.from_limbs(np.asarray(cb.a)) % P
            assert va == vb


@pytest.mark.slow
def test_tower_differential_grid():
    """mul/square/inverse/frobenius on random + edge elements
    (zero, one, p-1 coefficients) — the satellite's differential
    surface, including the embedded-Fp6 path (odd w-coefficients
    zero: multiplication and inversion stay inside Fp6)."""
    from agnes_tpu.crypto import bls_tower_jax as T

    rng = np.random.default_rng(11)
    edge = [ref.FQ12.one(), ref.FQ12([P - 1] * 12),
            ref.FQ12([0, 1] + [0] * 10), _rnd12(rng), _rnd12(rng)]
    for e1 in edge:
        for e2 in edge[:3]:
            assert _host(T.fv12_mul(_dev(e1), _dev(e2))) == e1 * e2
        assert _host(T.fv12_square(_dev(e1))) == e1 * e1
        assert _host(T.fv12_frob(_dev(e1))) == e1 ** P
        assert _host(T.fv12_inv(_dev(e1))) == e1.inv()
    # embedded Fp6 (d1 = 0 <=> odd w-coeffs zero): closed under mul
    # and inverse — pins the Fp6 Karatsuba + _fp6_inv paths
    a6 = ref.FQ12([int.from_bytes(rng.bytes(47), "big") if i % 2 == 0
                   else 0 for i in range(12)])
    b6 = ref.FQ12([int.from_bytes(rng.bytes(47), "big") if i % 2 == 0
                   else 0 for i in range(12)])
    prod = a6 * b6
    assert all(prod.c[i] == 0 for i in range(1, 12, 2))
    assert _host(T.fv12_mul(_dev(a6), _dev(b6))) == prod
    inv6 = a6.inv()
    assert all(inv6.c[i] == 0 for i in range(1, 12, 2))
    assert _host(T.fv12_inv(_dev(a6))) == inv6
    # Fp2 closure the same way (only c0/c6 nonzero)
    a2 = ref.FQ12([7] + [0] * 5 + [9] + [0] * 5)
    assert _host(T.fv12_inv(_dev(a2))) == a2.inv()


@pytest.mark.slow
def test_miller_and_final_exp_vs_ref():
    """The device Miller loop equals the reference's (affine) one up
    to subfield factors — compared after the reference final
    exponentiation — and the device final exponentiation is EXACTLY
    the cube of the reference's (the documented 3H chain), on a
    known pair and under arbitrary projective scaling."""
    import jax.numpy as jnp

    from agnes_tpu.crypto import bls_pairing_jax as PJ
    from agnes_tpu.crypto import bls_tower_jax as T

    Q = ref.point_mul(5, ref.G2)
    Pt = ref.point_mul(7, ref.G1)
    f_ref = ref.miller_loop(ref._twist(Q), ref._cast_g1(Pt))
    want = ref.final_exponentiate(f_ref)

    f_dev = PJ.miller_loop(jnp.asarray(PJ.pack_g2_proj(Q)),
                           jnp.asarray(PJ.pack_g1_proj(Pt)))
    got = _host(PJ._red12(f_dev))
    assert ref.final_exponentiate(got) == want

    fe = PJ.final_exponentiate(_dev(f_ref))
    assert _host(PJ._red12(fe)) == want * want * want

    # projective scaling of BOTH inputs changes nothing (the MSM's
    # outputs arrive projective)
    lam = ref.fq2(3, 9)
    qp = PJ.pack_g2_proj((Q[0] * lam, Q[1] * lam))
    from agnes_tpu.crypto import bls_field_jax as BF

    qp[2, 0] = BF.to_limbs(3)
    qp[2, 1] = BF.to_limbs(9)
    pp = PJ.pack_g1_proj((Pt[0] * 11 % P, Pt[1] * 11 % P))
    pp[2] = BF.to_limbs(11)
    f_dev2 = PJ.miller_loop(jnp.asarray(qp), jnp.asarray(pp))
    assert ref.final_exponentiate(_host(PJ._red12(f_dev2))) == want
