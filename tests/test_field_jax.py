"""GF(2^255-19) limb arithmetic vs plain Python ints (the oracle)."""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from agnes_tpu.crypto import field_jax as F

P = F.P
rng = random.Random(1234)


def _cases(n):
    special = [0, 1, 2, 19, P - 1, P, P + 1, 2 * P - 1, (1 << 255) - 1,
               (1 << 256) - 1, (1 << 260) - 1]
    return special + [rng.randrange(1 << 260) for _ in range(n)]


def _batch(ints):
    return jnp.stack([F.to_limbs(x) for x in ints])


def test_roundtrip():
    xs = _cases(16)
    limbs = _batch(xs)
    for i, x in enumerate(xs):
        assert F.from_limbs(limbs[i]) == x


@pytest.mark.parametrize("op,ref", [
    ("add", lambda a, b: (a + b) % P),
    ("sub", lambda a, b: (a - b) % P),
    ("mul", lambda a, b: (a * b) % P),
])
def test_binary_ops(op, ref):
    xs, ys = _cases(24), list(reversed(_cases(24)))
    a, b = _batch(xs), _batch(ys)
    out = jax.jit(getattr(F, op))(a, b)
    frozen = F.freeze(out)
    for i, (x, y) in enumerate(zip(xs, ys)):
        got = F.from_limbs(frozen[i])
        assert got == ref(x, y), f"{op}[{i}]: {x} . {y} -> {got}"
    # limbs stay weakly normalized (safe as inputs to a further mul):
    # signed representation, |limb| <= 8800 (module docstring bounds)
    assert np.abs(np.asarray(out)).max() <= 8800


def test_freeze_canonical():
    xs = _cases(16)
    frozen = F.freeze(_batch(xs))
    for i, x in enumerate(xs):
        assert F.from_limbs(frozen[i]) == x % P


def test_inv():
    xs = [x for x in _cases(6) if x % P != 0]
    a = _batch(xs)
    out = F.freeze(jax.jit(F.inv)(a))
    for i, x in enumerate(xs):
        assert F.from_limbs(out[i]) == pow(x, P - 2, P)


def test_chained_ops_stay_bounded():
    """Long chains (like a 255-squaring pow) must not overflow int32."""
    x = _batch([rng.randrange(1 << 260) for _ in range(4)])
    acc = x
    ref = [F.from_limbs(x[i]) for i in range(4)]
    for _ in range(30):
        acc = F.mul(F.add(acc, x), acc)
        ref = [((r + s) * r) % P for r, s in zip(ref, [F.from_limbs(x[i])
                                                      for i in range(4)])]
    frozen = F.freeze(acc)
    for i in range(4):
        assert F.from_limbs(frozen[i]) == ref[i]


def test_bytes_conversion():
    xs = [rng.randrange(1 << 255) for _ in range(8)]
    raw = np.zeros((8, 32), np.int32)
    for i, x in enumerate(xs):
        raw[i] = np.frombuffer(x.to_bytes(32, "little"), np.uint8)
    limbs = F.bytes32_to_limbs(jnp.asarray(raw))
    for i, x in enumerate(xs):
        assert F.from_limbs(limbs[i]) == x
    back = F.limbs_to_bytes32(F.freeze(limbs))
    for i, x in enumerate(xs):
        assert bytes(np.asarray(back[i], np.uint8).tobytes()) == \
            (x % P).to_bytes(32, "little")
