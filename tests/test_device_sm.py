"""Exhaustive differential test: device state machine vs python oracle.

Enumerates the full Step × EventTag space crossed with the guard-relevant
state/payload configurations (round relation, lock/valid configs,
pol_round validity) — every reference match arm and every guard polarity
is hit many times.  ~25k cases run as ONE vmapped device call.
"""

import itertools

import jax.numpy as jnp
import numpy as np
import pytest

from agnes_tpu.core import state_machine as sm
from agnes_tpu.core.state_machine import Event, EventTag, Step
from agnes_tpu.device.encoding import (
    decode_message,
    decode_state,
    encode_event,
    encode_state,
    stack_pytree,
)
from agnes_tpu.device.state_machine import apply_batch
from agnes_tpu.types import NIL_ID

VAL, OTHER = 7, 9


def _cases():
    rounds = [0, 2]
    lock_cfgs = [None, (0, VAL), (1, VAL), (0, OTHER), (2, OTHER)]
    valid_cfgs = [None, (0, VAL)]
    pol_rounds = [-2, -1, 0, 1]
    values = [VAL, OTHER]
    for (step, tag, s_round, lock, valid) in itertools.product(
            Step, EventTag, rounds, lock_cfgs, valid_cfgs):
        state = sm.State(
            height=1, round=s_round, step=step,
            locked=sm.RoundValue(*lock) if lock else None,
            valid=sm.RoundValue(*valid) if valid else None)
        for ev_round in (s_round - 1, s_round, s_round + 1):
            if ev_round < 0:
                continue
            if tag == EventTag.PROPOSAL:
                for pol, v in itertools.product(pol_rounds, values):
                    yield state, ev_round, Event.proposal(pol, v)
            elif tag in (EventTag.NEW_ROUND_PROPOSER, EventTag.POLKA_VALUE,
                         EventTag.PRECOMMIT_VALUE):
                for v in values:
                    yield state, ev_round, Event(tag, value=v)
            else:
                yield state, ev_round, Event(tag)


def test_exhaustive_differential():
    cases = list(_cases())
    assert len(cases) > 5000  # full Step×Event×guard enumeration

    # oracle outputs
    expected = [sm.apply(s, r, ev) for (s, r, ev) in cases]

    # one batched device call
    batch_state = stack_pytree([encode_state(s) for (s, _, _) in cases])
    batch_event = stack_pytree([encode_event(r, ev) for (_, r, ev) in cases])
    out_state, out_msg = apply_batch(batch_state, batch_event)

    os = [np.asarray(x) for x in out_state]
    om = [np.asarray(x) for x in out_msg]

    mismatches = 0
    for i, ((s0, r, ev), (exp_s, exp_m)) in enumerate(zip(cases, expected)):
        got_s = decode_state(
            type(out_state)(*[leaf[i] for leaf in os]), height=1)
        got_m = decode_message(type(out_msg)(*[leaf[i] for leaf in om]))
        # python oracle keeps height; device state has no height field
        exp_cmp = sm.State(height=1, round=exp_s.round, step=exp_s.step,
                           locked=exp_s.locked, valid=exp_s.valid)
        # device flattens locked/valid: a lock set then never read keeps its
        # encoding; decode_state reproduces it exactly, so compare directly
        if got_s != exp_cmp or got_m != exp_m:
            mismatches += 1
            if mismatches <= 5:
                print(f"case {i}: state={s0} round={r} ev={ev}")
                print(f"  expected: {exp_cmp} / {exp_m}")
                print(f"  got:      {got_s} / {got_m}")
    assert mismatches == 0, f"{mismatches} mismatching cases"


def test_device_happy_case():
    """The reference's shipped trace through the device path
    (state_machine.rs:331-345)."""
    s = encode_state(sm.State.new(1))
    trace = [
        (0, Event.new_round_proposer(VAL)),
        (0, Event.proposal(-1, VAL)),
        (0, Event.polka_value(VAL)),
        (0, Event.precommit_value(VAL)),
    ]
    msgs = []
    for r, ev in trace:
        s, m = apply_batch(
            type(s)(*[jnp.asarray(x)[None] for x in s]),
            type(encode_event(r, ev))(
                *[jnp.asarray(x)[None] for x in encode_event(r, ev)]))
        s = type(s)(*[x[0] for x in s])
        msgs.append(decode_message(type(m)(*[x[0] for x in m])))
    assert msgs[0] == sm.Message.proposal_msg(0, VAL, -1)
    assert msgs[1] == sm.Message.prevote(0, VAL)
    assert msgs[2] == sm.Message.precommit(0, VAL)
    assert msgs[3] == sm.Message.decision_msg(0, VAL)
    assert int(s.step) == int(Step.COMMIT)
