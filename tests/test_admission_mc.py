"""Serve-plane admission model checker (analysis/admission_mc.py,
ISSUE 7) — model soundness, mutation detection, corpus determinism,
and the replay of admission schedules through the REAL ServePipeline
with a stubbed dispatch (the PR 4/5 registry-stub pattern).

The model itself is pure numpy/stdlib with ZERO jax imports (asserted
below); the serve-replay half imports jax for driver/batcher
construction but performs ZERO XLA compiles (dispatch stubbed), so the
file sits in conftest._CHEAP.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from agnes_tpu.analysis import admission_mc as am
from agnes_tpu.analysis import modelcheck as mc

CORPUS_DIR = os.path.join(os.path.dirname(__file__), "corpus",
                          "admission")


# ---------------------------------------------------------------------------
# zero-jax guarantee (the ci.sh gate slot depends on it)
# ---------------------------------------------------------------------------


def test_admission_model_is_jax_free():
    code = (
        "import sys\n"
        "from agnes_tpu.analysis import admission_mc as am\n"
        "rep = am.explore_admission(am.AdmissionMCConfig("
        "name='t', depth=5))\n"
        "assert rep.states > 10 and not rep.violations\n"
        "assert 'jax' not in sys.modules, 'jax leaked into the model'\n"
        "print('JAXFREE-OK')\n")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0 and "JAXFREE-OK" in out.stdout, (
        out.stdout, out.stderr)


# ---------------------------------------------------------------------------
# honest model: exhaustive-clean, deterministic, conserving
# ---------------------------------------------------------------------------


def test_tiny_scope_explores_clean_and_deterministic():
    cfg = am.ADMISSION_TINY[0]
    a = am.explore_admission(cfg, collect_digests=True)
    b = am.explore_admission(cfg, collect_digests=True)
    assert a.complete and not a.violations
    assert a.states > 1000
    assert (a.states, a.transitions, a.digests) == \
        (b.states, b.transitions, b.digests)


def test_drop_oldest_evictions_stay_conserved():
    """drop_oldest sheds admitted records — the conservation monitor
    must count them as counted drops, not losses."""
    cfg = am.AdmissionMCConfig(
        name="evict", capacity=2, policy="drop_oldest", depth=6,
        max_copies=2, target=1,
        templates=((0, 0, 0, 0), (1, 1, 0, 0), (1, 2, 0, 0)))
    sys_, viols = am.run_admission_with_monitors(
        cfg, [("s", 0), ("s", 1), ("s", 2), ("b",)])
    assert not viols
    assert sum(sys_.evicted) == 1        # capacity 2, third submit shed
    assert sys_.queue.counters["evicted"] == 1


def test_held_window_reentry_and_split_purity():
    """The held-vote window milestone by hand: a future-round record
    holds through pumps, re-enters on ("w",), and the dedup round trip
    dispatches identical bytes unsigned — with every unsigned row a
    cache hit."""
    cfg = am.ADMISSION_SMOKE[0]
    sched = [("s", 3), ("b",), ("b",)]     # held: round 1, window 0
    sys_, viols = am.run_admission_with_monitors(cfg, sched)
    assert not viols
    assert sys_.dispatched[3] == 0 and len(sys_.pending) == 1
    sys_.run_schedule([("w",), ("b",)])
    assert sys_.dispatched[3] == 1
    # dedup round trip
    sys2, viols2 = am.run_admission_with_monitors(
        cfg, [("s", 0), ("s", 1), ("b",), ("v",),
              ("s", 0), ("s", 1), ("b",)])
    assert not viols2
    unsigned = [(p, rows) for p, signed, _c, rows in sys2.dispatch_log
                if not signed]
    assert unsigned, "cache hits should ride an unsigned dispatch"
    for p, rows in unsigned:
        assert p in (2, 3)
        assert all(ver for _k, ver in rows)


# ---------------------------------------------------------------------------
# mutation self-test: every monitor has teeth
# ---------------------------------------------------------------------------


def test_admission_self_test_end_to_end():
    out = am.self_test_admission()
    assert set(out) == set(am.ADMISSION_MUTANTS)
    for name, r in out.items():
        assert r["minimized_len"] <= r["schedule_len"]
        ce = r["counterexample"]
        assert ce["schedule"], name
        # 1-minimality of the lossy counterexample is cheap to prove
    name = "lose_drained_record"
    sys_cls, prop, cfg = am.ADMISSION_MUTANTS[name]
    ce = out[name]["counterexample"]
    small = [am.AdmissionSystem.action_from_json(a)
             for a in ce["schedule"]]
    for i in range(len(small)):
        trial = small[:i] + small[i + 1:]
        assert not trial or not am.admission_reproduces(
            cfg, trial, prop, system_cls=sys_cls)


def test_starvation_monitor_catches_lifo_queue():
    sys_cls, prop, cfg = am.ADMISSION_MUTANTS["starve_oldest_record"]
    rep = am.explore_admission(cfg, system_cls=sys_cls)
    caught = [c for c in rep.violations if c.violation.property == prop]
    assert caught, f"missed starvation in {rep.states} states"
    small = am.minimize_admission(cfg, caught[0].schedule, prop,
                                  system_cls=sys_cls)
    assert am.admission_reproduces(cfg, small, prop,
                                   system_cls=sys_cls)
    _, honest = am.run_admission_with_monitors(cfg, small)
    assert not honest


# ---------------------------------------------------------------------------
# regression corpus (tests/corpus/admission/*.json)
# ---------------------------------------------------------------------------


def test_admission_corpus_exists_and_covers():
    entries = mc.load_corpus(CORPUS_DIR)
    names = {e["name"] for e in entries}
    assert len(entries) >= 6, names
    assert {n for n in names if n.startswith("adm_mut_")} == {
        f"adm_mut_{m}" for m in am.ADMISSION_MUTANTS}
    assert "adm_dedup_roundtrip" in names
    assert "adm_held_window_flush" in names
    assert all(e["kind"] == "admission" for e in entries)


@pytest.mark.parametrize("entry", mc.load_corpus(CORPUS_DIR),
                         ids=lambda e: e["name"])
def test_admission_corpus_replays_deterministically(entry):
    sys_, _ = am.replay_admission_entry(entry)
    sys2, _ = am.replay_admission_entry(entry)
    assert sys_.mc_digest() == sys2.mc_digest()


# ---------------------------------------------------------------------------
# serve-plane replay: the model's schedules through the REAL
# ServePipeline (stubbed dispatch — zero XLA compiles)
# ---------------------------------------------------------------------------


def _real_service(cfg: am.AdmissionMCConfig,
                  native_admission: bool = False,
                  native_shards: int = 1):
    """A VoteService assembled from the REAL queue/batcher/pipeline
    with step_async stubbed (test_serve_cache.py pattern) and a
    1-round batcher window so the model's held-vote semantics map
    onto the real hold-back path.  `native_admission=True` swaps in
    the C++ admission front-end (ISSUE 14); `native_shards>1` the
    sharded group (ISSUE 20) — the conformance differentials drive
    both and assert leaf-for-leaf equality."""
    from agnes_tpu.bridge import VoteBatcher
    from agnes_tpu.harness.device_driver import DeviceDriver
    from agnes_tpu.harness.fixtures import (
        deterministic_seeds,
        validator_pubkeys,
    )
    from agnes_tpu.serve import ShapeLadder, VerifiedCache, VoteService

    from agnes_tpu.crypto.ed25519_ref import verify as ref_verify

    I = cfg.n_instances
    V = max(t[1] for t in cfg.templates) + 1
    d = DeviceDriver(I, V, advance_height=True, defer_collect=True)
    bat = VoteBatcher(I, V, n_slots=4, n_rounds=1)

    def host_verify(b, pubkeys):
        # the real batcher batch-verifies host-fallback subsets on the
        # JAX plane — a multi-minute Ed25519 trace on this box and a
        # compile this zero-compile file must not pay.  The model's
        # records carry REAL ref-signer signatures, so verify them
        # with the pure-python ref instead: same verdicts, no XLA.
        from agnes_tpu.crypto.encoding import vote_signing_bytes

        out = np.zeros(len(b), bool)
        for j in range(len(b)):
            msg = vote_signing_bytes(
                int(b.height[j]), int(b.round[j]), int(b.typ[j]),
                None if int(b.value[j]) < 0 else int(b.value[j]))
            pk = bytes(np.asarray(pubkeys[int(b.validator[j])],
                                  np.uint8))
            out[j] = ref_verify(pk, msg, bytes(b.signature[j]))
        return out

    bat._verify = host_verify
    window = {"base": np.zeros(I, np.int64)}
    svc = VoteService(
        d, bat, validator_pubkeys(deterministic_seeds(V)),
        dedup_cache=VerifiedCache() if cfg.dedup else None,
        capacity=cfg.capacity, instance_cap=cfg.instance_cap,
        overload_policy=cfg.policy, target_votes=cfg.target,
        max_delay_s=0.0,
        native_admission=native_admission,
        native_shards=native_shards,
        ladder=ShapeLadder.plan(I, V, min_rung=4),
        window_predictor=lambda: (window["base"].copy(),
                                  np.zeros(I, np.int64)))
    dispatches = []

    def stub(phases, lanes=None, exts=None, donate=True, tick=None):
        dispatches.append(
            (len(phases), lanes is None,
             tuple(np.asarray(p.slots).tobytes() for p in phases)))
        d.last_step_rejects = (None if lanes is None
                               else np.zeros((), np.int64))

    d.step_async = stub
    return svc, window, dispatches


def _replay_on_serve(cfg: am.AdmissionMCConfig, actions,
                     native_admission: bool = False):
    """Drive the real serve plane through an admission schedule:
    submit/pump/settle/window map onto the production calls."""
    sys_model = am.AdmissionSystem(cfg)      # for the wire bytes
    svc, window, dispatches = _real_service(
        cfg, native_admission=native_admission)
    for a in actions:
        act = am.AdmissionSystem.action_from_json(a) \
            if a and a[0] in am._ACT_CODES else tuple(a)
        if act[0] == "s":
            svc.submit(sys_model._wire[act[1]])
        elif act[0] == "b":
            batch = svc._close_batch()
            svc._pump_batch(batch)
            svc.pipeline.dispatch_staged()
        elif act[0] == "v":
            svc.poll_decisions()
        elif act[0] == "w":
            window["base"][:] = window["base"] + 1
    return svc, dispatches


@pytest.mark.parametrize(
    "entry",
    [e for e in mc.load_corpus(CORPUS_DIR)],
    ids=lambda e: e["name"])
def test_admission_corpus_replays_through_real_serve_plane(entry):
    """Every admission corpus schedule drives the REAL pipeline
    bit-identically across two runs, respects the P in {2, 3} bound
    on every stubbed dispatch, keeps admitted-vote conservation, and
    rides unsigned entries only for cache-verified traffic."""
    cfg = am.AdmissionMCConfig.from_json(entry["config"])
    svc, disp1 = _replay_on_serve(cfg, entry["actions"])
    _svc2, disp2 = _replay_on_serve(cfg, entry["actions"])
    assert disp1 == disp2, "serve replay not bit-identical"
    # the warmed-shape P bound applies to the signed-lane and
    # preverified entries; host-fallback builds (past-round spill
    # after a window advance) legitimately dispatch other P on the
    # host-verified path — scope the assertion the way the production
    # warmup does
    if svc.pipeline.host_fallback_builds == 0:
        for n_phases, unsigned, _blobs in disp1:
            assert n_phases in (2, 3), (entry["name"], n_phases)
    # conservation on the real plane: every admitted vote is either
    # dispatched, still queued, pending, or held — no silent loss.
    # Votes the batcher routed to its past-round HOST tally are
    # consumed there (and deduplicated), so exact equality holds only
    # when that path stayed empty.
    q = svc.queue.counters
    admitted = q["admitted"]
    accounted = (svc.pipeline.dispatched_votes + svc.queue.depth
                 + svc.batcher.pending_votes + svc.batcher.held_votes)
    if not svc.batcher._host_tally \
            and svc.batcher.rejected_signature == 0:
        assert admitted == accounted, (entry["name"], admitted,
                                       accounted, dict(q))
    else:
        assert admitted >= accounted, (entry["name"], admitted,
                                       accounted)
    assert svc.batcher.rejected_malformed == 0, entry["name"]
    # absent host fallbacks, unsigned dispatches exist only where the
    # pipeline dispatched pre-verified rows (the split-rung purity
    # story); host-fallback builds also ride the unsigned entries but
    # their rows were HOST-verified, which is its own covered path
    if any(u for _p, u, _b in disp1) \
            and svc.pipeline.host_fallback_builds == 0:
        assert svc.pipeline.preverified_votes > 0
        assert svc.cache is not None and svc.cache.counters["hits"] > 0


@pytest.mark.parametrize(
    "entry",
    [e for e in mc.load_corpus(CORPUS_DIR)],
    ids=lambda e: e["name"])
def test_admission_corpus_native_admission_conformance(entry):
    """ISSUE 14 conformance differential: every corpus schedule
    through native-ON vs native-OFF VoteService — dispatch streams
    bit-identical, reject taxonomy and dedup-cache counters
    leaf-for-leaf (the checker's corpus IS the admission spec, so
    the native front-end conforms by replay, not re-derivation).
    The deeper queue/column/BLS differentials live in
    tests/test_native_admission.py."""
    cfg = am.AdmissionMCConfig.from_json(entry["config"])
    svc_off, disp_off = _replay_on_serve(cfg, entry["actions"])
    svc_on, disp_on = _replay_on_serve(cfg, entry["actions"],
                                       native_admission=True)
    assert disp_on == disp_off, entry["name"]
    assert svc_on.queue.counters == svc_off.queue.counters
    assert svc_on.queue.mc_canonical()[0] == \
        svc_off.queue.mc_canonical()[0]
    if svc_off.cache is not None:
        assert svc_on.cache.counters == svc_off.cache.counters
    assert svc_on.pipeline.dispatched_votes == \
        svc_off.pipeline.dispatched_votes


def test_serve_replay_dedup_roundtrip_goes_unsigned():
    """The milestone in the flesh: fresh bytes dispatch signed; after
    settle, identical bytes dispatch UNSIGNED on the real pipeline."""
    entry = next(e for e in mc.load_corpus(CORPUS_DIR)
                 if e["name"] == "adm_dedup_roundtrip")
    cfg = am.AdmissionMCConfig.from_json(entry["config"])
    _svc, disp = _replay_on_serve(cfg, entry["actions"])
    assert any(unsigned for _p, unsigned, _b in disp)
    assert any(not unsigned for _p, unsigned, _b in disp)
