"""Pod-membership model checker (analysis/membership_mc.py, ISSUE 17)
— model soundness over the REAL MembershipEpoch, mutation detection,
corpus determinism, and the device-plane leg: every corpus entry's
recorded repartitions re-lift REAL `seq_in_specs`/`dense_lane_specs`-
shaped numpy leaves with `relift_tree` and the global assembly is
bit-identical across the boundary.

The model itself is pure numpy/stdlib with ZERO jax imports (asserted
below); the spec-tree half imports jax for the mesh + spec trees but
performs ZERO XLA compiles (pure numpy data movement), so the file
sits in conftest._CHEAP."""

import os
import subprocess
import sys

import numpy as np
import pytest

from agnes_tpu.analysis import membership_mc as mm
from agnes_tpu.analysis import modelcheck as mc

CORPUS_DIR = os.path.join(os.path.dirname(__file__), "corpus",
                          "membership")


# ---------------------------------------------------------------------------
# zero-jax guarantee (the ci.sh gate slot depends on it)
# ---------------------------------------------------------------------------


def test_membership_model_is_jax_free():
    code = (
        "import sys\n"
        "from agnes_tpu.analysis import membership_mc as mm\n"
        "rep = mm.explore_membership(mm.MembershipMCConfig("
        "name='t', depth=6))\n"
        "assert rep.states > 10 and not rep.violations\n"
        "assert 'jax' not in sys.modules, 'jax leaked into the model'\n"
        "print('JAXFREE-OK')\n")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0 and "JAXFREE-OK" in out.stdout, (
        out.stdout, out.stderr)


# ---------------------------------------------------------------------------
# honest model: exhaustive-clean, deterministic, envelope-respecting
# ---------------------------------------------------------------------------


def test_tiny_scope_explores_clean_and_deterministic():
    cfg = mm.MEMBERSHIP_TINY[0]
    a = mm.explore_membership(cfg, collect_digests=True)
    b = mm.explore_membership(cfg, collect_digests=True)
    assert a.complete and not a.violations
    assert a.states > 10
    assert (a.states, a.transitions, a.digests) == \
        (b.states, b.transitions, b.digests)


def test_sleep_only_enabled_on_even_splits():
    """The honest deployment envelope: on a 3-host pod a single leave
    keeps the split even only when 2 | n_instances — the enabled set
    must offer exactly the even-splitting departures."""
    cfg = mm.MembershipMCConfig(name="env", n_hosts=3, n_instances=6,
                                host_churn=2, max_height=1, depth=4)
    sys_ = mm.MembershipSystem(cfg)
    sleeps = [a for a in sys_.mc_enabled() if a[0] == "s"]
    assert len(sleeps) == 3           # 6 % 2 == 0: all three may leave
    # after one departure the live pair {a, b} may shrink to ONE host
    # (6 % 1 == 0) — pod-of-one is in the envelope
    assert sys_.mc_apply(("s", 2)) and sys_.mc_apply(("b",))
    assert [a for a in sys_.mc_enabled() if a[0] == "s"]
    # but a 4-instance pod of 3 hosts cannot exist at all (genesis
    # split rule), and a 6-instance pod that lost one host cannot lose
    # another on an odd count — model with 2 hosts x 3 instances each:
    # the only prospective live set after one leave has size 1 (even)
    cfg2 = mm.MembershipMCConfig(name="env2", n_hosts=2,
                                 n_instances=6, host_churn=1,
                                 max_height=1, depth=4)
    sys2 = mm.MembershipSystem(cfg2)
    assert len([a for a in sys2.mc_enabled() if a[0] == "s"]) == 2


def test_held_traffic_replays_on_readmission():
    """The sleepy-churn cycle by hand: traffic for a departed home is
    HELD (no height progress), then replays into heights at the
    readmission boundary — conservation all the way."""
    cfg = mm.MembershipMCConfig(name="cycle", n_hosts=2,
                                n_instances=2, host_churn=1,
                                max_height=3, depth=12)
    sys_, viols = mm.run_membership_with_monitors(
        cfg, [("s", 1), ("b",), ("d", 1), ("d", 1), ("d", 0)])
    assert not viols
    assert sys_.heights == [1, 0] and sys_.held == [0, 2]
    sys_.run_schedule([("w", 1), ("b",)])
    assert sys_.heights == [1, 2] and sys_.held == [0, 0]
    assert sys_.epoch.readmissions == 1
    assert not mm.membership_state_violations(sys_)


# ---------------------------------------------------------------------------
# mutation self-test: every monitor has teeth
# ---------------------------------------------------------------------------


def test_membership_self_test_end_to_end():
    out = mm.self_test_membership()
    assert set(out) == set(mm.MEMBERSHIP_MUTANTS)
    for name, r in out.items():
        assert r["minimized_len"] <= r["schedule_len"]
        assert r["counterexample"]["schedule"], name
    # 1-minimality of the overlap counterexample is cheap to prove
    name = "overlapping_range_repartition"
    sys_cls, prop, cfg = mm.MEMBERSHIP_MUTANTS[name]
    ce = out[name]["counterexample"]
    small = [mm.MembershipSystem.action_from_json(a)
             for a in ce["schedule"]]
    for i in range(len(small)):
        trial = small[:i] + small[i + 1:]
        assert not trial or not mm.membership_reproduces(
            cfg, trial, prop, system_cls=sys_cls)


def test_monotonic_monitor_catches_height_regression():
    """The third monitor's teeth without a registry mutant: a re-lift
    that rolls one height back passes conservation arithmetic only if
    it also forges `sent` — the edge monitor catches the regression
    directly."""

    class _Rollback(mm.MembershipSystem):
        def _relift_held(self, rep):
            super()._relift_held(rep)
            for i in range(self.cfg.n_instances):
                if self.heights[i]:
                    self.heights[i] -= 1
                    self.sent -= 1      # forge conservation
                    break

    cfg = mm.MembershipMCConfig(name="roll", n_hosts=2,
                                n_instances=2, host_churn=1,
                                max_height=2, depth=8)
    rep = mm.explore_membership(cfg, system_cls=_Rollback)
    caught = [c for c in rep.violations
              if c.violation.property == "monotonic"]
    assert caught, f"missed rollback in {rep.states} states"
    small = mm.minimize_membership(cfg, caught[0].schedule,
                                   "monotonic", system_cls=_Rollback)
    assert mm.membership_reproduces(cfg, small, "monotonic",
                                    system_cls=_Rollback)
    _, honest = mm.run_membership_with_monitors(cfg, small)
    assert not honest


# ---------------------------------------------------------------------------
# scope routing (the ci.sh gate aggregates membership_states from this)
# ---------------------------------------------------------------------------


def test_scope_worker_routes_membership_kind():
    cfg = mm.MEMBERSHIP_TINY[0]
    out = mc._scope_worker({"config": cfg.to_json(), "por": False,
                            "deadline_at": None})
    assert out["kind"] == "membership"
    assert out["config"] == cfg.name
    assert out["complete"] and out["states"] > 10
    assert not out["violations"]


# ---------------------------------------------------------------------------
# regression corpus (tests/corpus/membership/*.json)
# ---------------------------------------------------------------------------


def test_membership_corpus_exists_and_covers():
    entries = mc.load_corpus(CORPUS_DIR)
    names = {e["name"] for e in entries}
    assert len(entries) >= 5, names
    assert {n for n in names if n.startswith("mem_mut_")} == {
        f"mem_mut_{m}" for m in mm.MEMBERSHIP_MUTANTS}
    assert set(mm.MEMBERSHIP_MILESTONES) <= names
    assert all(e["kind"] == "membership" for e in entries)
    # every milestone with traffic+boundaries records its repartitions
    by_name = {e["name"]: e for e in entries}
    assert by_name["mem_leave_hold_rejoin_replay"][
        "expect"]["repartitions"]


@pytest.mark.parametrize("entry", mc.load_corpus(CORPUS_DIR),
                         ids=lambda e: e["name"])
def test_membership_corpus_replays_deterministically(entry):
    sys_, _ = mm.replay_membership_entry(entry)
    sys2, _ = mm.replay_membership_entry(entry)
    assert sys_.mc_digest() == sys2.mc_digest()


def test_mutant_corpus_entries_are_honest_clean():
    for e in mc.load_corpus(CORPUS_DIR):
        if e["name"].startswith("mem_mut_"):
            assert e["expect"]["violations"] == [], e["name"]


# ---------------------------------------------------------------------------
# device-plane leg: every recorded repartition re-lifts REAL spec-tree
# shaped leaves bit-identically (zero XLA compiles — pure numpy moves)
# ---------------------------------------------------------------------------


def _spec_leaves():
    """Flatten the production seq/dense spec trees the way the
    multi-host driver does (DistributedDriver._lift_tree), and map
    each leaf to its instance axis with the production
    `instance_axis_of` — one source of truth with the dispatch
    lift."""
    import jax
    from jax.sharding import PartitionSpec

    from agnes_tpu.distributed.membership import instance_axis_of
    from agnes_tpu.parallel import make_mesh
    from agnes_tpu.parallel.mesh import DATA_AXIS, SLICE_AXIS
    from agnes_tpu.parallel.sharded import dense_lane_specs, seq_in_specs

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    mesh = make_mesh(2, 4)
    specs = jax.tree.leaves(
        (seq_in_specs(mesh), dense_lane_specs(mesh)),
        is_leaf=lambda x: isinstance(x, PartitionSpec))
    axes = [instance_axis_of(tuple(s), (SLICE_AXIS, DATA_AXIS))
            for s in specs]
    assert any(a is not None for a in axes)     # instance-dim leaves
    assert any(a is None for a in axes)         # replicated leaves
    return specs, axes


def _ranges_of(triples):
    return {h: (lo, hi) for h, lo, hi in triples}


@pytest.mark.parametrize(
    "entry",
    [e for e in mc.load_corpus(CORPUS_DIR)
     if e["expect"]["repartitions"]],
    ids=lambda e: e["name"])
def test_membership_corpus_repartitions_relift_real_spec_trees(entry):
    """For every repartition the corpus entry's honest replay crossed,
    slice distinctive global leaves (one per production spec leaf) into
    per-host blocks on the OLD partition, `relift_tree` them onto the
    NEW one, and assert the global assembly is bit-identical — the
    no-decision-loss contract on the exact leaf layout the elastic
    driver re-lifts at a live boundary.  The round trip back must
    restore the original blocks."""
    from agnes_tpu.distributed.membership import relift_tree

    specs, axes = _spec_leaves()
    n = entry["config"]["n_instances"]
    rng = np.random.default_rng(7)
    for rep in entry["expect"]["repartitions"]:
        old = _ranges_of(rep["old"])
        new = _ranges_of(rep["new"])
        # one global leaf per spec leaf: rank = the spec's constrained
        # rank, instance dim sized n, other dims small but distinct
        globals_, per_leaf_shape = [], []
        for k, (spec, ax) in enumerate(zip(specs, axes)):
            rank = max(len(tuple(spec)), 1)
            shape = [2 + (k + d) % 3 for d in range(rank)]
            if ax is not None:
                shape[ax] = n
            g = rng.integers(0, 2**31, size=shape).astype(np.int64)
            globals_.append(g)
            per_leaf_shape.append(shape)
        blocks = {
            h: [g if ax is None
                else np.ascontiguousarray(np.take(
                    g, np.arange(lo, hi), axis=ax))
                for g, ax in zip(globals_, axes)]
            for h, (lo, hi) in old.items()}
        out = relift_tree(blocks, old, new, axes)
        assert set(out) == set(new)
        for k, (g, ax) in enumerate(zip(globals_, axes)):
            if ax is None:
                for h in new:
                    np.testing.assert_array_equal(out[h][k], g)
                continue
            assembled = np.empty_like(g)
            for h, (lo, hi) in new.items():
                sel = [slice(None)] * g.ndim
                sel[ax] = slice(lo, hi)
                assembled[tuple(sel)] = out[h][k]
            np.testing.assert_array_equal(assembled, g)
        back = relift_tree(out, new, old, axes)
        for h in old:
            for k in range(len(globals_)):
                np.testing.assert_array_equal(back[h][k],
                                              blocks[h][k])


def test_emit_membership_corpus_is_deterministic(tmp_path):
    import json

    d1, d2 = tmp_path / "a", tmp_path / "b"
    mm.emit_membership_corpus(str(d1))
    mm.emit_membership_corpus(str(d2))
    files1 = sorted(os.listdir(d1))
    assert files1 == sorted(os.listdir(d2))
    for fn in files1:
        assert (d1 / fn).read_text() == (d2 / fn).read_text()
    # and the committed corpus matches a fresh emission (drift gate)
    for fn in files1:
        committed = os.path.join(CORPUS_DIR, fn)
        assert os.path.exists(committed), fn
        assert json.loads((d1 / fn).read_text()) == \
            json.load(open(committed)), f"{fn}: corpus drifted"
