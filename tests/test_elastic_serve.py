"""Elastic pod membership plane: the spawned differential (ISSUE 17
acceptance).

Every plane runs in its OWN child interpreter (spawn_pod), composing
with the XLA:CPU child-interpreter discipline (tests/conftest.py):
two jax.distributed pod processes driven through ElasticShard's
negotiated ticks — deliberately HETEROGENEOUS per-host traffic (the
hosts close different batch shapes every tick; the per-tick
max-merge + padding keeps lockstep) plus ONE host leave + rejoin
cycle across membership epoch boundaries (the survivor adopts the
sleeper's ranges, holds its gossip and re-routes it through the
readmission boundary's frame) — one single-process mesh-serve
comparison over the SAME global mesh shape, and one offline fused
dense reference.  The parent never touches jax — elasticity must
change NO decision and NO state leaf.

Slow: each child pays its own compiles (the persistent cache is
deliberately off), and the elastic workers warm TWO phase shapes.
"""

import numpy as np
import pytest

I, V, HEIGHTS = 4, 4, 4
N_HOSTS, DPH, N_VAL = 2, 2, 2
LEAVE, REJOIN = 2, 3            # host 1 absent for height 2


@pytest.mark.slow
def test_elastic_pod_bit_identical_through_membership_cycle(tmp_path):
    """2-process elastic pod (heterogeneous traffic + leave/rejoin
    cycle) == single-process mesh serve == offline fused: state/tally
    leaf-for-leaf, height-stamped decision rows identical across
    hosts, zero unexpected retraces, zero unwarmed compiles (the two
    warmed phase shapes are the ONLY compiled entries), a completed
    membership cycle with the held gossip re-routed and none of it
    dropped, and the membership trail readable off the merged pod
    postmortem."""
    from agnes_tpu.distributed.smoke import spawn_pod
    from agnes_tpu.utils.metrics_cli import main as metrics_main

    res = spawn_pod(N_HOSTS, instances=I, validators=V,
                    heights=HEIGHTS, devices_per_host=DPH,
                    n_val=N_VAL, out_dir=str(tmp_path),
                    timeout_s=2500, heartbeat=True, dump_state=True,
                    elastic=True, leave_height=LEAVE,
                    rejoin_height=REJOIN,
                    extra_modes=["single", "offline"])
    assert not res["killed"], res["paths"]
    for rec in res["pod"] + [res["single"], res["offline"]]:
        assert "error" not in rec, (rec, res["paths"])

    n_sleeper_local = (I // N_HOSTS) * V
    held = 2 * n_sleeper_local * (REJOIN - LEAVE)   # both classes
    for rec in res["pod"]:
        # the serve-plane invariants the static pod also holds
        assert rec["retrace_unexpected"] == 0, rec
        assert rec["rejected_signature_device"] == 0, rec
        assert rec["offladder_builds"] == 0, rec
        assert rec["host_fallback_builds"] == 0, rec
        assert rec["compile_entries"] == ["sharded_step_seq_signed"], \
            rec
        # negotiation pads ONLY onto warmed shapes: P=2 and P=3 both
        # warmed, nothing else ever compiled (retrace==0 above)
        assert rec["warmed_shapes"] == 2, rec
        assert rec["padded_slots"] > 0, rec
        # elastic routing: nothing was foreign (the survivor ADOPTS
        # the sleeper's ranges instead of rejecting its gossip)
        assert rec["foreign_rejects"] == 0, rec
        assert rec["held_dropped"] == 0, rec
        assert rec["held_pending"] == 0, rec
        # the membership cycle COMPLETED on every host: leave
        # boundary + readmission boundary, one epoch each
        assert rec["boundaries"] == 2, rec
        assert rec["membership_epoch"] == 2, rec
        assert rec["readmissions"] == 1, rec
        assert rec["departures"] == 1, rec
        assert rec["alive"] == [0, 1], rec
        # despite the absence, EVERY height decided on every instance
        assert rec["decisions_total"] == \
            (I // N_HOSTS) * (HEIGHTS + 1), rec
        assert rec["pod_decisions"] == I, rec

    # the held gossip flowed survivor -> readmitted host, all of it
    surv, sleeper = res["pod"][0], res["pod"][1]
    assert surv["adopted_held"] == held, surv
    assert surv["reroute_sent"] == held, surv
    assert sleeper["reroute_received"] == held, sleeper
    assert sleeper["adopted_held"] == 0 and sleeper["reroute_sent"] == 0

    # both hosts gathered IDENTICAL height-stamped decision rows,
    # covering every global instance with the decided value
    rows0, rows1 = (r["pod_decision_rows"] for r in res["pod"])
    assert rows0 == rows1
    assert sorted(r[0] for r in rows0) == list(range(I))
    assert all(r[3] == 7 for r in rows0)

    assert res["single"]["decisions_total"] == I * (HEIGHTS + 1)
    assert res["offline"]["decisions_total"] == I * (HEIGHTS + 1)

    # leaf-for-leaf: host blocks concatenate host-major == global —
    # elasticity (negotiated padding, the membership cycle, the held
    # replay) changed NOTHING
    pods = [np.load(res["paths"][f"pod{k}"]["npz"])
            for k in range(N_HOSTS)]
    single = np.load(res["paths"]["single"]["npz"])
    offline = np.load(res["paths"]["offline"]["npz"])
    assert set(single.files) == set(offline.files) == set(pods[0].files)
    for key in single.files:
        merged = np.concatenate([p[key] for p in pods], axis=0)
        np.testing.assert_array_equal(
            merged, single[key], err_msg=f"{key}: elastic vs single")
        np.testing.assert_array_equal(
            merged, offline[key], err_msg=f"{key}: elastic vs offline")

    # one parseable host-id-stamped heartbeat per process, and the
    # merged postmortem renders the membership trail (the
    # observability satellite, end to end)
    hbs = [res["paths"][f"pod{k}"]["heartbeat"]
           for k in range(N_HOSTS)]
    assert metrics_main(["--check"] + hbs) == 0
    from agnes_tpu.utils.flightrec import (
        read_heartbeat,
        render_pod_postmortem,
    )

    for k, path in enumerate(hbs):
        lines, _bad = read_heartbeat(path)
        assert lines and all(ln["host_id"] == k for ln in lines), path
    post = render_pod_postmortem(hbs)
    assert "elastic membership:" in post
    assert "epoch 2" in post
    assert "membership_boundary=2" in post
    assert "membership_relift" in post
    assert "HELD GOSSIP DROPPED" not in post
