"""bench.py's deadline contract (ISSUE 1 / VERDICT r5 weak #1): with
an unreachable backend and an enclosing wall-clock budget — coreutils
`timeout`, the env override, or an outright SIGTERM — the process must
ALWAYS exit 0 having printed a parseable JSON verdict as its last
stdout line, well before the budget's kill escalation.

The dead backend is simulated with AGNES_BENCH_FORCE_DEAD=1 (the probe
child becomes an unconditional hang), so these run anywhere, no TPU or
jax import involved — bench's probe guard exits before the heavy
imports.  Each run gets a private lease path so rival-looking benches
in parallel CI never make each other "busy"."""

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _env(tmp_path, **extra):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("AGNES_BENCH_")}
    env["AGNES_BENCH_FORCE_DEAD"] = "1"
    env["AGNES_TPU_LEASE_PATH"] = str(tmp_path / "tpu.lease")
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _last_record(stdout: str) -> dict:
    lines = [ln for ln in stdout.strip().splitlines() if ln]
    assert lines, "bench printed nothing to stdout"
    rec = json.loads(lines[-1])          # MUST parse — the driver does
    assert rec["metric"] == "pipeline_votes_per_sec"
    assert rec["value"] == -1
    assert rec["vs_baseline"] == -1
    assert rec["unit"] == "votes/sec/chip"
    assert rec["note"]                   # states the actual cause
    return rec


def test_timeout_wrapped_dead_backend_still_emits_verdict(tmp_path):
    """The acceptance-criterion path: `timeout N python bench.py`
    against a dead backend.  bench must discover N from /proc, clamp
    its probe budget under it, and exit 0 with the JSON record BEFORE
    the wrapper's TERM ever fires."""
    t0 = time.monotonic()
    r = subprocess.run(
        ["timeout", "15", sys.executable, BENCH],
        env=_env(tmp_path), cwd=REPO,
        capture_output=True, text=True, timeout=60)
    took = time.monotonic() - t0
    assert r.returncode == 0, (r.returncode, r.stderr[-800:])
    rec = _last_record(r.stdout)
    # either the clamped probe loop gave up or the self-armed alarm
    # beat it by a hair — both are within-contract; what is NOT
    # allowed is "busy" (nobody held the claim) or silence
    assert "held by another process" not in rec["note"]
    assert "proc:timeout" in rec["note"]     # the discovery is stated
    assert took < 15, f"bench outlived its enclosing budget ({took:.0f}s)"


def test_env_deadline_beats_huge_probe_budget(tmp_path):
    """An env probe budget far past the deadline must be clamped: the
    r5 failure was exactly an env default outliving the wrapper."""
    r = subprocess.run(
        [sys.executable, BENCH],
        env=_env(tmp_path, AGNES_BENCH_DEADLINE_S=8,
                 AGNES_BENCH_PROBE_BUDGET_S=99999,
                 AGNES_BENCH_BUSY_BUDGET_S=99999),
        cwd=REPO, capture_output=True, text=True, timeout=30)
    assert r.returncode == 0, r.stderr[-800:]
    rec = _last_record(r.stdout)
    assert "env:AGNES_BENCH_DEADLINE_S" in rec["note"]


def test_sigterm_mid_probe_emits_verdict(tmp_path):
    """The kill path: TERM arriving while a probe hangs must produce
    the verdict from the signal handler and exit 0 — the last-resort
    guarantee when discovery finds no budget at all."""
    p = subprocess.Popen(
        [sys.executable, BENCH],
        env=_env(tmp_path, AGNES_BENCH_DEADLINE_S=600),
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True)
    try:
        time.sleep(2.0)                  # let it arm + start probing
        p.send_signal(signal.SIGTERM)
        out, _ = p.communicate(timeout=30)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
    assert p.returncode == 0
    rec = _last_record(out)
    assert "SIGTERM" in rec["note"]


def test_rival_lease_holder_means_busy(tmp_path):
    """A lease held by an UNRELATED live process must make bench wait
    (and, past the busy budget, report "busy" — not probe against the
    rival's claim)."""
    sys.path.insert(0, REPO)
    from scripts.tpu_holders import TpuLease

    rival = subprocess.Popen([sys.executable, "-c",
                              "import time; time.sleep(600)"])
    try:
        lease = TpuLease(path=str(tmp_path / "tpu.lease"), pid=rival.pid)
        assert lease.acquire(note="rival")
        # a roomy deadline with a SHORT busy budget: the busy verdict
        # must come from the lease check, well clear of the alarm
        r = subprocess.run(
            [sys.executable, BENCH],
            env=_env(tmp_path, AGNES_BENCH_DEADLINE_S=60,
                     AGNES_BENCH_BUSY_BUDGET_S=4,
                     AGNES_BENCH_PROBE_INTERVAL_S=1),
            cwd=REPO, capture_output=True, text=True, timeout=30)
        assert r.returncode == 0, r.stderr[-800:]
        rec = _last_record(r.stdout)
        assert "held by another process" in rec["note"]
    finally:
        rival.kill()
        rival.wait()


def test_ancestor_lease_is_inherited(tmp_path):
    """The suite-runner composition: run_hw_suite.sh leases the claim
    to its own shell, then launches bench as a stage.  bench must
    recognize the ANCESTOR's lease as covering it and probe normally —
    not busy-wait against its own parent (here: the lease names this
    pytest process, bench's grandparent-ish ancestor)."""
    sys.path.insert(0, REPO)
    from scripts.tpu_holders import TpuLease

    lease = TpuLease(path=str(tmp_path / "tpu.lease"))
    assert lease.acquire(note="suite runner (this test)")
    try:
        # roomy deadline, tight probe caps: the wedged verdict must
        # come from the probe loop itself, well clear of the alarm
        r = subprocess.run(
            [sys.executable, BENCH],
            env=_env(tmp_path, AGNES_BENCH_DEADLINE_S=60,
                     AGNES_BENCH_PROBE_TIMEOUT_S=3,
                     AGNES_BENCH_PROBE_BUDGET_S=3,
                     AGNES_BENCH_PROBE_INTERVAL_S=1),
            cwd=REPO, capture_output=True, text=True, timeout=30)
        assert r.returncode == 0, r.stderr[-800:]
        rec = _last_record(r.stdout)
        # probed (and found the forced-dead backend wedged) — did NOT
        # classify its own ancestor's lease as a rival
        assert "wedged" in rec["note"] or "timed out" in rec["note"]
        # and it did not release or overwrite our lease on exit
        mine = lease.holder()
        assert mine is not None and mine["pid"] == os.getpid()
    finally:
        lease.release()


def test_self_armed_alarm_is_the_backstop(tmp_path):
    """No TERM ever arrives (e.g. an intermediate shell swallowed it):
    the self-armed SIGALRM margin before the env deadline must fire
    and deliver the verdict on its own."""
    t0 = time.monotonic()
    r = subprocess.run(
        [sys.executable, BENCH],
        env=_env(tmp_path, AGNES_BENCH_DEADLINE_S=7,
                 # probe caps that would outlive the alarm on their own
                 AGNES_BENCH_PROBE_TIMEOUT_S=600,
                 AGNES_BENCH_PROBE_INTERVAL_S=600),
        cwd=REPO, capture_output=True, text=True, timeout=40)
    took = time.monotonic() - t0
    assert r.returncode == 0, r.stderr[-800:]
    rec = _last_record(r.stdout)
    assert took < 12, f"alarm never fired ({took:.0f}s)"
    # either the clamped probe loop returned first or the alarm did;
    # both are within-contract, but the record must say which
    assert ("SIGALRM" in rec["note"] or "wedged" in rec["note"]
            or "timed out" in rec["note"])
