"""Multi-host distributed serve: the jax-free half (ISSUE 15).

Instance-range sharding math, the decision-gather wire codec,
dead-host/straggler detection on stubbed clocks, the per-host budget/
ladder planning fix, the schema-v2 heartbeat host stamp + merged pod
postmortem, and the pod coordinator's single-process degenerate —
all CPU-cheap, zero XLA compiles, no jax import (asserted)."""

import json
import os
import sys

import numpy as np
import pytest

from agnes_tpu.distributed import (
    DeadHostError,
    HostPlan,
    PodConfigError,
    StragglerMonitor,
    frame_capacity_bytes,
    pack_decision_frame,
    rebase_wire_instances,
    unpack_decision_frame,
    unpack_decision_frames,
)
from agnes_tpu.distributed.pod import PodCoordinator, plan_digest
from agnes_tpu.bridge.native_ingest import (
    REC_SIZE,
    pack_wire_votes,
    unpack_wire_votes,
)


def test_distributed_topology_layer_is_jax_free():
    """Fresh-interpreter proof (the suite's conftest imports jax
    before any test runs, so the check must leave this process)."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = ("import sys; import agnes_tpu.distributed; "
            "import agnes_tpu.distributed.pod; "
            "assert 'jax' not in sys.modules, 'pulled jax'")
    subprocess.run([sys.executable, "-c", code], check=True, cwd=repo,
                   env={**os.environ,
                        "PYTHONPATH": repo + os.pathsep
                        + os.environ.get("PYTHONPATH", "")})


# -- HostPlan -----------------------------------------------------------------

def test_host_plan_ranges_and_translation():
    p = HostPlan(4, 12)
    assert p.local_instances == 3
    assert [p.instance_range(h) for h in range(4)] == [
        (0, 3), (3, 6), (6, 9), (9, 12)]
    assert p.owner_of(0) == 0 and p.owner_of(11) == 3
    np.testing.assert_array_equal(p.to_local(2, [6, 8]), [0, 2])
    np.testing.assert_array_equal(p.to_global(2, [0, 2]), [6, 8])
    np.testing.assert_array_equal(
        p.owned_mask(1, [2, 3, 5, 6]), [False, True, True, False])


def test_host_plan_rejects_bad_shapes():
    with pytest.raises(PodConfigError):
        HostPlan(3, 10)                  # uneven split
    with pytest.raises(PodConfigError):
        HostPlan(0, 4)
    p = HostPlan(2, 4)
    with pytest.raises(PodConfigError):
        p.instance_range(2)
    with pytest.raises(PodConfigError):
        p.owner_of(4)


# -- decision-gather codec ----------------------------------------------------

def test_decision_frame_roundtrip():
    cap = 5
    f = pack_decision_frame(3, [7, 9], [4, -1], [0, 2], [11, 11], cap)
    assert len(f) == frame_capacity_bytes(cap)
    decs = unpack_decision_frame(f)
    assert len(decs) == 2
    assert (decs[0].instance, decs[0].host, decs[0].round,
            decs[0].height, decs[0].value_id) == (7, 3, 0, 11, 4)
    assert decs[1].value_id is None          # nil decision
    assert unpack_decision_frame(
        pack_decision_frame(0, [], [], [], [], cap)) == []


def test_decision_frames_gather_order_and_limits():
    cap = 2
    rows = np.stack([
        pack_decision_frame(0, [1], [7], [0], [0], cap),
        pack_decision_frame(1, [3, 2], [7, 7], [1, 0], [0, 0], cap),
    ])
    decs = unpack_decision_frames(rows)
    assert [(d.instance, d.host) for d in decs] == [
        (1, 0), (3, 1), (2, 1)]              # host-major order
    with pytest.raises(PodConfigError):
        pack_decision_frame(0, [1, 2, 3], [7] * 3, [0] * 3, [0] * 3,
                            cap)             # over capacity
    bad = rows[0].copy()
    bad[0:4] = np.uint32(99).reshape(1).view(np.uint8)  # count > cap
    with pytest.raises(PodConfigError):
        unpack_decision_frame(bad)


def test_decision_frame_rides_the_wire_abi():
    """A decision frame's payload IS 96-byte wire records — the vote
    plane's parser reads it (one codec, one byte layout)."""
    f = pack_decision_frame(1, [5], [7], [2], [9], 1)
    from agnes_tpu.distributed.topology import FRAME_HEADER

    inst, val, hts, rnd, typ, value, _ = unpack_wire_votes(
        bytes(f[FRAME_HEADER:FRAME_HEADER + REC_SIZE]))
    assert (int(inst[0]), int(val[0]), int(hts[0]), int(rnd[0]),
            int(value[0])) == (5, 1, 9, 2, 7)


# -- wire rebase (the pod front door) -----------------------------------------

def test_rebase_wire_instances():
    w = pack_wire_votes([5, 6, 7], [0, 1, 2], [3] * 3, [0] * 3,
                        [0, 1, 0], [7, -1, 7],
                        np.arange(3 * 64, dtype=np.uint8).reshape(3, 64))
    tail = b"trunc"
    out = rebase_wire_instances(w + tail, -5)
    assert out[-len(tail):] == tail          # truncated tail preserved
    inst, val, hts, rnd, typ, value, sigs = unpack_wire_votes(
        out[:-len(tail)])
    np.testing.assert_array_equal(inst, [0, 1, 2])
    # every other field byte-identical
    np.testing.assert_array_equal(val, [0, 1, 2])
    np.testing.assert_array_equal(value, [7, -1, 7])
    np.testing.assert_array_equal(
        sigs, np.arange(3 * 64, dtype=np.uint8).reshape(3, 64))


# -- straggler / dead-host detection (stubbed clocks) -------------------------

def _monitor(clk, **kw):
    kw.setdefault("dead_after_s", 30.0)
    kw.setdefault("straggler_after_s", 5.0)
    return StragglerMonitor(3, 0, clock=lambda: clk["t"], **kw)


def test_straggler_then_dead_progression():
    clk = {"t": 100.0}
    m = _monitor(clk)
    assert m.check() == []                   # fresh at construction
    clk["t"] = 104.0
    assert m.stragglers() == [] and m.dead() == []
    clk["t"] = 110.0
    assert m.check() == [1, 2]               # past straggler age
    m.beat(1)                                # host 1 shows evidence
    assert m.check() == [2]
    clk["t"] = 135.0                         # host 2: 35s, host 1: 25s
    with pytest.raises(DeadHostError) as e:
        m.check()
    assert "[2]" in str(e.value)
    assert m.dead() == [2] and m.stragglers() == [1]


def test_monitor_never_flags_self_and_collective_beats_all():
    clk = {"t": 0.0}
    m = _monitor(clk)
    clk["t"] = 1000.0
    assert 0 not in m.dead()                 # self never flagged
    m.beat(None)                             # completed allgather
    assert m.check() == []


def test_monitor_reads_heartbeat_files(tmp_path):
    from agnes_tpu.utils.flightrec import Heartbeat

    path = str(tmp_path / "hb.ndjson")
    Heartbeat(path, host_id=1).beat()
    clk = {"t": 1000.0}
    m = _monitor(clk)
    clk["t"] = 2000.0
    # the trail was just written: its wall-clock age is ~0, so host 1
    # gets fresh evidence while host 2 stays dead
    m.observe_heartbeat_files([None, path, None])
    with pytest.raises(DeadHostError) as e:
        m.check()
    assert "[2]" in str(e.value)


def test_monitor_rejects_inverted_thresholds():
    with pytest.raises(PodConfigError):
        StragglerMonitor(2, 0, dead_after_s=1.0, straggler_after_s=5.0)


# -- per-host budget/ladder planning (the ISSUE 15 satellite fix) -------------

class _FakeMesh:
    """Duck-typed mesh: utils/budget.mesh_local_shape only reads
    .shape (an axis-name -> size mapping)."""

    def __init__(self, **shape):
        self.shape = shape


def test_mesh_local_shape_per_host_division():
    from agnes_tpu.utils.budget import mesh_local_shape

    pod = _FakeMesh(slice=2, data=1, val=2)
    # global figure over the global mesh: per-device = (I/2, V/2)
    assert mesh_local_shape(pod, 8, 4) == (4, 2)
    # a HOST'S slice (I already divided by hosts): divide only by the
    # data extent one host owns — NOT by the pod-wide extent
    assert mesh_local_shape(pod, 4, 4, n_hosts=2) == (4, 2)
    # the pre-fix behavior under-claimed by n_hosts:
    assert mesh_local_shape(pod, 4, 4) == (2, 2)
    with pytest.raises(ValueError):
        mesh_local_shape(_FakeMesh(slice=1, data=3, val=1), 6, 4,
                         n_hosts=2)          # 3 devices over 2 hosts


def test_plan_dense_ladder_sized_to_the_host_slice():
    from agnes_tpu.serve.batcher import ShapeLadder

    hbm = 1 << 34
    pod = ShapeLadder.plan_dense(8, 4, local_shape=(4, 2), n_hosts=2,
                                 min_rung=4, hbm_bytes=hbm)
    one = ShapeLadder.plan_dense(4, 4, local_shape=(4, 2),
                                 min_rung=4, hbm_bytes=hbm)
    glob = ShapeLadder.plan_dense(8, 4, local_shape=(4, 2),
                                  min_rung=4, hbm_bytes=hbm)
    # hosts=2 over the global figure == a single host planning its
    # own slice; the unfixed global plan paced rungs 2x too big
    assert pod.rungs == one.rungs
    assert glob.max_rung == 2 * pod.max_rung
    with pytest.raises(ValueError):
        ShapeLadder.plan_dense(9, 4, local_shape=(4, 2), n_hosts=2,
                               hbm_bytes=hbm)


# -- heartbeat schema v2 (host stamp) -----------------------------------------

def test_heartbeat_v2_host_stamp(tmp_path):
    from agnes_tpu.utils.flightrec import (
        Heartbeat,
        SCHEMA_VERSION,
        read_heartbeat,
        validate_heartbeat_line,
    )

    assert SCHEMA_VERSION >= 2
    path = str(tmp_path / "hb.ndjson")
    line = Heartbeat(path, host_id=3).beat()
    assert line["host_id"] == 3 and line["process_index"] == 3
    lines, bad = read_heartbeat(path)
    assert not bad and lines[0]["host_id"] == 3
    # single-process trails omit the stamp and stay valid (v1 shape)
    p1 = str(tmp_path / "hb1.ndjson")
    l1 = Heartbeat(p1).beat()
    assert "host_id" not in l1
    assert validate_heartbeat_line(l1) == []
    # a mistyped stamp fails the schema the way a bad seq does
    wrong = dict(l1, host_id="zero")
    assert any("host_id" in p for p in validate_heartbeat_line(wrong))


def test_pod_postmortem_ranks_the_first_silent_host(tmp_path):
    import time

    from agnes_tpu.utils.flightrec import render_pod_postmortem

    now = time.time()
    paths = []
    for host, age in ((0, 500.0), (1, 2.0)):
        p = str(tmp_path / f"hb{host}.ndjson")
        rec = {"v": 2, "kind": "hb", "seq": 0, "t": now - age,
               "pid": 1, "uptime_s": 1.0, "interval_s": 1.0,
               "host_id": host, "process_index": host}
        with open(p, "w") as f:
            f.write(json.dumps(rec) + "\n")
        paths.append(p)
    out = render_pod_postmortem(paths + [str(tmp_path / "gone")],
                                now=now)
    lines = out.splitlines()
    order = [k for k, ln in enumerate(lines)
             if "UNREADABLE" in ln or "host 0:" in ln
             or "host 1:" in ln]
    # unreadable (never beat) first, then host 0 (500s stale), then
    # host 1 (fresh) — the wedge-order ranking
    assert "UNREADABLE" in lines[order[0]]
    assert "host 0:" in lines[order[1]] and "STALE" in lines[order[1]]
    assert "host 1:" in lines[order[2]]


def test_metrics_cli_multi_file_check_and_merge(tmp_path, capsys):
    from agnes_tpu.utils.flightrec import Heartbeat
    from agnes_tpu.utils.metrics_cli import main

    p0 = str(tmp_path / "h0.ndjson")
    p1 = str(tmp_path / "h1.ndjson")
    Heartbeat(p0, host_id=0).beat()
    Heartbeat(p1, host_id=1).beat()
    assert main(["--check", p0, p1]) == 0
    out = capsys.readouterr().out
    assert "host_id 0" in out and "host_id 1" in out
    # merged postmortem renders the pod timeline
    assert main([p0, p1]) == 0
    out = capsys.readouterr().out
    assert "pod heartbeat merge: 2 trail(s)" in out
    # a missing file fails --check pod-wide
    assert main(["--check", p0, str(tmp_path / "nope")]) == 2
    capsys.readouterr()                      # clear the check output
    # single-path --json keeps its historical record shape
    assert main(["--json", p0]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["path"] == p0 and rec["valid_lines"] == 1


# -- pod coordinator (single-process degenerate + digests) --------------------

def test_pod_coordinator_single_process_degenerates():
    c = PodCoordinator(n_hosts=1, host=0)
    c.agree(("entry", (1, 2), ((3,), "int32")))
    c.barrier("warmup_enter", ("plan",))
    out = c.allgather_bytes(np.arange(4, dtype=np.uint8))
    np.testing.assert_array_equal(out, [[0, 1, 2, 3]])
    assert c.agrees == 2 and c.barriers == 1


def test_plan_digest_stability():
    a = plan_digest(("e", (1,), ((2, 3), "int32")))
    assert a == plan_digest(("e", (1,), ((2, 3), "int32")))
    assert a != plan_digest(("e", (1,), ((2, 4), "int32")))
    assert len(a) == 16


def test_agree_divergence_fails_loudly_naming_hosts():
    """A mismatched dispatch plan raises PodDivergenceError naming
    the differing host(s) — the transport is stubbed so the digest-
    compare logic tests without a jax.distributed pod."""
    from agnes_tpu.distributed.pod import PodDivergenceError

    class _Stub(PodCoordinator):
        def allgather_bytes(self, frame):
            other = np.frombuffer(
                plan_digest(("other", "plan")), np.uint8)
            return np.stack([np.asarray(frame, np.uint8), other])

    c = _Stub(n_hosts=2, host=0)
    with pytest.raises(PodDivergenceError) as e:
        c.agree(("entry", (3,), "sig"))
    assert "[1]" in str(e.value)
    # matching plans pass (host 1's frame == ours)

    class _Same(PodCoordinator):
        def allgather_bytes(self, frame):
            return np.stack([np.asarray(frame, np.uint8)] * 2)

    _Same(n_hosts=2, host=0).agree(("entry", (3,), "sig"))


def test_coordinator_beats_monitor_on_gather():
    clk = {"t": 0.0}
    m = StragglerMonitor(2, 0, dead_after_s=30, straggler_after_s=5,
                         clock=lambda: clk["t"])
    c = PodCoordinator(n_hosts=1, host=0, monitor=m)
    clk["t"] = 100.0
    c.allgather_bytes(np.zeros(1, np.uint8))
    assert m.check() == []


# -- hot-path map coverage (rot guard) ----------------------------------------

def test_lint_hot_paths_cover_distributed_plane():
    from agnes_tpu.analysis.lint import HOT_PATHS

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert "agnes_tpu/distributed/shard.py" in HOT_PATHS
    assert "agnes_tpu/distributed/driver.py" in HOT_PATHS
    assert "agnes_tpu/distributed/elastic.py" in HOT_PATHS
    for rel, funcs in HOT_PATHS.items():
        path = os.path.join(repo, rel)
        assert os.path.exists(path), f"HOT_PATHS rot: {rel}"
        src = open(path).read()
        for fn in funcs:
            assert f"def {fn}(" in src, f"{rel} lost hot fn {fn}"
