"""ISSUE 18 — Pallas Barrett-field kernel lane.

Interpret-mode differentials: the fused multiply+reduce and
reduce/carry-chain kernels must match the rolled `bls_field_jax` path
LEAF-FOR-LEAF (exact limbs, not just mod-p values) over random and
boundary operands — the kernels transliterate the rolled integer
operation order, so any drift is a bug, not rounding.  Plus the
satellite-5 discipline check: the serve lane's kernel/rolled selection
is a retrace STATIC, so warming one lane and dispatching the other
fails loudly at the armed sentinel, never as a live mid-serve compile
(driven through registry stubs — zero XLA compiles).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from agnes_tpu.crypto import bls_field_jax as BF
from agnes_tpu.crypto import bls_ref as ref
from agnes_tpu.crypto import pallas_field as PF

P = ref.P

#: boundary VALUES the ISSUE names: zero, one, p-1, and the top of the
#: <4p pre-reduce representative range every strict limb array may hold
_BOUNDARY = (0, 1, P - 1, P, 4 * P - 1)


def _operand_rows(rng, n_random):
    """[R, NLIMBS] int32 operand rows: the boundary values strict, the
    random tail as loose sums a+b (a, b < 2p) — limbs <= 2*LMASK and
    value < 4p, the exact operand envelope `fv_mul_pairs` feeds the
    reduce (products stay under the Barrett cap)."""
    rows = [BF.to_limbs(v) for v in _BOUNDARY]
    for _ in range(n_random):
        a = int(rng.integers(0, 2**62)) * int(rng.integers(0, 2**62)) \
            % (2 * P)
        b = int(rng.integers(0, 2**62)) ** 2 % (2 * P)
        rows.append(BF.to_limbs(a) + BF.to_limbs(b))
    return jnp.asarray(np.stack(rows).astype(np.int32))


def test_mul_kernel_matches_rolled_leaf_for_leaf():
    rng = np.random.default_rng(7)
    xa = _operand_rows(rng, 11)
    ya = jnp.flip(_operand_rows(rng, 11), axis=0)
    want = BF.reduce_cols(BF._mul_cols(xa, ya),
                          BF.NLIMBS * BF._ELEM_LIMB * BF._ELEM_LIMB)
    got = PF.mul_pairs_call(xa, ya, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # and the limbs really are the product mod p (strict < 4p rep)
    for i in range(xa.shape[0]):
        x = BF.from_limbs(np.asarray(xa[i]))
        y = BF.from_limbs(np.asarray(ya[i]))
        g = BF.from_limbs(np.asarray(got[i]))
        assert g < 4 * P and g % P == (x * y) % P, i


def _col_rows(rng, n, k):
    """[n, NLIMBS] columns as k-fold sums of strict encodings of
    values < 4p/k — limbs <= k*LMASK with total value < 4p, the shape
    `fv_reduce_stack` columns actually take (a synthetic huge TOP limb
    would put the value outside the reduce's envelope)."""
    rows = []
    for _ in range(n):
        acc = None
        for _ in range(k):
            v = (int(rng.integers(0, 2**62)) ** 2) % (4 * P // k)
            lv = BF.to_limbs(v)
            acc = lv if acc is None else acc + lv
        rows.append(acc)
    return jnp.asarray(np.stack(rows).astype(np.int32))


def test_reduce_kernel_matches_rolled_leaf_for_leaf():
    rng = np.random.default_rng(11)
    # the `_z_is_zero_g2` bound: one loosen pass
    b_small = BF._ELEM_LIMB + BF.LMASK
    cols_small = jnp.concatenate([
        jnp.asarray(np.stack([BF.to_limbs(v) for v in _BOUNDARY])
                    .astype(np.int32)),
        _col_rows(rng, 19, 3)])
    # a deep-stack bound: two loosen passes
    b_big = 16 * BF._ELEM_LIMB
    cols_big = _col_rows(rng, 24, 32)
    for cols, bound in ((cols_small, b_small), (cols_big, b_big)):
        want = BF.reduce_cols(cols, bound)
        got = PF.reduce_call(cols, bound, interpret=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want),
                                      err_msg=f"bound={bound}")
        for i in range(cols.shape[0]):
            v = BF.from_limbs(np.asarray(cols[i], np.int64))
            g = BF.from_limbs(np.asarray(got[i]))
            assert g < 4 * P and g % P == v % P, (bound, i)


def test_field_backend_routing_and_restore():
    """`field_backend("interpret")` routes `fv_mul` and `reduce_cols`
    through the kernels and produces the SAME limbs as the rolled
    path; the context restores the prior backend on every exit."""
    xs = jnp.asarray(BF.ints_to_limbs(list(_BOUNDARY)))
    x = BF.fv_in(xs, bound=4 * P)
    y = BF.fv_in(jnp.flip(xs, axis=0), bound=4 * P)
    rolled_mul = BF.fv_mul(x, y)
    cols = jnp.asarray(BF.ints_to_limbs([3, P - 1, 4 * P - 1]))
    rolled_red = BF.reduce_cols(cols, BF._ELEM_LIMB + BF.LMASK)
    assert BF.current_backend() is False
    with BF.field_backend("interpret"):
        assert BF.current_backend() == "interpret"
        kern_mul = BF.fv_mul(x, y)
        kern_red = BF.reduce_cols(cols, BF._ELEM_LIMB + BF.LMASK)
    assert BF.current_backend() is False
    assert kern_mul.bound == rolled_mul.bound     # FV bound contract
    np.testing.assert_array_equal(np.asarray(kern_mul.a),
                                  np.asarray(rolled_mul.a))
    np.testing.assert_array_equal(np.asarray(kern_red),
                                  np.asarray(rolled_red))
    with pytest.raises(AssertionError):
        BF.field_backend("cuda").__enter__()      # unknown lane name


def test_kernel_lane_selection_is_a_retrace_static():
    """Satellite 5: the BLS lane resolves `pallas_field` ONCE and
    carries it in every observe's statics — after warming the rolled
    lane and arming, a dispatch on the kernel lane raises RetraceError
    AT THE OBSERVE (before any dispatch could trigger a live compile).
    Registry-stubbed: the machinery under test is the signature
    discipline, not XLA."""
    from agnes_tpu.analysis import retrace
    from agnes_tpu.device import registry
    from agnes_tpu.serve.bls_lane import (
        AggregateClass,
        BlsKeyRegistry,
        BlsLane,
    )
    from agnes_tpu.utils.metrics import Metrics

    V = 2
    _pts, pk = _keys(V)
    reg = BlsKeyRegistry(pk)
    reg.mark_trusted(np.arange(V))

    class _Driver:
        def __init__(self):
            self.sentinel = retrace.RetraceSentinel(metrics=Metrics())

        def _observe(self, entry, args, statics=()):
            self.sentinel.observe(entry,
                                  retrace.signature(args, statics))

    share = ref.g2_to_bytes(ref.point_add(ref.G2, ref.G2))
    cls = AggregateClass(key=(0, 0, 0, 0, 7), signers={0, 1},
                         shares={0: share, 1: share}, weight=2,
                         t_first=0.0)
    drv = _Driver()
    with registry.override("bls_aggregate",
                           jit=lambda *a, **kw: (None, None)):
        lane = BlsLane(reg, 1, pallas_field=False)
        lane.bind(drv)
        assert lane.uses_pallas_field is False
        lane._msm_dispatch(cls, [0, 1])     # learning: becomes expected
        drv.sentinel.arm()
        lane._msm_dispatch(cls, [0, 1])     # same lane: silent
        assert drv.sentinel.report()["unexpected"] == 0

        # lane flip after warmup — the kernel-lane signature was never
        # warmed, so the armed set rejects it BEFORE dispatch
        lane.pallas_field = "interpret"
        with pytest.raises(retrace.RetraceError):
            lane._msm_dispatch(cls, [0, 1])
    assert drv.sentinel.report()["unexpected"] == 1


def _keys(V):
    pts, acc = [], None
    for _ in range(V):
        acc = ref.point_add(acc, ref.G1)
        pts.append(acc)
    pk = np.stack([np.frombuffer(ref.g1_compress(p), np.uint8)
                   for p in pts])
    return pts, pk
