"""Adversarial drive of the native C ABI (core/native/capi.cpp).

VERDICT r2 missing #5 / weak #8: nothing hostile ever reached capi.cpp
itself — these tests hammer the raw ctypes surface with extreme-but-
pointer-valid inputs (hostile enum tags, INT64 extremes, unsorted and
duplicate validator rows, zero/large caps, randomized event storms) and
assert the library neither crashes nor returns nonsense.  `ci.sh` runs
this file (and the C++-vs-Python differential suite) with the library
built under AddressSanitizer + UBSan, which is what gives the memory-
safety assertions teeth.

The wrappers in core/native.py screen lengths before the C calls
(round-2 hardening); this file deliberately goes BELOW the wrappers for
the handle-based APIs, and through them for the byte-buffer APIs (the
wrapper screen is itself part of the attack surface contract).
"""

import ctypes
import random

import numpy as np
import pytest

from agnes_tpu.core import native
from agnes_tpu.core.native import _AgEvent, _AgMessage, _AgState
from agnes_tpu.core import state_machine as sm
from agnes_tpu.types import MAX_ROUND

I64_MAX = 2**63 - 1
I64_MIN = -(2**63)


@pytest.fixture(scope="module")
def L():
    return native._lib()


def _apply_raw(L, height, round_, step, ev_tag, ev_round, value, pol):
    s = _AgState(height, round_, step, -1, -1, -1, -1)
    e = _AgEvent(ev_tag, 1, value, pol)
    out_s, out_m = _AgState(), _AgMessage()
    L.ag_apply(ctypes.byref(s), ev_round, ctypes.byref(e),
               ctypes.byref(out_s), ctypes.byref(out_m))
    return out_s, out_m


def test_apply_hostile_tags_and_extremes(L):
    """Garbage event tags / steps / INT64 extremes must not crash and
    must keep the output state inside the legal Step range."""
    hostile_tags = [-1, 13, 14, 99, 2**31 - 1, -2**31]
    hostile_steps = [-1, 5, 99, 2**31 - 1]
    for tag in hostile_tags:
        for step in [0, 2, 4] + hostile_steps:
            out_s, out_m = _apply_raw(L, 1, 0, step, tag, 0, 7, -1)
            # hostile inputs may no-op or fall to the default arm, but
            # the emitted state/step must never be a new invalid value
            # unless it was already the (hostile) input step
            assert out_s.step == step or 0 <= out_s.step <= 4
    for big in (I64_MAX, I64_MIN, I64_MAX - 1):
        out_s, out_m = _apply_raw(L, big, big, 0, 0, big, big, big)
        assert out_s.height == big     # height is never touched by apply
    # TimeoutPrecommit at round I64_MAX: the skip target saturates at
    # the framework rounds domain top MAX_ROUND (types.py) — never
    # wraps negative, never widens past what the int32 device plane
    # can represent (a wrapped round would reset the instance to the
    # past; a widened one would fork the planes)
    out_s, _ = _apply_raw(L, 1, I64_MAX, 2,
                          int(sm.EventTag.TIMEOUT_PRECOMMIT), I64_MAX,
                          -1, -1)
    assert out_s.round == MAX_ROUND


def test_apply_differential_random_storm(L):
    """5k random (state, event) pairs: C++ == Python oracle bit-for-bit
    (the randomized twin of the exhaustive suite in test_native_core)."""
    rng = random.Random(1234)
    for _ in range(5000):
        step = rng.randrange(0, 5)
        round_ = rng.randrange(0, 6)
        ev_round = rng.randrange(0, 6)
        tag = rng.randrange(0, 13)
        # value-carrying tags always carry one (the None/-1 encoding is
        # only defined for events that can actually occur)
        carries = tag in (int(sm.EventTag.NEW_ROUND_PROPOSER),
                          int(sm.EventTag.PROPOSAL),
                          int(sm.EventTag.POLKA_VALUE),
                          int(sm.EventTag.PRECOMMIT_VALUE))
        value = rng.choice([0, 1, 7] if carries else [None, 0, 1, 7])
        pol = rng.randrange(-2, 5)
        locked = rng.choice([None, (0, 1), (2, 7)])
        valid = rng.choice([None, (0, 1), (1, 7)])
        st = sm.State(height=1, round=round_, step=sm.Step(step),
                      locked=sm.RoundValue(*locked) if locked else None,
                      valid=sm.RoundValue(*valid) if valid else None)
        ev = sm.Event(sm.EventTag(tag), value=value, pol_round=pol)
        want_s, want_m = sm.apply(st, ev_round, ev)
        got_s, got_m = native.native_apply(st, ev_round, ev)
        assert got_s == want_s, (st, ev_round, ev)
        assert got_m == want_m, (st, ev_round, ev)


def test_apply_parity_at_int64_edge(L):
    """Oracle and native both saturate TimeoutPrecommit's round+1 at
    the framework domain top MAX_ROUND even for hostile INT64_MAX
    inputs (both sides clamp identically; divergence here would break
    the bit-for-bit parity contract — and the int32 device plane pins
    the same edge in tests/test_cross_plane.py)."""
    st = sm.State(height=1, round=I64_MAX, step=sm.Step.PRECOMMIT,
                  locked=None, valid=None)
    ev = sm.Event(sm.EventTag.TIMEOUT_PRECOMMIT)
    want_s, want_m = sm.apply(st, I64_MAX, ev)
    got_s, got_m = native.native_apply(st, I64_MAX, ev)
    assert want_s.round == MAX_ROUND
    assert got_s == want_s and got_m == want_m


def test_tally_hostile_rounds_indices_weights(L):
    t = L.ag_tally_new(1, 0, 4)
    try:
        tv = ctypes.c_int64(-1)
        # huge validator indices, negative weights, INT64 extremes
        for validator in (I64_MAX, I64_MIN, -2, 10**12):
            rc = L.ag_tally_add(t, 0, validator, 1, 1, ctypes.byref(tv))
            assert 0 <= rc <= 3
        # weight extremes: saturating tally + 128-bit quorum products —
        # I64_MAX weight IS a (clamped) quorum of total 4, and must say so
        rc = L.ag_tally_add(t, 1, 1, 2, I64_MAX, ctypes.byref(tv))
        assert rc == 3 and tv.value == 2
        rc = L.ag_tally_add(t, 1, 2, 2, I64_MIN, ctypes.byref(tv))
        assert 0 <= rc <= 3
        # hostile vote types — identified AND identity-free (validator=-1
        # routes to the anon_weight_ path, which must index by class,
        # never by the raw tag: OOB write otherwise)
        for typ in (-1, 2, 99, 2**31 - 1, -(2**31)):
            rc = L.ag_tally_add(t, typ, 3, 1, 1, ctypes.byref(tv))
            assert 0 <= rc <= 3
            rc = L.ag_tally_add(t, typ, -1, 1, 1, ctypes.byref(tv))
            assert 0 <= rc <= 3
        assert L.ag_tally_skip_weight(t) >= I64_MIN  # just: no crash
    finally:
        L.ag_tally_free(t)


def test_tally_hostile_tags_no_quorum_forgery(L):
    """Distinct hostile vote-type tags from ONE validator must not
    stack weight into precommits_ repeatedly: seen_ is keyed by the
    normalized class, so replays under different raw tags are dups."""
    t = L.ag_tally_new(1, 0, 9)
    try:
        tv = ctypes.c_int64(-1)
        for typ in (1, 2, 3, 99, -1):   # all route to the precommit class
            rc = L.ag_tally_add(t, typ, 7, 5, 4, ctypes.byref(tv))
            # 4 of 9 is under 2/3: no replay may ever cross the quorum
            assert rc == 0, (typ, rc)
    finally:
        L.ag_tally_free(t)


def test_tally_hostile_total(L):
    """Negative total must not make an empty tally report a quorum
    (is_quorum(0, -1) would be 0 > -2 without the ag_tally_new clamp)."""
    t = L.ag_tally_new(1, 0, -1)
    try:
        tv = ctypes.c_int64(-1)
        rc = L.ag_tally_add(t, 0, 0, 3, 0, ctypes.byref(tv))
        assert rc == 0                 # zero weight: still Init
        # clamped to empty-set total: any positive weight IS +2/3 of 0
        rc = L.ag_tally_add(t, 0, 1, 3, 1, ctypes.byref(tv))
        assert rc == 3 and tv.value == 3
    finally:
        L.ag_tally_free(t)


def test_tally_equivocations_cap_edges(L):
    t = L.ag_tally_new(1, 0, 10)
    try:
        tv = ctypes.c_int64(-1)
        for v in range(8):
            L.ag_tally_add(t, 0, v, 1, 1, ctypes.byref(tv))
            L.ag_tally_add(t, 0, v, 2, 1, ctypes.byref(tv))  # conflict
        n = L.ag_tally_equiv_count(t)
        assert n == 8
        buf = (ctypes.c_int64 * (5 * 8))()
        # cap 0 and negative cap must write nothing
        assert L.ag_tally_equivocations(t, buf, 0) == 0
        assert L.ag_tally_equivocations(t, buf, -5) == 0
        # cap smaller than count truncates exactly
        assert L.ag_tally_equivocations(t, buf, 3) == 3
        assert L.ag_tally_equivocations(t, buf, 8) == 8
        # over-large cap writes only count rows
        big = (ctypes.c_int64 * (5 * 64))(*([-7] * (5 * 64)))
        assert L.ag_tally_equivocations(t, big, 64) == 8
        assert big[5 * 8] == -7        # row 8 untouched
    finally:
        L.ag_tally_free(t)


def test_valset_unsorted_duplicate_and_zero_rows(L):
    def mk(rows):
        packed = b"".join(pk + int(p).to_bytes(8, "little", signed=True)
                          for pk, p in rows)
        return L.ag_valset_new(packed, len(rows))

    # unsorted + duplicate keys: set must sort and dedup
    a, b, c = (bytes([x]) * 32 for x in (3, 1, 2))
    v = mk([(a, 5), (b, 1), (c, 2), (a, 9)])
    try:
        assert L.ag_valset_len(v) == 3
        out = ctypes.create_string_buffer(40 * 3)
        L.ag_valset_get(v, out)
        keys = [out.raw[40 * i: 40 * i + 32] for i in range(3)]
        assert keys == sorted(keys)
    finally:
        L.ag_valset_free(v)

    # zero rows
    v = mk([])
    try:
        assert L.ag_valset_len(v) == 0
        assert L.ag_valset_total_power(v) == 0
        assert L.ag_valset_index_of(v, b"\x00" * 32) == -1
    finally:
        L.ag_valset_free(v)

    # extreme powers saturate (sat_add) instead of wrapping: a wrapped
    # total could un-cross a crossed quorum
    v = mk([(a, I64_MAX), (b, 1)])
    try:
        assert L.ag_valset_len(v) == 2
        assert L.ag_valset_total_power(v) == I64_MAX
    finally:
        L.ag_valset_free(v)


def test_rotation_on_hostile_powers(L):
    a, b = (bytes([x]) * 32 for x in (1, 2))
    packed = (a + (0).to_bytes(8, "little")
              + b + (3).to_bytes(8, "little"))
    v = L.ag_valset_new(packed, 2)
    try:
        r = L.ag_rotation_new(v)
        try:
            seen = [L.ag_rotation_step(r) for _ in range(12)]
            # zero-power validator must never be elected
            assert all(s == L.ag_valset_index_of(v, b) for s in seen)
        finally:
            L.ag_rotation_free(r)
    finally:
        L.ag_valset_free(v)


def test_crypto_wrappers_screen_lengths():
    """The byte-buffer APIs go through the native.py screens: hostile
    lengths must come back False/raise cleanly, never reach the raw
    32/64-byte reads."""
    seed = b"\x11" * 32
    pk = native.pubkey(seed)
    sig = native.sign(seed, b"msg")
    assert native.verify(pk, b"msg", sig)
    assert not native.verify(b"", b"msg", sig)
    assert not native.verify(pk, b"msg", b"")
    assert not native.verify(pk * 2, b"msg", sig)
    with pytest.raises(Exception):
        native.pubkey(b"short")
    # empty message is legal and stable
    s2 = native.sign(seed, b"")
    assert native.verify(pk, b"", s2)
    res = native.verify_batch([], [], [])
    assert res == []


def test_ingest_abi_hostile(L):
    """Adversarial drive of the ingestion event loop C ABI
    (core/native/ingest.cpp): hostile record fields, OOB phase
    indices, zero caps, truncated pushes — no crash, sane returns."""
    from agnes_tpu.bridge.native_ingest import _lib as ing_lib

    G = ing_lib()
    h = G.ag_ing_new(4, 4, 4, 2, None, None)
    try:
        # garbage records: all-0xFF (instance/validator way OOB)
        G.ag_ing_push(h, b"\xff" * (96 * 8), 8)
        # hostile rounds/heights/values via a crafted record
        rec = np.zeros(96, np.uint8)
        rec[0:4] = np.frombuffer((3).to_bytes(4, "little"), np.uint8)
        rec[4:8] = np.frombuffer((3).to_bytes(4, "little"), np.uint8)
        rec[16:20] = 0xFF              # round = -1 -> malformed
        G.ag_ing_push(h, rec.tobytes(), 1)
        cnt = np.empty(7, np.int64)
        G.ag_ing_counters(h, cnt.ctypes.data)
        assert cnt[0] == 9             # all rejected malformed
        # stage/verdicts/emit on empty sets are no-ops
        assert G.ag_ing_stage(h) == 0
        assert G.ag_ing_apply_verdicts(h, None) == 0
        assert G.ag_ing_emit(h) == 0
        # OOB phase index
        r32, t32 = ctypes.c_int32(), ctypes.c_int32()
        n64 = ctypes.c_int64()
        sp = ctypes.POINTER(ctypes.c_int32)()
        mp = ctypes.POINTER(ctypes.c_uint8)()
        assert G.ag_ing_phase(h, 99, ctypes.byref(r32), ctypes.byref(t32),
                              ctypes.byref(n64), ctypes.byref(sp),
                              ctypes.byref(mp)) == -1
        assert G.ag_ing_phase(h, -1, ctypes.byref(r32), ctypes.byref(t32),
                              ctypes.byref(n64), ctypes.byref(sp),
                              ctypes.byref(mp)) == -1
        # zero-cap drain writes nothing
        assert G.ag_ing_drain_events(h, None, 0) == 0
        # decode hostile slots
        assert G.ag_ing_decode_slot(h, -1, 0) == -1
        assert G.ag_ing_decode_slot(h, 99, 0) == -1
        assert G.ag_ing_decode_slot(h, 0, -1) == -1
        assert G.ag_ing_decode_slot(h, 0, 99) == -1
        # evidence on an empty log
        buf = ctypes.create_string_buffer(2 * 96)
        assert G.ag_ing_evidence(h, 0, 0, buf) == 0
        # hostile sync values must not poison window arithmetic:
        # INT64_MIN base_round would make round - base overflow (UB)
        base = np.full(4, -2**63, np.int64)
        hts = np.zeros(4, np.int64)
        G.ag_ing_sync(h, base.ctypes.data, hts.ctypes.data)
        ok_rec = np.zeros(96, np.uint8)
        ok_rec[16:20] = np.frombuffer(
            (2**31 - 1).to_bytes(4, "little"), np.uint8)  # max round
        assert G.ag_ing_push(h, ok_rec.tobytes(), 1) == 1
        G.ag_ing_stage(h)              # held (future) — no UB, no crash
    finally:
        G.ag_ing_free(h)


def test_ingest_abi_hostile_dims(L):
    """ag_ing_new must fail closed (NULL) on hostile dimensions
    instead of throwing bad_alloc across the C boundary or
    overflowing the int64 cell math."""
    from agnes_tpu.bridge.native_ingest import _lib as ing_lib

    G = ing_lib()
    for dims in [(-1, 4, 4, 2), (4, -1, 4, 2), (4, 4, -1, 2),
                 (4, 4, 4, -1), (0, 4, 4, 2), (4, 0, 4, 2),
                 (2**62, 4, 4, 2), (2**31, 2**31, 4, 2),
                 (2**40, 2**40, 4, 2), (4, 4, 2**32, 2),
                 (4, 4, 4, 2**32)]:
        assert G.ag_ing_new(*dims, None, None) is None, dims
    h = G.ag_ing_new(4, 4, 4, 2, None, None)   # sane dims still work
    assert h is not None
    G.ag_ing_free(h)


def test_sha512_zero_and_large(L):
    out = ctypes.create_string_buffer(64)
    L.ag_sha512(b"", 0, out)
    import hashlib
    assert out.raw == hashlib.sha512(b"").digest()
    big = np.random.RandomState(7).bytes(1 << 17)
    L.ag_sha512(big, len(big), out)
    assert out.raw == hashlib.sha512(big).digest()
