"""Elastic pod membership plane, jax-free (ISSUE 17 satellite).

Everything here runs without a backend: the repartition/re-lift math
and the MembershipEpoch protocol (distributed/membership.py), the
combined negotiation frame codec (distributed/elastic.py — jax-free
at module level), the StragglerMonitor recovery path, and the
live-membership budget threading.  The spawned 2-process differential
that exercises the SAME protocol against real devices lives in
tests/test_elastic_serve.py (slow)."""

import numpy as np
import pytest

from agnes_tpu.distributed.membership import (
    KIND_DENSE_SIGNED,
    KIND_UNSIGNED,
    MembershipEpoch,
    MembershipError,
    TickSlot,
    instance_axis_of,
    merge_tick_plans,
    partition_ranges,
    relift_ranges,
    relift_tree,
    validate_partition,
)
from agnes_tpu.distributed.topology import StragglerMonitor

# -- range repartition --------------------------------------------------------


def test_partition_even_and_sorted():
    assert partition_ranges(8, [1, 0]) == {0: (0, 4), 1: (4, 8)}
    assert partition_ranges(8, [1]) == {1: (0, 8)}
    assert partition_ranges(12, [0, 2, 3]) == {
        0: (0, 4), 2: (4, 8), 3: (8, 12)}


def test_partition_rejects_uneven_and_empty():
    with pytest.raises(MembershipError):
        partition_ranges(7, [0, 1])          # uneven split
    with pytest.raises(MembershipError):
        partition_ranges(8, [])              # nobody alive
    with pytest.raises(MembershipError):
        partition_ranges(0, [0])


def test_validate_partition_disjoint_and_covering():
    ok = {0: (0, 4), 1: (4, 8)}
    validate_partition(ok, 8)
    with pytest.raises(MembershipError, match="overlaps"):
        validate_partition({0: (0, 5), 1: (4, 8)}, 8)
    with pytest.raises(MembershipError, match="unowned"):
        validate_partition({0: (0, 3), 1: (4, 8)}, 8)
    with pytest.raises(MembershipError, match="outside"):
        validate_partition({0: (0, 9)}, 8)


def test_relift_ranges_transfer_plan():
    old = {0: (0, 4), 1: (4, 8)}
    # host 1 leaves: its whole block moves to host 0
    assert relift_ranges(old, {0: (0, 8)}) == [(1, 0, 4, 8)]
    # ... and comes back: the block moves home
    assert relift_ranges({0: (0, 8)}, old) == [(0, 1, 4, 8)]
    # no change -> no transfers
    assert relift_ranges(old, old) == []
    # 3 -> 2 hosts: maximal changed ranges, sorted by lo
    assert relift_ranges(
        {0: (0, 2), 1: (2, 4), 2: (4, 6)},
        {0: (0, 3), 2: (3, 6)}) == [
        (1, 0, 2, 3), (1, 2, 3, 4)]


# -- spec-tree re-lift --------------------------------------------------------


def test_instance_axis_of_spec_leaves():
    # PartitionSpec-like tuples: names / tuples of names / None
    assert instance_axis_of(("slice", "val"), ["slice", "data"]) == 0
    assert instance_axis_of((None, ("slice", "data")),
                            ["slice", "data"]) == 1
    assert instance_axis_of((None, "val"), ["slice", "data"]) is None
    assert instance_axis_of((), ["slice"]) is None


def test_relift_tree_round_trips_leaves():
    old = {0: (0, 2), 1: (2, 4)}
    new = {0: (0, 4)}
    rng = np.random.default_rng(17)
    # two instance-sharded leaves (axis 0 and axis 1) + a replicated
    leaf_a = rng.integers(0, 100, (4, 3))
    leaf_b = rng.integers(0, 100, (2, 4, 5))
    leaf_r = rng.integers(0, 100, (7,))
    blocks = {h: [leaf_a[lo:hi], leaf_b[:, lo:hi], leaf_r]
              for h, (lo, hi) in old.items()}
    out = relift_tree(blocks, old, new, axes=[0, 1, None])
    np.testing.assert_array_equal(out[0][0], leaf_a)
    np.testing.assert_array_equal(out[0][1], leaf_b)
    np.testing.assert_array_equal(out[0][2], leaf_r)
    # ... and back out to the two-host partition, bit-identical
    back = relift_tree(out, new, old, axes=[0, 1, None])
    for h, (lo, hi) in old.items():
        np.testing.assert_array_equal(back[h][0], leaf_a[lo:hi])
        np.testing.assert_array_equal(back[h][1], leaf_b[:, lo:hi])
        np.testing.assert_array_equal(back[h][2], leaf_r)


def test_relift_tree_rejects_bad_partitions():
    blocks = {0: [np.zeros((2, 1))], 1: [np.zeros((2, 1))]}
    with pytest.raises(MembershipError):
        relift_tree(blocks, {0: (0, 2), 1: (2, 4)},
                    {0: (0, 3), 1: (2, 4)}, axes=[0])  # overlap
    with pytest.raises(MembershipError):
        relift_tree(blocks, {0: (0, 2), 1: (1, 4)},
                    {0: (0, 4)}, axes=[0])             # old overlaps


# -- per-tick plan negotiation ------------------------------------------------


def test_merge_picks_the_per_slot_max():
    full = (TickSlot(KIND_DENSE_SIGNED, 3),)
    closed = (TickSlot(KIND_DENSE_SIGNED, 2),)
    assert merge_tick_plans([full, closed]) == full
    # rung and BLS class rung also max per slot
    a = (TickSlot(KIND_DENSE_SIGNED, 2, rung=256, bls_class_rung=1),)
    b = (TickSlot(KIND_DENSE_SIGNED, 3, rung=512, bls_class_rung=4),)
    assert merge_tick_plans([a, b]) == (
        TickSlot(KIND_DENSE_SIGNED, 3, rung=512, bls_class_rung=4),)


def test_merge_pads_missing_slots_and_hosts():
    two = (TickSlot(KIND_DENSE_SIGNED, 3),
           TickSlot(KIND_UNSIGNED, 2))
    # a host with fewer slots contributes nothing to the tail slot
    assert merge_tick_plans([two, two[:1]]) == two
    # an idle host (no slots) adopts the whole merged plan
    assert merge_tick_plans([(), two]) == two
    assert merge_tick_plans([(), ()]) == ()
    assert merge_tick_plans([]) == ()


def test_merge_kind_divergence_fails_loudly():
    with pytest.raises(MembershipError, match="statics divergence"):
        merge_tick_plans([(TickSlot(KIND_DENSE_SIGNED, 3),),
                          (TickSlot(KIND_UNSIGNED, 3),)])


# -- the membership protocol --------------------------------------------------


def test_leave_applies_at_boundary_not_before():
    ep = MembershipEpoch(2, 8)
    assert ep.view.ranges == {0: (0, 4), 1: (4, 8)}
    assert ep.note_leave(1) is True
    assert ep.note_leave(1) is False          # idempotent
    # mid-epoch: partition unchanged, intent latched + broadcastable
    assert ep.view.ranges == {0: (0, 4), 1: (4, 8)}
    assert ep.pending() == (0b10, 0)
    rep = ep.boundary()
    assert rep is not None and rep.left == (1,)
    assert ep.view.epoch == 1 and ep.view.alive == (0,)
    assert ep.view.ranges == {0: (0, 8)}
    assert rep.transfers == ((1, 0, 4, 8),)
    # no pending change -> a boundary burns no epoch
    assert ep.boundary() is None
    assert ep.view.epoch == 1


def test_rejoin_readmits_and_counts():
    ep = MembershipEpoch(2, 8)
    ep.note_leave(1)
    ep.boundary()
    assert ep.note_join(1) is True
    rep = ep.boundary()
    assert rep is not None and rep.joined == (1,)
    assert ep.view.epoch == 2
    assert ep.view.ranges == {0: (0, 4), 1: (4, 8)}
    assert rep.transfers == ((0, 1, 4, 8),)
    assert ep.readmissions == 1 and ep.departures == 1


def test_rejoin_holddown_with_injected_clock():
    clk = {"t": 100.0}
    ep = MembershipEpoch(2, 8, rejoin_holddown_s=10.0,
                         clock=lambda: clk["t"])
    ep.note_leave(1)
    ep.boundary()
    clk["t"] = 105.0                          # inside the holddown
    assert ep.note_join(1) is False
    assert ep.deferred_joins == 1
    assert ep.boundary() is None              # nothing latched
    clk["t"] = 111.0                          # holddown aged out
    assert ep.note_join(1) is True
    rep = ep.boundary()
    assert rep is not None and rep.joined == (1,)
    assert ep.readmissions == 1


def test_merge_intents_from_peer_masks():
    a, b = MembershipEpoch(2, 8), MembershipEpoch(2, 8)
    a.note_leave(1)
    b.merge_intents(*a.pending())             # what the frame carries
    assert b.pending() == a.pending()
    ra, rb = a.boundary(), b.boundary()
    assert ra.new.ranges == rb.new.ranges == {0: (0, 8)}


def test_uneven_live_set_fails_loudly_at_boundary():
    ep = MembershipEpoch(3, 9)                # 9 over 2 can't split
    ep.note_leave(2)
    with pytest.raises(MembershipError, match="evenly"):
        ep.boundary()


# -- the combined elastic frame codec ----------------------------------------


def test_elastic_frame_round_trip():
    from agnes_tpu.distributed.elastic import (
        elastic_frame_capacity,
        pack_elastic_frame,
        unpack_elastic_frame,
    )
    from agnes_tpu.distributed.topology import pack_decision_frame

    slots = (TickSlot(KIND_DENSE_SIGNED, 3),
             TickSlot(KIND_UNSIGNED, 2, rung=0, bls_class_rung=4))
    dec = pack_decision_frame(
        1, np.asarray([5, 6]), np.asarray([2, -1]),
        np.asarray([7, 7]), np.asarray([0, 1]), max_decisions=4)
    reroute = bytes(range(96)) * 2            # two fake records
    frame = pack_elastic_frame(
        1, 3, 0b11, 0b10, 0b01, slots, dec, reroute,
        max_slots=4, reroute_cap=96 * 4)
    assert len(frame) == elastic_frame_capacity(4, 4, 96 * 4)
    f = unpack_elastic_frame(frame, 4, 4, 96 * 4)
    assert (f.host, f.epoch) == (1, 3)
    assert (f.alive_mask, f.leave_mask, f.join_mask) == (3, 2, 1)
    assert f.slots == slots
    assert [(d.instance, d.host, d.round, d.value_id)
            for d in f.decisions] == [(5, 1, 7, 2), (6, 1, 7, None)]
    assert f.reroute == reroute


def test_elastic_frame_capacity_enforced():
    from agnes_tpu.distributed.elastic import (
        pack_elastic_frame,
        unpack_elastic_frame,
    )
    from agnes_tpu.distributed.topology import pack_decision_frame

    dec = pack_decision_frame(0, np.asarray([], np.int64),
                              np.asarray([], np.int64),
                              np.asarray([], np.int64),
                              np.asarray([], np.int64),
                              max_decisions=1)
    too_many = tuple(TickSlot(KIND_DENSE_SIGNED, 3)
                     for _ in range(5))
    with pytest.raises(MembershipError, match="slots"):
        pack_elastic_frame(0, 0, 1, 0, 0, too_many, dec, b"",
                           max_slots=4, reroute_cap=96)
    with pytest.raises(MembershipError, match="reroute"):
        pack_elastic_frame(0, 0, 1, 0, 0, (), dec, bytes(96 * 2),
                           max_slots=4, reroute_cap=96)
    with pytest.raises(MembershipError, match="whole"):
        pack_elastic_frame(0, 0, 1, 0, 0, (), dec, bytes(95),
                           max_slots=4, reroute_cap=96)
    ok = pack_elastic_frame(0, 0, 1, 0, 0, (), dec, b"",
                            max_slots=4, reroute_cap=96)
    with pytest.raises(MembershipError, match="magic"):
        unpack_elastic_frame(np.zeros_like(ok), 4, 1, 96)
    with pytest.raises(MembershipError, match="capacities"):
        unpack_elastic_frame(ok[:-1], 4, 1, 96)


# -- StragglerMonitor recovery (the readmission satellite) --------------------


def test_monitor_dead_verdict_recovers_and_counts():
    clk = {"t": 100.0}
    m = StragglerMonitor(2, 0, dead_after_s=30.0,
                         straggler_after_s=5.0,
                         clock=lambda: clk["t"])
    clk["t"] = 140.0
    assert m.dead() == [1]
    # fresh evidence CLEARS the verdict (no longer permanent) ...
    m.beat(1)
    assert m.dead() == [] and m.check() == []
    # ... and is counted as a readmission
    assert m.readmissions == 1
    # a live beat is not a readmission
    m.beat(1)
    assert m.readmissions == 1


def test_monitor_fail_closed_without_membership_plane():
    from agnes_tpu.distributed.topology import DeadHostError

    clk = {"t": 0.0}
    m = StragglerMonitor(2, 0, dead_after_s=30.0,
                         straggler_after_s=5.0,
                         clock=lambda: clk["t"])
    clk["t"] = 40.0
    with pytest.raises(DeadHostError):
        m.check()                             # the ISSUE-15 contract


def test_monitor_with_membership_degrades_to_intents():
    clk = {"t": 0.0}
    m = StragglerMonitor(2, 0, dead_after_s=30.0,
                         straggler_after_s=5.0,
                         clock=lambda: clk["t"])
    ep = MembershipEpoch(2, 8)
    m.attach_membership(ep)
    clk["t"] = 40.0
    assert m.check() == []                    # degrades, no raise
    assert ep.pending() == (0b10, 0)          # leave latched once
    m.check()
    assert ep.pending() == (0b10, 0)
    ep.boundary()
    assert ep.view.alive == (0,)
    # resumed evidence latches the join intent through the monitor
    m.beat(1)
    assert m.readmissions == 1
    assert ep.pending() == (0, 0b10)
    rep = ep.boundary()
    assert rep.joined == (1,) and ep.readmissions == 1


# -- live-membership budget threading (the plan satellite) --------------------


class _FakeMesh:
    def __init__(self, **shape):
        self.shape = shape


def test_mesh_local_shape_live_membership():
    from agnes_tpu.utils.budget import mesh_local_shape

    pod = _FakeMesh(slice=2, data=1, val=2)
    # static pod: each of 2 hosts' slice divides by its data share
    assert mesh_local_shape(pod, 4, 4, n_hosts=2) == (4, 2)
    # shrunk to ONE live owner: its slice is the whole deployment,
    # spread over the WHOLE data extent (the sleeper's devices stay
    # in the mesh) — per-device load is unchanged, and the live
    # divisor is what keeps the plan from under-claiming
    assert mesh_local_shape(pod, 8, 4, n_hosts=2, n_live=1) == (4, 2)
    with pytest.raises(ValueError, match="live membership"):
        mesh_local_shape(pod, 8, 4, n_hosts=2, n_live=3)
    with pytest.raises(ValueError, match="live membership"):
        mesh_local_shape(pod, 8, 4, n_hosts=2, n_live=0)


def test_plan_dense_replans_for_live_membership():
    from agnes_tpu.serve.batcher import ShapeLadder

    hbm = 1 << 34
    static = ShapeLadder.plan_dense(8, 4, local_shape=(4, 2),
                                    n_hosts=2, min_rung=4,
                                    hbm_bytes=hbm)
    # one live owner serves the WHOLE deployment: the top rung paces
    # a full-deployment tick, twice the static per-host figure
    shrunk = ShapeLadder.plan_dense(8, 4, local_shape=(4, 2),
                                    n_hosts=2, n_live=1, min_rung=4,
                                    hbm_bytes=hbm)
    assert shrunk.max_rung == 2 * static.max_rung
    with pytest.raises(ValueError, match="live membership"):
        ShapeLadder.plan_dense(8, 4, n_hosts=2, n_live=3)
    with pytest.raises(ValueError, match="repartition evenly"):
        # 9 shards over 3 hosts, but 2 survivors cannot split it
        ShapeLadder.plan_dense(9, 3, local_shape=(3, 3), n_hosts=3,
                               n_live=2, min_rung=4, hbm_bytes=hbm)
