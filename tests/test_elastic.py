"""Elastic pod membership plane, jax-free (ISSUE 17 satellite).

Everything here runs without a backend: the repartition/re-lift math
and the MembershipEpoch protocol (distributed/membership.py), the
combined negotiation frame codec (distributed/elastic.py — jax-free
at module level), the StragglerMonitor recovery path, and the
live-membership budget threading.  The spawned 2-process differential
that exercises the SAME protocol against real devices lives in
tests/test_elastic_serve.py (slow)."""

import numpy as np
import pytest

from agnes_tpu.distributed.membership import (
    KIND_DENSE_SIGNED,
    KIND_UNSIGNED,
    MembershipEpoch,
    MembershipError,
    TickSlot,
    instance_axis_of,
    merge_tick_plans,
    partition_ranges,
    relift_ranges,
    relift_tree,
    validate_partition,
)
from agnes_tpu.distributed.topology import StragglerMonitor

# -- range repartition --------------------------------------------------------


def test_partition_even_and_sorted():
    assert partition_ranges(8, [1, 0]) == {0: (0, 4), 1: (4, 8)}
    assert partition_ranges(8, [1]) == {1: (0, 8)}
    assert partition_ranges(12, [0, 2, 3]) == {
        0: (0, 4), 2: (4, 8), 3: (8, 12)}


def test_partition_rejects_uneven_and_empty():
    with pytest.raises(MembershipError):
        partition_ranges(7, [0, 1])          # uneven split
    with pytest.raises(MembershipError):
        partition_ranges(8, [])              # nobody alive
    with pytest.raises(MembershipError):
        partition_ranges(0, [0])


def test_validate_partition_disjoint_and_covering():
    ok = {0: (0, 4), 1: (4, 8)}
    validate_partition(ok, 8)
    with pytest.raises(MembershipError, match="overlaps"):
        validate_partition({0: (0, 5), 1: (4, 8)}, 8)
    with pytest.raises(MembershipError, match="unowned"):
        validate_partition({0: (0, 3), 1: (4, 8)}, 8)
    with pytest.raises(MembershipError, match="outside"):
        validate_partition({0: (0, 9)}, 8)


def test_relift_ranges_transfer_plan():
    old = {0: (0, 4), 1: (4, 8)}
    # host 1 leaves: its whole block moves to host 0
    assert relift_ranges(old, {0: (0, 8)}) == [(1, 0, 4, 8)]
    # ... and comes back: the block moves home
    assert relift_ranges({0: (0, 8)}, old) == [(0, 1, 4, 8)]
    # no change -> no transfers
    assert relift_ranges(old, old) == []
    # 3 -> 2 hosts: maximal changed ranges, sorted by lo
    assert relift_ranges(
        {0: (0, 2), 1: (2, 4), 2: (4, 6)},
        {0: (0, 3), 2: (3, 6)}) == [
        (1, 0, 2, 3), (1, 2, 3, 4)]


# -- spec-tree re-lift --------------------------------------------------------


def test_instance_axis_of_spec_leaves():
    # PartitionSpec-like tuples: names / tuples of names / None
    assert instance_axis_of(("slice", "val"), ["slice", "data"]) == 0
    assert instance_axis_of((None, ("slice", "data")),
                            ["slice", "data"]) == 1
    assert instance_axis_of((None, "val"), ["slice", "data"]) is None
    assert instance_axis_of((), ["slice"]) is None


def test_relift_tree_round_trips_leaves():
    old = {0: (0, 2), 1: (2, 4)}
    new = {0: (0, 4)}
    rng = np.random.default_rng(17)
    # two instance-sharded leaves (axis 0 and axis 1) + a replicated
    leaf_a = rng.integers(0, 100, (4, 3))
    leaf_b = rng.integers(0, 100, (2, 4, 5))
    leaf_r = rng.integers(0, 100, (7,))
    blocks = {h: [leaf_a[lo:hi], leaf_b[:, lo:hi], leaf_r]
              for h, (lo, hi) in old.items()}
    out = relift_tree(blocks, old, new, axes=[0, 1, None])
    np.testing.assert_array_equal(out[0][0], leaf_a)
    np.testing.assert_array_equal(out[0][1], leaf_b)
    np.testing.assert_array_equal(out[0][2], leaf_r)
    # ... and back out to the two-host partition, bit-identical
    back = relift_tree(out, new, old, axes=[0, 1, None])
    for h, (lo, hi) in old.items():
        np.testing.assert_array_equal(back[h][0], leaf_a[lo:hi])
        np.testing.assert_array_equal(back[h][1], leaf_b[:, lo:hi])
        np.testing.assert_array_equal(back[h][2], leaf_r)


def test_relift_tree_rejects_bad_partitions():
    blocks = {0: [np.zeros((2, 1))], 1: [np.zeros((2, 1))]}
    with pytest.raises(MembershipError):
        relift_tree(blocks, {0: (0, 2), 1: (2, 4)},
                    {0: (0, 3), 1: (2, 4)}, axes=[0])  # overlap
    with pytest.raises(MembershipError):
        relift_tree(blocks, {0: (0, 2), 1: (1, 4)},
                    {0: (0, 4)}, axes=[0])             # old overlaps


# -- per-tick plan negotiation ------------------------------------------------


def test_merge_picks_the_per_slot_max():
    full = (TickSlot(KIND_DENSE_SIGNED, 3),)
    closed = (TickSlot(KIND_DENSE_SIGNED, 2),)
    assert merge_tick_plans([full, closed]) == full
    # rung and BLS class rung also max per slot
    a = (TickSlot(KIND_DENSE_SIGNED, 2, rung=256, bls_class_rung=1),)
    b = (TickSlot(KIND_DENSE_SIGNED, 3, rung=512, bls_class_rung=4),)
    assert merge_tick_plans([a, b]) == (
        TickSlot(KIND_DENSE_SIGNED, 3, rung=512, bls_class_rung=4),)


def test_merge_pads_missing_slots_and_hosts():
    two = (TickSlot(KIND_DENSE_SIGNED, 3),
           TickSlot(KIND_UNSIGNED, 2))
    # a host with fewer slots contributes nothing to the tail slot
    assert merge_tick_plans([two, two[:1]]) == two
    # an idle host (no slots) adopts the whole merged plan
    assert merge_tick_plans([(), two]) == two
    assert merge_tick_plans([(), ()]) == ()
    assert merge_tick_plans([]) == ()


def test_merge_kind_divergence_fails_loudly():
    with pytest.raises(MembershipError, match="statics divergence"):
        merge_tick_plans([(TickSlot(KIND_DENSE_SIGNED, 3),),
                          (TickSlot(KIND_UNSIGNED, 3),)])


# -- the membership protocol --------------------------------------------------


def test_leave_applies_at_boundary_not_before():
    ep = MembershipEpoch(2, 8)
    assert ep.view.ranges == {0: (0, 4), 1: (4, 8)}
    assert ep.note_leave(1) is True
    assert ep.note_leave(1) is False          # idempotent
    # mid-epoch: partition unchanged, intent latched + broadcastable
    assert ep.view.ranges == {0: (0, 4), 1: (4, 8)}
    assert ep.pending() == (0b10, 0)
    rep = ep.boundary()
    assert rep is not None and rep.left == (1,)
    assert ep.view.epoch == 1 and ep.view.alive == (0,)
    assert ep.view.ranges == {0: (0, 8)}
    assert rep.transfers == ((1, 0, 4, 8),)
    # no pending change -> a boundary burns no epoch
    assert ep.boundary() is None
    assert ep.view.epoch == 1


def test_rejoin_readmits_and_counts():
    ep = MembershipEpoch(2, 8)
    ep.note_leave(1)
    ep.boundary()
    assert ep.note_join(1) is True
    rep = ep.boundary()
    assert rep is not None and rep.joined == (1,)
    assert ep.view.epoch == 2
    assert ep.view.ranges == {0: (0, 4), 1: (4, 8)}
    assert rep.transfers == ((0, 1, 4, 8),)
    assert ep.readmissions == 1 and ep.departures == 1


def test_rejoin_holddown_with_injected_ticks():
    # the holddown clock is the lockstep LOGICAL tick (note_tick) and
    # the departure stamps at the boundary that applied it — both
    # pod-shared state, so the deferral verdict cannot diverge across
    # hosts the way per-process wall clocks near a threshold would
    ep = MembershipEpoch(2, 8, rejoin_holddown_ticks=3)
    ep.note_tick()
    ep.note_leave(1)
    ep.boundary()                             # departure stamps tick 1
    ep.note_tick()                            # tick 2: 1 tick elapsed
    assert ep.note_join(1) is False
    assert ep.deferred_joins == 1
    assert ep.boundary() is None              # nothing latched
    ep.note_tick()
    ep.note_tick()                            # tick 4: holddown aged out
    assert ep.note_join(1) is True
    rep = ep.boundary()
    assert rep is not None and rep.joined == (1,)
    assert ep.readmissions == 1


def test_rejoin_holddown_verdict_identical_across_hosts():
    # an originator that latches a join broadcasts it on the NEXT
    # frame: peers evaluate the (monotone-in-tick) holddown predicate
    # at the same or a later tick, so a join latched anywhere latches
    # everywhere — the pending sets never diverge
    a, b = (MembershipEpoch(2, 8, rejoin_holddown_ticks=2)
            for _ in range(2))
    for ep in (a, b):
        ep.note_tick()
        ep.note_leave(1)
        ep.boundary()                         # both stamp tick 1
        ep.note_tick()
        ep.note_tick()                        # tick 3
    assert a.note_join(1) is True             # 3 - 1 >= 2: latches
    for ep in (a, b):
        ep.note_tick()                        # the broadcast tick
    b.merge_intents(*a.pending())
    assert b.pending() == a.pending()
    assert b.deferred_joins == 0
    ra, rb = a.boundary(), b.boundary()
    assert ra.new.ranges == rb.new.ranges


def test_rejoin_holddown_skips_unapplied_leaves():
    # a leave cancelled before any boundary never moved the partition
    # — the intra-epoch flap owes no holddown (the mem_flap corpus
    # milestone's semantics)
    ep = MembershipEpoch(2, 8, rejoin_holddown_ticks=5)
    ep.note_tick()
    ep.note_leave(1)
    assert ep.note_join(1) is True
    assert ep.deferred_joins == 0
    assert ep.boundary() is None              # net no-op, no epoch burned
    assert ep.view.epoch == 0


def test_merge_intents_from_peer_masks():
    a, b = MembershipEpoch(2, 8), MembershipEpoch(2, 8)
    a.note_leave(1)
    b.merge_intents(*a.pending())             # what the frame carries
    assert b.pending() == a.pending()
    ra, rb = a.boundary(), b.boundary()
    assert ra.new.ranges == rb.new.ranges == {0: (0, 8)}


def test_uneven_live_set_fails_loudly_at_boundary():
    ep = MembershipEpoch(3, 9)                # 9 over 2 can't split
    ep.note_leave(2)
    with pytest.raises(MembershipError, match="evenly"):
        ep.boundary()


# -- the combined elastic frame codec ----------------------------------------


def test_elastic_frame_round_trip():
    from agnes_tpu.distributed.elastic import (
        elastic_frame_capacity,
        pack_elastic_frame,
        unpack_elastic_frame,
    )
    from agnes_tpu.distributed.topology import pack_decision_frame

    slots = (TickSlot(KIND_DENSE_SIGNED, 3),
             TickSlot(KIND_UNSIGNED, 2, rung=0, bls_class_rung=4))
    dec = pack_decision_frame(
        1, np.asarray([5, 6]), np.asarray([2, -1]),
        np.asarray([7, 7]), np.asarray([0, 1]), max_decisions=4)
    reroute = bytes(range(96)) * 2            # two fake records
    frame = pack_elastic_frame(
        1, 3, 0b11, 0b10, 0b01, slots, dec, reroute,
        max_slots=4, reroute_cap=96 * 4)
    assert len(frame) == elastic_frame_capacity(4, 4, 96 * 4)
    f = unpack_elastic_frame(frame, 4, 4, 96 * 4)
    assert (f.host, f.epoch) == (1, 3)
    assert (f.alive_mask, f.leave_mask, f.join_mask) == (3, 2, 1)
    assert f.slots == slots
    assert [(d.instance, d.host, d.round, d.value_id)
            for d in f.decisions] == [(5, 1, 7, 2), (6, 1, 7, None)]
    assert f.reroute == reroute


def test_elastic_frame_capacity_enforced():
    from agnes_tpu.distributed.elastic import (
        pack_elastic_frame,
        unpack_elastic_frame,
    )
    from agnes_tpu.distributed.topology import pack_decision_frame

    dec = pack_decision_frame(0, np.asarray([], np.int64),
                              np.asarray([], np.int64),
                              np.asarray([], np.int64),
                              np.asarray([], np.int64),
                              max_decisions=1)
    too_many = tuple(TickSlot(KIND_DENSE_SIGNED, 3)
                     for _ in range(5))
    with pytest.raises(MembershipError, match="slots"):
        pack_elastic_frame(0, 0, 1, 0, 0, too_many, dec, b"",
                           max_slots=4, reroute_cap=96)
    with pytest.raises(MembershipError, match="reroute"):
        pack_elastic_frame(0, 0, 1, 0, 0, (), dec, bytes(96 * 2),
                           max_slots=4, reroute_cap=96)
    with pytest.raises(MembershipError, match="whole"):
        pack_elastic_frame(0, 0, 1, 0, 0, (), dec, bytes(95),
                           max_slots=4, reroute_cap=96)
    ok = pack_elastic_frame(0, 0, 1, 0, 0, (), dec, b"",
                            max_slots=4, reroute_cap=96)
    with pytest.raises(MembershipError, match="magic"):
        unpack_elastic_frame(np.zeros_like(ok), 4, 1, 96)
    with pytest.raises(MembershipError, match="capacities"):
        unpack_elastic_frame(ok[:-1], 4, 1, 96)


# -- StragglerMonitor recovery (the readmission satellite) --------------------


def test_monitor_dead_verdict_recovers_and_counts():
    clk = {"t": 100.0}
    m = StragglerMonitor(2, 0, dead_after_s=30.0,
                         straggler_after_s=5.0,
                         clock=lambda: clk["t"])
    clk["t"] = 140.0
    assert m.dead() == [1]
    # fresh evidence CLEARS the verdict (no longer permanent) ...
    m.beat(1)
    assert m.dead() == [] and m.check() == []
    # ... and is counted as a readmission
    assert m.readmissions == 1
    # a live beat is not a readmission
    m.beat(1)
    assert m.readmissions == 1


def test_monitor_fail_closed_without_membership_plane():
    from agnes_tpu.distributed.topology import DeadHostError

    clk = {"t": 0.0}
    m = StragglerMonitor(2, 0, dead_after_s=30.0,
                         straggler_after_s=5.0,
                         clock=lambda: clk["t"])
    clk["t"] = 40.0
    with pytest.raises(DeadHostError):
        m.check()                             # the ISSUE-15 contract


def test_monitor_with_membership_degrades_to_intents():
    clk = {"t": 0.0}
    m = StragglerMonitor(2, 0, dead_after_s=30.0,
                         straggler_after_s=5.0,
                         clock=lambda: clk["t"])
    ep = MembershipEpoch(2, 8)
    m.attach_membership(ep)
    clk["t"] = 40.0
    assert m.check() == []                    # degrades, no raise
    assert ep.pending() == (0b10, 0)          # leave latched once
    m.check()
    assert ep.pending() == (0b10, 0)
    ep.boundary()
    assert ep.view.alive == (0,)
    # resumed evidence latches the join intent through the monitor
    m.beat(1)
    assert m.readmissions == 1
    assert ep.pending() == (0, 0b10)
    rep = ep.boundary()
    assert rep.joined == (1,) and ep.readmissions == 1


# -- static-home gossip routing (jax-free ElasticShard surface) ---------------


class _SinkService:
    """The slice of VoteService the front-door screen touches."""

    def __init__(self):
        self.got = []
        self.flightrec = None

        class _M:
            @staticmethod
            def count(*a, **k):
                pass

        self.metrics = _M()

    def submit(self, b):
        self.got.append(bytes(b))


def _rec(inst):
    from agnes_tpu.bridge.native_ingest import REC_SIZE

    r = np.zeros(REC_SIZE, np.uint8)
    r[0:4] = np.asarray([inst], np.uint32).view(np.uint8)
    return r


def _routing_shard(host, membership, per=3):
    """An ElasticShard reduced to its routing surface: the screen
    methods only touch plan/lo/hi/membership/service, so the jax-free
    predicate is testable without a driver or a backend."""
    from agnes_tpu.bridge.native_ingest import REC_SIZE
    from agnes_tpu.distributed.elastic import ElasticShard
    from agnes_tpu.distributed.topology import HostPlan

    sh = ElasticShard.__new__(ElasticShard)
    sh.n_hosts = membership.view.n_hosts
    sh.host = host
    sh.plan = HostPlan(sh.n_hosts, sh.n_hosts * per)
    sh.lo, sh.hi = host * per, (host + 1) * per
    sh.membership = membership
    sh.service = _SinkService()
    sh.reroute_capacity = 64 * REC_SIZE
    sh._held = []
    sh.foreign_rejects = sh.adopted_held = sh.held_dropped = 0
    sh.reroute_sent = sh.reroute_received = sh.reroute_reheld = 0
    return sh


def _departed(n_hosts, per, *left):
    ep = MembershipEpoch(n_hosts, n_hosts * per)
    for h in left:
        ep.note_leave(h)
    ep.boundary()
    return ep


def test_submit_holds_departed_homes_only():
    # 4 hosts x 3: host 2 away -> ranges {0:(0,4), 1:(4,8), 3:(8,12)}.
    # Host 1 (static 3..6, owns 4..8): inst 6,7 have home 2 (away) ->
    # HELD; inst 3 is static-mine even though epoch-owned by host 0;
    # inst 8 is epoch-foreign.
    ep = _departed(4, 3, 2)
    assert ep.view.ranges == {0: (0, 4), 1: (4, 8), 3: (8, 12)}
    sh = _routing_shard(1, ep)
    sh.submit(b"".join(_rec(i).tobytes() for i in (3, 6, 7, 8)))
    assert sh.adopted_held == 2 and len(sh._held) == 2
    assert sh.foreign_rejects == 1
    # the static-mine record reached the local service, rebased
    from agnes_tpu.distributed.topology import wire_instance_ids

    kept = np.frombuffer(sh.service.got[0], np.uint8)
    assert list(wire_instance_ids(kept.reshape(1, -1))) == [0]


def test_submit_rejects_live_homes_in_owned_range():
    # hosts 2 AND 3 away -> ranges {0:(0,6), 1:(6,12)}.  Host 0 owns
    # 0..6 but inst 3,4,5 belong to host 1's static block and host 1
    # is ALIVE: its own front door serves them, so adopting here would
    # replay duplicates — they must be foreign, never held.
    ep = _departed(4, 3, 2, 3)
    assert ep.view.ranges == {0: (0, 6), 1: (6, 12)}
    sh = _routing_shard(0, ep)
    sh.submit(b"".join(_rec(i).tobytes() for i in (3, 4, 5)))
    assert sh.adopted_held == 0 and sh._held == []
    assert sh.foreign_rejects == 3
    # host 1 holds for BOTH departed static blocks it now owns
    sh1 = _routing_shard(1, ep)
    sh1.submit(b"".join(_rec(i).tobytes() for i in (6, 8, 9, 11)))
    assert sh1.adopted_held == 4 and sh1.foreign_rejects == 0


def test_take_reroute_targets_static_home_not_epoch_owner():
    # host 1 holds inst 6 (home 2) and inst 10 (home 3) while both
    # are away; host 2 rejoins.  Only inst 6 may travel: inst 10's
    # epoch owner is a live host whose static screen would discard it
    # (the silent-loss path) — it stays with its holder.
    ep = _departed(4, 3, 2, 3)
    sh = _routing_shard(1, ep)
    sh._hold(np.stack([_rec(6), _rec(10)]))
    ep.note_join(2)
    ep.boundary()
    out = sh._take_reroute(ep.view)
    from agnes_tpu.bridge.native_ingest import REC_SIZE
    from agnes_tpu.distributed.topology import wire_instance_ids

    sent = np.frombuffer(out, np.uint8).reshape(-1, REC_SIZE)
    assert list(wire_instance_ids(sent)) == [6]
    assert sh.reroute_sent == 1 and len(sh._held) == 1


def test_ingest_reroute_absorbs_static_block_and_reholds_strays():
    ep = _departed(4, 3, 3)
    raw = b"".join(_rec(i).tobytes() for i in (6, 7, 0))
    # the readmitted home (host 2, static 6..9) absorbs its records
    # rebased; host 0's record is another screen's business
    sh2 = _routing_shard(2, ep)
    sh2._ingest_reroute(raw)
    assert sh2.reroute_received == 2 and sh2.reroute_reheld == 0
    from agnes_tpu.distributed.topology import wire_instance_ids

    kept = np.frombuffer(sh2.service.got[0], np.uint8)
    assert list(wire_instance_ids(kept.reshape(2, -1))) == [0, 1]
    # a stray addressed to a STILL-DEPARTED home (sender bug) is
    # re-held by the current epoch owner, not dropped: host 2 owns
    # 8..12 after host 3 left, so inst 10 (home 3) re-holds there
    ep3 = _departed(4, 3, 3)
    assert ep3.view.ranges[2] == (8, 12)
    sh = _routing_shard(2, ep3)
    sh._ingest_reroute(_rec(10).tobytes())
    assert sh.reroute_received == 0
    assert sh.reroute_reheld == 1 and len(sh._held) == 1


# -- live-membership budget threading (the plan satellite) --------------------


class _FakeMesh:
    def __init__(self, **shape):
        self.shape = shape


def test_mesh_local_shape_live_membership():
    from agnes_tpu.utils.budget import mesh_local_shape

    pod = _FakeMesh(slice=2, data=1, val=2)
    # static pod: each of 2 hosts' slice divides by its data share
    assert mesh_local_shape(pod, 4, 4, n_hosts=2) == (4, 2)
    # shrunk to ONE live owner: its slice is the whole deployment,
    # spread over the WHOLE data extent (the sleeper's devices stay
    # in the mesh) — per-device load is unchanged, and the live
    # divisor is what keeps the plan from under-claiming
    assert mesh_local_shape(pod, 8, 4, n_hosts=2, n_live=1) == (4, 2)
    with pytest.raises(ValueError, match="live membership"):
        mesh_local_shape(pod, 8, 4, n_hosts=2, n_live=3)
    with pytest.raises(ValueError, match="live membership"):
        mesh_local_shape(pod, 8, 4, n_hosts=2, n_live=0)


def test_plan_dense_replans_for_live_membership():
    from agnes_tpu.serve.batcher import ShapeLadder

    hbm = 1 << 34
    static = ShapeLadder.plan_dense(8, 4, local_shape=(4, 2),
                                    n_hosts=2, min_rung=4,
                                    hbm_bytes=hbm)
    # one live owner serves the WHOLE deployment: the top rung paces
    # a full-deployment tick, twice the static per-host figure
    shrunk = ShapeLadder.plan_dense(8, 4, local_shape=(4, 2),
                                    n_hosts=2, n_live=1, min_rung=4,
                                    hbm_bytes=hbm)
    assert shrunk.max_rung == 2 * static.max_rung
    with pytest.raises(ValueError, match="live membership"):
        ShapeLadder.plan_dense(8, 4, n_hosts=2, n_live=3)
    with pytest.raises(ValueError, match="repartition evenly"):
        # 9 shards over 3 hosts, but 2 survivors cannot split it
        ShapeLadder.plan_dense(9, 3, local_shape=(3, 3), n_hosts=3,
                               n_live=2, min_rung=4, hbm_bytes=hbm)
