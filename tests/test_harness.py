"""Harness: Byzantine network simulations + the six configs (small)."""

import numpy as np
import pytest

from agnes_tpu.harness.configs import CONFIGS
from agnes_tpu.harness.device_driver import DeviceDriver
from agnes_tpu.harness.simulator import Network, NodeSpec
from agnes_tpu.types import VoteType


def test_network_with_silent_node_still_decides():
    """3 of 4 honest is exactly +2/3: consensus proceeds via timeouts."""
    net = Network(n=4, specs=[NodeSpec(), NodeSpec(), NodeSpec(),
                              NodeSpec(behavior="silent")])
    net.start()
    net.run_until(lambda: net.decided(0))
    assert set(net.decisions(0)) == {100}
    assert net.dropped > 0


def test_network_equivocator_detected_and_consensus_holds():
    net = Network(n=4, specs=[NodeSpec(behavior="equivocator"),
                              NodeSpec(), NodeSpec(), NodeSpec()])
    net.start()
    net.run_until(lambda: net.decided(0))
    assert set(net.decisions(0)) == {100}
    ev = net.equivocations()
    assert ev, "double-sign evidence must be collected"
    flagged = {e.validator for evs in ev.values() for e in evs}
    # the equivocator's sorted index is the only flagged validator
    eq_idx = [i for i, s in enumerate(net.specs)
              if s.behavior == "equivocator"]
    assert flagged == set(eq_idx)


def test_network_nil_flooder_delays_but_does_not_block():
    net = Network(n=4, specs=[NodeSpec(behavior="nil_flood"),
                              NodeSpec(), NodeSpec(), NodeSpec()])
    net.start()
    net.run_until(lambda: net.decided(0))
    assert set(net.decisions(0)) == {100}


def test_device_driver_honest_round():
    d = DeviceDriver(n_instances=4, n_validators=8)
    d.run_honest_round(0, slot=1)
    assert d.all_decided()
    assert (np.asarray(d.stats.decision_value) == 1).all()
    assert (np.asarray(d.stats.decision_round) == 0).all()


def test_device_driver_nil_then_decide():
    d = DeviceDriver(n_instances=4, n_validators=8, proposer_is_self=False)
    d.run_nil_round(0)
    assert not d.stats.decided.any()
    assert (np.asarray(d.state.round) == 1).all()
    d.run_proposed_round(1, slot=2)
    assert d.all_decided(value=2)
    assert (np.asarray(d.stats.decision_round) == 1).all()


def test_device_driver_equivocation_detection():
    d = DeviceDriver(n_instances=3, n_validators=8)
    d.step()
    expected = d.run_equivocation_phase(0, VoteType.PREVOTE, 1, 2, frac=0.5)
    det = d.equivocators_detected()
    assert (det == expected).all()
    # honest completion: first votes still count
    d.step(phase=d.phase(0, VoteType.PREVOTE, 1, frac=1.0))
    d.step(phase=d.phase(0, VoteType.PRECOMMIT, 1))
    assert d.all_decided(value=1)


@pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 6])
def test_configs_small(n):
    out = CONFIGS[n](small=True)
    assert out["config"] == n


def test_partition_stalls_then_heals_to_decision():
    """The liveness-recovery scenario: a 2-2 partition of 4 honest
    nodes leaves no side with +2/3 power, so neither side can decide
    (nodes stall exactly where Tendermint stalls — no PolkaAny, no
    prevote timeout); heal() delivers the gossip-held cross traffic,
    the mixed nil/value prevotes drive the timeout chain to a fresh
    round, and the reunited quorum decides unanimously at round
    >= 1."""
    net = Network(n=4)
    net.start()
    heal_round = net.partition_heal_drill([0, 1], [2, 3])
    assert heal_round >= 1                  # decided after recovery
    assert net.held_partition > 0           # traffic was held, not lost
    assert net.equivocations() == {}        # nobody double-signed


def test_partition_requires_total_membership():
    net = Network(n=4)
    with pytest.raises(AssertionError):
        net.partition([0, 1], [2])          # node 3 unassigned
    with pytest.raises(AssertionError):
        net.partition([0, 1], [1, 2, 3])    # node 1 twice
