"""utils: checkpoint/resume, metrics, tracing, config."""

import json
import os

import numpy as np
import pytest

from agnes_tpu.harness.device_driver import DeviceDriver
from agnes_tpu.harness.simulator import Network
from agnes_tpu.types import VoteType
from agnes_tpu.utils import Metrics, RunConfig, Tracer, span
from agnes_tpu.utils.checkpoint import (
    load_driver,
    load_executor_into,
    save_driver,
    save_executor,
)
from agnes_tpu.utils.metrics import DECISIONS, VOTES_INGESTED, \
    attach_to_driver


def test_driver_checkpoint_roundtrip(tmp_path):
    """Snapshot mid-consensus, resume, finish — byte-identical state."""
    d = DeviceDriver(n_instances=4, n_validators=8)
    d.step()
    d.step(phase=d.phase(0, VoteType.PREVOTE, 1))  # polka reached
    path = str(tmp_path / "snap.npz")
    save_driver(d, path)

    d2 = load_driver(path)
    for a, b in zip(d.state, d2.state):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(d.tally, d2.tally):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert d2.stats.votes_ingested == d.stats.votes_ingested

    # both copies complete identically from the snapshot
    d.step(phase=d.phase(0, VoteType.PRECOMMIT, 1))
    d2.step(phase=d2.phase(0, VoteType.PRECOMMIT, 1))
    assert d.all_decided(value=1) and d2.all_decided(value=1)
    assert np.array_equal(d.stats.decision_round, d2.stats.decision_round)


def test_driver_checkpoint_preserves_configuration(tmp_path):
    """proposer_is_self=False (nil-round setup) must survive resume —
    a resumed driver defaulting to self-proposal would diverge."""
    d = DeviceDriver(n_instances=2, n_validators=4, proposer_is_self=False)
    d.step()
    path = str(tmp_path / "cfg.npz")
    save_driver(d, path)
    d2 = load_driver(path)
    assert not bool(np.asarray(d2.proposer_flag).any())
    assert np.array_equal(np.asarray(d.powers), np.asarray(d2.powers))
    # both continue the nil round identically
    from agnes_tpu.core.state_machine import EventTag
    for x in (d, d2):
        x.step(ext=x.ext(int(EventTag.TIMEOUT_PROPOSE), 0))
        x.step(phase=x.phase(0, VoteType.PREVOTE, -1))
    for a, b in zip(d.state, d2.state):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_executor_checkpoint_resume(tmp_path):
    """A node snapshots after deciding heights, restarts, rejoins and
    keeps deciding with the same network."""
    net = Network(n=4)
    net.start()
    net.run_until(lambda: net.decided(1))
    victim = net.nodes[0]
    path = str(tmp_path / "node0.json")
    save_executor(victim, path)

    # fresh executor, same identity; restore
    from agnes_tpu.core.executor import ConsensusExecutor
    fresh = ConsensusExecutor(net.vset, index=0, seed=net.seeds[0],
                              get_value=lambda h: 100 + h)
    h, decided = load_executor_into(fresh, path)
    assert h >= 2 and decided[0].value == 100 and decided[1].value == 101
    assert fresh.state.height == h


def test_executor_checkpoint_preserves_evidence(tmp_path):
    """ADVICE r1: collected double-sign evidence must survive a restart
    (the executor deliberately archives it across heights)."""
    from agnes_tpu.core.executor import ConsensusExecutor
    from agnes_tpu.harness.simulator import NodeSpec

    net = Network(n=4, specs=[NodeSpec(behavior="equivocator"),
                              NodeSpec(), NodeSpec(), NodeSpec()])
    net.start()
    net.run_until(lambda: net.decided(0))
    honest = next(i for i, s in enumerate(net.specs)
                  if s.behavior == "honest")
    victim = net.nodes[honest]
    ev_before = victim.all_equivocations()
    assert ev_before, "setup: evidence must exist before snapshot"

    path = str(tmp_path / "node.json")
    save_executor(victim, path)
    fresh = ConsensusExecutor(net.vset, index=honest,
                              seed=net.seeds[honest],
                              get_value=lambda h: 100 + h)
    load_executor_into(fresh, path)
    assert fresh.all_equivocations() == ev_before


def test_metrics_registry_and_driver_attach():
    m = Metrics()
    m.count("x", 5)
    m.gauge("g", 1.5)
    snap = m.snapshot()
    assert snap["x"] == 5 and snap["g"] == 1.5 and "x_per_sec" in snap
    json.loads(m.json_line())

    d = DeviceDriver(n_instances=2, n_validators=4)
    m2 = attach_to_driver(d)
    d.run_honest_round(0)
    snap = m2.snapshot()
    assert snap[VOTES_INGESTED] == 2 * 2 * 4
    assert snap[DECISIONS] == 2


def test_metrics_interval_rates_are_windowed():
    """ISSUE-2 satellite: lifetime rate() divides by process elapsed
    and trends to zero on a long-lived service; interval rates measure
    since the PREVIOUS call and must see the full delta of a fresh
    window regardless of prior history."""
    import time as _time

    m = Metrics()
    m.count("x", 10)
    _time.sleep(0.02)
    r1 = m.interval_rate("x")
    assert r1 > 0
    # an idle window reads ~0 even though lifetime rate stays > 0
    _time.sleep(0.02)
    assert m.interval_rate("x") == 0.0
    assert m.rate("x") > 0
    # a fresh burst is measured against ITS window, not the lifetime
    m.count("x", 100)
    assert m.interval_rate("x") > 0
    # per-name windows are independent: reading x must not shorten y's
    m.count("y", 5)
    assert m.interval_rate("y") > 0
    # the shared-window snapshot covers every counter at once
    m.count("x", 3)
    rates = m.interval_rates()
    assert set(rates) == {"x_per_sec", "y_per_sec"}
    assert rates["x_per_sec"] > 0
    second = m.interval_rates()
    assert second["x_per_sec"] == 0.0       # window consumed


def test_metrics_attach_to_driver_is_idempotent():
    """ISSUE-2 satellite: re-attaching used to stack wrappers on
    driver.step and double-count every counter."""
    d = DeviceDriver(n_instances=2, n_validators=4)
    m1 = attach_to_driver(d)
    step_after_first = d.step
    m2 = attach_to_driver(d)
    assert m2 is m1                    # bare re-attach: same registry
    assert d.step is step_after_first  # no second wrapper stacked
    d.run_honest_round(0)
    assert m1.snapshot()[VOTES_INGESTED] == 2 * 2 * 4  # counted ONCE

    # re-attach with a NEW registry rebinds without re-wrapping
    fresh = Metrics()
    m3 = attach_to_driver(d, fresh)
    assert m3 is fresh and d.step is step_after_first
    d.run_honest_round(1)
    assert fresh.snapshot()[VOTES_INGESTED] == 2 * 2 * 4
    assert m1.snapshot()[VOTES_INGESTED] == 2 * 2 * 4  # old one frozen


def test_tracer_chrome_trace(tmp_path):
    tr = Tracer()
    with tr.span("outer"):
        with tr.span("inner"):
            pass
    with span("device_scope", tr):   # named_scope + host span
        pass
    path = str(tmp_path / "trace.json")
    tr.write(path)
    with open(path) as f:
        doc = json.load(f)
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert {e["name"] for e in spans} == {"outer", "inner",
                                          "device_scope"}
    # ISSUE 8: stable per-thread ids + thread_name metadata rows
    assert meta and all(e["name"] == "thread_name" for e in meta)
    assert {e["tid"] for e in spans} <= {e["tid"] for e in meta}
    assert tr.total_us("outer") >= tr.total_us("inner")


def test_run_config_validation_and_cli():
    cfg = RunConfig.from_args(["--validators", "64", "--instances", "128",
                               "--mesh", "4x2"])
    assert cfg.n_validators == 64 and cfg.mesh == (4, 2)
    with pytest.raises(AssertionError):
        RunConfig(n_instances=10, mesh=(3, 1)).validate()
    assert "n_validators" in cfg.as_dict()


def test_checkpoint_files_are_atomic(tmp_path):
    """No .tmp litter left behind."""
    d = DeviceDriver(n_instances=2, n_validators=4)
    path = str(tmp_path / "s.npz")
    save_driver(d, path)
    assert os.path.exists(path)
    assert [f for f in os.listdir(tmp_path) if f.endswith(".tmp")] == []

def test_runconfig_bridge_factories_apply_policy():
    """verify_mode/held_cap must actually govern the bridges a config
    builds (dead configuration would silently misreport the run)."""
    from agnes_tpu.utils.config import RunConfig

    cfg = RunConfig(n_validators=4, n_instances=2, n_slots=3,
                    verify_mode="msm", held_cap=123).validate()
    b = cfg.make_batcher()
    assert b.verify_mode == "msm" and b.held_cap == 123
    assert b.I == 2 and b.V == 4 and b.slots.n_slots == 3
    # the native loop has no msm verify stage: an msm config must
    # fail loudly, a lanes config builds fine
    import pytest as _pytest
    with _pytest.raises(ValueError):
        cfg.make_native_loop()
    lanes = RunConfig(n_validators=4, n_instances=2, n_slots=3,
                      held_cap=123).validate()
    loop = lanes.make_native_loop()
    assert loop.I == 2 and loop.V == 4
    # override forwards
    assert cfg.make_batcher(verify_mode="lanes").verify_mode == "lanes"

def test_batcher_checkpoint_roundtrip(tmp_path):
    """Slot decode and slashing evidence must survive a crash/restart
    (the executor already persists its evidence; the batcher's signed
    log and slot<->value maps are the device plane's decode surface)."""
    import numpy as np

    from agnes_tpu.bridge import VoteBatcher
    from agnes_tpu.bridge.ingest import vote_messages_np
    from agnes_tpu.core import native
    from agnes_tpu.types import VoteType
    from agnes_tpu.utils.checkpoint import load_batcher, save_batcher

    V = 4
    seeds = [bytes([i + 1]) * 32 for i in range(V)]
    pubkeys = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                        for s in seeds])
    bat = VoteBatcher(2, V, n_slots=4, held_cap=77)
    # validator 1 double-signs (values 7 then 9) in instance 0
    inst = np.array([0, 0, 0], np.int64)
    val = np.array([0, 1, 1], np.int64)
    h = np.zeros(3, np.int64)
    rnd = np.zeros(3, np.int64)
    typ = np.full(3, int(VoteType.PREVOTE), np.int64)
    value = np.array([7, 7, 9], np.int64)
    msgs = vote_messages_np(h, rnd, typ, value)
    sigs = np.stack([np.frombuffer(
        native.sign(seeds[val[k]], msgs[k].tobytes()), np.uint8)
        for k in range(3)])
    bat.add_arrays(inst, val, h, rnd, typ, value, sigs)
    bat.build_phases(pubkeys)
    assert bat.decode_slot(0, 0) == 7 and bat.decode_slot(0, 1) == 9

    p = str(tmp_path / "bat.npz")
    save_batcher(bat, p)
    fresh = load_batcher(p)
    assert fresh.decode_slot(0, 0) == 7 and fresh.decode_slot(0, 1) == 9
    assert fresh.held_cap == 77 and fresh.W == bat.W
    ev = fresh.signed_evidence(0, 1)
    assert ev is not None
    a, b = ev
    assert {a.value, b.value} == {7, 9}
    from agnes_tpu.crypto import host_verify
    m = vote_messages_np(np.array([0]), np.array([0]),
                         np.array([int(VoteType.PREVOTE)]),
                         np.array([a.value]))[0].tobytes()
    assert host_verify(native.pubkey(seeds[1]), m, a.signature)

def test_batcher_checkpoint_mixed_signed_unsigned_log():
    """Votes logged without signatures must restore with
    signature=None — all-zero bytes surfacing as 'signed' evidence
    would make a node emit unverifiable proofs."""
    import numpy as np

    from agnes_tpu.bridge import VoteBatcher
    from agnes_tpu.utils.checkpoint import load_batcher, save_batcher
    import tempfile, os

    bat = VoteBatcher(1, 4, n_slots=4)
    # unsigned tick: validator 2 double-signs (no signatures)
    bat.add_arrays(np.zeros(2, np.int64), np.full(2, 2, np.int64),
                   np.zeros(2), np.zeros(2), np.zeros(2),
                   np.array([7, 9]))
    bat.build_phases()
    # signed-column tick (garbage sigs, unverified path)
    sigs = np.ones((1, 64), np.uint8)
    bat.add_arrays([0], [3], [0], [0], [0], [7], sigs)
    bat.build_phases()

    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "bat.npz")
        save_batcher(bat, p)
        fresh = load_batcher(p)
    ev = fresh.signed_evidence(0, 2)
    assert ev is not None
    a, b = ev
    assert a.signature is None and b.signature is None   # not zeros


def test_batcher_restore_preserves_log_interleaving(tmp_path):
    """Evidence extraction must be restore-stable: load_batcher keeps
    the log's arrival interleaving (unsigned/signed/unsigned runs), so
    signed_evidence scans rows in the same order before and after a
    restart and extracts the SAME conflicting pair."""
    import numpy as np

    from agnes_tpu.bridge import VoteBatcher
    from agnes_tpu.utils.checkpoint import load_batcher, save_batcher

    bat = VoteBatcher(1, 4, n_slots=4)
    # three ticks, validator 2 equivocating across them; the middle
    # tick carries a signature column, the outer two do not
    bat.add_arrays([0], [2], [0], [0], [0], [7])
    bat.build_phases()
    bat.add_arrays([0], [2], [0], [0], [0], [9],
                   np.ones((1, 64), np.uint8))
    bat.build_phases()
    bat.add_arrays([0], [2], [0], [0], [0], [5])
    bat.build_phases()

    before = bat.signed_evidence(0, 2)
    order_before = [int(v) for b in bat._log for v in b.value]

    p = str(tmp_path / "bat.npz")
    save_batcher(bat, p)
    fresh = load_batcher(p)

    order_after = [int(v) for b in fresh._log for v in b.value]
    assert order_after == order_before          # arrival order preserved
    after = fresh.signed_evidence(0, 2)
    assert before is not None and after is not None
    assert ([(w.value, w.signature) for w in after]
            == [(w.value, w.signature) for w in before])


def test_make_z_is_fresh_os_entropy():
    """Batch-verification coefficients must come from OS entropy when
    unseeded (soundness rests on the CSPRNG, not PCG64) and stay
    deterministic when seeded (tests only)."""
    import numpy as np

    from agnes_tpu.crypto import msm_jax as M

    a, b = np.asarray(M.make_z(4)), np.asarray(M.make_z(4))
    assert a.shape == b.shape == (4, M.Z_LIMBS)
    assert (a >= 0).all() and (a <= M.F.LMASK).all()
    assert not np.array_equal(a, b)             # fresh entropy per call
    np.testing.assert_array_equal(np.asarray(M.make_z(4, seed=1)),
                                  np.asarray(M.make_z(4, seed=1)))
