"""Adversarial value-flood behavior: bounded degradation + bounded
memory (SURVEY §7 hard part 2, VERDICT r3 next #7 and weak #6).

The S-slot budget means a many-distinct-values flood pushes all but S
values per instance onto the host-fallback tally.  These tests pin the
two properties that make that path safe:

  * throughput degrades BOUNDEDLY (no quadratic collapse) — the flood
    rate stays within a generous constant factor of the honest rate at
    the same shape;
  * memory stays bounded — per-validator dedup runs before bucket
    allocation, so an equivocating flooder gets ONE bucket and ONE
    evidence record, not one bucket per flooded value.
"""

import numpy as np

import bench
from agnes_tpu.bridge import NativeIngestLoop, pack_wire_votes
from agnes_tpu.types import VoteType

PV, PC = int(VoteType.PREVOTE), int(VoteType.PRECOMMIT)


def test_flood_degradation_is_bounded():
    """Flood rate within 50x of honest at the same small shape —
    catches an accidental quadratic (which would be ~1000x here) while
    staying robust to CI timing noise."""
    I, V, ticks = 32, 64, 3
    honest = bench.bench_value_flood(I, V, ticks, flood=False)
    flood = bench.bench_value_flood(I, V, ticks, flood=True)
    assert flood > 0 and honest > 0
    assert flood * 50 >= honest, (
        f"flood {flood:.0f}/s vs honest {honest:.0f}/s: degradation "
        "exceeds the 50x bound")


def test_flooding_equivocator_gets_one_bucket_not_many():
    """One validator spraying K distinct values at one (round, class):
    dedup-before-bucket means exactly one counted vote + one evidence
    record; the host tally must not grow with K.  Observable surface:
    no host event can fire from the flooder's weight alone, and the
    evidence join still returns exactly one conflicting pair."""
    V, K = 4, 200
    from agnes_tpu.bridge.ingest import vote_messages_np
    from agnes_tpu.core import native

    seeds = [bytes([i + 1]) * 32 for i in range(V)]
    pub = np.stack([np.frombuffer(native.pubkey(s), np.uint8)
                    for s in seeds])
    loop = NativeIngestLoop(1, V, n_slots=4, pubkeys=pub,
                            powers=np.array([3, 1, 1, 1], np.int64))
    # window moved past round 0: everything falls back to host tally
    loop.sync_device(np.full(1, 3, np.int64), np.zeros(1, np.int64))

    vals = np.arange(K, dtype=np.int64) + 100
    h = np.zeros(K, np.int64)
    r = np.zeros(K, np.int64)
    t = np.full(K, PC, np.int64)
    msgs = vote_messages_np(h, r, t, vals)
    sigs = np.stack([np.frombuffer(
        native.sign(seeds[0], msgs[k].tobytes()), np.uint8)
        for k in range(K)])
    loop.push(pack_wire_votes(np.zeros(K, np.int64),
                              np.zeros(K, np.int64), h, r, t, vals, sigs))
    loop.build_phases()
    # flooder weight 3 of 6 alone is not +2/3: no event, despite K
    # distinct values — only the FIRST vote counted
    assert loop.drain_host_events() == []
    ev = loop.signed_evidence(0, 0)
    assert ev is not None                 # flagged as equivocator once
    # two more honest precommits on the flooder's FIRST value complete
    # +2/3 (3+1+1 of 6): had later flood values counted, this would
    # have fired on a different value or not at all
    first = int(vals[0])
    h2 = np.zeros(2, np.int64)
    r2 = np.zeros(2, np.int64)
    t2 = np.full(2, PC, np.int64)
    v2 = np.full(2, first, np.int64)
    msgs2 = vote_messages_np(h2, r2, t2, v2)
    sigs2 = np.stack([np.frombuffer(
        native.sign(seeds[k + 1], msgs2[k].tobytes()), np.uint8)
        for k in range(2)])
    loop.push(pack_wire_votes(np.zeros(2, np.int64),
                              np.array([1, 2], np.int64),
                              h2, r2, t2, v2, sigs2))
    loop.build_phases()
    assert loop.drain_host_events() == [(0, 0, 0, first)]


def test_flood_slots_still_decode_for_honest_values():
    """The flood must not evict honest slots: values interned before
    the flood keep decoding (spill affects only post-budget values)."""
    I, V = 2, 8
    loop = NativeIngestLoop(I, V, n_slots=2)
    loop.sync_device(np.zeros(I, np.int64), np.zeros(I, np.int64))
    loop.push(pack_wire_votes([0, 0], [0, 1], [0, 0], [0, 0],
                              [PV, PV], [7, 9]))
    loop.build_phases()
    # flood: validators 2..7 each with a distinct value
    n = 6
    loop.push(pack_wire_votes(np.zeros(n, np.int64),
                              np.arange(2, 8, dtype=np.int64),
                              np.zeros(n, np.int64), np.zeros(n, np.int64),
                              np.full(n, PV, np.int64),
                              np.arange(n, dtype=np.int64) + 1000))
    loop.build_phases()
    assert loop.decode_slot(0, 0) == 7 and loop.decode_slot(0, 1) == 9
    assert loop.counters["overflow_votes"] == n
