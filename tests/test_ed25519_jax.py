"""Batched JAX Ed25519 vs the pure-Python RFC 8032 oracle.

One jit compile is shared across the module (the Straus scan body is
the expensive compile); batches are kept small for CPU test speed.
"""

import random

import jax
import jax.numpy as jnp
import numpy as np

from agnes_tpu.crypto import ed25519_jax as E
from agnes_tpu.crypto import ed25519_ref as ref
from agnes_tpu.crypto import scalar_jax as S
from agnes_tpu.crypto.encoding import VOTE_MSG_LEN, vote_signing_bytes
from agnes_tpu.types import VoteType

rng = random.Random(99)


def _enc_batch(points):
    return jnp.asarray(
        np.stack([np.frombuffer(ref._compress(p), np.uint8)
                  for p in points]), jnp.int32)


def _as_bytes(arr_row) -> bytes:
    return np.asarray(arr_row, np.uint8).tobytes()


def test_decompress_compress_roundtrip():
    pts = [ref.BASE, ref._mul(2, ref.BASE), ref._mul(3, ref.BASE),
           ref._mul(rng.randrange(ref.L), ref.BASE)]
    enc = _enc_batch(pts)
    P, ok = jax.jit(E.decompress)(enc)
    assert bool(ok.all())
    out = jax.jit(E.compress)(P)
    for i, p in enumerate(pts):
        assert _as_bytes(out[i]) == ref._compress(p)


def test_decompress_rejects_bad_encodings():
    bad = np.zeros((3, 32), np.int32)
    bad[0] = np.frombuffer((ref.P + 1).to_bytes(32, "little"), np.uint8)
    bad[1] = np.frombuffer((2).to_bytes(32, "little"), np.uint8)  # y=2 off-curve
    # x = 0 with sign bit set: y = 1 encodes the identity, sign must be 0
    one_enc = (1 | (1 << 255)).to_bytes(32, "little")
    bad[2] = np.frombuffer(one_enc, np.uint8)
    _, ok = jax.jit(E.decompress)(jnp.asarray(bad))
    assert not bool(ok.any())


def test_point_add_matches_oracle():
    a = ref._mul(7, ref.BASE)
    b = ref._mul(11, ref.BASE)
    enc = _enc_batch([a, b])
    P, _ = jax.jit(E.decompress)(enc)
    s = E.point_add(E.Point(*[c[0:1] for c in P]),
                    E.Point(*[c[1:2] for c in P]))
    assert _as_bytes(jax.jit(E.compress)(s)[0]) == \
        ref._compress(ref._add(a, b))


def test_barrett_reduce_matches_python():
    ks = [0, 1, S.L - 1, S.L, S.L + 1, 2**252, 2**512 - 1,
          rng.randrange(2**512), rng.randrange(2**512)]
    limbs = jnp.stack(
        [jnp.asarray([(k >> (13 * i)) & 0x1FFF for i in range(S.N_HASH)],
                     jnp.int32) for k in ks])
    out = jax.jit(S.barrett_reduce)(limbs)
    for i, k in enumerate(ks):
        got = sum(int(np.asarray(out[i])[j]) << (13 * j)
                  for j in range(S.N_SCALAR))
        assert got == k % S.L, f"case {i}"


def test_verify_batch():
    seeds = [bytes([i]) * 32 for i in range(5)]
    keys = [ref.keypair(s) for s in seeds]
    msgs = [vote_signing_bytes(height=1, round=0,
                               typ=int(VoteType.PREVOTE), value=i)
            for i in range(5)]
    assert all(len(m) == VOTE_MSG_LEN for m in msgs)
    sigs = [ref.sign(sk, m) for (sk, _), m in zip(keys, msgs)]
    pubs = [pk for _, pk in keys]
    # corrupt: bad sig bit, wrong message, non-canonical S
    sigs[1] = sigs[1][:5] + bytes([sigs[1][5] ^ 1]) + sigs[1][6:]
    msgs[2] = msgs[2][:-1] + b"X"
    s3 = int.from_bytes(sigs[3][32:], "little")
    sigs[3] = sigs[3][:32] + (s3 + ref.L).to_bytes(32, "little")

    pub, sig, blocks = E.pack_verify_inputs_host(pubs, msgs, sigs)
    ok = E.verify_batch_jit(pub, sig, blocks)
    assert ok.tolist() == [True, False, False, False, True]
    # parity with the oracle on every lane
    for i in range(5):
        assert bool(ok[i]) == ref.verify(pubs[i], msgs[i], sigs[i])


def test_verify_fuzz_parity():
    """Randomized parity: valid/invalid mix must agree with the oracle.
    Batch of 5 keeps the same shape as test_verify_batch so the Straus
    scan compile is shared."""
    n = 5
    pubs, msgs, sigs = [], [], []
    for i in range(n):
        seed = bytes(rng.randrange(256) for _ in range(32))
        sk, pk = ref.keypair(seed)
        m = bytes(rng.randrange(256) for _ in range(VOTE_MSG_LEN))
        sg = ref.sign(sk, m)
        if i % 3 == 1:
            pos = rng.randrange(64)
            sg = sg[:pos] + bytes([sg[pos] ^ (1 << rng.randrange(8))]) \
                + sg[pos + 1:]
        if i % 3 == 2:
            pk = ref.keypair(bytes(rng.randrange(256)
                                   for _ in range(32)))[1]
        pubs.append(pk), msgs.append(m), sigs.append(sg)
    pub, sig, blocks = E.pack_verify_inputs_host(pubs, msgs, sigs)
    ok = E.verify_batch_jit(pub, sig, blocks)
    for i in range(n):
        assert bool(ok[i]) == ref.verify(pubs[i], msgs[i], sigs[i]), i
