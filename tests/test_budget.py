"""utils/budget.py — the deadline/HBM-budget subsystem.

Planner tests are PURE MATH: the headline assertion is that the
north-star shape (Ps=2 vote classes x 10k instances x 1000 validators,
BASELINE config 4) gets a valid chunked plan under a simulated 16 GB
v5e budget WITHOUT allocating anything — the proof VERDICT r5 weak #3
asked for that the fused signed path can run at full shape at all.
"""

import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import pytest

from agnes_tpu.utils.budget import (
    DEFAULT_HBM_BYTES,
    GIB,
    BudgetError,
    Deadline,
    dense_resident_bytes,
    device_hbm_bytes,
    enclosing_timeout_remaining,
    parse_timeout_argv,
    parse_timeout_duration,
    plan_dense_verify,
    plan_lane_verify,
)

# --- the north-star plan (ISSUE 1 acceptance criterion) --------------------


def test_north_star_shape_plans_under_16gb():
    """Ps=2, I=10k, V=1000 must yield a valid tile plan within a
    simulated 16 GiB budget — statically, no device, no allocation."""
    plan = plan_dense_verify(2, 10_000, 1000, n_blocks=1,
                             hbm_bytes=16 * GIB)
    assert plan.fits()
    assert plan.chunked                       # one batch can NOT fit
    assert 1 <= plan.tile < 10_000
    assert plan.n_chunks == -(-10_000 // plan.tile)
    assert plan.lanes_per_chunk == plan.tile * 2 * 1000
    assert plan.peak_bytes <= 16 * GIB * plan.safety
    # the resident operands alone are most of the budget (sig ~5.1 GB
    # + blocks ~2.6 GB) — sanity that the operand math is in range
    assert 7 * GIB < plan.resident_bytes < 12 * GIB


def test_north_star_unchunked_exceeds_16gb():
    """The r5 status quo: the single-batch verify at full shape blows
    the budget (this is WHY the chunked path exists)."""
    plan = plan_dense_verify(2, 10_000, 1000, hbm_bytes=16 * GIB)
    unchunked_peak = (plan.resident_bytes
                      + (plan.chunk_bytes // plan.tile) * 10_000)
    assert unchunked_peak > 16 * GIB


def test_plan_scales_with_budget():
    small = plan_dense_verify(2, 1024, 64, hbm_bytes=2 * GIB)
    large = plan_dense_verify(2, 1024, 64, hbm_bytes=64 * GIB)
    assert small.fits() and large.fits()
    assert small.tile <= large.tile
    # power-of-two tiles (logarithmic compile-cache pressure)
    assert small.tile & (small.tile - 1) == 0


def test_plan_unchunked_when_everything_fits():
    plan = plan_dense_verify(2, 8, 4, hbm_bytes=16 * GIB)
    assert not plan.chunked and plan.tile == 8 and plan.n_chunks == 1


def test_plan_raises_when_nothing_fits():
    with pytest.raises(BudgetError):
        plan_dense_verify(2, 10_000, 1000, hbm_bytes=1 * GIB)


def test_lane_plan():
    plan = plan_lane_verify(1 << 21, hbm_bytes=4 * GIB)  # 2M lanes
    assert plan.chunked and plan.fits()
    assert plan.tile * plan.n_chunks >= 1 << 21
    tiny = plan_lane_verify(256, hbm_bytes=16 * GIB)
    assert not tiny.chunked and tiny.tile == 256


def test_resident_bytes_monotone():
    a = dense_resident_bytes(2, 100, 64)
    b = dense_resident_bytes(2, 200, 64)
    assert 0 < a < b


def test_device_hbm_env_override(monkeypatch):
    monkeypatch.setenv("AGNES_HBM_BUDGET_BYTES", str(3 * GIB))
    assert device_hbm_bytes() == 3 * GIB
    monkeypatch.setenv("AGNES_HBM_BUDGET_BYTES", "nonsense")
    # unparseable env falls through (CPU backend has no memory_stats
    # limit here, so the v5e default comes back)
    assert device_hbm_bytes() in (DEFAULT_HBM_BYTES,) or \
        device_hbm_bytes() > 0


# --- timeout cmdline parsing ------------------------------------------------


def test_parse_timeout_duration():
    assert parse_timeout_duration("870") == 870.0
    assert parse_timeout_duration("30m") == 1800.0
    assert parse_timeout_duration("2h") == 7200.0
    assert parse_timeout_duration("1.5s") == 1.5
    assert parse_timeout_duration("junk") is None


def test_parse_timeout_argv():
    assert parse_timeout_argv(["timeout", "1800", "bash", "-c", "x"]) \
        == 1800.0
    assert parse_timeout_argv(
        ["timeout", "-k", "10", "870", "env", "python"]) == 870.0
    assert parse_timeout_argv(
        ["/usr/bin/timeout", "--kill-after=10", "-s", "TERM", "30m",
         "python", "bench.py"]) == 1800.0
    assert parse_timeout_argv(["timeout", "--foreground", "60",
                               "sleep", "999"]) == 60.0
    assert parse_timeout_argv(["python", "bench.py"]) is None
    assert parse_timeout_argv(["timeout"]) is None
    assert parse_timeout_argv([]) is None


def test_deadline_env_override(monkeypatch):
    monkeypatch.setenv("AGNES_BENCH_DEADLINE_S", "120")
    d = Deadline.discover()
    assert d.source == "env:AGNES_BENCH_DEADLINE_S"
    assert 110 < d.remaining() <= 120


def test_deadline_none_and_cap():
    d = Deadline.none()
    assert d.remaining() == float("inf") and not d.expired()
    assert d.cap(300.0) == 300.0
    d2 = Deadline.after(10.0)
    assert 0 < d2.cap(300.0, margin=2.0) <= 8.0
    assert d2.cap(1.0) == 1.0


def test_enclosing_timeout_discovered_from_child(monkeypatch):
    """A child under `timeout 300` must discover ~300s remaining via
    the /proc walk — the exact mechanism bench.py relies on under the
    driver's `timeout 1800`."""
    monkeypatch.delenv("AGNES_BENCH_DEADLINE_S", raising=False)
    code = ("import sys; sys.path.insert(0, '.');"
            "from agnes_tpu.utils.budget import Deadline;"
            "d = Deadline.discover();"
            "print(d.source, d.remaining())")
    r = subprocess.run(
        ["timeout", "300", sys.executable, "-c", code],
        capture_output=True, text=True, timeout=60,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert r.returncode == 0, r.stderr
    source, rem = r.stdout.split()
    assert source == "proc:timeout"
    # the discovery takes the TIGHTEST enclosing timeout: if this test
    # session itself runs under one shorter than 300s, remaining is
    # smaller — but never larger, and never non-positive
    assert 0 < float(rem) <= 300


def test_enclosing_timeout_none_here():
    """This pytest process itself may or may not be under a timeout;
    the call must simply not crash and return None-or-positive."""
    rem = enclosing_timeout_remaining()
    assert rem is None or isinstance(rem, float)
