"""Multi-host distributed serve: the spawned differential (ISSUE 15
acceptance).

Every plane runs in its OWN child interpreter (spawn_pod), composing
with the XLA:CPU child-interpreter discipline (tests/conftest.py):
two jax.distributed pod processes (2 faked CPU devices each, gloo
collectives), one single-process mesh-serve comparison over the SAME
(slice=2, data=1, val=2) global mesh shape, and one offline fused
dense reference.  The parent never touches jax — it compares the
dumped state/tally npz blocks leaf-for-leaf.

Slow: each child pays its own sharded/dense compile (the persistent
cache is deliberately off)."""

import numpy as np
import pytest

I, V, HEIGHTS = 4, 4, 2
N_HOSTS, DPH, N_VAL = 2, 2, 2


@pytest.mark.slow
def test_multihost_serve_bit_identical(tmp_path):
    """2-process multi-host serve == single-process mesh serve ==
    offline fused: state/tally leaf-for-leaf, decision stats equal,
    zero unexpected retraces and zero unwarmed compiles on every
    host, one parseable host-id-stamped heartbeat per process."""
    from agnes_tpu.distributed.smoke import spawn_pod
    from agnes_tpu.utils.metrics_cli import main as metrics_main

    res = spawn_pod(N_HOSTS, instances=I, validators=V,
                    heights=HEIGHTS, devices_per_host=DPH,
                    n_val=N_VAL, out_dir=str(tmp_path),
                    timeout_s=1500, heartbeat=True, dump_state=True,
                    extra_modes=["single", "offline"])
    assert not res["killed"], res["paths"]
    for rec in res["pod"] + [res["single"], res["offline"]]:
        assert "error" not in rec, (rec, res["paths"])

    # per-host serve-plane invariants
    for rec in res["pod"]:
        assert rec["retrace_unexpected"] == 0, rec
        assert rec["rejected_signature_device"] == 0, rec
        assert rec["offladder_builds"] == 0, rec
        assert rec["host_fallback_builds"] == 0, rec
        # zero unwarmed compiles: the ONLY compiled dispatch entry is
        # the warmed global-SPMD fused signed step
        assert rec["compile_entries"] == ["sharded_step_seq_signed"], \
            rec
        assert rec["warmed_shapes"] == 1
        # the pod front door screened the other host's share
        assert rec["foreign_rejects"] == \
            (HEIGHTS + 1) * 2 * (I // N_HOSTS) * V
        assert rec["decisions_total"] == (I // N_HOSTS) * (HEIGHTS + 1)
        # the gather gave every host the POD-wide first-decision view
        assert rec["pod_decisions"] == I
    # both hosts gathered the IDENTICAL decision rows, covering every
    # global instance with the decided value
    rows0, rows1 = (r["pod_decision_rows"] for r in res["pod"])
    assert rows0 == rows1
    assert sorted(r[0] for r in rows0) == list(range(I))
    assert all(r[3] == 7 for r in rows0)

    assert res["single"]["decisions_total"] == I * (HEIGHTS + 1)
    assert res["offline"]["decisions_total"] == I * (HEIGHTS + 1)

    # leaf-for-leaf: host blocks concatenate host-major == global
    pods = [np.load(res["paths"][f"pod{k}"]["npz"])
            for k in range(N_HOSTS)]
    single = np.load(res["paths"]["single"]["npz"])
    offline = np.load(res["paths"]["offline"]["npz"])
    assert set(single.files) == set(offline.files) == set(pods[0].files)
    for key in single.files:
        merged = np.concatenate([p[key] for p in pods], axis=0)
        np.testing.assert_array_equal(
            merged, single[key], err_msg=f"{key}: pod vs single-mesh")
        np.testing.assert_array_equal(
            merged, offline[key], err_msg=f"{key}: pod vs offline")

    # one parseable host-id-stamped heartbeat trail per process
    hbs = [res["paths"][f"pod{k}"]["heartbeat"]
           for k in range(N_HOSTS)]
    assert metrics_main(["--check"] + hbs) == 0
    from agnes_tpu.utils.flightrec import read_heartbeat

    for k, path in enumerate(hbs):
        lines, _bad = read_heartbeat(path)
        assert lines and all(ln["host_id"] == k for ln in lines), path


@pytest.mark.slow
def test_multihost_native_admission_front_end(tmp_path):
    """The PR 14 rung: one native C++ admission front-end per host
    feeding its host-local shard — same pod, native_admission=True,
    same invariants (the native queue is byte-compatible, so the pod
    plane's decisions/screens are unchanged)."""
    from agnes_tpu.distributed.smoke import spawn_pod

    res = spawn_pod(N_HOSTS, instances=I, validators=V,
                    heights=HEIGHTS, devices_per_host=DPH,
                    n_val=N_VAL, out_dir=str(tmp_path),
                    timeout_s=1500, native_admission=True)
    assert not res["killed"], res["paths"]
    for rec in res["pod"]:
        assert "error" not in rec, (rec, res["paths"])
        assert rec["native_admission"] is True
        assert rec["retrace_unexpected"] == 0, rec
        assert rec["rejected_signature_device"] == 0, rec
        assert rec["pod_decisions"] == I
