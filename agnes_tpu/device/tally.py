"""Vote tally + threshold detection on device: the hot loop of SURVEY §3.2.

Semantics are `core.round_votes`'s (which fixes the reference's
round_votes.rs single-bucket/no-dedup limitations, SURVEY.md §2.3):
per-value weight buckets, per-validator dedup, equivocation evidence,
quorum predicate `3*v > 2*total` (round_votes.rs:31-33), threshold
priority Value > Nil > Any > Init (round_votes.rs:58-66), `Any` computed
over all weight seen (round_votes.rs:62).

TPU-first formulation (SURVEY.md §2.3 "TPU mapping"): instead of the
reference's one-`add_vote`-per-message hot path (round_votes.rs:48-67),
votes are ingested as **dense per-phase matrices** — one row per
instance, one column per validator, one call per (round, vote-class)
phase.  The tally is then a masked one-hot segment-sum over the
validator axis (an [I,V]×[V,S] contraction XLA maps onto the MXU), the
threshold check a handful of vectorized compares, and dedup/equivocation
a gather/compare/scatter against the per-validator vote record.  The
bridge densifies sparse wire votes into these matrices on the host.

Events are **edge-triggered** here (unlike the reference's re-fire on
every vote, vote_executor.rs:20-23): `emitted` records the highest
threshold code already fired per (instance, round, class), and a call
emits only codes strictly above it.  Weights only grow, and dedup
bounds per-class weight by total power, so threshold codes are
monotone — at most one value slot can ever hold a quorum.  The missed-
edge hazard (threshold fired while the state machine's step ignored it)
is handled by the instance driver re-querying `current_threshold`.

Slots: a value *slot* is an instance-local dense index for a value id;
slot -1 is nil (NIL_ID).  The bridge owns the slot<->value-id mapping.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from agnes_tpu.core.state_machine import EventTag
from agnes_tpu.device.encoding import I32
from agnes_tpu.types import VoteType

# threshold codes, ordered by priority (round_votes.rs:58-66)
TH_INIT, TH_ANY, TH_NIL, TH_VALUE = 0, 1, 2, 3
# voted-record sentinels
NOT_VOTED = -2
VOTED_NIL = -1
# "no event" tag
NO_EVENT = -1


class TallyConfig(NamedTuple):
    """Static shapes: V validators, W rounds in the tracked window,
    S value slots per instance."""

    n_validators: int
    n_rounds: int = 4
    n_slots: int = 4


class TallyState(NamedTuple):
    """Per-instance tally arrays.  I = batch of instances.

    weights  [I, W, 2, S+1] — voting power per (round, class, slot);
                              slot index 0 is nil, slot s is column s+1.
    voted    [I, W, 2, V]   — what each validator voted (NOT_VOTED /
                              VOTED_NIL / slot) — the dedup +
                              equivocation record (SURVEY.md §2.3 fix 2).
    emitted  [I, W, 2]      — highest threshold code already emitted.
    skipped  [I, W]         — RoundSkip already fired for this round.
    equiv    [I, V]         — validator produced conflicting votes.
    q_round  [I]            — (round, step) the re-query stages last ran
    q_step   [I]              against; each state-machine state is
                              re-queried at most once, so level-triggered
                              catch-up cannot re-schedule timeouts forever
                              (spec line 47 "for the first time").
    pc_done  [I, W]         — a precommit-class threshold event for this
                              round was already *consumed* by the state
                              machine.  PRECOMMIT_ANY/PRECOMMIT_VALUE
                              arms are step-independent (state_machine.rs
                              :208,:211), so first delivery at the right
                              round consumes them for good — exactly one
                              TimeoutPrecommit schedule per round.
    skip_w   [I, W]         — distinct-voter weight per round (either
                              class), maintained incrementally so the
                              round-skip check needs no O(W*V) sweep of
                              the voted record per phase.
    base_round [I]          — absolute round of window row 0.  Window
                              row w tracks absolute round base+w; the
                              step's rotation stage advances the base
                              as instances progress (`rotate_window`),
                              so round numbers are unbounded like the
                              reference's per-round map
                              (round_votes.rs:74-97) even though the
                              device tracks a fixed W-row window.
    """

    weights: jnp.ndarray
    voted: jnp.ndarray
    emitted: jnp.ndarray
    skipped: jnp.ndarray
    equiv: jnp.ndarray
    q_round: jnp.ndarray
    q_step: jnp.ndarray
    pc_done: jnp.ndarray
    skip_w: jnp.ndarray
    base_round: jnp.ndarray

    @classmethod
    def new(cls, n_instances: int, cfg: TallyConfig) -> "TallyState":
        I_, W, S, V = n_instances, cfg.n_rounds, cfg.n_slots, cfg.n_validators
        return cls(
            weights=jnp.zeros((I_, W, 2, S + 1), I32),
            voted=jnp.full((I_, W, 2, V), NOT_VOTED, I32),
            emitted=jnp.zeros((I_, W, 2), I32),
            skipped=jnp.zeros((I_, W), jnp.bool_),
            equiv=jnp.zeros((I_, V), jnp.bool_),
            q_round=jnp.full((I_,), -1, I32),
            q_step=jnp.full((I_,), -1, I32),
            pc_done=jnp.zeros((I_, W), jnp.bool_),
            skip_w=jnp.zeros((I_, W), I32),
            base_round=jnp.zeros((I_,), I32),
        )


class TallyEvents(NamedTuple):
    """Per-instance outputs of one ingestion phase.

    tag        [I] — EventTag code or NO_EVENT.
    value_slot [I] — slot for *_VALUE events, else -1.
    round      [I] — the round the event belongs to.
    skip_round [I] — lowest round whose +1/3 skip threshold newly fired,
                     or -1 (maps to Event::RoundSkip, state_machine.rs:106).
    """

    tag: jnp.ndarray
    value_slot: jnp.ndarray
    round: jnp.ndarray
    skip_round: jnp.ndarray


def _thresh_code(weights_row: jnp.ndarray, total: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """weights_row [..., S+1] -> (code, value_slot).

    Priority Value > Nil > Any (round_votes.rs:58-66); `Any` is quorum of
    all weight seen in the class (round_votes.rs:62)."""
    nil_w = weights_row[..., 0]
    val_w = weights_row[..., 1:]
    q = lambda w: 3 * w > 2 * total  # noqa: E731  (round_votes.rs:31-33)
    val_q = q(val_w)
    has_val = jnp.any(val_q, axis=-1)
    # at most one slot can hold >2/3 when weights are deduped; argmax of the
    # masked weights breaks ties for adversarial identity-free streams
    vslot = jnp.argmax(jnp.where(val_q, val_w, -1), axis=-1).astype(I32)
    code = jnp.where(
        has_val, TH_VALUE,
        jnp.where(q(nil_w), TH_NIL,
                  jnp.where(q(jnp.sum(weights_row, axis=-1)), TH_ANY, TH_INIT)))
    return code.astype(I32), jnp.where(has_val, vslot, -1)


# (class, code) -> EventTag, the vote_executor.rs:26-36 table.  There is
# no PrecommitNil event; a pure-nil precommit quorum maps to
# PRECOMMIT_ANY so spec line 47's timeout actually triggers (see
# core.vote_executor.to_event for the full rationale).
_EVENT_TABLE = jnp.asarray([
    # INIT       ANY                        NIL                      VALUE
    [NO_EVENT, int(EventTag.POLKA_ANY), int(EventTag.POLKA_NIL),
     int(EventTag.POLKA_VALUE)],
    [NO_EVENT, int(EventTag.PRECOMMIT_ANY), int(EventTag.PRECOMMIT_ANY),
     int(EventTag.PRECOMMIT_VALUE)],
], dtype=jnp.int32)


def _sel_wt(W: int, round_idx: jnp.ndarray, typ: jnp.ndarray) -> jnp.ndarray:
    """[I, W, 2] one-hot selector of each instance's (round, class) row.
    All-false when round_idx is outside the tracked window [0, W)."""
    onehot_w = (jnp.arange(W)[None, :] == round_idx[:, None])
    onehot_t = (jnp.arange(2)[None, :] == typ[:, None])
    return onehot_w[:, :, None] & onehot_t[:, None, :]


def _gather_row(arr: jnp.ndarray, sel_wt: jnp.ndarray,
                fill: int = 0) -> jnp.ndarray:
    """One-hot gather of the selected [I, ...] row of an [I, W, 2, ...]
    (or [I, W, 2]) array; rows outside the window read as `fill`.

    Values are shifted so real entries are never confused with the
    zeroed non-selected rows, whatever `fill` is."""
    sel = sel_wt.reshape(sel_wt.shape + (1,) * (arr.ndim - 3))
    return jnp.sum(jnp.where(sel, arr - fill, 0), axis=(1, 2)) + fill


def add_votes(tally: TallyState,
              powers: jnp.ndarray,        # [V] voting power
              total_power: jnp.ndarray,   # scalar
              round_idx: jnp.ndarray,     # [I] round being ingested
              typ: jnp.ndarray,           # [I] VoteType code
              slots: jnp.ndarray,         # [I, V] value slot or VOTED_NIL
              mask: jnp.ndarray,          # [I, V] vote present
              cur_round: jnp.ndarray,     # [I] instance's current round
              axis_name: str | None = None,
              ) -> Tuple[TallyState, TallyEvents]:
    """Ingest one dense vote phase; returns the updated tally and the
    newly crossed threshold events (the fused verify+tally hot path of
    the north star, minus signatures which are checked upstream).

    Under `shard_map` over a validator-sharded mesh axis, pass
    `axis_name` and per-device V-shards of `powers`/`slots`/`mask`/
    `tally.voted`/`tally.equiv`: the two validator-axis reductions
    (weight delta and round-skip weight) become `psum`s over the axis —
    quorum aggregation rides the ICI, everything else stays local
    (SURVEY.md §2.7 "validator-axis data parallelism")."""
    I_, W, _, S1 = tally.weights.shape

    # --- translate absolute rounds to window rows (row w = absolute
    # round base+w; the step's rotation stage keeps the window around
    # each instance's current round).  Votes outside the window are
    # dropped HERE — the bridge holds back future-round votes until the
    # window rotates to them and host-tallies past rounds (the fallback
    # for the reference's unbounded per-round map, round_votes.rs:74-97)
    widx = round_idx - tally.base_round                              # [I]
    in_window = (widx >= 0) & (widx < W)                             # [I]
    # invalid slots (outside [VOTED_NIL, S)) are dropped too — clipping
    # them into a real bucket would manufacture a quorum for a value
    # nobody voted for, which arm 14 would commit unconditionally
    valid_slot = (slots >= VOTED_NIL) & (slots < S1 - 1)             # [I, V]
    mask = mask & in_window[:, None] & valid_slot
    sel_wt = _sel_wt(W, widx, typ)                                   # [I, W, 2]
    voted_row = _gather_row(tally.voted, sel_wt, fill=NOT_VOTED)     # [I, V]

    # --- dedup + equivocation (SURVEY.md §2.3 fix 2)
    fresh = mask & (voted_row == NOT_VOTED)
    conflict = mask & (voted_row != NOT_VOTED) & (voted_row != slots)
    voted_row_new = jnp.where(fresh, slots, voted_row)

    # --- masked one-hot segment-sum over the validator axis
    # column 0 = nil (slot -1), column s+1 = slot s
    col = jnp.clip(slots + 1, 0, S1 - 1)                             # [I, V]
    onehot_s = (jnp.arange(S1)[None, None, :] == col[:, :, None])    # [I, V, S1]
    contrib = jnp.where(fresh, powers[None, :], 0).astype(I32)       # [I, V]
    delta = jnp.einsum("ivs,iv->is", onehot_s.astype(I32), contrib)  # [I, S1]
    if axis_name is not None:
        delta = jax.lax.psum(delta, axis_name)

    weights_row = _gather_row(tally.weights, sel_wt)
    weights_row_new = weights_row + delta

    # --- threshold detection + edge-triggered event
    code, vslot = _thresh_code(weights_row_new, total_power)
    emitted_row = _gather_row(tally.emitted, sel_wt)
    # fire only when the code rises AND maps to a different event: the
    # precommit class maps both ANY and NIL codes to PRECOMMIT_ANY, which
    # must fire at most once per round (spec line 47 "for the first time")
    rising = (in_window & (code > emitted_row)
              & (_EVENT_TABLE[typ, code] != _EVENT_TABLE[typ, emitted_row]))
    tag = jnp.where(rising, _EVENT_TABLE[typ, code], NO_EVENT).astype(I32)
    value_slot = jnp.where(tag >= 0, vslot, -1).astype(I32)

    # --- scatter rows back
    weights = jnp.where(sel_wt[:, :, :, None],
                        weights_row_new[:, None, None, :], tally.weights)
    voted = jnp.where(sel_wt[:, :, :, None],
                      voted_row_new[:, None, None, :], tally.voted)
    emitted = jnp.where(sel_wt, jnp.maximum(emitted_row, code)[:, None, None],
                        tally.emitted)
    equiv = tally.equiv | conflict

    # --- RoundSkip: +1/3 of distinct-voter weight on a round above the
    # instance's current one (state_machine.rs:106; detection absent in
    # the reference).  One vote per validator regardless of class;
    # maintained incrementally: a fresh vote adds its power iff the
    # validator was unseen in the round's OTHER class too (the phase's
    # own class dedup is already `fresh`).
    sel_other = _sel_wt(W, widx, 1 - typ)
    other_row = _gather_row(tally.voted, sel_other, fill=NOT_VOTED)  # [I, V]
    new_voter = fresh & (other_row == NOT_VOTED)
    dskip = jnp.sum(jnp.where(new_voter, powers[None, :], 0), axis=1)  # [I]
    if axis_name is not None:
        dskip = jax.lax.psum(dskip, axis_name)
    onehot_r = (jnp.arange(W)[None, :] == widx[:, None])             # [I, W]
    w_skip = tally.skip_w + jnp.where(onehot_r, dskip[:, None], 0)
    abs_round = tally.base_round[:, None] + jnp.arange(W)[None, :]   # [I, W]
    eligible = ((3 * w_skip > total_power)
                & (abs_round > cur_round[:, None])
                & ~tally.skipped)                                    # [I, W]
    any_skip = jnp.any(eligible, axis=1)
    skip_widx = jnp.argmax(eligible, axis=1).astype(I32)  # lowest eligible
    skip_round = jnp.where(
        any_skip, tally.base_round + skip_widx, -1)
    skipped = tally.skipped | (
        any_skip[:, None] & (jnp.arange(W)[None, :] == skip_widx[:, None]))

    new_tally = tally._replace(weights=weights, voted=voted, emitted=emitted,
                               skipped=skipped, equiv=equiv, skip_w=w_skip)
    events = TallyEvents(tag=tag, value_slot=value_slot,
                         round=round_idx.astype(I32), skip_round=skip_round)
    return new_tally, events


def current_threshold(tally: TallyState, round_idx: jnp.ndarray,
                      typ: jnp.ndarray, total_power: jnp.ndarray
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(code, value_slot) currently reached at [I] (round, class) — the
    re-query path for consumers that advanced step/round after an edge
    was consumed (mirrors core.vote_executor.threshold_events).
    round_idx is absolute; out-of-window rounds read as empty (TH_INIT)."""
    W = tally.weights.shape[1]
    sel_wt = _sel_wt(W, round_idx - tally.base_round, typ)
    weights_row = _gather_row(tally.weights, sel_wt)
    return _thresh_code(weights_row, total_power)


def rotate_window(tally: TallyState, new_base: jnp.ndarray) -> TallyState:
    """Roll each instance's W-row window forward so row 0 becomes
    absolute round `new_base` (>= the current base; per-instance).

    Rows for rounds that stay in the window are shifted down; rows
    entering the window are fresh-empty.  This is the device half of
    the reference's unbounded per-round tally (round_votes.rs:74-97):
    combined with the bridge's hold-back of future-round votes and
    host tally of dropped past rounds, no round is ever silently lost.
    """
    W = tally.weights.shape[1]
    shift = jnp.maximum(new_base - tally.base_round, 0)              # [I]
    src = jnp.arange(W)[None, :] + shift[:, None]                    # [I, W]
    keep = src < W
    srcc = jnp.minimum(src, W - 1)

    def roll(arr, fill):
        idx = srcc.reshape(srcc.shape + (1,) * (arr.ndim - 2))
        idx = jnp.broadcast_to(idx, arr.shape)
        out = jnp.take_along_axis(arr, idx, axis=1)
        k = keep.reshape(keep.shape + (1,) * (arr.ndim - 2))
        return jnp.where(k, out, fill)

    return tally._replace(
        weights=roll(tally.weights, 0),
        voted=roll(tally.voted, NOT_VOTED),
        emitted=roll(tally.emitted, TH_INIT),
        skipped=roll(tally.skipped, False),
        pc_done=roll(tally.pc_done, False),
        skip_w=roll(tally.skip_w, 0),
        base_round=tally.base_round + shift,
    )


add_votes_jit = jax.jit(add_votes)

from agnes_tpu.device import registry as _registry  # noqa: E402

_registry.register(_registry.EntrySpec(
    name="add_votes", fn=add_votes, jit=add_votes_jit, hot=False))
