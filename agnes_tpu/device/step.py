"""The fused per-instance consensus step — the flagship device kernel.

One call advances a batch of I independent consensus instances through
one delivery phase, reproducing the reference's intended top-level loop
(consensus_executor.rs:24-49, SURVEY.md §3.3) as a fixed pipeline of
seven branch-free stages, each an `apply` of the vmapped state machine:

  0. external event   — harness/bridge-injected Proposal /
                        ProposalInvalid / Timeout* (the reference's
                        inbound wire alphabet, consensus_executor.rs:16-20)
  1. vote ingestion   — dense tally phase -> edge-triggered threshold
                        event (stack §3.2: the verify+tally hot path)
  2. round skip       — +1/3 weight on a higher round -> RoundSkip
  3. re-query prevote — level-triggered catch-up of the current round's
     /4. precommit      thresholds, so an edge consumed in a step that
                        ignored it is never lost (liveness; see
                        device/tally.py docstring)
  5. round entry      — step == NewRound -> NewRound/NewRoundProposer
                        from the precomputed proposer table (fills the
                        "check if we're the proposer" stub,
                        consensus_executor.rs:31-33)
  6. self-proposal    — the proposer processes its own Proposal message
                        immediately (the re-entrant "call execute"
                        intent, consensus_executor.rs:36-41)

Every stage emits a DeviceMessage batch; the step returns them stacked
on a leading stage axis.  The harness/bridge routes VOTE messages back
into the next phase's dense matrices (self-votes take the same path as
peer votes, exactly the reference's intent), TIMEOUT to the timer
wheel, DECISION to the decided log.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from agnes_tpu.core.state_machine import EventTag, MsgTag, Step
from agnes_tpu.device import registry as _registry
from agnes_tpu.device.encoding import I32, DeviceEvent, DeviceMessage, DeviceState
from agnes_tpu.device.state_machine import apply_scalar
from agnes_tpu.device.tally import (
    _EVENT_TABLE,
    NO_EVENT,
    NOT_VOTED,
    TH_INIT,
    TallyState,
    add_votes,
    current_threshold,
    rotate_window,
)
from agnes_tpu.types import NIL_ID, VoteType

# module-scope, NOT lazy: ed25519_jax builds module-level limb-constant
# arrays at import; importing it for the first time INSIDE a jit trace
# (consensus_step_seq_signed) would create those constants as tracers
# and leak them into module globals (UnexpectedTracerError on the next
# independent trace that touches them)
from agnes_tpu.crypto import ed25519_jax as _ejax

# "no event" tag: matches no transition arm -> guaranteed no-op
NULL_EVENT = NO_EVENT

_apply = jax.vmap(apply_scalar)


class VotePhase(NamedTuple):
    """One dense delivery phase (see device/tally.py).

    `height` fences ingestion: with on-device height advance an
    instance can move to h+1 between phases, and a replayed phase of
    height-h votes must not tally into h+1 (the reference drops votes
    for decided heights the same way, core.executor's HeightVotes)."""

    round: jnp.ndarray   # [I]
    typ: jnp.ndarray     # [I]
    slots: jnp.ndarray   # [I, V]
    mask: jnp.ndarray    # [I, V]
    height: jnp.ndarray  # [I]


class ExtEvent(NamedTuple):
    """Harness-injected events (tag NULL_EVENT = none)."""

    tag: jnp.ndarray        # [I]
    round: jnp.ndarray      # [I]
    value: jnp.ndarray      # [I]
    pol_round: jnp.ndarray  # [I]

    @classmethod
    def none(cls, n: int) -> "ExtEvent":
        z = jnp.zeros((n,), I32)
        return cls(jnp.full((n,), NULL_EVENT, I32), z, z, z - 1)


class StepOutputs(NamedTuple):
    state: DeviceState
    tally: TallyState
    msgs: DeviceMessage  # [n_stages, I] leaves


def consensus_step(state: DeviceState,
                   tally: TallyState,
                   ext: ExtEvent,
                   phase: VotePhase,
                   powers: jnp.ndarray,         # [V]
                   total_power: jnp.ndarray,    # scalar
                   proposer_flag: jnp.ndarray,  # [I, R] this node proposes (h,r)
                   propose_value: jnp.ndarray,  # [I] fresh value to propose
                   axis_name: str | None = None,  # validator mesh axis (psum)
                   advance_height: bool = False,  # stage 8 on/off
                   ) -> StepOutputs:
    msgs = []

    def apply_ev(st, tag, round_, value, pol):
        ev = DeviceEvent(tag.astype(I32), round_.astype(I32),
                         value.astype(I32), pol.astype(I32))
        st, m = _apply(st, ev)
        msgs.append(m)
        return st

    # --- 0. external event
    state = apply_ev(state, ext.tag, ext.round, ext.value, ext.pol_round)

    # --- 1. vote ingestion (height-fenced: stale-height phases no-op)
    height_ok = phase.height == state.height
    tally, tev = add_votes(tally, powers, total_power, phase.round, phase.typ,
                           phase.slots, phase.mask & height_ok[:, None],
                           state.round, axis_name=axis_name)
    neg1 = jnp.full_like(tev.tag, -1)
    # precommit-class events are consumed on first in-round delivery
    # (their arms are step-independent, state_machine.rs:208,:211) —
    # record that so they are never re-delivered (one TimeoutPrecommit
    # schedule per round, spec line 47 "for the first time")
    is_pc_ev = ((tev.tag == int(EventTag.PRECOMMIT_ANY))
                | (tev.tag == int(EventTag.PRECOMMIT_VALUE)))
    consumed = is_pc_ev & ((tev.round == state.round)
                           | (tev.tag == int(EventTag.PRECOMMIT_VALUE)))
    W_t = tally.pc_done.shape[1]
    ev_widx = tev.round - tally.base_round        # window row of the event
    pc_hit = ((jnp.arange(W_t)[None, :] == ev_widx[:, None])
              & consumed[:, None])
    tally = tally._replace(pc_done=tally.pc_done | pc_hit)
    state = apply_ev(state, tev.tag, tev.round, tev.value_slot, neg1)

    # --- 2. round skip
    skip_tag = jnp.where(tev.skip_round >= 0, int(EventTag.ROUND_SKIP),
                         NULL_EVENT)
    state = apply_ev(state, skip_tag, tev.skip_round,
                     jnp.full_like(skip_tag, NIL_ID), neg1)

    # --- 3./4. re-query current-round thresholds (prevote then precommit),
    # at most once per state-machine (round, step): the q_round/q_step
    # cursor records the state the re-query stages last ran against, so a
    # standing threshold cannot re-schedule its timeout every step (spec
    # line 47 "for the first time") — it re-fires only after the state
    # machine actually moved, which is exactly when a previously ignored
    # edge may have become applicable (the missed-edge hazard).
    for typ_code in (int(VoteType.PREVOTE), int(VoteType.PRECOMMIT)):
        typ_arr = jnp.full_like(state.round, typ_code)
        code, vslot = current_threshold(tally, state.round, typ_arr,
                                        total_power)
        moved = (state.round != tally.q_round) | (state.step != tally.q_step)
        tag = jnp.where(moved, _EVENT_TABLE[typ_arr, code], NULL_EVENT)
        # suppress re-delivery of the event stage 1 just delivered for
        # the same round (same-call duplicate, cursor not yet advanced)
        tag = jnp.where((tag == tev.tag) & (state.round == tev.round),
                        NULL_EVENT, tag)
        if typ_code == int(VoteType.PRECOMMIT):
            cur_widx = state.round - tally.base_round
            round_c_t = jnp.clip(cur_widx, 0, W_t - 1)
            done = jnp.take_along_axis(tally.pc_done, round_c_t[:, None],
                                       axis=1)[:, 0]
            tag = jnp.where(done, NULL_EVENT, tag)
            fired = ((tag != NULL_EVENT) & (cur_widx >= 0)
                     & (cur_widx < W_t))
            pc_hit = ((jnp.arange(W_t)[None, :] == cur_widx[:, None])
                      & fired[:, None])
            tally = tally._replace(pc_done=tally.pc_done | pc_hit)
        state = apply_ev(state, tag, state.round, vslot, neg1)
    tally = tally._replace(q_round=state.round, q_step=state.step)

    # --- 5. round entry.  proposer_flag[i, r % R] = "this node proposes
    # round r of instance i".  The weighted-round-robin rotation the
    # host executor uses (core.validators.ProposerRotation) is periodic
    # with period total_power, so a table covering a multiple of the
    # period is exact for ALL rounds — rounds never outrun it the way
    # they outrun a fixed window.
    R = proposer_flag.shape[1]
    round_c = state.round % R
    is_prop = jnp.take_along_axis(proposer_flag, round_c[:, None],
                                  axis=1)[:, 0]
    at_new_round = state.step == int(Step.NEW_ROUND)
    entry_tag = jnp.where(
        at_new_round,
        jnp.where(is_prop, int(EventTag.NEW_ROUND_PROPOSER),
                  int(EventTag.NEW_ROUND)),
        NULL_EVENT)
    state = apply_ev(state, entry_tag, state.round, propose_value, neg1)

    # --- 6. self-proposal: the proposer processes its own proposal
    prop_msg = msgs[-1]
    was_proposal = prop_msg.tag == int(MsgTag.PROPOSAL)
    self_tag = jnp.where(was_proposal, int(EventTag.PROPOSAL), NULL_EVENT)
    state = apply_ev(state, self_tag, prop_msg.round, prop_msg.value,
                     prop_msg.aux)

    # --- 7. window rotation: keep the tally window around the current
    # round (one past round stays tracked for late polka/precommit
    # evidence; W-2 future rounds stay tracked for round-skip weight).
    # This is the rotation the reference's unbounded per-round map
    # (round_votes.rs:74-97) makes implicit.
    new_base = jnp.maximum(tally.base_round,
                           jnp.maximum(state.round - 1, 0))
    tally = rotate_window(tally, new_base)

    # --- 8. height advance (optional): a decided instance is reset to
    # State::new(height+1) semantics — the reference's contract that "a
    # decision ends the instance and the consumer starts a new State at
    # the next height" (README.md:43-44), folded onto the device so
    # multi-height throughput never round-trips the host.
    if advance_height:
        decided = state.step == int(Step.COMMIT)

        def sel(new, old):
            mask = decided.reshape(decided.shape
                                   + (1,) * (old.ndim - 1))
            return jnp.where(mask, new, old)

        zero = jnp.zeros_like(state.round)
        state = DeviceState(
            round=sel(zero, state.round),
            step=sel(zero, state.step),                 # Step.NEW_ROUND
            locked_round=sel(zero - 1, state.locked_round),
            locked_value=sel(zero - 1, state.locked_value),
            valid_round=sel(zero - 1, state.valid_round),
            valid_value=sel(zero - 1, state.valid_value),
            height=sel(state.height + 1, state.height),
        )
        tally = tally._replace(
            weights=sel(jnp.zeros_like(tally.weights), tally.weights),
            voted=sel(jnp.full_like(tally.voted, NOT_VOTED), tally.voted),
            emitted=sel(jnp.full_like(tally.emitted, TH_INIT),
                        tally.emitted),
            skipped=sel(jnp.zeros_like(tally.skipped), tally.skipped),
            q_round=sel(zero - 1, tally.q_round),
            q_step=sel(zero - 1, tally.q_step),
            pc_done=sel(jnp.zeros_like(tally.pc_done), tally.pc_done),
            skip_w=sel(jnp.zeros_like(tally.skip_w), tally.skip_w),
            base_round=sel(zero, tally.base_round),
            # equiv is cumulative evidence about validators, not about a
            # height — it survives the advance
        )

    stacked = DeviceMessage(*[jnp.stack([getattr(m, f) for m in msgs])
                              for f in DeviceMessage._fields])
    return StepOutputs(state=state, tally=tally, msgs=stacked)


consensus_step_jit = jax.jit(consensus_step,
                             static_argnames=("axis_name", "advance_height"))

N_STAGES = 7


def consensus_step_seq(state: DeviceState,
                       tally: TallyState,
                       exts: ExtEvent,      # [P, I] leaves
                       phases: VotePhase,   # [P, I(, V)] leaves
                       powers: jnp.ndarray,
                       total_power: jnp.ndarray,
                       proposer_flag: jnp.ndarray,
                       propose_value: jnp.ndarray,
                       axis_name: str | None = None,
                       advance_height: bool = False,
                       ) -> StepOutputs:
    """P sequential fused steps in ONE traced computation: `lax.scan`
    over the leading axis of `exts`/`phases`, so a whole delivery
    sequence (e.g. the dedup layers of one vote class, or a height's
    entry + prevote + precommit) is a single device dispatch.

    Why this exists: each dispatch on the axon-tunneled TPU costs
    ~60-70ms in fixed host/tunnel overhead regardless of the work in
    it (scripts/timing_check.py, r4) — phase-at-a-time stepping is
    overhead-bound long before the chip is busy.  Keeping the loop on
    device is also the XLA-idiomatic shape: the scanned body compiles
    once, and no host round-trip separates the phases.

    msgs leaves come back stacked [P, n_stages, I]."""

    def body(carry, xs):
        st, ta = carry
        ext, phase = xs
        out = consensus_step(st, ta, ext, phase, powers, total_power,
                             proposer_flag, propose_value,
                             axis_name=axis_name,
                             advance_height=advance_height)
        return (out.state, out.tally), out.msgs

    (state, tally), msgs = jax.lax.scan(body, (state, tally),
                                        (exts, phases))
    return StepOutputs(state=state, tally=tally, msgs=msgs)


consensus_step_seq_jit = jax.jit(
    consensus_step_seq, static_argnames=("axis_name", "advance_height"))

# DONATED variant for the streaming serve plane (serve/pipeline.py):
# state/tally buffers are donated to XLA so the step sequence updates
# them in place instead of allocating a fresh copy per dispatch — at
# the north-star shape the tally's voted array alone is
# I*W*2*V*4 B = 320 MB, and a service dispatching continuously would
# otherwise hold two generations live across every in-flight step.
# A SEPARATE jit entry (not a flag): donation is part of the compiled
# executable's buffer aliasing, and the non-donating entries must keep
# their historical semantics (callers may legally reuse the passed
# state, e.g. the differential tests stepping two drivers in lockstep).
consensus_step_seq_donated_jit = jax.jit(
    consensus_step_seq, static_argnames=("axis_name", "advance_height"),
    donate_argnums=(0, 1))


class SignedLanes(NamedTuple):
    """Packed per-lane Ed25519 verify inputs for DEVICE-FUSED
    verification: lane j is one wire vote destined for phase
    `phase_idx[j]` of a step sequence, cell (inst[j], val[j]).
    pub/sig/blocks are `ed25519_jax.verify_batch` inputs (the bridge
    packs them with its existing vectorized packers)."""

    pub: jnp.ndarray        # [N, 32] int32
    sig: jnp.ndarray        # [N, 64] int32
    blocks: jnp.ndarray     # [N, nb, 32] uint32
    phase_idx: jnp.ndarray  # [N] int32; out-of-range = padding lane
    inst: jnp.ndarray       # [N] int32
    val: jnp.ndarray        # [N] int32
    real: jnp.ndarray       # [N] bool; False = shape-bucketing pad


class SignedStepOutputs(NamedTuple):
    state: DeviceState
    tally: TallyState
    msgs: DeviceMessage      # [P, n_stages, I] leaves
    n_rejected: jnp.ndarray  # failed-verification count: scalar (lane
    #                          path) or [I] per-instance (dense path);
    #                          consumers sum (driver._settle_rejects)


def _verify_lanes_chunked(pub: jnp.ndarray, sig: jnp.ndarray,
                          blocks: jnp.ndarray,
                          verify_chunk: int | None) -> jnp.ndarray:
    """`verify_batch` over [N] lanes in bounded microbatches: a
    `lax.map` over chunks of `verify_chunk` lanes, so only ONE chunk's
    field temporaries (~10 KB/lane, utils/budget.py operand math) are
    live at a time instead of all N at once — the HBM-graceful path
    for north-star lane counts (20M lanes would need hundreds of GB of
    workspace unchunked).

    Bit-identical to the unchunked call BY CONSTRUCTION: every lane's
    verification is independent integer math (vmapped elementwise over
    the lane axis; reductions only run over the limb axes inside a
    lane), so regrouping lanes into chunks cannot change any verdict.
    A ragged last chunk is padded with zero lanes whose garbage
    verdicts are sliced off before returning.  `None` (or a chunk
    >= N) falls through to the single-call path unchanged."""
    N = pub.shape[0]
    if not verify_chunk or verify_chunk >= N:
        return _ejax.verify_batch(pub, sig, blocks)
    c = int(verify_chunk)
    n_chunks = -(-N // c)
    pad = n_chunks * c - N

    def chunked(x):
        if pad:
            x = jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))
        return x.reshape((n_chunks, c) + x.shape[1:])

    ok = jax.lax.map(lambda t: _ejax.verify_batch(*t),
                     (chunked(pub), chunked(sig), chunked(blocks)))
    return ok.reshape(n_chunks * c)[:N]


def consensus_step_seq_signed(state: DeviceState,
                              tally: TallyState,
                              exts: ExtEvent,      # [P, I] leaves
                              phases: VotePhase,   # [P, I(, V)] leaves
                              lanes: SignedLanes,  # [N, ...] leaves
                              powers: jnp.ndarray,
                              total_power: jnp.ndarray,
                              proposer_flag: jnp.ndarray,
                              propose_value: jnp.ndarray,
                              advance_height: bool = False,
                              verify_chunk: int | None = None,
                              ) -> SignedStepOutputs:
    """`consensus_step_seq` with signature verification FUSED into the
    same dispatch — the SURVEY §3.2 north-star shape ("this whole
    stack plus signature verification is the single fused TPU
    kernel"): ONE batched Ed25519 verify (the Pallas kernel on TPU)
    runs over every lane of every phase in the sequence, its verdicts
    are scattered to [P, I, V] and ANDed into the phase masks ON
    DEVICE, and only then does the scanned step sequence run.

    Why it exists: the host-verified path must fetch verdicts to
    densify (a device->host sync per build), which serializes the
    ~60-70ms/dispatch tunnel latency between heights.  Here no
    roundtrip separates verification from tallying, so consecutive
    heights queue back-to-back through JAX async dispatch and the
    latency amortizes (the same property `honest_heights` exploits
    for unsigned traffic).

    Caller contract (VoteBatcher device_verify / DeviceDriver
    step_seq_signed enforce it): at most one lane per (phase, cell);
    host-fallback tallies (past rounds, slot spill) must be verified
    host-side by the builder because verdicts never reach the host
    here.  A forged lane is masked out before it can tally; the count
    returns in `n_rejected` — fetch it lazily, it does not gate the
    pipeline.  (Reference anchor: the verify responsibility stubbed at
    consensus_executor.rs:38-41, resolved on device instead of in the
    consumer.)

    `verify_chunk` (static; lanes per microbatch — size it with
    utils/budget.plan_lane_verify) streams the batched verify through
    bounded chunks instead of one N-lane call, bit-identically; None
    keeps the historical single-call path."""
    ok = _verify_lanes_chunked(lanes.pub, lanes.sig, lanes.blocks,
                               verify_chunk)                     # [N]
    P, I, V = phases.mask.shape
    # padding lanes carry an out-of-range phase_idx: mode="drop" makes
    # their scatter a no-op, and `real` keeps them out of the count
    vmask = jnp.zeros((P, I, V), bool).at[
        lanes.phase_idx, lanes.inst, lanes.val].set(ok, mode="drop")
    phases = phases._replace(mask=phases.mask & vmask)
    out = consensus_step_seq(state, tally, exts, phases, powers,
                             total_power, proposer_flag, propose_value,
                             advance_height=advance_height)
    return SignedStepOutputs(state=out.state, tally=out.tally,
                             msgs=out.msgs,
                             n_rejected=(lanes.real & ~ok).sum()
                             .astype(I32))


consensus_step_seq_signed_jit = jax.jit(
    consensus_step_seq_signed,
    static_argnames=("advance_height", "verify_chunk"))

# donated twin (see consensus_step_seq_donated_jit): the serve plane's
# continuous dispatch loop updates state/tally in place
consensus_step_seq_signed_donated_jit = jax.jit(
    consensus_step_seq_signed,
    static_argnames=("advance_height", "verify_chunk"),
    donate_argnums=(0, 1))


class DenseSignedPhases(NamedTuple):
    """Dense per-cell Ed25519 inputs for the SHARDED fused path: entry
    (p, i, v) holds the signature material for phase `P - Ps + p`'s
    vote by validator v in instance i (the LAST Ps phases of the
    sequence are the signed vote classes; leading phases — e.g. the
    round-entry phase — carry no lanes).  The dense [.., I, V, ..]
    layout shards exactly like the phase masks (data x val), so under
    shard_map each device verifies its own cells LOCALLY — fused
    verification adds no collective; the tally's quorum psums remain
    the only communication (parallel/sharded.py layout table)."""

    pub: jnp.ndarray      # [V, 32] int32 validator table
    sig: jnp.ndarray      # [Ps, I, V, 64] int32
    blocks: jnp.ndarray   # [Ps, I, V, nb, 32] uint32


def consensus_step_seq_signed_dense(state: DeviceState,
                                    tally: TallyState,
                                    exts: ExtEvent,       # [P, I]
                                    phases: VotePhase,    # [P, I(, V)]
                                    dense: DenseSignedPhases,
                                    powers: jnp.ndarray,
                                    total_power: jnp.ndarray,
                                    proposer_flag: jnp.ndarray,
                                    propose_value: jnp.ndarray,
                                    axis_name: str | None = None,
                                    advance_height: bool = False,
                                    verify_chunk: int | None = None,
                                    ) -> SignedStepOutputs:
    """consensus_step_seq_signed with DENSE per-cell lanes — the
    layout that runs under shard_map (make_sharded_step_seq_signed):
    verification is elementwise in (instance, validator), so it
    shards with the phases and each device verifies only its local
    cells.  Unmasked cells verify garbage and are discarded by the
    mask AND; `n_rejected` comes back PER INSTANCE ([I], psum'd over
    the validator axis when sharded) counting masked cells whose
    signature failed.

    `verify_chunk` (static; INSTANCE ROWS per microbatch — size it
    with utils/budget.plan_dense_verify) streams the Ps*I*V-lane
    verify through chunks of verify_chunk*Ps*V lanes via `lax.map`,
    so the 20-limb field workspace stays bounded at any instance
    count — the HBM-graceful north-star path (VERDICT r5 weak #3: the
    unchunked call cannot fit 2x10k x1000 on a 16 GB chip).  Under
    shard_map the chunk applies to LOCAL rows and the chunk loop adds
    no collective — verification stays cell-local, so the sharded
    zero-added-collectives property holds per chunk.  Bit-identical
    to unchunked for the same reason as _verify_lanes_chunked; a
    ragged last tile is padded and sliced."""
    Ps, I, V = dense.sig.shape[:3]
    P = phases.mask.shape[0]
    nb_tail = dense.blocks.shape[3:]
    if verify_chunk is None or verify_chunk >= I:
        pub = jnp.broadcast_to(dense.pub[None, None], (Ps, I, V, 32))
        ok = _ejax.verify_batch(
            pub.reshape(Ps * I * V, 32),
            dense.sig.reshape(Ps * I * V, 64),
            dense.blocks.reshape(Ps * I * V, *nb_tail))
        ok = ok.reshape(Ps, I, V)
    else:
        t = int(verify_chunk)
        n_chunks = -(-I // t)
        pad = n_chunks * t - I

        def tiles(x):
            # [Ps, I, V, ...] -> [n_chunks, Ps, t, V, ...]
            if pad:
                x = jnp.pad(x, ((0, 0), (0, pad))
                            + ((0, 0),) * (x.ndim - 2))
            x = x.reshape((Ps, n_chunks, t) + x.shape[2:])
            return x.swapaxes(0, 1)

        def body(xs):
            s, b = xs
            pub = jnp.broadcast_to(dense.pub[None, None], (Ps, t, V, 32))
            okc = _ejax.verify_batch(pub.reshape(Ps * t * V, 32),
                                     s.reshape(Ps * t * V, 64),
                                     b.reshape((Ps * t * V,) + nb_tail))
            return okc.reshape(Ps, t, V)

        ok = jax.lax.map(body, (tiles(dense.sig), tiles(dense.blocks)))
        ok = ok.swapaxes(0, 1).reshape(Ps, n_chunks * t, V)[:, :I]
    vmask = jnp.concatenate(
        [jnp.ones((P - Ps, I, V), bool), ok], axis=0)
    n_rej = (phases.mask & ~vmask).sum(axis=(0, 2)).astype(I32)  # [I]
    if axis_name is not None:
        n_rej = jax.lax.psum(n_rej, axis_name)
    phases = phases._replace(mask=phases.mask & vmask)
    out = consensus_step_seq(state, tally, exts, phases, powers,
                             total_power, proposer_flag, propose_value,
                             axis_name=axis_name,
                             advance_height=advance_height)
    return SignedStepOutputs(state=out.state, tally=out.tally,
                             msgs=out.msgs, n_rejected=n_rej)


consensus_step_seq_signed_dense_jit = jax.jit(
    consensus_step_seq_signed_dense,
    static_argnames=("axis_name", "advance_height", "verify_chunk"))

# donated twin (see consensus_step_seq_donated_jit): the serve plane's
# dense dispatch mode — single-device here; parallel/sharded.py's
# make_sharded_step_seq_signed(donate=True) is the mesh analogue
consensus_step_seq_signed_dense_donated_jit = jax.jit(
    consensus_step_seq_signed_dense,
    static_argnames=("axis_name", "advance_height", "verify_chunk"),
    donate_argnums=(0, 1))


def honest_heights(state: DeviceState,
                   tally: TallyState,
                   slots: jnp.ndarray,      # [I, V] value slot votes
                   mask: jnp.ndarray,       # [I, V] voter mask
                   powers: jnp.ndarray,
                   total_power: jnp.ndarray,
                   proposer_flag: jnp.ndarray,
                   propose_value: jnp.ndarray,
                   heights: int,
                   axis_name: str | None = None,
                   ) -> StepOutputs:
    """`heights` consecutive honest heights — entry step, full prevote
    phase, full precommit phase, decision, stage-8 height advance — in
    ONE device dispatch (`lax.scan` over heights; the phases take their
    round/height from the carried state, so nothing round-trips the
    host).  This is the reference's intended top-level loop
    (consensus_executor.rs:24-49) run entirely on device, H heights at
    a time.

    msgs leaves come back stacked [H, 3, n_stages, I]."""
    n = state.round.shape[0]

    def phase_of(st, typ_code, sl, mk):
        return VotePhase(round=st.round,
                         typ=jnp.full_like(st.round, typ_code),
                         slots=sl, mask=mk, height=st.height)

    def one(st, ta, phase):
        return consensus_step(st, ta, ExtEvent.none(n), phase,
                              powers, total_power, proposer_flag,
                              propose_value, axis_name=axis_name,
                              advance_height=True)

    def height_body(carry, _):
        st, ta = carry
        out0 = one(st, ta, phase_of(st, 0, jnp.full_like(slots, -1),
                                    jnp.zeros_like(mask)))
        out1 = one(out0.state, out0.tally,
                   phase_of(out0.state, int(VoteType.PREVOTE), slots, mask))
        out2 = one(out1.state, out1.tally,
                   phase_of(out1.state, int(VoteType.PRECOMMIT), slots,
                            mask))
        msgs = DeviceMessage(*[
            jnp.stack([getattr(m, f) for m in
                       (out0.msgs, out1.msgs, out2.msgs)])
            for f in DeviceMessage._fields])
        return (out2.state, out2.tally), msgs

    (state, tally), msgs = jax.lax.scan(height_body, (state, tally),
                                        None, length=heights)
    return StepOutputs(state=state, tally=tally, msgs=msgs)


honest_heights_jit = jax.jit(
    honest_heights, static_argnames=("heights", "axis_name"))


# -- entry registry -----------------------------------------------------------
# Every jit entry above is registered by name (device/registry.py) so
# DeviceDriver/ServePipeline resolve ONE table, the static analyzer
# (analysis/jaxpr_audit.py) can enumerate and abstractly trace every
# entry, and the retrace tripwire (analysis/retrace.py) keys its
# expected-trace sets.  Adding a jit entry without registering it is
# caught by analysis/lint.py's import-time-jit rule.

def _reg(name, fn, jit_fn, statics, donated=()):
    _registry.register(_registry.EntrySpec(
        name=name, fn=fn, jit=jit_fn, statics=tuple(statics),
        donated=tuple(donated)))


_STEP_STATICS = ("axis_name", "advance_height")
_SIGNED_STATICS = ("advance_height", "verify_chunk")
_DENSE_STATICS = ("axis_name", "advance_height", "verify_chunk")
_reg("consensus_step", consensus_step, consensus_step_jit, _STEP_STATICS)
_reg("consensus_step_seq", consensus_step_seq, consensus_step_seq_jit,
     _STEP_STATICS)
_reg("consensus_step_seq_donated", consensus_step_seq,
     consensus_step_seq_donated_jit, _STEP_STATICS, donated=(0, 1))
_reg("consensus_step_seq_signed", consensus_step_seq_signed,
     consensus_step_seq_signed_jit, _SIGNED_STATICS)
_reg("consensus_step_seq_signed_donated", consensus_step_seq_signed,
     consensus_step_seq_signed_donated_jit, _SIGNED_STATICS,
     donated=(0, 1))
_reg("consensus_step_seq_signed_dense", consensus_step_seq_signed_dense,
     consensus_step_seq_signed_dense_jit, _DENSE_STATICS)
_reg("consensus_step_seq_signed_dense_donated",
     consensus_step_seq_signed_dense,
     consensus_step_seq_signed_dense_donated_jit, _DENSE_STATICS,
     donated=(0, 1))
_reg("honest_heights", honest_heights, honest_heights_jit,
     ("heights", "axis_name"))
