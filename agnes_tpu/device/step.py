"""The fused per-instance consensus step — the flagship device kernel.

One call advances a batch of I independent consensus instances through
one delivery phase, reproducing the reference's intended top-level loop
(consensus_executor.rs:24-49, SURVEY.md §3.3) as a fixed pipeline of
seven branch-free stages, each an `apply` of the vmapped state machine:

  0. external event   — harness/bridge-injected Proposal /
                        ProposalInvalid / Timeout* (the reference's
                        inbound wire alphabet, consensus_executor.rs:16-20)
  1. vote ingestion   — dense tally phase -> edge-triggered threshold
                        event (stack §3.2: the verify+tally hot path)
  2. round skip       — +1/3 weight on a higher round -> RoundSkip
  3. re-query prevote — level-triggered catch-up of the current round's
     /4. precommit      thresholds, so an edge consumed in a step that
                        ignored it is never lost (liveness; see
                        device/tally.py docstring)
  5. round entry      — step == NewRound -> NewRound/NewRoundProposer
                        from the precomputed proposer table (fills the
                        "check if we're the proposer" stub,
                        consensus_executor.rs:31-33)
  6. self-proposal    — the proposer processes its own Proposal message
                        immediately (the re-entrant "call execute"
                        intent, consensus_executor.rs:36-41)

Every stage emits a DeviceMessage batch; the step returns them stacked
on a leading stage axis.  The harness/bridge routes VOTE messages back
into the next phase's dense matrices (self-votes take the same path as
peer votes, exactly the reference's intent), TIMEOUT to the timer
wheel, DECISION to the decided log.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from agnes_tpu.core.state_machine import EventTag, MsgTag, Step
from agnes_tpu.device.encoding import I32, DeviceEvent, DeviceMessage, DeviceState
from agnes_tpu.device.state_machine import apply_scalar
from agnes_tpu.device.tally import (
    _EVENT_TABLE,
    NO_EVENT,
    TallyState,
    add_votes,
    current_threshold,
)
from agnes_tpu.types import NIL_ID, VoteType

# "no event" tag: matches no transition arm -> guaranteed no-op
NULL_EVENT = NO_EVENT

_apply = jax.vmap(apply_scalar)


class VotePhase(NamedTuple):
    """One dense delivery phase (see device/tally.py)."""

    round: jnp.ndarray   # [I]
    typ: jnp.ndarray     # [I]
    slots: jnp.ndarray   # [I, V]
    mask: jnp.ndarray    # [I, V]


class ExtEvent(NamedTuple):
    """Harness-injected events (tag NULL_EVENT = none)."""

    tag: jnp.ndarray        # [I]
    round: jnp.ndarray      # [I]
    value: jnp.ndarray      # [I]
    pol_round: jnp.ndarray  # [I]

    @classmethod
    def none(cls, n: int) -> "ExtEvent":
        z = jnp.zeros((n,), I32)
        return cls(jnp.full((n,), NULL_EVENT, I32), z, z, z - 1)


class StepOutputs(NamedTuple):
    state: DeviceState
    tally: TallyState
    msgs: DeviceMessage  # [n_stages, I] leaves


def consensus_step(state: DeviceState,
                   tally: TallyState,
                   ext: ExtEvent,
                   phase: VotePhase,
                   powers: jnp.ndarray,         # [V]
                   total_power: jnp.ndarray,    # scalar
                   proposer_flag: jnp.ndarray,  # [I, W] this node proposes (h,r)
                   propose_value: jnp.ndarray,  # [I] fresh value to propose
                   axis_name: str | None = None,  # validator mesh axis (psum)
                   ) -> StepOutputs:
    msgs = []

    def apply_ev(st, tag, round_, value, pol):
        ev = DeviceEvent(tag.astype(I32), round_.astype(I32),
                         value.astype(I32), pol.astype(I32))
        st, m = _apply(st, ev)
        msgs.append(m)
        return st

    # --- 0. external event
    state = apply_ev(state, ext.tag, ext.round, ext.value, ext.pol_round)

    # --- 1. vote ingestion
    tally, tev = add_votes(tally, powers, total_power, phase.round, phase.typ,
                           phase.slots, phase.mask, state.round,
                           axis_name=axis_name)
    neg1 = jnp.full_like(tev.tag, -1)
    # precommit-class events are consumed on first in-round delivery
    # (their arms are step-independent, state_machine.rs:208,:211) —
    # record that so they are never re-delivered (one TimeoutPrecommit
    # schedule per round, spec line 47 "for the first time")
    is_pc_ev = ((tev.tag == int(EventTag.PRECOMMIT_ANY))
                | (tev.tag == int(EventTag.PRECOMMIT_VALUE)))
    consumed = is_pc_ev & ((tev.round == state.round)
                           | (tev.tag == int(EventTag.PRECOMMIT_VALUE)))
    W_t = tally.pc_done.shape[1]
    pc_hit = ((jnp.arange(W_t)[None, :] == tev.round[:, None])
              & consumed[:, None])
    tally = tally._replace(pc_done=tally.pc_done | pc_hit)
    state = apply_ev(state, tev.tag, tev.round, tev.value_slot, neg1)

    # --- 2. round skip
    skip_tag = jnp.where(tev.skip_round >= 0, int(EventTag.ROUND_SKIP),
                         NULL_EVENT)
    state = apply_ev(state, skip_tag, tev.skip_round,
                     jnp.full_like(skip_tag, NIL_ID), neg1)

    # --- 3./4. re-query current-round thresholds (prevote then precommit),
    # at most once per state-machine (round, step): the q_round/q_step
    # cursor records the state the re-query stages last ran against, so a
    # standing threshold cannot re-schedule its timeout every step (spec
    # line 47 "for the first time") — it re-fires only after the state
    # machine actually moved, which is exactly when a previously ignored
    # edge may have become applicable (the missed-edge hazard).
    for typ_code in (int(VoteType.PREVOTE), int(VoteType.PRECOMMIT)):
        typ_arr = jnp.full_like(state.round, typ_code)
        code, vslot = current_threshold(tally, state.round, typ_arr,
                                        total_power)
        moved = (state.round != tally.q_round) | (state.step != tally.q_step)
        tag = jnp.where(moved, _EVENT_TABLE[typ_arr, code], NULL_EVENT)
        # suppress re-delivery of the event stage 1 just delivered for
        # the same round (same-call duplicate, cursor not yet advanced)
        tag = jnp.where((tag == tev.tag) & (state.round == tev.round),
                        NULL_EVENT, tag)
        if typ_code == int(VoteType.PRECOMMIT):
            round_c_t = jnp.clip(state.round, 0, W_t - 1)
            done = jnp.take_along_axis(tally.pc_done, round_c_t[:, None],
                                       axis=1)[:, 0]
            tag = jnp.where(done, NULL_EVENT, tag)
            fired = (tag != NULL_EVENT) & (state.round < W_t)
            pc_hit = ((jnp.arange(W_t)[None, :] == state.round[:, None])
                      & fired[:, None])
            tally = tally._replace(pc_done=tally.pc_done | pc_hit)
        state = apply_ev(state, tag, state.round, vslot, neg1)
    tally = tally._replace(q_round=state.round, q_step=state.step)

    # --- 5. round entry (only for rounds inside the proposer-table /
    # tally window; the host driver rotates the window for rounds beyond)
    W = proposer_flag.shape[1]
    round_c = jnp.clip(state.round, 0, W - 1)
    is_prop = jnp.take_along_axis(proposer_flag, round_c[:, None],
                                  axis=1)[:, 0]
    at_new_round = ((state.step == int(Step.NEW_ROUND))
                    & (state.round < W))
    entry_tag = jnp.where(
        at_new_round,
        jnp.where(is_prop, int(EventTag.NEW_ROUND_PROPOSER),
                  int(EventTag.NEW_ROUND)),
        NULL_EVENT)
    state = apply_ev(state, entry_tag, state.round, propose_value, neg1)

    # --- 6. self-proposal: the proposer processes its own proposal
    prop_msg = msgs[-1]
    was_proposal = prop_msg.tag == int(MsgTag.PROPOSAL)
    self_tag = jnp.where(was_proposal, int(EventTag.PROPOSAL), NULL_EVENT)
    state = apply_ev(state, self_tag, prop_msg.round, prop_msg.value,
                     prop_msg.aux)

    stacked = DeviceMessage(*[jnp.stack([getattr(m, f) for m in msgs])
                              for f in DeviceMessage._fields])
    return StepOutputs(state=state, tally=tally, msgs=stacked)


consensus_step_jit = jax.jit(consensus_step)

N_STAGES = 7
