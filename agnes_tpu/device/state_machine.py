"""Branch-free Tendermint state machine for the device plane.

Semantically identical to `core.state_machine.apply` (itself identical
to the reference, src/state_machine.rs:183-214) — pinned by the
exhaustive differential test in tests/test_device_sm.py over the full
Step × Event × guard space.

Design (SURVEY.md §2.2 "TPU mapping"): the match expression compiles to
an *arm selector* — one boolean per reference match arm, first-true-wins
via argmax over the stacked predicates, exactly reproducing Rust match
priority — followed by `lax.select_n` over the per-arm candidate
(state', message) tuples.  Every candidate is computed unconditionally;
they are a handful of int ops each, so the whole transition is a few
dozen VPU ops with no data-dependent control flow, which is what lets
`jax.vmap` drive 10k+ instances in lockstep under one `jit`.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from agnes_tpu.core.state_machine import EventTag, MsgTag, Step, TimeoutStep
from agnes_tpu.device.encoding import I32, DeviceEvent, DeviceMessage, DeviceState
from agnes_tpu.types import MAX_ROUND, NIL_ID, VoteType

_S = Step
_E = EventTag
_M = MsgTag


def _msg(tag: int, round, value=NIL_ID, aux=0) -> DeviceMessage:
    i = partial(jnp.asarray, dtype=I32)
    return DeviceMessage(i(tag), i(round), i(value), i(aux))


def apply_scalar(s: DeviceState, ev: DeviceEvent
                 ) -> Tuple[DeviceState, DeviceMessage]:
    """One instance, one event.  vmap over this for batches."""
    eqr = s.round == ev.round
    step, tag = s.step, ev.tag

    def at(st: Step):
        return step == int(st)

    def on(t: EventTag):
        return tag == int(t)

    # valid_vr: -1 <= vr < round (state_machine.rs:170-172)
    vr_ok = (ev.pol_round >= -1) & (ev.pol_round < s.round)

    # --- arm predicates, in reference match order (state_machine.rs:185-213)
    arms = jnp.stack([
        at(_S.NEW_ROUND) & on(_E.NEW_ROUND_PROPOSER) & eqr,          # 0 propose
        at(_S.NEW_ROUND) & on(_E.NEW_ROUND) & eqr,                   # 1 sched t.propose
        at(_S.PROPOSE) & on(_E.PROPOSAL) & eqr & vr_ok,              # 2 prevote
        at(_S.PROPOSE) & on(_E.PROPOSAL_INVALID) & eqr,              # 3 prevote nil
        at(_S.PROPOSE) & on(_E.TIMEOUT_PROPOSE) & eqr,               # 4 prevote nil
        at(_S.PREVOTE) & on(_E.POLKA_ANY) & eqr,                     # 5 sched t.prevote
        at(_S.PREVOTE) & on(_E.POLKA_NIL) & eqr,                     # 6 precommit nil
        at(_S.PREVOTE) & on(_E.POLKA_VALUE) & eqr,                   # 7 precommit
        at(_S.PREVOTE) & on(_E.TIMEOUT_PREVOTE) & eqr,               # 8 precommit nil
        at(_S.PRECOMMIT) & on(_E.POLKA_VALUE) & eqr,                 # 9 set valid
        at(_S.COMMIT),                                               # 10 absorb
        on(_E.PRECOMMIT_ANY) & eqr,                                  # 11 sched t.precommit
        on(_E.TIMEOUT_PRECOMMIT) & eqr,                              # 12 skip round+1
        on(_E.ROUND_SKIP) & (s.round < ev.round),                    # 13 skip ev.round
        on(_E.PRECOMMIT_VALUE),                                      # 14 commit (no eqr!)
        jnp.ones_like(eqr),                                          # 15 no-op
    ])
    arm = jnp.argmax(arms)  # first true wins == Rust match priority

    # --- shared pieces
    # next_step saturates at Precommit; Commit unchanged (state_machine.rs:58-66)
    stepped = jnp.where(step < int(_S.PRECOMMIT), step + 1, step)
    s_next = s._replace(step=stepped)
    has_valid = s.valid_round >= 0

    # --- candidates per arm
    # 0: propose (state_machine.rs:222-229): valid value/round if set, else
    #    the event's value with pol_round -1
    prop_val = jnp.where(has_valid, s.valid_value, ev.value)
    prop_pol = jnp.where(has_valid, s.valid_round, jnp.asarray(-1, I32))
    c0 = (s_next, _msg(_M.PROPOSAL, s.round, prop_val, prop_pol))

    # 1: schedule timeout propose (state_machine.rs:278-281)
    c1 = (s_next, _msg(_M.TIMEOUT, s.round, NIL_ID, int(TimeoutStep.PROPOSE)))

    # 2: prevote with the lock rule (state_machine.rs:237-246)
    lock_ok = ((s.locked_round < 0)                 # not locked
               | (s.locked_round <= ev.pol_round)   # unlock
               | (s.locked_value == ev.value))      # same value
    pv_val = jnp.where(lock_ok, ev.value, jnp.asarray(NIL_ID, I32))
    c2 = (s_next, _msg(_M.VOTE, s.round, pv_val, int(VoteType.PREVOTE)))

    # 3/4: prevote nil (state_machine.rs:250-253)
    c3 = (s_next, _msg(_M.VOTE, s.round, NIL_ID, int(VoteType.PREVOTE)))

    # 5: schedule timeout prevote — NO step change (state_machine.rs:287-289)
    c5 = (s, _msg(_M.TIMEOUT, s.round, NIL_ID, int(TimeoutStep.PREVOTE)))

    # 6/8: precommit nil (state_machine.rs:268-271)
    c6 = (s_next, _msg(_M.VOTE, s.round, NIL_ID, int(VoteType.PRECOMMIT)))

    # 7: precommit value: lock + valid at current round (state_machine.rs:261-264)
    s7 = s._replace(step=stepped,
                    locked_round=s.round, locked_value=ev.value,
                    valid_round=s.round, valid_value=ev.value)
    c7 = (s7, _msg(_M.VOTE, s.round, ev.value, int(VoteType.PRECOMMIT)))

    # 9: set valid value only, no message (state_machine.rs:304-306)
    s9 = s._replace(valid_round=s.round, valid_value=ev.value)
    c9 = (s9, _msg(_M.NONE, 0))

    # 10/15: absorb / no-op
    c10 = (s, _msg(_M.NONE, 0))

    # 11: schedule timeout precommit — no step change (state_machine.rs:293-295)
    c11 = (s, _msg(_M.TIMEOUT, s.round, NIL_ID, int(TimeoutStep.PRECOMMIT)))

    # 12/13: round skip → NewRound at target round (state_machine.rs:314-316)
    def skip(r):
        return (s._replace(round=r, step=jnp.asarray(int(_S.NEW_ROUND), I32)),
                _msg(_M.NEW_ROUND, r))

    # clamp BEFORE the +1: at ev.round == MAX_ROUND (the top of the
    # framework rounds domain, types.py) a bare int32 +1 would wrap
    # negative here while the int64 oracle/C++ saturate — clamping the
    # operand keeps all three planes bit-for-bit at the edge
    c12 = skip(jnp.minimum(ev.round, jnp.asarray(MAX_ROUND - 1, I32)) + 1)
    c13 = skip(ev.round)

    # 14: commit: step only; Decision carries the EVENT round
    #     (state_machine.rs:320-322)
    s14 = s._replace(step=jnp.asarray(int(_S.COMMIT), I32))
    c14 = (s14, _msg(_M.DECISION, ev.round, ev.value))

    cands = [c0, c1, c2, c3, c3, c5, c6, c7, c6, c9, c10, c11, c12, c13, c14, c10]

    def sel(*leaves):
        return lax.select_n(arm, *leaves)

    state_out = jax.tree.map(sel, *[c[0] for c in cands])
    msg_out = jax.tree.map(sel, *[c[1] for c in cands])
    return state_out, msg_out


# Batched transition: one event per instance, [n] leaves.
apply_batch = jax.jit(jax.vmap(apply_scalar))

from agnes_tpu.device import registry as _registry  # noqa: E402

_registry.register(_registry.EntrySpec(
    name="apply_batch", fn=apply_scalar, jit=apply_batch, hot=False))
