"""JAX data plane: int-encoded consensus instances on device.

The entire transition table of the pure state machine is a function of a
6-field int state and a 13-way event tag (SURVEY.md §2.2 "TPU mapping"),
so it compiles to a branch-free select chain that `vmap` runs over
thousands of concurrent (height, round) instances.
"""

from agnes_tpu.device.encoding import (  # noqa: F401
    DeviceEvent,
    DeviceMessage,
    DeviceState,
)
from agnes_tpu.device.state_machine import apply_batch, apply_scalar  # noqa: F401
