"""Integer encodings of State/Event/Message for the device plane.

The enum codes are the canonical ones from `core.state_machine` (Step,
EventTag, MsgTag, TimeoutStep) — this module only defines the *array
layout* and host<->device conversion helpers used by tests and the
bridge.

Layout decisions:

* `Option<RoundValue>` (locked/valid, state_machine.rs:29-30) flattens to
  a (round, value) int pair with round == -1 meaning None — legal because
  a real locked/valid round is always >= 0 (set_locked/set_valid use the
  current round, state_machine.rs:78-89).
* Nil values (`Option<Value>::None`, lib.rs:26) are value id -1 (NIL_ID).
* A Message flattens to (tag, round, value, aux) where aux carries the
  proposal's pol_round, the vote's type, or the timeout's step; tag NONE
  encodes Rust's Option::None return (state_machine.rs:174).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from agnes_tpu.core import state_machine as sm
from agnes_tpu.types import NIL_ID, Vote, VoteType

I32 = jnp.int32


class DeviceState(NamedTuple):
    """Per-instance consensus state; every leaf is an int32 array of the
    same (possibly empty) batch shape.

    `height` mirrors State.height (state_machine.rs:25): the transition
    function never reads it (height never changes within an instance,
    README.md:43-44), but the device height-advance stage increments it
    when installing the next instance after a decision."""

    round: jnp.ndarray
    step: jnp.ndarray
    locked_round: jnp.ndarray   # -1 = not locked
    locked_value: jnp.ndarray
    valid_round: jnp.ndarray    # -1 = no valid value
    valid_value: jnp.ndarray
    height: jnp.ndarray

    @classmethod
    def new(cls, batch_shape: Tuple[int, ...] = (),
            height: int = 0) -> "DeviceState":
        """Fresh instances at round 0, NewRound (state_machine.rs:35-43)."""
        z = jnp.zeros(batch_shape, I32)
        neg = jnp.full(batch_shape, -1, I32)
        return cls(round=z, step=z, locked_round=neg, locked_value=neg,
                   valid_round=neg, valid_value=neg,
                   height=jnp.full(batch_shape, height, I32))


class DeviceEvent(NamedTuple):
    """An event plus the round it belongs to (the `round` argument of
    apply, state_machine.rs:183)."""

    tag: jnp.ndarray
    round: jnp.ndarray
    value: jnp.ndarray      # NIL_ID when the tag carries no value
    pol_round: jnp.ndarray  # PROPOSAL only; -1 otherwise


class DeviceMessage(NamedTuple):
    tag: jnp.ndarray
    round: jnp.ndarray
    value: jnp.ndarray  # NIL_ID = nil vote / no value
    aux: jnp.ndarray    # pol_round | vote type | timeout step


# ---------------------------------------------------------------------------
# Host <-> device conversion (tests, bridge, checkpointing)
# ---------------------------------------------------------------------------


def encode_state(s: sm.State) -> DeviceState:
    """Host State -> numpy int32 leaves (cheap; no device dispatch)."""
    def rv(x):
        return (x.round, x.value) if x is not None else (-1, -1)

    lr, lv = rv(s.locked)
    vr, vv = rv(s.valid)
    a = lambda x: np.int32(x)  # noqa: E731
    return DeviceState(a(s.round), a(int(s.step)), a(lr), a(lv), a(vr), a(vv),
                       a(s.height))


def decode_state(d: DeviceState, height: int | None = None) -> sm.State:
    g = lambda x: int(np.asarray(x))  # noqa: E731
    locked = (sm.RoundValue(g(d.locked_round), g(d.locked_value))
              if g(d.locked_round) >= 0 else None)
    valid = (sm.RoundValue(g(d.valid_round), g(d.valid_value))
             if g(d.valid_round) >= 0 else None)
    h = g(d.height) if height is None else height
    return sm.State(height=h, round=g(d.round), step=sm.Step(g(d.step)),
                    locked=locked, valid=valid)


def encode_event(round: int, ev: sm.Event) -> DeviceEvent:
    a = lambda x: np.int32(x)  # noqa: E731
    value = ev.value if ev.value is not None else NIL_ID
    return DeviceEvent(a(int(ev.tag)), a(round), a(value), a(ev.pol_round))


def stack_pytree(items):
    """Stack a list of same-type NamedTuples of scalars into one NamedTuple
    of [n] numpy int32 arrays."""
    t = type(items[0])
    return t(*[np.asarray([getattr(e, f) for e in items], dtype=np.int32)
               for f in t._fields])


def decode_message(m: DeviceMessage) -> Optional[sm.Message]:
    g = lambda x: int(np.asarray(x))  # noqa: E731
    tag = sm.MsgTag(g(m.tag))
    rnd, val, aux = g(m.round), g(m.value), g(m.aux)
    if tag == sm.MsgTag.NONE:
        return None
    if tag == sm.MsgTag.NEW_ROUND:
        return sm.Message.new_round(rnd)
    if tag == sm.MsgTag.PROPOSAL:
        return sm.Message.proposal_msg(rnd, val, aux)
    if tag == sm.MsgTag.VOTE:
        value = None if val == NIL_ID else val
        vote = Vote(typ=VoteType(aux), round=rnd, value=value)
        return sm.Message(sm.MsgTag.VOTE, round=rnd, vote=vote)
    if tag == sm.MsgTag.TIMEOUT:
        return sm.Message.timeout_msg(rnd, sm.TimeoutStep(aux))
    return sm.Message.decision_msg(rnd, val)
